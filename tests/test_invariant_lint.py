"""The static invariant linter (simclr_pytorch_distributed_tpu/analysis/).

Two halves, mirroring docs/ANALYSIS.md:

- the KNOWN-BAD fixture corpus (tests/lint_fixtures/): one minimal
  reconstruction per rule — incl. the PR-1 donated-read and the
  split-verdict conditional collective — each asserted to fire exactly
  the expected findings (a rule that stops firing is a dead gate);
- the CLEAN-TREE contract: the full linter over the real package reports
  zero unallowlisted findings, every allowlist entry is used and carries
  a reason, and the committed evidence artifact still passes the pure
  ratchet lint_gate_record.

Everything here is stdlib-ast only — no jax, no driver runs.
"""

import importlib.util
import json
import os
import subprocess
import sys

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
FIXTURES = os.path.join(REPO, "tests", "lint_fixtures")
sys.path.insert(0, os.path.join(REPO, "scripts"))

from simclr_pytorch_distributed_tpu.analysis import (  # noqa: E402
    allowlist as allowlist_mod,
    build_output,
    run_lint,
    runner,
)
from simclr_pytorch_distributed_tpu.analysis import core  # noqa: E402
from simclr_pytorch_distributed_tpu.analysis import (  # noqa: E402
    rule_collectives,
    rule_donation,
    rule_hotloop,
    rule_registry,
)

pytestmark = pytest.mark.lint


def fixture(name: str) -> core.LintModule:
    return core.load_module(os.path.join(FIXTURES, name), repo_root=FIXTURES)


# -- known-bad corpus: each rule fires on its reconstruction --------------

def test_conditional_collective_fires_once():
    """The split-verdict shape: a collective only process 0 enters."""
    findings = rule_collectives.check_module(
        fixture("bad_conditional_collective.py")
    )
    assert [f.rule for f in findings] == [rule_collectives.RULE_CONDITIONAL]
    f = findings[0]
    assert "save_checkpoint" in f.why and f.file.endswith(
        "bad_conditional_collective.py"
    )
    assert f.allowlist_key.startswith(
        "collective-schedule:conditional:bad_conditional_collective.py:"
        "save_if_main"
    )


def test_early_exit_collective_fires_once():
    findings = rule_collectives.check_module(
        fixture("bad_early_exit_collective.py")
    )
    assert [f.rule for f in findings] == [rule_collectives.RULE_EARLY_EXIT]
    assert "drain_global" in findings[0].why


def test_swallowed_collective_fires_once():
    findings = rule_collectives.check_module(
        fixture("bad_swallowed_collective.py")
    )
    assert [f.rule for f in findings] == [rule_collectives.RULE_SWALLOWED]
    assert "OSError" in findings[0].why


def test_bypassable_reraise_still_swallows(tmp_path):
    """A top-level raise that a conditional return can bypass is NOT a
    re-raise guarantee — the host taking the bypass branch swallows while
    a peer re-raises (review-hardened case)."""
    src = (
        "def boundary(telemetry, ring, consume, step, can_recover, retry):\n"
        "    try:\n"
        "        telemetry.flush_boundary(ring, consume, step_hint=step)\n"
        "    except OSError:\n"
        "        if can_recover():\n"
        "            return retry()\n"
        "        raise\n"
        "\n"
        "def boundary_ok(telemetry, ring, consume, step, log):\n"
        "    try:\n"
        "        telemetry.flush_boundary(ring, consume, step_hint=step)\n"
        "    except OSError:\n"
        "        log('failed')\n"
        "        raise\n"
    )
    path = str(tmp_path / "_tmp_bypass.py")
    with open(path, "w") as f:
        f.write(src)
    findings = rule_collectives.check_module(
        core.load_module(path, repo_root=str(tmp_path))
    )
    # the bypassable handler fires; the unconditional re-raise does not
    assert [f.rule for f in findings] == [rule_collectives.RULE_SWALLOWED]
    assert "boundary" in findings[0].allowlist_key
    assert "boundary_ok" not in findings[0].allowlist_key


def test_loop_nested_bypass_still_swallows(tmp_path):
    """A return nested in a for/while before the raise bypasses it (the
    review-hardened compound-statement case); a loop-LOCAL break binds to
    that loop and is not a handler exit, so the trailing raise holds."""
    src = (
        "def retry_loop(telemetry, ring, consume, step, retries, retry):\n"
        "    try:\n"
        "        telemetry.flush_boundary(ring, consume, step_hint=step)\n"
        "    except OSError:\n"
        "        for r in retries:\n"
        "            return retry(r)\n"
        "        raise\n"
        "\n"
        "def scan_then_raise(telemetry, ring, consume, step, retries, ok):\n"
        "    try:\n"
        "        telemetry.flush_boundary(ring, consume, step_hint=step)\n"
        "    except OSError:\n"
        "        for r in retries:\n"
        "            if ok(r):\n"
        "                break\n"
        "        raise\n"
    )
    path = str(tmp_path / "_tmp_loop_bypass.py")
    with open(path, "w") as f:
        f.write(src)
    findings = rule_collectives.check_module(
        core.load_module(path, repo_root=str(tmp_path))
    )
    assert [f.rule for f in findings] == [rule_collectives.RULE_SWALLOWED]
    assert "retry_loop" in findings[0].allowlist_key
    assert "scan_then_raise" not in findings[0].allowlist_key


def test_donated_read_fires_once():
    """The PR-1 reconstruction: the crash handler reads the donated state."""
    findings = rule_donation.check_module(fixture("bad_donated_read.py"))
    assert [f.rule for f in findings] == [rule_donation.RULE]
    f = findings[0]
    assert "'state'" in f.why and "donated" in f.why
    # the finding anchors on the post-donation READ, not the call
    assert f.line > 0


def test_donation_loop_without_rebind_fires(tmp_path):
    """A loop that re-dispatches the same donated object every iteration."""
    src = (
        "def run(update_fn, state, images, key):\n"
        "    for _ in range(3):\n"
        "        update_fn(state, images, key)\n"
    )
    path = str(tmp_path / "_tmp_loop.py")
    with open(path, "w") as f:
        f.write(src)
    findings = rule_donation.check_module(
        core.load_module(path, repo_root=str(tmp_path))
    )
    assert [f.rule for f in findings] == [rule_donation.RULE]
    assert "loop" in findings[0].why


def test_hotloop_sync_and_bare_annotation_fire():
    """float() in the boundary loop fires; the reasoned sync-ok site is
    suppressed; the bare marker fires the missing-reason rule."""
    findings = rule_hotloop.check_module(fixture("bad_hotloop_sync.py"))
    rules = sorted(f.rule for f in findings)
    assert rules == sorted([
        rule_hotloop.RULE_LOOP, rule_hotloop.RULE_ANNOTATION,
    ])
    loop_f = next(f for f in findings if f.rule == rule_hotloop.RULE_LOOP)
    assert "float()" in loop_f.why


def test_hotloop_jit_fires_once():
    findings = rule_hotloop.check_module(fixture("bad_hotloop_jit.py"))
    assert [f.rule for f in findings] == [rule_hotloop.RULE_JIT]
    assert "np.asarray" in findings[0].why


def test_pallas_kernel_sync_fires_once():
    """np.asarray inside a kernel handed to pallas_call via the
    intermediate-partial shape fires the pallas-kernel region; the clean
    kernel beside it stays silent."""
    findings = rule_hotloop.check_module(fixture("bad_pallas_kernel_sync.py"))
    assert [f.rule for f in findings] == [rule_hotloop.RULE_KERNEL]
    assert "_bad_kernel" in findings[0].why
    assert "Pallas kernel builder" in findings[0].why


def test_real_pallas_kernel_modules_are_clean():
    """The production kernel modules (ops/pallas_loss.py,
    ops/pallas_conv.py) pass the extended hot-loop rule: their kernel
    builders contain no sync-forcing host ops."""
    pkg = os.path.join(REPO, "simclr_pytorch_distributed_tpu", "ops")
    expected = {
        # every kernel builder must be under coverage — the builders all
        # reuse the local name 'kernel =' for their partial, so a
        # last-binding-wins resolution would silently drop most of them
        "pallas_loss.py": {"_fwd_kernel", "_bwd_kernel"},
        "pallas_conv.py": {"_stem_fwd_kernel", "_stem_bwd_kernel",
                           "_block_fwd_kernel", "_block_bwd_kernel"},
    }
    for name, want in expected.items():
        mod = core.load_module(os.path.join(pkg, name), repo_root=REPO)
        kernels = {f.name for f in rule_hotloop._pallas_kernel_functions(mod)}
        assert want <= kernels, f"{name}: {want - kernels} not covered"
        assert rule_hotloop.check_module(mod) == []


def test_metric_keys_unsorted_fires_once():
    findings = rule_registry.check_metric_keys([fixture("bad_metric_keys.py")])
    assert [f.rule for f in findings] == [rule_registry.RULE_KEYS_SORTED]
    assert "FIXTURE_METRIC_KEYS" in findings[0].why


def test_metric_keys_multi_source_fires_once():
    findings = rule_registry.check_metric_keys([
        fixture("bad_metric_keys_copy.py"), fixture("bad_metric_keys_dup.py"),
    ])
    assert [f.rule for f in findings] == [rule_registry.RULE_KEYS_DUP]
    assert "FIXTURE_DUP_METRIC_KEYS" in findings[0].why


def test_schema_literal_fires_once():
    mod = core.load_module(
        os.path.join(FIXTURES, "scripts", "bad_schema_literal.py"),
        repo_root=FIXTURES,
    )
    assert mod.rel == "scripts/bad_schema_literal.py"
    findings = rule_registry.check_schema_stamps([mod])
    assert [f.rule for f in findings] == [rule_registry.RULE_SCHEMA]


def test_flag_type_mismatch_fires_once():
    findings = rule_registry.check_parser_flags(fixture("bad_flag_type.py"))
    assert [f.rule for f in findings] == [rule_registry.RULE_FLAG_TYPE]
    assert "--print_freq" in findings[0].why


def test_shared_flag_inline_fires_once():
    findings = rule_registry.check_parser_flags(fixture("bad_flag_inline.py"))
    assert [f.rule for f in findings] == [rule_registry.RULE_FLAG_INLINE]
    assert "--telemetry" in findings[0].why


def test_shared_flag_default_mismatch_fires_once():
    findings = rule_registry.check_parser_flags(
        fixture("bad_flag_default.py")
    )
    assert [f.rule for f in findings] == [rule_registry.RULE_FLAG_DEFAULT]
    assert "--telemetry" in findings[0].why


def test_rebound_donation_is_clean(tmp_path):
    """The canonical `state, ring = update_fn(state, ring, ...)` rotation
    must NOT fire — it is the whole tree's correct shape."""
    src = (
        "def run(update_fn, state, ring, batches, key):\n"
        "    for images, labels in batches:\n"
        "        state, ring = update_fn(state, ring, images, labels, key)\n"
        "    return state\n"
    )
    path = str(tmp_path / "_tmp_clean.py")
    with open(path, "w") as f:
        f.write(src)
    findings = rule_donation.check_module(
        core.load_module(path, repo_root=str(tmp_path))
    )
    assert findings == []


def test_uniform_conditionals_are_clean(tmp_path):
    """process_count short-circuits and epoch-uniform tests are the repo's
    standard shapes — not hazards."""
    src = (
        "def boundary(telemetry, jax, epoch, save_freq, step):\n"
        "    if jax.process_count() == 1:\n"
        "        return\n"
        "    telemetry.check_failures_global(step)\n"
        "    if epoch % save_freq == 0:\n"
        "        telemetry.drain_global(step)\n"
    )
    path = str(tmp_path / "_tmp_uniform.py")
    with open(path, "w") as f:
        f.write(src)
    findings = rule_collectives.check_module(
        core.load_module(path, repo_root=str(tmp_path))
    )
    assert findings == []


# -- the clean-tree contract ---------------------------------------------

def test_clean_tree_no_unallowlisted_findings():
    """The full linter over the real tree: zero findings, and every
    allowlist entry both used and reasoned (stale entries are findings,
    so this also pins allowlist hygiene)."""
    result = run_lint(REPO)
    assert result["findings"] == [], "\n".join(
        f.render() for f in result["findings"]
    )
    assert result["rules_run"] == list(runner.RULE_FAMILIES)
    assert result["files_scanned"] > 50  # the whole tree, not a subset
    # the one designed matched point (train/supcon.py NaN rollback) matched
    assert all(a["findings"] for a in result["allowlisted"])


def test_allowlist_entries_carry_reasons():
    allowlist_mod.validate()  # must not raise on the committed allowlist
    with pytest.raises(ValueError, match="no reason"):
        run_lint(REPO, allowlist={"some:key": "  "})


def test_stale_allowlist_entry_is_a_finding():
    result = run_lint(REPO, allowlist={"bogus:key:never:matches": "reason"})
    stale = [f for f in result["findings"]
             if f.rule == runner.RULE_STALE]
    assert len(stale) == 1 and "bogus:key:never:matches" in stale[0].why


def test_analysis_package_is_stdlib_only():
    """The linter must run without jax: no analysis module may import
    jax/numpy/flax (the package PARENT's convenience re-export is outside
    this contract and documented in docs/ANALYSIS.md)."""
    import ast as ast_mod

    adir = os.path.join(REPO, "simclr_pytorch_distributed_tpu", "analysis")
    banned = {"jax", "numpy", "np", "flax", "optax", "orbax"}
    for fn in sorted(os.listdir(adir)):
        if not fn.endswith(".py"):
            continue
        with open(os.path.join(adir, fn)) as f:
            tree = ast_mod.parse(f.read())
        for node in ast_mod.walk(tree):
            mods = []
            if isinstance(node, ast_mod.Import):
                mods = [a.name.split(".")[0] for a in node.names]
            elif isinstance(node, ast_mod.ImportFrom) and node.module:
                mods = [node.module.split(".")[0]]
            assert not (set(mods) & banned), f"{fn} imports {mods}"


# -- artifact, CLI, and the ratchet gate ----------------------------------

def test_build_output_schema_pinned():
    out = build_output(run_lint(REPO))
    assert out["schema"] == runner.SCHEMA == "invariant_lint/v1"
    assert out["ok"] is True and out["n_findings"] == 0
    assert set(out) == {
        "schema", "ok", "n_findings", "findings", "allowlisted",
        "files_scanned", "rules_run",
    }
    json.dumps(out)  # JSON-safe


def test_cli_runs_without_jax(tmp_path):
    """The linter's whole point is running anywhere instantly: the CLI
    must work on a box with NO jax (the package parent's re-export is
    lazy, PEP 562). A meta-path blocker makes any jax/flax/optax/orbax
    import raise — the CLI must still lint the tree and exit 0."""
    blocker = tmp_path / "noheavy.py"
    blocker.write_text(
        "import sys\n"
        "class _Block:\n"
        "    BANNED = {'jax', 'jaxlib', 'flax', 'optax', 'orbax'}\n"
        "    def find_spec(self, name, path=None, target=None):\n"
        "        if name.split('.')[0] in self.BANNED:\n"
        "            raise ImportError(f'{name} blocked for the jax-free "
        "lint contract')\n"
        "        return None\n"
        "sys.meta_path.insert(0, _Block())\n"
        "import runpy\n"
        "sys.argv = sys.argv[1:]\n"
        "runpy.run_path(sys.argv[0], run_name='__main__')\n"
    )
    proc = subprocess.run(
        [sys.executable, str(blocker),
         os.path.join(REPO, "scripts", "invariant_lint.py")],
        cwd=REPO, capture_output=True, text=True,
    )
    assert proc.returncode == 0, proc.stdout + proc.stderr
    assert "invariant_lint: 0 finding(s)" in proc.stdout


def test_cli_exits_zero_and_writes_artifact(tmp_path):
    out_json = tmp_path / "lint.json"
    proc = subprocess.run(
        [sys.executable, "scripts/invariant_lint.py", "--json",
         str(out_json)],
        cwd=REPO, capture_output=True, text=True,
    )
    assert proc.returncode == 0, proc.stdout + proc.stderr
    with open(out_json) as f:
        artifact = json.load(f)
    assert artifact["ok"] is True
    assert "invariant_lint: 0 finding(s)" in proc.stdout


def _ratchet():
    spec = importlib.util.spec_from_file_location(
        "ratchet", os.path.join(REPO, "scripts", "ratchet.py")
    )
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def test_lint_gate_record_pass_fail_matrix():
    ratchet = _ratchet()
    good = build_output(run_lint(REPO))
    rec = ratchet.lint_gate_record(good)
    assert rec["ok"] is True and rec["metric"] == "ratchet_invariant_lint"

    bad_schema = dict(good, schema="nope/v1")
    assert ratchet.lint_gate_record(bad_schema)["ok"] is False

    missing_rule = dict(good, rules_run=good["rules_run"][:-1])
    rec = ratchet.lint_gate_record(missing_rule)
    assert rec["ok"] is False and "did not run" in rec["error"]

    with_finding = dict(
        good, ok=False, n_findings=1,
        findings=[{"rule": "donation-safety:post-donation-read",
                   "file": "x.py", "line": 3, "why": "w",
                   "allowlist_key": "k"}],
    )
    rec = ratchet.lint_gate_record(with_finding)
    assert rec["ok"] is False and "x.py:3" in rec["error"]

    no_reason = dict(
        good,
        allowlisted=[{"key": "k", "reason": " ", "findings": [{}]}],
    )
    rec = ratchet.lint_gate_record(no_reason)
    assert rec["ok"] is False and "no reason" in rec["error"]


def test_ratchet_default_list_includes_lint_gate():
    ratchet = _ratchet()
    assert "invariant_lint" in ratchet.CONFIGS
    assert ratchet.CONFIGS["invariant_lint"]["kind"] == "invariant_lint"


def test_committed_evidence_passes_gate():
    """The committed docs/evidence artifact re-verifies under the pure
    gate record — the acceptance-criteria bind."""
    # r19: regenerated after the fused-conv ladder round (bf16 kernels,
    # projection/Bottleneck blocks) reshaped ops/pallas_conv.py,
    # models/resnet.py, and scripts/convblock_ab.py in place (101 files —
    # no new files joined the surface, the scanned set's contents moved)
    path = os.path.join(REPO, "docs", "evidence", "invariant_lint_r19.json")
    with open(path) as f:
        artifact = json.load(f)
    ratchet = _ratchet()
    rec = ratchet.lint_gate_record(artifact)
    assert rec["ok"] is True, rec
    # the artifact reflects the current allowlist (no silent drift): same
    # keys as a fresh run
    fresh = build_output(run_lint(REPO))
    assert (
        [a["key"] for a in artifact["allowlisted"]]
        == [a["key"] for a in fresh["allowlisted"]]
    )
