"""Tests for the scripts/ gate + measurement tooling.

The reference ships no CI tooling at all (SURVEY.md §4); this repo's round
gates (`scripts/ratchet.py`, `scripts/northstar.py`) and PERF.md evidence
(`scripts/xplane_bw.py`, `scripts/crop_ab.py`, `scripts/_honest_timing.py`)
hang off small parsing/summary functions that until now were only exercised
by the full chip runs. A silent parse regression there would let a failing
accuracy gate read as green — worth pinning with fast CPU tests.
"""

import importlib.util
import json
import os
import sys

import jax.numpy as jnp
import numpy as np
import pytest

SCRIPTS = os.path.abspath(
    os.path.join(os.path.dirname(__file__), "..", "scripts")
)


def _load(name):
    spec = importlib.util.spec_from_file_location(
        name, os.path.join(SCRIPTS, f"{name}.py")
    )
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


# ---------------------------------------------------------------- ratchet


def test_ratchet_best_acc_takes_last_line(tmp_path):
    ratchet = _load("ratchet")
    log = tmp_path / "probe.log"
    log.write_text(
        "Train: [1][1/7] loss 2.3\n"
        "best accuracy: 41.20\n"
        "noise\n"
        "best accuracy: 96.43\n"
    )
    assert ratchet.best_acc(str(log)) == 96.43


def test_ratchet_best_acc_missing_raises(tmp_path):
    ratchet = _load("ratchet")
    log = tmp_path / "probe.log"
    log.write_text("no accuracy lines here\n")
    with pytest.raises(ratchet.ConfigFailed):
        ratchet.best_acc(str(log))


def test_ratchet_dead_config_emits_record_and_continues(tmp_path, monkeypatch, capsys):
    """The ConfigFailed pattern: one dead config must not skip the remaining
    gates or eat the summary line the CI parses."""
    ratchet = _load("ratchet")

    def fake_run_config(name, spec, epochs, bar, args):
        if name == "rn50_100ep":
            raise ratchet.ConfigFailed("simulated dead config")
        record = {
            "metric": f"ratchet_x_probe_top1_{name}", "value": 97.0,
            "bar": bar, "ok": True,
        }
        print(json.dumps(record), flush=True)
        return record

    monkeypatch.setattr(ratchet, "run_config", fake_run_config)
    monkeypatch.setattr(
        sys, "argv",
        ["ratchet.py", "--configs", "rn50_100ep", "rn18_100ep",
         "--workdir", str(tmp_path)],
    )
    with pytest.raises(SystemExit) as exc:
        ratchet.main()
    assert exc.value.code == 1  # the dead config fails the gate...

    lines = [json.loads(l) for l in capsys.readouterr().out.splitlines()]
    summary = lines[-1]
    assert summary["metric"] == "ratchet_gate" and summary["ok"] is False
    # ...but BOTH configs appear in the summary, the dead one with value None
    assert len(summary["configs"]) == 2
    dead = [r for r in lines[:-1] if r.get("value") is None]
    assert len(dead) == 1 and "simulated dead config" in dead[0]["error"]
    assert any(r.get("value") == 97.0 for r in lines[:-1])


# -------------------------------------------------------------- northstar


def test_northstar_parse_probe_log_top5_and_fallback(tmp_path):
    northstar = _load("northstar")
    log = tmp_path / "probe.log"
    log.write_text(
        "best accuracy: 80.00, accuracy5: 99.00\n"
        "best accuracy: 84.76, accuracy5: 99.36\n"
    )
    assert northstar.parse_probe_log(str(log)) == (84.76, 99.36)
    # top1-only fallback (older probe logs)
    log.write_text("best accuracy: 84.76\n")
    assert northstar.parse_probe_log(str(log)) == (84.76, None)
    log.write_text("nothing\n")
    with pytest.raises(northstar.PointFailed):
        northstar.parse_probe_log(str(log))


def test_northstar_newest_run_dir(tmp_path):
    northstar = _load("northstar")
    models = tmp_path / "cifar10_models"
    models.mkdir()
    older = models / "run_a_trial_t_cosine"
    newer = models / "run_b_trial_t_cosine"
    other = models / "run_c_trial_other_cosine_warm"
    for d in (older, newer, other):
        d.mkdir()
    os.utime(older, (1, 1))
    os.utime(newer, (2, 2))
    got = northstar.newest_run_dir(str(tmp_path), "cifar10", "trial_t_cosine")
    assert got == str(newer)
    with pytest.raises(northstar.PointFailed):
        northstar.newest_run_dir(str(tmp_path), "cifar10", "trial_missing")


def test_northstar_published_points_match_baseline():
    """Every number the north star gates against must appear verbatim in
    BASELINE.md's published table (reference README.md:44-45,51-52) — the
    two must not drift apart."""
    northstar = _load("northstar")
    repo = os.path.dirname(SCRIPTS)
    with open(os.path.join(repo, "BASELINE.md")) as f:
        baseline_md = f.read()
    for points in northstar.PUBLISHED.values():
        for top1, top5 in points.values():
            assert f"{top1:.2f}%" in baseline_md
            assert f"{top5:.2f}%" in baseline_md


# ---------------------------------------------------- crop A/B + timing


def test_crop_gather_matches_matmul_crop():
    """The per-pixel-gather reference in scripts/crop_ab.py and the
    production interpolation-matmul crop (ops/augment.py crop_and_resize)
    are the same bilinear sampler — on CPU (fp32 matmuls) they must agree
    to float tolerance, including at the borders."""
    crop_ab = _load("crop_ab")
    from simclr_pytorch_distributed_tpu.ops import augment

    rng = np.random.default_rng(3)
    img = jnp.asarray(rng.random((32, 32, 3), dtype=np.float32))
    boxes = [
        (0.0, 0.0, 32.0, 32.0),    # identity crop
        (5.0, 7.0, 20.0, 13.0),    # interior, non-square
        (0.0, 0.0, 1.0, 1.0),      # degenerate 1x1 crop
        (31.0, 31.0, 1.0, 1.0),    # bottom-right corner
        (10.5, 3.25, 15.5, 21.0),  # fractional origin/size
    ]
    for top, left, h, w in boxes:
        a = augment.crop_and_resize(
            img, jnp.float32(top), jnp.float32(left),
            jnp.float32(h), jnp.float32(w), 32,
        )
        b = crop_ab.crop_and_resize_gather(
            img, jnp.float32(top), jnp.float32(left),
            jnp.float32(h), jnp.float32(w), 32,
        )
        np.testing.assert_allclose(a, b, atol=1e-5, err_msg=str((top, left, h, w)))


def test_honest_timing_harness_smoke():
    """time_per_iter runs its chained fori_loop program and returns a
    finite nonnegative per-iteration time."""
    ht = _load("_honest_timing")

    def core(i, lead):
        return jnp.sum(lead) * 1e-20 + jnp.float32(i) * 0.0

    dt = ht.time_per_iter(core, (jnp.ones((16,), jnp.float32),), iters=4, windows=2)
    assert np.isfinite(dt) and dt >= 0.0


def test_honest_timing_rejects_degenerate_iters():
    """iters < 2 cannot subtract the dispatch floor: a bad CLI --iters flag
    must fail with a clear message BEFORE the warmup compiles, not with a
    ZeroDivisionError after them (ADVICE.md round 5)."""
    import pytest

    ht = _load("_honest_timing")

    def core(i, lead):
        return jnp.sum(lead)

    for bad in (1, 0, -3):
        with pytest.raises(ValueError, match="iters must be >= 2"):
            ht.time_per_iter(core, (jnp.ones((4,), jnp.float32),), iters=bad)


def test_crop_ab_patch_brackets_compilation():
    """The pipeline-level A/B patches augment.crop_and_resize at the
    make_core level (_patched_crop), so EVERY trace of the timed program —
    including re-traces from jit cache misses — sees the selected backend
    (ADVICE.md round 5: an inside-the-core patch only covered the first
    trace)."""
    import jax

    crop_ab = _load("crop_ab")
    from simclr_pytorch_distributed_tpu.ops import augment

    orig = augment.crop_and_resize
    seen = []

    def fake_crop(img, top, left, h, w, out_size):
        seen.append(1)
        return orig(img, top, left, h, w, out_size)

    core = crop_ab._pipeline_core(fake_crop)
    imgs = jnp.ones((2, 32, 32, 3), jnp.float32) * 128.0
    with crop_ab._patched_crop(fake_crop):
        assert augment.crop_and_resize is fake_crop
        out = core(0, imgs, jax.random.key(0))
        assert np.isfinite(float(out))
    assert augment.crop_and_resize is orig  # restored after the window
    assert seen  # the selected backend was actually traced

    # outside the patch window the core refuses to run (the trace would
    # silently time the production backend)
    with pytest.raises(AssertionError, match="_patched_crop"):
        core(0, imgs, jax.random.key(0))


# ---------------------------------------------------------- h2d_overlap_ab


def test_h2d_build_output_single_run_keeps_variants_schema():
    h2d = _load("h2d_overlap_ab")
    records = [{"resident": 64.5, "put_then_step": 70.1, "step_then_put": 66.0}]
    glitched = [{"resident": 0, "put_then_step": 1, "step_then_put": 0}]
    out = h2d.build_output(256, "cpu", records, glitched)
    assert out["variants"] == records[0]
    assert out["windows_discarded_as_clock_glitch"] == glitched[0]
    assert "runs" not in out


def test_h2d_build_output_multi_run_emits_committed_schema():
    """--runs N must emit the {runs: [...]} schema of the committed
    docs/evidence/h2d_overlap_ab_r5.json artifact (ADVICE.md round 5: the
    artifact was hand-assembled from a schema the script never produced)."""
    h2d = _load("h2d_overlap_ab")
    records = [
        {"resident": 64.5, "put_then_step": 70.1, "step_then_put": 66.0},
        {"resident": 64.8, "put_then_step": 69.0, "step_then_put": 74.4},
        {"resident": 65.1, "put_then_step": 65.0, "step_then_put": 65.7},
    ]
    glitched = [{"resident": 0, "put_then_step": 1, "step_then_put": 0}] * 3
    out = h2d.build_output(256, "TPU v5 lite", records, glitched)
    assert out["runs"] == records and "variants" not in out
    assert out["windows_discarded_as_clock_glitch"] == 3  # summed, as committed
    assert out["metric"] == "h2d_overlap_ab_step_ms" and out["batch"] == 256
    # committed artifact's key set, exactly
    import os

    with open(os.path.join(
        os.path.dirname(SCRIPTS), "docs", "evidence", "h2d_overlap_ab_r5.json"
    )) as f:
        committed = json.load(f)
    assert set(out) == set(committed)


# ------------------------------------------------------------- serve_bench


@pytest.mark.serve
def test_serve_bench_smoke_end_to_end(tmp_path):
    """The acceptance run: engine → batcher → cache → HTTP endpoint on CPU,
    artifact written, no recompiles within buckets, cache pass skipped the
    engine."""
    serve_bench = _load("serve_bench")
    out_path = tmp_path / "serve_bench_smoke.json"
    out = serve_bench.main(["--smoke", "--json", str(out_path)])

    with open(out_path) as f:
        artifact = json.load(f)
    assert artifact == json.loads(json.dumps(out))  # what returned is what landed
    assert artifact["metric"] == "serve_bench" and artifact["mode"] == "smoke"
    # one compile per bucket, ever — request sizes varied within buckets
    assert all(n == 1 for n in artifact["engine_stats"]["traces"].values())
    assert set(artifact["engine_stats"]["traces"]) == {"2", "8"}
    # both loops produced latency populations with sane percentiles
    for loop in ("closed_loop", "open_loop"):
        assert artifact[loop]["requests"] > 0
        for pcts in artifact[loop]["latency_by_bucket"].values():
            assert pcts["p50_ms"] <= pcts["p95_ms"] <= pcts["p99_ms"]
    # the cache answered the duplicate pass without touching the engine
    assert artifact["cache"]["hit_rows"] == 4
    assert artifact["cache"]["extra_dispatches"] == 0
    # the real HTTP endpoint served /healthz, /embed (both encodings), /stats
    assert artifact["http"]["healthz"] == "ok"
    assert artifact["http"]["embed_n"] == 2 and artifact["http"]["embed_dim"] == 512
    assert artifact["http"]["encodings_agree"] is True
    assert artifact["batcher_stats"]["errors"] == 0


@pytest.mark.serve
def test_serve_bench_sweep_smoke_end_to_end(tmp_path):
    """The saturation-sweep acceptance run on CPU: both comparison arms
    (synchronous baseline, pipelined) climb the offered-rate ladder through
    the REAL assembler -> inflight window -> completer stack, per-window
    inflight gauges land in the artifact, and the pipelined arm PROVABLY
    held >1 batch in flight while the baseline never did."""
    serve_bench = _load("serve_bench")
    out_path = tmp_path / "serve_bench_sweep_smoke.json"
    out = serve_bench.main(["--smoke", "--sweep", "--json", str(out_path)])

    with open(out_path) as f:
        artifact = json.load(f)
    assert artifact == json.loads(json.dumps(out))
    assert artifact["metric"] == "serve_bench_sweep"
    assert artifact["mode"] == "smoke"
    for arm, inflight in (("baseline", 1), ("pipelined", 3)):
        a = artifact[arm]
        assert a["max_inflight"] == inflight
        assert len(a["windows"]) >= 1
        assert a["saturated_imgs_per_s"] > 0
        for w in a["windows"]:
            assert w["requests_completed"] > 0
            assert w["latency"]["p50_ms"] <= w["latency"]["p99_ms"]
            assert 0.0 <= w["inflight"]["pipeline_occupancy"] <= 1.0
            assert (
                w["inflight"]["dispatched_batches"]
                >= w["inflight"]["batches"]
            )
    # the pipelined arm really pipelined; the baseline arm never could
    assert max(
        w["inflight"]["max_inflight_observed"]
        for w in artifact["pipelined"]["windows"]
    ) > 1
    assert all(
        w["inflight"]["max_inflight_observed"] <= 1
        for w in artifact["baseline"]["windows"]
    )
    # one compile per bucket ACROSS both arms and the HTTP round trip —
    # the ladder never re-traced
    assert artifact["engine_stats"]["traces"] == {"2": 1, "8": 1}
    assert artifact["http"]["healthz"] == "ok"
    assert artifact["saturated_speedup"] > 0
    # the mixed-tenant multi-model arm: both hosted versions served their
    # skewed tenant's requests through the registry with zero errors
    mm = artifact["multi_model"]
    assert mm["tenancy"] == {"bulk": "prod", "interactive": "canary"}
    assert mm["requests"] > 0 and mm["throughput_imgs_per_s"] > 0
    per_model = mm["per_model"]
    assert set(per_model) == {"prod", "canary"}
    assert per_model["prod"]["requests"] > per_model["canary"]["requests"]
    for m in per_model.values():
        assert m["errors"] == 0
        if m["latency"]:
            assert m["latency"]["p50_ms"] <= m["latency"]["p99_ms"]
    assert mm["admission"]["rejected"] == 0  # quota disabled in the bench
    # the retrieval arm: closed-loop /neighbors under mixed /embed load,
    # once per impl rung on the SAME workload stream — the IVF arm reached
    # the trained path (not just the provisional single-list rung) and
    # both indexes ingested the identical corpus
    ra = artifact["retrieval"]
    assert set(ra["per_impl"]) == {"brute", "ivf"}
    brute, ivf = ra["per_impl"]["brute"], ra["per_impl"]["ivf"]
    assert brute["index"]["entries"] == ivf["index"]["entries"] > 0
    assert brute["neighbors_queries"] == ivf["neighbors_queries"] > 0
    for arm in (brute, ivf):
        assert arm["index"]["queries"] == arm["neighbors_queries"]
        assert arm["query_latency"]["p50_ms"] <= arm["query_latency"]["p99_ms"]
    assert ivf["index"]["trained_lists"] == ra["nlist"]
    assert ivf["index"]["retrains"] >= 1
    # early queries land on the untrained single-list rung (1 probe each),
    # later ones fan out to nprobe lists
    assert (ivf["neighbors_queries"] <= ivf["index"]["probes"]
            <= ra["nprobe"] * ivf["neighbors_queries"])
    assert ra["query_p50_ratio_brute_over_ivf"] is not None


# ------------------------------------------------------------ retrieval_ab


def _retrieval_rung(rows, recall=1.0, speedup=6.0):
    return {
        "rows": rows, "recall_at_k": recall, "speedup_p50": speedup,
        "insert_ms": {"brute": 1.0, "ivf": 2.0}, "runs": [],
        "lat_ms": {"brute": {"p50": 10.0, "p99": 20.0, "n": 16},
                   "ivf": {"p50": 2.0, "p99": 4.0, "n": 16}},
        "ivf_stats": {"trained_lists": 8, "retrains": 1},
    }


def test_retrieval_ab_build_output_schema():
    """The committed docs/evidence/retrieval_ab_r18.json schema, pinned
    without building a 262144-row index (the window_ab pattern)."""
    retrieval_ab = _load("retrieval_ab")
    rungs = [_retrieval_rung(4096), _retrieval_rung(65536, 0.98, 50.0)]
    oracle = {"ids_identical": True, "scores_bit_identical": True,
              "queries_checked": 32, "rungs_checked": [4096, 65536]}
    out = retrieval_ab.build_output(
        "cpu", {"dim": 64, "k": 10, "nprobe": 8}, rungs, oracle
    )
    assert out["schema"] == retrieval_ab.SCHEMA == "retrieval_ab/v1"
    assert out["metric"] == "retrieval_query_ms"
    assert "ABBA" in out["arm_order"]
    s = out["summary"]
    assert s["min_recall_at_k"] == 0.98
    assert s["max_rung_rows"] == 65536 and s["speedup_p50_max_rung"] == 50.0
    assert s["recall_bar"] == retrieval_ab.RECALL_BAR
    assert [r["rows"] for r in s["per_rung"]] == [4096, 65536]
    with open(os.path.join(
        os.path.dirname(SCRIPTS), "docs", "evidence", "retrieval_ab_r18.json"
    )) as f:
        committed = json.load(f)
    assert set(out) == set(committed)


def test_retrieval_ab_smoke_oracle_and_recall(tmp_path):
    """The real A/B end-to-end on tiny rungs: both indexes built from the
    same chunked insert stream, the brute arm bit-checked against the
    frozen PR-17 scoring oracle on EVERY rung before any timing, IVF
    recall measured against the brute answers, artifact committed."""
    retrieval_ab = _load("retrieval_ab")
    out_path = tmp_path / "retrieval_ab.json"
    out = retrieval_ab.main(["--smoke", "--json", str(out_path)])
    artifact = json.loads(out_path.read_text())
    assert artifact == json.loads(json.dumps(out))
    assert artifact["schema"] == "retrieval_ab/v1"
    oracle = artifact["oracle"]
    assert oracle["ids_identical"] and oracle["scores_bit_identical"]
    assert oracle["rungs_checked"] == [1024, 4096]
    assert oracle["queries_checked"] > 0
    # clustered smoke corpora: the trained quantizer holds the recall bar
    assert artifact["summary"]["min_recall_at_k"] >= 0.95
    top = max(artifact["rungs"], key=lambda r: r["rows"])
    assert top["ivf_stats"]["trained_lists"] > 1  # not the provisional rung
    assert top["speedup_p50"] > 0


# -------------------------------------------------------------- xplane_bw


def _varint(n):
    out = b""
    while True:
        b7 = n & 0x7F
        n >>= 7
        out += bytes([b7 | (0x80 if n else 0)])
        if not n:
            return out


def test_xplane_parse_breakdown_wire_decode():
    """_parse_breakdown hand-decodes the repeated MemoryAccessed block
    (field 1, LEN-delimited) because the wrapper message type is not
    exported by the installed xprof protos — pin the framing."""
    op_metrics_pb2 = pytest.importorskip("xprof.protobuf.op_metrics_pb2")
    xplane_bw = _load("xplane_bw")
    MA = op_metrics_pb2.OpMetrics.MemoryAccessed
    hbm = op_metrics_pb2.MemorySpace.Value("MEMORY_SPACE_HBM")

    msgs = [
        MA(memory_space=hbm, bytes_accessed=12345),
        MA(memory_space=hbm, bytes_accessed=2**40),
    ]
    payloads = [m.SerializeToString() for m in msgs]
    raw = b"\x0a" + _varint(len(payloads[0])) + payloads[0]
    # a MemoryAccessed message can never exceed 127 bytes, so force the
    # multi-byte length-varint continuation path with the (legal)
    # non-canonical two-byte encoding of the same length
    ln = len(payloads[1])
    assert ln < 128
    raw += b"\x0a" + bytes([(ln & 0x7F) | 0x80, 0x00]) + payloads[1]

    got = xplane_bw._parse_breakdown(raw, MA)
    assert [g.bytes_accessed for g in got] == [12345, 2**40]
    assert all(g.memory_space == hbm for g in got)

    # an unknown field tag after the repeated block stops the scan cleanly
    got2 = xplane_bw._parse_breakdown(raw + b"\x12\x00", MA)
    assert [g.bytes_accessed for g in got2] == [12345, 2**40]


# ----------------------------------------------------------------- flush_ab


def test_flush_ab_build_output_schema():
    """The committed docs/evidence/flush_ab_r6.json schema, pinned without
    running the measurement (the h2d_overlap_ab pattern)."""
    flush_ab = _load("flush_ab")
    rounds = [
        {"sync": [12.0, 11.8], "async": [7.1, 7.0]},
        {"sync": [12.4, 12.2], "async": [7.3, 6.9]},
    ]
    out = flush_ab.build_output("cpu", 60.0, 10, 3, rounds)
    assert out["metric"] == "flush_ab_ms_per_step"
    assert out["runs"] == rounds
    assert out["delay_ms"] == 60.0 and out["window"] == 10
    s = out["summary"]
    assert s["sync_ms_per_step"] == 12.1  # median of 4 sync measurements
    assert s["async_ms_per_step"] == 7.05
    assert s["stall_removed_ms_per_window"] == round((12.1 - 7.05) * 10, 1)
    assert s["speedup"] == round(12.1 / 7.05, 3)
    assert "ABBA" in out["arm_order"]


@pytest.mark.slow
def test_flush_ab_smoke_async_removes_stall(tmp_path):
    """End-to-end CPU proxy: with an injected per-flush transfer delay the
    async arm must be strictly faster per step than the sync arm (the whole
    point of the background executor) — same compiled update both arms."""
    flush_ab = _load("flush_ab")
    out_path = tmp_path / "flush_ab.json"
    out = flush_ab.main(["--smoke", "--rounds", "1", "--json", str(out_path)])
    s = out["summary"]
    # the sync arm pays delay_ms per window on the dispatch thread; the
    # async arm amortizes one drain-tail delay per arm. Require at least
    # half the injected stall to vanish (generous vs timer noise).
    assert s["async_ms_per_step"] < s["sync_ms_per_step"]
    assert s["stall_removed_ms_per_window"] > out["delay_ms"] / 2
    assert json.loads(out_path.read_text())["metric"] == "flush_ab_ms_per_step"


# --------------------------------------------------------------- resident_ab


def test_resident_ab_build_output_schema():
    """The committed docs/evidence/resident_ab_r7.json schema, pinned without
    running the measurement (the flush_ab/h2d_overlap_ab pattern)."""
    resident_ab = _load("resident_ab")
    rounds = [
        {"host": [300.0, 310.0], "device": [150.0, 148.0]},
        {"host": [305.0, 295.0], "device": [151.0, 149.0]},
    ]
    eq = {"equivalence_ok": True, "steps_compared": 16, "epochs": 2,
          "mid_epoch_resume_checked": True}
    out = resident_ab.build_output("cpu", 200.0, 8, 2, rounds, eq)
    assert out["metric"] == "resident_ab_ms_per_step"
    assert out["runs"] == rounds and out["equivalence"] == eq
    assert out["h2d_delay_ms"] == 200.0 and out["steps_per_epoch"] == 8
    s = out["summary"]
    assert s["host_ms_per_step"] == 302.5  # median of the 4 host arms
    assert s["device_ms_per_step"] == 149.5
    assert s["transfer_removed_ms_per_step"] == 153.0
    assert s["speedup"] == round(302.5 / 149.5, 3)
    assert "ABBA" in out["arm_order"]


@pytest.mark.resident
def test_resident_ab_smoke_device_arm_removes_per_step_transfer(tmp_path):
    """Tier-1 guard on the committed-artifact path (the serve_bench smoke
    pattern): the real script end-to-end on a tiny config — equivalence pass
    (byte-identical batches incl. mid-epoch resume), both compiled arms, the
    ABBA loop, and the JSON artifact. Under the injected serialized-link
    delay the device arm pays it once per EPOCH instead of once per STEP, so
    most of the per-step delay must vanish."""
    resident_ab = _load("resident_ab")
    out_path = tmp_path / "resident_ab.json"
    out = resident_ab.main([
        "--smoke", "--rounds", "1", "--steps", "4", "--epochs", "1",
        "--h2d_delay_ms", "120", "--json", str(out_path),
    ])
    assert out["equivalence"]["equivalence_ok"]
    assert out["equivalence"]["steps_compared"] == 8  # 2 epochs x 4 steps
    s = out["summary"]
    assert s["device_ms_per_step"] < s["host_ms_per_step"]
    # expected removal ~= delay * (1 - 1/steps) = 90 ms at these settings;
    # require a third of the delay (generous vs 1-core contention noise)
    assert s["transfer_removed_ms_per_step"] > out["h2d_delay_ms"] / 3
    artifact = json.loads(out_path.read_text())
    assert artifact["metric"] == "resident_ab_ms_per_step"
    assert artifact["equivalence"]["equivalence_ok"]


# --------------------------------------------------------------- window_ab


def test_window_ab_build_output_schema():
    """The committed docs/evidence/window_ab_r8.json schema, pinned without
    running the measurement (the resident_ab/flush_ab pattern)."""
    window_ab = _load("window_ab")
    rounds = [
        {"host": [250.0, 260.0], "window": [100.0, 98.0]},
        {"host": [255.0, 245.0], "window": [101.0, 99.0]},
    ]
    eq = {"equivalence_ok": True, "steps_compared": 16, "epochs": 2,
          "mid_epoch_resume_checked": True}
    out = window_ab.build_output("cpu", 200.0, 8, 4, 2, rounds, eq)
    assert out["metric"] == "window_ab_ms_per_step"
    assert out["runs"] == rounds and out["equivalence"] == eq
    assert out["h2d_delay_ms"] == 200.0 and out["steps_per_epoch"] == 8
    assert out["window_batches"] == 4
    s = out["summary"]
    assert s["host_ms_per_step"] == 252.5  # median of the 4 host arms
    assert s["window_ms_per_step"] == 99.5
    assert s["transfer_removed_ms_per_step"] == 153.0
    assert s["speedup"] == round(252.5 / 99.5, 3)
    assert "ABBA" in out["arm_order"]
    # the committed artifact carries this exact key set
    with open(os.path.join(
        os.path.dirname(SCRIPTS), "docs", "evidence", "window_ab_r8.json"
    )) as f:
        committed = json.load(f)
    assert set(out) == set(committed)


@pytest.mark.window
def test_window_ab_smoke_window_arm_amortizes_per_step_transfer(tmp_path):
    """Tier-1 guard on the committed-artifact path (the resident_ab smoke
    pattern): the real script end-to-end on a tiny config — equivalence
    pass (byte-identical batches incl. the window+offset mid-epoch resume),
    both compiled arms, the ABBA loop, and the JSON artifact. Under the
    injected serialized-link delay the window arm pays it once per WINDOW
    instead of once per STEP, so most of the per-step delay must vanish."""
    window_ab = _load("window_ab")
    out_path = tmp_path / "window_ab.json"
    out = window_ab.main([
        "--smoke", "--rounds", "1", "--steps", "4", "--epochs", "1",
        "--h2d_delay_ms", "120", "--json", str(out_path),
    ])
    assert out["equivalence"]["equivalence_ok"]
    assert out["equivalence"]["steps_compared"] == 8  # 2 epochs x 4 steps
    s = out["summary"]
    assert s["window_ms_per_step"] < s["host_ms_per_step"]
    # expected removal ~= delay * (1 - 1/window_batches) = 90 ms at these
    # settings (W=4); require a third of the delay (generous vs 1-core
    # contention noise)
    assert s["transfer_removed_ms_per_step"] > out["h2d_delay_ms"] / 3
    artifact = json.loads(out_path.read_text())
    assert artifact["metric"] == "window_ab_ms_per_step"
    assert artifact["equivalence"]["equivalence_ok"]


# ------------------------------------------------------- convblock_ab


def _convblock_parity(ok=True):
    return {
        "parity_ok": ok, "value_ok": ok, "grads_ok": True,
        "stats_ok": True, "max_abs_diffs": {"out": 1e-6 if ok else 0.5},
        "tolerances": {"value_atol": 3e-5, "grad_rtol": 1e-4,
                       "grad_atol": 1e-3},
    }


def test_convblock_ab_build_output_schema():
    """The committed docs/evidence/convblock_ab_r19.json schema (v2: one
    section per block kind x compute dtype), pinned without running the
    measurement (the window_ab pattern)."""
    convblock_ab = _load("convblock_ab")
    from simclr_pytorch_distributed_tpu.ops import pallas_conv

    runs = [
        {"xla": [120.0, 118.0], "pallas": [65.0, 64.0]},
        {"xla": [119.0, 121.0], "pallas": [66.0, 63.0]},
    ]
    blocks = {}
    for kind in ("basic", "bottleneck_bf16"):
        geo = convblock_ab.kind_geometry(kind, 32, 16, 16)
        # the kinds the artifact times must be kinds the resolution-time
        # gates actually admit (the full-config geometry)
        assert convblock_ab.kind_supported(kind, geo)
        base = kind.split("_bf16")[0]
        blocks[kind] = {
            "geometry": geo,
            "dtype": "bf16" if kind.endswith("_bf16") else "fp32",
            "bytes_scale": 0.5 if kind.endswith("_bf16") else 1.0,
            "traversals": convblock_ab.TRAVERSALS[base],
            "parity": _convblock_parity(), "runs": runs,
        }
    out = convblock_ab.build_output("cpu", 5.0, 8, blocks)
    assert out["schema"] == convblock_ab.SCHEMA == "convblock_ab/v2"
    assert out["metric"] == "convblock_ab_ms_per_step"
    assert out["parity_ok"] and "ABBA" in out["arm_order"]
    # traversal counts are the kernels' own constants, not free parameters
    assert convblock_ab.TRAVERSALS["basic"] == {"xla": 21, "pallas": 11}
    assert convblock_ab.TRAVERSALS["basic"]["pallas"] == (
        pallas_conv.FWD_HBM_TRAVERSALS_BLOCK
        + pallas_conv.BWD_HBM_TRAVERSALS_BLOCK
    )
    assert convblock_ab.TRAVERSALS["proj"] == {
        "xla": (pallas_conv.FWD_HBM_TRAVERSALS_PROJ_XLA
                + pallas_conv.BWD_HBM_TRAVERSALS_PROJ_XLA),
        "pallas": (pallas_conv.FWD_HBM_TRAVERSALS_PROJ
                   + pallas_conv.BWD_HBM_TRAVERSALS_PROJ),
    }
    assert convblock_ab.TRAVERSALS["bottleneck"] == {"xla": 32, "pallas": 14}
    b = out["blocks"]["basic"]
    assert b["runs"] == runs and b["parity"]["parity_ok"]
    s = b["summary"]
    assert s["xla_ms_per_step"] == 119.5  # median of the 4 xla arms
    assert s["pallas_ms_per_step"] == 64.5
    assert s["traversal_removed_ms_per_step"] == 55.0
    assert s["expected_removed_ms_per_step"] == 5.0 * (21 - 11)
    # the bf16 kind's expectation is bytes-scaled: half the bytes per
    # traversal is the reason the bf16 kernels exist
    s = out["blocks"]["bottleneck_bf16"]["summary"]
    assert s["expected_removed_ms_per_step"] == 5.0 * 0.5 * (32 - 14)
    # the committed artifact: same key set, ALL SIX kinds, parity green
    # and the traversal reduction realized per kind
    with open(os.path.join(
        os.path.dirname(SCRIPTS), "docs", "evidence", "convblock_ab_r19.json"
    )) as f:
        committed = json.load(f)
    assert set(out) == set(committed)
    assert set(committed["blocks"]) == set(convblock_ab.BLOCK_KINDS)
    for kind, cb in committed["blocks"].items():
        assert cb["parity"]["parity_ok"], kind
        cs = cb["summary"]
        assert cs["pallas_ms_per_step"] < cs["xla_ms_per_step"], kind
        assert cs["traversal_removed_ms_per_step"] > \
            cs["expected_removed_ms_per_step"] / 3, kind
        if kind.endswith("_bf16"):
            # bf16 parity binds on the derived agreement metrics
            m = cb["parity"]["bf16_metrics"]
            assert m["out"]["cos"] >= convblock_ab.BF16_VAL_COS_FLOOR, kind
            assert cb["parity"]["tolerances"]["grad_cos_floor"] == \
                convblock_ab.BF16_GRAD_COS_FLOOR


def test_convblock_ab_build_output_tolerates_broken_parity():
    """A broken-parity kind carries no timed rounds but must still write
    its artifact section (the ratchet gate carries the structured diffs):
    empty records produce None timing summaries, never a raise — and one
    broken kind poisons only the top-level parity_ok, not the healthy
    kinds' summaries."""
    convblock_ab = _load("convblock_ab")
    runs = [{"xla": [120.0, 118.0], "pallas": [65.0, 64.0]}]
    blocks = {
        "basic": {
            "geometry": convblock_ab.kind_geometry("basic", 16, 8, 8),
            "dtype": "fp32", "bytes_scale": 1.0,
            "traversals": convblock_ab.TRAVERSALS["basic"],
            "parity": _convblock_parity(), "runs": runs,
        },
        "proj_bf16": {
            "geometry": convblock_ab.kind_geometry("proj_bf16", 16, 8, 8),
            "dtype": "bf16", "bytes_scale": 0.5,
            "traversals": convblock_ab.TRAVERSALS["proj"],
            "parity": _convblock_parity(ok=False), "runs": [],
        },
    }
    out = convblock_ab.build_output("cpu", 5.0, 4, blocks)
    assert not out["parity_ok"]
    s = out["blocks"]["proj_bf16"]["summary"]
    assert s["xla_ms_per_step"] is None
    assert s["pallas_ms_per_step"] is None
    assert s["traversal_removed_ms_per_step"] is None
    assert s["speedup"] is None
    assert out["blocks"]["basic"]["summary"]["pallas_ms_per_step"] == 64.5
    # and the gate fails it on the parity verdict, everywhere, naming
    # the broken kind
    ratchet = _load("ratchet")
    rec = ratchet.convblock_gate_record(out)
    assert not rec["ok"] and "diverges" in rec["error"]
    assert "proj_bf16" in rec["error"]
    rec = ratchet.convblock_gate_record({**out, "device": "TPU v4"})
    assert not rec["ok"] and "proj_bf16" in rec["error"]


@pytest.mark.kernel
def test_convblock_ab_smoke_parity_and_traversal_removal(tmp_path):
    """Tier-1 guard on the committed-artifact path: the real script
    end-to-end on the tiny config — interpret-mode kernel parity gating
    each kind's timing, both timed arms, the ABBA loop, and the JSON
    artifact. One kind per base shape (the full six-kind sweep is the
    committed-artifact run): the identity BasicBlock in fp32 plus the two
    NEW round-19 fusions on their bf16 arms. Under the injected
    bytes-scaled per-traversal delay the pallas arm pays ~40% of the
    traversals, so most of the modeled delta must materialize."""
    convblock_ab = _load("convblock_ab")
    out_path = tmp_path / "convblock_ab.json"
    out = convblock_ab.main([
        "--smoke", "--rounds", "1", "--steps", "2", "--hbm_delay_ms", "15",
        "--kinds", "basic", "proj_bf16", "bottleneck_bf16",
        "--json", str(out_path),
    ])
    assert out["parity_ok"]
    assert set(out["blocks"]) == {"basic", "proj_bf16", "bottleneck_bf16"}
    for kind, b in out["blocks"].items():
        assert b["parity"]["parity_ok"], kind
        s = b["summary"]
        assert s["pallas_ms_per_step"] < s["xla_ms_per_step"], kind
        # e.g. basic: removal = 15 * (21 - 11) = 150 ms at these
        # settings; require a third (generous vs 1-core contention noise)
        assert s["traversal_removed_ms_per_step"] > \
            s["expected_removed_ms_per_step"] / 3, kind
    # bf16 sections carry the agreement metrics next to the raw diffs
    assert "bf16_metrics" in out["blocks"]["proj_bf16"]["parity"]
    assert "bf16_metrics" not in out["blocks"]["basic"]["parity"]
    artifact = json.loads(out_path.read_text())
    assert artifact["schema"] == convblock_ab.SCHEMA
    assert artifact["parity_ok"]


def test_ratchet_convblock_gate_decision():
    """The fused conv-block gate rides the default config list: per-kind
    kernel parity binds on EVERY device, the CPU-calibrated
    traversal-delay timing claim binds per kind on CPU and pass-skips
    off-CPU with the reason on record."""
    ratchet = _load("ratchet")
    assert "convblock" in ratchet.CONFIGS
    assert ratchet.CONFIGS["convblock"]["kind"] == "convblock_ab"

    def kind_section(xla=120.0, pallas=65.0, parity_ok=True):
        return {
            "summary": {"xla_ms_per_step": xla,
                        "pallas_ms_per_step": pallas},
            "parity": {"parity_ok": parity_ok, "value_ok": parity_ok,
                       "grads_ok": parity_ok, "stats_ok": parity_ok,
                       "max_abs_diffs": {"out": 1e-6}},
            "traversals": {"xla": 21, "pallas": 11},
        }

    def art(device="cpu", **kinds):
        kinds = kinds or {"basic": kind_section()}
        return {
            "blocks": kinds,
            "parity_ok": all(k["parity"]["parity_ok"]
                             for k in kinds.values()),
            "device": device,
        }

    r = ratchet.convblock_gate_record(
        art(basic=kind_section(), proj_bf16=kind_section(xla=60, pallas=30))
    )
    assert r["ok"] and "skipped" not in r
    assert r["metric"] == "ratchet_convblock_ab_parity"
    assert set(r["kinds"]) == {"basic", "proj_bf16"}
    # main()'s summary table requires "value" on every record
    assert r["value"] == 2
    # ONE broken kind's parity fails EVERYWHERE, even where timing
    # pass-skips, and the record names it
    r = ratchet.convblock_gate_record(art(
        device="TPU v4", basic=kind_section(),
        bottleneck_bf16=kind_section(parity_ok=False),
    ))
    assert not r["ok"] and "diverges" in r["error"]
    assert "bottleneck_bf16" in r["error"] and "basic:" not in r["error"]
    # an accelerator: parity enforced, CPU-calibrated timing skipped
    r = ratchet.convblock_gate_record(
        art(device="TPU v4", basic=kind_section(xla=64.9, pallas=65.2))
    )
    assert r["ok"] and "calibrated" in r["skipped"]
    # on CPU the timing claim binds per kind
    r = ratchet.convblock_gate_record(art(
        basic=kind_section(), proj=kind_section(xla=65.0, pallas=65.0),
    ))
    assert not r["ok"] and "not faster" in r["error"] and "proj" in r["error"]


# ------------------------------------------------------- ratchet bench gate


def test_ratchet_parse_bench_json_takes_last_metric_line(tmp_path):
    ratchet = _load("ratchet")
    log = tmp_path / "bench.log"
    log.write_text(
        "warmup noise\n"
        '{"run": 0, "variant": "x"}\n'
        '{"metric": "pretrain_imgs_per_sec_per_chip", "value": 100.0}\n'
        "not json {\n"
        '{"metric": "pretrain_imgs_per_sec_per_chip", "value": 4100.2, '
        '"vs_baseline": 1.0083}\n'
    )
    rec = ratchet.parse_bench_json(str(log))
    assert rec["value"] == 4100.2 and rec["vs_baseline"] == 1.0083

    (tmp_path / "empty.log").write_text("nothing\n")
    with pytest.raises(ratchet.ConfigFailed):
        ratchet.parse_bench_json(str(tmp_path / "empty.log"))


def test_ratchet_bench_gate_bar_and_config():
    """The perf bar (VERDICT #6) rides the default config list and its bar
    is 95% of the RECORDED repo baseline — bench.py and ratchet.py must
    agree on the number (single source of truth in bench.REPO_BASELINES)."""
    ratchet = _load("ratchet")
    import bench

    assert "bench_pretrain" in ratchet.CONFIGS
    spec = ratchet.CONFIGS["bench_pretrain"]
    assert spec["kind"] == "bench"
    # ONE series name for success and ConfigFailed records alike
    assert ratchet.bench_metric_name(spec) == (
        "ratchet_bench_pretrain_imgs_per_sec_per_chip"
    )
    assert bench.REPO_BASELINES["pretrain"] == 4066.5  # BENCH_r05 headline
    assert ratchet._bench_bar() == round(0.95 * 4066.5, 1)
    # vs_baseline now reads the recorded baseline, not the hardcoded 1.0
    assert bench.vs_baseline_for("pretrain", 4066.5) == 1.0
    assert bench.vs_baseline_for("pretrain", 2033.25) == 0.5
    assert bench.vs_baseline_for("linear", 999.0) == 1.0  # no record yet


def test_ratchet_bench_gate_decision():
    """The gate only enforces the chip-specific bar ON the baseline chip;
    elsewhere it pass-skips with the reason on record. On the baseline chip
    a clock_suspect run fails even above the bar — an inflated number must
    not mask a regression."""
    ratchet = _load("ratchet")
    import bench

    spec = ratchet.CONFIGS["bench_pretrain"]
    kind = bench.REPO_BASELINE_DEVICE_KIND

    def rec(value, device_kind, clock_suspect=False, chips=1):
        return {"value": value, "vs_baseline": 1.0,
                "detail": {"device_kind": device_kind, "chips": chips,
                           "clock_suspect": clock_suspect}}

    bar = 3863.2
    r = ratchet.bench_gate_record(spec, rec(4000.0, kind), bar)
    assert r["ok"] and "skipped" not in r
    r = ratchet.bench_gate_record(spec, rec(3000.0, kind), bar)
    assert not r["ok"]
    # above the bar but the clock is suspect: fail, never certify
    r = ratchet.bench_gate_record(spec, rec(6000.0, kind, clock_suspect=True),
                                  bar)
    assert not r["ok"] and "clock_suspect" in r["error"]
    # a different accelerator: the v5-lite bar is not comparable — pass-skip
    r = ratchet.bench_gate_record(spec, rec(100.0, "TPU v4"), bar)
    assert r["ok"] and "not comparable" in r["skipped"]
    # same kind but multi-chip: the 1-chip baseline's per-chip workload is
    # 256 imgs/chip; a sharded 32/chip run sits below the bar with no real
    # regression (bench_perchip32_r5.json: 3294.5) — pass-skip, never fail
    r = ratchet.bench_gate_record(spec, rec(3294.5, kind, chips=8), bar)
    assert r["ok"] and "not comparable" in r["skipped"]


def test_ratchet_resident_gate_decision():
    """The placement-equivalence gate rides the default config list.
    Bit-identity (equivalence_ok) binds on EVERY device — it is the
    hardware-independent contract that carries accuracy ratchets across
    placements; the timing claim binds only on CPU where the injected
    serialized-link delay is the calibrated proxy (elsewhere: pass-skip
    with the reason on record, the bench gate's device-kind convention)."""
    ratchet = _load("ratchet")
    assert "resident_ab" in ratchet.CONFIGS
    assert ratchet.CONFIGS["resident_ab"]["kind"] == "resident_ab"

    def art(device="cpu", host=300.0, dev=150.0, eq=True):
        return {
            "summary": {"host_ms_per_step": host, "device_ms_per_step": dev},
            "equivalence": {"equivalence_ok": eq, "steps_compared": 16},
            "device": device,
        }

    r = ratchet.resident_gate_record(art())
    assert r["ok"] and "skipped" not in r
    # broken bit-identity fails EVERYWHERE, even where timing pass-skips
    r = ratchet.resident_gate_record(art(device="TPU v4", eq=False))
    assert not r["ok"] and "differ" in r["error"]
    # an accelerator: equivalence enforced, CPU-calibrated timing skipped
    # (even a slower device arm does not fail there)
    r = ratchet.resident_gate_record(art(device="TPU v4", host=64.9, dev=65.2))
    assert r["ok"] and "calibrated" in r["skipped"]
    # on CPU the timing claim binds: the device arm must beat the host arm
    r = ratchet.resident_gate_record(art(host=150.0, dev=150.0))
    assert not r["ok"] and "not faster" in r["error"]


def test_ratchet_window_gate_decision():
    """The WINDOWED placement equivalence gate rides the default config
    list with the resident_ab conventions: bit-identity binds on EVERY
    device, the CPU-calibrated injected-delay timing claim pass-skips
    off-CPU with the reason on record."""
    ratchet = _load("ratchet")
    assert "window_ab" in ratchet.CONFIGS
    assert ratchet.CONFIGS["window_ab"]["kind"] == "window_ab"

    def art(device="cpu", host=250.0, win=100.0, eq=True):
        return {
            "summary": {"host_ms_per_step": host, "window_ms_per_step": win},
            "equivalence": {"equivalence_ok": eq, "steps_compared": 16},
            "window_batches": 4,
            "device": device,
        }

    r = ratchet.window_gate_record(art())
    assert r["ok"] and "skipped" not in r
    assert r["metric"] == "ratchet_window_ab_equivalence"
    # broken bit-identity fails EVERYWHERE, even where timing pass-skips
    r = ratchet.window_gate_record(art(device="TPU v4", eq=False))
    assert not r["ok"] and "differ" in r["error"]
    # an accelerator: equivalence enforced, CPU-calibrated timing skipped
    r = ratchet.window_gate_record(art(device="TPU v4", host=64.9, win=65.2))
    assert r["ok"] and "calibrated" in r["skipped"]
    # on CPU the timing claim binds: the window arm must beat the host arm
    r = ratchet.window_gate_record(art(host=100.0, win=100.0))
    assert not r["ok"] and "not faster" in r["error"]


def test_ratchet_retrieval_gate_decision():
    """The retrieval A/B gate rides the default list: brute bit-identity
    to the PR-17 oracle and the per-rung recall bar bind on EVERY device;
    the CPU-calibrated p50-speedup bar at the top rung pass-skips
    off-CPU with the reason on record."""
    ratchet = _load("ratchet")
    assert "retrieval_ab" in ratchet.CONFIGS
    assert ratchet.CONFIGS["retrieval_ab"]["kind"] == "retrieval_gate"

    def art(device="cpu", recall=(1.0, 0.97), speedup=6.0, ids=True,
            bits=True, checked=None, bar=0.95):
        rungs = [{"rows": rows, "recall_at_k": rc}
                 for rows, rc in zip((4096, 262144), recall)]
        return {
            "schema": "retrieval_ab/v1",
            "rungs": rungs,
            "oracle": {"ids_identical": ids, "scores_bit_identical": bits,
                       "rungs_checked": (
                           checked if checked is not None else [4096, 262144]
                       )},
            "summary": {"recall_bar": bar, "speedup_bar": 5.0,
                        "min_recall_at_k": min(recall),
                        "max_rung_rows": 262144,
                        "speedup_p50_max_rung": speedup},
            "device": device,
        }

    r = ratchet.retrieval_gate_record(art())
    assert r["ok"] and "skipped" not in r
    assert r["metric"] == "ratchet_retrieval_ab" and r["value"] == 6.0
    # the oracle bind is hardware-independent: broken bit-identity fails
    # even where the timing claim would pass-skip
    r = ratchet.retrieval_gate_record(art(device="TPU v4", bits=False))
    assert not r["ok"] and "bitwise" in r["error"]
    r = ratchet.retrieval_gate_record(art(ids=False))
    assert not r["ok"] and "ids diverge" in r["error"]
    # ...and so is the recall bar, naming the offending rung
    r = ratchet.retrieval_gate_record(art(device="TPU v4", recall=(1.0, 0.9)))
    assert not r["ok"] and "262144" in r["error"]
    # the oracle must have covered every rung in the artifact
    r = ratchet.retrieval_gate_record(art(checked=[4096]))
    assert not r["ok"] and "every rung" in r["error"]
    # off-CPU: the CPU-calibrated speedup claim pass-skips
    r = ratchet.retrieval_gate_record(art(device="TPU v4", speedup=1.0))
    assert r["ok"] and "calibrated" in r["skipped"]
    # on CPU the artifact's own speedup bar binds at the top rung
    r = ratchet.retrieval_gate_record(art(speedup=4.0))
    assert not r["ok"] and "5.0x bar" in r["error"]
    # degenerate artifacts never pass silently
    assert not ratchet.retrieval_gate_record({"schema": "nope"})["ok"]
    thin = art()
    thin["rungs"] = thin["rungs"][:1]
    assert "two corpus-size rungs" in ratchet.retrieval_gate_record(thin)["error"]
    bare = art(bar=None)
    bare["summary"]["recall_bar"] = None
    assert "no recall bar" in ratchet.retrieval_gate_record(bare)["error"]


# ------------------------------------------------------------------ hygiene


def test_no_binaries_or_pycache_tracked():
    """VERDICT #7: the compiled .so (and any __pycache__/.pyc) must never be
    committed — native/build.py compiles on demand."""
    import subprocess

    repo = os.path.abspath(os.path.join(os.path.dirname(__file__), ".."))
    if not os.path.isdir(os.path.join(repo, ".git")):
        pytest.skip("not a git checkout")
    try:
        tracked = subprocess.run(
            ["git", "ls-files"], cwd=repo, capture_output=True, text=True,
            timeout=60, check=True,
        ).stdout.splitlines()
    except (OSError, subprocess.SubprocessError):
        pytest.skip("git unavailable")
    offenders = [
        f for f in tracked
        if f.endswith((".so", ".pyc")) or "__pycache__" in f
    ]
    assert not offenders, offenders
    gitignore = open(os.path.join(repo, ".gitignore")).read()
    assert "*.so" in gitignore and "__pycache__/" in gitignore


# ------------------------------------------------------------ trace_report


def _span(name, track, ts, dur, **args):
    e = {"name": name, "track": track, "ph": "X", "ts": ts, "dur": dur}
    if args:
        e["args"] = args
    return e


def _instant(name, track, ts, **args):
    e = {"name": name, "track": track, "ph": "i", "ts": ts}
    if args:
        e["args"] = args
    return e


def _good_events():
    """A consistent synthetic run: wall 100s, phases partitioning part of
    it, the rest steady-state."""
    return [
        _instant("run_start", "events", 0.0),
        _span("epoch", "main:epoch", 0.0, 100.0, epoch=1),  # envelope
        _span("first_step", "main:compile", 1.0, 40.0),
        _span("epoch_gather", "main:data", 0.2, 0.5),
        _span("flush_boundary", "main:flush", 50.0, 2.0),
        _span("flush_boundary", "main:flush", 60.0, 2.0),
        _span("flush_boundary", "main:flush", 70.0, 2.0),
        _span("checkpoint_save", "main:checkpoint", 90.0, 5.0),
        _span("flush_job", "telemetry:flush", 50.5, 8.0),  # other thread
        _instant("run_end", "events", 100.0),
    ]


def test_trace_report_attribution_partitions_wall(tmp_path):
    tr = _load("trace_report")
    report = tr.build_report(_good_events())
    cons = report["consistency"]
    assert cons["wall_s"] == pytest.approx(100.0)
    # compile 40 + data 0.5 + flush 6 + checkpoint 5 = 51.5 attributed
    assert cons["attributed_s"] == pytest.approx(51.5)
    assert cons["steady_state_s"] == pytest.approx(48.5)
    assert cons["monotone_ok"] and cons["nonnegative_ok"] and cons["ok"]
    assert set(report["phases"]) == {"compile", "data", "flush", "checkpoint"}
    assert report["phases"]["flush"]["count"] == 3
    assert report["phases"]["flush"]["mean_ms"] == pytest.approx(2000.0)
    # shares + steady share sum to 1
    total = sum(p["share"] for p in report["phases"].values())
    assert total + report["steady_state"]["share"] == pytest.approx(1.0, abs=1e-3)
    # the epoch envelope and the telemetry-thread job are NOT attributed
    assert "epoch" not in report["phases"]
    # compile at 40% of wall stays under the 50% advisory bar
    assert not any(a["phase"] == "compile" for a in report["anomalies"])


def test_trace_report_flags_overlapping_spans():
    tr = _load("trace_report")
    events = _good_events() + [
        # overlaps the 50.0-52.0 flush boundary ON another main track:
        # main-thread phases may never overlap across tracks either
        _span("checkpoint_save", "main:checkpoint", 51.0, 3.0),
    ]
    report = tr.build_report(events)
    assert not report["consistency"]["monotone_ok"]
    assert not report["consistency"]["ok"]


def test_trace_report_anomaly_flags_and_event_findings():
    tr = _load("trace_report")
    events = [
        _span("first_step", "main:compile", 0.0, 80.0),  # 80% of wall
        _span("flush_boundary", "main:flush", 90.0, 1.0),
        _instant("stall_detected", "watchdog", 95.0, dump=1),
        _instant("nan_rollback", "main:guard", 96.0, epoch=3),
        _instant("end", "events", 100.0),
    ]
    report = tr.build_report(events)
    flags = {a["phase"]: a["flag"] for a in report["anomalies"]}
    assert "compile" in flags  # 80% > 50% advisory bar
    joined = " ".join(a["flag"] for a in report["anomalies"])
    assert "stall watchdog fired" in joined and "NaN rollback" in joined


def test_trace_report_empty_events_raise():
    tr = _load("trace_report")
    with pytest.raises(ValueError):
        tr.build_report([])


def test_trace_report_cli_writes_artifact(tmp_path):
    tr = _load("trace_report")
    events_path = tmp_path / "events.jsonl"
    with open(events_path, "w") as f:
        for e in _good_events():
            f.write(json.dumps(e) + "\n")
    out = tmp_path / "report.json"
    rc = tr.main(["--events", str(events_path), "--json", str(out)])
    assert rc == 0
    artifact = json.load(open(out))
    assert artifact["schema"] == "trace_report/v1"
    assert artifact["report"]["consistency"]["ok"]
    # the rendered table reached stdout is covered by rc; pin the artifact
    # keys the ratchet gate consumes
    assert {"phases", "steady_state", "anomalies", "consistency",
            "n_events"} <= set(artifact["report"])


def test_trace_report_gate_record():
    ratchet = _load("ratchet")
    tr = _load("trace_report")
    artifact = tr.build_output("x/events.jsonl", tr.build_report(_good_events()))
    r = ratchet.trace_report_gate_record(artifact)
    assert r["ok"] and r["metric"] == "ratchet_trace_report_attribution"
    assert r["wall_s"] == pytest.approx(100.0)
    # inconsistent attribution fails the gate
    bad = tr.build_output(
        "x", tr.build_report(_good_events() + [
            _span("checkpoint_save", "main:checkpoint", 51.0, 3.0),
        ]),
    )
    r = ratchet.trace_report_gate_record(bad)
    assert not r["ok"] and "inconsistent" in r["error"]
    # a run with no flush boundaries means the recorder was dead
    silent = tr.build_output("x", tr.build_report([
        _span("first_step", "main:compile", 0.0, 1.0),
        _instant("end", "events", 10.0),
    ]))
    r = ratchet.trace_report_gate_record(silent)
    assert not r["ok"] and "flush-boundary" in r["error"]


def _fleet_session(run_dir, suffix="", scale=1.02, offset=5.0, late=0.4,
                   n_boundaries=3):
    """Write one recorder SESSION as two virtual processes: p0 on the
    reference clock, p1 on a rate-drifted + offset clock, arriving
    ``late`` seconds after p0 at every collective (the straggler)."""
    p0, p1 = [], []
    anchor = 0

    def boundary(name, kind, T, step=None):
        nonlocal anchor
        anchor += 1
        a0, a1 = T - late - 0.05, T - 0.05
        args = {"step": step} if step is not None else {}
        p0.append(_span(name, "main:collective", a0, T - a0, **args))
        p1.append(_span(name, "main:collective", scale * a1 + offset,
                        scale * (T - a1), **args))
        p0.append(_instant("clock_anchor", "fleet", T,
                           kind=kind, anchor=anchor))
        p1.append(_instant("clock_anchor", "fleet", scale * T + offset,
                           kind=kind, anchor=anchor))

    boundary("placement_decision", "placement", 1.0)
    for k in range(n_boundaries):
        boundary("failure_code_allgather", "flush_boundary",
                 10.0 + 5.0 * k, step=2 * (k + 1))
    p0.append(_span("flush_boundary", "main:flush", 2.0, 0.5, step=0))
    p1.append(_span("flush_boundary", "main:flush", scale * 2.0 + offset,
                    scale * 0.5, step=0))
    names = {0: f"events{suffix}.jsonl", 1: f"events_p1{suffix}.jsonl"}
    for pidx, events in ((0, p0), (1, p1)):
        with open(os.path.join(run_dir, names[pidx]), "w") as f:
            for e in sorted(events, key=lambda e: e["ts"]):
                f.write(json.dumps(e) + "\n")


def test_trace_report_fleet_cli_merges_two_virtual_processes(tmp_path):
    """The tier-1 fleet smoke: a 2-virtual-process run dir (two per-process
    events files on deliberately offset clocks, across TWO sessions) goes
    through the real ``--fleet`` CLI — sessions discovered and merged,
    anchors aligned to sub-tolerance residual, the injected straggler
    named, one pid per process in the merged Chrome trace."""
    tr = _load("trace_report")
    run_dir = tmp_path / "run"
    run_dir.mkdir()
    _fleet_session(str(run_dir))
    _fleet_session(str(run_dir), suffix="_r2", offset=-3.0, late=0.2)
    # a torn tail on one file must not break the merge (SIGKILL session)
    with open(run_dir / "events_p1_r2.jsonl", "a") as f:
        f.write('{"half": ')
    out = tmp_path / "fleet.json"
    trace_out = tmp_path / "fleet_trace.json"
    rc = tr.main(["--fleet", str(run_dir), "--json", str(out),
                  "--trace", str(trace_out)])
    assert rc == 0
    artifact = json.load(open(out))
    assert artifact["schema"] == "fleet_report/v1" and artifact["ok"]
    assert sorted(artifact["sessions"]) == ["r1", "r2"]
    for label, rep in artifact["sessions"].items():
        cons = rep["consistency"]
        assert cons["ok"] and cons["n_processes"] == 2
        assert cons["max_residual_s"] <= tr.FLEET_RESIDUAL_TOL_S
        assert rep["straggler_ranking"][0]["process"] == 1
        assert all(r["straggler"] == 1 for r in rep["skew_table"])
        assert rep["files"] == {
            "0": "events.jsonl" if label == "r1" else "events_r2.jsonl",
            "1": "events_p1.jsonl" if label == "r1"
                 else "events_p1_r2.jsonl",
        }
    trace = json.load(open(trace_out))
    pids = {e["pid"] for e in trace["traceEvents"]}
    assert pids == {0, 1}


def test_trace_report_fleet_cli_fails_on_recordless_process(tmp_path):
    """Review fix, CLI level: a discovered per-process file with zero
    complete records (dead-before-first-line process) must fail the merge
    rather than shrink the session to one process and exit 0."""
    tr = _load("trace_report")
    run_dir = tmp_path / "run"
    run_dir.mkdir()
    _fleet_session(str(run_dir))
    (run_dir / "events_p1.jsonl").write_text('{"torn": ')  # nothing complete
    out = tmp_path / "fleet.json"
    rc = tr.main(["--fleet", str(run_dir), "--json", str(out)])
    assert rc == 1
    artifact = json.load(open(out))
    assert not artifact["ok"]
    rep = artifact["sessions"]["r1"]
    assert rep["consistency"]["n_processes"] == 2
    assert rep["processes"]["1"]["n_events"] == 0


def test_trace_report_flags_recorder_saturation():
    tr = _load("trace_report")
    events = _good_events() + [
        _instant("recorder_dropped", "events", 99.0, records=12),
    ]
    report = tr.build_report(events)
    joined = " ".join(a["flag"] for a in report["anomalies"])
    assert "ring saturated" in joined


def test_ratchet_fleet_and_ledger_in_default_gate_list():
    ratchet = _load("ratchet")
    assert ratchet.CONFIGS["fleet_report"]["kind"] == "fleet_report"
    assert ratchet.CONFIGS["perf_ledger"]["kind"] == "perf_ledger"
    # ...and the committed evidence artifacts they bind on exist and pass
    repo = os.path.dirname(SCRIPTS)
    with open(os.path.join(repo,
                           ratchet.CONFIGS["fleet_report"]["artifact"])) as f:
        fleet_artifact = json.load(f)
    assert ratchet.fleet_gate_record(fleet_artifact)["ok"]
    pl = _load("perf_ledger")
    records = pl.load_ledger(
        os.path.join(repo, ratchet.CONFIGS["perf_ledger"]["artifact"])
    )
    assert ratchet.ledger_gate_record(records)["ok"]


def test_no_stale_pycache_for_deleted_modules():
    """A __pycache__ .pyc whose source module no longer exists (e.g. the
    once-stray serve/__pycache__/registry.cpython-310.pyc) advertises a
    dead module name to grep/archaeology — untracked, so the git hygiene
    test above can't see it. Bytecode for LIVE modules is fine."""
    repo = os.path.abspath(os.path.join(os.path.dirname(__file__), ".."))
    pkg = os.path.join(repo, "simclr_pytorch_distributed_tpu")
    stale = []
    for dirpath, _, files in os.walk(pkg):
        if os.path.basename(dirpath) != "__pycache__":
            continue
        for f in files:
            if not f.endswith(".pyc"):
                continue
            module = f.split(".")[0] + ".py"
            if not os.path.exists(os.path.join(os.path.dirname(dirpath), module)):
                stale.append(os.path.relpath(os.path.join(dirpath, f), repo))
    assert not stale, f"stale bytecode for deleted modules: {stale}"
