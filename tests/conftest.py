"""Test configuration: force an 8-device virtual CPU mesh BEFORE jax initializes.

The reference has no tests at all (SURVEY.md §4); its distributed semantics were
only ever exercised on 2 real GPUs. The TPU-native answer is
``--xla_force_host_platform_device_count=8`` so every sharding/collective test
runs against a real 8-way mesh on CPU.
"""

import os

os.environ["JAX_PLATFORMS"] = "cpu"
xla_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in xla_flags:
    os.environ["XLA_FLAGS"] = (
        xla_flags + " --xla_force_host_platform_device_count=8"
    ).strip()
# Keep CPU matmuls deterministic-ish and fast on the single-core test host.
os.environ.setdefault("JAX_ENABLE_X64", "0")

# The image's sitecustomize (PYTHONPATH=/root/.axon_site) imports jax at
# interpreter startup with JAX_PLATFORMS=axon baked in, so the env var above is
# captured too late — override through the live config as well.
import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

# Persistent compilation cache: the heavyweight sharded-step compiles dominate
# suite runtime on the single-core test host; cache them across pytest runs.
_CACHE_DIR = os.path.join(os.path.dirname(__file__), "..", ".jax_cache")
jax.config.update("jax_compilation_cache_dir", os.path.abspath(_CACHE_DIR))
jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)

import numpy as np  # noqa: E402
import pytest  # noqa: E402


@pytest.fixture
def rng():
    return np.random.default_rng(0)
