"""ImageFolder-equivalent reader: class-per-subdir tree -> uint8 arrays."""

import numpy as np
import pytest
from PIL import Image

from simclr_pytorch_distributed_tpu.data.cifar import load_dataset
from simclr_pytorch_distributed_tpu.data.folder import (
    find_classes,
    load_image_folder,
)


@pytest.fixture
def image_tree(tmp_path):
    rng = np.random.default_rng(0)
    counts = {"cats": 3, "dogs": 2}
    for cls, n in counts.items():
        d = tmp_path / cls
        d.mkdir()
        for i in range(n):
            arr = rng.integers(0, 256, size=(48, 64, 3), dtype=np.uint8)
            Image.fromarray(arr).save(d / f"img_{i}.png")
    (tmp_path / "notes.txt").write_text("not an image")
    return tmp_path, counts


def test_classes_sorted_and_labeled(image_tree):
    root, counts = image_tree
    assert find_classes(str(root)) == ["cats", "dogs"]
    data, classes = load_image_folder(str(root), size=16)
    assert classes == ["cats", "dogs"]
    assert data["images"].shape == (5, 32, 32, 3)  # store_size = 2*size
    assert data["images"].dtype == np.uint8
    np.testing.assert_array_equal(np.bincount(data["labels"]), [3, 2])


def test_store_size_override(image_tree):
    root, _ = image_tree
    data, _ = load_image_folder(str(root), size=16, store_size=24)
    assert data["images"].shape[1:] == (24, 24, 3)


def test_load_dataset_path_mode(image_tree):
    root, _ = image_tree
    train, test, n_cls = load_dataset("path", str(root), size=16)
    assert n_cls == 2
    assert train["images"].shape[0] == 5
    assert test["images"].shape[0] == 0  # no val split in path mode


def test_empty_root_raises(tmp_path):
    with pytest.raises(FileNotFoundError):
        load_image_folder(str(tmp_path))
    (tmp_path / "cls_a").mkdir()
    with pytest.raises(FileNotFoundError):
        load_image_folder(str(tmp_path))
