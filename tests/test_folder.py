"""ImageFolder-equivalent reader: class-per-subdir tree -> uint8 arrays."""

import jax
import numpy as np
import pytest
from PIL import Image

from simclr_pytorch_distributed_tpu.data.cifar import load_dataset
from simclr_pytorch_distributed_tpu.data.folder import (
    find_classes,
    load_image_folder,
)


@pytest.fixture
def image_tree(tmp_path):
    rng = np.random.default_rng(0)
    counts = {"cats": 3, "dogs": 2}
    for cls, n in counts.items():
        d = tmp_path / cls
        d.mkdir()
        for i in range(n):
            arr = rng.integers(0, 256, size=(48, 64, 3), dtype=np.uint8)
            Image.fromarray(arr).save(d / f"img_{i}.png")
    (tmp_path / "notes.txt").write_text("not an image")
    return tmp_path, counts


def test_classes_sorted_and_labeled(image_tree):
    root, counts = image_tree
    assert find_classes(str(root)) == ["cats", "dogs"]
    data, classes = load_image_folder(str(root), size=16)
    assert classes == ["cats", "dogs"]
    assert data["images"].shape == (5, 32, 32, 3)  # store_size = 2*size
    assert data["images"].dtype == np.uint8
    np.testing.assert_array_equal(np.bincount(data["labels"]), [3, 2])


def test_store_size_override(image_tree):
    root, _ = image_tree
    data, _ = load_image_folder(str(root), size=16, store_size=24)
    assert data["images"].shape[1:] == (24, 24, 3)


def test_load_dataset_path_mode(image_tree):
    root, _ = image_tree
    train, test, n_cls = load_dataset("path", str(root), size=16)
    assert n_cls == 2
    assert train["images"].shape[0] == 5
    assert test["images"].shape[0] == 0  # no val split in path mode


def test_empty_root_raises(tmp_path):
    with pytest.raises(FileNotFoundError):
        load_image_folder(str(tmp_path))
    (tmp_path / "cls_a").mkdir()
    with pytest.raises(FileNotFoundError):
        load_image_folder(str(tmp_path))


def test_large_tree_uses_memmap_cache(image_tree, tmp_path):
    """Above the threshold the decode goes through an on-disk memmap (bounded
    host RSS: pages are file-backed and reclaimable, not anonymous memory),
    and a second load reuses the cache without re-decoding."""
    root, _ = image_tree
    cache = tmp_path / "cache"
    data, _ = load_image_folder(
        str(root), size=16, cache_dir=str(cache), mmap_threshold_bytes=1
    )
    assert isinstance(data["images"], np.memmap)
    assert data["images"].shape == (5, 32, 32, 3)
    # identical content to the in-RAM path
    ram, _ = load_image_folder(str(root), size=16)
    np.testing.assert_array_equal(np.asarray(data["images"]), ram["images"])

    # second load: cache hit (the .npy's mtime must not change)
    npys = list(cache.glob("*.npy"))
    assert len(npys) == 1
    mtime = npys[0].stat().st_mtime_ns
    data2, _ = load_image_folder(
        str(root), size=16, cache_dir=str(cache), mmap_threshold_bytes=1
    )
    assert npys[0].stat().st_mtime_ns == mtime
    np.testing.assert_array_equal(np.asarray(data2["images"]), ram["images"])

    # touching a source image invalidates the manifest key -> fresh cache entry
    some_img = next((root / "cats").glob("*.png"))
    arr = np.zeros((48, 64, 3), np.uint8)
    Image.fromarray(arr).save(some_img)
    import os as _os
    _os.utime(some_img, (0, 0))  # force a distinct mtime second
    data3, _ = load_image_folder(
        str(root), size=16, cache_dir=str(cache), mmap_threshold_bytes=1
    )
    assert len(list(cache.glob("*.npy"))) == 2
    assert np.asarray(data3["images"]).sum() != np.asarray(data2["images"]).sum()


@pytest.mark.window
def test_memmap_tree_streams_through_the_window_store(tmp_path):
    """The ISSUE-7 scenario end-to-end: a folder tree big enough to decode
    into the on-disk memmap cache is WINDOWABLE, not host-degraded — the
    ladder resolves 'auto' to the window store, every batch it serves is
    byte-identical to the host loader's, and the memmap is never silently
    paged whole into RAM: every upload the store performs is exactly one
    window's rows (counted mechanically via the injectable put hook)."""
    from simclr_pytorch_distributed_tpu.data import device_store
    from simclr_pytorch_distributed_tpu.data.device_store import WindowStore
    from simclr_pytorch_distributed_tpu.data.pipeline import EpochLoader
    from simclr_pytorch_distributed_tpu.parallel.mesh import create_mesh

    rng = np.random.default_rng(1)
    for cls in ("ants", "bees", "cats"):
        d = tmp_path / "tree" / cls
        d.mkdir(parents=True)
        for i in range(12):
            arr = rng.integers(0, 256, size=(40, 40, 3), dtype=np.uint8)
            Image.fromarray(arr).save(d / f"img_{i}.png")
    data, _ = load_image_folder(
        str(tmp_path / "tree"), size=16,
        cache_dir=str(tmp_path / "cache"), mmap_threshold_bytes=1,
    )
    assert isinstance(data["images"], np.memmap)  # the big-tree path

    batch, W = 8, 3
    loader = EpochLoader(data["images"], data["labels"], batch, base_seed=4)
    assert loader.steps_per_epoch == 4  # 36 rows, drop_last
    mesh = create_mesh()
    # the ladder's windowable verdict, from the loader's own (memmap-view)
    # arrays — residency would page the whole tree
    store = device_store.make_store(
        "auto", loader, mesh, budget_bytes=1 << 30, window_batches=W
    )
    assert isinstance(store, WindowStore)

    uploads = []

    def counting_put(w_imgs, w_labs):
        uploads.append(w_imgs.nbytes + w_labs.nbytes)
        return jax.device_put(w_imgs), jax.device_put(w_labs)

    store = WindowStore(loader, mesh, W, window_put=counting_put,
                        prefetch=False)
    row_bytes = data["images"][0].nbytes + 4  # uint8 row + int32 label
    for epoch in (1, 2):
        for s, (h_imgs, h_labs) in enumerate(loader.epoch(epoch)):
            b_imgs, b_labs = store.batch_buffers(epoch, s)
            off = s % W
            np.testing.assert_array_equal(np.asarray(b_imgs)[off], h_imgs)
            np.testing.assert_array_equal(np.asarray(b_labs)[off], h_labs)
    # one upload per window, never per step — and each upload is exactly
    # window-sized (W batches), never the dataset: the memmap streams
    # through the page cache window by window
    assert len(uploads) == 2 * store.n_windows
    assert all(u == W * batch * row_bytes for u in uploads)
    assert uploads[0] < data["images"].nbytes
