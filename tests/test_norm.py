"""CrossReplicaBatchNorm numerics vs torch BatchNorm2d, and sync semantics."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
import torch

from simclr_pytorch_distributed_tpu.models.norm import CrossReplicaBatchNorm


def torch_bn_reference(x_nhwc, n_steps=1):
    """Run torch BatchNorm2d over the same data, return (y, running_mean, running_var)."""
    bn = torch.nn.BatchNorm2d(x_nhwc.shape[-1])
    bn.train()
    xt = torch.from_numpy(np.transpose(x_nhwc, (0, 3, 1, 2)))
    for _ in range(n_steps):
        y = bn(xt)
    return (
        np.transpose(y.detach().numpy(), (0, 2, 3, 1)),
        bn.running_mean.numpy(),
        bn.running_var.numpy(),
    )


def test_train_mode_matches_torch(rng):
    x = rng.normal(loc=1.5, scale=2.0, size=(8, 4, 4, 16)).astype(np.float32)
    bn = CrossReplicaBatchNorm()
    variables = bn.init(jax.random.key(0), jnp.asarray(x))
    y, mutated = bn.apply(variables, jnp.asarray(x), mutable=["batch_stats"])
    y_t, rm_t, rv_t = torch_bn_reference(x)
    np.testing.assert_allclose(np.asarray(y), y_t, rtol=1e-4, atol=1e-5)
    np.testing.assert_allclose(np.asarray(mutated["batch_stats"]["mean"]), rm_t, rtol=1e-5, atol=1e-6)
    # unbiased running var is the torch semantic being checked here
    np.testing.assert_allclose(np.asarray(mutated["batch_stats"]["var"]), rv_t, rtol=1e-4, atol=1e-5)


def test_eval_mode_uses_running_stats(rng):
    x = rng.normal(size=(4, 2, 2, 8)).astype(np.float32)
    bn = CrossReplicaBatchNorm(use_running_average=True)
    variables = bn.init(jax.random.key(0), jnp.asarray(x))
    y = bn.apply(variables, jnp.asarray(x))
    # fresh running stats are mean 0 var 1 -> output ~ input (eps-scaled)
    np.testing.assert_allclose(np.asarray(y), x / np.sqrt(1 + 1e-5), rtol=1e-5, atol=1e-6)


def test_grouped_bn_matches_independent_per_shard_bn(rng):
    """GSPMD per-device mode (sync=False, local_groups=G) == G INDEPENDENT
    whole-batch BNs, one per data-parallel slice — the reference's default
    per-GPU BatchNorm2d, expressible without per-device programs."""
    g, v, per = 4, 2, 3  # groups x views x images-per-group-per-view
    x = rng.normal(size=(v * g * per, 4, 4, 8)).astype(np.float32)
    # make the groups statistically distinct
    xv = x.reshape(v, g, per, 4, 4, 8)
    xv += np.arange(g, dtype=np.float32)[None, :, None, None, None, None] * 5.0
    x = xv.reshape(x.shape)

    bn_grouped = CrossReplicaBatchNorm(sync=False, local_groups=g, group_views=v)
    variables = bn_grouped.init(jax.random.key(0), jnp.asarray(x))
    y, mut = bn_grouped.apply(variables, jnp.asarray(x), mutable=["batch_stats"])
    y = np.asarray(y).reshape(v, g, per, 4, 4, 8)

    bn_one = CrossReplicaBatchNorm()
    for gi in range(g):
        # group gi = both views of batch-slice gi, exactly the reference's
        # per-GPU batch composition
        xg = xv[:, gi].reshape(v * per, 4, 4, 8)
        y_ref, mut_ref = bn_one.apply(
            bn_one.init(jax.random.key(0), jnp.asarray(xg)),
            jnp.asarray(xg), mutable=["batch_stats"],
        )
        np.testing.assert_allclose(
            y[:, gi].reshape(v * per, 4, 4, 8), np.asarray(y_ref),
            rtol=1e-4, atol=1e-5,
        )
        if gi == 0:
            # running stats track group 0 (DDP broadcast_buffers semantics)
            np.testing.assert_allclose(
                np.asarray(mut["batch_stats"]["mean"]),
                np.asarray(mut_ref["batch_stats"]["mean"]), rtol=1e-5, atol=1e-6,
            )
            np.testing.assert_allclose(
                np.asarray(mut["batch_stats"]["var"]),
                np.asarray(mut_ref["batch_stats"]["var"]), rtol=1e-4, atol=1e-5,
            )

    # and it differs from global-batch BN (the groups were made distinct)
    y_global = np.asarray(bn_one.apply(variables, jnp.asarray(x), mutable=["batch_stats"])[0])
    assert np.abs(y_global - y.reshape(y_global.shape)).max() > 0.5

    # indivisible batch fails loudly instead of silently regrouping
    with pytest.raises(ValueError, match="views"):
        bn_grouped.apply(variables, jnp.asarray(x[:10]), mutable=["batch_stats"])


def test_grouped_bn_init_with_tiny_example_batch():
    """init() traces with a 2-row example batch that cannot divide into the
    groups — the grouped branch must be inert during initialization (the
    driver's create_train_state would otherwise crash every multi-device
    sync-off run at startup)."""
    bn = CrossReplicaBatchNorm(sync=False, local_groups=8, group_views=2)
    variables = bn.init(jax.random.key(0), jnp.zeros((2, 4, 4, 3)))
    assert variables["batch_stats"]["mean"].shape == (3,)


@pytest.mark.slow
def test_grouped_bn_identical_under_sharded_jit(rng):
    """The grouped math is layout-independent: jit over the 8-device mesh with
    the batch sharded on 'data' produces the same outputs and running stats."""
    from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

    g = 8
    x = rng.normal(size=(g * 2 * 2, 2, 2, 4)).astype(np.float32)
    bn = CrossReplicaBatchNorm(sync=False, local_groups=g, group_views=2)
    variables = bn.init(jax.random.key(0), jnp.asarray(x))

    y_host, mut_host = bn.apply(variables, jnp.asarray(x), mutable=["batch_stats"])

    mesh = Mesh(np.array(jax.devices()), ("data",))
    xs = jax.device_put(jnp.asarray(x), NamedSharding(mesh, P("data")))
    y_jit, mut_jit = jax.jit(
        lambda v, xx: bn.apply(v, xx, mutable=["batch_stats"])
    )(variables, xs)
    np.testing.assert_allclose(np.asarray(y_jit), np.asarray(y_host), rtol=1e-4, atol=1e-5)
    np.testing.assert_allclose(
        np.asarray(mut_jit["batch_stats"]["var"]),
        np.asarray(mut_host["batch_stats"]["var"]), rtol=1e-4, atol=1e-5,
    )


@pytest.mark.slow
def test_shard_map_sync_equals_full_batch(rng):
    """pmean-synced per-device BN == BN over the concatenated batch — the
    SyncBatchNorm semantic (reference main_supcon.py:223-224) mesh-natively."""
    from jax.sharding import Mesh, PartitionSpec as P
    from simclr_pytorch_distributed_tpu.compat import shard_map

    devices = jax.devices()
    assert len(devices) == 8, "conftest must fake 8 CPU devices"
    x = rng.normal(loc=0.5, size=(16, 4, 4, 8)).astype(np.float32)

    bn_sync = CrossReplicaBatchNorm(axis_name="data")
    bn_full = CrossReplicaBatchNorm()
    variables = bn_full.init(jax.random.key(0), jnp.asarray(x))

    mesh = Mesh(np.array(devices), ("data",))

    def per_device(xs):
        y, mut = bn_sync.apply(variables, xs, mutable=["batch_stats"])
        return y, mut["batch_stats"]["mean"], mut["batch_stats"]["var"]

    y_sharded, rm, rv = shard_map(
        per_device,
        mesh=mesh,
        in_specs=P("data"),
        out_specs=(P("data"), P(), P()),
    )(jnp.asarray(x))

    y_full, mut_full = bn_full.apply(variables, jnp.asarray(x), mutable=["batch_stats"])
    np.testing.assert_allclose(np.asarray(y_sharded), np.asarray(y_full), rtol=1e-4, atol=1e-5)
    np.testing.assert_allclose(np.asarray(rm), np.asarray(mut_full["batch_stats"]["mean"]), rtol=1e-5, atol=1e-6)
    np.testing.assert_allclose(np.asarray(rv), np.asarray(mut_full["batch_stats"]["var"]), rtol=1e-4, atol=1e-5)


@pytest.mark.slow
def test_unsynced_bn_uses_local_stats(rng):
    """sync=False reproduces the reference's non---syncBN per-device BN."""
    from jax.sharding import Mesh, PartitionSpec as P
    from simclr_pytorch_distributed_tpu.compat import shard_map

    x = rng.normal(loc=0.0, scale=1.0, size=(16, 2, 2, 4)).astype(np.float32)
    # make shards statistically distinct
    x[:8] += 10.0

    bn_local = CrossReplicaBatchNorm(axis_name="data", sync=False)
    variables = bn_local.init(jax.random.key(0), jnp.asarray(x))
    mesh = Mesh(np.array(jax.devices()[:2]), ("data",))

    y = shard_map(
        lambda xs: bn_local.apply(variables, xs, mutable=["batch_stats"])[0],
        mesh=mesh,
        in_specs=P("data"),
        out_specs=P("data"),
    )(jnp.asarray(x))

    # local normalization: each half is zero-mean on its own
    y = np.asarray(y)
    assert abs(y[:8].mean()) < 1e-4 and abs(y[8:].mean()) < 1e-4

    # whereas synced normalization would leave the halves offset
    bn_sync = CrossReplicaBatchNorm(axis_name="data")
    y_s = shard_map(
        lambda xs: bn_sync.apply(variables, xs, mutable=["batch_stats"])[0],
        mesh=mesh,
        in_specs=P("data"),
        out_specs=P("data"),
    )(jnp.asarray(x))
    y_s = np.asarray(y_s)
    assert y_s[:8].mean() > 0.5 and y_s[8:].mean() < -0.5
