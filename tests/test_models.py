"""Model shape / parameter-count / init tests.

Golden parameter counts were computed once from the reference architecture
definition (networks/resnet_big.py) with torch and hardcoded here, so any
architectural drift (widths, strides, shortcut placement, head sizes) fails.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from simclr_pytorch_distributed_tpu.models import (
    MODEL_DICT,
    LinearClassifier,
    SupCEResNet,
    SupConResNet,
)

# (encoder params, SupConResNet total params) from the reference model defs.
GOLDEN_COUNTS = {
    "resnet18": (11_168_832, 11_497_152),
    "resnet34": (21_276_992, 21_605_312),
    "resnet50": (23_500_352, 27_958_976),
    "resnet101": (42_492_480, 46_951_104),
}


def n_params(tree):
    return sum(int(np.prod(x.shape)) for x in jax.tree.leaves(tree))


@pytest.mark.parametrize("name", ["resnet18", "resnet50"])
def test_encoder_shape_and_params(name):
    model_fn, feat_dim = MODEL_DICT[name]
    model = model_fn()
    x = jnp.zeros((2, 32, 32, 3))
    variables = model.init(jax.random.key(0), x, train=False)
    out = model.apply(variables, x, train=False)
    assert out.shape == (2, feat_dim)
    assert n_params(variables["params"]) == GOLDEN_COUNTS[name][0]


@pytest.mark.parametrize("name", ["resnet34", "resnet101"])
def test_encoder_params_slow(name):
    model_fn, _ = MODEL_DICT[name]
    variables = jax.eval_shape(
        lambda: model_fn().init(jax.random.key(0), jnp.zeros((1, 32, 32, 3)), train=False)
    )
    assert n_params(variables["params"]) == GOLDEN_COUNTS[name][0]


def test_supcon_model_shape_and_params():
    model = SupConResNet(model_name="resnet50")
    x = jnp.zeros((2, 32, 32, 3))
    variables = model.init(jax.random.key(0), x, train=False)
    out = model.apply(variables, x, train=False)
    assert out.shape == (2, 128)
    assert n_params(variables["params"]) == GOLDEN_COUNTS["resnet50"][1]
    # unnormalized output: norms should not all be ~1
    assert not np.allclose(np.linalg.norm(np.asarray(out), axis=1), 1.0, atol=1e-3)


def test_supcon_linear_head():
    model = SupConResNet(model_name="resnet18", head="linear")
    variables = jax.eval_shape(
        lambda: model.init(jax.random.key(0), jnp.zeros((1, 32, 32, 3)), train=False)
    )
    # encoder + single 512->128 linear
    assert n_params(variables["params"]) == GOLDEN_COUNTS["resnet18"][0] + 512 * 128 + 128


def test_linear_classifier_params():
    cls = LinearClassifier(model_name="resnet50", num_classes=10)
    variables = cls.init(jax.random.key(0), jnp.zeros((2, 2048)))
    assert n_params(variables["params"]) == 20_490
    assert cls.apply(variables, jnp.zeros((2, 2048))).shape == (2, 10)


def test_supce_params():
    model = SupCEResNet(model_name="resnet50", num_classes=10)
    variables = jax.eval_shape(
        lambda: model.init(jax.random.key(0), jnp.zeros((1, 32, 32, 3)), train=True)
    )
    assert n_params(variables["params"]) == 23_520_842


def test_encode_matches_encoder_output():
    model = SupConResNet(model_name="resnet18")
    x = jax.random.normal(jax.random.key(1), (2, 32, 32, 3))
    variables = model.init(jax.random.key(0), x, train=False)
    feats = model.apply(variables, x, train=False, method=SupConResNet.encode)
    assert feats.shape == (2, 512)


def test_batch_stats_update_in_train_mode():
    model = SupConResNet(model_name="resnet18")
    x = jax.random.normal(jax.random.key(1), (4, 32, 32, 3)) + 3.0
    variables = model.init(jax.random.key(0), x, train=True)
    _, mutated = model.apply(variables, x, train=True, mutable=["batch_stats"])
    old = jax.tree.leaves(variables["batch_stats"])
    new = jax.tree.leaves(mutated["batch_stats"])
    assert any(not np.allclose(a, b) for a, b in zip(old, new))


def test_conv_init_statistics():
    """Kaiming fan-out: stem conv std ~ sqrt(2 / (3*3*64))."""
    model_fn, _ = MODEL_DICT["resnet18"]
    variables = model_fn().init(
        jax.random.key(0), jnp.zeros((1, 32, 32, 3)), train=False
    )
    k = np.asarray(variables["params"]["conv1"]["kernel"])  # (3,3,3,64)
    expected_std = np.sqrt(2.0 / (3 * 3 * 64))
    assert abs(k.std() - expected_std) / expected_std < 0.15


def test_linear_init_statistics():
    """torch Linear init: U(±1/sqrt(fan_in)) for kernel and bias."""
    cls = LinearClassifier(model_name="resnet50", num_classes=100)
    variables = cls.init(jax.random.key(0), jnp.zeros((2, 2048)))
    k = np.asarray(variables["params"]["fc"]["kernel"])
    bound = 1.0 / np.sqrt(2048)
    assert k.min() >= -bound and k.max() <= bound
    assert k.std() > bound / 3  # uniform, not degenerate


def test_remat_identical_numerics():
    """remat=True recomputes activations in backward but must not change the
    forward output or the gradients."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    from simclr_pytorch_distributed_tpu.models import SupConResNet

    x = jnp.asarray(
        np.random.default_rng(0).standard_normal((4, 16, 16, 3)), jnp.float32
    )

    outs = {}
    for remat in (False, True):
        model = SupConResNet(model_name="resnet10", remat=remat)
        v = model.init(jax.random.key(0), jnp.zeros((2, 16, 16, 3)), train=False)

        def loss(params):
            feats, _ = model.apply(
                {"params": params, "batch_stats": v["batch_stats"]},
                x, train=True, mutable=["batch_stats"],
            )
            return jnp.sum(jnp.square(feats))

        val, grads = jax.value_and_grad(loss)(v["params"])
        outs[remat] = (float(val), grads)

    np.testing.assert_allclose(outs[True][0], outs[False][0], rtol=1e-6)
    for a, b in zip(jax.tree.leaves(outs[False][1]), jax.tree.leaves(outs[True][1])):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), rtol=1e-5, atol=1e-6)


def test_s2d_stem_variant_shapes():
    """The space-to-depth stem experiment (models/resnet.py) preserves every
    downstream shape: same feature dim, same head output."""
    import jax
    import jax.numpy as jnp

    from simclr_pytorch_distributed_tpu.models import SupConResNet

    m = SupConResNet(model_name="resnet10", stem="s2d")
    v = m.init(jax.random.key(0), jnp.zeros((2, 32, 32, 3)))
    out, _ = m.apply(v, jnp.ones((2, 32, 32, 3)), mutable=["batch_stats"])
    assert out.shape == (2, 128)
    feats = m.apply(v, jnp.ones((2, 32, 32, 3)), train=False,
                    method=SupConResNet.encode)
    assert feats.shape == (2, 512)
    assert "conv1_s2d" in v["params"]["encoder"]
