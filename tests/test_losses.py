"""Golden-value tests for supcon_loss against an independent numpy oracle.

The oracle below is written straight from the math (per-anchor mean log-likelihood
of positives under a temperature softmax over non-self pairs, scaled by
-tau/tau_base), NOT from the reference's tensor program, so agreement is evidence
of semantic parity rather than shared bugs.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from simclr_pytorch_distributed_tpu.ops.losses import cross_entropy_loss, supcon_loss


def oracle_supcon(features, labels=None, mask=None, temperature=0.07,
                  base_temperature=0.07, contrast_mode="all"):
    """Direct per-anchor computation of the SupCon/SimCLR loss."""
    B, V, D = features.shape
    # All views, view-major rows.
    rows = np.concatenate([features[:, v, :] for v in range(V)], axis=0)  # [V*B, D]

    def positives_of(i_sample):
        if mask is not None:
            return [j for j in range(B) if mask[i_sample, j]]
        if labels is not None:
            return [j for j in range(B) if labels[j] == labels[i_sample]]
        return [i_sample]

    anchors = range(V * B) if contrast_mode == "all" else range(B)
    losses = []
    for a in anchors:
        a_sample = a % B
        a_vec = rows[a] if contrast_mode == "all" else features[a, 0]
        sims = rows @ a_vec / temperature
        # softmax denominator over every non-self contrast row
        others = [j for j in range(V * B) if j != a]
        denom = np.log(np.sum(np.exp(sims[others] - sims[others].max()))) + sims[others].max()
        pos_samples = positives_of(a_sample)
        # positive rows: every view of each positive sample, excluding self row
        pos_rows = [v * B + j for v in range(V) for j in pos_samples if v * B + j != a]
        mean_logprob = np.mean([sims[p] - denom for p in pos_rows])
        losses.append(-(temperature / base_temperature) * mean_logprob)
    return float(np.mean(losses))


def normed(rng, B, V, D):
    x = rng.normal(size=(B, V, D)).astype(np.float32)
    return x / np.linalg.norm(x, axis=-1, keepdims=True)


@pytest.mark.parametrize("temperature", [0.07, 0.5])
@pytest.mark.parametrize("mode", ["all", "one"])
def test_simclr_matches_oracle(rng, temperature, mode):
    f = normed(rng, B=8, V=2, D=16)
    got = supcon_loss(jnp.asarray(f), temperature=temperature, contrast_mode=mode)
    want = oracle_supcon(f, temperature=temperature, contrast_mode=mode)
    np.testing.assert_allclose(np.asarray(got), want, rtol=1e-5)


def test_supcon_labels_matches_oracle(rng):
    f = normed(rng, B=10, V=2, D=8)
    labels = rng.integers(0, 3, size=10)
    got = supcon_loss(jnp.asarray(f), labels=jnp.asarray(labels), temperature=0.1)
    want = oracle_supcon(f, labels=labels, temperature=0.1)
    np.testing.assert_allclose(np.asarray(got), want, rtol=1e-5)


def test_explicit_mask_matches_oracle(rng):
    f = normed(rng, B=6, V=2, D=8)
    labels = rng.integers(0, 2, size=6)
    mask = (labels[:, None] == labels[None, :]).astype(np.float32)
    got = supcon_loss(jnp.asarray(f), mask=jnp.asarray(mask))
    want = oracle_supcon(f, mask=mask)
    np.testing.assert_allclose(np.asarray(got), want, rtol=1e-5)


def test_base_temperature_scale(rng):
    """tau/tau_base multiplier: at tau=0.5, tau_base=0.07 the loss is ~7.14x the
    tau_base=0.5 value (reference losses.py:90 quirk, part of the recipe)."""
    f = normed(rng, B=8, V=2, D=16)
    ratio = supcon_loss(jnp.asarray(f), temperature=0.5) / supcon_loss(
        jnp.asarray(f), temperature=0.5, base_temperature=0.5
    )
    np.testing.assert_allclose(float(ratio), 0.5 / 0.07, rtol=1e-5)


def test_more_views(rng):
    f = normed(rng, B=4, V=3, D=8)
    got = supcon_loss(jnp.asarray(f))
    want = oracle_supcon(f)
    np.testing.assert_allclose(np.asarray(got), want, rtol=1e-5)


def test_labels_and_mask_mutually_exclusive(rng):
    f = jnp.asarray(normed(rng, 4, 2, 8))
    with pytest.raises(ValueError):
        supcon_loss(f, labels=jnp.zeros(4, jnp.int32), mask=jnp.eye(4))


def test_rank2_features_rejected():
    with pytest.raises(ValueError):
        supcon_loss(jnp.ones((4, 8)))


def test_extra_dims_flattened(rng):
    f = normed(rng, 4, 2, 16)
    got4d = supcon_loss(jnp.asarray(f.reshape(4, 2, 4, 4)))
    got3d = supcon_loss(jnp.asarray(f))
    np.testing.assert_allclose(np.asarray(got4d), np.asarray(got3d), rtol=1e-6)


def test_jit_and_grad(rng):
    f = jnp.asarray(normed(rng, 8, 2, 16))
    loss_fn = jax.jit(lambda x: supcon_loss(x, temperature=0.5))
    g = jax.grad(lambda x: supcon_loss(x, temperature=0.5))(f)
    assert jnp.isfinite(loss_fn(f))
    assert jnp.all(jnp.isfinite(g))
    # detached row-max: grads must not flow through the max subtraction; an easy
    # necessary condition is that loss is invariant to it numerically
    assert g.shape == f.shape


def test_cross_entropy_against_numpy(rng):
    logits = rng.normal(size=(16, 10)).astype(np.float32)
    labels = rng.integers(0, 10, size=16)
    got = cross_entropy_loss(jnp.asarray(logits), jnp.asarray(labels))
    z = logits - logits.max(axis=1, keepdims=True)
    logp = z - np.log(np.exp(z).sum(axis=1, keepdims=True))
    want = -np.mean(logp[np.arange(16), labels])
    np.testing.assert_allclose(np.asarray(got), want, rtol=1e-5)


def test_resolve_loss_impl_heuristic(monkeypatch):
    """The 'auto' resolution table (train/supcon.py): fused on TPU wherever
    the kernels can tile (single chip AND sharded meshes — the v5e-8 target
    path, round-4 verdict weak #1/#2), dense on CPU and on untileable shapes.
    Explicit impls pass through untouched."""
    from simclr_pytorch_distributed_tpu.train.supcon import resolve_loss_impl

    for explicit in ("dense", "fused", "ring"):
        assert resolve_loss_impl(explicit, 256, 8) == explicit

    monkeypatch.setattr(jax, "default_backend", lambda: "cpu")
    assert resolve_loss_impl("auto", 256, 1) == "dense"
    assert resolve_loss_impl("auto", 256, 8) == "dense"

    monkeypatch.setattr(jax, "default_backend", lambda: "tpu")
    # single chip -> plain fused kernel
    assert resolve_loss_impl("auto", 256, 1) == "fused"
    # multi-device data-parallel mesh -> sharded fused kernel (m=64 rows/dev
    # at the v5e-8 recipe geometry; measured parity-or-better vs dense,
    # docs/PERF.md "Per-device kernel time")
    assert resolve_loss_impl("auto", 256, 8) == "fused"
    # full model-parallel: data axis is 1 -> single-device kernel rules
    assert resolve_loss_impl("auto", 256, 8, model_parallel=8) == "fused"
    # V*B not divisible by 8: kernels cannot tile -> dense fallback
    assert resolve_loss_impl("auto", 3, 1) == "dense"
    # local rows not divisible: 2*36/8 = 9 rows/device -> dense fallback
    assert resolve_loss_impl("auto", 36, 8) == "dense"
