"""Schedule parity tests: closed forms re-derived independently from util.py:54-76
and main_supcon.py:120-131 semantics."""

import math

import numpy as np

from simclr_pytorch_distributed_tpu.ops.schedules import (
    cosine_lr,
    make_lr_schedule,
    step_lr,
    warmup_to_value,
)


def test_cosine_endpoints():
    lr, rate, total = 0.5, 0.1, 200
    eta_min = lr * rate**3
    # epoch=0 would give lr exactly; epoch starts at 1 in the reference loop
    np.testing.assert_allclose(
        float(cosine_lr(lr, rate, 1, total)),
        eta_min + (lr - eta_min) * (1 + math.cos(math.pi / total)) / 2,
        rtol=1e-6,
    )
    np.testing.assert_allclose(float(cosine_lr(lr, rate, total, total)), eta_min, rtol=1e-6)


def test_step_decay_boundaries():
    lr, rate = 0.1, 0.2
    bounds = (60, 75, 90)
    np.testing.assert_allclose(float(step_lr(lr, rate, bounds, 60)), lr, rtol=1e-6)  # epoch > bound strictly
    np.testing.assert_allclose(float(step_lr(lr, rate, bounds, 61)), lr * rate, rtol=1e-6)
    np.testing.assert_allclose(float(step_lr(lr, rate, bounds, 100)), lr * rate**3, rtol=1e-6)


def test_warmup_to_closed_form():
    lr, rate, warm_epochs, epochs = 0.5, 0.1, 10, 200
    eta_min = lr * rate**3
    want = eta_min + (lr - eta_min) * (1 + math.cos(math.pi * warm_epochs / epochs)) / 2
    np.testing.assert_allclose(warmup_to_value(lr, rate, warm_epochs, epochs, True), want)
    assert warmup_to_value(lr, rate, warm_epochs, epochs, False) == lr


def test_schedule_composition():
    spe = 50  # steps per epoch
    sched = make_lr_schedule(
        learning_rate=0.5, epochs=100, steps_per_epoch=spe, cosine=True,
        lr_decay_rate=0.1, warm=True, warm_epochs=10, warmup_from=0.01,
    )
    warmup_to = warmup_to_value(0.5, 0.1, 10, 100, True)
    # step 0 == epoch 1 batch 0: p=0 -> warmup_from
    np.testing.assert_allclose(float(sched(0)), 0.01, rtol=1e-6)
    # middle of warmup
    step = 5 * spe  # epoch 6 batch 0 -> p = 0.5
    np.testing.assert_allclose(
        float(sched(step)), 0.01 + 0.5 * (warmup_to - 0.01), rtol=1e-6
    )
    # first step after warmup -> cosine at epoch 11
    step = 10 * spe
    np.testing.assert_allclose(
        float(sched(step)), float(cosine_lr(0.5, 0.1, 11, 100)), rtol=1e-6
    )


def test_schedule_no_warm_uses_base_everywhere():
    sched = make_lr_schedule(
        learning_rate=0.1, epochs=100, steps_per_epoch=10, cosine=False,
        lr_decay_rate=0.2, lr_decay_epochs=(60, 75, 90), warm=False,
    )
    np.testing.assert_allclose(float(sched(0)), 0.1, rtol=1e-6)
    np.testing.assert_allclose(float(sched(70 * 10)), 0.1 * 0.2, rtol=1e-6)


def test_lars_optimizer_wiring():
    """--optimizer lars: trust-ratio-scaled updates, wired through the config.

    Property check (not golden): for a single param tensor, the LARS update
    norm is lr * ||p|| / ||g + wd*p|| * ||g + wd*p|| ... i.e. the update
    magnitude is proportional to the PARAM norm, not the gradient norm —
    doubling the gradient must leave the first-step update norm unchanged
    (unlike SGD, where it doubles)."""
    import jax.numpy as jnp

    from simclr_pytorch_distributed_tpu.train.state import make_optimizer

    p = {"w": jnp.asarray(np.random.default_rng(0).normal(size=(16, 16)), jnp.float32)}
    g1 = {"w": jnp.asarray(np.random.default_rng(1).normal(size=(16, 16)), jnp.float32)}
    g2 = {"w": 2.0 * g1["w"]}

    lars = make_optimizer(0.1, momentum=0.9, weight_decay=0.0, optimizer="lars")
    u1, _ = lars.update(g1, lars.init(p), p)
    u2, _ = lars.update(g2, lars.init(p), p)
    n1 = float(jnp.linalg.norm(u1["w"]))
    n2 = float(jnp.linalg.norm(u2["w"]))
    np.testing.assert_allclose(n1, n2, rtol=1e-5)  # scale-invariant

    # 1-D params (biases / BN scale-bias) are EXCLUDED from trust-ratio
    # adaptation: their update stays gradient-proportional like plain SGD
    pb = {"b": jnp.ones((16,))}
    gb1 = {"b": jnp.full((16,), 0.5)}
    gb2 = {"b": jnp.full((16,), 1.0)}
    ub1, _ = lars.update(gb1, lars.init(pb), pb)
    ub2, _ = lars.update(gb2, lars.init(pb), pb)
    np.testing.assert_allclose(
        2 * float(jnp.linalg.norm(ub1["b"])), float(jnp.linalg.norm(ub2["b"])),
        rtol=1e-5,
    )

    sgd = make_optimizer(0.1, momentum=0.9, weight_decay=0.0, optimizer="sgd")
    s1, _ = sgd.update(g1, sgd.init(p), p)
    s2, _ = sgd.update(g2, sgd.init(p), p)
    np.testing.assert_allclose(
        float(jnp.linalg.norm(s2["w"])), 2 * float(jnp.linalg.norm(s1["w"])),
        rtol=1e-5,
    )

    import pytest

    with pytest.raises(ValueError, match="optimizer"):
        make_optimizer(0.1, optimizer="adamw")


def test_lars_config_flag():
    from simclr_pytorch_distributed_tpu import config as config_lib

    cfg = config_lib.parse_supcon(
        ["--dataset", "synthetic", "--optimizer", "lars", "--workdir", "/tmp/x"]
    )
    assert cfg.optimizer == "lars"
