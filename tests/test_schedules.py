"""Schedule parity tests: closed forms re-derived independently from util.py:54-76
and main_supcon.py:120-131 semantics."""

import math

import numpy as np

from simclr_pytorch_distributed_tpu.ops.schedules import (
    cosine_lr,
    make_lr_schedule,
    step_lr,
    warmup_to_value,
)


def test_cosine_endpoints():
    lr, rate, total = 0.5, 0.1, 200
    eta_min = lr * rate**3
    # epoch=0 would give lr exactly; epoch starts at 1 in the reference loop
    np.testing.assert_allclose(
        float(cosine_lr(lr, rate, 1, total)),
        eta_min + (lr - eta_min) * (1 + math.cos(math.pi / total)) / 2,
        rtol=1e-6,
    )
    np.testing.assert_allclose(float(cosine_lr(lr, rate, total, total)), eta_min, rtol=1e-6)


def test_step_decay_boundaries():
    lr, rate = 0.1, 0.2
    bounds = (60, 75, 90)
    np.testing.assert_allclose(float(step_lr(lr, rate, bounds, 60)), lr, rtol=1e-6)  # epoch > bound strictly
    np.testing.assert_allclose(float(step_lr(lr, rate, bounds, 61)), lr * rate, rtol=1e-6)
    np.testing.assert_allclose(float(step_lr(lr, rate, bounds, 100)), lr * rate**3, rtol=1e-6)


def test_warmup_to_closed_form():
    lr, rate, warm_epochs, epochs = 0.5, 0.1, 10, 200
    eta_min = lr * rate**3
    want = eta_min + (lr - eta_min) * (1 + math.cos(math.pi * warm_epochs / epochs)) / 2
    np.testing.assert_allclose(warmup_to_value(lr, rate, warm_epochs, epochs, True), want)
    assert warmup_to_value(lr, rate, warm_epochs, epochs, False) == lr


def test_schedule_composition():
    spe = 50  # steps per epoch
    sched = make_lr_schedule(
        learning_rate=0.5, epochs=100, steps_per_epoch=spe, cosine=True,
        lr_decay_rate=0.1, warm=True, warm_epochs=10, warmup_from=0.01,
    )
    warmup_to = warmup_to_value(0.5, 0.1, 10, 100, True)
    # step 0 == epoch 1 batch 0: p=0 -> warmup_from
    np.testing.assert_allclose(float(sched(0)), 0.01, rtol=1e-6)
    # middle of warmup
    step = 5 * spe  # epoch 6 batch 0 -> p = 0.5
    np.testing.assert_allclose(
        float(sched(step)), 0.01 + 0.5 * (warmup_to - 0.01), rtol=1e-6
    )
    # first step after warmup -> cosine at epoch 11
    step = 10 * spe
    np.testing.assert_allclose(
        float(sched(step)), float(cosine_lr(0.5, 0.1, 11, 100)), rtol=1e-6
    )


def test_schedule_no_warm_uses_base_everywhere():
    sched = make_lr_schedule(
        learning_rate=0.1, epochs=100, steps_per_epoch=10, cosine=False,
        lr_decay_rate=0.2, lr_decay_epochs=(60, 75, 90), warm=False,
    )
    np.testing.assert_allclose(float(sched(0)), 0.1, rtol=1e-6)
    np.testing.assert_allclose(float(sched(70 * 10)), 0.1 * 0.2, rtol=1e-6)
