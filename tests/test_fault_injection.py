"""Fault-injection harness: REAL signals against the REAL driver.

Runs the supcon driver in a subprocess on the synthetic dataset and delivers
actual SIGTERM / SIGKILL at randomized mid-epoch steps, then resumes and
asserts exact state continuity — turning the preemption layer
(utils/preempt.py + step-granular checkpoint/resume) from dead code into
tested behavior:

- SIGTERM mid-epoch -> emergency checkpoint written with ``step_in_epoch`` in
  its meta -> clean distinct exit code -> ``--resume`` produces params
  bit-identical (allclose at fp32) to an uninterrupted run of the same seed;
- kill -9 (no grace, nothing saved, torn async writes possible) -> resume
  picks the newest COMPLETE scheduled save; a truncated/corrupt meta.json
  planted in the run dir never wins;
- ``--nan_policy rollback`` -> a poisoned epoch is rolled back from its
  boundary backup and the run completes instead of dying.

Markers: the whole module is ``fault``; the kill -9 and in-process-driver
variants are additionally ``slow`` so tier-1 (``-m 'not slow'``) keeps only
the SIGTERM + resume-continuity proof.
"""

import os
import signal
import subprocess
import sys
import threading
import time

import numpy as np
import pytest

from simclr_pytorch_distributed_tpu.utils import preempt

pytestmark = pytest.mark.fault

CHILD = os.path.join(os.path.dirname(__file__), "fault_injection_child.py")
CACHE = os.path.join(os.path.dirname(__file__), "..", ".jax_cache")
STEPS_PER_EPOCH = 7  # the child's synthetic config: 224 train / batch 32


class Child:
    """A driver subprocess whose stdout is streamed line-by-line so the test
    can react (send a signal) at a chosen training step.

    ``ndev`` pins the child's VIRTUAL mesh shape (the XLA host-platform
    device count — rewritten through supervise.launch.topology_env, the
    same env hook the supervisor's restart-resized relaunch uses), so the
    kill-on-N / resume-on-M matrix runs each leg on a different topology.
    ``ngpu``/``syncbn`` ride through to the child config (see its
    docstring for why the matrix pins them)."""

    def __init__(self, workdir, epochs, resume="", trial="f", save_freq=100,
                 data_placement="auto", ndev=None, ngpu="2", syncbn=False):
        from simclr_pytorch_distributed_tpu.supervise.launch import (
            topology_env,
        )

        env = topology_env(ndev, os.environ.copy())
        env["JAX_PLATFORMS"] = "cpu"
        env["JAX_COMPILATION_CACHE_DIR"] = os.path.abspath(CACHE)
        self.proc = subprocess.Popen(
            [sys.executable, CHILD, str(workdir), str(epochs), resume,
             trial, str(save_freq), data_placement, str(ngpu),
             "1" if syncbn else "0"],
            stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True,
            env=env, cwd=os.path.dirname(os.path.dirname(CHILD)) or ".",
        )
        self.lines = []
        self._reader = threading.Thread(target=self._read, daemon=True)
        self._reader.start()

    def _read(self):
        for line in self.proc.stdout:
            self.lines.append(line.rstrip("\n"))

    def wait_for_line(self, needle, timeout=420.0):
        """Block until a line containing ``needle`` appears; returns it."""
        deadline = time.time() + timeout
        seen = 0
        while time.time() < deadline:
            while seen < len(self.lines):
                if needle in self.lines[seen]:
                    return self.lines[seen]
                seen += 1
            if self.proc.poll() is not None and seen >= len(self.lines):
                raise AssertionError(
                    f"child exited rc={self.proc.returncode} before "
                    f"{needle!r}:\n" + "\n".join(self.lines[-30:])
                )
            time.sleep(0.02)
        raise AssertionError(
            f"timeout waiting for {needle!r}:\n" + "\n".join(self.lines[-30:])
        )

    def wait(self, timeout=420.0):
        rc = self.proc.wait(timeout=timeout)
        self._reader.join(timeout=10)
        return rc

    def grep(self, needle):
        return [ln for ln in self.lines if needle in ln]

    def save_folder(self):
        return self.wait_for_line("SAVE_FOLDER ").split("SAVE_FOLDER ", 1)[1]


def _load_params(ckpt_dir):
    """The saved model params as a flat {path: np.ndarray} dict (no abstract
    tree needed — the parent only compares values)."""
    import jax
    import orbax.checkpoint as ocp

    ckptr = ocp.StandardCheckpointer()
    try:
        tree = ckptr.restore(os.path.join(ckpt_dir, "model"))
    finally:
        ckptr.close()
    flat = jax.tree_util.tree_flatten_with_path(tree["params"])[0]
    return {jax.tree_util.keystr(k): np.asarray(v) for k, v in flat}


def _find_preempt_save(run_dir):
    names = [n for n in os.listdir(run_dir) if n.startswith("preempt_")]
    assert names, f"no preempt_* save in {run_dir}: {os.listdir(run_dir)}"
    assert len(names) == 1, names
    return os.path.join(run_dir, names[0])


@pytest.mark.parametrize("placement", ["host", "auto"])
def test_sigterm_mid_epoch_emergency_save_and_bit_identical_resume(
    tmp_path, placement
):
    """The tentpole proof. SIGTERM lands mid-epoch at a step chosen by run
    timing (randomized across runs by construction); the child must write an
    emergency checkpoint recording its intra-epoch position, exit with the
    distinct preemption code, and the resumed run must land on EXACTLY the
    params an uninterrupted run of the same seed produces.

    Parametrized over ``--data_placement``: 'auto' resolves to the
    device-resident epoch buffer on the child's in-RAM synthetic set, 'host'
    pins the per-step H2D loop (the production path for memmap/over-budget
    datasets) — the preemption contract is placement-independent
    (docs/RESILIENCE.md), so BOTH driver loops must honor it."""
    import json

    # reference: uninterrupted 2-epoch run
    ref = Child(tmp_path / "uninterrupted", epochs=2, trial="ref",
                data_placement=placement)
    ref.wait_for_line("DONE step=")
    assert ref.wait() == 0
    assert ref.grep(f"DONE step={2 * STEPS_PER_EPOCH}"), ref.lines[-5:]
    ref_last = os.path.join(ref.save_folder(), "last")

    # victim: SIGTERM after the first step's log line of epoch 1 — the flag
    # is observed at the next print_freq flush, strictly mid-epoch
    victim = Child(tmp_path / "preempted", epochs=2, trial="victim",
                   data_placement=placement)
    victim.wait_for_line("Train: [1][1/")
    victim.proc.send_signal(signal.SIGTERM)
    rc = victim.wait()
    assert rc == preempt.EXIT_PREEMPTED, (rc, victim.lines[-30:])
    run_dir = victim.save_folder()
    assert not os.path.exists(os.path.join(run_dir, "last"))  # not finished

    ppath = _find_preempt_save(run_dir)
    with open(os.path.join(ppath, "meta.json")) as f:
        meta = json.load(f)
    # mid-epoch coordinate: some steps of epoch 1 consumed, not all
    assert meta["epoch"] == 0
    assert 1 <= meta["step_in_epoch"] < STEPS_PER_EPOCH, meta
    assert f"step_{meta['step_in_epoch']}" in os.path.basename(ppath)

    # resume from the RUN DIR (resolution must find the emergency save)
    resumed = Child(tmp_path / "preempted", epochs=2, resume=run_dir,
                    trial="victim", data_placement=placement)
    resumed.wait_for_line("DONE step=")
    assert resumed.wait() == 0
    assert resumed.grep(f"resumed from {ppath} at epoch 1 step "
                        f"{meta['step_in_epoch']}"), resumed.lines[:10]
    assert resumed.grep(f"DONE step={2 * STEPS_PER_EPOCH}")

    a = _load_params(ref_last)
    b = _load_params(os.path.join(resumed.save_folder(), "last"))
    assert a.keys() == b.keys()
    exact = sum(np.array_equal(a[k], b[k]) for k in a)
    for k in a:
        np.testing.assert_allclose(
            a[k], b[k], rtol=1e-6, atol=1e-7,
            err_msg=f"{k} diverged across preempt/resume "
                    f"({exact}/{len(a)} tensors bit-identical)",
        )


@pytest.mark.slow
def test_kill9_resumes_from_newest_complete_save_and_corrupt_meta_loses(tmp_path):
    """kill -9 gives no grace: nothing new is saved, and the in-flight async
    scheduled save stays TORN (payload, no meta.json stamp). Resume must pick
    the newest COMPLETE save — never the torn one, and never a planted
    corrupt/truncated meta claiming huge progress."""
    victim = Child(tmp_path / "killed", epochs=4, trial="k9", save_freq=1)
    # epoch 3 running: ckpt_epoch_1's meta was stamped by epoch 2's save
    # drain; ckpt_epoch_2's write is still pending -> torn after SIGKILL
    victim.wait_for_line("Train: [3][1/")
    victim.proc.send_signal(signal.SIGKILL)
    rc = victim.wait()
    assert rc == -signal.SIGKILL
    run_dir = victim.save_folder()

    assert os.path.exists(os.path.join(run_dir, "ckpt_epoch_1", "meta.json"))
    # plant a corrupt (truncated) meta claiming absurd progress: it must lose
    fake = os.path.join(run_dir, "preempt_epoch_99_step_99")
    os.makedirs(fake, exist_ok=True)
    with open(os.path.join(fake, "meta.json"), "w") as f:
        f.write('{"epoch": 99, "step_in_ep')

    resumed = Child(tmp_path / "killed", epochs=4, resume=run_dir,
                    trial="k9", save_freq=1)
    resumed.wait_for_line("DONE step=")
    assert resumed.wait() == 0
    # resumed from a COMPLETE scheduled save (epoch 1 is guaranteed complete;
    # epoch 2's stamp raced the SIGKILL) — never the torn/corrupt candidates
    (resume_line,) = resumed.grep("resumed from ")
    assert "ckpt_epoch_" in resume_line and "preempt_epoch_99" not in resume_line
    assert resumed.grep(f"DONE step={4 * STEPS_PER_EPOCH}"), (
        resume_line, resumed.grep("DONE"))


@pytest.mark.slow
def test_nan_rollback_policy_completes_run(tmp_path, monkeypatch):
    """--nan_policy rollback (in-process): a poisoned first epoch is rolled
    back from its boundary backup, the crash checkpoint is still written for
    forensics, the LR is damped, and the run completes with the step counter
    aligned past the skipped epoch."""
    import jax

    from simclr_pytorch_distributed_tpu import config as config_lib
    from simclr_pytorch_distributed_tpu.data import cifar as cifar_lib
    from simclr_pytorch_distributed_tpu.parallel import mesh as mesh_lib
    from simclr_pytorch_distributed_tpu.train import supcon as supcon_driver
    from simclr_pytorch_distributed_tpu.utils.guard import NonFiniteLossError

    orig = cifar_lib.synthetic_dataset
    monkeypatch.setattr(
        cifar_lib, "synthetic_dataset",
        lambda n=2048, num_classes=10, seed=0, size=32: orig(
            n=128, num_classes=num_classes, seed=seed, size=8
        ),
    )
    monkeypatch.setattr(
        supcon_driver, "create_mesh",
        lambda devices=None, **kw: mesh_lib.create_mesh(
            devices=jax.devices()[:1] if devices is None else devices, **kw
        ),
    )

    real_check = supcon_driver.check_finite_loss
    calls = {"n": 0}

    def poisoned_check(loss, step, enabled=True):
        calls["n"] += 1
        if calls["n"] == 1:  # first flush of epoch 1
            raise NonFiniteLossError(float("nan"), step)
        return real_check(loss, step, enabled)

    monkeypatch.setattr(supcon_driver, "check_finite_loss", poisoned_check)

    cfg = config_lib.SupConConfig(
        model="resnet10", dataset="synthetic", batch_size=32, epochs=3,
        learning_rate=0.05, temp=0.5, cosine=True, save_freq=100,
        print_freq=1, size=8, workdir=str(tmp_path), seed=0,
        method="SimCLR", trial="rb", nan_policy="rollback",
    )
    cfg = config_lib.finalize_supcon(cfg)
    state = supcon_driver.run(cfg)  # must NOT raise
    spe = 112 // 32  # 128 synthetic - 16 test = 112 train
    # the skipped epoch still advances the step counter (LR-schedule / PRNG
    # alignment), so the final step equals the uninterrupted count
    assert int(state.step) == 3 * spe
    # ... and the optimizer's OWN schedule counter (the one the applied LR
    # actually reads) advanced in lockstep — not an epoch behind
    import optax

    counts = [int(s.count) for s in jax.tree.leaves(
        state.opt_state,
        is_leaf=lambda s: isinstance(s, optax.ScaleByScheduleState),
    ) if isinstance(s, optax.ScaleByScheduleState)]
    assert counts == [3 * spe], counts
    assert os.path.isdir(os.path.join(cfg.save_folder, "crash_epoch_1"))
    assert os.path.isdir(os.path.join(cfg.save_folder, "last"))
    # the damping is RUN state: it rides checkpoint meta so a resumed run
    # re-enters at the damped LR with its rollback budget intact
    import json

    with open(os.path.join(cfg.save_folder, "last", "meta.json")) as f:
        last_meta = json.load(f)
    assert last_meta["lr_scale"] == 0.5 and last_meta["rollbacks"] == 1

    # abort policy on the same poison dies like before
    calls["n"] = 0
    cfg2 = config_lib.SupConConfig(
        model="resnet10", dataset="synthetic", batch_size=32, epochs=3,
        learning_rate=0.05, temp=0.5, cosine=True, save_freq=100,
        print_freq=1, size=8, workdir=str(tmp_path), seed=0,
        method="SimCLR", trial="rb2", nan_policy="abort",
    )
    cfg2 = config_lib.finalize_supcon(cfg2)
    with pytest.raises(NonFiniteLossError):
        supcon_driver.run(cfg2)
    assert os.path.isdir(os.path.join(cfg2.save_folder, "crash_epoch_1"))


# ------------------------------------------------- elastic resume (mesh matrix)


@pytest.mark.slow
@pytest.mark.supervisor
def test_kill_on_mesh_8_resume_on_mesh_4_matches_uninterrupted(tmp_path):
    """The kill-on-N / resume-on-M leg of the elastic-resume contract
    (docs/RESILIENCE.md): with the two shape-dependent terms pinned —
    --syncBN on (global BN statistics) and a fixed --ngpu divisor — a run
    preempted mid-epoch on an 8-device virtual mesh and resumed on a
    4-device mesh must land on the params an UNINTERRUPTED 4-device run of
    the same seed produces (batch composition is mesh-shape-independent by
    construction: tests/test_data.py proves the permutation contract, this
    proves it end-to-end through the real driver + orbax reshard-on-load).
    The restore must also emit the loud elastic-resume note naming the
    documented divergences (per-device BN, non-auto ngpu)."""
    ref = Child(tmp_path / "ref4", epochs=2, trial="e4ref", ndev=4,
                syncbn=True)
    ref.wait_for_line("DONE step=")
    assert ref.wait() == 0
    ref_last = os.path.join(ref.save_folder(), "last")

    victim = Child(tmp_path / "elastic", epochs=2, trial="e84", ndev=8,
                   syncbn=True)
    victim.wait_for_line("Train: [1][1/")
    victim.proc.send_signal(signal.SIGTERM)
    assert victim.wait() == preempt.EXIT_PREEMPTED
    run_dir = victim.save_folder()

    resumed = Child(tmp_path / "elastic", epochs=2, resume=run_dir,
                    trial="e84", ndev=4, syncbn=True)
    resumed.wait_for_line("DONE step=")
    assert resumed.wait() == 0
    # the loud divergence note: saved under 8 devices, restored under 4
    note = resumed.grep("elastic resume")
    assert note and "8 device(s), restoring under 4" in note[0], (
        resumed.lines[:25])

    a = _load_params(ref_last)
    b = _load_params(os.path.join(resumed.save_folder(), "last"))
    assert a.keys() == b.keys()
    for k in a:
        np.testing.assert_allclose(
            a[k], b[k], rtol=1e-4, atol=1e-6,
            err_msg=f"{k} diverged across the 8->4 device resume "
                    f"(syncBN + fixed ngpu should be shape-independent)",
        )


# --------------------------------------------- the supervisor, real driver


VICTIM = os.path.join(os.path.dirname(__file__), "..", "scripts",
                      "supervisor_victim.py")


@pytest.mark.supervisor
def test_supervisor_absorbs_nan_abort_and_resumes_real_driver(tmp_path, monkeypatch):
    """The REAL supervisor babysitting the REAL driver through a typed
    failure (tier-1 smoke; the full SIGKILL/stall/collapse/resize matrix is
    scripts/supervisor_matrix.py + the slow tests): attempt 1 NaN-aborts
    with exit code 1 after the crash save, the supervisor backoff-restarts
    with --resume, attempt 2 (fault marker tripped) completes — and every
    decision lands in the supervisor's events.jsonl."""
    import json

    from simclr_pytorch_distributed_tpu.supervise import policy
    from simclr_pytorch_distributed_tpu.supervise.supervisor import (
        SuperviseConfig,
        Supervisor,
    )

    monkeypatch.setenv("JAX_COMPILATION_CACHE_DIR", os.path.abspath(CACHE))
    wd = str(tmp_path / "ws")
    cfg = SuperviseConfig(
        command=[sys.executable, os.path.abspath(VICTIM), "--workdir", wd,
                 "--epochs", "2", "--trial", "nan", "--save_freq", "1",
                 "--fault", "nan", "--fault_step", "2",
                 "--fault_marker", str(tmp_path / "nan.marker")],
        workdir=wd, max_restarts=3, backoff_base_s=0.1, poll_s=0.2,
    )
    sup = Supervisor(cfg)
    rc = sup.run()
    assert rc == 0
    assert [d.action for d in sup.decisions] == [
        policy.BACKOFF_RESTART, policy.DONE,
    ]
    with open(os.path.join(sup.supervise_dir, "events.jsonl")) as f:
        events = [json.loads(line) for line in f]
    launches = [e["args"] for e in events if e["name"] == "launch"]
    assert len(launches) == 2
    assert launches[1]["resume"], "the relaunch must carry --resume"
    decisions = [e["args"] for e in events if e["name"] == "decision"]
    assert decisions[0]["rc"] == 1  # the typed NaN exit, classified
    # the crash save the resume resolved from was observed as evidence
    assert any(e["name"] == "checkpoint_observed" for e in events)


@pytest.mark.slow
@pytest.mark.supervisor
def test_supervisor_matrix_collapse_scenario_via_script(tmp_path, monkeypatch):
    """Keep scripts/supervisor_matrix.py (the evidence producer) from
    rotting: its fastest scenario, run exactly as the committed artifact
    was produced, must pass and write a gate-accepted partial artifact."""
    import json

    monkeypatch.setenv("JAX_COMPILATION_CACHE_DIR", os.path.abspath(CACHE))
    out = tmp_path / "matrix.json"
    proc = subprocess.run(
        [sys.executable, "scripts/supervisor_matrix.py",
         "--workdir", str(tmp_path / "ws"), "--scenarios", "collapse",
         "--json", str(out)],
        cwd=os.path.dirname(os.path.dirname(CHILD)) or ".",
        capture_output=True, text=True, timeout=560,
    )
    assert proc.returncode == 0, proc.stdout[-2000:] + proc.stderr[-2000:]
    artifact = json.loads(out.read_text())
    rec = artifact["scenarios"]["collapse"]
    assert rec["ok"] and rec["rc"] == 3 and rec["decisions"] == ["give_up"]
