"""torch_convert round-trip: orbax -> reference .pth -> orbax, bit-identical.

The serving engine ingests reference ``.pth`` checkpoints through
``convert_reference_checkpoint``; this proves the converter pair is lossless
(pure transposes both ways), so `.pth` ingestion rests on a proven inverse
rather than on "the shapes happened to fit". Lazy-skips when torch is
unavailable (conversion is the only torch consumer in the repo).
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from simclr_pytorch_distributed_tpu.models import SupConResNet
from simclr_pytorch_distributed_tpu.utils.checkpoint import (
    MODEL_LAYOUT_VERSION,
    _save_tree,
    _write_meta,
)
from simclr_pytorch_distributed_tpu.utils.torch_convert import (
    convert_reference_checkpoint,
    export_reference_checkpoint,
    torch_state_dict_to_variables,
    variables_to_torch_state_dict,
)

pytestmark = pytest.mark.serve


def _leaves_with_paths(tree, prefix=()):
    if isinstance(tree, dict):
        for k in sorted(tree):
            yield from _leaves_with_paths(tree[k], prefix + (k,))
    else:
        yield prefix, np.asarray(tree)


@pytest.fixture(scope="module")
def rn18_variables():
    # resnet18: the smallest architecture the reference's model_dict accepts
    # for export (resnet10 is a framework-only extension and is refused)
    model = SupConResNet(model_name="resnet18")
    v = model.init(jax.random.key(7), jnp.zeros((2, 8, 8, 3)), train=False)
    return {"params": v["params"], "batch_stats": v["batch_stats"]}


def test_state_dict_mapping_roundtrip_bit_identical(rn18_variables):
    """variables -> reference state_dict -> variables, no torch needed:
    every leaf returns bit-identical (the mappings are pure transposes)."""
    sd = variables_to_torch_state_dict(rn18_variables)
    back = torch_state_dict_to_variables(sd)
    orig = dict(_leaves_with_paths(rn18_variables))
    rt = dict(_leaves_with_paths(back))
    assert orig.keys() == rt.keys()
    for path, leaf in orig.items():
        np.testing.assert_array_equal(
            leaf, rt[path], err_msg="/".join(path)
        )


def test_export_import_roundtrip_bit_identical(tmp_path, rn18_variables):
    """Full on-disk loop through the reference's torch.save layout."""
    pytest.importorskip("torch")
    ckpt = tmp_path / "ckpt_epoch_3"
    _save_tree(str(ckpt / "model"), rn18_variables)
    _write_meta(str(ckpt), {"epoch": 3, "model_layout": MODEL_LAYOUT_VERSION})

    pth = tmp_path / "exported.pth"
    info = export_reference_checkpoint(str(ckpt), str(pth))
    assert info["model_name"] == "resnet18" and info["epoch"] == 3

    back_dir = tmp_path / "reimported"
    info2 = convert_reference_checkpoint(str(pth), str(back_dir))
    assert info2["model_name"] == "resnet18" and info2["epoch"] == 3

    import orbax.checkpoint as ocp

    ckptr = ocp.StandardCheckpointer()
    restored = ckptr.restore(str(back_dir / "model"))
    ckptr.close()
    orig = dict(_leaves_with_paths(rn18_variables))
    rt = dict(_leaves_with_paths(restored))
    assert orig.keys() == rt.keys()
    for path, leaf in orig.items():
        np.testing.assert_array_equal(leaf, rt[path], err_msg="/".join(path))


def test_serving_engine_ingests_pth(tmp_path, rn18_variables):
    """The engine's `.pth` ingestion path: EmbeddingEngine.from_checkpoint on
    a reference-format file converts in place and infers the architecture."""
    pytest.importorskip("torch")
    from simclr_pytorch_distributed_tpu.serve.engine import EmbeddingEngine

    ckpt = tmp_path / "ckpt"
    _save_tree(str(ckpt / "model"), rn18_variables)
    _write_meta(str(ckpt), {"epoch": 1, "model_layout": MODEL_LAYOUT_VERSION})
    pth = tmp_path / "ref.pth"
    export_reference_checkpoint(str(ckpt), str(pth))

    eng = EmbeddingEngine.from_checkpoint(str(pth), buckets=(2,))
    assert eng.model.model_name == "resnet18"
    assert eng.feat_dim == 512
    assert (tmp_path / "ref.pth.converted" / "model").is_dir()
