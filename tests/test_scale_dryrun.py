"""ImageNet-scale config certification (BASELINE.json configs[4]) as a dryrun.

Round-2 verdict item 7: the large-recipe parts — LARS, the ring-sharded loss,
tensor parallelism, and the memmap ImageFolder path — were each tested alone
but never driven TOGETHER through the real pretrain driver. This test runs
``train/supcon.run`` with ``--optimizer lars --loss_impl ring
--model_parallel 2`` at GLOBAL BATCH 4096 over the virtual 8-device mesh on a
memmap-cached ``--dataset path`` tree: compile + 2 steps, finite result,
host RSS bounded by the memmap (not anonymous RAM).
"""

import os
import resource

import jax
import numpy as np
import pytest

pytestmark = pytest.mark.slow


def _write_ppm_tree(root, n_per_class=2080, classes=("a", "b"), px=8):
    """Tiny ImageFolder tree of raw P6 .ppm files (fast to write + PIL-readable)."""
    rng = np.random.default_rng(0)
    header = f"P6\n{px} {px}\n255\n".encode()
    for cls in classes:
        d = os.path.join(root, cls)
        os.makedirs(d)
        for i in range(n_per_class):
            body = rng.integers(0, 256, px * px * 3, dtype=np.uint8).tobytes()
            with open(os.path.join(d, f"{i:05d}.ppm"), "wb") as f:
                f.write(header + body)


def test_imagenet_scale_config_drives_end_to_end(tmp_path):
    from simclr_pytorch_distributed_tpu import config as config_lib
    from simclr_pytorch_distributed_tpu.train import supcon as supcon_driver

    data_root = tmp_path / "tree"
    _write_ppm_tree(str(data_root))  # 4160 images -> 1 global step/epoch @ 4096

    cfg = config_lib.SupConConfig(
        model="resnet10", dataset="path", data_folder=str(data_root),
        mean="(0.5, 0.5, 0.5)", std="(0.25, 0.25, 0.25)",
        batch_size=4096, epochs=2, learning_rate=0.5, temp=0.5, cosine=True,
        syncBN=True, optimizer="lars", loss_impl="ring", model_parallel=2,
        size=8, store_size=8, mmap_threshold_mb=0,  # force the memmap cache
        save_freq=2, print_freq=1, workdir=str(tmp_path / "work"), seed=0,
        method="SimCLR", trial="scale", ngpu=8,
    )
    cfg = config_lib.finalize_supcon(cfg)

    # the loader must actually take the memmap path at this threshold
    from simclr_pytorch_distributed_tpu.data.cifar import load_dataset

    train_data, _, _ = load_dataset(
        "path", str(data_root), size=8, store_size=8, mmap_threshold_mb=0
    )
    assert isinstance(train_data["images"], np.memmap)
    assert len(train_data["images"]) == 4160

    state = supcon_driver.run(cfg)

    # 4160 // 4096 = 1 step/epoch x 2 epochs; nan_guard (default on) would
    # have raised on any non-finite loss, so arrival here == finite steps
    assert int(state.step) == 2
    for leaf in jax.tree.leaves(state.params):
        assert np.isfinite(np.asarray(leaf)).all()
    # checkpoints written through the same run
    assert os.path.exists(os.path.join(cfg.save_folder, "last", "meta.json"))

    # bounded host footprint: the decoded tree rides the page cache, and the
    # whole driver (incl. XLA compile of the 8192-row ring program) stays
    # far below what an in-RAM ImageNet-scale decode would need
    rss_gb = resource.getrusage(resource.RUSAGE_SELF).ru_maxrss / 1e6
    assert rss_gb < 10.0, f"RSS {rss_gb:.1f} GB"
