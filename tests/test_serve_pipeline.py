"""Pipelined-executor tests: the dispatch/completion split in
serve/batcher.py + serve/engine.py.

The pipelining PROOF tests drive a gated fake dispatch function whose
``result()`` blocks on an Event the test controls — so "batch k+1 was
dispatched while batch k was still in flight" is asserted directly, not
inferred from timing. The stress tests then run the REAL engine behind the
pipelined batcher and pin the concurrent results to sequential
``engine.embed`` under the cross-bucket allclose contract
(tests/test_serve_engine.py).
"""

import threading
import time

import numpy as np
import pytest

from simclr_pytorch_distributed_tpu.serve.batcher import DynamicBatcher

pytestmark = pytest.mark.serve

H = W = 2


def imgs(*values):
    out = np.zeros((len(values), H, W, 3), np.uint8)
    for i, v in enumerate(values):
        out[i] = v
    return out


def fake_rows(images):
    images = np.asarray(images)
    return images.reshape(len(images), -1).sum(
        axis=1, keepdims=True
    ).astype(np.float32)


class GatedDispatch:
    """Fake async engine: dispatch returns instantly, each handle's
    ``result()`` blocks until the test releases that handle's gate."""

    def __init__(self):
        self.handles = []
        self.lock = threading.Lock()
        self.auto = False  # release_all is sticky: later handles born open

    def __call__(self, images):
        h = _GatedHandle(np.asarray(images))
        with self.lock:
            if self.auto:
                h.gate.set()
            self.handles.append(h)
        return h

    def release_all(self):
        with self.lock:
            self.auto = True
            for h in self.handles:
                h.gate.set()

    def count(self):
        with self.lock:
            return len(self.handles)


class _GatedHandle:
    def __init__(self, images):
        self.images = images
        self.gate = threading.Event()

    def result(self):
        assert self.gate.wait(10), "test forgot to release a gate"
        return fake_rows(self.images)


def wait_until(cond, timeout=5.0):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if cond():
            return True
        time.sleep(0.002)
    return False


def make(dispatch, **kw):
    kw.setdefault("max_batch", 1)  # one request = one batch: window counts requests
    kw.setdefault("max_wait_ms", 0)
    return DynamicBatcher(dispatch_fn=dispatch, **kw)


# ------------------------------------------------------- pipelining proof


def test_next_batch_dispatched_before_previous_materializes():
    """THE acceptance property: with max_inflight > 1, batch k+1's dispatch
    happens while batch k is still unmaterialized (its gate is closed)."""
    d = GatedDispatch()
    b = make(d, max_inflight=2)
    try:
        f1 = b.submit(imgs(1))
        f2 = b.submit(imgs(2))
        # both dispatched, NEITHER completed — the device-side window holds 2
        assert wait_until(lambda: d.count() == 2)
        assert not f1.done() and not f2.done()
        s = b.stats()
        assert s["inflight_batches"] == 2 and s["inflight_rows"] == 2
        assert s["dispatched_batches"] == 2 and s["batches"] == 0
        d.release_all()
        np.testing.assert_array_equal(f1.result(5), fake_rows(imgs(1)))
        np.testing.assert_array_equal(f2.result(5), fake_rows(imgs(2)))
    finally:
        d.release_all()
        b.close()
    assert b.stats()["max_inflight_observed"] == 2


def test_inflight_batch_count_bound_enforced():
    d = GatedDispatch()
    b = make(d, max_inflight=2)
    try:
        futs = [b.submit(imgs(i)) for i in range(4)]
        assert wait_until(lambda: d.count() == 2)
        time.sleep(0.05)  # window full: the 3rd batch must NOT dispatch
        assert d.count() == 2
        d.handles[0].gate.set()  # one completes -> exactly one more dispatches
        assert wait_until(lambda: d.count() == 3)
        time.sleep(0.05)
        assert d.count() == 3
        d.release_all()
        for i, f in enumerate(futs):
            np.testing.assert_array_equal(f.result(5), fake_rows(imgs(i)))
    finally:
        d.release_all()
        b.close()


def test_inflight_row_bound_enforced_under_load():
    """The HBM cap: max_inflight alone admits 8 batches, but the ROW bound
    (5) must hold dispatch at 2 two-row batches until one lands."""
    d = GatedDispatch()
    b = make(d, max_inflight=8, max_inflight_images=5)
    try:
        futs = [b.submit(imgs(i, i)) for i in range(4)]  # 2 rows each
        assert wait_until(lambda: d.count() == 2)  # 2+2 <= 5 < 2+2+2
        time.sleep(0.05)
        assert d.count() == 2 and b.stats()["inflight_rows"] == 4
        d.handles[0].gate.set()
        assert wait_until(lambda: d.count() == 3)
        d.release_all()
        for i, f in enumerate(futs):
            np.testing.assert_array_equal(f.result(5), fake_rows(imgs(i, i)))
    finally:
        d.release_all()
        b.close()


def test_oversized_batch_admitted_alone_not_deadlocked():
    """A single batch larger than max_inflight_images must dispatch when the
    window is empty (the engine chunks it) instead of waiting forever."""
    d = GatedDispatch()
    b = make(d, max_inflight=2, max_inflight_images=3)
    try:
        f = b.submit(imgs(1, 2, 3, 4, 5))  # 5 rows > bound 3
        assert wait_until(lambda: d.count() == 1)
        d.release_all()
        np.testing.assert_array_equal(
            f.result(5), fake_rows(imgs(1, 2, 3, 4, 5))
        )
    finally:
        d.release_all()
        b.close()


def test_completion_is_fifo_in_dispatch_order():
    """Releasing batch 2 FIRST must not resolve it before batch 1 — the
    completer preserves dispatch order end to end."""
    d = GatedDispatch()
    b = make(d, max_inflight=2)
    try:
        f1 = b.submit(imgs(1))
        f2 = b.submit(imgs(2))
        assert wait_until(lambda: d.count() == 2)
        d.handles[1].gate.set()  # batch 2 "lands" first
        time.sleep(0.05)
        assert not f2.done()  # still behind batch 1
        d.handles[0].gate.set()
        np.testing.assert_array_equal(f1.result(5), fake_rows(imgs(1)))
        np.testing.assert_array_equal(f2.result(5), fake_rows(imgs(2)))
    finally:
        d.release_all()
        b.close()


# ------------------------------------------------------------- lifecycle


def test_close_drains_inflight_batches_cleanly():
    """close() with batches still in flight: no hung futures, no deadlock
    (a background release models the device finishing mid-close)."""
    d = GatedDispatch()
    b = make(d, max_inflight=2)
    futs = [b.submit(imgs(i)) for i in range(3)]
    assert wait_until(lambda: d.count() == 2)
    releaser = threading.Timer(0.05, d.release_all)
    releaser.start()

    def late_release():
        # the 3rd batch dispatches during the drain; keep releasing
        wait_until(lambda: d.count() == 3)
        d.release_all()

    t = threading.Thread(target=late_release)
    t.start()
    b.close()  # must return with everything resolved
    releaser.join()
    t.join()
    for i, f in enumerate(futs):
        np.testing.assert_array_equal(f.result(0), fake_rows(imgs(i)))


def test_close_without_drain_still_completes_dispatched_batches():
    """drain=False fails requests still in the PENDING queue, but work that
    already left it — the in-flight batch, and the batch the assembler has
    popped and is holding for window room — completes: its compute is spent
    (or committed) and its waiters are real."""
    d = GatedDispatch()
    b = make(d, max_inflight=1)
    in_flight = b.submit(imgs(1))
    assert wait_until(lambda: d.count() == 1)
    held = b.submit(imgs(2))  # popped by the assembler, waiting for room
    queued = b.submit(imgs(3))  # stays pending while the assembler holds #2
    assert wait_until(lambda: b.stats()["queue_depth"] == 1)
    assert d.count() == 1  # window of 1 is full: #2 not dispatched yet
    threading.Timer(0.05, d.release_all).start()
    b.close(drain=False)
    np.testing.assert_array_equal(in_flight.result(5), fake_rows(imgs(1)))
    np.testing.assert_array_equal(held.result(5), fake_rows(imgs(2)))
    with pytest.raises(RuntimeError, match="closed"):
        queued.result(0)


def test_dispatch_error_fails_batch_immediately():
    def broken(images):
        raise ValueError("dispatch exploded")

    b = DynamicBatcher(dispatch_fn=broken, max_batch=8, max_wait_ms=0)
    try:
        fut = b.submit(imgs(1))
        with pytest.raises(ValueError, match="dispatch exploded"):
            fut.result(5)
        assert b.stats()["errors"] >= 1
    finally:
        b.close()


def test_completion_error_fails_waiters_and_frees_the_window():
    class BrokenHandle:
        def result(self):
            raise RuntimeError("D2H exploded")

    b = make(lambda images: BrokenHandle(), max_inflight=1)
    try:
        f1 = b.submit(imgs(1))
        f2 = b.submit(imgs(2))  # must still dispatch after f1's failure
        for f in (f1, f2):
            with pytest.raises(RuntimeError, match="D2H exploded"):
                f.result(5)
        s = b.stats()
        assert s["errors"] == 2 and s["inflight_batches"] == 0
    finally:
        b.close()


def test_constructor_validation():
    with pytest.raises(ValueError, match="embed_fn or dispatch_fn"):
        DynamicBatcher()
    with pytest.raises(ValueError, match="not both"):
        DynamicBatcher(fake_rows, dispatch_fn=GatedDispatch())
    with pytest.raises(ValueError, match="max_inflight"):
        DynamicBatcher(fake_rows, max_inflight=0)
    with pytest.raises(ValueError, match="max_inflight"):
        DynamicBatcher(fake_rows, max_inflight_images=0)


def test_occupancy_gauges_present_and_bounded():
    d = GatedDispatch()
    b = make(d, max_inflight=2)
    try:
        f = b.submit(imgs(1))
        assert wait_until(lambda: d.count() == 1)
        time.sleep(0.03)  # accrue busy time while one batch is in flight
        s = b.stats()
        assert s["inflight_batches"] == 1
        assert 0.0 < s["pipeline_occupancy"] <= 1.0
        assert 0.0 < s["avg_inflight_depth"] <= 2.0
        d.release_all()
        f.result(5)
    finally:
        d.release_all()
        b.close()
    s = b.stats()
    assert s["max_inflight"] == 2 and s["max_inflight_images"] == 4096


# ---------------------------------------------- real engine, real threads


SIZE = 8


@pytest.fixture(scope="module")
def engine():
    from simclr_pytorch_distributed_tpu.serve.engine import EmbeddingEngine

    return EmbeddingEngine.random_init(
        model_name="resnet10", size=SIZE, buckets=(2, 8)
    )


def real_images(rng, n):
    return rng.integers(0, 256, size=(n, SIZE, SIZE, 3), dtype=np.uint8)


def test_concurrent_mixed_sizes_match_sequential_embed(engine):
    """Satellite stress: N threads × mixed request sizes through the
    pipelined batcher == sequential engine.embed, within the pinned
    cross-bucket allclose contract (coalescing may route a request through
    a different bucket program than its solo embed took)."""
    rng = np.random.default_rng(0)
    requests = [real_images(rng, int(n)) for n in rng.integers(1, 9, size=24)]
    expected = [engine.embed(x) for x in requests]

    b = DynamicBatcher(
        dispatch_fn=engine.dispatch, max_batch=8, max_wait_ms=2,
        max_inflight=3, max_inflight_images=64,
        validate=engine.validate_images,
    )
    results = [None] * len(requests)
    errors = []

    def client(k):
        try:
            results[k] = b.submit(requests[k]).result(timeout=60)
        except Exception as e:  # noqa: BLE001 — surfaced below
            errors.append((k, e))

    threads = [
        threading.Thread(target=client, args=(k,))
        for k in range(len(requests))
    ]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    b.close()
    assert not errors, errors
    for k, (got, want) in enumerate(zip(results, expected)):
        np.testing.assert_allclose(got, want, rtol=1e-4, atol=1e-5,
                                   err_msg=f"request {k}")
    assert b.stats()["errors"] == 0


def test_real_engine_close_with_inflight_drains(engine):
    """close() racing live device work: every submitted future resolves."""
    rng = np.random.default_rng(1)
    b = DynamicBatcher(
        dispatch_fn=engine.dispatch, max_batch=8, max_wait_ms=1,
        max_inflight=3, validate=engine.validate_images,
    )
    futs = [b.submit(real_images(rng, 4)) for _ in range(6)]
    b.close()  # drain: returns only after the pipeline is empty
    for f in futs:
        assert f.done()
        assert f.result(0).shape == (4, 512)
