"""Windowed streaming device store (data/device_store.py WindowStore).

The contract under test is the ISSUE-7 tentpole carried to datasets that
don't fit HBM: with ``--data_placement window`` every training batch is
BYTE-IDENTICAL to what the host ``EpochLoader`` would have produced — full
epochs (including the padded short tail window), mid-epoch resume as a
window + in-window slice offset shift, and the multi-process slicing —
while the hot loop performs exactly ONE host->device upload per WINDOW
(never per step), counted mechanically through the store's injectable
``window_put`` hook. Plus the three-way placement ladder
(device -> window -> host) that replaces the old binary verdict. All on
the virtual 8-device CPU mesh (conftest.py).
"""

import logging
import threading

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from simclr_pytorch_distributed_tpu.data import device_store
from simclr_pytorch_distributed_tpu.data.device_store import (
    DeviceStore,
    WindowStore,
    epoch_index_matrix,
    resolve_data_placement,
    windowed_bytes_per_device,
)
from simclr_pytorch_distributed_tpu.data.pipeline import EpochLoader
from simclr_pytorch_distributed_tpu.parallel.mesh import create_mesh
from simclr_pytorch_distributed_tpu.train.supcon_step import epoch_position

pytestmark = pytest.mark.window


def _dataset(n=70, size=8, seed=0):
    rng = np.random.default_rng(seed)
    images = rng.integers(0, 256, (n, size, size, 3), dtype=np.uint8)
    labels = rng.integers(0, 10, n).astype(np.int32)
    return images, labels


# ------------------------------------------------------------ bit-identity


def test_window_batches_byte_equal_to_host_loader_full_epochs():
    """Every step of two epochs: the window-buffer row at the in-window
    offset equals the host loader's batch, bytes and labels alike (the
    acceptance contract) — including the padded short tail window
    (4 steps, W=3 -> windows of 3 and 1+pad)."""
    images, labels = _dataset()
    loader = EpochLoader(images, labels, 16, base_seed=5)
    mesh = create_mesh()  # the full 8-device virtual mesh
    store = WindowStore(loader, mesh, 3, prefetch=False)
    assert loader.steps_per_epoch == 4 and store.n_windows == 2
    for epoch in (1, 2):
        host = list(loader.epoch(epoch))
        assert len(host) == loader.steps_per_epoch
        for s, (h_imgs, h_labs) in enumerate(host):
            b_imgs, b_labs = store.batch_buffers(epoch, s)
            off = s % store.window_batches
            d_imgs, d_labs = np.asarray(b_imgs), np.asarray(b_labs)
            assert d_imgs.dtype == np.uint8 and d_labs.dtype == np.int32
            assert d_imgs.shape[0] == store.window_batches  # static shape
            np.testing.assert_array_equal(d_imgs[off], h_imgs)
            np.testing.assert_array_equal(d_labs[off], h_labs)


def test_mid_epoch_resume_is_a_window_plus_slice_offset_shift():
    """``epoch(e, start_step=k)`` equals the window buffers from window
    ``k // W`` offset ``k % W`` on, and the in-program position derived
    from the restored global step (``epoch_position % W``) lands exactly
    there — the resume path never replays consumed batches."""
    images, labels = _dataset(n=130)
    loader = EpochLoader(images, labels, 16, base_seed=5)
    mesh = create_mesh()
    steps = loader.steps_per_epoch  # 8
    W = 3
    store = WindowStore(loader, mesh, W, prefetch=False)
    epoch, start_step = 3, 4  # mid-window resume: window 1, offset 1
    resumed = list(loader.epoch(epoch, start_step=start_step))
    assert len(resumed) == steps - start_step
    for off, (h_imgs, _) in enumerate(resumed):
        idx = start_step + off
        b_imgs, _b = store.batch_buffers(epoch, idx)
        np.testing.assert_array_equal(np.asarray(b_imgs)[idx % W], h_imgs)
    # the restored counter maps to the right in-window slice on device
    gstep = (epoch - 1) * steps + start_step
    pos = int(jax.jit(
        lambda s: epoch_position(s, steps) % W
    )(jnp.int32(gstep)))
    assert pos == start_step % W


def test_windowed_position_stays_on_valid_tail_rows():
    """The padded tail rows are never addressable: windows are aligned to
    multiples of W, so whenever a step lands in the short tail window its
    in-program position (``epoch_position % W``) stays below the tail's
    real length — for every global step of several epochs."""
    steps, W = 7, 3  # tail window holds 1 real batch + 2 padded rows
    tail_window = (steps - 1) // W
    tail_len = steps - tail_window * W
    for gstep in range(3 * steps):
        pos = gstep % steps  # epoch_position
        if pos // W == tail_window:
            assert pos % W < tail_len


def test_multi_process_virtual_mesh_slices_match_per_process_loaders():
    """Multi-host layout: window w's rows are ``epoch_index_matrix`` rows
    ``[w*W, (w+1)*W)``, and column block p of every row IS process p's
    ``EpochLoader`` stream — so a mesh whose data axis spans processes
    gives each process's devices exactly its host-loader slice of every
    global batch in the window (the virtual-mesh stand-in for a pod run)."""
    images, labels = _dataset(n=64)
    nproc, global_batch, W = 4, 16, 3
    per_proc = global_batch // nproc
    ref = EpochLoader(images, labels, global_batch, base_seed=3)
    mesh = create_mesh()
    store = WindowStore(ref, mesh, W, prefetch=False)
    idx = epoch_index_matrix(ref, epoch=5)
    for p in range(nproc):
        shard_loader = EpochLoader(
            images, labels, global_batch, base_seed=3,
            process_index=p, process_count=nproc,
        )
        for s, (h_imgs, h_labs) in enumerate(shard_loader.epoch(5)):
            b_imgs, b_labs = store.batch_buffers(5, s)
            cols = slice(p * per_proc, (p + 1) * per_proc)
            np.testing.assert_array_equal(
                np.asarray(b_imgs)[s % W, cols], h_imgs
            )
            np.testing.assert_array_equal(
                np.asarray(b_labs)[s % W, cols], h_labs
            )
            # and the window rows are exactly the index-matrix rows
            np.testing.assert_array_equal(
                images[idx[s, cols]], h_imgs
            )


# ------------------------------------------------------- transfer counting


def test_one_upload_per_window_never_per_step():
    """The per-window H2D is ONE window-sized upload: every step inside a
    window hits the cached handles; a new window uploads once; re-requests
    of the current window never re-upload. Counted mechanically via the
    injectable ``window_put`` (the index_put pattern)."""
    images, labels = _dataset(n=130)
    loader = EpochLoader(images, labels, 16, base_seed=5)  # 8 steps
    mesh = create_mesh()
    uploads = []

    def counting_put(w_imgs, w_labs):
        uploads.append((w_imgs.nbytes, w_labs.nbytes))
        return jax.device_put(w_imgs), jax.device_put(w_labs)

    W = 4
    store = WindowStore(loader, mesh, W, window_put=counting_put,
                        prefetch=False)
    assert store.n_windows == 2
    for idx in range(loader.steps_per_epoch):
        store.batch_buffers(1, idx)
        store.batch_buffers(1, idx)  # driver re-entry: cached, no re-upload
    assert len(uploads) == store.n_windows
    # the transfer really is window-sized — W batches, not the dataset
    row = images[0].nbytes
    assert all(u[0] == W * 16 * row for u in uploads)
    assert all(u[1] == W * 16 * 4 for u in uploads)  # int32 labels
    # a second epoch uploads its own windows once each
    for idx in range(loader.steps_per_epoch):
        store.batch_buffers(2, idx)
    assert len(uploads) == 2 * store.n_windows


def test_stage_gathers_only_the_process_local_column_block():
    """On a pod each process stages exactly the 1/P column block of the
    window its own devices will hold — never the peers' slices (a
    memmap-backed tree pages only those rows). Pinned through the hook:
    the uploaded block is [W, B/P, ...] and byte-equal to the process's
    own EpochLoader stream."""
    images, labels = _dataset(n=64)
    nproc, global_batch, W = 4, 16, 2
    mesh = create_mesh()
    blocks = []

    def recording_put(w_imgs, w_labs):
        blocks.append((w_imgs, w_labs))
        return jax.device_put(w_imgs), jax.device_put(w_labs)

    p = 1
    loader = EpochLoader(
        images, labels, global_batch, base_seed=3,
        process_index=p, process_count=nproc,
    )
    store = WindowStore(loader, mesh, W, window_put=recording_put,
                        prefetch=False)
    host = list(loader.epoch(1))  # process p's own slices
    for s, (h_imgs, h_labs) in enumerate(host):
        store.batch_buffers(1, s)
        w_imgs, w_labs = blocks[-1]
        assert w_imgs.shape == (W, global_batch // nproc) + images.shape[1:]
        np.testing.assert_array_equal(w_imgs[s % W], h_imgs)
        np.testing.assert_array_equal(w_labs[s % W], h_labs)
    assert len(blocks) == store.n_windows


def test_prefetch_thread_stages_the_next_window():
    """Double buffering is real, not assumed: with ``prefetch=True`` every
    window after the first of an epoch is staged by the WindowStore
    prefetch thread (shadow buffer), not the training thread, and the
    boundary swap consumes the staged upload instead of re-staging."""
    images, labels = _dataset(n=130)
    loader = EpochLoader(images, labels, 16, base_seed=5)  # 8 steps
    mesh = create_mesh()
    staged = []  # (window, thread_name)

    def recording_put(w_imgs, w_labs):
        staged.append(threading.current_thread().name)
        return jax.device_put(w_imgs), jax.device_put(w_labs)

    store = WindowStore(loader, mesh, 2, window_put=recording_put)
    assert store.n_windows == 4
    for idx in range(loader.steps_per_epoch):
        store.batch_buffers(1, idx)
    assert len(staged) == store.n_windows  # still one upload per window
    assert not staged[0].startswith("WindowStore-prefetch")
    assert all(t.startswith("WindowStore-prefetch") for t in staged[1:])


def test_jump_frees_the_abandoned_staged_window_before_restaging():
    """A resume/rollback jump abandons the staged shadow window; the store
    must wait the in-flight stage out and free its device shard BEFORE
    staging the replacement — otherwise a device admitted at exactly the
    ladder's 2x-window budget transiently holds a third shard (OOM on the
    very path documented as safe)."""
    images, labels = _dataset(n=130)
    loader = EpochLoader(images, labels, 16, base_seed=5)  # 8 steps
    mesh = create_mesh()
    staged = []

    def slow_put(w_imgs, w_labs):
        import time

        time.sleep(0.15)  # keep the prefetch RUNNING when the jump lands
        bufs = (jax.device_put(w_imgs), jax.device_put(w_labs))
        staged.append(bufs)
        return bufs

    store = WindowStore(loader, mesh, 2, window_put=slow_put)
    store.batch_buffers(1, 0)  # schedules the window-1 prefetch
    store.batch_buffers(3, 0)  # the jump: epoch 3, while the stage runs
    assert len(staged) == 3  # window (1,0) + abandoned (1,1) + new (3,0)
    abandoned = staged[1]
    assert all(a.is_deleted() for a in abandoned)
    # the served buffers are live and correct
    host = list(loader.epoch(3))
    cur = store.batch_buffers(3, 0)
    np.testing.assert_array_equal(np.asarray(cur[0])[0], host[0][0])


def test_close_stops_the_prefetch_worker():
    """Drivers close() the store on any exit (the EpochLoader hygiene):
    the prefetch thread dies instead of stalling interpreter exit on a
    staged upload nothing will read, and a closed store still serves
    buffers — synchronously (the prefetch=False path)."""
    images, labels = _dataset(n=130)
    loader = EpochLoader(images, labels, 16, base_seed=5)
    mesh = create_mesh()
    store = WindowStore(loader, mesh, 2)
    store.batch_buffers(1, 0)  # schedules the window-1 prefetch
    store.close()
    assert store._executor is None and store._next is None
    deadline = [t for t in threading.enumerate()
                if t.name.startswith("WindowStore-prefetch")]
    for t in deadline:
        t.join(timeout=5.0)
    assert not any(
        t.is_alive() for t in threading.enumerate()
        if t.name.startswith("WindowStore-prefetch")
    )
    b_imgs, _ = store.batch_buffers(1, 2)  # degrades to synchronous staging
    host = list(loader.epoch(1))
    np.testing.assert_array_equal(np.asarray(b_imgs)[0], host[2][0])
    # DeviceStore shares the close() API (a no-op — no threads)
    DeviceStore(loader, mesh).close()


def test_prefetch_exception_reraises_on_the_training_thread():
    """A staging failure (disk error on a memmap, a bad hook) must abort
    the step with a real traceback, not strand the loop — the EpochLoader
    worker convention."""
    images, labels = _dataset(n=130)
    loader = EpochLoader(images, labels, 16, base_seed=5)
    mesh = create_mesh()
    calls = []

    def failing_put(w_imgs, w_labs):
        calls.append(1)
        if len(calls) > 1:
            raise OSError("simulated staging failure")
        return jax.device_put(w_imgs), jax.device_put(w_labs)

    store = WindowStore(loader, mesh, 4, window_put=failing_put)
    store.batch_buffers(1, 0)  # ok; schedules the poisoned prefetch
    with pytest.raises(OSError, match="staging failure"):
        store.batch_buffers(1, 4)  # the swap surfaces the worker's error


def test_jitted_windowed_step_slices_the_host_batch():
    """The compiled windowed slice (what the resident train step runs with
    ``window_batches`` set) returns the host loader's exact batch."""
    from simclr_pytorch_distributed_tpu.data.device_store import (
        slice_epoch_step,
    )

    images, labels = _dataset()
    loader = EpochLoader(images, labels, 16, base_seed=9)  # 4 steps
    mesh = create_mesh()
    W = 2
    store = WindowStore(loader, mesh, W, prefetch=False)
    steps = loader.steps_per_epoch

    @jax.jit
    def sliced(w_imgs, w_labs, gstep):
        pos = epoch_position(gstep, steps) % W
        return slice_epoch_step(w_imgs, w_labs, pos)

    epoch = 2
    for s, (h_imgs, h_labs) in enumerate(loader.epoch(epoch)):
        w_imgs, w_labs = store.batch_buffers(epoch, s)
        gstep = (epoch - 1) * steps + s
        im, lb = sliced(w_imgs, w_labs, jnp.int32(gstep))
        np.testing.assert_array_equal(np.asarray(im), h_imgs)
        np.testing.assert_array_equal(np.asarray(lb), h_labs)


# ------------------------------------------------------ placement ladder


def test_ladder_device_when_resident_fits():
    images, labels = _dataset()
    mesh = create_mesh()
    assert resolve_data_placement(
        "auto", images, labels, 16, mesh, budget_bytes=1 << 30
    ) == "device"


def test_ladder_window_when_only_window_fits(caplog):
    """The middle rung: a budget too small for residency but holding
    2x window bytes resolves 'auto' to 'window' (with the banner naming
    why it is not fully resident), and explicit 'window' is honored."""
    images, labels = _dataset(n=130)
    mesh = create_mesh()
    W = 2
    need_res = device_store.resident_bytes_per_device(images, labels, 16, 8)
    need_win = windowed_bytes_per_device(images, labels, 16, 8, W)
    budget = (need_res + need_win) // 2
    assert need_win <= budget < need_res
    with caplog.at_level(
        logging.INFO,
        logger="simclr_pytorch_distributed_tpu.data.device_store",
    ):
        got = resolve_data_placement(
            "auto", images, labels, 16, mesh,
            budget_bytes=budget, window_batches=W,
        )
    assert got == "window"
    assert any("data_placement: window" in r.message for r in caplog.records)
    assert resolve_data_placement(
        "window", images, labels, 16, mesh,
        budget_bytes=budget, window_batches=W,
    ) == "window"


def test_ladder_host_when_nothing_fits(caplog):
    images, labels = _dataset()
    mesh = create_mesh()
    with caplog.at_level(
        logging.WARNING,
        logger="simclr_pytorch_distributed_tpu.data.device_store",
    ):
        got = resolve_data_placement(
            "auto", images, labels, 16, mesh, budget_bytes=10
        )
    assert got == "host"
    assert any("auto -> host" in r.message for r in caplog.records)
    # explicit 'window' over budget fails loudly at startup, never OOMs
    with pytest.raises(ValueError, match="cannot be satisfied"):
        resolve_data_placement(
            "window", images, labels, 16, mesh, budget_bytes=10
        )


def test_memmap_is_windowable_not_host_degraded(tmp_path):
    """The ladder's reason for existing: a memmap-backed dataset (folder.py
    trees) disqualifies RESIDENCY (it would page the whole tree into RAM)
    but is windowable — each window's gather reads only its own rows — so
    'auto' resolves to 'window', not 'host'."""
    images, labels = _dataset()
    mm_path = tmp_path / "imgs.npy"
    np.save(mm_path, images)
    mm = np.load(mm_path, mmap_mode="r")
    mesh = create_mesh()
    assert isinstance(mm, np.memmap)
    assert resolve_data_placement(
        "auto", mm, labels, 16, mesh, budget_bytes=1 << 30
    ) == "window"
    # explicit residency still refuses a memmap, loudly
    with pytest.raises(ValueError, match="memmap"):
        resolve_data_placement(
            "device", mm, labels, 16, mesh, budget_bytes=1 << 30
        )
    # the PRODUCTION path: EpochLoader's ascontiguousarray strips the
    # np.memmap subclass into a plain ndarray VIEW; make_store must still
    # see through it and build the WINDOW store, never the resident one
    loader = EpochLoader(mm, labels, 16, base_seed=0)
    assert device_store._is_memmap_backed(loader.images)
    store = device_store.make_store(
        "auto", loader, mesh, budget_bytes=1 << 30, window_batches=2
    )
    assert isinstance(store, WindowStore) and store.window_batches == 2


def test_make_store_builds_the_ladder_verdict():
    """make_store returns DeviceStore / WindowStore / None as the ladder
    decides, resolving from the loader's own arrays."""
    images, labels = _dataset(n=130)
    mesh = create_mesh()
    loader = EpochLoader(images, labels, 16, base_seed=3)
    assert isinstance(
        device_store.make_store("auto", loader, mesh, budget_bytes=1 << 30),
        DeviceStore,
    )
    need_res = device_store.resident_bytes_per_device(images, labels, 16, 8)
    need_win = windowed_bytes_per_device(images, labels, 16, 8, 2)
    mid_budget = (need_res + need_win) // 2
    store = device_store.make_store(
        "auto", loader, mesh, budget_bytes=mid_budget, window_batches=2
    )
    assert isinstance(store, WindowStore) and store.loader is loader
    assert device_store.make_store(
        "auto", loader, mesh, budget_bytes=10
    ) is None
    assert device_store.make_store("host", loader, mesh) is None


def test_windowed_bytes_accounting():
    """2x one window shard (training window + shadow), dataset-size
    independent — the whole point of the middle rung."""
    images, labels = _dataset(n=130)
    row = images[0].nbytes + 4
    assert windowed_bytes_per_device(images, labels, 16, 1, 4) == (
        2 * 4 * 16 * row
    )
    # 8-way sharding divides the window term
    assert windowed_bytes_per_device(images, labels, 16, 8, 4) == (
        2 * ((4 * 16 * row + 7) // 8)
    )
    # window clamped to the epoch (130 rows @ batch 16 -> 8 steps)
    assert windowed_bytes_per_device(images, labels, 16, 1, 99) == (
        2 * 8 * 16 * row
    )


def test_three_way_ladder_verdict_is_collective(monkeypatch, caplog):
    """Each ladder rung is one matched collective point: a peer's rejection
    of residency walks every process down to the window rung together, and
    a peer's rejection there sends every process to host. Explicit
    'window' raises on every process when a peer rejects."""
    images, labels = _dataset()
    mesh = create_mesh()
    calls = []

    def peer_disagrees(local_ok):
        calls.append(local_ok)
        return False  # some OTHER process was over budget; we were fine

    monkeypatch.setattr(
        device_store, "_agree_across_processes", peer_disagrees
    )
    with caplog.at_level(
        logging.WARNING,
        logger="simclr_pytorch_distributed_tpu.data.device_store",
    ):
        got = resolve_data_placement(
            "auto", images, labels, 16, mesh, budget_bytes=1 << 30
        )
    assert got == "host"
    assert calls == [True, True]  # both rungs reached, local verdict 'fits'
    assert any("peer process" in r.message for r in caplog.records)
    calls.clear()
    with pytest.raises(ValueError, match="peer process"):
        resolve_data_placement(
            "window", images, labels, 16, mesh, budget_bytes=1 << 30
        )
    # explicit 'window' is a single collective point, entered with the
    # local verdict (here: fits)
    assert calls == [True]


def test_store_rejects_bad_geometry():
    images, labels = _dataset(n=70)
    mesh = create_mesh()  # data axis = 8
    ragged = EpochLoader(images, labels, 16, drop_last=False, shuffle=False)
    with pytest.raises(ValueError, match="drop_last"):
        WindowStore(ragged, mesh, 2)
    indivisible = EpochLoader(images, labels, 12, base_seed=0)
    with pytest.raises(ValueError, match="divisible"):
        WindowStore(indivisible, mesh, 2)
    ok = EpochLoader(images, labels, 16, base_seed=0)
    with pytest.raises(ValueError, match="window_batches"):
        WindowStore(ok, mesh, 0)
    # window longer than the epoch clamps to the epoch (degenerate but legal)
    assert WindowStore(ok, mesh, 99).window_batches == ok.steps_per_epoch
