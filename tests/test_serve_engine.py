"""Engine tests: the shape-bucketed jit cache, the padding invariant the
bucketing scheme rests on, chunking, normalization, the content cache, and
checkpoint ingestion with architecture inference.

Compile budget: one shared module-scoped engine (resnet10 @ 8x8, buckets
(2, 8) — one replicated + one sharded program) carries most tests; the
cached/normalized variants each add a single bucket-2 program.
"""

import numpy as np
import pytest

from simclr_pytorch_distributed_tpu.models import infer_architecture_from_variables
from simclr_pytorch_distributed_tpu.serve.cache import EmbeddingCache
from simclr_pytorch_distributed_tpu.serve.engine import EmbeddingEngine

pytestmark = pytest.mark.serve

SIZE = 8


def images_of(rng, n):
    return rng.integers(0, 256, size=(n, SIZE, SIZE, 3), dtype=np.uint8)


@pytest.fixture(scope="module")
def engine():
    return EmbeddingEngine.random_init(
        model_name="resnet10", size=SIZE, buckets=(2, 8)
    )


def test_no_recompile_within_a_bucket(engine):
    """Request sizes 3..8 all share the bucket-8 program: exactly ONE trace
    (the compile-count witness — the engine's reason to exist)."""
    rng = np.random.default_rng(0)
    for n in (3, 5, 7, 8, 4, 6):
        out = engine.embed(images_of(rng, n))
        assert out.shape == (n, 512) and out.dtype == np.float32
    assert engine.stats()["traces"].get(8) == 1
    engine.embed(images_of(rng, 1))
    engine.embed(images_of(rng, 2))
    assert engine.stats()["traces"].get(2) == 1
    assert sum(engine.stats()["traces"].values()) == 2  # and nothing else


def test_padded_bucket_equals_exact_batch(engine):
    """Row i depends only on image i. Within one compiled bucket program the
    equality is BITWISE: a batch of 5 padded to bucket 8 returns exactly the
    rows those 5 images get when batched with 3 real peers instead."""
    rng = np.random.default_rng(1)
    x5 = images_of(rng, 5)
    peers = images_of(rng, 3)
    a = engine.embed(x5)  # 5 -> bucket 8, zero-padded
    b = engine.embed(np.concatenate([x5, peers]))  # exact bucket-8 batch
    np.testing.assert_array_equal(a, b[:5])


def test_cross_bucket_agreement_is_float_tight(engine):
    """Across DIFFERENT bucket programs (different shardings/layouts) XLA may
    reorder reductions — agreement is to float tolerance, not bitwise (the
    honest half of the padding contract; see docs/SERVING.md)."""
    rng = np.random.default_rng(2)
    x2 = images_of(rng, 2)
    a = engine.embed(x2)  # bucket 2 (replicated program)
    b = engine.embed(np.concatenate([x2, images_of(rng, 6)]))  # bucket 8 (sharded)
    np.testing.assert_allclose(a, b[:2], rtol=1e-5, atol=1e-5)


def test_repeat_call_bit_stable(engine):
    rng = np.random.default_rng(3)
    x = images_of(rng, 4)
    np.testing.assert_array_equal(engine.embed(x), engine.embed(x))


def test_requests_above_top_bucket_are_chunked(engine):
    rng = np.random.default_rng(4)
    x = images_of(rng, 13)  # 13 > top bucket 8: chunks of 8 + 5
    before = dict(engine.stats()["bucket_dispatches"])
    out = engine.embed(x)
    after = engine.stats()["bucket_dispatches"]
    assert out.shape == (13, 512)
    assert after[8] - before[8] == 2
    # chunk rows match embedding the pieces separately (same bucket program)
    np.testing.assert_array_equal(out[:8], engine.embed(x[:8]))
    np.testing.assert_array_equal(out[8:], engine.embed(x[8:]))
    assert sum(engine.stats()["traces"].values()) == 2  # still no recompiles


def test_empty_request_and_validation(engine):
    assert engine.embed(np.zeros((0, SIZE, SIZE, 3), np.uint8)).shape == (0, 512)
    with pytest.raises(ValueError, match="expected"):
        engine.embed(np.zeros((2, SIZE, SIZE), np.uint8))
    with pytest.raises(ValueError, match="uint8"):
        engine.embed(np.zeros((2, SIZE, SIZE, 3), np.float32))


def test_unpinned_geometry_is_rejected_not_compiled(engine):
    """The bucket scheme bounds compiles only with the spatial shape pinned:
    a novel (H, W) must be REJECTED (-> HTTP 400 through the batcher's
    validate hook), never traced — else arbitrary client sizes recompile per
    request (a trivial DoS on the open endpoint)."""
    traces_before = sum(engine.stats()["traces"].values())
    with pytest.raises(ValueError, match="pinned at construction"):
        engine.embed(np.zeros((2, SIZE * 2, SIZE * 2, 3), np.uint8))
    with pytest.raises(ValueError, match="pinned"):
        engine.validate_images(np.zeros((1, SIZE, SIZE + 1, 3), np.uint8))
    assert sum(engine.stats()["traces"].values()) == traces_before


def test_bucket_for(engine):
    assert [engine.bucket_for(n) for n in (1, 2, 3, 8, 9)] == [2, 2, 8, 8, 8]


def test_dispatch_defers_materialization_and_dispatches_all_chunks(engine):
    """The async API's contract, pinned by the dispatch counters: a miss set
    spanning bucket chunks enqueues EVERY chunk's compiled call before
    anything materializes (the old ``embed`` round-tripped chunk k's D2H
    before dispatching chunk k+1 — the serialization this PR removes)."""
    rng = np.random.default_rng(8)
    x = images_of(rng, 13)  # chunks of 8 + 5 through the top bucket
    before = dict(engine.stats()["bucket_dispatches"])
    h = engine.dispatch(x)
    mid = engine.stats()["bucket_dispatches"]
    assert mid[8] - before[8] == 2  # both chunks already dispatched...
    assert not h.done()             # ...and nothing materialized yet
    assert h.n_rows == 13
    out = h.result()
    assert h.done() and out.shape == (13, 512)
    # completion == the synchronous spelling (same bucket programs: bitwise)
    np.testing.assert_array_equal(out, engine.embed(x))
    assert h.result() is out  # idempotent; device buffers already released


def test_dispatch_populates_cache_at_completion():
    eng = EmbeddingEngine.random_init(
        model_name="resnet10", size=SIZE, buckets=(2,),
        cache=EmbeddingCache(capacity=64),
    )
    x = images_of(np.random.default_rng(9), 2)
    h = eng.dispatch(x)
    assert len(eng.cache) == 0  # rows land in the cache at COMPLETION
    first = h.result()
    assert len(eng.cache) == 2
    dispatches = sum(eng.stats()["bucket_dispatches"].values())
    second = eng.dispatch(x).result()  # full hit: the device is not touched
    assert sum(eng.stats()["bucket_dispatches"].values()) == dispatches
    np.testing.assert_array_equal(first, second)


def test_bf16_serving_parity_and_contract(engine):
    """--dtype bf16: params cast to bf16, BN statistics kept fp32, head
    output returned fp32 — and embeddings within a pinned tolerance of the
    fp32 engine (observed ~7e-3 max abs on CPU; 5x margin)."""
    import jax
    import jax.numpy as jnp

    b16 = EmbeddingEngine.random_init(
        model_name="resnet10", size=SIZE, buckets=(8,), dtype="bf16"
    )  # seed 0 = the shared fp32 fixture's weights, cast
    x = images_of(np.random.default_rng(10), 8)
    a = engine.embed(x)  # fp32 reference (bucket-8 program)
    b = b16.embed(x)
    assert b.dtype == np.float32
    np.testing.assert_allclose(b, a, rtol=0.05, atol=0.05)
    cos = (a * b).sum(1) / (
        np.linalg.norm(a, axis=1) * np.linalg.norm(b, axis=1)
    )
    assert cos.min() > 0.995
    assert b16.stats()["dtype"] == "bf16"
    for leaf in jax.tree.leaves(b16._variables["params"]):
        if jnp.issubdtype(leaf.dtype, jnp.floating):
            assert leaf.dtype == jnp.bfloat16
    for leaf in jax.tree.leaves(b16._variables["batch_stats"]):
        assert leaf.dtype == jnp.float32  # models/norm.py fp32-stats contract
    # byte-identical images served under different dtypes never share a
    # cache row
    assert b16._key_prefix != engine._key_prefix
    with pytest.raises(ValueError, match="dtype"):
        EmbeddingEngine.random_init(model_name="resnet10", size=SIZE,
                                    dtype="fp16")


def test_bucket_sharding_policy(engine):
    """Buckets divisible by the data axis shard across it; the rest run
    replicated (latency path) instead of erroring on indivisibility."""
    from jax.sharding import PartitionSpec as P

    from simclr_pytorch_distributed_tpu.parallel.mesh import (
        DATA_AXIS,
        batch_sharding_if_divisible,
    )

    mesh = engine.mesh
    data = mesh.shape[DATA_AXIS]  # 8 on the virtual test mesh
    assert batch_sharding_if_divisible(mesh, data * 2, 4).spec == P(
        DATA_AXIS, None, None, None
    )
    assert batch_sharding_if_divisible(mesh, 1, 4).spec == P()


def test_normalized_output_is_unit_norm():
    eng = EmbeddingEngine.random_init(
        model_name="resnet10", size=SIZE, buckets=(2,), normalize=True
    )
    out = eng.embed(images_of(np.random.default_rng(5), 2))
    np.testing.assert_allclose(np.linalg.norm(out, axis=1), 1.0, rtol=1e-5)


def test_cache_hits_skip_engine_execution():
    eng = EmbeddingEngine.random_init(
        model_name="resnet10", size=SIZE, buckets=(2,),
        cache=EmbeddingCache(capacity=64),
    )
    rng = np.random.default_rng(6)
    x = images_of(rng, 2)
    first = eng.embed(x)
    dispatches = sum(eng.stats()["bucket_dispatches"].values())
    second = eng.embed(x)  # all rows cached: the device is never touched
    assert sum(eng.stats()["bucket_dispatches"].values()) == dispatches
    assert eng.stats()["cache_hit_rows"] == 2
    np.testing.assert_array_equal(first, second)
    # partial hit: one old + one new image -> exactly one more dispatch,
    # and the cached row is identical to the originally computed one
    y = np.stack([x[0], images_of(rng, 1)[0]])
    mixed = eng.embed(y)
    assert sum(eng.stats()["bucket_dispatches"].values()) == dispatches + 1
    np.testing.assert_array_equal(mixed[0], first[0])
    assert eng.stats()["cache"]["hits"] == 3


def test_shared_cache_never_crosses_engines():
    """One EmbeddingCache behind two engines (same arch, different weights):
    the weights fingerprint in the key must keep their rows apart — engine B
    must never serve engine A's embeddings."""
    shared = EmbeddingCache(capacity=64)
    a = EmbeddingEngine.random_init(
        model_name="resnet10", size=SIZE, seed=0, buckets=(2,), cache=shared
    )
    b = EmbeddingEngine.random_init(
        model_name="resnet10", size=SIZE, seed=1, buckets=(2,), cache=shared
    )
    x = images_of(np.random.default_rng(7), 2)
    out_a = a.embed(x)
    out_b = b.embed(x)  # must MISS despite byte-identical images
    assert shared.stats()["hits"] == 0
    assert not np.allclose(out_a, out_b)  # different weights, different rows
    np.testing.assert_array_equal(b.embed(x), out_b)  # b hits its OWN rows
    assert shared.stats()["hits"] == 2


def test_infer_architecture_from_variables():
    import jax
    import jax.numpy as jnp

    from simclr_pytorch_distributed_tpu.models import SupConResNet

    # eval_shape: architecture inference needs only the tree, never values
    for name, head, feat_dim in (
        ("resnet18", "mlp", 128),
        ("resnet50", "mlp", 64),
        ("resnet10", "linear", 128),
    ):
        model = SupConResNet(model_name=name, head=head, feat_dim=feat_dim)
        v = jax.eval_shape(
            lambda m=model: m.init(
                jax.random.key(0), jnp.zeros((1, 8, 8, 3)), train=False
            )
        )
        assert infer_architecture_from_variables(v) == (name, head, feat_dim)
    with pytest.raises(ValueError, match="encoder"):
        infer_architecture_from_variables({"params": {"whatever": {}}})


def test_from_checkpoint_infers_architecture(tmp_path):
    """An orbax model payload round-trips into a serving engine with no
    --model flag: the architecture is read off the restored tree."""
    import jax
    import jax.numpy as jnp

    from simclr_pytorch_distributed_tpu.models import SupConResNet
    from simclr_pytorch_distributed_tpu.utils.checkpoint import (
        MODEL_LAYOUT_VERSION,
        _save_tree,
        _write_meta,
    )

    model = SupConResNet(model_name="resnet10")
    v = model.init(jax.random.key(0), jnp.zeros((2, SIZE, SIZE, 3)), train=False)
    ckpt = tmp_path / "ckpt_epoch_1"
    _save_tree(
        str(ckpt / "model"),
        {"params": v["params"], "batch_stats": v["batch_stats"]},
    )
    _write_meta(str(ckpt), {
        "epoch": 1, "model_layout": MODEL_LAYOUT_VERSION,
        "config": {"dataset": "cifar100"},
    })
    eng = EmbeddingEngine.from_checkpoint(str(ckpt), buckets=(2,))
    assert eng.model.model_name == "resnet10"
    assert eng.feat_dim == 512
    # dataset stats were taken from the checkpoint's config
    from simclr_pytorch_distributed_tpu.ops.augment import DATASET_STATS

    assert eng._aug_cfg.mean == DATASET_STATS["cifar100"][0]
    # ...but an explicit caller override is never clobbered, even when only
    # one of mean/std is supplied
    eng2 = EmbeddingEngine.from_checkpoint(
        str(ckpt), buckets=(2,), std=(1.0, 1.0, 1.0)
    )
    assert eng2._aug_cfg.std == (1.0, 1.0, 1.0)
