"""CIFAR download fallback (data/cifar.py download_cifar) against a local
HTTP server — the torchvision-download parity path (reference
``main_supcon.py:181-188``) tested with zero egress.
"""

import functools
import hashlib
import io
import os
import pickle
import tarfile
import threading
from http.server import HTTPServer, SimpleHTTPRequestHandler

import numpy as np
import pytest

from simclr_pytorch_distributed_tpu.data.cifar import (
    CIFAR_ARCHIVES,
    download_cifar,
    load_dataset,
    maybe_download,
)


def _tiny_archive(dataset, n=4):
    """A structurally real CIFAR tar.gz, tiny (returns (bytes, md5))."""
    rng = np.random.default_rng(0)
    if dataset == "cifar10":
        members = [
            (f"cifar-10-batches-py/{name}", "labels", 10)
            for name in [f"data_batch_{i}" for i in range(1, 6)] + ["test_batch"]
        ]
    else:
        members = [(f"cifar-100-python/{s}", "fine_labels", 100)
                   for s in ("train", "test")]
    buf = io.BytesIO()
    with tarfile.open(fileobj=buf, mode="w:gz") as tar:
        for member, label_key, n_cls in members:
            payload = pickle.dumps({
                "data": rng.integers(0, 256, (n, 3072), dtype=np.uint8),
                label_key: rng.integers(0, n_cls, n).tolist(),
            })
            info = tarfile.TarInfo(member)
            info.size = len(payload)
            tar.addfile(info, io.BytesIO(payload))
    data = buf.getvalue()
    return data, hashlib.md5(data).hexdigest()


def _serve_archive(tmp_path, dataset):
    """Start an HTTP server hosting a tiny archive; returns (url, md5, stop)."""
    site = tmp_path / "site"
    site.mkdir()
    data, md5 = _tiny_archive(dataset)
    (site / CIFAR_ARCHIVES[dataset][0]).write_bytes(data)
    handler = functools.partial(SimpleHTTPRequestHandler, directory=str(site))
    server = HTTPServer(("127.0.0.1", 0), handler)
    thread = threading.Thread(target=server.serve_forever, daemon=True)
    thread.start()

    def stop():
        server.shutdown()
        thread.join()

    return f"http://127.0.0.1:{server.server_port}", md5, stop


@pytest.fixture
def http_site(tmp_path):
    url, md5, stop = _serve_archive(tmp_path, "cifar10")
    try:
        yield url, md5
    finally:
        stop()


def test_download_extract_load_end_to_end(http_site, tmp_path):
    base_url, md5 = http_site
    dest = tmp_path / "data"
    marker = download_cifar("cifar10", str(dest), base_url=base_url, md5=md5)
    assert os.path.isdir(marker)
    # the fetched tree is directly consumable by the normal load path
    train, test, n_cls = load_dataset("cifar10", str(dest))
    assert n_cls == 10
    assert train["images"].shape == (20, 32, 32, 3)
    assert test["images"].shape == (4, 32, 32, 3)
    assert train["images"].dtype == np.uint8


def test_download_md5_mismatch_rejected(http_site, tmp_path):
    base_url, _ = http_site
    dest = tmp_path / "data"
    with pytest.raises(ValueError, match="md5 mismatch"):
        download_cifar("cifar10", str(dest), base_url=base_url, md5="0" * 32)
    fname = CIFAR_ARCHIVES["cifar10"][0]
    # neither a committed archive nor a torn .partial survives
    assert not os.path.exists(dest / fname)
    assert not os.path.exists(dest / (fname + ".partial"))


def test_download_idempotent_without_network(http_site, tmp_path):
    base_url, md5 = http_site
    dest = tmp_path / "data"
    download_cifar("cifar10", str(dest), base_url=base_url, md5=md5)
    # marker dir present -> second call never touches the network
    marker = download_cifar(
        "cifar10", str(dest), base_url="http://127.0.0.1:1", md5=md5
    )
    assert os.path.isdir(marker)


def test_ensure_dataset_available_lock_flow(http_site, tmp_path, monkeypatch):
    """The driver entry point: flock-serialized download (one downloader per
    filesystem, the multi-host-safe gate) + barrier. The lock FILE persists
    by design (unlinking it would reintroduce the unlink/recreate race) but
    must hold no active flock afterwards."""
    import fcntl

    from simclr_pytorch_distributed_tpu.data import cifar as cifar_lib

    base_url, md5 = http_site
    fname, _, marker = cifar_lib.CIFAR_ARCHIVES["cifar10"]
    monkeypatch.setattr(cifar_lib, "CIFAR_BASE_URL", base_url)
    monkeypatch.setitem(
        cifar_lib.CIFAR_ARCHIVES, "cifar10", (fname, md5, marker)
    )
    dest = tmp_path / "data"
    cifar_lib.ensure_dataset_available("cifar10", str(dest))
    assert (dest / marker).is_dir()
    lock = dest / ".cifar10.download.lock"
    assert lock.exists()  # kept on purpose; contents identify the downloader
    fd = os.open(lock, os.O_RDWR)
    try:
        # must not block: the downloader released its flock
        fcntl.flock(fd, fcntl.LOCK_EX | fcntl.LOCK_NB)
        fcntl.flock(fd, fcntl.LOCK_UN)
    finally:
        os.close(fd)
    # non-cifar datasets and download=False are no-ops
    cifar_lib.ensure_dataset_available("synthetic", str(dest))
    cifar_lib.ensure_dataset_available("cifar10", str(dest), download=False)


def test_ensure_dataset_available_dead_holder_lock(
    http_site, tmp_path, monkeypatch
):
    """A lock file left behind by a hard-killed downloader (SIGKILL/OOM) must
    not block at all: the kernel released the dead process's flock with it,
    so a new process acquires immediately — no staleness window to sleep out
    and no lock-breaking races (the round-5 redesign's point)."""
    import time

    from simclr_pytorch_distributed_tpu.data import cifar as cifar_lib

    base_url, md5 = http_site
    fname, _, marker = cifar_lib.CIFAR_ARCHIVES["cifar10"]
    monkeypatch.setattr(cifar_lib, "CIFAR_BASE_URL", base_url)
    monkeypatch.setitem(
        cifar_lib.CIFAR_ARCHIVES, "cifar10", (fname, md5, marker)
    )
    dest = tmp_path / "data"
    dest.mkdir()
    lock = dest / ".cifar10.download.lock"
    lock.write_text("99999 0\n")  # leftover file from a dead pid, no flock

    t0 = time.time()
    cifar_lib.ensure_dataset_available("cifar10", str(dest))
    assert time.time() - t0 < 60  # no staleness window
    assert (dest / marker).is_dir()


def test_ensure_dataset_available_concurrent_callers(
    http_site, tmp_path, monkeypatch
):
    """Three concurrent callers (flock is per-open-file-description, so
    threads serialize exactly like processes do): exactly one downloads,
    the rest block on the flock and then see the completed marker — and the
    extracted tree is fully readable afterwards (no half-extracted state
    can escape the lock)."""
    import threading

    from simclr_pytorch_distributed_tpu.data import cifar as cifar_lib

    base_url, md5 = http_site
    fname, _, marker = cifar_lib.CIFAR_ARCHIVES["cifar10"]
    monkeypatch.setattr(cifar_lib, "CIFAR_BASE_URL", base_url)
    monkeypatch.setitem(
        cifar_lib.CIFAR_ARCHIVES, "cifar10", (fname, md5, marker)
    )
    dest = tmp_path / "data"
    errs = []

    def call():
        try:
            cifar_lib.ensure_dataset_available("cifar10", str(dest))
        except Exception as e:  # noqa: BLE001 — surfaced via assert below
            errs.append(e)

    threads = [threading.Thread(target=call) for _ in range(3)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert not errs, errs
    train, test_split, n_cls = load_dataset("cifar10", str(dest))
    assert n_cls == 10 and train["images"].shape[0] == 20


class _FlakyHandler(SimpleHTTPRequestHandler):
    """Fails the first N requests with a 503, then serves normally —
    the transient-HTTP-failure shape the retry loop is for."""

    failures_left = 0

    def do_GET(self):
        cls = type(self)
        if cls.failures_left > 0:
            cls.failures_left -= 1
            self.send_error(503, "transient")
            return
        super().do_GET()

    def log_message(self, *a):  # keep pytest output clean
        pass


def _serve_flaky(tmp_path, failures):
    site = tmp_path / "flaky_site"
    site.mkdir()
    data, md5 = _tiny_archive("cifar10")
    (site / CIFAR_ARCHIVES["cifar10"][0]).write_bytes(data)
    handler = functools.partial(_FlakyHandler, directory=str(site))
    _FlakyHandler.failures_left = failures
    server = HTTPServer(("127.0.0.1", 0), handler)
    thread = threading.Thread(target=server.serve_forever, daemon=True)
    thread.start()

    def stop():
        server.shutdown()
        thread.join()

    return f"http://127.0.0.1:{server.server_port}", md5, stop


def test_download_retries_transient_failures(tmp_path):
    """Two 503s then success: the backoff loop (3 attempts) absorbs the
    transient failure instead of aborting the multi-host launch that holds
    the download flock."""
    url, md5, stop = _serve_flaky(tmp_path, failures=2)
    try:
        marker = download_cifar(
            "cifar10", str(tmp_path / "data"), base_url=url, md5=md5,
            backoff_base=0.01,
        )
        assert os.path.isdir(marker)
        assert _FlakyHandler.failures_left == 0  # all three attempts fired
    finally:
        stop()


def test_download_gives_up_after_attempts(tmp_path):
    """A persistent failure still aborts — after exactly `attempts` tries —
    and leaves no torn partial file behind."""
    from urllib.error import HTTPError

    url, md5, stop = _serve_flaky(tmp_path, failures=99)
    try:
        with pytest.raises(HTTPError):
            download_cifar(
                "cifar10", str(tmp_path / "data"), base_url=url, md5=md5,
                backoff_base=0.01,
            )
    finally:
        stop()
    assert _FlakyHandler.failures_left == 99 - 3  # 3 attempts, no more
    fname = CIFAR_ARCHIVES["cifar10"][0]
    leftovers = [p for p in (tmp_path / "data").iterdir() if fname in p.name]
    assert not leftovers  # neither the archive nor a .partial survives


def test_download_md5_mismatch_retries_then_fails(tmp_path, caplog):
    """An md5 mismatch is treated as a truncated transfer: retried (fresh
    temp each attempt), and only after the retry budget does it raise."""
    import logging

    url, _, stop = _serve_flaky(tmp_path, failures=0)
    try:
        with caplog.at_level(logging.WARNING):
            with pytest.raises(ValueError, match="md5 mismatch"):
                download_cifar(
                    "cifar10", str(tmp_path / "data"), base_url=url,
                    md5="0" * 32, backoff_base=0.01,
                )
    finally:
        stop()
    retries = [r for r in caplog.records if "retrying" in r.message]
    assert len(retries) == 2  # attempts 1 and 2 warned; attempt 3 raised


def test_download_cifar100_archive_shape(tmp_path):
    """The cifar100 archive constants (name, marker dir, pickle layout) drive
    the same fetch->extract->load path northstar --dataset cifar100 uses."""
    url, md5, stop = _serve_archive(tmp_path, "cifar100")
    try:
        dest = tmp_path / "data"
        marker = download_cifar("cifar100", str(dest), base_url=url, md5=md5)
        assert os.path.isdir(marker)
        train, test, n_cls = load_dataset("cifar100", str(dest))
        assert n_cls == 100
        assert train["images"].shape == (4, 32, 32, 3)
        assert test["labels"].shape == (4,)
    finally:
        stop()


def test_maybe_download_swallows_network_failure(tmp_path, caplog):
    """No egress must degrade to a warning (load_dataset's pre-placed-
    binaries error stays the user-facing failure)."""
    import logging

    from simclr_pytorch_distributed_tpu.data import cifar as cifar_lib

    orig = cifar_lib.CIFAR_BASE_URL
    cifar_lib.CIFAR_BASE_URL = "http://127.0.0.1:1"  # connection refused
    try:
        with caplog.at_level(logging.WARNING):
            maybe_download("cifar10", str(tmp_path))
    finally:
        cifar_lib.CIFAR_BASE_URL = orig
    assert any("could not download" in r.message for r in caplog.records)
    with pytest.raises(FileNotFoundError):
        load_dataset("cifar10", str(tmp_path))
