"""Unit tests for serve/batcher.py — coalescing policy, backpressure,
timeouts, draining.

No jax here: the engine is a fake per-row map (row i of the result identifies
image i), which makes "each request got exactly ITS rows back" checkable
after any batching the worker chose to do. Deadline logic runs on an
injected fake clock — no test sleeps longer than the worker's poll
granularity (a few ms).
"""

import threading
import time

import numpy as np
import pytest

from simclr_pytorch_distributed_tpu.serve.batcher import (
    DynamicBatcher,
    QueueFull,
    RequestTimeout,
)

pytestmark = pytest.mark.serve

H = W = 2  # tiny "images"; the fake engine only hashes rows


def fake_embed(images):
    """Per-row map: embedding = [sum of the image's pixels]."""
    images = np.asarray(images)
    return images.reshape(len(images), -1).sum(axis=1, keepdims=True).astype(np.float32)


def imgs(*values):
    """One image per value, every pixel = value -> row sum identifies it."""
    out = np.zeros((len(values), H, W, 3), np.uint8)
    for i, v in enumerate(values):
        out[i] = v
    return out


class FakeClock:
    def __init__(self):
        self.t = 1000.0

    def __call__(self):
        return self.t

    def advance(self, dt):
        self.t += dt


# ------------------------------------------------------------ policy (no thread)


def test_coalesces_pending_requests_into_one_batch():
    b = DynamicBatcher(fake_embed, max_batch=8, max_wait_ms=50, start=False)
    futs = [b.submit(imgs(1, 2)), b.submit(imgs(3)), b.submit(imgs(4, 5, 6))]
    batch = b._next_batch()
    assert [r.n for r in batch] == [2, 1, 3]  # all coalesced, FIFO order
    b._dispatch(batch)
    np.testing.assert_array_equal(
        futs[0].result(0), fake_embed(imgs(1, 2))
    )
    np.testing.assert_array_equal(futs[1].result(0), fake_embed(imgs(3)))
    np.testing.assert_array_equal(futs[2].result(0), fake_embed(imgs(4, 5, 6)))
    s = b.stats()
    assert s["batches"] == 1 and s["batched_images"] == 6


def test_max_batch_splits_but_never_splits_a_request():
    b = DynamicBatcher(fake_embed, max_batch=8, max_wait_ms=50, start=False)
    b.submit(imgs(*range(3)))
    b.submit(imgs(*range(3)))
    b.submit(imgs(*range(3)))  # 3+3+3 > 8: third rides the next batch
    first = b._next_batch()
    assert [r.n for r in first] == [3, 3]
    second = b._next_batch()
    assert [r.n for r in second] == [3]


def test_oversize_request_dispatches_alone():
    b = DynamicBatcher(fake_embed, max_batch=4, max_wait_ms=50, start=False)
    fut = b.submit(imgs(*range(10)))  # bigger than max_batch: engine chunks it
    b.submit(imgs(1))
    batch = b._next_batch()
    assert [r.n for r in batch] == [10]
    b._dispatch(batch)
    assert fut.result(0).shape == (10, 1)


def test_backpressure_rejects_with_queue_full():
    b = DynamicBatcher(fake_embed, max_batch=8, max_queue=3, start=False)
    for _ in range(3):
        b.submit(imgs(1))
    with pytest.raises(QueueFull):
        b.submit(imgs(2))
    assert b.stats()["rejected"] == 1
    assert b.stats()["queue_depth"] == 3  # the queue did NOT grow


def test_backpressure_bounds_queued_rows_not_just_requests():
    """Request count alone doesn't bound memory: a few large-batch requests
    must trip QueueFull via the row cap."""
    b = DynamicBatcher(fake_embed, max_batch=8, max_queue=100,
                       max_queue_images=10, start=False)
    b.submit(imgs(*range(6)))
    with pytest.raises(QueueFull, match="row cap"):
        b.submit(imgs(*range(5)))  # 6 + 5 > 10
    b.submit(imgs(*range(4)))  # 6 + 4 == 10: still admitted
    assert b.stats()["queued_images"] == 10
    # dispatching frees the budget: the 6-row request goes alone (6+4 would
    # exceed max_batch=8), leaving the 4-row one queued
    b._dispatch(b._next_batch())
    assert b.stats()["queued_images"] == 4
    b.submit(imgs(*range(6)))  # 4 + 6 == 10: fits again


def test_validate_hook_rejects_at_submit():
    """A request gate (the engine's geometry check) fails bad submits
    synchronously — the worker and its batch-mates never see them."""
    def gate(images):
        if images.shape[1] != H:
            raise ValueError("wrong geometry")
        return images

    b = DynamicBatcher(fake_embed, validate=gate, start=False)
    b.submit(imgs(1))
    with pytest.raises(ValueError, match="wrong geometry"):
        b.submit(np.zeros((1, H + 1, W, 3), np.uint8))
    assert b.stats()["submitted"] == 1  # the bad request was never queued


def test_expired_request_fails_with_timeout_on_fake_clock():
    clock = FakeClock()
    # max_wait_ms=0: the coalescing window closes instantly — on a fake
    # clock a nonzero window would never elapse without another advance()
    b = DynamicBatcher(fake_embed, max_batch=8, max_wait_ms=0, clock=clock,
                       start=False)
    stale = b.submit(imgs(1), timeout_ms=1000)
    live = b.submit(imgs(2))  # no timeout
    clock.advance(2.0)  # stale's deadline passes without any real sleep
    batch = b._next_batch()
    assert [r.n for r in batch] == [1] and batch[0].future is live
    with pytest.raises(RequestTimeout):
        stale.result(0)
    assert b.stats()["timeouts"] == 1


def test_expired_request_mid_queue_does_not_drop_its_neighbor():
    """Regression: discarding an expired request during coalescing must not
    swallow the live request behind it (the discard helper used to pop AND
    return the neighbor, which the call site threw away — its future then
    hung forever)."""
    clock = FakeClock()
    b = DynamicBatcher(fake_embed, max_batch=8, max_wait_ms=0, clock=clock,
                       start=False)
    a = b.submit(imgs(1))                      # live head
    stale = b.submit(imgs(2), timeout_ms=500)  # expires mid-queue
    c = b.submit(imgs(3))                      # live tail — must NOT be lost
    clock.advance(1.0)
    batch = b._next_batch()
    assert [r.future for r in batch] == [a, c]
    b._dispatch(batch)
    np.testing.assert_array_equal(a.result(0), fake_embed(imgs(1)))
    np.testing.assert_array_equal(c.result(0), fake_embed(imgs(3)))
    with pytest.raises(RequestTimeout):
        stale.result(0)


def test_mixed_shapes_split_into_separate_batches():
    """One odd-shaped request must not poison its batch-mates: requests whose
    image geometry differs from the batch head's are deferred to lead their
    own batch, and ALL of them succeed."""
    b = DynamicBatcher(fake_embed, max_batch=8, max_wait_ms=0, start=False)
    small = b.submit(imgs(1))
    big = b.submit(np.full((1, 4, 4, 3), 7, np.uint8))  # different H/W
    first = b._next_batch()
    assert [r.future for r in first] == [small]
    second = b._next_batch()
    assert [r.future for r in second] == [big]
    b._dispatch(first)
    b._dispatch(second)
    np.testing.assert_array_equal(small.result(0), fake_embed(imgs(1)))
    np.testing.assert_array_equal(
        big.result(0), fake_embed(np.full((1, 4, 4, 3), 7, np.uint8))
    )


# ------------------------------------------------------- worker thread (live)


def test_live_roundtrip_and_close_drains():
    b = DynamicBatcher(fake_embed, max_batch=8, max_wait_ms=5)
    futs = [b.submit(imgs(i)) for i in range(6)]
    b.close()  # drains everything queued before returning
    for i, fut in enumerate(futs):
        np.testing.assert_array_equal(fut.result(0), fake_embed(imgs(i)))
    with pytest.raises(RuntimeError, match="closed"):
        b.submit(imgs(0))
    s = b.stats()
    assert s["batched_images"] == 6 and s["batches"] <= 6


def test_close_without_drain_fails_pending():
    b = DynamicBatcher(fake_embed, max_batch=8, start=False)
    fut = b.submit(imgs(1))
    b.close(drain=False)
    with pytest.raises(RuntimeError, match="closed"):
        fut.result(0)


def test_close_with_no_worker_fails_pending_even_when_draining():
    """Regression: drain=True with start=False has nobody to drain — the
    queued future must fail instead of hanging its waiter forever."""
    b = DynamicBatcher(fake_embed, max_batch=8, start=False)
    fut = b.submit(imgs(1))
    b.close(drain=True)
    with pytest.raises(RuntimeError, match="closed"):
        fut.result(0)


def test_fake_clock_controls_the_coalescing_window():
    """With max_wait_ms=10s on a fake clock, a lone request dispatches only
    after the CLOCK passes the window — in a few real milliseconds."""
    clock = FakeClock()
    b = DynamicBatcher(fake_embed, max_batch=8, max_wait_ms=10_000,
                       clock=clock, poll_interval=0.001)
    try:
        fut = b.submit(imgs(3))
        time.sleep(0.03)  # worker is inside the window, holding the request
        assert not fut.done()
        clock.advance(11.0)  # close the window; no real 10 s elapses
        np.testing.assert_array_equal(
            fut.result(timeout=2), fake_embed(imgs(3))
        )
    finally:
        b.close()


def test_engine_error_propagates_to_every_waiter():
    def broken(images):
        raise ValueError("engine exploded")

    b = DynamicBatcher(broken, max_batch=8, max_wait_ms=5)
    try:
        futs = [b.submit(imgs(1)), b.submit(imgs(2))]
        for fut in futs:
            with pytest.raises(ValueError, match="engine exploded"):
                fut.result(timeout=2)
        assert b.stats()["errors"] >= 1
    finally:
        b.close()


def test_concurrent_submitters_all_get_their_rows():
    b = DynamicBatcher(fake_embed, max_batch=16, max_wait_ms=5)
    results = {}
    lock = threading.Lock()

    def client(i):
        out = b.submit(imgs(i, i)).result(timeout=5)
        with lock:
            results[i] = out

    threads = [threading.Thread(target=client, args=(i,)) for i in range(8)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    b.close()
    for i in range(8):
        np.testing.assert_array_equal(results[i], fake_embed(imgs(i, i)))


def test_pipeline_stats_present_in_no_worker_path():
    """The synchronous (start=False) path reports the pipeline gauges too —
    depth stays 0 but dispatched/completed counters move together."""
    b = DynamicBatcher(fake_embed, max_batch=8, start=False)
    s = b.stats()
    assert s["inflight_batches"] == 0 and s["inflight_rows"] == 0
    assert s["dispatched_batches"] == 0 and s["max_inflight_observed"] == 0
    b.submit(imgs(1))
    b._dispatch(b._next_batch())
    s = b.stats()
    assert s["dispatched_batches"] == 1 and s["batches"] == 1
    assert s["max_inflight"] == 2  # config echo (the default window)
    b.close()


def test_submit_validation():
    b = DynamicBatcher(fake_embed, start=False)
    with pytest.raises(ValueError):
        b.submit(np.zeros((4, 4, 3), np.uint8))  # missing batch dim
    with pytest.raises(ValueError):
        b.submit(np.zeros((0, 4, 4, 3), np.uint8))  # empty
