"""Observability layer tests: flight recorder, stall watchdog, Prometheus
exposition, and the tier-1 recorder-overhead proof.

Everything timing-shaped runs on fake clocks (the watchdog's ``check()`` is
the testable core — the background thread only calls it on a cadence), and
the "zero added device transfers" claim is MECHANICAL: a real driver epoch
runs with the recorder on while the metric ring's ``device_get`` and the
device store's ``index_put`` count every transfer — the counts must equal
the PR-4/PR-5 proven contract (one ring D2H per window, one index upload
per epoch) exactly, recorder or no recorder.
"""

import json
import logging
import os
import urllib.request

import numpy as np
import pytest

from simclr_pytorch_distributed_tpu.utils import prom, tracing

pytestmark = pytest.mark.obs

SIZE = 8


class FakeClock:
    def __init__(self, t=0.0):
        self.t = t

    def __call__(self):
        return self.t

    def advance(self, dt):
        self.t += dt


# ------------------------------------------------------------- recorder


def test_recorder_jsonl_roundtrip_and_snapshot(tmp_path):
    clk = FakeClock(100.0)
    path = str(tmp_path / "events.jsonl")
    rec = tracing.FlightRecorder(path, clock=clk)
    with rec.span("phase_a", track="main:flush", step=3):
        clk.advance(0.5)
    clk.advance(0.25)
    rec.event("nan_rollback", track="main:guard", epoch=2)
    rec.close()

    lines = [json.loads(x) for x in open(path).read().splitlines()]
    assert [e["name"] for e in lines] == ["phase_a", "nan_rollback"]
    span = lines[0]
    assert span["ph"] == "X" and span["track"] == "main:flush"
    assert span["ts"] == pytest.approx(0.0) and span["dur"] == pytest.approx(0.5)
    assert span["args"] == {"step": 3}
    ev = lines[1]
    assert ev["ph"] == "i" and ev["ts"] == pytest.approx(0.75)
    # snapshot is the same records (the watchdog dump source)
    snap = rec.snapshot()
    assert [e["name"] for e in snap] == ["phase_a", "nan_rollback"]
    assert rec.snapshot(last=1)[0]["name"] == "nan_rollback"


def test_recorder_record_span_explicit_clock_domain():
    clk = FakeClock(10.0)
    rec = tracing.FlightRecorder(clock=clk)
    start = rec.now()
    clk.advance(2.0)
    rec.record_span("request", "serve:request", start, rec.now(), n=4)
    (span,) = rec.snapshot()
    assert span["ts"] == pytest.approx(0.0) and span["dur"] == pytest.approx(2.0)


def test_chrome_trace_export_schema(tmp_path):
    """Schema pin: Chrome trace-event JSON with integer microsecond
    ts/dur, thread_name metadata per track, and monotone non-overlapping
    spans within each main:* track."""
    clk = FakeClock()
    trace_path = str(tmp_path / "trace.json")
    rec = tracing.FlightRecorder(clock=clk, trace_path=trace_path)
    for _ in range(3):  # sequential spans on one track
        with rec.span("flush_boundary", track="main:flush"):
            clk.advance(0.01)
        clk.advance(0.05)
    with rec.span("first_step", track="main:compile"):
        clk.advance(1.0)
    rec.event("cache_hits", track="serve:cache", rows=2)
    rec.close()

    trace = json.load(open(trace_path))
    assert set(trace) == {"traceEvents", "displayTimeUnit"}
    events = trace["traceEvents"]
    metas = [e for e in events if e["ph"] == "M"]
    assert {m["args"]["name"] for m in metas} == {
        "main:flush", "main:compile", "serve:cache"
    }
    by_track_tid = {m["args"]["name"]: m["tid"] for m in metas}
    spans = [e for e in events if e["ph"] == "X"]
    instants = [e for e in events if e["ph"] == "i"]
    assert len(spans) == 4 and len(instants) == 1
    for e in spans:
        assert isinstance(e["ts"], int) and isinstance(e["dur"], int)
        assert e["ts"] >= 0 and e["dur"] >= 0 and e["pid"] == 0
    # per-main-track monotone non-overlap (the attribution invariant)
    flush = sorted(
        (e for e in spans if e["tid"] == by_track_tid["main:flush"]),
        key=lambda e: e["ts"],
    )
    assert len(flush) == 3
    for a, b in zip(flush, flush[1:]):
        assert b["ts"] >= a["ts"] + a["dur"]


def test_recorder_ring_bound_drops_oldest_keeps_jsonl(tmp_path):
    path = str(tmp_path / "events.jsonl")
    rec = tracing.FlightRecorder(path, clock=FakeClock(), max_events=4)
    for i in range(10):
        rec.event(f"e{i}")
    assert [e["name"] for e in rec.snapshot()] == ["e6", "e7", "e8", "e9"]
    assert rec.dropped == 6
    rec.close()
    lines = [json.loads(x) for x in open(path).read().splitlines()]
    # disk keeps all 10, plus the close-time saturation marker so a
    # post-mortem (trace_report flags it) knows the ring views truncated
    assert len(lines) == 11
    assert lines[-1]["name"] == "recorder_dropped"
    assert lines[-1]["args"]["records"] == 6


def test_recorder_close_without_drops_stays_silent(tmp_path):
    path = str(tmp_path / "events.jsonl")
    rec = tracing.FlightRecorder(path, clock=FakeClock())
    rec.event("only")
    rec.close()
    names = [json.loads(x)["name"] for x in open(path).read().splitlines()]
    assert names == ["only"]


# ------------------------------------------- shared torn-tolerant loader


def test_parse_jsonl_tolerates_torn_tail_and_corrupt_lines(tmp_path):
    """The satellite round-trip: the one shared loader (trace_report,
    health_report, the supervisor's watcher, the perf ledger) must survive
    the half-written final line a SIGKILL leaves behind AND a corrupt
    middle line, consuming only complete lines."""
    good = [{"name": "a", "ts": 1.0}, {"name": "b", "ts": 2.0}]
    text = (
        json.dumps(good[0]) + "\n"
        + "{not json}\n"          # complete but corrupt: skipped
        + json.dumps(good[1]) + "\n"
        + '{"name": "torn", "ts'  # no newline: the SIGKILL tail
    )
    records, consumed = tracing.parse_jsonl(text)
    assert records == good
    assert consumed == len(text) - len('{"name": "torn", "ts')
    path = tmp_path / "events.jsonl"
    path.write_text(text)
    assert tracing.load_events_jsonl(str(path)) == good
    # incremental-tail contract: appending the rest of the torn line makes
    # it parse from the recorded offset (the RunDirWatcher pattern)
    with open(path, "a") as f:
        f.write('": 3.0}\n')
    tail, _ = tracing.parse_jsonl(path.read_text()[consumed:])
    assert tail == [{"name": "torn", "ts": 3.0}]


def test_session_files_for_orders_rotations(tmp_path):
    for name in ("events.jsonl", "events_r2.jsonl", "events_r4.jsonl",
                 "events_p1.jsonl", "events_p1_r2.jsonl"):
        (tmp_path / name).write_text("")
    files = tracing.session_files_for(str(tmp_path / "events.jsonl"))
    # stops at the first missing rotation (r3): r4 is another process's
    # numbering error, not a later session of this run
    assert [os.path.basename(p) for p in files] == [
        "events.jsonl", "events_r2.jsonl"
    ]
    files = tracing.session_files_for(str(tmp_path / "events_p1.jsonl"))
    assert [os.path.basename(p) for p in files] == [
        "events_p1.jsonl", "events_p1_r2.jsonl"
    ]
    # unknown names degrade to themselves
    other = str(tmp_path / "whatever.jsonl")
    assert tracing.session_files_for(other) == [other]


def test_discover_fleet_sessions_groups_processes_and_sessions(tmp_path):
    for name in ("events.jsonl", "events_p1.jsonl", "events_r2.jsonl",
                 "events_p1_r2.jsonl", "trace.json", "stall_dump_1.txt"):
        (tmp_path / name).write_text("")
    sessions = tracing.discover_fleet_sessions(str(tmp_path))
    assert list(sessions) == ["r1", "r2"]
    assert {p: os.path.basename(f) for p, f in sessions["r1"].items()} == {
        0: "events.jsonl", 1: "events_p1.jsonl"
    }
    assert {p: os.path.basename(f) for p, f in sessions["r2"].items()} == {
        0: "events_r2.jsonl", 1: "events_p1_r2.jsonl"
    }


def test_module_level_helpers_noop_without_install(tmp_path):
    tracing.uninstall()
    with tracing.span("x", track="main:flush"):
        pass
    tracing.event("y")
    tracing.record_span("z", "t", 0.0, 1.0)  # all silently dropped
    rec = tracing.FlightRecorder(clock=FakeClock())
    tracing.install(rec)
    try:
        with tracing.span("x", track="main:flush"):
            pass
        tracing.event("y")
    finally:
        tracing.uninstall()
    assert [e["name"] for e in rec.snapshot()] == ["x", "y"]


# ------------------------------------------------------------- watchdog


def test_watchdog_fires_on_stuck_boundary_and_dumps_artifacts(tmp_path):
    clk = FakeClock()
    rec = tracing.FlightRecorder(clock=clk)
    rec.event("last_good_boundary", track="main:flush", step=40)
    wd = tracing.StallWatchdog(
        10.0, str(tmp_path), clock=clk, recorder=rec, start=False,
        name="train",
    )
    wd.beat()
    clk.advance(5.0)
    assert not wd.check()  # within deadline: silent
    clk.advance(6.0)
    assert wd.check()  # 11s > 10s: fires
    txt = tmp_path / "stall_dump_1.txt"
    js = tmp_path / "stall_dump_1.json"
    assert txt.exists() and js.exists()
    body = txt.read_text()
    # faulthandler wrote real stacks: this very test frame is in them
    assert "STALL" in body and "test_tracing" in body
    dump = json.loads(js.read_text())
    assert dump["age_s"] == pytest.approx(11.0)
    assert any(e["name"] == "last_good_boundary" for e in dump["events"])
    # one dump per stall: no re-fire until a beat re-arms
    clk.advance(100.0)
    assert not wd.check()
    wd.beat()
    clk.advance(11.0)
    assert wd.check()
    assert (tmp_path / "stall_dump_2.txt").exists()


def test_watchdog_silent_on_healthy_run(tmp_path):
    clk = FakeClock()
    wd = tracing.StallWatchdog(10.0, str(tmp_path), clock=clk, start=False)
    for _ in range(20):
        clk.advance(5.0)
        wd.beat()
        assert not wd.check()
    assert list(tmp_path.iterdir()) == []


def test_watchdog_disarm_suppresses_then_arm_restores(tmp_path):
    clk = FakeClock()
    wd = tracing.StallWatchdog(10.0, str(tmp_path), clock=clk, start=False)
    wd.disarm()
    clk.advance(100.0)
    assert not wd.check()  # disarmed silence is expected (idle serve)
    wd.arm()
    assert not wd.check()  # arm() beats: full deadline from here
    clk.advance(11.0)
    assert wd.check()


def test_watchdog_rejects_nonpositive_deadline(tmp_path):
    with pytest.raises(ValueError):
        tracing.StallWatchdog(0.0, str(tmp_path), start=False)


# ------------------------------------------------- logging dedup satellite


def test_setup_logging_dedups_file_handlers(tmp_path):
    """Regression (satellite): repeated setup_logging calls against the
    same work_dir must not stack duplicate ``log-ing`` FileHandlers — each
    stacked handler wrote every line once more (resume loops, tests)."""
    from simclr_pytorch_distributed_tpu.utils.logging_utils import setup_logging

    root = logging.getLogger()
    before = list(root.handlers)
    try:
        for _ in range(3):
            setup_logging(str(tmp_path), is_main=True)
        target = os.path.abspath(os.path.join(str(tmp_path), "log-ing"))
        mine = [
            h for h in root.handlers
            if isinstance(h, logging.FileHandler)
            and getattr(h, "baseFilename", None) == target
        ]
        assert len(mine) == 1
        logging.getLogger().info("exactly-once-line")
        mine[0].flush()
        text = open(target).read()
        assert text.count("exactly-once-line") == 1
    finally:
        for h in list(root.handlers):
            if h not in before:
                root.removeHandler(h)
                h.close()


# ------------------------------------------------------------------ prom


def test_render_prometheus_format_and_escaping():
    text = prom.render_prometheus([
        ("train_step", None, 42),
        ("lat_bucket", {"bucket": "8", "le": "+Inf"}, 3),
        ("weird", {"l": 'a"b\nc'}, 1.5),
    ])
    lines = text.splitlines()
    assert lines[0] == "train_step 42"
    assert lines[1] == 'lat_bucket{bucket="8",le="+Inf"} 3'
    assert "\\n" in lines[2] and '\\"' in lines[2]
    assert text.endswith("\n")


def test_latency_histogram_quantiles_and_samples():
    h = prom.LatencyHistogram(bounds_ms=(1, 10, 100, 1000))
    for ms in (5, 5, 5, 5, 5, 5, 5, 5, 5, 50):  # 9 fast + 1 slow
        h.observe(8, ms)
    s = h.summary()["8"]
    assert s["count"] == 10
    assert 1 < s["p50_ms"] <= 10
    assert 10 < s["p95_ms"] <= 100  # the slow one pulls the tail bucket
    assert s["p50_ms"] <= s["p95_ms"] <= s["p99_ms"]
    # overflow clamps to the top bound instead of inventing a number
    h.observe("big", 99999)
    assert h.quantile("big", 0.5) == 1000
    samples = h.samples("req_ms")
    names = {n for n, _, _ in samples}
    assert names == {"req_ms_bucket", "req_ms_sum", "req_ms_count"}
    inf_8 = [v for n, lab, v in samples
             if n == "req_ms_bucket" and lab == {"bucket": "8", "le": "+Inf"}]
    assert inf_8 == [10]
    # cumulative within one key: counts never decrease along the bounds
    buckets_8 = [v for n, lab, v in samples
                 if n == "req_ms_bucket" and lab.get("bucket") == "8"]
    assert buckets_8 == sorted(buckets_8)


def test_latency_histogram_rejects_bad_bounds():
    with pytest.raises(ValueError):
        prom.LatencyHistogram(bounds_ms=(10, 5))


def test_trainer_gauges_liveness_age():
    clk = FakeClock()
    g = prom.TrainerGauges(clock=clk)
    assert g.collect()["last_boundary_age_seconds"] == -1.0  # no beat yet
    g.beat(120)
    g.set(epoch=3, inflight_windows=1)
    clk.advance(7.5)
    g.register("checkpoint_pending_saves", lambda: 2)
    out = g.collect()
    assert out["step"] == 120 and out["epoch"] == 3
    assert out["last_boundary_age_seconds"] == pytest.approx(7.5)
    assert out["checkpoint_pending_saves"] == 2
    g.register("broken", lambda: 1 / 0)
    assert g.collect()["broken"] == -1.0  # a scrape never raises
    assert "train_step 120" in g.prometheus_text()


def test_trainer_gauges_supervisor_surface():
    """The supervisor-facing gauges (docs/RESILIENCE.md): start_time_seconds
    is stamped from the injectable WALL clock at construction (uptime
    without /proc), and exit_code is a terminal gauge — absent until the
    driver's exit path stamps it (RunObservability.close), then exposed so
    the last scrape classifies the exit."""
    g = prom.TrainerGauges(clock=FakeClock(), wall_clock=lambda: 1722.25)
    out = g.collect()
    assert out["start_time_seconds"] == 1722.25
    assert "exit_code" not in out  # terminal: absent while running
    g.set_exit_code(75)
    assert g.collect()["exit_code"] == 75.0
    text = g.prometheus_text()
    assert "train_start_time_seconds 1722.25" in text
    assert "train_exit_code 75" in text


def test_metrics_sidecar_http_endpoint():
    g = prom.TrainerGauges(clock=FakeClock())
    g.beat(7)
    server = prom.start_metrics_server(0, g.prometheus_text, host="127.0.0.1")
    try:
        host, port = server.server_address[:2]
        with urllib.request.urlopen(
            f"http://{host}:{port}/metrics", timeout=10
        ) as r:
            assert r.status == 200
            assert "text/plain" in r.headers["Content-Type"]
            body = r.read().decode()
        assert "train_step 7" in body
        assert "train_last_boundary_age_seconds" in body
        with urllib.request.urlopen(
            f"http://{host}:{port}/healthz", timeout=10
        ) as r:
            assert json.loads(r.read()) == {"status": "ok"}
    finally:
        server.shutdown()
        server.server_close()


# ------------------------------------ the recorder-overhead proof (tier-1)


def test_recorder_adds_no_device_transfers_in_driver_hot_loop(
    tmp_path, monkeypatch
):
    """The acceptance-criteria proof, mechanical: one REAL supcon epoch
    under device placement with the flight recorder ON, every ring D2H
    counted through the MetricRing's injectable ``device_get`` and every
    index upload through the DeviceStore's ``index_put``. The counts must
    equal the PR-4/PR-5 contract exactly — 3 ring transfers (windows
    2+2+1 of a 5-step epoch at print_freq 2) and 1 index upload (one
    epoch) — so the recorder added ZERO device transfers between flush
    boundaries, while events.jsonl proves it was live the whole time."""
    import jax as _jax

    from simclr_pytorch_distributed_tpu import config as config_lib
    from simclr_pytorch_distributed_tpu.data import cifar as cifar_lib
    from simclr_pytorch_distributed_tpu.data import device_store
    from simclr_pytorch_distributed_tpu.parallel import mesh as mesh_lib
    from simclr_pytorch_distributed_tpu.train import supcon as supcon_driver
    from simclr_pytorch_distributed_tpu.utils.telemetry import TelemetrySession

    orig_synth = cifar_lib.synthetic_dataset
    monkeypatch.setattr(
        cifar_lib, "synthetic_dataset",
        lambda n=2048, num_classes=10, seed=0, size=32: orig_synth(
            n=200, num_classes=num_classes, seed=seed, size=SIZE
        ),
    )
    monkeypatch.setattr(
        supcon_driver, "create_mesh",
        lambda devices=None, **kw: mesh_lib.create_mesh(
            devices=_jax.devices()[:1] if devices is None else devices, **kw
        ),
    )

    counts = {"ring": 0, "index": 0}

    class CountingSession(TelemetrySession):
        def __init__(self, window, keys, mode="async", **kw):
            def counting_get(x):
                counts["ring"] += 1
                return _jax.device_get(x)

            super().__init__(
                window, keys, mode, device_get=counting_get, **kw
            )

    real_store = device_store.DeviceStore

    class CountingStore(real_store):
        def __init__(self, loader, mesh, **kw):
            super().__init__(loader, mesh, **kw)
            inner = self._index_put

            def counting_put(idx):
                counts["index"] += 1
                return inner(idx)

            self._index_put = counting_put

    monkeypatch.setattr(supcon_driver, "TelemetrySession", CountingSession)
    monkeypatch.setattr(device_store, "DeviceStore", CountingStore)

    cfg = config_lib.SupConConfig(
        model="resnet10", dataset="synthetic", batch_size=32, epochs=1,
        learning_rate=0.05, cosine=True, save_freq=5, print_freq=2,
        size=SIZE, workdir=str(tmp_path), seed=0, method="SimCLR",
        telemetry="sync", data_placement="device", flight_recorder="on",
    )
    cfg = config_lib.finalize_supcon(cfg)
    supcon_driver.run(cfg)

    # the mechanical bound: exactly the pre-recorder transfer contract
    assert counts == {"ring": 3, "index": 1}

    # ...and the recorder really was on through the whole loop
    events_path = os.path.join(cfg.save_folder, "events.jsonl")
    events = [json.loads(x) for x in open(events_path).read().splitlines()]
    boundaries = [e for e in events if e["name"] == "flush_boundary"]
    # 3 real windows (2+2+1) + the epoch-tail boundary finish_epoch submits
    # with ZERO pending steps — a span records (the recorder saw it) but no
    # transfer happened (the ring count above stayed 3)
    assert len([b for b in boundaries if b["args"]["steps"] > 0]) == 3
    assert all(b["args"]["steps"] == 0 for b in boundaries[3:])
    assert any(e["name"] == "first_step" for e in events)
    assert any(e["name"] == "epoch_gather" for e in events)
    assert any(e["name"] == "epoch" for e in events)
    assert any(e["name"] == "checkpoint_save" for e in events)
    assert os.path.exists(os.path.join(cfg.save_folder, "trace.json"))

    # ...and the FLEET instrumentation (clock anchors at the placement
    # agreement + every flush-boundary failure observation) was live for
    # the whole run while the transfer count above stayed at the PR-4/PR-5
    # contract: the anchors are host-only stamps, zero device cost
    anchors = [e for e in events if e["name"] == tracing.ANCHOR_EVENT]
    kinds = [a["args"]["kind"] for a in anchors]
    assert kinds[0] == "placement" and kinds.count("placement") == 1
    assert kinds.count("flush_boundary") >= len(boundaries)
    assert [a["args"]["anchor"] for a in anchors] == list(
        range(1, len(anchors) + 1)
    )


def test_sidecar_exposes_recorder_dropped_records(tmp_path):
    """Satellite: FlightRecorder.dropped (ring evictions — truncated
    trace.json/watchdog snapshots) must be an operator-visible gauge on
    the /metrics sidecar, wired by RunObservability."""
    import types
    import urllib.request as _url

    from simclr_pytorch_distributed_tpu.utils.obs import RunObservability

    cfg = types.SimpleNamespace(
        save_folder=str(tmp_path), flight_recorder="on", watchdog_secs=0,
        metrics_port=0, metrics_host="127.0.0.1",
    )
    # port 0 means "no sidecar" to the config surface; give a real
    # ephemeral-port server by patching after construction is overkill —
    # bind one directly through the same wiring with a truthy port
    server = None
    try:
        cfg.metrics_port = _free_port()
        obs = RunObservability(cfg, name="test")
        server = obs.sidecar
        assert obs.recorder is not None and obs.gauges is not None
        # the gauge is lazy (scrape-time read of recorder.dropped), so a
        # simulated saturation is visible without filling the real ring
        obs.recorder.dropped = 5
        host, port = server.server_address[:2]
        with _url.urlopen(f"http://{host}:{port}/metrics", timeout=10) as r:
            body = r.read().decode()
        assert "train_recorder_dropped_records 5" in body
    finally:
        if server is not None:
            obs.close()


def _free_port():
    import socket

    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def test_obs_closes_on_placement_rejection(tmp_path):
    """Review fix: the obs stack now builds BEFORE make_store, so the
    placement rejection (a designed startup raise) must still close it —
    recorder exported, terminal run_exit stamped — on exactly the
    startup-failure run whose post-mortem the stack exists to capture."""
    from simclr_pytorch_distributed_tpu import config as config_lib
    from simclr_pytorch_distributed_tpu.train import supcon as supcon_driver

    cfg = config_lib.SupConConfig(
        model="resnet10", dataset="synthetic", batch_size=32, epochs=1,
        learning_rate=0.05, workdir=str(tmp_path), seed=0, method="SimCLR",
        data_placement="device", device_budget_mb=1,  # 6.3MB set: rejected
        flight_recorder="on",
    )
    cfg = config_lib.finalize_supcon(cfg)
    with pytest.raises(ValueError, match="device"):
        supcon_driver.run(cfg)
    events_path = os.path.join(cfg.save_folder, "events.jsonl")
    events = [json.loads(x) for x in open(events_path).read().splitlines()]
    (exit_ev,) = [e for e in events if e["name"] == "run_exit"]
    assert exit_ev["args"]["code"] == 1  # plain-crash code for ValueError
    assert os.path.exists(os.path.join(cfg.save_folder, "trace.json"))
    # the stack is closed: the module-level recorder is uninstalled
    assert tracing.current() is None


def test_obs_staged_resets_watchdog_deadline(tmp_path):
    """Review fix: the obs stack now builds BEFORE make_store (so the
    placement collective runs under the armed watchdog), which put the
    store's one-time dataset upload inside the first watchdog window —
    staged() beats after staging so that time no longer counts against
    --watchdog_secs (a spurious staging dump reads as a stall to the
    supervisor)."""
    import types

    from simclr_pytorch_distributed_tpu.utils.obs import RunObservability

    cfg = types.SimpleNamespace(
        save_folder=str(tmp_path), flight_recorder="off", watchdog_secs=30,
        metrics_port=0, metrics_host="127.0.0.1",
    )
    obs = RunObservability(cfg, name="t")
    try:
        wd = obs.watchdog
        wd.close()  # drive check() on a fake clock, not the poll thread
        clk = FakeClock()
        wd._clock = clk
        wd._last = clk()
        clk.advance(wd.deadline_s + 1)  # "staging took longer than the deadline"
        obs.staged()
        assert not wd.check()  # staging time no longer counts
        clk.advance(wd.deadline_s + 1)
        assert wd.check()  # a real post-staging stall still fires
    finally:
        obs.close()


def test_run_paths_rotate_per_session(tmp_path):
    """A resumed run (exit-75 relaunch into the SAME save_folder) must not
    append a second ts~0 timeline into the first session's events.jsonl —
    each session gets a fresh _rK file, one self-consistent timeline per
    file (trace_report consumes them independently)."""
    e1, t1 = tracing.run_paths(str(tmp_path))
    assert os.path.basename(e1) == "events.jsonl"
    open(e1, "w").write("{}\n")
    e2, t2 = tracing.run_paths(str(tmp_path))
    assert os.path.basename(e2) == "events_r2.jsonl"
    assert os.path.basename(t2) == "trace_r2.json"
    open(e2, "w").write("{}\n")
    e3, _ = tracing.run_paths(str(tmp_path))
    assert os.path.basename(e3) == "events_r3.jsonl"
    # pod processes rotate independently under their own _pN prefix
    ep, tp = tracing.run_paths(str(tmp_path), process_index=1)
    assert os.path.basename(ep) == "events_p1.jsonl"
    assert os.path.basename(tp) == "trace_p1.json"
