"""KNOWN-BAD (with bad_metric_keys_copy.py): the second definition of the
same registry name — the multi-source half of the fixture pair."""

FIXTURE_DUP_METRIC_KEYS = ("loss", "top1")
