"""KNOWN-BAD: a sync-forcing host op inside a flush-boundary hot loop.

The zero-sync contract (PR 4/5): between flush boundaries the main thread
only dispatches. ``float()`` on the step's device output is the
reference's per-iter ``loss.item()`` sync point reborn — one blocking D2H
per step. The annotated line below is a DESIGNED sync site (reason
recorded) and must NOT fire; the bare-marker line must fire the
missing-reason rule.
"""

import time


def epoch(update_fn, state, ring_buf, batches, key, telemetry, consume,
          print_freq):
    for idx, (images, labels) in enumerate(batches):
        state, ring_buf = update_fn(state, ring_buf, images, labels, key)
        loss = float(state.last_loss)  # BUG: per-step blocking readback
        # designed site, reason recorded — suppressed by the annotation:
        t = float(time.time() - state.t0)  # sync-ok: host wall-clock only, no device value involved
        # marker without a reason — itself a finding:
        u = bool(state.flag)  # sync-ok
        if (idx + 1) % print_freq == 0:
            telemetry.flush_boundary(ring_buf, consume, step_hint=idx)
    return loss, t, u
