"""KNOWN-BAD: a process-dependent early exit ahead of a collective.

The lone-host-leaves-the-loop hazard: non-main processes return before
the collective drain, the main process blocks in it forever (the hazard
drain_global/check_failures_global document as 'a lone host raising out
of a plain drain would skip the collective save its peers enter')."""


def finish(telemetry, is_main_process, step):
    if not is_main_process():
        return
    telemetry.drain_global(step)
