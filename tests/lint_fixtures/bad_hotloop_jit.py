"""KNOWN-BAD: a host materialization inside a jitted step function.

``np.asarray`` on a traced value either crashes at trace time or silently
constant-folds a stale value into the compiled program — both belong at
review time, not on the chip.
"""

import jax
import numpy as np


def make_step(tx):
    def step(state, batch):
        grads = grad_fn(state, batch)
        gnorm = np.asarray(grads)  # BUG: host op under trace
        return apply_updates(tx, state, grads), gnorm

    return jax.jit(step, donate_argnums=(0,))
