"""Known-bad: a sync-forcing host op inside a Pallas kernel builder.

Minimal reconstruction of the hazard the pallas-kernel region guards: a
``np.asarray`` on a kernel ref would either fail the TPU lowering or
silently constant-fold in interpret mode while the compiled path
diverges. The kernel reaches ``pallas_call`` through the repo's real
shape — an intermediate ``functools.partial`` assignment.
"""

import functools

import numpy as np
from jax.experimental import pallas as pl


def _bad_kernel(x_ref, o_ref):
    peek = np.asarray(x_ref[0])  # BAD: host materialization inside a kernel
    o_ref[:] = x_ref[:] * peek[0]


def _clean_kernel(x_ref, o_ref):
    o_ref[:] = x_ref[:] * 2.0


def build(x, out_shape):
    kernel = functools.partial(_bad_kernel)
    bad = pl.pallas_call(kernel, out_shape=out_shape)(x)
    clean = pl.pallas_call(_clean_kernel, out_shape=out_shape)(x)
    return bad, clean
