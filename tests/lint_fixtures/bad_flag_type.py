"""KNOWN-BAD: one shared flag, two argparse types. The trainers parse the
same CLI surface; an int/float drift silently changes values on one stage
only (the class the hand-synced copies invited)."""

import argparse


def a_parser():
    p = argparse.ArgumentParser()
    p.add_argument("--print_freq", type=int, default=10)
    return p


def b_parser():
    p = argparse.ArgumentParser()
    p.add_argument("--print_freq", type=float, default=10)
    return p
