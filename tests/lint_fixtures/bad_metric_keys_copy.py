"""KNOWN-BAD (with bad_metric_keys_dup.py): the same registry name
literally re-defined in a second module — readers must IMPORT the one
source, or the writer/reader column derivations drift."""

FIXTURE_DUP_METRIC_KEYS = ("loss", "top1")
