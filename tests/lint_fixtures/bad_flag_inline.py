"""KNOWN-BAD: a shared RUNTIME flag hand-registered inline in two parsers
instead of through the shared registry helper (the pre-refactor config.py
shape the flag-consistency rule exists to forbid)."""

import argparse


def a_parser():
    p = argparse.ArgumentParser()
    p.add_argument("--telemetry", type=str, default="async")
    return p


def b_parser():
    p = argparse.ArgumentParser()
    p.add_argument("--telemetry", type=str, default="async")
    return p
