"""KNOWN-BAD: a collective under a process-dependent conditional.

The split-verdict deadlock shape: only process 0 enters the allgather-
backed checkpoint save, every other process dispatches the next step —
the pod wedges inside the collective. (The class the device_store
placement review fix closed: PR 5 "the 'auto' verdict is COLLECTIVE".)
"""


def save_if_main(state, save_folder, config, epoch, is_main_process,
                 save_checkpoint):
    if is_main_process():
        # orbax multi-process saves are collective: every process must call
        save_checkpoint(save_folder, "ckpt", state, config=config,
                        epoch=epoch)
