"""KNOWN-BAD: a hardcoded artifact schema in a dict literal while the
module pins schemas in constants. The writer and the ratchet gate must
reference ONE definition (the scripts/perf_ledger.py CHECK_SCHEMA fix)."""

SCHEMA = "fixture_artifact/v1"


def build_output(records):
    return {
        "schema": "fixture_artifact/v1",  # BUG: bypasses the SCHEMA pin
        "records": records,
    }
