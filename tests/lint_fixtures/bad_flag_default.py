"""KNOWN-BAD: a shared runtime flag registered through ONE helper but
resolving to different dataclass defaults per trainer config — the shared
surface must behave identically on every stage."""

import argparse
import dataclasses


@dataclasses.dataclass
class AConfig:
    telemetry: str = "async"


@dataclasses.dataclass
class BConfig:
    telemetry: str = "sync"


def _add_shared(p, d):
    p.add_argument("--telemetry", type=str, default=d.telemetry)


def a_parser():
    d = AConfig()
    p = argparse.ArgumentParser()
    _add_shared(p, d)
    return p


def b_parser():
    d = BConfig()
    p = argparse.ArgumentParser()
    _add_shared(p, d)
    return p
