"""KNOWN-BAD: a ring-column registry tuple that is neither sorted nor
unique. The ring column order is sorted(keys) on writer AND reader
(train/supcon_step.metric_keys), so the declaration must read in column
order and a duplicate would silently collapse two columns into one."""

FIXTURE_METRIC_KEYS = ("top1", "loss", "top1")
