# Known-bad fixture corpus for the invariant linter (tests/test_invariant_lint.py).
# Each module is a MINIMAL reconstruction of one real bug class from this
# repo's history; the tests assert each rule fires on its fixture exactly
# once. Never imported — the linter parses, it does not execute.
