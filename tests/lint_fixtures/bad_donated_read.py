"""KNOWN-BAD: a read of a donated binding after the donating call.

The PR-1 reconstruction: the crash handler saved the SAME ``state`` object
the jitted update had already donated — on the real chip its buffers were
deleted on dispatch, and the resume segfaulted within 2 steps (the second
PR-1 variant persisted a torn state mid-background-write). ``update_fn``
is the drivers' donating step callable (donate_argnums=(0,)).
"""


def step_then_crash_save(update_fn, state, ring_buf, images, labels, key,
                         save_folder, config):
    new_state, ring_buf = update_fn(state, ring_buf, images, labels, key)
    # BUG: `state` was donated above — its device buffers are gone
    snapshot = {"params": state.params, "config": config,
                "folder": save_folder}
    return new_state, ring_buf, snapshot
