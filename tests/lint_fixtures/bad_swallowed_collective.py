"""KNOWN-BAD: a collective inside an exception-swallowing try.

Exception delivery is per-host (a local TB IOError, a local orbax fault):
the host that swallows keeps its loop running while the host that raised
left it — their collective schedules diverge at the next boundary. The
repo's real recovery points route failures through the COLLECTIVE
failure-code exchange instead (utils/telemetry.py check_failures_global).
"""

import logging


def boundary(telemetry, ring_buf, consume, step):
    try:
        telemetry.flush_boundary(ring_buf, consume, step_hint=step)
    except OSError:
        logging.warning("flush failed; continuing")  # local swallow
