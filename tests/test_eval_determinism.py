"""Eval-mode determinism — the invariant the serving buckets depend on.

``SupConResNet.encode(train=False)`` must be (a) bit-stable across calls of
the same compiled program and (b) per-example independent: row i's output
cannot depend on rows != i (BN reads running statistics, every other op is
per-row), so the engine's pad rows are invisible **bitwise** within one
program. Across DIFFERENT compiled programs (another batch size/sharding)
XLA may reorder reductions, so the guarantee honestly weakens to float
tolerance — both halves pinned here at the model level
(tests/test_serve_engine.py pins them at the engine level).
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from simclr_pytorch_distributed_tpu.models import SupConResNet

pytestmark = pytest.mark.serve

SIZE = 8


@pytest.fixture(scope="module")
def model_and_vars():
    model = SupConResNet(model_name="resnet10")
    variables = model.init(
        jax.random.key(0), jnp.zeros((2, SIZE, SIZE, 3)), train=False
    )
    encode = jax.jit(
        lambda v, x: model.apply(v, x, train=False, method=SupConResNet.encode)
    )
    return model, variables, encode


def _images(rng, n):
    return rng.standard_normal((n, SIZE, SIZE, 3)).astype(np.float32)


def test_repeat_calls_bit_identical(model_and_vars):
    _, v, encode = model_and_vars
    x = jnp.asarray(_images(np.random.default_rng(0), 4))
    a = np.asarray(encode(v, x))
    b = np.asarray(encode(v, x))
    np.testing.assert_array_equal(a, b)


def test_rows_independent_of_pad_content(model_and_vars):
    """Same compiled program (batch 8): 5 real rows + zero pad vs the SAME 5
    rows + large garbage pad — the real rows are bit-identical. This is what
    makes padded-bucket serving exact."""
    _, v, encode = model_and_vars
    rng = np.random.default_rng(1)
    x5 = _images(rng, 5)
    zeros = np.zeros((3, SIZE, SIZE, 3), np.float32)
    garbage = _images(rng, 3) * 100.0
    a = np.asarray(encode(v, jnp.asarray(np.concatenate([x5, zeros]))))[:5]
    b = np.asarray(encode(v, jnp.asarray(np.concatenate([x5, garbage]))))[:5]
    np.testing.assert_array_equal(a, b)


def test_across_batch_sizes_float_tight(model_and_vars):
    """A batch of 5 on its own program vs the same 5 padded to 32 on another:
    per-row agreement to float tolerance (bitwise is only guaranteed within
    ONE compiled program — measured ~1 ulp drift across programs on CPU)."""
    _, v, encode = model_and_vars
    rng = np.random.default_rng(2)
    x5 = _images(rng, 5)
    x32 = np.concatenate([x5, np.zeros((27, SIZE, SIZE, 3), np.float32)])
    a = np.asarray(encode(v, jnp.asarray(x5)))
    b = np.asarray(encode(v, jnp.asarray(x32)))[:5]
    np.testing.assert_allclose(a, b, rtol=1e-5, atol=1e-5)


def test_eval_mode_does_not_touch_batch_stats(model_and_vars):
    """train=False must not mutate running statistics — the frozen-encoder
    contract serving (and the probe) rely on."""
    model, v, _ = model_and_vars
    x = jnp.asarray(_images(np.random.default_rng(3), 4) + 3.0)
    _, mutated = model.apply(
        v, x, train=False, method=SupConResNet.encode, mutable=["batch_stats"]
    )
    for old, new in zip(
        jax.tree.leaves(v["batch_stats"]), jax.tree.leaves(mutated["batch_stats"])
    ):
        np.testing.assert_array_equal(np.asarray(old), np.asarray(new))
