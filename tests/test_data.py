"""Data pipeline tests: synthetic datasets, CIFAR binary decoding, epoch loader
sharding/shuffle/drop_last semantics."""

import os
import pickle

import numpy as np
import pytest

from simclr_pytorch_distributed_tpu.data.cifar import (
    load_cifar10,
    load_dataset,
    synthetic_dataset,
)
from simclr_pytorch_distributed_tpu.data.pipeline import EpochLoader


def test_synthetic_dataset_structure():
    train, test = synthetic_dataset(n=256, num_classes=10, seed=0)
    assert train["images"].dtype == np.uint8
    assert train["images"].shape[1:] == (32, 32, 3)
    assert train["labels"].min() >= 0 and train["labels"].max() < 10
    assert len(test["images"]) == 32
    # class conditionality: per-class image means differ
    m0 = train["images"][train["labels"] == 0].mean()
    m1 = train["images"][train["labels"] == 1].mean()
    assert abs(m0 - m1) > 1.0


def test_load_cifar10_binary_format(tmp_path):
    """Write the canonical pickle layout and read it back."""
    root = tmp_path / "cifar-10-batches-py"
    os.makedirs(root)
    rng = np.random.default_rng(0)
    for i in range(1, 6):
        data = rng.integers(0, 256, size=(20, 3072), dtype=np.uint8)
        with open(root / f"data_batch_{i}", "wb") as f:
            pickle.dump({"data": data, "labels": list(rng.integers(0, 10, 20))}, f)
    with open(root / "test_batch", "wb") as f:
        pickle.dump(
            {"data": rng.integers(0, 256, size=(10, 3072), dtype=np.uint8),
             "labels": list(rng.integers(0, 10, 10))}, f)

    train, test = load_cifar10(str(tmp_path))
    assert train["images"].shape == (100, 32, 32, 3)
    assert test["images"].shape == (10, 32, 32, 3)

    # channel-major decode: row = [R plane, G plane, B plane]
    row = np.arange(3072, dtype=np.uint8)
    with open(root / "data_batch_1", "wb") as f:
        pickle.dump({"data": row[None], "labels": [0]}, f)
    for i in range(2, 6):
        with open(root / f"data_batch_{i}", "wb") as f:
            pickle.dump({"data": row[None] * 0, "labels": [0]}, f)
    train, _ = load_cifar10(str(tmp_path))
    img = train["images"][0]
    assert img[0, 0, 0] == 0        # R plane starts at 0
    assert img[0, 1, 0] == 1
    assert img[0, 0, 1] == 1024 % 256  # G plane offset 1024


def test_load_dataset_fallback(tmp_path):
    with pytest.raises(FileNotFoundError):
        load_dataset("cifar10", str(tmp_path))
    train, test, n_cls = load_dataset(
        "cifar10", str(tmp_path), allow_synthetic_fallback=True
    )
    assert n_cls == 10 and len(train["images"]) > 0


def test_epoch_loader_drop_last_and_shuffle():
    images = np.arange(25)[:, None].astype(np.uint8)
    labels = np.arange(25).astype(np.int32)
    loader = EpochLoader(images, labels, global_batch_size=8, base_seed=7)
    assert len(loader) == 3  # drop_last: 25 // 8

    seen1 = np.concatenate([lab for _, lab in loader.epoch(1)])
    seen1b = np.concatenate([lab for _, lab in loader.epoch(1)])
    seen2 = np.concatenate([lab for _, lab in loader.epoch(2)])
    assert len(seen1) == 24
    np.testing.assert_array_equal(seen1, seen1b)  # same epoch -> same order
    assert not np.array_equal(seen1, seen2)  # set_epoch reshuffles


def test_epoch_loader_process_sharding():
    """Process slices partition every global batch, matching batch//nproc."""
    images = np.arange(64)[:, None].astype(np.uint8)
    labels = np.arange(64).astype(np.int32)
    shards = []
    for p in range(4):
        loader = EpochLoader(
            images, labels, global_batch_size=16,
            process_index=p, process_count=4, base_seed=3,
        )
        shards.append([lab for _, lab in loader.epoch(5)])
    for step in range(4):
        merged = np.concatenate([shards[p][step] for p in range(4)])
        assert len(merged) == 16
        assert len(np.unique(merged)) == 16  # disjoint slices
        assert all(len(shards[p][step]) == 4 for p in range(4))


def test_epoch_loader_validation_mode():
    images = np.arange(10)[:, None].astype(np.uint8)
    labels = np.arange(10).astype(np.int32)
    loader = EpochLoader(
        images, labels, global_batch_size=4, shuffle=False, drop_last=False
    )
    batches = list(loader.epoch(0))
    assert len(batches) == 3
    np.testing.assert_array_equal(batches[0][1], [0, 1, 2, 3])
    np.testing.assert_array_equal(batches[2][1], [8, 9])  # ragged tail kept


def test_epoch_loader_prefetch_worker_exception_propagates():
    """A raise inside the prefetch thread must surface on the consumer,
    not strand it in q.get() forever (round-2 judge repro: poisoned
    ``_gather`` left training hanging with no traceback)."""
    images = np.arange(32)[:, None].astype(np.uint8)
    labels = np.arange(32).astype(np.int32)
    loader = EpochLoader(images, labels, global_batch_size=8, prefetch=2)

    class Poison(RuntimeError):
        pass

    def poisoned_batches(epoch, start_step=0):
        yield images[:8], labels[:8]
        raise Poison("bad index / memmap I/O error")

    loader._batches = poisoned_batches
    it = loader.epoch(0)
    next(it)  # first batch arrives fine
    with pytest.raises(Poison):
        # bounded: the exception is enqueued, so this returns immediately
        next(it)


def test_epoch_loader_abandoned_iterator_stops_prefetch_worker():
    """A consumer that walks away mid-epoch (preemption, an exception
    between batches) must not strand the prefetch worker blocked in
    ``q.put()`` forever: closing the generator (which is what GC does too)
    stops and joins the worker thread."""
    import threading
    import time

    def worker_threads():
        return [
            t for t in threading.enumerate()
            if t.name == "EpochLoader-prefetch" and t.is_alive()
        ]

    images = np.arange(64)[:, None].astype(np.uint8)
    labels = np.arange(64).astype(np.int32)
    # prefetch=1: after the consumer takes one batch the worker is
    # guaranteed to be BLOCKED in q.put() on the next one
    loader = EpochLoader(images, labels, global_batch_size=8, prefetch=1)
    assert not worker_threads()
    it = loader.epoch(0)
    next(it)
    deadline = time.time() + 5
    while not worker_threads() and time.time() < deadline:
        time.sleep(0.01)  # let the worker reach the blocking put
    assert worker_threads()
    it.close()  # abandon mid-epoch
    assert not worker_threads(), "prefetch worker leaked after abandon"

    # the exhausted path still terminates cleanly too
    assert len(list(loader.epoch(0))) == 8
    assert not worker_threads()


def test_check_start_step_rejects_out_of_range_resume_offsets():
    """An oversized resume offset (a checkpoint whose step_in_epoch no
    longer fits this run's geometry, e.g. a changed batch size) must raise
    loudly — the drivers call this BEFORE their step loop, because both
    loop shapes iterate range(start_step, steps_per_epoch) and an empty
    range would otherwise 'complete' a zero-step epoch silently."""
    images = np.arange(64)[:, None].astype(np.uint8)
    labels = np.arange(64).astype(np.int32)
    loader = EpochLoader(images, labels, global_batch_size=8)  # 8 steps
    loader.check_start_step(0)
    loader.check_start_step(7)
    for bad in (-1, 8, 100):
        with pytest.raises(ValueError, match="outside"):
            loader.check_start_step(bad)
    # epoch() still validates for direct consumers
    with pytest.raises(ValueError, match="outside"):
        next(loader.epoch(0, start_step=8))


def test_synthetic_texture_dataset_contract():
    """Deterministic, disjoint split, labels in range, uint8 HWC — and class
    signal is NOT in the color channel means (ColorJitter robustness: unlike
    `synthetic_dataset`'s color-mean classes, per-class mean colors coincide)."""
    import numpy as np

    from simclr_pytorch_distributed_tpu.data.cifar import (
        synthetic_texture_dataset,
    )

    tr1, te1 = synthetic_texture_dataset(n=512, num_classes=10, seed=3)
    tr2, te2 = synthetic_texture_dataset(n=512, num_classes=10, seed=3)
    np.testing.assert_array_equal(tr1["images"], tr2["images"])
    np.testing.assert_array_equal(te1["labels"], te2["labels"])
    assert tr1["images"].dtype == np.uint8
    assert tr1["images"].shape[1:] == (32, 32, 3)
    assert len(tr1["labels"]) + len(te1["labels"]) == 512
    assert 0 <= tr1["labels"].min() and tr1["labels"].max() <= 9

    # per-class mean color is ~identical across classes (no color shortcut):
    # spread of class means is far below the within-class pixel std
    means = np.stack([
        tr1["images"][tr1["labels"] == c].mean(axis=(0, 1, 2))
        for c in range(10)
    ])
    assert means.std(axis=0).max() < 0.1 * tr1["images"].std()


def test_epoch_loader_start_step_resumes_permutation():
    """Mid-epoch resume contract (utils/preempt.py): epoch(e, start_step=k)
    yields EXACTLY the suffix of the uninterrupted epoch(e) stream — same
    batches, same order — for both the prefetch-thread and inline paths."""
    rng = np.random.default_rng(0)
    images = rng.integers(0, 255, (64, 4, 4, 3), dtype=np.uint8)
    labels = np.arange(64, dtype=np.int32)
    for prefetch in (0, 2):
        loader = EpochLoader(images, labels, 16, base_seed=5, prefetch=prefetch)
        full = list(loader.epoch(3))
        resumed = list(loader.epoch(3, start_step=2))
        assert len(full) == 4 and len(resumed) == 2
        for (fi, fl), (ri, rl) in zip(full[2:], resumed):
            np.testing.assert_array_equal(fi, ri)
            np.testing.assert_array_equal(fl, rl)

    loader = EpochLoader(images, labels, 16, base_seed=5)
    with pytest.raises(ValueError, match="start_step"):
        next(loader.epoch(3, start_step=4))  # a whole epoch is not an offset
    with pytest.raises(ValueError, match="start_step"):
        next(loader.epoch(3, start_step=-1))


def test_global_batch_composition_is_mesh_shape_independent():
    """The elastic-resume shuffle contract (docs/RESILIENCE.md): the global
    permutation is a pure function of (base_seed, epoch) — NOT of the
    process/device topology — and per-process slices are contiguous blocks
    of it. So a run killed at (epoch e, step k) under one topology and
    resumed at start_step=k under another consumes EXACTLY the remaining
    global batches, bit-identically. This is what makes the supervisor's
    restart-resized decision legal."""
    images = np.arange(96)[:, None].astype(np.uint8)
    labels = np.arange(96).astype(np.int32)

    def global_batches(process_count, epoch, start_step=0):
        merged = None
        for p in range(process_count):
            loader = EpochLoader(
                images, labels, global_batch_size=32, base_seed=11,
                process_index=p, process_count=process_count, prefetch=0,
            )
            rows = [lab for _, lab in loader.epoch(epoch, start_step=start_step)]
            merged = rows if merged is None else [
                np.concatenate([m, r]) for m, r in zip(merged, rows)
            ]
        return merged

    ref = global_batches(1, epoch=4)
    for pc in (2, 4):
        got = global_batches(pc, epoch=4)
        assert len(got) == len(ref)
        for a, b in zip(ref, got):
            np.testing.assert_array_equal(a, b)  # bit-identical composition
    # the mid-epoch resume coordinate is topology-independent too: the
    # tail consumed from start_step=2 matches the uninterrupted run's tail
    for pc in (1, 4):
        tail = global_batches(pc, epoch=4, start_step=2)
        for a, b in zip(ref[2:], tail):
            np.testing.assert_array_equal(a, b)
    # ...and the permutation depends only on (base_seed, epoch): another
    # epoch reshuffles, the same epoch never does
    np.testing.assert_array_equal(
        np.concatenate(ref), np.concatenate(global_batches(1, epoch=4))
    )
    assert not np.array_equal(
        np.concatenate(ref), np.concatenate(global_batches(1, epoch=5))
    )


def test_share_hint_parsing_is_forgiving():
    from simclr_pytorch_distributed_tpu.data.pipeline import parse_share_hint

    assert parse_share_hint("1:0.5") == (1, 0.5)
    assert parse_share_hint("0:1.0") == (0, 1.0)
    for bad in (None, "", "garbage", "1:", ":0.5", "1:0", "1:-0.5",
                "1:1.5", "-1:0.5", "1:nan", "x:0.5"):
        assert parse_share_hint(bad) is None, bad


def test_share_splits_invariants():
    """Whatever the hint, the bounds are a contiguous partition of the
    global batch with every process keeping at least one row — the
    invariant the collective-participation contract needs."""
    from simclr_pytorch_distributed_tpu.data.pipeline import share_splits

    assert share_splits(64, 4) == [(0, 16), (16, 32), (32, 48), (48, 64)]
    b = share_splits(64, 4, "1:0.5")
    sizes = [hi - lo for lo, hi in b]
    assert sizes[1] == 8 and sum(sizes) == 64  # host 1 sheds half its share
    for hint in (None, "0:0.5", "3:0.25", "2:0.01", "9:0.5", "bad", "1:1.0"):
        bounds = share_splits(96, 4, hint)
        sizes = [hi - lo for lo, hi in bounds]
        assert bounds[0][0] == 0 and bounds[-1][1] == 96
        assert sum(sizes) == 96 and all(s >= 1 for s in sizes)
        assert all(
            bounds[i][1] == bounds[i + 1][0] for i in range(len(bounds) - 1)
        )
    # out-of-range host and single-process hints degrade to uniform
    assert share_splits(96, 4, "9:0.5") == share_splits(96, 4)
    assert share_splits(96, 1, "0:0.5") == [(0, 96)]
    # an extreme factor still leaves the slow host one row, never zero
    tiny = share_splits(8, 4, "2:0.01")
    assert tiny[2][1] - tiny[2][0] == 1


def test_share_hint_preserves_global_batch_composition():
    """FLEET_SHARE_HINT consumption (supervise/launch.py share_env -> this
    loader): an uneven split moves rows BETWEEN processes but the union of
    the per-process slices is bit-identical to the uniform split's — the
    epoch permutation, not the share, defines what the fleet consumes."""
    images = np.arange(96)[:, None].astype(np.uint8)
    labels = np.arange(96).astype(np.int32)

    def global_batches(share_hint):
        loaders = [
            EpochLoader(
                images, labels, global_batch_size=32, base_seed=11,
                process_index=p, process_count=4, prefetch=0,
                share_hint=share_hint,
            )
            for p in range(4)
        ]
        return [
            np.concatenate([lab for _, lab in parts])
            for parts in zip(*[list(l.epoch(3)) for l in loaders])
        ]

    ref = global_batches(None)
    skew = global_batches("2:0.5")
    for a, b in zip(ref, skew):
        np.testing.assert_array_equal(a, b)
    # and the hinted process genuinely carries fewer rows
    slow = EpochLoader(
        images, labels, global_batch_size=32, base_seed=11,
        process_index=2, process_count=4, prefetch=0, share_hint="2:0.5",
    )
    _, lab = next(iter(slow.epoch(3)))
    assert len(lab) == 4  # half of the uniform 8
    assert slow.share_bounds[2] == (slow._lo, slow._hi)
