"""Exit-75 retry contract in the launchers (run_supcon.sh / run_linear.sh).

PR 1 built the preemption machinery (emergency checkpoint + exit 75,
docs/RESILIENCE.md) but the launchers launched once and exited — the
contract's "re-run with --resume" half never actually happened. These tests
run the REAL launcher scripts against a stub ``python`` on PATH that logs
its argv and scripts the exit codes, proving: bounded retries happen only on
exit 75, ``--resume`` points at the newest pretrain run dir, and every other
exit code passes through untouched.

Two launcher paths share the stub-python pattern:

- the DEFAULT path delegates babysitting to the supervisor CLI
  (``python -m simclr_pytorch_distributed_tpu.supervise -- python
  main_supcon.py ...``) — the stub sees the delegation argv, and the
  launcher's exit code is the supervisor's;
- ``SUPERVISE=0`` keeps the legacy bounded shell loop, whose behavior the
  original tests below pin unchanged.
"""

import os
import stat
import subprocess

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def write_stub_python(bin_dir, tmp_path, exit_codes, make_run_dir=None):
    """A fake ``python`` that logs argv, optionally creates a run dir (as a
    real preempted driver would have), and exits per-invocation codes."""
    log = tmp_path / "calls.log"
    codes = " ".join(str(c) for c in exit_codes)
    mkdir_cmd = f'mkdir -p "{make_run_dir}"' if make_run_dir else ":"
    stub = bin_dir / "python"
    stub.write_text(f"""#!/bin/bash
echo "$@" >> "{log}"
count=$(wc -l < "{log}")
{mkdir_cmd}
codes=({codes})
exit "${{codes[$((count - 1))]}}"
""")
    stub.chmod(stub.stat().st_mode | stat.S_IEXEC)
    return log


def run_launcher(script, args, bin_dir, tmp_path, supervise="0"):
    """Legacy loop by default (``SUPERVISE=0``) — the original contract
    tests below pin that path; pass ``supervise='1'`` for the delegation
    path."""
    env = dict(
        os.environ, PATH=f"{bin_dir}:{os.environ['PATH']}",
        SUPERVISE=supervise,
    )
    return subprocess.run(
        ["bash", os.path.join(REPO, script), *args],
        env=env, cwd=tmp_path, capture_output=True, text=True, timeout=60,
    )


@pytest.fixture
def bin_dir(tmp_path):
    d = tmp_path / "bin"
    d.mkdir()
    return d


def test_supcon_retries_with_resume_then_succeeds(tmp_path, bin_dir):
    workdir = tmp_path / "ws"
    run_dir = workdir / "cifar10_models" / "cifar10_0101_0000_SimCLR_run"
    log = write_stub_python(
        bin_dir, tmp_path, exit_codes=[75, 75, 0], make_run_dir=run_dir
    )
    proc = run_launcher(
        "run_supcon.sh", ["--workdir", str(workdir)], bin_dir, tmp_path
    )
    assert proc.returncode == 0, proc.stderr
    calls = log.read_text().splitlines()
    assert len(calls) == 3
    assert "--resume" not in calls[0]
    for call in calls[1:]:  # every retry resumes from the newest run dir
        assert f"--resume {run_dir}" in call
    assert "retry 1/3" in proc.stderr and "retry 2/3" in proc.stderr


def test_supcon_ignores_probe_and_ce_dirs_when_resolving_resume(tmp_path, bin_dir):
    workdir = tmp_path / "ws"
    pretrain = workdir / "cifar10_models" / "cifar10_0101_0000_SimCLR_run"
    log = write_stub_python(bin_dir, tmp_path, [75, 0], make_run_dir=pretrain)
    # decoys that sort NEWER than the pretrain dir must not win
    far_future = 4102444800  # newer than any mtime the stub's mkdir produces
    for decoy in ("classifier_0102_0000_foo", "ce_0102_0000_bar"):
        d = workdir / "cifar10_models" / decoy
        d.mkdir(parents=True)
        os.utime(d, (far_future, far_future))
    proc = run_launcher(
        "run_supcon.sh", ["--workdir", str(workdir)], bin_dir, tmp_path
    )
    assert proc.returncode == 0, proc.stderr
    assert f"--resume {pretrain}" in log.read_text().splitlines()[1]


def test_supcon_retry_resume_beats_user_supplied_resume(tmp_path, bin_dir):
    """argparse is last-wins: on a retry the freshly resolved run dir must
    come AFTER any --resume the user passed, or every retry would restart
    from the user's stale checkpoint and lose the preempted progress."""
    workdir = tmp_path / "ws"
    run_dir = workdir / "cifar10_models" / "cifar10_0101_0000_SimCLR_run"
    log = write_stub_python(bin_dir, tmp_path, [75, 0], make_run_dir=run_dir)
    proc = run_launcher(
        "run_supcon.sh",
        ["--workdir", str(workdir), "--resume", "stale_dir"],
        bin_dir, tmp_path,
    )
    assert proc.returncode == 0, proc.stderr
    retry = log.read_text().splitlines()[1]
    assert retry.index("--resume stale_dir") < retry.index(f"--resume {run_dir}")


def test_supcon_honors_workdir_equals_spelling(tmp_path, bin_dir):
    """argparse accepts '--workdir=DIR'; the launcher's resume scan must too
    — otherwise a retry silently restarts from scratch in ./work_space."""
    workdir = tmp_path / "ce_experiments" / "ws"  # also: '/ce_' IN the path
    run_dir = workdir / "cifar10_models" / "cifar10_0101_0000_SimCLR_run"
    log = write_stub_python(bin_dir, tmp_path, [75, 0], make_run_dir=run_dir)
    proc = run_launcher(
        "run_supcon.sh", [f"--workdir={workdir}"], bin_dir, tmp_path
    )
    assert proc.returncode == 0, proc.stderr
    # the basename filter must not be fooled by 'ce_' in the workdir path
    assert f"--resume {run_dir}" in log.read_text().splitlines()[1]


def test_supcon_non_75_exit_passes_through_without_retry(tmp_path, bin_dir):
    log = write_stub_python(bin_dir, tmp_path, exit_codes=[3])
    proc = run_launcher("run_supcon.sh", [], bin_dir, tmp_path)
    assert proc.returncode == 3
    assert len(log.read_text().splitlines()) == 1  # no retry


def test_supcon_retries_are_bounded(tmp_path, bin_dir):
    log = write_stub_python(bin_dir, tmp_path, exit_codes=[75] * 10)
    proc = run_launcher("run_supcon.sh", [], bin_dir, tmp_path)
    assert proc.returncode == 75  # still preempted after the budget: honest rc
    assert len(log.read_text().splitlines()) == 4  # 1 launch + PREEMPT_RETRIES=3


def test_linear_retries_from_scratch_then_passes_through(tmp_path, bin_dir):
    log = write_stub_python(bin_dir, tmp_path, exit_codes=[75, 2])
    proc = run_launcher("run_linear.sh", ["--ckpt", "x"], bin_dir, tmp_path)
    assert proc.returncode == 2  # second run's code passes through
    calls = log.read_text().splitlines()
    assert len(calls) == 2
    assert "--resume" not in calls[0]
    assert "--resume preempted-retry" in calls[1]  # probe: retrain from scratch
    assert "--ckpt x" in calls[1]  # user args survive the relaunch


# -------------------------------------------------- supervisor delegation


def test_supcon_default_path_delegates_to_supervisor(tmp_path, bin_dir):
    """SUPERVISE unset/1: one stub invocation carrying the supervisor
    module, the launcher's workdir/retry budget as supervisor flags, and
    the full trainer command after ``--``; the supervisor's exit code IS
    the launcher's."""
    workdir = tmp_path / "ws"
    log = write_stub_python(bin_dir, tmp_path, exit_codes=[7])
    proc = run_launcher(
        "run_supcon.sh", ["--workdir", str(workdir)], bin_dir, tmp_path,
        supervise="1",
    )
    assert proc.returncode == 7, proc.stderr
    calls = log.read_text().splitlines()
    assert len(calls) == 1  # retries are the SUPERVISOR'S job now
    call = calls[0]
    assert "-m simclr_pytorch_distributed_tpu.supervise" in call
    assert f"--workdir {workdir}" in call
    assert "--max_restarts 3" in call
    # the trainer command rides after the separator, recipe flags intact
    sep = call.index(" -- ")
    assert "python main_supcon.py" in call[sep:]
    assert "--method SimCLR" in call[sep:]
    assert f"--workdir {workdir}" in call[sep:]  # user args pass through


def test_supcon_supervisor_honors_preempt_retries_env(tmp_path, bin_dir):
    log = write_stub_python(bin_dir, tmp_path, exit_codes=[0])
    env_retries = dict(os.environ, PREEMPT_RETRIES="7")
    proc = subprocess.run(
        ["bash", os.path.join(REPO, "run_supcon.sh")],
        env=dict(env_retries, PATH=f"{bin_dir}:{os.environ['PATH']}",
                 SUPERVISE="1"),
        cwd=tmp_path, capture_output=True, text=True, timeout=60,
    )
    assert proc.returncode == 0, proc.stderr
    assert "--max_restarts 7" in log.read_text()


def test_linear_default_path_delegates_to_supervisor(tmp_path, bin_dir):
    log = write_stub_python(bin_dir, tmp_path, exit_codes=[0])
    proc = run_launcher(
        "run_linear.sh", ["--ckpt", "x"], bin_dir, tmp_path, supervise="1",
    )
    assert proc.returncode == 0, proc.stderr
    call = log.read_text().splitlines()[0]
    assert "-m simclr_pytorch_distributed_tpu.supervise" in call
    sep = call.index(" -- ")
    # the probe's run dirs are classifier_* — the supervisor must be told
    # not to exclude them, or its watch channel is blind
    assert "--all_run_dirs" in call[:sep]
    assert "python main_linear.py" in call[sep:]
    assert "--ckpt x" in call[sep:]  # user args survive the delegation


def test_supcon_supervisor_liveness_env_wiring(tmp_path, bin_dir):
    """SUPERVISE_STALL_SECS / SUPERVISE_METRICS_PORT opt into liveness-kill:
    the supervisor gets --stall_secs/--metrics_port and the TRAINER command
    gets the matching --metrics_port (after user args: argparse last-wins),
    so one env var wires both ends of the scrape to the same port."""
    log = write_stub_python(bin_dir, tmp_path, exit_codes=[0])
    env = dict(
        os.environ, PATH=f"{bin_dir}:{os.environ['PATH']}", SUPERVISE="1",
        SUPERVISE_STALL_SECS="300", SUPERVISE_METRICS_PORT="9100",
    )
    proc = subprocess.run(
        ["bash", os.path.join(REPO, "run_supcon.sh")],
        env=env, cwd=tmp_path, capture_output=True, text=True, timeout=60,
    )
    assert proc.returncode == 0, proc.stderr
    call = log.read_text().splitlines()[0]
    sep = call.index(" -- ")
    assert "--stall_secs 300" in call[:sep]
    assert "--metrics_port 9100" in call[:sep]
    assert "--metrics_port 9100" in call[sep:]  # the trainer side too
    # the trainer's watchdog is the stall verdict's dump channel: without
    # it SUPERVISE_STALL_SECS alone would be a silent no-op
    assert "--watchdog_secs 300" in call[sep:]
    # unset -> observe-only: no liveness flags anywhere
    (tmp_path / "b").mkdir()
    log2 = write_stub_python(bin_dir, tmp_path / "b", exit_codes=[0])
    proc2 = run_launcher("run_supcon.sh", [], bin_dir, tmp_path / "b",
                         supervise="1")
    assert proc2.returncode == 0
    call2 = log2.read_text().splitlines()[0]
    assert "--stall_secs" not in call2 and "--metrics_port" not in call2
