"""Replica-fleet supervision tests (supervise/replica.py + replica_fleet.py).

Same discipline as test_supervise.py's policy table: ``classify`` and
``ReplicaPolicy.decide`` are pure, so every row of the decision table is
enumerated without a process or a clock. The supervisor loop runs on fake
Popen/scraper/clock — spawn-to-floor, restart-on-kill (same port), budget
exhaustion to give-up, saturation scale-up, idle scale-down — end to end
in milliseconds. The REAL subprocess scenario (live HTTP replicas, kill -9,
promote under load) is scripts/serve_fleet_scenario.py, whose committed
evidence scripts/ratchet.py gates.
"""

import itertools
import json
import os
import sys

import pytest

from simclr_pytorch_distributed_tpu.supervise.replica import (
    AGE_GAUGE,
    BUSY,
    DEAD,
    DRAIN,
    GIVE_UP,
    IDLE,
    INFLIGHT_GAUGE,
    OCC_GAUGE,
    QUEUE_GAUGE,
    RESTART,
    SATURATED,
    SPAWN,
    STALLED,
    STARTING,
    UNSCRAPEABLE,
    ReplicaObservation,
    ReplicaPolicy,
    classify,
)
from simclr_pytorch_distributed_tpu.supervise.replica_fleet import (
    ReplicaFleetConfig,
    ReplicaFleetSupervisor,
)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

pytestmark = [pytest.mark.supervisor, pytest.mark.servefleet]


def gauges(queued=0.0, inflight=0.0, age=0.0, occ=0.0):
    return {
        QUEUE_GAUGE: queued, INFLIGHT_GAUGE: inflight,
        AGE_GAUGE: age, OCC_GAUGE: occ,
    }


def obs(rid=0, alive=True, metrics=None, age_s=0.0):
    return ReplicaObservation(rid, alive, metrics, age_s)


# --------------------------------------------------------------- classify


def test_classify_exhaustive_over_the_condition_grid():
    """Every combination of the table's binary conditions lands in exactly
    the documented class — the table has no unreachable or ambiguous row.

    Grid axes: alive, scraped, past startup grace, work pending, completion
    age past stall threshold, occupancy high, queue high, fully quiescent.
    """
    P = dict(startup_grace_s=60.0, stall_age_s=30.0,
             occ_hi=0.9, queue_hi=64.0, occ_lo=0.1)
    for alive, scraped in itertools.product([False, True], repeat=2):
        for young, pending, stale, occ_hi, q_hi in itertools.product(
            [False, True], repeat=5
        ):
            m = gauges(
                queued=80.0 if q_hi else (1.0 if pending else 0.0),
                inflight=1.0 if pending else 0.0,
                age=99.0 if stale else 1.0,
                occ=0.95 if occ_hi else 0.5,
            )
            o = obs(alive=alive, metrics=m if scraped else None,
                    age_s=5.0 if young else 120.0)
            got = classify(o, **P)
            if not alive:
                assert got == DEAD
            elif not scraped:
                assert got == (STARTING if young else UNSCRAPEABLE)
            elif (pending or q_hi) and stale:
                assert got == STALLED
            elif occ_hi or q_hi:
                assert got == SATURATED
            else:
                assert got == BUSY  # occ 0.5 > occ_lo, never idle here


def test_classify_idle_requires_full_quiescence():
    assert classify(obs(metrics=gauges())) == IDLE
    assert classify(obs(metrics=gauges(occ=0.05))) == IDLE
    # ANY of queued / inflight / occupancy above the floor blocks idle
    assert classify(obs(metrics=gauges(queued=1))) == BUSY
    assert classify(obs(metrics=gauges(inflight=1))) == BUSY
    assert classify(obs(metrics=gauges(occ=0.5))) == BUSY


def test_classify_thresholds_are_inclusive_where_documented():
    assert classify(obs(metrics=gauges(occ=0.9))) == SATURATED       # >=
    assert classify(obs(metrics=gauges(queued=64))) == SATURATED     # >=
    assert classify(obs(metrics=gauges(occ=0.1))) == IDLE            # <=
    assert classify(obs(metrics=None, age_s=60.0)) == STARTING       # <=
    # stall is strict: exactly the threshold is not yet a stall
    assert classify(obs(metrics=gauges(queued=1, age=30.0))) == BUSY


# ----------------------------------------------------------------- policy


def test_policy_validation():
    with pytest.raises(ValueError):
        ReplicaPolicy(0, 4)
    with pytest.raises(ValueError):
        ReplicaPolicy(3, 2)
    with pytest.raises(ValueError):
        ReplicaPolicy(1, 2, unscrape_strikes=0)


def test_dead_replica_restarts_until_budget_then_gives_up():
    p = ReplicaPolicy(1, 4, max_restarts=2)
    busy = obs(1, metrics=gauges(queued=1, inflight=1, age=1, occ=0.5))
    for expect in (1, 2):
        d = p.decide([obs(0, alive=False), busy])
        assert [x.action for x in d] == [RESTART]
        assert d[0].replica == 0 and f"{expect}/2" in d[0].reason
    d = p.decide([obs(0, alive=False), busy])
    assert [x.action for x in d] == [GIVE_UP]
    assert p.given_up == {0}
    # the abandoned slot is ignored thereafter; fleet still >= min via 1
    assert p.decide([obs(0, alive=False), busy]) == []


def test_stalled_replica_is_repaired_with_the_age_in_the_reason():
    p = ReplicaPolicy(1, 4)
    d = p.decide([obs(0, metrics=gauges(queued=3, age=45.0))])
    assert d[0].action == RESTART and "45.0s" in d[0].reason


def test_unscrapeable_needs_consecutive_strikes_and_recovery_resets():
    p = ReplicaPolicy(1, 4, unscrape_strikes=3)
    gone = obs(0, metrics=None, age_s=120.0)
    ok = obs(0, metrics=gauges(queued=1, inflight=1, age=1, occ=0.5))
    assert p.decide([gone]) == []          # strike 1
    assert p.decide([gone]) == []          # strike 2
    assert p.decide([ok]) == []            # recovery resets the count
    assert p.decide([gone]) == []          # strike 1 again
    assert p.decide([gone]) == []
    d = p.decide([gone])                   # strike 3: escalate
    assert [x.action for x in d] == [RESTART]


def test_fleet_below_min_spawns():
    p = ReplicaPolicy(2, 4)
    d = p.decide([obs(0, metrics=gauges(queued=1, occ=0.5, inflight=1, age=1))])
    assert [x.action for x in d] == [SPAWN] and d[0].replica == -1


def test_saturation_spawns_one_per_tick_up_to_max():
    p = ReplicaPolicy(1, 2)
    hot = obs(0, metrics=gauges(occ=0.95))
    d = p.decide([hot])
    assert [x.action for x in d] == [SPAWN]
    # at max: saturation no longer spawns
    hot2 = obs(1, metrics=gauges(occ=0.95))
    assert p.decide([hot, hot2]) == []


def test_idle_drains_highest_id_only_without_saturation_above_min():
    p = ReplicaPolicy(1, 4)
    idle0 = obs(0, metrics=gauges())
    idle2 = obs(2, metrics=gauges())
    busy1 = obs(1, metrics=gauges(queued=1, inflight=1, age=1, occ=0.5))
    d = p.decide([idle0, busy1, idle2])
    assert [(x.action, x.replica) for x in d] == [(DRAIN, 2)]
    # at min: idle never drains below the floor
    p2 = ReplicaPolicy(1, 4)
    assert p2.decide([idle0]) == []
    # saturation anywhere suppresses draining (the fleet is not oversized)
    p3 = ReplicaPolicy(1, 4)
    hot = obs(1, metrics=gauges(occ=0.95))
    d = p3.decide([idle0, hot])
    assert all(x.action != DRAIN for x in d)


def test_repair_and_scaling_compose_in_one_tick():
    """A dead replica and a below-min fleet produce repair AND spawn in the
    same decide call — recovery does not wait a tick behind sizing."""
    p = ReplicaPolicy(3, 4, max_restarts=0)  # dead -> immediate give-up
    busy = obs(1, metrics=gauges(queued=1, inflight=1, age=1, occ=0.5))
    d = p.decide([obs(0, alive=False), busy])
    assert [x.action for x in d] == [GIVE_UP, SPAWN]


# ------------------------------------------------------- supervisor (fakes)


class FakeProc:
    def __init__(self, cmd):
        self.cmd = cmd
        self.returncode = None

    def poll(self):
        return self.returncode

    def wait(self):
        return self.returncode if self.returncode is not None else 0

    def send_signal(self, _sig):
        self.returncode = -15

    def kill(self):
        self.returncode = -9


class FakeScraper:
    def __init__(self, port):
        self.port = port
        self.metrics = None

    def scrape(self):
        return self.metrics


@pytest.fixture()
def harness():
    state = {"t": 0.0, "procs": [], "scrapers": {}}
    ports = itertools.count(9000)

    def popen(cmd, env=None):
        p = FakeProc(cmd)
        state["procs"].append(p)
        return p

    def fake_sleep(seconds):
        state["t"] += seconds

    def sup(policy, **cfg_kwargs):
        cfg = ReplicaFleetConfig(
            command=["serve", "--port", "{port}"], grace_s=1.0, **cfg_kwargs
        )
        return ReplicaFleetSupervisor(
            cfg, policy, popen=popen,
            clock=lambda: state["t"],
            sleep=fake_sleep,
            free_port=lambda: next(ports),
            scraper_factory=lambda port: state["scrapers"].setdefault(
                port, FakeScraper(port)
            ),
        )

    return sup, state


BUSY_M = gauges(queued=1, inflight=1, age=1, occ=0.5)


def test_supervisor_spawns_to_floor_and_substitutes_the_port(harness):
    make, state = harness
    sup = make(ReplicaPolicy(2, 3))
    assert [r["action"] for r in sup.step()] == [SPAWN]
    assert [r["action"] for r in sup.step()] == [SPAWN]
    assert len(sup.replicas()) == 2
    assert state["procs"][0].cmd == ["serve", "--port", "9000"]
    assert state["procs"][1].cmd == ["serve", "--port", "9001"]
    for s in state["scrapers"].values():
        s.metrics = dict(BUSY_M)
    assert sup.step() == []  # steady state
    sup.stop_all()
    assert sup.replicas() == {}
    assert all(p.returncode is not None for p in state["procs"])


def test_supervisor_restarts_killed_replica_on_the_same_port(harness):
    make, state = harness
    sup = make(ReplicaPolicy(2, 3, max_restarts=1))
    sup.step(); sup.step()
    for s in state["scrapers"].values():
        s.metrics = dict(BUSY_M)
    state["procs"][0].returncode = -9  # kill -9 replica 0
    d = sup.step()
    assert [r["action"] for r in d] == [RESTART]
    assert d[0]["replica"] == 0 and d[0]["port"] == 9000  # SAME port
    assert d[0]["old_returncode"] == -9
    assert sup.replicas()[0] == {
        "port": 9000, "pid": None, "alive": True, "restarts": 1,
    }


def test_supervisor_budget_exhaustion_gives_up_then_backfills(harness):
    make, state = harness
    sup = make(ReplicaPolicy(2, 3, max_restarts=0))
    sup.step(); sup.step()
    for s in state["scrapers"].values():
        s.metrics = dict(BUSY_M)
    state["procs"][0].returncode = -9
    d = sup.step()
    assert [r["action"] for r in d] == [GIVE_UP, SPAWN]
    assert sup.gave_up() == [0]
    assert sorted(sup.replicas()) == [1, 2]  # fresh slot, fresh id


def test_supervisor_scales_up_on_saturation_and_drains_idle(harness):
    make, state = harness
    sup = make(ReplicaPolicy(1, 2))
    sup.step()
    state["scrapers"][9000].metrics = gauges(occ=0.95)
    d = sup.step()
    assert [r["action"] for r in d] == [SPAWN]
    assert len(sup.replicas()) == 2
    state["scrapers"][9000].metrics = dict(BUSY_M)
    state["scrapers"][9001].metrics = gauges()  # newest idle
    d = sup.step()
    assert [(r["action"], r["replica"]) for r in d] == [(DRAIN, 1)]
    assert sorted(sup.replicas()) == [0]


def test_supervisor_run_until_predicate(harness):
    make, state = harness
    sup = make(ReplicaPolicy(1, 2))
    sup.run(until=lambda: len(sup.replicas()) >= 1)
    assert len(sup.replicas()) == 1
    assert [r["action"] for r in sup.decisions()] == [SPAWN]


# ------------------------------------------- committed evidence + ratchet gate


def _gate():
    sys.path.insert(0, os.path.join(REPO, "scripts"))
    import ratchet

    return ratchet


def sample_fleet_artifact():
    return {
        "metric": "serve_fleet_scenario",
        "schema": "serve_fleet/v1",
        "phases": {
            "spawn": {
                "ok": True,
                "replicas": {"0": {"port": 9000}, "1": {"port": 9001}},
                "warm_embed": {"0": {"status": 200}, "1": {"status": 200}},
            },
            "restart": {
                "ok": True, "replica": 0, "port": 9000,
                "decisions": [{"action": "restart_replica", "replica": 0,
                               "port": 9000, "old_returncode": -9}],
                "served_after_restart": True,
            },
            "promote": {
                "ok": True, "response": {"model": "prod", "version": 2,
                                         "draining": 1},
                "embed_ok": 500, "embed_failures": {},
                "versions": {"1": "retired", "2": "serving"},
                "drained": True,
            },
            "neighbors": {
                "ok": True, "self_top1": True, "top1_score": 0.99999,
            },
        },
        "gave_up": [],
        "ok": True,
    }


def test_serve_fleet_gate_record_accepts_complete_artifact():
    r = _gate().serve_fleet_gate_record(sample_fleet_artifact())
    assert r["ok"], r
    assert r["metric"] == "ratchet_serve_fleet"
    assert sorted(r["phases"]) == ["neighbors", "promote", "restart", "spawn"]


def test_serve_fleet_gate_record_rejects_weakened_evidence():
    """Each load-bearing claim, individually removed, must fail the gate —
    a hand-edited artifact cannot sneak past on phase ok flags alone."""
    gate = _gate()
    art = sample_fleet_artifact()
    art["schema"] = "serve_fleet/v0"
    assert not gate.serve_fleet_gate_record(art)["ok"]

    art = sample_fleet_artifact()
    del art["phases"]["promote"]
    r = gate.serve_fleet_gate_record(art)
    assert not r["ok"] and "promote" in r["error"]

    # a single-replica fleet proves nothing about the floor
    art = sample_fleet_artifact()
    del art["phases"]["spawn"]["replicas"]["1"]
    assert not gate.serve_fleet_gate_record(art)["ok"]

    # a restart that changed port broke the address contract
    art = sample_fleet_artifact()
    art["phases"]["restart"]["decisions"][0]["port"] = 9005
    r = gate.serve_fleet_gate_record(art)
    assert not r["ok"] and "port" in r["error"]

    # the kill must really have been a SIGKILL, not a clean exit
    art = sample_fleet_artifact()
    art["phases"]["restart"]["decisions"][0]["old_returncode"] = 0
    assert not gate.serve_fleet_gate_record(art)["ok"]

    # ANY dropped request across the swap window is disqualifying
    art = sample_fleet_artifact()
    art["phases"]["promote"]["embed_failures"] = {"http_503": 1}
    r = gate.serve_fleet_gate_record(art)
    assert not r["ok"] and "dropped" in r["error"]

    # a swap with no live load proves nothing about draining
    art = sample_fleet_artifact()
    art["phases"]["promote"]["embed_ok"] = 3
    assert not gate.serve_fleet_gate_record(art)["ok"]

    art = sample_fleet_artifact()
    art["phases"]["promote"]["drained"] = False
    assert not gate.serve_fleet_gate_record(art)["ok"]

    art = sample_fleet_artifact()
    art["phases"]["neighbors"]["top1_score"] = 0.42
    assert not gate.serve_fleet_gate_record(art)["ok"]

    # an abandoned slot means the fleet did not actually hold its floor
    art = sample_fleet_artifact()
    art["gave_up"] = [0]
    assert not gate.serve_fleet_gate_record(art)["ok"]


def test_committed_fleet_evidence_passes_the_gate():
    """docs/evidence/serve_fleet_r17.json — produced by
    scripts/serve_fleet_scenario.py driving a REAL supervised replica fleet
    — must satisfy the same pure gate ratchet runs."""
    path = os.path.join(REPO, "docs", "evidence", "serve_fleet_r17.json")
    with open(path) as f:
        artifact = json.load(f)
    r = _gate().serve_fleet_gate_record(artifact)
    assert r["ok"], r
