"""Ring-sharded contrastive loss == dense supcon_loss, values AND gradients."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from simclr_pytorch_distributed_tpu.compat import shard_map
from jax.sharding import Mesh, PartitionSpec as P

from simclr_pytorch_distributed_tpu.ops.losses import supcon_loss
from simclr_pytorch_distributed_tpu.parallel.collectives import ring_supcon_loss


def normed(seed, B, V, D):
    x = np.random.default_rng(seed).normal(size=(B, V, D)).astype(np.float32)
    return x / np.linalg.norm(x, axis=-1, keepdims=True)


def dense_loss(fbvd, labels=None, temperature=0.5):
    return supcon_loss(
        fbvd, labels=labels, temperature=temperature, base_temperature=0.07
    )


def to_rows(fbvd):
    """[B, V, D] -> view-major rows [V*B, D]."""
    return jnp.transpose(fbvd, (1, 0, 2)).reshape(-1, fbvd.shape[-1])


def ring_on_mesh(rows, labels=None, temperature=0.5, n_devices=8):
    mesh = Mesh(np.array(jax.devices()[:n_devices]), ("data",))
    kwargs = dict(temperature=temperature, base_temperature=0.07, axis_name="data")

    if labels is None:
        fn = shard_map(
            lambda r: ring_supcon_loss(r, None, **kwargs),
            mesh=mesh, in_specs=P("data"), out_specs=P(),
        )
        return fn(rows)
    fn = shard_map(
        lambda r, lab: ring_supcon_loss(r, lab, **kwargs),
        mesh=mesh, in_specs=(P("data"), P()), out_specs=P(),
    )
    return fn(rows, labels)


@pytest.mark.parametrize("temperature", [0.5, 0.1])
def test_ring_simclr_matches_dense(temperature):
    B, V, D = 16, 2, 24
    f = jnp.asarray(normed(0, B, V, D))
    dense = dense_loss(f, temperature=temperature)
    ring = ring_on_mesh(to_rows(f), temperature=temperature)
    np.testing.assert_allclose(float(ring), float(dense), rtol=2e-5)


def test_ring_supcon_labels_matches_dense():
    B, V, D = 16, 2, 16
    f = jnp.asarray(normed(1, B, V, D))
    labels = jnp.asarray(np.random.default_rng(2).integers(0, 4, B))
    dense = dense_loss(f, labels=labels)
    ring = ring_on_mesh(to_rows(f), labels=labels)
    np.testing.assert_allclose(float(ring), float(dense), rtol=2e-5)


@pytest.mark.slow
def test_ring_gradients_match_dense():
    B, V, D = 8, 2, 12
    f = jnp.asarray(normed(3, B, V, D))

    g_dense = jax.grad(lambda x: dense_loss(x, temperature=0.5))(f)
    g_ring = jax.grad(
        lambda x: ring_on_mesh(to_rows(x), temperature=0.5, n_devices=4)
    )(f)
    np.testing.assert_allclose(np.asarray(g_ring), np.asarray(g_dense),
                               rtol=1e-4, atol=1e-6)


def test_ring_four_views():
    B, V, D = 8, 4, 8
    f = jnp.asarray(normed(4, B, V, D))
    dense = dense_loss(f)
    mesh = Mesh(np.array(jax.devices()[:8]), ("data",))
    fn = shard_map(
        lambda r: ring_supcon_loss(
            r, None, axis_name="data", temperature=0.5, base_temperature=0.07,
            n_views=4,
        ),
        mesh=mesh, in_specs=P("data"), out_specs=P(),
    )
    ring = fn(to_rows(f))
    np.testing.assert_allclose(
        float(ring), float(dense_loss(f, temperature=0.5)), rtol=2e-5
    )


@pytest.mark.slow
def test_ring_matches_dense_at_recipe_scale():
    """VERDICT r1 #6: ring == dense at the ImageNet-recipe loss scale —
    global batch 4096 (512 rows/device on the 8-way mesh), 8192x8192 logical
    logits. Value AND gradient, fp32."""
    B, V, D = 4096, 2, 128
    f = jnp.asarray(normed(7, B, V, D))
    rows = to_rows(f)

    dense_val, dense_grad = jax.value_and_grad(
        lambda r: dense_loss(r.reshape(V, B, D).transpose(1, 0, 2))
    )(rows)
    ring_val, ring_grad = jax.value_and_grad(lambda r: ring_on_mesh(r))(rows)

    np.testing.assert_allclose(float(ring_val), float(dense_val), rtol=2e-5)
    np.testing.assert_allclose(
        np.asarray(ring_grad), np.asarray(dense_grad), atol=2e-6
    )
