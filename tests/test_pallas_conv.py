"""Fused Pallas conv+BN+ReLU kernels vs the Flax oracle (interpret mode).

The fused stem (``fused_conv_bn_relu``) and residual-block kernels
(``fused_basic_block`` / ``fused_projection_block`` /
``fused_bottleneck_block``, ops/pallas_conv.py) must match the
bitwise-pinned Flax path — ``nn.Conv`` + ``CrossReplicaBatchNorm`` in
whole-batch train mode — in value, in every parameter/input gradient, and
in the batch statistics that feed the running-stat update, across every
geometry class ``supports_*`` admits. bf16 kernel variants compare
against the SAME fp32 Flax reference at the round-19 derived tolerances
(docs/PERF.md round 19). Unsupported geometries and dtypes must fall back
to the XLA path, eval mode must stay bitwise-XLA, and the param tree must
be impl-independent (a ``--conv_impl pallas`` checkpoint restores under
``--conv_impl xla`` — proven through the real driver below).
"""

import os
import sys

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from flax import linen as nn

from simclr_pytorch_distributed_tpu import config as config_lib
from simclr_pytorch_distributed_tpu.models import SupConResNet
from simclr_pytorch_distributed_tpu.models.norm import (
    CrossReplicaBatchNorm,
    FusedTrainBN,
    running_stats_update,
)
from simclr_pytorch_distributed_tpu.models.resnet import (
    BasicBlock,
    Bottleneck,
    fused_site_plan,
)
from simclr_pytorch_distributed_tpu.ops import pallas_conv

pytestmark = pytest.mark.kernel

# Interpret-mode kernels accumulate in a different order than XLA's conv
# emitter; fp32 accumulation noise at these magnitudes measured ~1e-6
# relative (values) / ~3e-5 absolute on O(100) gradient scales. Pinned
# with ~30x margin.
VAL_RTOL, VAL_ATOL = 3e-5, 3e-5
GRAD_RTOL, GRAD_ATOL = 1e-4, 1e-3

# bf16 kernels vs the fp32 Flax reference: bf16 unit roundoff is
# 2^-8 ~= 3.9e-3; measured worst cases across all kinds/geometries were
# value scaled-maxabs 5.9e-3 (~1.5 ulp) and grad cosine 0.9905 — ReLU
# masks flip for pre-activations within roundoff of zero, which spikes
# per-entry grad diffs while leaving the gradient DIRECTION intact, so
# grads bind on cosine with a loose scaled-maxabs sanity bound. Pinned at
# ~3-5x margin (full derivation: docs/PERF.md round 19).
BF16_VAL_SCALED, BF16_VAL_COS = 2e-2, 0.9999
BF16_GRAD_COS, BF16_GRAD_SCALED = 0.95, 0.5
BF16_STATS_SCALED = 2e-2


def _assert_close_bf16(a, b, *, kind, name=""):
    a = np.asarray(a, np.float32)
    b = np.asarray(b, np.float32)
    scaled = float(np.max(np.abs(a - b))) / (float(np.max(np.abs(b))) + 1e-30)
    if kind == "stats":
        assert scaled <= BF16_STATS_SCALED, (name, scaled)
        return
    av, bv = a.astype(np.float64).ravel(), b.astype(np.float64).ravel()
    cos = float(np.dot(av, bv)
                / (np.linalg.norm(av) * np.linalg.norm(bv) + 1e-30))
    if kind == "value":
        assert scaled <= BF16_VAL_SCALED and cos >= BF16_VAL_COS, (
            name, scaled, cos)
    else:
        assert cos >= BF16_GRAD_COS and scaled <= BF16_GRAD_SCALED, (
            name, scaled, cos)


def _flax_stem(x, k, g, b):
    """conv3x3/s1 + whole-batch train BN + ReLU via the production
    modules, returning (out, mutated batch_stats)."""

    class Stem(nn.Module):
        @nn.compact
        def __call__(self, xin):
            y = nn.Conv(
                k.shape[3], (3, 3), strides=(1, 1), use_bias=False,
                padding=((1, 1), (1, 1)), param_dtype=jnp.float32,
                name="conv",
            )(xin)
            return nn.relu(
                CrossReplicaBatchNorm(use_running_average=False, name="bn")(y)
            )

    mod = Stem()
    variables = {
        "params": {
            "conv": {"kernel": k},
            "bn": {"scale": g, "bias": b},
        },
        "batch_stats": {
            "bn": {
                "mean": jnp.zeros((k.shape[3],), jnp.float32),
                "var": jnp.ones((k.shape[3],), jnp.float32),
            }
        },
    }
    return mod.apply(variables, x, mutable=["batch_stats"])


def _flax_block(x, k1, g1, b1, k2, g2, b2):
    """The production BasicBlock (identity shortcut) in train mode."""
    mod = BasicBlock(planes=k1.shape[3])
    variables = {
        "params": {
            "Conv_0": {"kernel": k1},
            "bn1": {"scale": g1, "bias": b1},
            "Conv_1": {"kernel": k2},
            "bn2": {"scale": g2, "bias": b2},
        },
        "batch_stats": {
            "bn1": {
                "mean": jnp.zeros((k1.shape[3],), jnp.float32),
                "var": jnp.ones((k1.shape[3],), jnp.float32),
            },
            "bn2": {
                "mean": jnp.zeros((k2.shape[3],), jnp.float32),
                "var": jnp.ones((k2.shape[3],), jnp.float32),
            },
        },
    }
    return mod.apply(variables, x, True, mutable=["batch_stats"])


def _block_args(rng, n, h, w, c):
    def arr(*shape, scale=1.0, shift=0.0):
        return jnp.asarray(
            rng.standard_normal(shape).astype(np.float32) * scale + shift
        )

    return (
        arr(n, h, w, c),
        arr(3, 3, c, c, scale=0.2), arr(c, shift=1.0), arr(c, scale=0.1),
        arr(3, 3, c, c, scale=0.2), arr(c, shift=1.0), arr(c, scale=0.1),
    )


# one geometry per admitted class: square stage-1-like, non-square (h != w),
# tall-channel, and a batch the tile picker must split unevenly (bn=4)
BLOCK_GEOMETRIES = [(16, 8, 8, 8), (8, 10, 6, 16), (16, 4, 4, 24), (12, 8, 8, 8)]


@pytest.mark.parametrize("n,h,w,c", BLOCK_GEOMETRIES)
def test_fused_block_forward_matches_flax(rng, n, h, w, c):
    x, k1, g1, b1, k2, g2, b2 = _block_args(rng, n, h, w, c)
    assert pallas_conv.supports_block(n, h, w, c)
    out_f, m1, v1, m2, v2 = pallas_conv.fused_basic_block(
        x, k1, g1, b1, k2, g2, b2, interpret=True
    )
    out_r, mut = _flax_block(x, k1, g1, b1, k2, g2, b2)
    np.testing.assert_allclose(
        np.asarray(out_f), np.asarray(out_r), rtol=VAL_RTOL, atol=VAL_ATOL
    )
    # batch moments -> the same running-stat update as models/norm.py
    count = n * h * w
    for bn_name, (m, v) in (("bn1", (m1, v1)), ("bn2", (m2, v2))):
        ra_m, ra_v = running_stats_update(
            jnp.zeros((c,)), jnp.ones((c,)), m, v, count, 0.1
        )
        np.testing.assert_allclose(
            np.asarray(ra_m),
            np.asarray(mut["batch_stats"][bn_name]["mean"]),
            rtol=VAL_RTOL, atol=VAL_ATOL,
        )
        np.testing.assert_allclose(
            np.asarray(ra_v),
            np.asarray(mut["batch_stats"][bn_name]["var"]),
            rtol=VAL_RTOL, atol=VAL_ATOL,
        )


@pytest.mark.parametrize("n,h,w,c", BLOCK_GEOMETRIES[:2])
def test_fused_block_gradients_match_flax(rng, n, h, w, c):
    args = _block_args(rng, n, h, w, c)

    def loss_fused(*a):
        out = pallas_conv.fused_basic_block(*a, interpret=True)[0]
        return jnp.sum(out * jnp.cos(out))

    def loss_flax(*a):
        out, _ = _flax_block(*a)
        return jnp.sum(out * jnp.cos(out))

    gf = jax.grad(loss_fused, argnums=tuple(range(7)))(*args)
    gr = jax.grad(loss_flax, argnums=tuple(range(7)))(*args)
    names = ("dx", "dk1", "dg1", "db1", "dk2", "dg2", "db2")
    for name, a, b in zip(names, gf, gr):
        np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), rtol=GRAD_RTOL, atol=GRAD_ATOL,
            err_msg=name,
        )


def test_fused_stem_matches_flax_value_and_grads(rng):
    n, h, w, cin, cout = 8, 8, 8, 3, 16
    x = jnp.asarray(rng.standard_normal((n, h, w, cin)).astype(np.float32))
    k = jnp.asarray(
        rng.standard_normal((3, 3, cin, cout)).astype(np.float32) * 0.2
    )
    g = jnp.asarray(rng.standard_normal((cout,)).astype(np.float32) + 1.0)
    b = jnp.asarray(rng.standard_normal((cout,)).astype(np.float32) * 0.1)
    assert pallas_conv.supports_stem(n, h, w, cin, cout)

    out_f, m, v = pallas_conv.fused_conv_bn_relu(x, k, g, b, interpret=True)
    out_r, mut = _flax_stem(x, k, g, b)
    np.testing.assert_allclose(
        np.asarray(out_f), np.asarray(out_r), rtol=VAL_RTOL, atol=VAL_ATOL
    )
    ra_m, ra_v = running_stats_update(
        jnp.zeros((cout,)), jnp.ones((cout,)), m, v, n * h * w, 0.1
    )
    np.testing.assert_allclose(
        np.asarray(ra_m), np.asarray(mut["batch_stats"]["bn"]["mean"]),
        rtol=VAL_RTOL, atol=VAL_ATOL,
    )
    np.testing.assert_allclose(
        np.asarray(ra_v), np.asarray(mut["batch_stats"]["bn"]["var"]),
        rtol=VAL_RTOL, atol=VAL_ATOL,
    )

    def loss_fused(*a):
        out, _, _ = pallas_conv.fused_conv_bn_relu(*a, interpret=True)
        return jnp.sum(out * jnp.cos(out))

    def loss_flax(*a):
        out, _ = _flax_stem(*a)
        return jnp.sum(out * jnp.cos(out))

    gf = jax.grad(loss_fused, argnums=(0, 1, 2, 3))(x, k, g, b)
    gr = jax.grad(loss_flax, argnums=(0, 1, 2, 3))(x, k, g, b)
    for name, a, bb in zip(("dx", "dk", "dg", "db"), gf, gr):
        np.testing.assert_allclose(
            np.asarray(a), np.asarray(bb), rtol=GRAD_RTOL, atol=GRAD_ATOL,
            err_msg=name,
        )


# ------------------------------------- projection / Bottleneck / bf16


def _flax_proj_block(x, k1, g1, b1, k2, g2, b2, ks, gs, bs, stride):
    """The production BasicBlock with the 1x1-conv+BN projection shortcut
    in train mode."""
    c = k1.shape[3]
    mod = BasicBlock(planes=c, stride=stride)
    variables = {
        "params": {
            "Conv_0": {"kernel": k1}, "bn1": {"scale": g1, "bias": b1},
            "Conv_1": {"kernel": k2}, "bn2": {"scale": g2, "bias": b2},
            "shortcut_conv": {"kernel": ks},
            "shortcut_bn": {"scale": gs, "bias": bs},
        },
        "batch_stats": {
            bn: {"mean": jnp.zeros((c,)), "var": jnp.ones((c,))}
            for bn in ("bn1", "bn2", "shortcut_bn")
        },
    }
    return mod.apply(variables, x, True, mutable=["batch_stats"])


def _proj_args(rng, n, h, w, cin, c):
    def arr(*shape, scale=1.0, shift=0.0):
        return jnp.asarray(
            rng.standard_normal(shape).astype(np.float32) * scale + shift
        )

    return (
        arr(n, h, w, cin),
        arr(3, 3, cin, c, scale=0.2), arr(c, shift=1.0), arr(c, scale=0.1),
        arr(3, 3, c, c, scale=0.2), arr(c, shift=1.0), arr(c, scale=0.1),
        arr(1, 1, cin, c, scale=0.3), arr(c, shift=1.0), arr(c, scale=0.1),
    )


# stride-2 square, stride-1 channel-change, stride-2 non-square (h != w:
# the even-dims requirement is per-axis), uneven batch tile
PROJ_GEOMETRIES = [
    (16, 8, 8, 8, 16, 2), (8, 6, 6, 8, 24, 1), (8, 10, 6, 16, 16, 2),
    (12, 8, 8, 8, 16, 2),
]


@pytest.mark.parametrize("n,h,w,cin,c,stride", PROJ_GEOMETRIES)
def test_fused_projection_block_matches_flax(rng, n, h, w, cin, c, stride):
    args = _proj_args(rng, n, h, w, cin, c)
    assert pallas_conv.supports_block(n, h, w, c, stride=stride,
                                      in_channels=cin)
    out_f, m1, v1, m2, v2, mS, vS = pallas_conv.fused_projection_block(
        *args, stride=stride, interpret=True
    )
    out_r, mut = _flax_proj_block(*args, stride=stride)
    np.testing.assert_allclose(
        np.asarray(out_f), np.asarray(out_r), rtol=VAL_RTOL, atol=VAL_ATOL
    )
    # all three BNs normalize over the block's OUTPUT grid
    count = n * (h // stride) * (w // stride)
    for bn_name, (m, v) in (
        ("bn1", (m1, v1)), ("bn2", (m2, v2)), ("shortcut_bn", (mS, vS))
    ):
        ra_m, ra_v = running_stats_update(
            jnp.zeros((c,)), jnp.ones((c,)), m, v, count, 0.1
        )
        np.testing.assert_allclose(
            np.asarray(ra_m),
            np.asarray(mut["batch_stats"][bn_name]["mean"]),
            rtol=VAL_RTOL, atol=VAL_ATOL, err_msg=bn_name,
        )
        np.testing.assert_allclose(
            np.asarray(ra_v),
            np.asarray(mut["batch_stats"][bn_name]["var"]),
            rtol=VAL_RTOL, atol=VAL_ATOL, err_msg=bn_name,
        )


@pytest.mark.parametrize("n,h,w,cin,c,stride", PROJ_GEOMETRIES[:2])
def test_fused_projection_block_gradients_match_flax(
    rng, n, h, w, cin, c, stride
):
    args = _proj_args(rng, n, h, w, cin, c)
    argnums = tuple(range(10))

    def loss_fused(*a):
        out = pallas_conv.fused_projection_block(
            *a, stride=stride, interpret=True
        )[0]
        return jnp.sum(out * jnp.cos(out))

    def loss_flax(*a):
        out, _ = _flax_proj_block(*a, stride=stride)
        return jnp.sum(out * jnp.cos(out))

    gf = jax.grad(loss_fused, argnums=argnums)(*args)
    gr = jax.grad(loss_flax, argnums=argnums)(*args)
    names = ("dx", "dk1", "dg1", "db1", "dk2", "dg2", "db2",
             "dks", "dgs", "dbs")
    for name, a, b in zip(names, gf, gr):
        np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), rtol=GRAD_RTOL, atol=GRAD_ATOL,
            err_msg=name,
        )


def _flax_bottleneck(x, k1, g1, b1, k2, g2, b2, k3, g3, b3, shortcut,
                     stride):
    """The production Bottleneck (expansion 4) in train mode; ``shortcut``
    is (ks, gs, bs) for projection sites, None for identity."""
    pln = k1.shape[3]
    c4 = 4 * pln
    mod = Bottleneck(planes=pln, stride=stride)
    params = {
        "Conv_0": {"kernel": k1}, "bn1": {"scale": g1, "bias": b1},
        "Conv_1": {"kernel": k2}, "bn2": {"scale": g2, "bias": b2},
        "Conv_2": {"kernel": k3}, "bn3": {"scale": g3, "bias": b3},
    }
    stats = {
        "bn1": {"mean": jnp.zeros((pln,)), "var": jnp.ones((pln,))},
        "bn2": {"mean": jnp.zeros((pln,)), "var": jnp.ones((pln,))},
        "bn3": {"mean": jnp.zeros((c4,)), "var": jnp.ones((c4,))},
    }
    if shortcut is not None:
        ks, gs, bs = shortcut
        params["shortcut_conv"] = {"kernel": ks}
        params["shortcut_bn"] = {"scale": gs, "bias": bs}
        stats["shortcut_bn"] = {
            "mean": jnp.zeros((c4,)), "var": jnp.ones((c4,))
        }
    return mod.apply(
        {"params": params, "batch_stats": stats}, x, True,
        mutable=["batch_stats"],
    )


def _bottleneck_args(rng, n, h, w, cin, planes, proj):
    def arr(*shape, scale=1.0, shift=0.0):
        return jnp.asarray(
            rng.standard_normal(shape).astype(np.float32) * scale + shift
        )

    c4 = 4 * planes
    args = (
        arr(n, h, w, cin),
        arr(1, 1, cin, planes, scale=0.3),
        arr(planes, shift=1.0), arr(planes, scale=0.1),
        arr(3, 3, planes, planes, scale=0.2),
        arr(planes, shift=1.0), arr(planes, scale=0.1),
        arr(1, 1, planes, c4, scale=0.3),
        arr(c4, shift=1.0), arr(c4, scale=0.1),
    )
    if proj:
        args += (arr(1, 1, cin, c4, scale=0.3),
                 arr(c4, shift=1.0), arr(c4, scale=0.1))
    return args


# identity (in == 4*planes, stride 1), stride-2 projection, stride-1
# channel-change projection on a non-square grid
BOTTLENECK_GEOMETRIES = [
    (8, 8, 8, 32, 8, 1), (8, 8, 8, 16, 8, 2), (8, 10, 6, 16, 8, 1),
]


@pytest.mark.parametrize("n,h,w,cin,planes,stride", BOTTLENECK_GEOMETRIES)
def test_fused_bottleneck_block_matches_flax(
    rng, n, h, w, cin, planes, stride
):
    c4 = 4 * planes
    proj = stride != 1 or cin != c4
    args = _bottleneck_args(rng, n, h, w, cin, planes, proj)
    assert pallas_conv.supports_bottleneck(
        n, h, w, planes, stride=stride, in_channels=cin
    )
    sc = args[10:] if proj else None
    r = pallas_conv.fused_bottleneck_block(
        *args[:10], sc, stride=stride, interpret=True
    )
    out_r, mut = _flax_bottleneck(*args[:10], sc, stride=stride)
    np.testing.assert_allclose(
        np.asarray(r[0]), np.asarray(out_r), rtol=VAL_RTOL, atol=VAL_ATOL
    )
    # bn1 reduces over the INPUT grid (the 1x1 runs pre-stride);
    # bn2/bn3/shortcut_bn over the strided output grid
    count1 = n * h * w
    count2 = n * (h // stride) * (w // stride)
    moments = [("bn1", r[1], r[2], planes, count1),
               ("bn2", r[3], r[4], planes, count2),
               ("bn3", r[5], r[6], c4, count2)]
    if proj:
        moments.append(("shortcut_bn", r[7], r[8], c4, count2))
    for bn_name, m, v, cc, count in moments:
        ra_m, ra_v = running_stats_update(
            jnp.zeros((cc,)), jnp.ones((cc,)), m, v, count, 0.1
        )
        np.testing.assert_allclose(
            np.asarray(ra_m),
            np.asarray(mut["batch_stats"][bn_name]["mean"]),
            rtol=VAL_RTOL, atol=VAL_ATOL, err_msg=bn_name,
        )
        np.testing.assert_allclose(
            np.asarray(ra_v),
            np.asarray(mut["batch_stats"][bn_name]["var"]),
            rtol=VAL_RTOL, atol=VAL_ATOL, err_msg=bn_name,
        )


@pytest.mark.parametrize("n,h,w,cin,planes,stride", BOTTLENECK_GEOMETRIES[:2])
def test_fused_bottleneck_block_gradients_match_flax(
    rng, n, h, w, cin, planes, stride
):
    c4 = 4 * planes
    proj = stride != 1 or cin != c4
    args = _bottleneck_args(rng, n, h, w, cin, planes, proj)
    argnums = tuple(range(len(args)))

    def loss_fused(*a):
        sc = a[10:] if proj else None
        out = pallas_conv.fused_bottleneck_block(
            *a[:10], sc, stride=stride, interpret=True
        )[0]
        return jnp.sum(out * jnp.cos(out))

    def loss_flax(*a):
        sc = a[10:] if proj else None
        out, _ = _flax_bottleneck(*a[:10], sc, stride=stride)
        return jnp.sum(out * jnp.cos(out))

    gf = jax.grad(loss_fused, argnums=argnums)(*args)
    gr = jax.grad(loss_flax, argnums=argnums)(*args)
    names = ["dx", "dk1", "dg1", "db1", "dk2", "dg2", "db2",
             "dk3", "dg3", "db3"]
    if proj:
        names += ["dks", "dgs", "dbs"]
    for name, a, b in zip(names, gf, gr):
        np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), rtol=GRAD_RTOL, atol=GRAD_ATOL,
            err_msg=name,
        )


def test_fused_stem_bf16_matches_fp32_reference(rng):
    """The bf16 stem kernel vs the fp32 Flax reference at the derived
    tolerances: MXU matmuls take bf16 inputs but accumulate fp32, and the
    BN statistics stay fp32 — so agreement is bf16-roundoff-bounded, not
    bitwise."""
    n, h, w, cin, cout = 16, 8, 8, 8, 16
    x = jnp.asarray(rng.standard_normal((n, h, w, cin)).astype(np.float32))
    k = jnp.asarray(
        rng.standard_normal((3, 3, cin, cout)).astype(np.float32) * 0.2
    )
    g = jnp.asarray(rng.standard_normal((cout,)).astype(np.float32) + 1.0)
    b = jnp.asarray(rng.standard_normal((cout,)).astype(np.float32) * 0.1)
    assert pallas_conv.supports_stem(n, h, w, cin, cout, dtype=jnp.bfloat16)

    xb = x.astype(jnp.bfloat16)
    out_f, m, v = pallas_conv.fused_conv_bn_relu(xb, k, g, b, interpret=True)
    assert out_f.dtype == jnp.bfloat16
    # BN moments accumulate and emit fp32 regardless of compute dtype
    assert m.dtype == jnp.float32 and v.dtype == jnp.float32
    out_r, mut = _flax_stem(x, k, g, b)
    _assert_close_bf16(out_f, out_r, kind="value", name="out")
    ra_m, ra_v = running_stats_update(
        jnp.zeros((cout,)), jnp.ones((cout,)), m, v, n * h * w, 0.1
    )
    _assert_close_bf16(ra_m, mut["batch_stats"]["bn"]["mean"],
                       kind="stats", name="mean")
    _assert_close_bf16(ra_v, mut["batch_stats"]["bn"]["var"],
                       kind="stats", name="var")

    def loss_fused(*a):
        out, _, _ = pallas_conv.fused_conv_bn_relu(
            a[0].astype(jnp.bfloat16), *a[1:], interpret=True
        )
        return jnp.sum(out.astype(jnp.float32) * jnp.cos(out))

    def loss_flax(*a):
        out, _ = _flax_stem(*a)
        return jnp.sum(out * jnp.cos(out))

    gf = jax.grad(loss_fused, argnums=(0, 1, 2, 3))(x, k, g, b)
    gr = jax.grad(loss_flax, argnums=(0, 1, 2, 3))(x, k, g, b)
    for name, a, bb in zip(("dx", "dk", "dg", "db"), gf, gr):
        _assert_close_bf16(a, bb, kind="grad", name=name)


@pytest.mark.parametrize("n,h,w,c", [(16, 8, 8, 8), (8, 10, 6, 16)])
def test_fused_basic_block_bf16_matches_fp32_reference(rng, n, h, w, c):
    args = _block_args(rng, n, h, w, c)
    assert pallas_conv.supports_block(n, h, w, c, dtype=jnp.bfloat16)
    r = pallas_conv.fused_basic_block(
        args[0].astype(jnp.bfloat16), *args[1:], interpret=True
    )
    assert r[0].dtype == jnp.bfloat16
    out_r, mut = _flax_block(*args)
    _assert_close_bf16(r[0], out_r, kind="value", name="out")
    count = n * h * w
    for bn_name, (m, v) in (("bn1", (r[1], r[2])), ("bn2", (r[3], r[4]))):
        ra_m, ra_v = running_stats_update(
            jnp.zeros((c,)), jnp.ones((c,)), m, v, count, 0.1
        )
        _assert_close_bf16(ra_m, mut["batch_stats"][bn_name]["mean"],
                           kind="stats", name=bn_name)
        _assert_close_bf16(ra_v, mut["batch_stats"][bn_name]["var"],
                           kind="stats", name=bn_name)

    def loss_fused(*a):
        out = pallas_conv.fused_basic_block(
            a[0].astype(jnp.bfloat16), *a[1:], interpret=True
        )[0]
        return jnp.sum(out.astype(jnp.float32) * jnp.cos(out))

    def loss_flax(*a):
        out, _ = _flax_block(*a)
        return jnp.sum(out * jnp.cos(out))

    gf = jax.grad(loss_fused, argnums=tuple(range(7)))(*args)
    gr = jax.grad(loss_flax, argnums=tuple(range(7)))(*args)
    for name, a, b in zip(
        ("dx", "dk1", "dg1", "db1", "dk2", "dg2", "db2"), gf, gr
    ):
        _assert_close_bf16(a, b, kind="grad", name=name)


@pytest.mark.parametrize("n,h,w,cin,c,stride",
                         [(16, 8, 8, 8, 16, 2), (8, 6, 6, 8, 24, 1)])
def test_fused_projection_block_bf16_matches_fp32_reference(
    rng, n, h, w, cin, c, stride
):
    args = _proj_args(rng, n, h, w, cin, c)
    assert pallas_conv.supports_block(
        n, h, w, c, stride=stride, in_channels=cin, dtype=jnp.bfloat16
    )
    r = pallas_conv.fused_projection_block(
        args[0].astype(jnp.bfloat16), *args[1:], stride=stride,
        interpret=True,
    )
    out_r, mut = _flax_proj_block(*args, stride=stride)
    _assert_close_bf16(r[0], out_r, kind="value", name="out")
    count = n * (h // stride) * (w // stride)
    for bn_name, (m, v) in (
        ("bn1", (r[1], r[2])), ("bn2", (r[3], r[4])),
        ("shortcut_bn", (r[5], r[6])),
    ):
        ra_m, ra_v = running_stats_update(
            jnp.zeros((c,)), jnp.ones((c,)), m, v, count, 0.1
        )
        _assert_close_bf16(ra_m, mut["batch_stats"][bn_name]["mean"],
                           kind="stats", name=bn_name)
        _assert_close_bf16(ra_v, mut["batch_stats"][bn_name]["var"],
                           kind="stats", name=bn_name)

    argnums = tuple(range(10))

    def loss_fused(*a):
        out = pallas_conv.fused_projection_block(
            a[0].astype(jnp.bfloat16), *a[1:], stride=stride, interpret=True
        )[0]
        return jnp.sum(out.astype(jnp.float32) * jnp.cos(out))

    def loss_flax(*a):
        out, _ = _flax_proj_block(*a, stride=stride)
        return jnp.sum(out * jnp.cos(out))

    gf = jax.grad(loss_fused, argnums=argnums)(*args)
    gr = jax.grad(loss_flax, argnums=argnums)(*args)
    names = ("dx", "dk1", "dg1", "db1", "dk2", "dg2", "db2",
             "dks", "dgs", "dbs")
    for name, a, b in zip(names, gf, gr):
        _assert_close_bf16(a, b, kind="grad", name=name)


@pytest.mark.parametrize("n,h,w,cin,planes,stride",
                         [(8, 8, 8, 32, 8, 1), (8, 8, 8, 16, 8, 2)])
def test_fused_bottleneck_block_bf16_matches_fp32_reference(
    rng, n, h, w, cin, planes, stride
):
    c4 = 4 * planes
    proj = stride != 1 or cin != c4
    args = _bottleneck_args(rng, n, h, w, cin, planes, proj)
    assert pallas_conv.supports_bottleneck(
        n, h, w, planes, stride=stride, in_channels=cin, dtype=jnp.bfloat16
    )
    sc = args[10:] if proj else None
    r = pallas_conv.fused_bottleneck_block(
        args[0].astype(jnp.bfloat16), *args[1:10], sc, stride=stride,
        interpret=True,
    )
    out_r, mut = _flax_bottleneck(*args[:10], sc, stride=stride)
    _assert_close_bf16(r[0], out_r, kind="value", name="out")
    count1 = n * h * w
    count2 = n * (h // stride) * (w // stride)
    moments = [("bn1", r[1], r[2], planes, count1),
               ("bn2", r[3], r[4], planes, count2),
               ("bn3", r[5], r[6], c4, count2)]
    if proj:
        moments.append(("shortcut_bn", r[7], r[8], c4, count2))
    for bn_name, m, v, cc, count in moments:
        ra_m, ra_v = running_stats_update(
            jnp.zeros((cc,)), jnp.ones((cc,)), m, v, count, 0.1
        )
        _assert_close_bf16(ra_m, mut["batch_stats"][bn_name]["mean"],
                           kind="stats", name=bn_name)
        _assert_close_bf16(ra_v, mut["batch_stats"][bn_name]["var"],
                           kind="stats", name=bn_name)

    argnums = tuple(range(len(args)))

    def loss_fused(*a):
        sc = a[10:] if proj else None
        out = pallas_conv.fused_bottleneck_block(
            a[0].astype(jnp.bfloat16), *a[1:10], sc, stride=stride,
            interpret=True,
        )[0]
        return jnp.sum(out.astype(jnp.float32) * jnp.cos(out))

    def loss_flax(*a):
        sc = a[10:] if proj else None
        out, _ = _flax_bottleneck(*a[:10], sc, stride=stride)
        return jnp.sum(out * jnp.cos(out))

    gf = jax.grad(loss_fused, argnums=argnums)(*args)
    gr = jax.grad(loss_flax, argnums=argnums)(*args)
    names = ["dx", "dk1", "dg1", "db1", "dk2", "dg2", "db2",
             "dk3", "dg3", "db3"]
    if proj:
        names += ["dks", "dgs", "dbs"]
    for name, a, b in zip(names, gf, gr):
        _assert_close_bf16(a, b, kind="grad", name=name)


def test_supports_gates():
    # stride-2 / channel-changing sites are admitted since round 19 (the
    # projection-shortcut kernel) — the round-15 inversions, inverted
    assert pallas_conv.supports_block(16, 8, 8, 16, stride=2, in_channels=8)
    assert pallas_conv.supports_block(16, 8, 8, 16, in_channels=8)
    # ... but stride 2 requires EVEN input dims (the dilated
    # transposed-conv backward assumes ho == h // 2 exactly), per axis
    assert not pallas_conv.supports_block(16, 9, 8, 16, stride=2,
                                          in_channels=8)
    assert not pallas_conv.supports_block(16, 8, 9, 16, stride=2,
                                          in_channels=8)
    # stride-1 odd dims stay admitted (no such constraint)
    assert pallas_conv.supports_block(8, 9, 9, 8)
    # degenerate spatial dims (3x3 window needs h,w >= 3)
    assert not pallas_conv.supports_block(16, 2, 2, 8)
    # VMEM blowout: stage-4-like 512 channels (weights + dW accumulators
    # alone exceed the budget)
    assert not pallas_conv.supports_block(8, 16, 16, 512)
    # admitted classes
    assert pallas_conv.supports_block(512, 32, 32, 64)   # rn18 stage 1 @ B=256
    assert pallas_conv.supports_block(512, 16, 16, 128)  # rn18 stage 2 @ B=256
    assert pallas_conv.supports_stem(512, 32, 32, 3, 64)
    # Bottleneck gate: rn50 stage-1 identity and stage-leading projection
    assert pallas_conv.supports_bottleneck(512, 32, 32, 64, in_channels=256)
    assert pallas_conv.supports_bottleneck(
        512, 32, 32, 64, stride=1, in_channels=64  # layer1_block0
    )
    assert not pallas_conv.supports_bottleneck(
        512, 33, 32, 64, stride=2, in_channels=64  # odd dim at stride 2
    )
    assert not pallas_conv.supports_bottleneck(
        512, 32, 32, 128, stride=2, in_channels=256  # VMEM: rn50 layer2_block0
    )
    # compute dtype is part of the admission key: bf16 halves the VMEM
    # footprint, admitting sites fp32 rejects...
    assert not pallas_conv.supports_block(
        512, 16, 16, 256, stride=2, in_channels=128
    )
    assert pallas_conv.supports_block(
        512, 16, 16, 256, stride=2, in_channels=128, dtype=jnp.bfloat16
    )
    # ...and any dtype outside {fp32, bf16} is rejected outright
    assert not pallas_conv.supports_block(16, 8, 8, 8, dtype=jnp.float16)
    assert not pallas_conv.supports_stem(16, 8, 8, 3, 16, dtype=jnp.float16)
    assert not pallas_conv.supports_bottleneck(
        16, 8, 8, 8, in_channels=32, dtype=jnp.float16
    )


def test_direct_call_rejects_inadmissible_geometry():
    with pytest.raises(ValueError, match="supports_block"):
        # stride/in_channels admissible but VMEM-inadmissible channels
        pallas_conv.fused_basic_block(
            jnp.zeros((8, 16, 16, 512)), jnp.zeros((3, 3, 512, 512)),
            jnp.ones((512,)), jnp.zeros((512,)),
            jnp.zeros((3, 3, 512, 512)), jnp.ones((512,)),
            jnp.zeros((512,)), interpret=True,
        )
    c = 8
    proj_args = (
        jnp.zeros((8, 8, 8, c)), jnp.zeros((3, 3, c, c)),
        jnp.ones((c,)), jnp.zeros((c,)), jnp.zeros((3, 3, c, c)),
        jnp.ones((c,)), jnp.zeros((c,)), jnp.zeros((1, 1, c, c)),
        jnp.ones((c,)), jnp.zeros((c,)),
    )
    with pytest.raises(ValueError, match="identity"):
        # an identity-geometry site must use fused_basic_block, not the
        # projection kernel (the shortcut conv would change the math)
        pallas_conv.fused_projection_block(
            *proj_args, stride=1, interpret=True
        )
    bot_args = (
        jnp.zeros((8, 8, 8, 32)), jnp.zeros((1, 1, 32, 8)),
        jnp.ones((8,)), jnp.zeros((8,)), jnp.zeros((3, 3, 8, 8)),
        jnp.ones((8,)), jnp.zeros((8,)), jnp.zeros((1, 1, 8, 32)),
        jnp.ones((32,)), jnp.zeros((32,)),
    )
    with pytest.raises(ValueError, match="shortcut"):
        # identity geometry (in == 4*planes, stride 1) with a shortcut
        # supplied: the static proj flag must match the geometry
        pallas_conv.fused_bottleneck_block(
            *bot_args,
            (jnp.zeros((1, 1, 32, 32)), jnp.ones((32,)), jnp.zeros((32,))),
            stride=1, interpret=True,
        )


# ---------------------------------------------------------------- module


def _models(**kw):
    mx = SupConResNet(model_name="resnet10", head="mlp", feat_dim=16, **kw)
    mp = SupConResNet(
        model_name="resnet10", head="mlp", feat_dim=16, conv_impl="pallas",
        **kw,
    )
    return mx, mp


@pytest.mark.parametrize("model_name", ["resnet10", "resnet50"])
def test_encoder_param_trees_impl_independent(model_name):
    """Init under both impls yields IDENTICAL trees (structure and values):
    the checkpoint contract that lets --conv_impl swap across restores —
    for the BasicBlock family AND the Bottleneck family (whose pallas
    branch shadows three convs + three BNs + the projection shortcut)."""
    kw = dict(model_name=model_name, head="mlp", feat_dim=16)
    mx = SupConResNet(**kw)
    mp = SupConResNet(conv_impl="pallas", **kw)
    vx = mx.init(jax.random.key(0), jnp.zeros((2, 8, 8, 3)), train=True)
    vp = mp.init(jax.random.key(0), jnp.zeros((2, 8, 8, 3)), train=True)
    jax.tree.map(
        lambda a, b: np.testing.assert_array_equal(
            np.asarray(a), np.asarray(b)
        ),
        vx, vp,
    )


def test_encoder_pallas_matches_xla_fwd_grads_stats(rng):
    mx, mp = _models()
    x = jnp.asarray(rng.standard_normal((8, 8, 8, 3)).astype(np.float32))
    v = mx.init(jax.random.key(0), jnp.zeros((2, 8, 8, 3)), train=True)

    def run(m):
        return m.apply(v, x, train=True, mutable=["batch_stats"])

    ox, mutx = run(mx)
    op, mutp = run(mp)
    np.testing.assert_allclose(
        np.asarray(ox), np.asarray(op), rtol=1e-4, atol=1e-4
    )
    jax.tree.map(
        lambda a, b: np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), rtol=1e-4, atol=1e-4
        ),
        mutx["batch_stats"], mutp["batch_stats"],
    )

    def loss(params, m):
        out, _ = m.apply(
            {"params": params, "batch_stats": v["batch_stats"]},
            x, train=True, mutable=["batch_stats"],
        )
        return jnp.sum(out * jnp.cos(out))

    gx = jax.grad(loss)(v["params"], mx)
    gp = jax.grad(loss)(v["params"], mp)
    jax.tree.map(
        lambda a, b: np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), rtol=1e-3, atol=1e-3
        ),
        gx, gp,
    )


def test_encoder_eval_mode_stays_bitwise_xla(rng):
    """train=False never touches the fused path: bitwise-identical output
    (the validation/probe encode path keeps its pinned numerics)."""
    mx, mp = _models()
    x = jnp.asarray(rng.standard_normal((8, 8, 8, 3)).astype(np.float32))
    v = mx.init(jax.random.key(0), jnp.zeros((2, 8, 8, 3)), train=True)
    ex = mx.apply(v, x, train=False)
    ep = mp.apply(v, x, train=False)
    np.testing.assert_array_equal(np.asarray(ex), np.asarray(ep))


def test_unsupported_sites_fall_back_without_touching_kernels(
    rng, monkeypatch
):
    """Non-admitted compute dtypes (anything outside {fp32, bf16}) and
    eval mode must never call into ops/pallas_conv — proven by poisoning
    ALL FOUR fused entry points (stem, identity block, projection block,
    Bottleneck)."""

    def boom(*a, **k):
        raise AssertionError("fused kernel called on an unsupported path")

    for entry in ("fused_basic_block", "fused_projection_block",
                  "fused_bottleneck_block", "fused_conv_bn_relu"):
        monkeypatch.setattr(pallas_conv, entry, boom)
    x = jnp.asarray(rng.standard_normal((8, 8, 8, 3)).astype(np.float32))
    # fp16 is not an admitted compute dtype: every site falls back to XLA
    # (bf16 IS admitted since round 19 — covered by the bf16 parity tests)
    m_fp16 = SupConResNet(
        model_name="resnet10", head="mlp", feat_dim=16,
        conv_impl="pallas", dtype=jnp.float16,
    )
    v = m_fp16.init(jax.random.key(0), jnp.zeros((2, 8, 8, 3)), train=True)
    m_fp16.apply(v, x, train=True, mutable=["batch_stats"])  # xla fallback
    # same through a Bottleneck model (the new shadow modules)
    m50 = SupConResNet(
        model_name="resnet50", head="mlp", feat_dim=16,
        conv_impl="pallas", dtype=jnp.float16,
    )
    v50 = m50.init(jax.random.key(0), jnp.zeros((2, 8, 8, 3)), train=True)
    m50.apply(v50, x, train=True, mutable=["batch_stats"])  # xla fallback
    mx, mp = _models()
    v = mx.init(jax.random.key(0), jnp.zeros((2, 8, 8, 3)), train=True)
    mp.apply(v, x, train=False)  # eval: fused path must stay untouched


# ------------------------------------------------------------- resolution


def test_resolve_conv_impl_ladder(monkeypatch):
    from simclr_pytorch_distributed_tpu.train import supcon

    # explicit xla: honored anywhere
    impl, reason = supcon.resolve_conv_impl("xla", "resnet18", 256, 32, 1)
    assert impl == "xla" and "explicit" in reason
    # auto on CPU: degrades with the backend named
    impl, reason = supcon.resolve_conv_impl("auto", "resnet18", 256, 32, 1)
    assert impl == "xla" and "non-TPU" in reason
    # auto on TPU single chip: pallas, reason names the fused sites and
    # the compute dtype
    monkeypatch.setattr(supcon.jax, "default_backend", lambda: "tpu")
    impl, reason = supcon.resolve_conv_impl("auto", "resnet18", 256, 32, 1)
    assert impl == "pallas"
    assert "layer1_block0" in reason and "stem" in reason
    assert "fp32" in reason
    # auto multi-device: xla with the mesh named
    impl, reason = supcon.resolve_conv_impl("auto", "resnet18", 256, 32, 8)
    assert impl == "xla" and "multi-device" in reason
    # auto + bf16: pallas since round 19 (the bf16 kernel variants), with
    # the dtype on record and the wider bf16 admission visible
    impl, reason = supcon.resolve_conv_impl(
        "auto", "resnet18", 256, 32, 1, bf16=True
    )
    assert impl == "pallas" and "bf16" in reason
    assert "layer3_block0" in reason  # bf16-only site (half the VMEM)
    # explicit pallas + bf16: honored, the round-15 raise inverted
    impl, reason = supcon.resolve_conv_impl(
        "pallas", "resnet18", 256, 32, 1, bf16=True
    )
    assert impl == "pallas" and "bf16" in reason
    # rn50 resolves too (the Bottleneck kernel): no more stem-only edge
    impl, reason = supcon.resolve_conv_impl("pallas", "resnet50", 256, 32, 1)
    assert impl == "pallas" and "bottleneck" in reason
    # explicit pallas: still honored-or-raise on real contradictions
    with pytest.raises(ValueError, match="single-device"):
        supcon.resolve_conv_impl("pallas", "resnet18", 256, 32, 8)
    with pytest.raises(ValueError, match="admits no site"):
        # a geometry with zero admitted sites still raises, naming the
        # dtype it resolved under
        supcon.resolve_conv_impl("pallas", "resnet18", 2, 2, 1)


def test_conv_fused_sites_geometry_walk():
    from simclr_pytorch_distributed_tpu.train import supcon

    sites = supcon.conv_fused_sites("resnet18", 512, 32)
    # stage 1 fully fused INCLUDING the stage-2 stride-2 projection lead
    # (admitted since round 19); VMEM-inadmissible late stages excluded
    assert "stem 3->64@32x32" in sites
    assert "layer1_block0[basic] 64->64@32x32/s1" in sites
    assert "layer2_block0[proj] 64->128@32x32/s2" in sites
    assert "layer2_block1[basic] 128->128@16x16/s1" in sites
    assert not any("layer3" in s or "layer4" in s for s in sites)
    # bf16 halves the per-site VMEM footprint: strictly more sites
    bf16_sites = supcon.conv_fused_sites(
        "resnet18", 512, 32, dtype=jnp.bfloat16
    )
    assert set(sites) < set(bf16_sites)
    assert "layer3_block0[proj] 128->256@16x16/s2" in bf16_sites
    # bottleneck models fuse real blocks now (round-15's stem-only edge
    # closed); the VMEM-rejected stride-2 stage-2 lead stays excluded
    r50 = supcon.conv_fused_sites("resnet50", 512, 32)
    assert "layer1_block0[bottleneck] 64->256@32x32/s1" in r50
    assert "layer2_block1[bottleneck] 512->512@16x16/s1" in r50
    assert not any("layer2_block0" in s for s in r50)
    # odd sizes: the walker halves like the stride-2 conv itself does
    # (ceil(h/2) under (1,1) padding), so the banner/raise geometry can
    # never diverge from the model's own per-site gates; odd-dim stride-2
    # sites themselves are NOT admitted (the kernels' even-dims rule)
    odd = supcon.conv_fused_sites("resnet18", 32, 33)
    assert "layer2_block1[basic] 128->128@17x17/s1" in odd
    assert not any("/s2" in s for s in odd)


def test_fused_site_plan_single_sources_the_walk():
    """The plan IS the geometry contract: every site row carries the block
    INPUT dims its admission was judged at, and re-consulting the
    supports_* gates with those dims reproduces the verdict — banner,
    module gate, and kernel wrapper can never disagree."""
    for model, dtype in (("resnet18", jnp.float32),
                         ("resnet50", jnp.bfloat16)):
        plan = fused_site_plan(model, 512, 32, dtype=dtype)
        assert plan[0]["kind"] == "stem"
        # one row per potential site: stem + every residual block
        from simclr_pytorch_distributed_tpu.models.resnet import MODEL_DICT

        n_blocks = sum(MODEL_DICT[model][0]().stage_sizes)
        assert len(plan) == 1 + n_blocks
        for site in plan[1:]:
            if site["kind"] == "bottleneck":
                regate = pallas_conv.supports_bottleneck(
                    512, site["h"], site["w"], site["width"],
                    stride=site["stride"], in_channels=site["in_channels"],
                    dtype=dtype,
                )
            else:
                regate = pallas_conv.supports_block(
                    512, site["h"], site["w"], site["width"],
                    stride=site["stride"], in_channels=site["in_channels"],
                    dtype=dtype,
                )
            assert site["admitted"] == regate, site["desc"]
            # identity vs projection dispatch keys on the same fields the
            # module branch reads
            if site["kind"] == "basic":
                assert site["stride"] == 1
                assert site["in_channels"] == site["width"]
            elif site["kind"] == "proj":
                assert site["stride"] != 1 or \
                    site["in_channels"] != site["width"]


def test_resolve_loss_impl_reasoned_names_degradations(monkeypatch):
    from simclr_pytorch_distributed_tpu.train import supcon

    impl, reason = supcon.resolve_loss_impl_reasoned("auto", 256, 1)
    assert impl == "dense" and "non-TPU" in reason
    impl, reason = supcon.resolve_loss_impl_reasoned("dense", 256, 1)
    assert impl == "dense" and reason == "explicit request"
    impl, reason = supcon.resolve_loss_impl_reasoned(
        "auto", 256, 1, moco_queue=512
    )
    assert impl == "dense" and "moco_queue" in reason
    monkeypatch.setattr(supcon.jax, "default_backend", lambda: "tpu")
    impl, reason = supcon.resolve_loss_impl_reasoned("auto", 256, 1)
    assert impl == "fused" and "single-chip" in reason
    impl, reason = supcon.resolve_loss_impl_reasoned("auto", 3, 1)
    assert impl == "dense" and "tile" in reason


def test_impl_resolution_banner_format():
    line = config_lib.impl_resolution_banner(
        "conv_impl", "auto", "xla", "non-TPU backend (cpu)"
    )
    assert line == (
        "[conv_impl] requested 'auto' -> resolved 'xla': non-TPU backend (cpu)"
    )
    same = config_lib.impl_resolution_banner(
        "conv_impl", "xla", "xla", "explicit request"
    )
    assert same == "[conv_impl] 'xla': explicit request"


def test_build_logs_resolution_banners(tmp_path, caplog):
    import logging

    from simclr_pytorch_distributed_tpu.train.supcon import build

    cfg = config_lib.SupConConfig(
        model="resnet10", dataset="synthetic", batch_size=8, epochs=1,
        size=8, workdir=str(tmp_path),
    )
    cfg = config_lib.finalize_supcon(cfg, make_dirs=False)
    with caplog.at_level(logging.INFO):
        build(cfg, steps_per_epoch=4, n_devices=1)
    text = caplog.text
    assert "[conv_impl]" in text and "[loss_impl]" in text


def test_validate_conv_impl_admits_pallas_bf16():
    """The round-15 parse-time pallas+bf16 rejection is GONE: admission is
    per-site at resolution time (resolve_conv_impl), where the actual
    geometry and backend are known. The seam stays callable and silent."""
    config_lib.validate_conv_impl(
        config_lib.SupConConfig(conv_impl="pallas", bf16=True)
    )
    config_lib.validate_conv_impl(
        config_lib.SupConConfig(conv_impl="auto", bf16=True)
    )


def test_parser_accepts_conv_impl():
    p = config_lib.supcon_parser()
    ns = p.parse_args(["--conv_impl", "pallas"])
    assert ns.conv_impl == "pallas"
    assert p.parse_args([]).conv_impl == "auto"


def test_pallas_bf16_parses_and_finalizes(tmp_path):
    """--conv_impl pallas --bf16 survives the full parse->finalize
    pipeline (the round-15 parse-time rejection, inverted): admission is
    resolution-time now."""
    cfg = config_lib.SupConConfig(
        model="resnet18", dataset="synthetic", conv_impl="pallas",
        bf16=True, workdir=str(tmp_path),
    )
    out = config_lib.finalize_supcon(cfg, make_dirs=False)
    assert out.conv_impl == "pallas" and out.bf16


def test_fused_train_bn_running_update_matches_norm():
    """FusedTrainBN's second call applies EXACTLY the norm.py running
    update (single-sourced via running_stats_update)."""
    bn = FusedTrainBN(4)
    v = bn.init(jax.random.key(0))
    m = jnp.asarray([1.0, 2.0, 3.0, 4.0])
    var = jnp.asarray([0.5, 1.5, 2.5, 3.5])
    (scale, bias), mut = bn.apply(v, m, var, 100, mutable=["batch_stats"])
    exp_m, exp_v = running_stats_update(
        jnp.zeros((4,)), jnp.ones((4,)), m, var, 100, 0.1
    )
    np.testing.assert_allclose(np.asarray(mut["batch_stats"]["mean"]), exp_m)
    np.testing.assert_allclose(np.asarray(mut["batch_stats"]["var"]), exp_v)
    np.testing.assert_array_equal(np.asarray(scale), np.ones(4))
    np.testing.assert_array_equal(np.asarray(bias), np.zeros(4))


# ----------------------------------------------------- real-driver smoke


@pytest.mark.parametrize("model,bf16", [("resnet10", False),
                                        ("resnet50", True)])
def test_driver_pallas_checkpoint_restores_under_xla(
    tmp_path, monkeypatch, model, bf16
):
    """2-epoch --conv_impl pallas pretrain through the REAL driver, then a
    resume under --conv_impl xla: the param tree is impl-independent, so
    the restore continues the trajectory (and the banners name both
    resolutions). Run once for the BasicBlock family in fp32 and once for
    rn50's Bottleneck family on the bf16 arm — the two new round-19
    fused-ladder ends."""
    from simclr_pytorch_distributed_tpu.data import cifar as cifar_lib
    from simclr_pytorch_distributed_tpu.parallel import mesh as mesh_lib
    from simclr_pytorch_distributed_tpu.train import supcon as supcon_driver

    orig = cifar_lib.synthetic_dataset

    def small(n=2048, num_classes=10, seed=0, size=32):
        return orig(n=104, num_classes=num_classes, seed=seed, size=8)

    monkeypatch.setattr(cifar_lib, "synthetic_dataset", small)

    def limited_create_mesh(devices=None, **kw):
        if devices is None:
            devices = jax.devices()[:1]
        return mesh_lib.create_mesh(devices=devices, **kw)

    monkeypatch.setattr(supcon_driver, "create_mesh", limited_create_mesh)

    def cfg_for(conv_impl, epochs, resume=""):
        cfg = config_lib.SupConConfig(
            model=model, dataset="synthetic", batch_size=32, epochs=epochs,
            learning_rate=0.05, temp=0.5, size=8, workdir=str(tmp_path),
            save_freq=1, print_freq=2, seed=0, method="SimCLR",
            conv_impl=conv_impl, resume=resume, health_freq=0, bf16=bf16,
        )
        return config_lib.finalize_supcon(cfg)

    cfg1 = cfg_for("pallas", epochs=2)
    state1 = supcon_driver.run(cfg1)
    steps1 = int(state1.step)
    assert steps1 > 0
    # restore the pallas-written checkpoint under the xla impl
    cfg2 = cfg_for("xla", epochs=3, resume=f"{cfg1.save_folder}/last")
    state2 = supcon_driver.run(cfg2)
    assert int(state2.step) == steps1 // 2 * 3
