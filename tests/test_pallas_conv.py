"""Fused Pallas conv+BN+ReLU kernels vs the Flax oracle (interpret mode).

The fused stem (``fused_conv_bn_relu``) and residual-block
(``fused_basic_block``) kernels (ops/pallas_conv.py) must match the
bitwise-pinned Flax path — ``nn.Conv`` + ``CrossReplicaBatchNorm`` in
whole-batch train mode — in value, in every parameter/input gradient, and
in the batch statistics that feed the running-stat update, across every
geometry class ``supports_*`` admits. Unsupported geometries must fall
back to the XLA path, eval mode must stay bitwise-XLA, and the param tree
must be impl-independent (a ``--conv_impl pallas`` checkpoint restores
under ``--conv_impl xla`` — proven through the real driver below).
"""

import os
import sys

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from flax import linen as nn

from simclr_pytorch_distributed_tpu import config as config_lib
from simclr_pytorch_distributed_tpu.models import SupConResNet
from simclr_pytorch_distributed_tpu.models.norm import (
    CrossReplicaBatchNorm,
    FusedTrainBN,
    running_stats_update,
)
from simclr_pytorch_distributed_tpu.models.resnet import BasicBlock
from simclr_pytorch_distributed_tpu.ops import pallas_conv

pytestmark = pytest.mark.kernel

# Interpret-mode kernels accumulate in a different order than XLA's conv
# emitter; fp32 accumulation noise at these magnitudes measured ~1e-6
# relative (values) / ~3e-5 absolute on O(100) gradient scales. Pinned
# with ~30x margin.
VAL_RTOL, VAL_ATOL = 3e-5, 3e-5
GRAD_RTOL, GRAD_ATOL = 1e-4, 1e-3


def _flax_stem(x, k, g, b):
    """conv3x3/s1 + whole-batch train BN + ReLU via the production
    modules, returning (out, mutated batch_stats)."""

    class Stem(nn.Module):
        @nn.compact
        def __call__(self, xin):
            y = nn.Conv(
                k.shape[3], (3, 3), strides=(1, 1), use_bias=False,
                padding=((1, 1), (1, 1)), param_dtype=jnp.float32,
                name="conv",
            )(xin)
            return nn.relu(
                CrossReplicaBatchNorm(use_running_average=False, name="bn")(y)
            )

    mod = Stem()
    variables = {
        "params": {
            "conv": {"kernel": k},
            "bn": {"scale": g, "bias": b},
        },
        "batch_stats": {
            "bn": {
                "mean": jnp.zeros((k.shape[3],), jnp.float32),
                "var": jnp.ones((k.shape[3],), jnp.float32),
            }
        },
    }
    return mod.apply(variables, x, mutable=["batch_stats"])


def _flax_block(x, k1, g1, b1, k2, g2, b2):
    """The production BasicBlock (identity shortcut) in train mode."""
    mod = BasicBlock(planes=k1.shape[3])
    variables = {
        "params": {
            "Conv_0": {"kernel": k1},
            "bn1": {"scale": g1, "bias": b1},
            "Conv_1": {"kernel": k2},
            "bn2": {"scale": g2, "bias": b2},
        },
        "batch_stats": {
            "bn1": {
                "mean": jnp.zeros((k1.shape[3],), jnp.float32),
                "var": jnp.ones((k1.shape[3],), jnp.float32),
            },
            "bn2": {
                "mean": jnp.zeros((k2.shape[3],), jnp.float32),
                "var": jnp.ones((k2.shape[3],), jnp.float32),
            },
        },
    }
    return mod.apply(variables, x, True, mutable=["batch_stats"])


def _block_args(rng, n, h, w, c):
    def arr(*shape, scale=1.0, shift=0.0):
        return jnp.asarray(
            rng.standard_normal(shape).astype(np.float32) * scale + shift
        )

    return (
        arr(n, h, w, c),
        arr(3, 3, c, c, scale=0.2), arr(c, shift=1.0), arr(c, scale=0.1),
        arr(3, 3, c, c, scale=0.2), arr(c, shift=1.0), arr(c, scale=0.1),
    )


# one geometry per admitted class: square stage-1-like, non-square (h != w),
# tall-channel, and a batch the tile picker must split unevenly (bn=4)
BLOCK_GEOMETRIES = [(16, 8, 8, 8), (8, 10, 6, 16), (16, 4, 4, 24), (12, 8, 8, 8)]


@pytest.mark.parametrize("n,h,w,c", BLOCK_GEOMETRIES)
def test_fused_block_forward_matches_flax(rng, n, h, w, c):
    x, k1, g1, b1, k2, g2, b2 = _block_args(rng, n, h, w, c)
    assert pallas_conv.supports_block(n, h, w, c)
    out_f, m1, v1, m2, v2 = pallas_conv.fused_basic_block(
        x, k1, g1, b1, k2, g2, b2, interpret=True
    )
    out_r, mut = _flax_block(x, k1, g1, b1, k2, g2, b2)
    np.testing.assert_allclose(
        np.asarray(out_f), np.asarray(out_r), rtol=VAL_RTOL, atol=VAL_ATOL
    )
    # batch moments -> the same running-stat update as models/norm.py
    count = n * h * w
    for bn_name, (m, v) in (("bn1", (m1, v1)), ("bn2", (m2, v2))):
        ra_m, ra_v = running_stats_update(
            jnp.zeros((c,)), jnp.ones((c,)), m, v, count, 0.1
        )
        np.testing.assert_allclose(
            np.asarray(ra_m),
            np.asarray(mut["batch_stats"][bn_name]["mean"]),
            rtol=VAL_RTOL, atol=VAL_ATOL,
        )
        np.testing.assert_allclose(
            np.asarray(ra_v),
            np.asarray(mut["batch_stats"][bn_name]["var"]),
            rtol=VAL_RTOL, atol=VAL_ATOL,
        )


@pytest.mark.parametrize("n,h,w,c", BLOCK_GEOMETRIES[:2])
def test_fused_block_gradients_match_flax(rng, n, h, w, c):
    args = _block_args(rng, n, h, w, c)

    def loss_fused(*a):
        out = pallas_conv.fused_basic_block(*a, interpret=True)[0]
        return jnp.sum(out * jnp.cos(out))

    def loss_flax(*a):
        out, _ = _flax_block(*a)
        return jnp.sum(out * jnp.cos(out))

    gf = jax.grad(loss_fused, argnums=tuple(range(7)))(*args)
    gr = jax.grad(loss_flax, argnums=tuple(range(7)))(*args)
    names = ("dx", "dk1", "dg1", "db1", "dk2", "dg2", "db2")
    for name, a, b in zip(names, gf, gr):
        np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), rtol=GRAD_RTOL, atol=GRAD_ATOL,
            err_msg=name,
        )


def test_fused_stem_matches_flax_value_and_grads(rng):
    n, h, w, cin, cout = 8, 8, 8, 3, 16
    x = jnp.asarray(rng.standard_normal((n, h, w, cin)).astype(np.float32))
    k = jnp.asarray(
        rng.standard_normal((3, 3, cin, cout)).astype(np.float32) * 0.2
    )
    g = jnp.asarray(rng.standard_normal((cout,)).astype(np.float32) + 1.0)
    b = jnp.asarray(rng.standard_normal((cout,)).astype(np.float32) * 0.1)
    assert pallas_conv.supports_stem(n, h, w, cin, cout)

    out_f, m, v = pallas_conv.fused_conv_bn_relu(x, k, g, b, interpret=True)
    out_r, mut = _flax_stem(x, k, g, b)
    np.testing.assert_allclose(
        np.asarray(out_f), np.asarray(out_r), rtol=VAL_RTOL, atol=VAL_ATOL
    )
    ra_m, ra_v = running_stats_update(
        jnp.zeros((cout,)), jnp.ones((cout,)), m, v, n * h * w, 0.1
    )
    np.testing.assert_allclose(
        np.asarray(ra_m), np.asarray(mut["batch_stats"]["bn"]["mean"]),
        rtol=VAL_RTOL, atol=VAL_ATOL,
    )
    np.testing.assert_allclose(
        np.asarray(ra_v), np.asarray(mut["batch_stats"]["bn"]["var"]),
        rtol=VAL_RTOL, atol=VAL_ATOL,
    )

    def loss_fused(*a):
        out, _, _ = pallas_conv.fused_conv_bn_relu(*a, interpret=True)
        return jnp.sum(out * jnp.cos(out))

    def loss_flax(*a):
        out, _ = _flax_stem(*a)
        return jnp.sum(out * jnp.cos(out))

    gf = jax.grad(loss_fused, argnums=(0, 1, 2, 3))(x, k, g, b)
    gr = jax.grad(loss_flax, argnums=(0, 1, 2, 3))(x, k, g, b)
    for name, a, bb in zip(("dx", "dk", "dg", "db"), gf, gr):
        np.testing.assert_allclose(
            np.asarray(a), np.asarray(bb), rtol=GRAD_RTOL, atol=GRAD_ATOL,
            err_msg=name,
        )


def test_supports_gates():
    # identity shortcut only
    assert not pallas_conv.supports_block(16, 8, 8, 8, stride=2)
    assert not pallas_conv.supports_block(16, 8, 8, 16, in_channels=8)
    # degenerate spatial dims (3x3 window needs h,w >= 3)
    assert not pallas_conv.supports_block(16, 2, 2, 8)
    # VMEM blowout: stage-4-like 512 channels (weights + dW accumulators
    # alone exceed the budget)
    assert not pallas_conv.supports_block(8, 16, 16, 512)
    # admitted classes
    assert pallas_conv.supports_block(512, 32, 32, 64)   # rn18 stage 1 @ B=256
    assert pallas_conv.supports_block(512, 16, 16, 128)  # rn18 stage 2 @ B=256
    assert pallas_conv.supports_stem(512, 32, 32, 3, 64)


def test_direct_call_rejects_inadmissible_geometry():
    with pytest.raises(ValueError, match="supports_block"):
        # stride/in_channels admissible but VMEM-inadmissible channels
        pallas_conv.fused_basic_block(
            jnp.zeros((8, 16, 16, 512)), jnp.zeros((3, 3, 512, 512)),
            jnp.ones((512,)), jnp.zeros((512,)),
            jnp.zeros((3, 3, 512, 512)), jnp.ones((512,)),
            jnp.zeros((512,)), interpret=True,
        )


# ---------------------------------------------------------------- module


def _models(**kw):
    mx = SupConResNet(model_name="resnet10", head="mlp", feat_dim=16, **kw)
    mp = SupConResNet(
        model_name="resnet10", head="mlp", feat_dim=16, conv_impl="pallas",
        **kw,
    )
    return mx, mp


def test_encoder_param_trees_impl_independent():
    """Init under both impls yields IDENTICAL trees (structure and values):
    the checkpoint contract that lets --conv_impl swap across restores."""
    mx, mp = _models()
    vx = mx.init(jax.random.key(0), jnp.zeros((2, 8, 8, 3)), train=True)
    vp = mp.init(jax.random.key(0), jnp.zeros((2, 8, 8, 3)), train=True)
    jax.tree.map(
        lambda a, b: np.testing.assert_array_equal(
            np.asarray(a), np.asarray(b)
        ),
        vx, vp,
    )


def test_encoder_pallas_matches_xla_fwd_grads_stats(rng):
    mx, mp = _models()
    x = jnp.asarray(rng.standard_normal((8, 8, 8, 3)).astype(np.float32))
    v = mx.init(jax.random.key(0), jnp.zeros((2, 8, 8, 3)), train=True)

    def run(m):
        return m.apply(v, x, train=True, mutable=["batch_stats"])

    ox, mutx = run(mx)
    op, mutp = run(mp)
    np.testing.assert_allclose(
        np.asarray(ox), np.asarray(op), rtol=1e-4, atol=1e-4
    )
    jax.tree.map(
        lambda a, b: np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), rtol=1e-4, atol=1e-4
        ),
        mutx["batch_stats"], mutp["batch_stats"],
    )

    def loss(params, m):
        out, _ = m.apply(
            {"params": params, "batch_stats": v["batch_stats"]},
            x, train=True, mutable=["batch_stats"],
        )
        return jnp.sum(out * jnp.cos(out))

    gx = jax.grad(loss)(v["params"], mx)
    gp = jax.grad(loss)(v["params"], mp)
    jax.tree.map(
        lambda a, b: np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), rtol=1e-3, atol=1e-3
        ),
        gx, gp,
    )


def test_encoder_eval_mode_stays_bitwise_xla(rng):
    """train=False never touches the fused path: bitwise-identical output
    (the validation/probe encode path keeps its pinned numerics)."""
    mx, mp = _models()
    x = jnp.asarray(rng.standard_normal((8, 8, 8, 3)).astype(np.float32))
    v = mx.init(jax.random.key(0), jnp.zeros((2, 8, 8, 3)), train=True)
    ex = mx.apply(v, x, train=False)
    ep = mp.apply(v, x, train=False)
    np.testing.assert_array_equal(np.asarray(ex), np.asarray(ep))


def test_unsupported_sites_fall_back_without_touching_kernels(
    rng, monkeypatch
):
    """bf16 compute admits no fused site: the pallas-impl model must never
    call into ops/pallas_conv (proven by poisoning the kernels), and eval
    mode likewise."""

    def boom(*a, **k):
        raise AssertionError("fused kernel called on an unsupported path")

    monkeypatch.setattr(pallas_conv, "fused_basic_block", boom)
    monkeypatch.setattr(pallas_conv, "fused_conv_bn_relu", boom)
    x = jnp.asarray(rng.standard_normal((8, 8, 8, 3)).astype(np.float32))
    m_bf16 = SupConResNet(
        model_name="resnet10", head="mlp", feat_dim=16,
        conv_impl="pallas", dtype=jnp.bfloat16,
    )
    v = m_bf16.init(jax.random.key(0), jnp.zeros((2, 8, 8, 3)), train=True)
    m_bf16.apply(v, x, train=True, mutable=["batch_stats"])  # xla fallback
    mx, mp = _models()
    v = mx.init(jax.random.key(0), jnp.zeros((2, 8, 8, 3)), train=True)
    mp.apply(v, x, train=False)  # eval: fused path must stay untouched


# ------------------------------------------------------------- resolution


def test_resolve_conv_impl_ladder(monkeypatch):
    from simclr_pytorch_distributed_tpu.train import supcon

    # explicit xla: honored anywhere
    impl, reason = supcon.resolve_conv_impl("xla", "resnet18", 256, 32, 1)
    assert impl == "xla" and "explicit" in reason
    # auto on CPU: degrades with the backend named
    impl, reason = supcon.resolve_conv_impl("auto", "resnet18", 256, 32, 1)
    assert impl == "xla" and "non-TPU" in reason
    # auto on TPU single chip: pallas, reason names the fused sites
    monkeypatch.setattr(supcon.jax, "default_backend", lambda: "tpu")
    impl, reason = supcon.resolve_conv_impl("auto", "resnet18", 256, 32, 1)
    assert impl == "pallas"
    assert "layer1_block0" in reason and "stem" in reason
    # auto multi-device: xla with the mesh named
    impl, reason = supcon.resolve_conv_impl("auto", "resnet18", 256, 32, 8)
    assert impl == "xla" and "multi-device" in reason
    # auto + bf16: xla
    impl, reason = supcon.resolve_conv_impl(
        "auto", "resnet18", 256, 32, 1, bf16=True
    )
    assert impl == "xla" and "bf16" in reason
    # explicit pallas: honored-or-raise
    with pytest.raises(ValueError, match="single-device"):
        supcon.resolve_conv_impl("pallas", "resnet18", 256, 32, 8)
    with pytest.raises(ValueError, match="fp32"):
        supcon.resolve_conv_impl("pallas", "resnet18", 256, 32, 1, bf16=True)


def test_conv_fused_sites_geometry_walk():
    from simclr_pytorch_distributed_tpu.train import supcon

    sites = supcon.conv_fused_sites("resnet18", 512, 32)
    # stage 1 fully fused, stage-2 non-first block at 16x16; stride-2
    # stage-leading blocks and the VMEM-inadmissible late stages excluded
    assert "stem 3->64@32x32" in sites
    assert "layer1_block0 64@32x32" in sites
    assert "layer1_block1 64@32x32" in sites
    assert "layer2_block1 128@16x16" in sites
    assert not any(s.startswith("layer2_block0") for s in sites)
    assert not any(s.startswith("layer4") for s in sites)
    # bottleneck models: stem only (the recorded open edge)
    assert supcon.conv_fused_sites("resnet50", 512, 32) == ["stem 3->64@32x32"]
    # odd sizes: the walker halves like the stride-2 conv itself does
    # (ceil(h/2) under (1,1) padding), so the banner/raise geometry can
    # never diverge from the model's own per-site gates
    odd = supcon.conv_fused_sites("resnet18", 32, 33)
    assert "layer2_block1 128@17x17" in odd


def test_resolve_loss_impl_reasoned_names_degradations(monkeypatch):
    from simclr_pytorch_distributed_tpu.train import supcon

    impl, reason = supcon.resolve_loss_impl_reasoned("auto", 256, 1)
    assert impl == "dense" and "non-TPU" in reason
    impl, reason = supcon.resolve_loss_impl_reasoned("dense", 256, 1)
    assert impl == "dense" and reason == "explicit request"
    impl, reason = supcon.resolve_loss_impl_reasoned(
        "auto", 256, 1, moco_queue=512
    )
    assert impl == "dense" and "moco_queue" in reason
    monkeypatch.setattr(supcon.jax, "default_backend", lambda: "tpu")
    impl, reason = supcon.resolve_loss_impl_reasoned("auto", 256, 1)
    assert impl == "fused" and "single-chip" in reason
    impl, reason = supcon.resolve_loss_impl_reasoned("auto", 3, 1)
    assert impl == "dense" and "tile" in reason


def test_impl_resolution_banner_format():
    line = config_lib.impl_resolution_banner(
        "conv_impl", "auto", "xla", "non-TPU backend (cpu)"
    )
    assert line == (
        "[conv_impl] requested 'auto' -> resolved 'xla': non-TPU backend (cpu)"
    )
    same = config_lib.impl_resolution_banner(
        "conv_impl", "xla", "xla", "explicit request"
    )
    assert same == "[conv_impl] 'xla': explicit request"


def test_build_logs_resolution_banners(tmp_path, caplog):
    import logging

    from simclr_pytorch_distributed_tpu.train.supcon import build

    cfg = config_lib.SupConConfig(
        model="resnet10", dataset="synthetic", batch_size=8, epochs=1,
        size=8, workdir=str(tmp_path),
    )
    cfg = config_lib.finalize_supcon(cfg, make_dirs=False)
    with caplog.at_level(logging.INFO):
        build(cfg, steps_per_epoch=4, n_devices=1)
    text = caplog.text
    assert "[conv_impl]" in text and "[loss_impl]" in text


def test_validate_conv_impl_rejects_pallas_bf16():
    with pytest.raises(ValueError, match="conv_impl pallas"):
        config_lib.validate_conv_impl(
            config_lib.SupConConfig(conv_impl="pallas", bf16=True)
        )
    # auto + bf16 degrades instead (no raise)
    config_lib.validate_conv_impl(
        config_lib.SupConConfig(conv_impl="auto", bf16=True)
    )


def test_parser_accepts_conv_impl():
    p = config_lib.supcon_parser()
    ns = p.parse_args(["--conv_impl", "pallas"])
    assert ns.conv_impl == "pallas"
    assert p.parse_args([]).conv_impl == "auto"


def test_fused_train_bn_running_update_matches_norm():
    """FusedTrainBN's second call applies EXACTLY the norm.py running
    update (single-sourced via running_stats_update)."""
    bn = FusedTrainBN(4)
    v = bn.init(jax.random.key(0))
    m = jnp.asarray([1.0, 2.0, 3.0, 4.0])
    var = jnp.asarray([0.5, 1.5, 2.5, 3.5])
    (scale, bias), mut = bn.apply(v, m, var, 100, mutable=["batch_stats"])
    exp_m, exp_v = running_stats_update(
        jnp.zeros((4,)), jnp.ones((4,)), m, var, 100, 0.1
    )
    np.testing.assert_allclose(np.asarray(mut["batch_stats"]["mean"]), exp_m)
    np.testing.assert_allclose(np.asarray(mut["batch_stats"]["var"]), exp_v)
    np.testing.assert_array_equal(np.asarray(scale), np.ones(4))
    np.testing.assert_array_equal(np.asarray(bias), np.zeros(4))


# ----------------------------------------------------- real-driver smoke


def test_driver_pallas_checkpoint_restores_under_xla(tmp_path, monkeypatch):
    """2-epoch --conv_impl pallas pretrain through the REAL driver, then a
    resume under --conv_impl xla: the param tree is impl-independent, so
    the restore continues the trajectory (and the banners name both
    resolutions)."""
    from simclr_pytorch_distributed_tpu.data import cifar as cifar_lib
    from simclr_pytorch_distributed_tpu.parallel import mesh as mesh_lib
    from simclr_pytorch_distributed_tpu.train import supcon as supcon_driver

    orig = cifar_lib.synthetic_dataset

    def small(n=2048, num_classes=10, seed=0, size=32):
        return orig(n=104, num_classes=num_classes, seed=seed, size=8)

    monkeypatch.setattr(cifar_lib, "synthetic_dataset", small)

    def limited_create_mesh(devices=None, **kw):
        if devices is None:
            devices = jax.devices()[:1]
        return mesh_lib.create_mesh(devices=devices, **kw)

    monkeypatch.setattr(supcon_driver, "create_mesh", limited_create_mesh)

    def cfg_for(conv_impl, epochs, resume=""):
        cfg = config_lib.SupConConfig(
            model="resnet10", dataset="synthetic", batch_size=32, epochs=epochs,
            learning_rate=0.05, temp=0.5, size=8, workdir=str(tmp_path),
            save_freq=1, print_freq=2, seed=0, method="SimCLR",
            conv_impl=conv_impl, resume=resume, health_freq=0,
        )
        return config_lib.finalize_supcon(cfg)

    cfg1 = cfg_for("pallas", epochs=2)
    state1 = supcon_driver.run(cfg1)
    steps1 = int(state1.step)
    assert steps1 > 0
    # restore the pallas-written checkpoint under the xla impl
    cfg2 = cfg_for("xla", epochs=3, resume=f"{cfg1.save_folder}/last")
    state2 = supcon_driver.run(cfg2)
    assert int(state2.step) == steps1 // 2 * 3
