"""StepTracer: windowed jax.profiler capture writes a TB-loadable trace."""

import os

import jax
import jax.numpy as jnp

from simclr_pytorch_distributed_tpu.utils.profiling import StepTracer


def test_tracer_captures_window(tmp_path):
    trace_dir = str(tmp_path / "trace")
    tracer = StepTracer(trace_dir, start_step=2, num_steps=2)
    f = jax.jit(lambda x: jnp.sin(x) * 2.0)
    x = jnp.ones((8, 8))
    for step in range(6):
        jax.block_until_ready(f(x))
        tracer.step(step)
    tracer.close()
    found = []
    for root, _, files in os.walk(trace_dir):
        found += [os.path.join(root, f) for f in files]
    assert found, "no trace events written"
    assert not tracer._active


def test_tracer_disabled_without_dir():
    tracer = StepTracer("", start_step=0, num_steps=1)
    for step in range(3):
        tracer.step(step)  # no-op, must not raise
    tracer.close()
    assert not tracer.enabled
