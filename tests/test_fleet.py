"""Fleet-scale observability: clock-anchor alignment, the trace_report
--fleet merge (skew table + straggler attribution), the boundary-skew
piggyback on the failure-code allgather, and the longitudinal perf
ledger's regression scan.

Everything runs on synthetic offset clocks / fake allgathers / synthetic
ledger records — the machinery is pure by design, so tier-1 proves it
without a pod: two deliberately offset (and rate-drifted) virtual process
clocks must align to sub-tolerance residual, an injected per-process delay
must name the straggler, and an injected throughput regression must trip
the ledger gate while an unchanged trailing window passes.
"""

import importlib.util
import json
import os

import numpy as np
import pytest

from simclr_pytorch_distributed_tpu.utils import prom, tracing

pytestmark = pytest.mark.fleet

SCRIPTS = os.path.abspath(
    os.path.join(os.path.dirname(__file__), "..", "scripts")
)


def _load(name):
    spec = importlib.util.spec_from_file_location(
        name, os.path.join(SCRIPTS, f"{name}.py")
    )
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


class FakeClock:
    def __init__(self, t=0.0):
        self.t = t

    def __call__(self):
        return self.t

    def advance(self, dt):
        self.t += dt


# ------------------------------------------------------------ clock anchors


def test_clock_anchor_event_schema_and_sequence():
    clk = FakeClock(10.0)
    rec = tracing.FlightRecorder(clock=clk)
    assert rec.clock_anchor("placement") == 1
    clk.advance(3.0)
    assert rec.clock_anchor("flush_boundary", step=4) == 2
    a, b = rec.snapshot()
    assert a["name"] == tracing.ANCHOR_EVENT and a["track"] == tracing.FLEET_TRACK
    assert a["args"] == {"kind": "placement", "anchor": 1}
    assert b["args"] == {"kind": "flush_boundary", "anchor": 2, "step": 4}
    assert b["ts"] == pytest.approx(3.0)


def test_module_level_clock_anchor_noop_without_recorder():
    tracing.uninstall()
    assert tracing.clock_anchor("placement") is None
    rec = tracing.FlightRecorder(clock=FakeClock())
    tracing.install(rec)
    try:
        assert tracing.clock_anchor("placement") == 1
    finally:
        tracing.uninstall()


# --------------------------------------------------- synthetic fleet runs


def _rec(lst, name, track, ts, dur=None, **args):
    e = {"name": name, "track": track, "ph": "i" if dur is None else "X",
         "ts": round(ts, 6)}
    if dur is not None:
        e["dur"] = round(dur, 6)
    if args:
        e["args"] = args
    lst.append(e)


def make_fleet(n_boundaries=4, late=0.55, scale=1.02, offset=5.0):
    """Two virtual processes observing the same run through different
    clocks: p0 is the reference; p1's clock reads ``scale*t + offset`` (a
    deliberate rate drift AND offset). p1 arrives ``late`` seconds after
    p0 at every collective; both stamp a clock anchor at the (shared)
    release instant T."""
    p0, p1 = [], []
    anchor = 0

    def boundary(name, kind, T, step=None):
        nonlocal anchor
        anchor += 1
        a0, a1 = T - late - 0.05, T - 0.05  # arrivals; release at T
        args = {} if step is None else {"step": step}
        _rec(p0, name, "main:collective", a0, T - a0, **args)
        _rec(p1, name, "main:collective", scale * a1 + offset,
             scale * (T - a1), **args)
        _rec(p0, "clock_anchor", "fleet", T, kind=kind, anchor=anchor)
        _rec(p1, "clock_anchor", "fleet", scale * T + offset,
             kind=kind, anchor=anchor)

    boundary("placement_decision", "placement", 1.0)
    for k in range(n_boundaries):
        boundary("failure_code_allgather", "flush_boundary", 10.0 + 5 * k,
                 step=2 * (k + 1))
    # a few main-thread phase spans so per-process attribution is real
    _rec(p0, "flush_boundary", "main:flush", 2.0, 0.5, step=0)
    _rec(p1, "flush_boundary", "main:flush", scale * 2.0 + offset,
         scale * 0.5, step=0)
    p0.sort(key=lambda e: e["ts"])
    p1.sort(key=lambda e: e["ts"])
    return {0: p0, 1: p1}


def test_fleet_merge_aligns_offset_clocks_and_names_straggler():
    """The acceptance-criteria core: two deliberately offset fake clocks
    align to sub-tolerance residual, and the injected per-process delay
    names process 1 the straggler at every boundary."""
    tr = _load("trace_report")
    report = tr.build_fleet_report(make_fleet())
    cons = report["consistency"]
    assert cons["ok"] and cons["n_processes"] == 2
    al = report["processes"]["1"]["alignment"]
    # exact affine clocks -> the fit recovers the inverse map exactly
    assert al["scale"] == pytest.approx(1 / 1.02, rel=1e-9)
    assert al["offset_s"] == pytest.approx(-5.0 / 1.02, abs=1e-4)
    assert al["residual_s"] < 1e-3 < tr.FLEET_RESIDUAL_TOL_S
    assert cons["max_residual_s"] < 1e-3
    # placement + 4 flush boundaries, each skewed by the injected 0.55 s
    assert len(report["skew_table"]) == 5
    for row in report["skew_table"]:
        assert row["skew_s"] == pytest.approx(0.55, abs=1e-3)
        assert row["straggler"] == 1
    ranking = report["straggler_ranking"]
    assert ranking[0]["process"] == 1 and ranking[0]["times_last"] == 5
    assert ranking[0]["mean_lateness_s"] == pytest.approx(0.55, abs=1e-3)
    # the rendered table names the straggler too
    assert "straggler=p1" in tr.render_fleet_table(report)


def test_fleet_merge_flags_missing_collective_member():
    tr = _load("trace_report")
    fleet = make_fleet()
    # p1 dies before the last boundary: its final collective span is gone
    dropped = [
        e for e in fleet[1]
        if not (e["track"] == "main:collective"
                and e.get("args", {}).get("step") == 8)
    ]
    report = tr.build_fleet_report({0: fleet[0], 1: dropped})
    cons = report["consistency"]
    assert cons["incomplete_boundaries"] == 1
    assert not cons["collective_match_ok"] and not cons["ok"]
    assert len(report["skew_table"]) == 4  # the whole boundaries remain


def test_fleet_merge_requires_two_anchors_per_process():
    tr = _load("trace_report")
    fleet = make_fleet()
    one_anchor = [
        e for e in fleet[1]
        if e["name"] != "clock_anchor"
        or e["args"]["anchor"] == 1
    ]
    report = tr.build_fleet_report({0: fleet[0], 1: one_anchor})
    assert report["processes"]["1"]["alignment"]["n_anchors"] == 1
    assert not report["consistency"]["aligned_ok"]
    assert not report["consistency"]["ok"]


def test_fleet_merge_fails_on_recordless_process():
    """Review fix: a process whose events file parsed to ZERO records (a
    SIGKILL before its first complete line) must fail the merge — not be
    silently dropped so the session reads as a consistent 1-process run."""
    tr = _load("trace_report")
    report = tr.build_fleet_report({0: make_fleet()[0], 1: []})
    cons = report["consistency"]
    assert cons["n_processes"] == 2 and not cons["ok"]
    assert not cons["aligned_ok"] and not cons["attribution_ok"]
    assert report["processes"]["1"]["n_events"] == 0
    assert report["processes"]["1"]["alignment"]["n_anchors"] == 0


def test_fleet_merge_single_process_is_trivially_consistent():
    tr = _load("trace_report")
    report = tr.build_fleet_report({0: make_fleet()[0]})
    cons = report["consistency"]
    assert cons["ok"] and cons["n_processes"] == 1
    assert report["skew_table"] == []


def test_fleet_chrome_trace_one_pid_per_process_nonnegative_ts():
    tr = _load("trace_report")
    fleet = make_fleet()
    report = tr.build_fleet_report(fleet)
    trace = tr.fleet_chrome_trace(fleet, report)
    assert set(trace) == {"traceEvents", "displayTimeUnit"}
    data = [e for e in trace["traceEvents"] if e["ph"] != "M"]
    assert {e["pid"] for e in data} == {0, 1}
    assert min(e["ts"] for e in data) == 0  # shifted, never negative
    # aligned: both processes' anchor instants land at the same merged ts
    anchors = {}
    for e in data:
        if e["name"] == "clock_anchor":
            anchors.setdefault(e["args"]["anchor"], []).append(e["ts"])
    for seq, ts_list in anchors.items():
        assert len(ts_list) == 2
        assert abs(ts_list[0] - ts_list[1]) <= 2  # integer-us rounding


# -------------------------------------- the skew piggyback (telemetry side)


def test_failure_code_allgather_carries_wait_and_stamps_skew(monkeypatch):
    """The live half of the skew story: the EXISTING failure-code
    allgather widens to [code, prev_wait_ms] — no new collective — and the
    gathered waits become train_boundary_skew_seconds /
    train_collective_wait_seconds plus a boundary_skew event naming the
    straggler (the process that waited least = arrived last)."""
    import jax as jax_mod
    from jax.experimental import multihost_utils

    from simclr_pytorch_distributed_tpu.utils.telemetry import TelemetrySession

    payloads = []

    def fake_allgather(arr):
        arr = np.asarray(arr)
        payloads.append(arr.copy())
        # peer 1 reports a 400 ms previous wait; this host's prev rides in
        peer = np.asarray([0, 400], np.int32)
        return np.stack([arr, peer])

    monkeypatch.setattr(jax_mod, "process_count", lambda: 2)
    monkeypatch.setattr(multihost_utils, "process_allgather", fake_allgather)

    gauges = prom.TrainerGauges(clock=FakeClock())
    session = TelemetrySession(4, ("loss",), mode="sync", gauges=gauges)
    recorder = tracing.FlightRecorder(clock=FakeClock())
    tracing.install(recorder)
    try:
        session.check_failures_global(step_hint=2)
        # first boundary: this host has no previous wait yet (-1 sentinel)
        assert payloads[0].tolist() == [0, -1]
        out = gauges.collect()
        assert out["collective_wait_seconds"] >= 0.0
        assert "boundary_skew_seconds" not in out  # no full wait row yet
        session.check_failures_global(step_hint=4)
        # second boundary: the measured wait from boundary 1 piggybacks
        assert payloads[1][0] == 0 and payloads[1][1] >= 0
        out = gauges.collect()
        # waits were [~0 ms, 400 ms] -> skew ~0.4 s, straggler = this host
        assert out["boundary_skew_seconds"] == pytest.approx(0.4, abs=0.05)
        events = recorder.snapshot()
        skews = [e for e in events if e["name"] == "boundary_skew"]
        assert len(skews) == 1 and skews[0]["track"] == tracing.FLEET_TRACK
        assert skews[0]["args"]["straggler"] == 0
        anchors = [e for e in events if e["name"] == tracing.ANCHOR_EVENT]
        assert [a["args"]["anchor"] for a in anchors] == [1, 2]
        assert all(a["args"]["kind"] == "flush_boundary" for a in anchors)
        spans = [e for e in events if e["name"] == "failure_code_allgather"]
        assert len(spans) == 2 and all(
            s["track"] == "main:collective" for s in spans
        )
    finally:
        tracing.uninstall()
        session.close()


def test_single_process_boundary_publishes_zero_skew_and_anchor():
    from simclr_pytorch_distributed_tpu.utils.telemetry import TelemetrySession

    gauges = prom.TrainerGauges(clock=FakeClock())
    session = TelemetrySession(4, ("loss",), mode="sync", gauges=gauges)
    recorder = tracing.FlightRecorder(clock=FakeClock())
    tracing.install(recorder)
    try:
        session.check_failures_global(step_hint=2)
        out = gauges.collect()
        assert out["collective_wait_seconds"] == 0.0
        assert out["boundary_skew_seconds"] == 0.0
        (anchor,) = [
            e for e in recorder.snapshot()
            if e["name"] == tracing.ANCHOR_EVENT
        ]
        assert anchor["args"]["kind"] == "flush_boundary"
    finally:
        tracing.uninstall()
        session.close()


# --------------------------------------------- supervisor straggler finding


def test_straggler_finding_warn_only_surface():
    from simclr_pytorch_distributed_tpu.supervise import observe

    gauges = {
        "train_boundary_skew_seconds": 1.5,
        "train_collective_wait_seconds": 1.4,
        "train_step": 120.0,
    }
    finding = observe.straggler_finding(gauges, 1.0)
    assert finding == {"skew_s": 1.5, "bar_s": 1.0, "wait_s": 1.4,
                       "step": 120.0}
    assert observe.straggler_finding(gauges, 2.0) is None  # under the bar
    assert observe.straggler_finding(gauges, 0.0) is None  # disabled
    assert observe.straggler_finding(None, 1.0) is None    # dead sidecar
    assert observe.straggler_finding({}, 1.0) is None      # no skew gauge


def test_straggler_finding_carries_rebalance_context():
    """A PR-16 sidecar names the straggler and the fleet size; the finding
    must carry them plus the per-process share a restart_rebalanced
    decision shrinks (1/processes — EpochLoader's uniform blocks)."""
    from simclr_pytorch_distributed_tpu.supervise import observe

    gauges = {
        "train_boundary_skew_seconds": 1.5,
        "train_step": 120.0,
        "train_boundary_straggler": 1.0,
        "train_process_count": 4.0,
    }
    finding = observe.straggler_finding(gauges, 1.0)
    assert finding["straggler"] == 1
    assert finding["processes"] == 4 and finding["share"] == 0.25


def test_straggler_finding_identity_gauges_missing_or_single_process():
    """Against an older sidecar (no identity gauges) the finding still
    fires but carries no identity — enough to warn, not to mitigate; a
    single-process fleet's -1 sentinel is likewise not an identity."""
    from simclr_pytorch_distributed_tpu.supervise import observe

    old = {"train_boundary_skew_seconds": 1.5, "train_step": 3.0}
    finding = observe.straggler_finding(old, 1.0)
    assert finding is not None
    assert "straggler" not in finding and "processes" not in finding

    single = dict(old, train_boundary_straggler=-1.0,
                  train_process_count=1.0)
    finding = observe.straggler_finding(single, 1.0)
    assert "straggler" not in finding  # -1 = nobody was waited on
    assert finding["processes"] == 1 and finding["share"] == 1.0


def test_supervisor_records_straggler_finding_once_per_step(tmp_path):
    from simclr_pytorch_distributed_tpu.supervise import supervisor as sup

    cfg = sup.SuperviseConfig(
        command=["true"], workdir=str(tmp_path), metrics_port=9,
        straggler_skew_secs=1.0,
    )

    class FakeScraper:
        def __init__(self):
            self.gauges = {
                "train_last_boundary_age_seconds": 0.5,
                "train_boundary_skew_seconds": 2.0,
                "train_step": 40.0,
            }

        def scrape(self):
            return dict(self.gauges)

    class DoneChild:
        pid = 1234

        def __init__(self):
            self.polls = 0

        def poll(self):
            # two observation loops, then exit 0
            self.polls += 1
            return 0 if self.polls >= 3 else None

    scraper = FakeScraper()
    s = sup.Supervisor(cfg, sleep=lambda dt: None, scraper=scraper)
    s.child = DoneChild()
    rc, stalled, dumps, alarms = s._watch_child()
    assert rc == 0 and not stalled
    findings = [
        e for e in s.recorder.snapshot() if e["name"] == "straggler_finding"
    ]
    # same step scraped on both polls: recorded ONCE, warn-only (no kill)
    assert len(findings) == 1
    assert findings[0]["args"]["skew_s"] == 2.0
    assert findings[0]["args"]["step"] == 40.0
    s.recorder.close()


# ------------------------------------------------- health_report sessions


def test_health_report_reads_rotated_sessions(tmp_path):
    """Satellite: a resumed run's health timeline spans events.jsonl +
    events_r2.jsonl (+...); reading only the first file silently truncated
    it at the first preemption."""
    import scripts.health_report as hr

    keys = dict.fromkeys(hr.REQUIRED_HEALTH_KEYS, 1.0)

    def window(step):
        return {"name": "health_window", "track": "health", "ph": "i",
                "ts": 0.1 * step, "args": dict(keys, step=step)}

    with open(tmp_path / "events.jsonl", "w") as f:
        for s in (2, 4):
            f.write(json.dumps(window(s)) + "\n")
    with open(tmp_path / "events_r2.jsonl", "w") as f:
        for s in (6, 8):
            f.write(json.dumps(window(s)) + "\n")
        f.write('{"torn": ')  # SIGKILL mid-line: must not crash the reader
    events = hr.load_events(str(tmp_path / "events.jsonl"))
    report = hr.build_report(events)
    assert report["consistency"]["n_windows"] == 4
    assert report["consistency"]["ok"]
    assert [w["step"] for w in report["timeline"]] == [2, 4, 6, 8]
    # an EXPLICIT rotated file selects exactly that session — asking for
    # one session must not be silently overridden with the whole family
    r2 = str(tmp_path / "events_r2.jsonl")
    assert hr.session_paths(r2) == [r2]
    solo = hr.build_report(hr.load_events(r2))
    assert [w["step"] for w in solo["timeline"]] == [6, 8]
    # ...and the artifact provenance records the files ACTUALLY read
    art = hr.build_output(
        str(tmp_path / "events.jsonl"), report, "cpu",
        session_files=hr.session_paths(str(tmp_path / "events.jsonl")),
    )
    assert art["session_files"] == ["events.jsonl", "events_r2.jsonl"]


# ------------------------------------------------------------- perf ledger


def _bench_record(value=4000.0, device_kind="cpu", chips=1,
                  clock_suspect=False, config="simclr rn50 bsz256"):
    return {
        "metric": "pretrain_imgs_per_sec_per_chip",
        "value": value,
        "vs_baseline": 1.0,
        "detail": {
            "global_batch": 256, "chips": chips,
            "device_kind": device_kind, "step_ms": 63.0,
            "clock_suspect": clock_suspect, "config": config,
        },
    }


def test_ledger_record_schema_and_fingerprint_identity():
    pl = _load("perf_ledger")
    rec = pl.record_from_bench(
        _bench_record(), "abc1234", 1722.0,
        phase_shares={"flush": 0.01, "steady_state": 0.9},
    )
    assert rec["schema"] == pl.SCHEMA
    assert not pl.schema_errors([rec])
    assert rec["imgs_per_sec_per_chip"] == 4000.0
    assert rec["git_rev"] == "abc1234" and rec["stage"] == "pretrain"
    assert rec["phase_shares"]["steady_state"] == 0.9
    # fingerprint: stable for the same workload, different across devices
    again = pl.record_from_bench(_bench_record(3900.0), "def", 1723.0)
    other = pl.record_from_bench(
        _bench_record(device_kind="TPU v5 lite"), "def", 1723.0
    )
    assert rec["fingerprint"] == again["fingerprint"]
    assert rec["fingerprint"] != other["fingerprint"]


def test_ledger_fingerprint_keys_on_conv_impl():
    """A --conv_impl pallas bench run must never land in an xla-path
    fingerprint group (the regression scan would compare across kernel
    implementations); records predating the flag — and the explicit
    default 'xla' — keep their committed fingerprints."""
    pl = _load("perf_ledger")
    base = _bench_record()
    pre_flag = pl.record_from_bench(base, "abc", 1722.0)
    explicit_xla = _bench_record()
    explicit_xla["detail"]["conv_impl"] = "xla"
    xla_rec = pl.record_from_bench(explicit_xla, "abc", 1722.0)
    pallas = _bench_record()
    pallas["detail"]["conv_impl"] = "pallas"
    pallas_rec = pl.record_from_bench(pallas, "abc", 1722.0)
    assert pre_flag["fingerprint"] == xla_rec["fingerprint"]
    assert pallas_rec["fingerprint"] != xla_rec["fingerprint"]


def test_ledger_fingerprint_keys_on_conv_dtype_for_pallas_only():
    """The pallas arm exists in fp32 AND bf16 compute (round 19): the
    dtype changes the workload, so the scan keys on it — but ONLY inside
    non-xla impls, so every committed record (all xla, no conv_dtype key)
    fingerprints exactly as before."""
    pl = _load("perf_ledger")

    def rec(**detail):
        b = _bench_record()
        b["detail"].update(detail)
        return pl.record_from_bench(b, "abc", 1722.0)

    pallas_fp32_implicit = rec(conv_impl="pallas")
    pallas_fp32 = rec(conv_impl="pallas", conv_dtype="fp32")
    pallas_bf16 = rec(conv_impl="pallas", conv_dtype="bf16")
    assert pallas_fp32["fingerprint"] == pallas_fp32_implicit["fingerprint"]
    assert pallas_bf16["fingerprint"] != pallas_fp32["fingerprint"]
    # an xla record ignores conv_dtype entirely: the committed history
    # (which never carried the key) keeps its fingerprints
    xla_plain = rec(conv_impl="xla")
    xla_tagged = rec(conv_impl="xla", conv_dtype="bf16")
    assert xla_plain["fingerprint"] == xla_tagged["fingerprint"]


def _ledger(values, suspects=None, shares=None):
    pl = _load("perf_ledger")
    suspects = suspects or [False] * len(values)
    out = []
    for i, (v, sus) in enumerate(zip(values, suspects)):
        rec = pl.record_from_bench(
            _bench_record(v, clock_suspect=sus), f"rev{i}", 1000.0 + i,
            phase_shares=(shares[i] if shares else None),
        )
        out.append(rec)
    return pl, out


def test_ledger_regression_and_no_regression_pair():
    """The acceptance-criteria pair: an unchanged trailing window passes;
    an injected regression is flagged — through the pure gate record."""
    ratchet = _load("ratchet")
    # unchanged: latest within noise of the trailing median
    pl, steady = _ledger([4000.0, 4010.0, 3995.0, 4005.0])
    verdicts = pl.detect_regression(steady)
    (v,) = verdicts.values()
    assert v["status"] == "ok" and v["ratio"] == pytest.approx(1.0, abs=0.01)
    rec = ratchet.ledger_gate_record(steady)
    assert rec["ok"] and rec["metric"] == "ratchet_perf_ledger"
    # injected regression: latest at 90% of the window median
    shares = [
        {"flush": 0.01, "steady_state": 0.95},
        {"flush": 0.01, "steady_state": 0.95},
        {"flush": 0.01, "steady_state": 0.95},
        {"flush": 0.12, "steady_state": 0.84},  # flush absorbed the time
    ]
    pl, regressed = _ledger([4000.0, 4010.0, 3995.0, 3600.0], shares=shares)
    verdicts = pl.detect_regression(regressed)
    (v,) = verdicts.values()
    assert v["status"] == "regression"
    assert v["ratio"] == pytest.approx(3600.0 / 4000.0, abs=0.01)
    assert v["latest_rev"] == "rev3"
    # ...and the drift is attributed to a PHASE, not just a revision
    assert v["phase_suspect"]["phase"] == "flush"
    rec = ratchet.ledger_gate_record(regressed)
    assert not rec["ok"] and "regression" in rec["error"]
    assert "rev3" in rec["error"]


def test_ledger_excludes_clock_suspect_runs_both_sides():
    pl, records = _ledger(
        [4000.0, 4010.0, 3995.0, 9000.0, 3990.0],
        suspects=[False, False, False, True, False],
    )
    (v,) = pl.detect_regression(records).values()
    # the 9000 glitch neither sets the baseline nor becomes the subject
    assert v["status"] == "ok" and v["window"] == 3
    assert v["baseline_median"] == pytest.approx(4000.0)
    # a glitched LATEST run cannot mask anything either: the last clean
    # record is judged instead
    pl2, records2 = _ledger(
        [4000.0, 4010.0, 3600.0, 9000.0],
        suspects=[False, False, False, True],
    )
    (v2,) = pl2.detect_regression(records2).values()
    assert v2["status"] == "regression" and v2["latest_rev"] == "rev2"


def test_ledger_short_window_pass_skips_with_reason():
    ratchet = _load("ratchet")
    pl, records = _ledger([4000.0, 3000.0])  # one trailing record only
    (v,) = pl.detect_regression(records).values()
    assert v["status"] == "skipped" and "window" in v["reason"]
    rec = ratchet.ledger_gate_record(records)
    assert rec["ok"] and rec["skipped"]
    # empty and schema-broken ledgers fail loudly
    assert not ratchet.ledger_gate_record([])["ok"]
    bad = ratchet.ledger_gate_record([{"schema": "bogus"}])
    assert not bad["ok"] and "schema" in bad["error"]


def test_ledger_check_cli_reports_schema_error_not_keyerror(tmp_path):
    """Review fix: a malformed ledger line (missing pinned keys) must
    surface as a schema error through the check CLI, not crash
    detect_regression with a KeyError."""
    pl = _load("perf_ledger")
    ledger = tmp_path / "ledger.jsonl"
    ledger.write_text('{"schema": "perf_ledger/v1"}\n')
    out = tmp_path / "check.json"
    rc = pl.main(["check", "--ledger", str(ledger), "--json", str(out)])
    assert rc == 1
    artifact = json.load(open(out))
    assert not artifact["ok"]
    assert artifact["schema_errors"] and artifact["verdicts"] == {}


def test_ledger_corrupt_complete_line_fails_gate_torn_tail_tolerated(tmp_path):
    """Review fix: the ledger loader tolerates only a torn FINAL line (an
    append racing the reader); a complete-but-corrupt line must surface as
    a schema error — a silently vanished newest record would make the
    previous one 'latest' and blind the regression scan."""
    pl = _load("perf_ledger")
    ratchet = _load("ratchet")
    good = pl.record_from_bench(_bench_record(), "rev0", 1000.0)
    ledger = tmp_path / "ledger.jsonl"
    ledger.write_text(
        json.dumps(good) + "\n"
        + "<<<<<<< conflict marker\n"       # complete corrupt line
        + json.dumps(good) + "\n"
        + '{"schema": "perf_ledger/v1", '    # torn tail: tolerated
    )
    records = pl.load_ledger(str(ledger))
    assert len(records) == 3  # the torn tail is not a record
    errors = pl.schema_errors(records)
    assert len(errors) == 1 and "unparseable" in errors[0]
    rec = ratchet.ledger_gate_record(records)
    assert not rec["ok"] and "schema" in rec["error"]
    # without the corrupt line the same ledger is clean
    ledger.write_text(json.dumps(good) + "\n" + json.dumps(good) + "\n")
    assert ratchet.ledger_gate_record(pl.load_ledger(str(ledger)))["ok"]


def test_ledger_append_and_check_cli_roundtrip(tmp_path):
    pl = _load("perf_ledger")
    bench_log = tmp_path / "bench.log"
    bench_log.write_text(
        "warmup noise\n" + json.dumps(_bench_record(4000.0)) + "\n"
    )
    ledger = tmp_path / "ledger.jsonl"
    for _ in range(3):
        assert pl.main(["append", "--bench-json", str(bench_log),
                        "--ledger", str(ledger)]) == 0
    out = tmp_path / "check.json"
    assert pl.main(["check", "--ledger", str(ledger),
                    "--json", str(out)]) == 0
    artifact = json.load(open(out))
    assert artifact["schema"] == "perf_ledger_check/v1"
    assert artifact["n_records"] == 3 and artifact["ok"]
    (v,) = artifact["verdicts"].values()
    assert v["status"] == "ok" and v["window"] == 2
    # all three appends share the workload fingerprint and carry a git rev
    records = pl.load_ledger(str(ledger))
    assert len({r["fingerprint"] for r in records}) == 1
    assert all(r["git_rev"] for r in records)


def test_ledger_append_from_bench_attaches_phase_shares(tmp_path):
    pl = _load("perf_ledger")
    tr = _load("trace_report")
    phases = tmp_path / "trace_report.json"
    events = [
        {"name": "first_step", "track": "main:compile", "ph": "X",
         "ts": 0.0, "dur": 10.0},
        {"name": "flush_boundary", "track": "main:flush", "ph": "X",
         "ts": 50.0, "dur": 2.0},
        {"name": "end", "track": "events", "ph": "i", "ts": 100.0},
    ]
    with open(phases, "w") as f:
        json.dump(tr.build_output("x", tr.build_report(events)), f)
    ledger = tmp_path / "ledger.jsonl"
    rec = pl.append_from_bench(
        str(ledger), _bench_record(), phases_path=str(phases), note="n1"
    )
    assert rec["phase_shares"]["compile"] == pytest.approx(0.10)
    assert rec["phase_shares"]["steady_state"] == pytest.approx(0.88)
    assert rec["note"] == "n1"
    (loaded,) = pl.load_ledger(str(ledger))
    assert loaded == json.loads(json.dumps(rec))  # round-trips losslessly


# ------------------------------------------------------- fleet ratchet gate


def test_fleet_gate_record_pass_and_failures():
    ratchet = _load("ratchet")
    tr = _load("trace_report")
    fleet = make_fleet()
    good = tr.build_fleet_output(
        "run", {"r1": tr.build_fleet_report(fleet)}
    )
    rec = ratchet.fleet_gate_record(good)
    assert rec["ok"] and rec["metric"] == "ratchet_fleet_report"
    assert rec["stragglers"] == {"r1": 1}
    assert rec["max_residual_s"] <= tr.FLEET_RESIDUAL_TOL_S
    # a single-process-only artifact proves nothing about alignment
    solo = tr.build_fleet_output(
        "run", {"r1": tr.build_fleet_report({0: fleet[0]})}
    )
    rec = ratchet.fleet_gate_record(solo)
    assert not rec["ok"] and "multi-process" in rec["error"]
    # an inconsistent merge fails
    broken = [
        e for e in fleet[1]
        if e["name"] != "clock_anchor" or e["args"]["anchor"] == 1
    ]
    bad = tr.build_fleet_output(
        "run", {"r1": tr.build_fleet_report({0: fleet[0], 1: broken})}
    )
    rec = ratchet.fleet_gate_record(bad)
    assert not rec["ok"] and "inconsistent" in rec["error"]
    # empty / wrong-schema artifacts fail
    assert not ratchet.fleet_gate_record({"schema": "fleet_report/v1",
                                          "sessions": {}})["ok"]
    assert not ratchet.fleet_gate_record({"schema": "nope"})["ok"]
