"""Training-health observability: on-device diagnostics, the online probe,
and the collapse detector (train/supcon_step.py, utils/guard.py,
scripts/health_report.py).

The load-bearing claims are tested mechanically, not assumed:

- RING EXTENSION: the health/probe columns extend the metric ring without
  corrupting any existing key's value stream, and a writer/reader key
  mismatch still fails loudly at trace time.
- DETACHMENT: encoder + projection-head params (and BN stats, and the
  optimizer state) after N steps are BITWISE identical with the online
  probe on vs off — ``stop_gradient`` really isolates it — and a resume
  restores the probe's own state.
- COLLAPSE: a degenerate constant-embedding run trips the windowed detector
  through the REAL driver; ``--health_policy abort`` exits with the typed
  ``RepresentationHealthError`` via the collective failure code (3), and
  the flight recorder holds the ``health_alarm`` event.
- ZERO-SYNC: a real supcon epoch with health metrics + the online probe
  enabled performs EXACTLY the PR-4/PR-5 transfer contract — one ring D2H
  per window and one index upload per epoch — counted through the
  injectable ``device_get``/``index_put`` hooks, same as PR 7's recorder
  proof.
"""

import json
import math
import os

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from simclr_pytorch_distributed_tpu.models import MODEL_DICT, SupConResNet
from simclr_pytorch_distributed_tpu.ops.metrics import MetricRing
from simclr_pytorch_distributed_tpu.train.state import (
    create_train_state,
    make_optimizer,
)
from simclr_pytorch_distributed_tpu.train import supcon_step
from simclr_pytorch_distributed_tpu.train.supcon_step import (
    HEALTH_METRIC_KEYS,
    METRIC_KEYS,
    ONLINE_PROBE_METRIC_KEYS,
    SupConStepConfig,
    build_online_probe,
    contrastive_health_metrics,
    make_train_step,
    metric_keys,
)
from simclr_pytorch_distributed_tpu.utils import tracing
from simclr_pytorch_distributed_tpu.utils.guard import (
    HealthMonitor,
    HealthThresholds,
    RepresentationHealthError,
)

pytestmark = pytest.mark.health

SIZE = 8


# ------------------------------------------------- the diagnostics themselves


def _healthy_sample():
    """All-NaN-free sample at plausible healthy values."""
    return {
        "health_align": 0.5, "health_con_top1": 30.0, "health_eff_rank": 12.0,
        "health_grad_norm": 5.0, "health_neg_max": 0.7,
        "health_neg_mean": 0.4, "health_unif": -2.0,
        "probe_loss": 2.0, "probe_top1": 25.0,
    }


def test_health_metrics_on_structured_embeddings():
    """B orthogonal directions, each duplicated across the two views:
    positives perfectly aligned, negatives orthogonal, every anchor's argmax
    is its positive, and the spectrum spreads over B dimensions."""
    b, d = 8, 16
    base = np.eye(d, dtype=np.float32)[:b]
    emb = jnp.asarray(np.concatenate([base, base]))  # view-major [2B, D]
    m = jax.device_get(contrastive_health_metrics(emb, {"w": jnp.zeros(3)}))
    assert m["health_align"] == pytest.approx(1.0)
    assert m["health_con_top1"] == pytest.approx(100.0)
    assert m["health_neg_mean"] == pytest.approx(0.0, abs=1e-6)
    assert m["health_neg_max"] == pytest.approx(0.0, abs=1e-6)
    assert m["health_eff_rank"] == pytest.approx(b, rel=1e-3)
    assert m["health_grad_norm"] == pytest.approx(0.0)
    assert m["health_unif"] < -0.5  # spread embeddings: well below the max
    assert set(m) == set(HEALTH_METRIC_KEYS)


def test_health_metrics_on_collapsed_embeddings():
    """Constant embeddings — the degenerate regime the detector exists for:
    align, neg_mean, neg_max -> 1; eff_rank -> 1; uniformity -> 0 (its
    maximum)."""
    emb = jnp.ones((16, 8), jnp.float32) / jnp.sqrt(8.0)
    m = jax.device_get(
        contrastive_health_metrics(emb, {"w": jnp.full((2,), 3.0)})
    )
    assert m["health_align"] == pytest.approx(1.0)
    assert m["health_neg_mean"] == pytest.approx(1.0)
    assert m["health_neg_max"] == pytest.approx(1.0)
    assert m["health_eff_rank"] == pytest.approx(1.0, abs=1e-3)
    assert m["health_unif"] == pytest.approx(0.0, abs=1e-5)
    assert m["health_grad_norm"] == pytest.approx(math.sqrt(18.0))


def _tiny_step(online_probe=False, health=False, health_freq=1, n_cls=4):
    model = SupConResNet(model_name="resnet10", feat_dim=16)
    tx = make_optimizer(0.1)
    cfg = SupConStepConfig(
        method="SimCLR", steps_per_epoch=4, online_probe=online_probe,
        health=health, health_freq=health_freq,
    )
    state = create_train_state(
        model, tx, jax.random.key(0), jnp.zeros((2, SIZE, SIZE, 3))
    )
    probe = None
    if online_probe:
        probe, pp, po = build_online_probe(
            "resnet10", MODEL_DICT["resnet10"][1], n_cls, lr=0.1,
        )
        state = state.replace(probe_params=pp, probe_opt_state=po)
    step = jax.jit(make_train_step(model, tx, lambda s: 0.1, cfg, probe=probe))
    return step, state


def _batch(key, b=8, n_cls=4):
    images = jax.random.uniform(key, (b, 2, SIZE, SIZE, 3))
    labels = jnp.arange(b) % n_cls
    return images, labels


def test_health_cadence_nan_sentinel_off_steps():
    """health_freq=2: steps 0 and 2 carry real diagnostics, step 1 the
    all-NaN sentinel row — and the base metrics stay finite throughout."""
    step, state = _tiny_step(health=True, health_freq=2)
    images, labels = _batch(jax.random.key(1))
    rows = []
    for _ in range(3):
        state, metrics = step(state, images, labels)
        rows.append(jax.device_get(metrics))
    for i, m in enumerate(rows):
        assert set(m) == set(metric_keys(health=True))
        assert math.isfinite(m["loss"])
        health_vals = [float(m[k]) for k in HEALTH_METRIC_KEYS]
        if i % 2 == 0:
            assert all(math.isfinite(v) for v in health_vals), (i, m)
        else:
            assert all(math.isnan(v) for v in health_vals), (i, m)


# ----------------------------------------------- ring key-extension contract


def test_metric_keys_derivation_is_sorted_and_superset():
    base = metric_keys()
    assert base == tuple(sorted(METRIC_KEYS))
    full = metric_keys(health=True, online_probe=True)
    assert set(full) == set(METRIC_KEYS) | set(HEALTH_METRIC_KEYS) | set(
        ONLINE_PROBE_METRIC_KEYS
    )
    assert list(full) == sorted(full)


def test_ring_extension_preserves_existing_key_streams():
    """Adding the health/probe columns must not corrupt any pre-existing
    key's value stream: the same (key -> value) writes resolve identically
    through the base ring and the extended ring."""
    values = {k: float(i + 1) for i, k in enumerate(METRIC_KEYS)}
    extended_values = dict(values)
    extended_values.update(
        {k: 100.0 + i for i, k in enumerate(HEALTH_METRIC_KEYS)}
    )
    extended_values.update(
        {k: 200.0 + i for i, k in enumerate(ONLINE_PROBE_METRIC_KEYS)}
    )
    for keys, metrics in (
        (metric_keys(), values),
        (metric_keys(health=True, online_probe=True), extended_values),
    ):
        ring = MetricRing(4, keys)
        buf = ring.init_buffer()
        buf = ring.write(
            buf, {k: jnp.float32(v) for k, v in metrics.items()}, 0
        )
        ring.append("i", 0)
        (_, resolved), = ring.resolve(buf, ring.take_window())
        for k, v in values.items():  # the BASE keys, under both layouts
            assert resolved[k] == v, (k, keys)


def test_ring_key_mismatch_fails_loudly_at_trace_time():
    """A writer whose metric dict doesn't match the ring's key set must
    raise during TRACING (where the write happens), not silently shift
    columns — in both directions (missing and extra keys)."""
    ring = MetricRing(4, metric_keys(health=True))
    base_only = {k: jnp.float32(0) for k in METRIC_KEYS}

    with pytest.raises(ValueError, match="metric keys"):
        ring.write(ring.init_buffer(), base_only, 0)

    # and inside an actual jit trace (the drivers' path)
    def traced(buf):
        return ring.write(buf, base_only, 0)

    with pytest.raises(ValueError, match="metric keys"):
        jax.jit(traced)(ring.init_buffer())

    narrow_ring = MetricRing(4, METRIC_KEYS)
    extended = {
        k: jnp.float32(0) for k in metric_keys(health=True)
    }
    with pytest.raises(ValueError, match="metric keys"):
        narrow_ring.write(narrow_ring.init_buffer(), extended, 0)


def test_step_and_probe_spec_must_agree():
    model = SupConResNet(model_name="resnet10", feat_dim=16)
    tx = make_optimizer(0.1)
    cfg_on = SupConStepConfig(method="SimCLR", online_probe=True)
    with pytest.raises(ValueError, match="online_probe"):
        make_train_step(model, tx, lambda s: 0.1, cfg_on, probe=None)
    probe, _, _ = build_online_probe("resnet10", 512, 4, lr=0.1)
    cfg_off = SupConStepConfig(method="SimCLR", online_probe=False)
    with pytest.raises(ValueError, match="online_probe"):
        make_train_step(model, tx, lambda s: 0.1, cfg_off, probe=probe)


# ------------------------------------------------------- probe detachment


def test_probe_detachment_bitwise_and_metrics():
    """The whole detachment contract: N steps with the probe ON produce
    BITWISE identical encoder+head params, BN stats, and optimizer state as
    the probe-OFF run on the same data — stop_gradient really isolates the
    probe — while the probe itself trains (its params move and its metrics
    stream)."""
    step_off, state_off = _tiny_step(online_probe=False)
    step_on, state_on = _tiny_step(online_probe=True)
    probe_init = jax.device_get(state_on.probe_params)
    images, labels = _batch(jax.random.key(2))
    for _ in range(3):
        state_off, m_off = step_off(state_off, images, labels)
        state_on, m_on = step_on(state_on, images, labels)

    def assert_bitwise(a, b):
        ja, jb = jax.device_get(a), jax.device_get(b)
        flat_a, _ = jax.tree.flatten(ja)
        flat_b, treedef = jax.tree.flatten(jb)
        assert len(flat_a) == len(flat_b)
        for xa, xb in zip(flat_a, flat_b):
            np.testing.assert_array_equal(np.asarray(xa), np.asarray(xb))

    assert_bitwise(state_off.params, state_on.params)
    assert_bitwise(state_off.batch_stats, state_on.batch_stats)
    assert_bitwise(state_off.opt_state, state_on.opt_state)
    # the probe is real training, not a no-op rider
    moved = jax.tree.map(
        lambda a, b: not np.array_equal(np.asarray(a), np.asarray(b)),
        probe_init, jax.device_get(state_on.probe_params),
    )
    assert any(jax.tree.leaves(moved))
    got = jax.device_get(m_on)
    assert math.isfinite(got["probe_loss"])
    assert 0.0 <= got["probe_top1"] <= 100.0
    assert set(m_on) == set(metric_keys(online_probe=True))
    assert set(m_off) == set(metric_keys())


def test_checkpoint_roundtrip_restores_probe_state(tmp_path):
    from simclr_pytorch_distributed_tpu.utils.checkpoint import (
        restore_checkpoint,
        save_checkpoint,
    )

    step, state = _tiny_step(online_probe=True)
    images, labels = _batch(jax.random.key(3))
    state, _ = step(state, images, labels)
    saved = jax.device_get(
        {"p": state.probe_params, "o": state.probe_opt_state}
    )
    save_checkpoint(str(tmp_path), "ckpt", state, epoch=1)

    _, abstract = _tiny_step(online_probe=True)
    restored, meta = restore_checkpoint(str(tmp_path / "ckpt"), abstract)
    got = jax.device_get(
        {"p": restored.probe_params, "o": restored.probe_opt_state}
    )
    for a, b in zip(jax.tree.leaves(saved), jax.tree.leaves(got)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    assert int(restored.step) == 1

    # a probe-OFF resume of the probe-on checkpoint ignores the payload
    _, abstract_off = _tiny_step(online_probe=False)
    restored_off, _ = restore_checkpoint(str(tmp_path / "ckpt"), abstract_off)
    assert restored_off.probe_params is None


def test_probe_on_resume_of_probe_off_checkpoint_degrades(tmp_path, caplog):
    """Turning the probe ON across a resume keeps the encoder trajectory
    and restarts the probe from its fresh init, with a warning."""
    import logging

    from simclr_pytorch_distributed_tpu.utils.checkpoint import (
        restore_checkpoint,
        save_checkpoint,
    )

    step, state = _tiny_step(online_probe=False)
    images, labels = _batch(jax.random.key(4))
    state, _ = step(state, images, labels)
    save_checkpoint(str(tmp_path), "ckpt", state, epoch=1)

    _, abstract_on = _tiny_step(online_probe=True)
    fresh = jax.device_get(abstract_on.probe_params)
    with caplog.at_level(logging.WARNING):
        restored, _ = restore_checkpoint(str(tmp_path / "ckpt"), abstract_on)
    assert "no online-probe payload" in caplog.text
    for a, b in zip(
        jax.tree.leaves(fresh), jax.tree.leaves(jax.device_get(restored.probe_params))
    ):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


# --------------------------------------------------------- the detector


def test_monitor_skips_sentinel_rows_and_windows_means():
    mon = HealthMonitor("warn")
    sentinel = {k: float("nan") for k in HEALTH_METRIC_KEYS}
    assert not mon.observe(sentinel, 1)
    assert mon.samples == 0
    assert mon.observe(_healthy_sample(), 2)
    s2 = dict(_healthy_sample(), health_align=0.7)
    assert mon.observe(s2, 4)
    means = mon.window_means()
    assert means["health_align"] == pytest.approx(0.6)
    assert means["step"] == 4


def test_monitor_warn_policy_emits_events_and_counts_alarms():
    rec = tracing.FlightRecorder(clock=lambda: 0.0)
    tracing.install(rec)
    try:
        mon = HealthMonitor("warn")
        collapsed = dict(
            _healthy_sample(), health_align=1.0, health_neg_mean=1.0,
            health_eff_rank=1.0,
        )
        findings = mon.ingest([(10, collapsed), (12, collapsed)])
    finally:
        tracing.uninstall()
    assert findings and mon.alarms == 1
    names = [e["name"] for e in rec.snapshot()]
    assert "health_window" in names and "health_alarm" in names
    alarm = [e for e in rec.snapshot() if e["name"] == "health_alarm"][0]
    assert alarm["track"] == "health" and alarm["args"]["findings"]


def test_monitor_abort_policy_raises_typed_error():
    mon = HealthMonitor("abort")
    collapsed = dict(_healthy_sample(), health_eff_rank=1.2)
    with pytest.raises(RepresentationHealthError, match="effective rank"):
        mon.ingest([(1, collapsed), (2, collapsed)])


def test_monitor_min_samples_guard_and_gauges():
    from simclr_pytorch_distributed_tpu.utils import prom

    mon = HealthMonitor(
        "abort", thresholds=HealthThresholds(min_samples=3)
    )
    collapsed = dict(_healthy_sample(), health_eff_rank=1.0)
    gauges = prom.TrainerGauges(clock=lambda: 0.0)
    assert mon.ingest([(1, collapsed)], gauges=gauges) == []  # 1 < 3
    assert gauges.collect()["health_eff_rank"] == pytest.approx(1.0)
    assert mon.ingest([(2, collapsed)], gauges=gauges) == []  # 2 < 3
    with pytest.raises(RepresentationHealthError):
        mon.ingest([(3, collapsed)], gauges=gauges)


def test_monitor_nonfinite_health_value_is_divergence():
    mon = HealthMonitor("warn")
    diverging = dict(_healthy_sample(), health_grad_norm=float("inf"))
    findings = mon.ingest([(1, diverging), (2, _healthy_sample())])
    assert any("non-finite" in f for f in findings)
    # ...and it never re-alarms for the SAME non-finite events
    assert mon.ingest([(3, _healthy_sample())]) == []


def test_monitor_nonfinite_surfaces_below_min_samples():
    """A non-finite health value is a hard signal: it must surface even
    while the window is below min_samples (one health sample per flush is
    the print_freq == health_freq cadence), never be swallowed by the
    windowed-verdict guard."""
    mon = HealthMonitor(
        "warn", thresholds=HealthThresholds(min_samples=3)
    )
    diverging = dict(_healthy_sample(), health_grad_norm=float("inf"))
    findings = mon.ingest([(1, diverging)])  # 1 sample < min_samples=3
    assert any("non-finite" in f for f in findings)
    assert mon.alarms == 1


def test_monitor_grad_norm_bar():
    mon = HealthMonitor(
        "warn", thresholds=HealthThresholds(grad_norm_max=10.0)
    )
    hot = dict(_healthy_sample(), health_grad_norm=50.0)
    findings = mon.ingest([(1, hot), (2, hot)])
    assert any("gradient norm" in f for f in findings)


def test_monitor_rejects_unknown_policy():
    with pytest.raises(ValueError):
        HealthMonitor("explode")


def test_health_abort_classified_as_code3_collectively():
    """A RepresentationHealthError stored by a flush job exits the boundary
    as ITSELF (failure code 3), not as the NaN policy's NonFiniteLossError
    and not as TelemetryFlushError — the type the driver's policy switch
    keys on."""
    from simclr_pytorch_distributed_tpu.utils.telemetry import TelemetrySession

    session = TelemetrySession(2, ("loss",), "sync")

    def bad_job():
        raise RepresentationHealthError(["collapse"], 7)

    session.executor.submit(bad_job)
    with pytest.raises(RepresentationHealthError):
        session.check_failures_global(7)
    session.close()


# ------------------------------------------- driver-level collapse injection


def test_collapse_injection_driver_aborts_with_typed_error(
    tmp_path, monkeypatch
):
    """Feed the REAL supcon driver constant embeddings (two_view_forward
    monkeypatched to a degenerate constant-feature forward): the windowed
    detector must fire through the ring->flush->monitor path, leave a
    health_alarm event in events.jsonl, and — under --health_policy abort —
    exit run() with the typed RepresentationHealthError."""
    import jax as _jax

    from simclr_pytorch_distributed_tpu import config as config_lib
    from simclr_pytorch_distributed_tpu.data import cifar as cifar_lib
    from simclr_pytorch_distributed_tpu.parallel import mesh as mesh_lib
    from simclr_pytorch_distributed_tpu.train import supcon as supcon_driver

    orig_synth = cifar_lib.synthetic_dataset
    monkeypatch.setattr(
        cifar_lib, "synthetic_dataset",
        lambda n=2048, num_classes=10, seed=0, size=32: orig_synth(
            n=200, num_classes=num_classes, seed=seed, size=SIZE
        ),
    )
    monkeypatch.setattr(
        supcon_driver, "create_mesh",
        lambda devices=None, **kw: mesh_lib.create_mesh(
            devices=_jax.devices()[:1] if devices is None else devices, **kw
        ),
    )

    def constant_forward(model, params, batch_stats, images, *, train=True,
                         with_features=False):
        B = images.shape[0]
        feats = jnp.ones((2 * B, 16), jnp.float32)
        if with_features:
            return (feats, feats), batch_stats
        return feats, batch_stats

    monkeypatch.setattr(supcon_step, "two_view_forward", constant_forward)

    cfg = config_lib.SupConConfig(
        model="resnet10", dataset="synthetic", batch_size=32, epochs=2,
        learning_rate=0.05, cosine=True, save_freq=5, print_freq=2,
        size=SIZE, workdir=str(tmp_path), seed=0, method="SimCLR",
        telemetry="sync", data_placement="host",
        health_freq=1, health_policy="abort",
    )
    cfg = config_lib.finalize_supcon(cfg)
    with pytest.raises(RepresentationHealthError, match="collapse"):
        supcon_driver.run(cfg)

    events_path = os.path.join(cfg.save_folder, "events.jsonl")
    events = [json.loads(x) for x in open(events_path).read().splitlines()]
    alarms = [e for e in events if e["name"] == "health_alarm"]
    assert alarms and alarms[0]["args"]["policy"] == "abort"
    assert any("collapse" in f for f in alarms[0]["args"]["findings"])
    # the boundary observed it as the collective code-3 exit
    failures = [e for e in events if e["name"] == "flush_failure"]
    assert failures and failures[0]["args"]["code"] == 3


# --------------------------------- the zero-sync proof (acceptance criteria)


def test_health_and_probe_add_no_device_transfers(tmp_path, monkeypatch):
    """PR 7's mechanical recorder proof, re-run with health metrics AND the
    online probe enabled: one real supcon epoch under device placement
    counts EXACTLY the PR-4/PR-5 contract — 3 ring D2H (windows 2+2+1 of a
    5-step epoch at print_freq 2) and 1 index upload — so the whole
    training-health layer adds zero per-step transfers or syncs."""
    import jax as _jax

    from simclr_pytorch_distributed_tpu import config as config_lib
    from simclr_pytorch_distributed_tpu.data import cifar as cifar_lib
    from simclr_pytorch_distributed_tpu.data import device_store
    from simclr_pytorch_distributed_tpu.parallel import mesh as mesh_lib
    from simclr_pytorch_distributed_tpu.train import supcon as supcon_driver
    from simclr_pytorch_distributed_tpu.utils.telemetry import TelemetrySession

    orig_synth = cifar_lib.synthetic_dataset
    monkeypatch.setattr(
        cifar_lib, "synthetic_dataset",
        lambda n=2048, num_classes=10, seed=0, size=32: orig_synth(
            n=200, num_classes=num_classes, seed=seed, size=SIZE
        ),
    )
    monkeypatch.setattr(
        supcon_driver, "create_mesh",
        lambda devices=None, **kw: mesh_lib.create_mesh(
            devices=_jax.devices()[:1] if devices is None else devices, **kw
        ),
    )

    counts = {"ring": 0, "index": 0}

    class CountingSession(TelemetrySession):
        def __init__(self, window, keys, mode="async", **kw):
            def counting_get(x):
                counts["ring"] += 1
                return _jax.device_get(x)

            super().__init__(window, keys, mode, device_get=counting_get, **kw)

    real_store = device_store.DeviceStore

    class CountingStore(real_store):
        def __init__(self, loader, mesh, **kw):
            super().__init__(loader, mesh, **kw)
            inner = self._index_put

            def counting_put(idx):
                counts["index"] += 1
                return inner(idx)

            self._index_put = counting_put

    monkeypatch.setattr(supcon_driver, "TelemetrySession", CountingSession)
    monkeypatch.setattr(device_store, "DeviceStore", CountingStore)

    cfg = config_lib.SupConConfig(
        model="resnet10", dataset="synthetic", batch_size=32, epochs=1,
        learning_rate=0.05, cosine=True, save_freq=5, print_freq=2,
        size=SIZE, workdir=str(tmp_path), seed=0, method="SimCLR",
        telemetry="sync", data_placement="device", flight_recorder="on",
        health_freq=1, online_probe="on", health_policy="warn",
    )
    cfg = config_lib.finalize_supcon(cfg)
    supcon_driver.run(cfg)

    # the mechanical bound: exactly the pre-health transfer contract
    assert counts == {"ring": 3, "index": 1}

    # ...and the health stream really flowed through those same transfers
    events_path = os.path.join(cfg.save_folder, "events.jsonl")
    events = [json.loads(x) for x in open(events_path).read().splitlines()]
    windows = [e for e in events if e["name"] == "health_window"]
    assert len(windows) == 3  # one summary per flushed window
    last = windows[-1]["args"]
    for k in HEALTH_METRIC_KEYS + ONLINE_PROBE_METRIC_KEYS:
        assert k in last and math.isfinite(last[k]), k
    assert not [e for e in events if e["name"] == "health_alarm"]


# ------------------------------------------------- health_report + the gate


def _window_event(step, **over):
    args = dict(_healthy_sample(), step=step)
    args.update(over)
    return {"name": "health_window", "track": "health", "ph": "i",
            "ts": 0.1 * step, "args": args}


def test_health_report_builds_timeline_and_series():
    import scripts.health_report as hr

    events = [
        {"name": "flush_boundary", "track": "main:flush", "ph": "X",
         "ts": 0.0, "dur": 0.01},
        _window_event(2, probe_top1=20.0),
        _window_event(4, probe_top1=40.0, health_align=0.6),
    ]
    rep = hr.build_report(events)
    assert rep["consistency"]["ok"]
    assert rep["consistency"]["n_windows"] == 2
    assert rep["series"]["health_align"]["last"] == 0.6
    assert rep["probe"] == {
        "first_top1": 20.0, "last_top1": 40.0, "best_top1": 40.0,
        "windows": 2,
    }
    assert rep["findings"] == []


def test_health_report_flags_alarms_and_collapse_signature():
    import scripts.health_report as hr

    events = [
        _window_event(2),
        {"name": "health_alarm", "track": "health", "ph": "i", "ts": 0.3,
         "args": {"step": 4, "policy": "warn", "findings": ["collapse: x"]}},
        _window_event(
            4, health_eff_rank=1.0, health_align=1.0, health_neg_mean=1.0,
        ),
    ]
    rep = hr.build_report(events)
    assert rep["alarms"] and rep["alarms"][0]["step"] == 4
    kinds = {f["kind"] for f in rep["findings"]}
    assert "health_alarm" in kinds and "collapse_signature" in kinds


def test_health_report_consistency_failures():
    import scripts.health_report as hr

    # empty stream
    rep = hr.build_report([{"name": "x", "ph": "i", "ts": 0.0}])
    assert not rep["consistency"]["ok"]
    # torn stream: a window missing a required column
    broken = _window_event(2)
    del broken["args"]["health_unif"]
    rep = hr.build_report([broken])
    assert rep["consistency"]["missing_keys"] == ["health_unif"]
    assert not rep["consistency"]["ok"]
    # non-monotone steps
    rep = hr.build_report([_window_event(4), _window_event(2)])
    assert not rep["consistency"]["ok"]


def test_health_report_gate_record_pass_fail_and_skip():
    import scripts.health_report as hr
    import scripts.ratchet as ratchet

    events = [_window_event(2, probe_top1=15.0),
              _window_event(4, probe_top1=55.0)]
    report = hr.build_report(events)
    artifact = hr.build_output("events.jsonl", report, "cpu")
    rec = ratchet.health_report_gate_record(artifact)
    assert rec["ok"] and rec["value"] == 55.0 and "skipped" not in rec

    # probe below the CPU bar fails ON CPU...
    low = hr.build_output(
        "e", hr.build_report([_window_event(2, probe_top1=11.0)]), "cpu"
    )
    rec = ratchet.health_report_gate_record(low)
    assert not rec["ok"] and "did not learn" in rec["error"]
    # ...but pass-skips off-CPU with the reason on record
    low_tpu = hr.build_output(
        "e", hr.build_report([_window_event(2, probe_top1=11.0)]), "tpu"
    )
    rec = ratchet.health_report_gate_record(low_tpu)
    assert rec["ok"] and "calibrated for the CPU smoke" in rec["skipped"]

    # an alarm on the healthy smoke fails EVERYWHERE
    alarm_events = [
        _window_event(2),
        {"name": "health_alarm", "track": "health", "ph": "i", "ts": 0.3,
         "args": {"step": 2, "policy": "warn", "findings": ["collapse"]}},
    ]
    bad = hr.build_output(
        "e", hr.build_report(alarm_events), "tpu"
    )
    rec = ratchet.health_report_gate_record(bad)
    assert not rec["ok"] and "false positive" in rec["error"]

    # a torn stream fails everywhere too
    torn = _window_event(2)
    del torn["args"]["health_eff_rank"]
    rec = ratchet.health_report_gate_record(
        hr.build_output("e", hr.build_report([torn]), "tpu")
    )
    assert not rec["ok"] and "inconsistent" in rec["error"]


def test_health_report_cli_roundtrip(tmp_path):
    import scripts.health_report as hr

    events_path = tmp_path / "events.jsonl"
    with open(events_path, "w") as f:
        for e in (_window_event(2), _window_event(4)):
            f.write(json.dumps(e) + "\n")
    out = tmp_path / "report.json"
    assert hr.main(["--events", str(events_path), "--json", str(out)]) == 0
    artifact = json.loads(out.read_text())
    assert artifact["schema"] == hr.SCHEMA
    assert artifact["report"]["consistency"]["ok"]
    assert artifact["device"] == jax.default_backend()
