"""End-to-end smoke: tiny configs through the real drivers on synthetic data,
exercising the full stack (config -> data -> augment -> sharded step -> ckpt ->
probe restore -> validation), all on the virtual 8-device CPU mesh.

Sized for the single-core CPU test host: 16x16 images, a few hundred examples,
a handful of steps — compile time dominates, so keep program count low.
"""

import numpy as np
import pytest

pytestmark = pytest.mark.slow  # compile-heavy: sharded-step programs on the 1-core CPU host

from simclr_pytorch_distributed_tpu import config as config_lib
from simclr_pytorch_distributed_tpu.data import cifar as cifar_lib
from simclr_pytorch_distributed_tpu.train import ce as ce_driver
from simclr_pytorch_distributed_tpu.train import linear as linear_driver
from simclr_pytorch_distributed_tpu.train import supcon as supcon_driver

SIZE = 16  # image side for all integration runs


@pytest.fixture(autouse=True)
def small_synthetic(monkeypatch):
    import jax

    from simclr_pytorch_distributed_tpu.parallel import mesh as mesh_lib

    orig = cifar_lib.synthetic_dataset

    def small(n=2048, num_classes=10, seed=0, size=32):
        return orig(n=320, num_classes=num_classes, seed=seed, size=SIZE)

    monkeypatch.setattr(cifar_lib, "synthetic_dataset", small)

    # 1-device mesh: the GSPMD partitioner cost on the 1-core CPU host scales
    # with partition count, and multi-way sharding semantics are covered by
    # test_distributed.py — integration only needs the drivers end-to-end.
    # The drivers import create_mesh by name, so patch their module bindings.
    def limited_create_mesh(devices=None, **kw):
        if devices is None:
            devices = jax.devices()[:1]
        return mesh_lib.create_mesh(devices=devices, **kw)

    for driver in (supcon_driver, linear_driver, ce_driver):
        monkeypatch.setattr(driver, "create_mesh", limited_create_mesh)


def supcon_cfg(tmp_path, **over):
    base = dict(
        model="resnet10", dataset="synthetic", batch_size=64, epochs=2,
        learning_rate=0.05, temp=0.5, cosine=True, syncBN=True,
        save_freq=2, print_freq=2, size=SIZE, workdir=str(tmp_path),
        seed=0, method="SimCLR",
    )
    base.update(over)
    cfg = config_lib.SupConConfig(**base)
    return config_lib.finalize_supcon(cfg)


def test_supcon_then_probe_end_to_end(tmp_path):
    cfg = supcon_cfg(tmp_path)
    state = supcon_driver.run(cfg)
    # synthetic: 320 - 40 test = 280 train -> 4 steps/epoch at batch 64
    assert int(state.step) == 2 * (280 // 64)

    lcfg = config_lib.LinearConfig(
        model="resnet10", dataset="synthetic", batch_size=64, epochs=2,
        learning_rate=0.5, size=SIZE, val_batch_size=40, workdir=str(tmp_path),
        ckpt=f"{cfg.save_folder}/last", print_freq=2,
    )
    lcfg = config_lib.finalize_linear(lcfg)
    best_acc, best_acc5 = linear_driver.run(lcfg)
    # synthetic data is class-conditional color: even 2 epochs beats chance (10%)
    assert best_acc > 15.0, best_acc
    assert best_acc5 >= best_acc


def test_supcon_resume(tmp_path):
    cfg = supcon_cfg(tmp_path, epochs=1, save_freq=1)
    state1 = supcon_driver.run(cfg)
    cfg2 = supcon_cfg(tmp_path, epochs=2, resume=f"{cfg.save_folder}/last")
    state2 = supcon_driver.run(cfg2)
    assert int(state2.step) == 2 * int(state1.step)


def test_ce_driver_end_to_end(tmp_path):
    # lr 0.1: lr=0.5 was on the edge of divergence for a from-scratch CNN on
    # 280 samples — tiny numeric perturbations flipped the trajectory between
    # ~8% and ~20% val top-1. At lr 0.1 / 6 epochs the margin over the 30%
    # bar is wide (72.5% observed on rn10 with this exact seed/config; 10
    # epochs reached 60-82% on rn18 — trimmed to keep `pytest -m slow` inside
    # a 10-minute harness budget).
    cfg = config_lib.LinearConfig(
        model="resnet10", dataset="synthetic", batch_size=64, epochs=6,
        learning_rate=0.1, size=SIZE, val_batch_size=40, workdir=str(tmp_path),
        print_freq=100,
    )
    cfg = config_lib.finalize_linear(cfg, prefix="ce_")
    best_acc, best_acc5 = ce_driver.run(cfg)
    assert best_acc > 30.0, (best_acc, best_acc5)
    assert best_acc5 >= best_acc
