"""Device-resident data placement (data/device_store.py).

The contract under test is the ISSUE-7 tentpole: with ``--data_placement
device`` every training batch is BYTE-IDENTICAL to what the host
``EpochLoader`` would have produced — full epochs, mid-epoch resume, and the
multi-process slicing — while the hot loop performs exactly ONE host->device
transfer per epoch (the int32 index matrix). All on the virtual 8-device CPU
mesh (conftest.py).
"""

import logging

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from simclr_pytorch_distributed_tpu.data import device_store
from simclr_pytorch_distributed_tpu.data.device_store import (
    DeviceStore,
    epoch_index_matrix,
    resident_bytes_per_device,
    resolve_data_placement,
    slice_epoch_step,
)
from simclr_pytorch_distributed_tpu.data.pipeline import EpochLoader
from simclr_pytorch_distributed_tpu.parallel.mesh import create_mesh
from simclr_pytorch_distributed_tpu.train.supcon_step import epoch_position

pytestmark = pytest.mark.resident


def _dataset(n=70, size=8, seed=0):
    rng = np.random.default_rng(seed)
    images = rng.integers(0, 256, (n, size, size, 3), dtype=np.uint8)
    labels = rng.integers(0, 10, n).astype(np.int32)
    return images, labels


# ------------------------------------------------------------ bit-identity


def test_device_batches_byte_equal_to_host_loader_full_epochs():
    """Every step of two epochs: the resident buffer row equals the host
    loader's batch, bytes and labels alike (the acceptance contract)."""
    images, labels = _dataset()
    loader = EpochLoader(images, labels, 16, base_seed=5)
    mesh = create_mesh()  # the full 8-device virtual mesh
    store = DeviceStore(loader, mesh)
    for epoch in (1, 2):
        ep_imgs, ep_labs = store.epoch_buffers(epoch)
        dev_imgs, dev_labs = np.asarray(ep_imgs), np.asarray(ep_labs)
        assert dev_imgs.dtype == np.uint8 and dev_labs.dtype == np.int32
        host = list(loader.epoch(epoch))
        assert len(host) == loader.steps_per_epoch
        for s, (h_imgs, h_labs) in enumerate(host):
            np.testing.assert_array_equal(dev_imgs[s], h_imgs)
            np.testing.assert_array_equal(dev_labs[s], h_labs)


def test_mid_epoch_resume_is_a_slice_offset_shift():
    """``epoch(e, start_step=k)`` equals the buffer rows from position k on,
    and the in-program position (epoch_position of the restored global step)
    lands exactly there — the resume path never replays consumed batches."""
    images, labels = _dataset()
    loader = EpochLoader(images, labels, 16, base_seed=5)
    mesh = create_mesh()
    store = DeviceStore(loader, mesh)
    steps = loader.steps_per_epoch
    epoch, start_step = 3, 2
    dev_imgs = np.asarray(store.epoch_buffers(epoch)[0])
    resumed = list(loader.epoch(epoch, start_step=start_step))
    assert len(resumed) == steps - start_step
    for off, (h_imgs, _) in enumerate(resumed):
        np.testing.assert_array_equal(dev_imgs[start_step + off], h_imgs)
    # the restored counter maps to the right slice position on device
    gstep = (epoch - 1) * steps + start_step
    pos = int(jax.jit(epoch_position, static_argnums=1)(
        jnp.int32(gstep), steps
    ))
    assert pos == start_step


def test_sliced_step_batch_matches_host_batch_under_jit():
    """The jitted leading-axis slice (what the resident train step runs)
    returns the host loader's exact batch for a traced position."""
    images, labels = _dataset()
    loader = EpochLoader(images, labels, 16, base_seed=9)
    mesh = create_mesh()
    store = DeviceStore(loader, mesh)
    ep_imgs, ep_labs = store.epoch_buffers(1)
    sliced = jax.jit(slice_epoch_step)
    host = list(loader.epoch(1))
    for s, (h_imgs, h_labs) in enumerate(host):
        im, lb = sliced(ep_imgs, ep_labs, jnp.int32(s))
        np.testing.assert_array_equal(np.asarray(im), h_imgs)
        np.testing.assert_array_equal(np.asarray(lb), h_labs)


def test_multi_process_virtual_mesh_slices_match_per_process_loaders():
    """Multi-host layout: column block p of the index matrix IS process p's
    ``EpochLoader`` stream, so a mesh whose data axis spans processes gives
    each process's devices exactly its host-loader slice of every global
    batch (the virtual-mesh stand-in for a real pod run, which
    tests/test_multiprocess.py covers end-to-end)."""
    images, labels = _dataset(n=64)
    nproc, global_batch = 4, 16
    per_proc = global_batch // nproc
    ref = EpochLoader(images, labels, global_batch, base_seed=3)
    idx = epoch_index_matrix(ref, epoch=5)
    assert idx.shape == (ref.steps_per_epoch, global_batch)
    for p in range(nproc):
        shard_loader = EpochLoader(
            images, labels, global_batch, base_seed=3,
            process_index=p, process_count=nproc,
        )
        for s, (h_imgs, h_labs) in enumerate(shard_loader.epoch(5)):
            cols = idx[s, p * per_proc:(p + 1) * per_proc]
            np.testing.assert_array_equal(images[cols], h_imgs)
            np.testing.assert_array_equal(labels[cols], h_labs)


# ------------------------------------------------------- transfer counting


def test_one_index_upload_per_epoch():
    """The per-epoch H2D is ONE index-matrix transfer: repeated buffer
    requests for the same epoch hit the cache; a new epoch uploads once."""
    images, labels = _dataset()
    loader = EpochLoader(images, labels, 16, base_seed=5)
    mesh = create_mesh()
    uploads = []

    def counting_put(idx):
        uploads.append(idx.nbytes)
        return jax.device_put(idx)

    store = DeviceStore(loader, mesh, index_put=counting_put)
    store.epoch_buffers(1)
    store.epoch_buffers(1)
    store.epoch_buffers(1)
    assert len(uploads) == 1
    b1 = store.epoch_buffers(2)
    assert len(uploads) == 2
    assert b1 is store.epoch_buffers(2)  # cached object, no regather
    # and the transfer really is the tiny index vector, not the data
    assert uploads[0] == loader.steps_per_epoch * 16 * 4  # int32


# ------------------------------------------------------ placement resolve


def test_resolve_placement_host_and_device_pass_through():
    images, labels = _dataset()
    mesh = create_mesh()
    assert resolve_data_placement("host", images, labels, 16, mesh) == "host"
    assert resolve_data_placement(
        "device", images, labels, 16, mesh, budget_bytes=1 << 30
    ) == "device"
    with pytest.raises(ValueError, match="unknown data_placement"):
        resolve_data_placement("hbm", images, labels, 16, mesh)


def test_resolve_auto_falls_back_over_budget_with_banner(caplog):
    images, labels = _dataset()
    mesh = create_mesh()
    with caplog.at_level(logging.WARNING, logger="simclr_pytorch_distributed_tpu.data.device_store"):
        got = resolve_data_placement(
            "auto", images, labels, 16, mesh, budget_bytes=10
        )
    assert got == "host"
    assert any("auto -> host" in r.message for r in caplog.records)
    # explicit 'device' over budget fails loudly at startup, never OOMs
    with pytest.raises(ValueError, match="cannot be satisfied"):
        resolve_data_placement(
            "device", images, labels, 16, mesh, budget_bytes=10
        )


def test_resolve_never_makes_a_memmap_resident(tmp_path):
    """A memmap-backed dataset disqualifies RESIDENCY on every path
    (paging the whole tree into RAM/HBM): explicit 'device' raises, and
    'auto' walks the ladder past the resident rung — to 'window' when the
    double-buffered window fits (tests/test_window_store.py proves the
    windowed contract), to 'host' when nothing does."""
    images, labels = _dataset()
    mm_path = tmp_path / "imgs.npy"
    np.save(mm_path, images)
    mm = np.load(mm_path, mmap_mode="r")
    mesh = create_mesh()
    assert isinstance(mm, np.memmap)
    assert resolve_data_placement(
        "auto", mm, labels, 16, mesh, budget_bytes=1 << 30
    ) == "window"
    assert resolve_data_placement(
        "auto", mm, labels, 16, mesh, budget_bytes=10
    ) == "host"
    with pytest.raises(ValueError, match="memmap"):
        resolve_data_placement(
            "device", mm, labels, 16, mesh, budget_bytes=1 << 30
        )
    # the PRODUCTION path: EpochLoader's ascontiguousarray strips the
    # np.memmap subclass into a plain ndarray VIEW (no copy — base chain
    # still ends at the on-disk file); make_store must still see through
    # it, or residency would silently page the whole tree into RAM/HBM
    loader = EpochLoader(mm, labels, 16, base_seed=0)
    assert not isinstance(loader.images, np.memmap)
    assert device_store._is_memmap_backed(loader.images)
    store = device_store.make_store(
        "auto", loader, mesh, budget_bytes=1 << 30
    )
    assert not isinstance(store, DeviceStore)


def test_resident_bytes_accounting():
    """dataset (replicated) + 2x the sharded drop_last epoch buffer."""
    images, labels = _dataset(n=70)
    row = images[0].nbytes + 4
    used = (70 // 16) * 16
    assert resident_bytes_per_device(images, labels, 16, 1) == (
        70 * row + 2 * used * row
    )
    # 8-way sharding divides only the buffer term
    assert resident_bytes_per_device(images, labels, 16, 8) == (
        70 * row + 2 * ((used * row + 7) // 8)
    )


def test_store_rejects_bad_geometry():
    images, labels = _dataset(n=70)
    mesh = create_mesh()  # data axis = 8
    ragged = EpochLoader(images, labels, 16, drop_last=False, shuffle=False)
    with pytest.raises(ValueError, match="drop_last"):
        DeviceStore(ragged, mesh)
    indivisible = EpochLoader(images, labels, 12, base_seed=0)
    with pytest.raises(ValueError, match="divisible"):
        DeviceStore(indivisible, mesh)


def test_make_store_resolves_from_the_loader_itself():
    """The drivers' one-call entry point: what resolution inspects must be
    exactly what the store would upload (the loader's own arrays), and the
    store/None contract follows the verdict."""
    images, labels = _dataset()
    mesh = create_mesh()
    loader = EpochLoader(images, labels, 16, base_seed=3)
    store = device_store.make_store("auto", loader, mesh,
                                    budget_bytes=1 << 30)
    assert store is not None and store.loader is loader
    assert device_store.make_store("auto", loader, mesh,
                                   budget_bytes=10) is None
    assert device_store.make_store("host", loader, mesh) is None


def test_device_budget_bytes_falls_back_without_memory_stats():
    # CPU devices report no memory stats -> the fixed conservative default
    assert device_store.device_budget_bytes() > 0


def test_resolve_placement_verdict_is_collective(monkeypatch, caplog):
    """The budget reads LOCAL memory_stats, but placement selects which
    collective programs a process runs — a split verdict across hosts would
    deadlock the pod at the first epoch's gather. One over-budget peer must
    send EVERY process to host placement ('auto') or raise on every process
    (explicit 'device')."""
    images, labels = _dataset()
    mesh = create_mesh()
    calls = []

    def peer_disagrees(local_ok):
        calls.append(local_ok)
        return False  # some OTHER process was over budget; we were fine

    monkeypatch.setattr(
        device_store, "_agree_across_processes", peer_disagrees
    )
    with caplog.at_level(logging.WARNING, logger="simclr_pytorch_distributed_tpu.data.device_store"):
        got = resolve_data_placement(
            "auto", images, labels, 16, mesh, budget_bytes=1 << 30
        )
    assert got == "host"
    # 'auto' walks BOTH ladder rungs as matched collective points (the
    # rung-1 result is identical everywhere, so every process proceeds to
    # rung 2 together); our local verdict was 'fits' at each
    assert calls == [True, True]
    assert any("peer process" in r.message for r in caplog.records)
    calls.clear()
    with pytest.raises(ValueError, match="peer process"):
        resolve_data_placement(
            "device", images, labels, 16, mesh, budget_bytes=1 << 30
        )
    assert calls == [True]  # explicit 'device': one collective point
    # each collective point is entered with the LOCAL verdict — a locally
    # over-budget process still participates in the allgathers (matched
    # schedules) before taking its reject path
    calls.clear()
    with caplog.at_level(logging.WARNING, logger="simclr_pytorch_distributed_tpu.data.device_store"):
        got = resolve_data_placement(
            "auto", images, labels, 16, mesh, budget_bytes=10
        )
    assert got == "host" and calls == [False, False]
