"""The fleet supervisor (simclr_pytorch_distributed_tpu/supervise/).

Three layers, matching the package split:

- the PURE decision policy, enumerated exhaustively (exit-code table,
  precedence, backoff growth/cap, budget, resize upgrade) — no processes;
- the signal collectors: Prometheus text parsing (round-tripped through
  utils/prom.render_prometheus — parser and renderer must agree), the
  incremental run-dir watcher, resume-dir resolution, the topology env
  rewrite;
- the LOOP against scripted children (the test_launchers stub pattern,
  python edition): exit-code sequences drive real Popen children, and the
  supervisor's decisions + events.jsonl records are asserted end to end.

The real-driver scenarios (SIGKILL / stall / collapse / resize against the
actual pretrain loop) live in tests/test_fault_injection.py and
scripts/supervisor_matrix.py; the committed evidence artifact their matrix
produced is gate-checked here through ratchet's pure
``supervisor_gate_record``.
"""

import json
import os
import sys
import threading

import pytest

from simclr_pytorch_distributed_tpu.supervise import launch, observe, policy
from simclr_pytorch_distributed_tpu.supervise.supervisor import (
    SuperviseConfig,
    Supervisor,
)
from simclr_pytorch_distributed_tpu.utils import prom

pytestmark = pytest.mark.supervisor

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


# ------------------------------------------------------------------ policy


def obs(rc, **kw):
    return policy.ExitObservation(returncode=rc, **kw)


def test_policy_exit_code_table():
    """The full classification table, one fresh policy per row."""
    rows = {
        0: policy.DONE,
        75: policy.RESTART,
        3: policy.GIVE_UP,             # health abort: never retried
        1: policy.BACKOFF_RESTART,     # NaN / unhandled crash
        2: policy.BACKOFF_RESTART,     # flush (I/O) failure
        -9: policy.BACKOFF_RESTART,    # SIGKILL
        -15: policy.BACKOFF_RESTART,   # SIGTERM death
        7: policy.BACKOFF_RESTART,     # unclassified nonzero
    }
    for rc, action in rows.items():
        p = policy.DecisionPolicy(max_restarts=3)
        assert p.decide(obs(rc)).action == action, rc


def test_policy_health_abort_outranks_budget_and_never_retries():
    """Exit 3 gives up even with a full budget left (collapse lives in the
    weights — the RESILIENCE.md precedence note), and also after restarts."""
    p = policy.DecisionPolicy(max_restarts=100)
    d = p.decide(obs(3))
    assert d.action == policy.GIVE_UP and "collapse" in d.reason


def test_policy_restart_budget_bounds_every_failure_class():
    p = policy.DecisionPolicy(max_restarts=2, backoff_base_s=0.1)
    assert p.decide(obs(75)).action == policy.RESTART
    assert p.decide(obs(-9)).action == policy.BACKOFF_RESTART
    d = p.decide(obs(1))
    assert d.action == policy.GIVE_UP and "budget" in d.reason
    # ...and 0 still reports done after exhaustion
    assert p.decide(obs(0)).action == policy.DONE


def test_policy_backoff_grows_exponentially_and_caps():
    p = policy.DecisionPolicy(
        max_restarts=100, backoff_base_s=1.0, backoff_max_s=5.0
    )
    delays = [p.decide(obs(-9)).delay_s for _ in range(5)]
    assert delays == [1.0, 2.0, 4.0, 5.0, 5.0]  # 2^k then the cap


def test_policy_clean_preemption_resets_failure_streak():
    p = policy.DecisionPolicy(max_restarts=100, backoff_base_s=1.0)
    p.decide(obs(-9))
    p.decide(obs(-9))
    assert p.decide(obs(-9)).delay_s == 4.0
    assert p.decide(obs(75)).delay_s == 0.0   # no backoff on preemption
    # streak reset: the next failure waits the base again
    assert p.decide(obs(-9)).delay_s == 1.0


def test_policy_pending_resize_upgrades_any_restartable_exit():
    """The resize request is the OPERATOR'S — it must survive whichever
    exit happens to land first (clean preempt or a crash), and it is
    consumed exactly once."""
    p = policy.DecisionPolicy(max_restarts=10)
    p.request_resize(4)
    d = p.decide(obs(75))
    assert d.action == policy.RESTART_RESIZED and d.devices == 4
    assert p.decide(obs(75)).action == policy.RESTART  # consumed

    p2 = policy.DecisionPolicy(max_restarts=10)
    p2.request_resize(2)
    d2 = p2.decide(obs(-9))
    assert d2.action == policy.RESTART_RESIZED and d2.devices == 2
    assert d2.delay_s > 0  # the crash's backoff still applies


def test_policy_stalled_observation_reason_names_the_kill():
    p = policy.DecisionPolicy(max_restarts=3)
    d = p.decide(obs(-9, stalled=True, stall_dumps=2))
    assert d.action == policy.BACKOFF_RESTART and "stalled" in d.reason


def test_policy_rejects_bad_config():
    with pytest.raises(ValueError):
        policy.DecisionPolicy(max_restarts=-1)
    with pytest.raises(ValueError):
        policy.DecisionPolicy(backoff_base_s=0.0)
    with pytest.raises(ValueError):
        policy.DecisionPolicy(backoff_base_s=2.0, backoff_max_s=1.0)
    with pytest.raises(ValueError):
        policy.DecisionPolicy().request_resize(0)


# ------------------------------------------------- the straggler ladder rows


def sobs(rc=75, **kw):
    """A mitigation-preempt exit: the supervisor gracefully preempted the
    child after a persistence verdict naming host 1 of 2 at 150 ms skew."""
    kw.setdefault("straggler_persistent", True)
    kw.setdefault("straggler_host", 1)
    kw.setdefault("straggler_skew_s", 0.15)
    kw.setdefault("processes", 2)
    return obs(rc, **kw)


@pytest.mark.chaos
def test_policy_extended_table_with_straggler_verdict():
    """The FULL extended classification table: every exit-code row crossed
    with a pending persistence verdict. Only the clean mitigation preempt
    (75, not stalled) takes the ladder; every other row keeps its
    pre-ladder decision — the verdict rides along as context, never as an
    override."""
    rows = {
        0: policy.DONE,                    # completed: no mitigation needed
        75: policy.RESTART_REBALANCED,     # the ladder's first rung
        3: policy.GIVE_UP,                 # health abort still outranks all
        1: policy.BACKOFF_RESTART,         # crash before the preempt landed
        2: policy.BACKOFF_RESTART,
        -9: policy.BACKOFF_RESTART,        # mitigation SIGTERM lapsed to KILL
        -15: policy.BACKOFF_RESTART,
        7: policy.BACKOFF_RESTART,
    }
    for rc, action in rows.items():
        p = policy.DecisionPolicy(max_restarts=10)
        assert p.decide(sobs(rc)).action == action, rc
        # the ladder only advanced on the one row that took it
        assert p.straggler_level == (1 if action == policy.RESTART_REBALANCED
                                     else 0), rc


@pytest.mark.chaos
def test_policy_straggler_ladder_escalates_then_gives_up():
    """Rung by rung: rebalance (share hint) -> exclude (topology minus the
    slow host) -> give_up, with the budget charged per rung."""
    p = policy.DecisionPolicy(max_restarts=10)
    d1 = p.decide(sobs())
    assert d1.action == policy.RESTART_REBALANCED
    assert d1.share == "1:0.5" and d1.devices is None
    assert "rebalancing" in d1.reason and d1.delay_s == 0.0
    d2 = p.decide(sobs())
    assert d2.action == policy.RESTART_RESIZED
    assert d2.devices == 1 and d2.share is None   # 2 processes minus host 1
    assert "excluding" in d2.reason
    d3 = p.decide(sobs())
    assert d3.action == policy.GIVE_UP and "ladder exhausted" in d3.reason
    assert p.restarts == 2  # give_up never burned budget


@pytest.mark.chaos
def test_policy_straggler_unknown_fleet_size_excludes_without_topology():
    """A verdict without a process count (older sidecar) still escalates,
    but the exclusion rung cannot compute a topology — devices stays None
    (inherit), the scheduler-level realization."""
    p = policy.DecisionPolicy(max_restarts=10)
    p.decide(sobs())
    d = p.decide(sobs(processes=0))
    assert d.action == policy.RESTART_RESIZED and d.devices is None


@pytest.mark.chaos
def test_policy_clean_preempt_without_verdict_resets_the_ladder():
    """Recovery: a later clean preemption with NO verdict in force means
    the rebalance worked — a straggler relapse starts the ladder at
    rebalance again instead of escalating straight to exclusion."""
    p = policy.DecisionPolicy(max_restarts=10)
    assert p.decide(sobs()).action == policy.RESTART_REBALANCED
    assert p.decide(obs(75)).action == policy.RESTART  # healthy preempt
    assert p.straggler_level == 0
    assert p.decide(sobs()).action == policy.RESTART_REBALANCED  # rung 1 again


@pytest.mark.chaos
def test_policy_pending_operator_resize_outranks_mitigation():
    """Both landing on the same exit: the operator's explicit resize wins,
    consumes the pending target, and the ladder does NOT advance — the
    next verdict still starts at rebalance."""
    p = policy.DecisionPolicy(max_restarts=10)
    p.request_resize(4)
    d = p.decide(sobs())
    assert d.action == policy.RESTART_RESIZED and d.devices == 4
    assert "explicit request wins" in d.reason
    assert p.pending_resize is None and p.straggler_level == 0
    assert p.decide(sobs()).action == policy.RESTART_REBALANCED


@pytest.mark.chaos
def test_policy_budget_caps_the_straggler_ladder():
    """Mitigation restarts draw from the SAME budget as every other class
    (the PREEMPT_RETRIES contract): an exhausted budget turns a verdict
    into give_up before the ladder is consulted."""
    p = policy.DecisionPolicy(max_restarts=1, backoff_base_s=0.1)
    assert p.decide(obs(-9)).action == policy.BACKOFF_RESTART
    d = p.decide(sobs())
    assert d.action == policy.GIVE_UP and "budget" in d.reason
    p0 = policy.DecisionPolicy(max_restarts=0)
    assert p0.decide(sobs()).action == policy.GIVE_UP


@pytest.mark.chaos
def test_policy_stall_kill_outranks_straggler_verdict():
    """A 75 forced by the supervisor's own STALL kill is a failure even
    with a verdict pending: the stall row wins (backoff, no ladder) — a
    wedged child must not be rewarded with a rebalance."""
    p = policy.DecisionPolicy(max_restarts=10, backoff_base_s=1.0)
    d = p.decide(sobs(stalled=True, stall_dumps=1))
    assert d.action == policy.BACKOFF_RESTART and "stalled" in d.reason
    assert p.straggler_level == 0


# ------------------------------------------------------- the straggler tracker


def skew_gauges(step, skew=0.2, straggler=1, processes=2):
    g = {
        "train_step": float(step),
        observe.SKEW_GAUGE: float(skew),
        observe.PROC_COUNT_GAUGE: float(processes),
    }
    if straggler is not None:
        g[observe.STRAGGLER_GAUGE] = float(straggler)
    return g


@pytest.mark.chaos
def test_tracker_k_of_n_verdict_and_consume():
    t = observe.StragglerTracker(0.1, persist_k=3, window_n=5,
                                 clock=lambda: 42.0)
    for step in (1, 2):
        f = t.observe(skew_gauges(step))
        assert f is not None and f["straggler"] == 1
        assert t.take_persistent() is None  # hysteresis: K not reached
    t.observe(skew_gauges(3))
    v = t.take_persistent()
    assert v is not None
    assert v["straggler"] == 1 and v["votes"] == 3 and v["window"] == 3
    assert v["at"] == 42.0 and v["processes"] == 2 and v["share"] == 0.5
    # consuming resets: detection starts fresh
    assert t.take_persistent() is None
    t.observe(skew_gauges(4))
    assert t.take_persistent() is None


@pytest.mark.chaos
def test_tracker_scrapes_dedup_on_the_step_gauge():
    """The skew gauge holds its value between flush boundaries, so many
    scrapes of one boundary must count ONCE — per-poll counting would
    convert one skewed boundary into an instant verdict."""
    t = observe.StragglerTracker(0.1, persist_k=3, window_n=5)
    assert t.observe(skew_gauges(7)) is not None
    for _ in range(10):
        assert t.observe(skew_gauges(7)) is None  # same boundary
    assert t.take_persistent() is None
    # a scrape with NO step gauge still dedups (None == None), not crash
    g = skew_gauges(0)
    del g["train_step"]
    assert t.observe(dict(g)) is not None
    assert t.observe(dict(g)) is None


@pytest.mark.chaos
def test_tracker_below_bar_boundaries_dilute_the_vote():
    """Recovery hysteresis: below-bar boundaries enter the window as
    non-votes, so a host that recovered walks itself back out instead of
    being convicted on stale evidence."""
    t = observe.StragglerTracker(0.1, persist_k=3, window_n=3)
    t.observe(skew_gauges(1))
    t.observe(skew_gauges(2))
    # recovered: two clean boundaries push the spikes out of the window
    t.observe(skew_gauges(3, skew=0.0))
    t.observe(skew_gauges(4, skew=0.0))
    t.observe(skew_gauges(5))
    assert t.take_persistent() is None  # only 1 vote in the last 3


@pytest.mark.chaos
def test_tracker_identity_hop_never_convicts_anyone():
    """Skew whose straggler identity hops between hosts is load imbalance,
    not a sick host: no single host accumulates K votes (a 3-host
    rotation caps any one host at 2 votes in a 5-boundary window)."""
    t = observe.StragglerTracker(0.1, persist_k=3, window_n=5)
    for step in range(1, 13):
        t.observe(skew_gauges(step, straggler=step % 3, processes=3))
        assert t.take_persistent() is None


@pytest.mark.chaos
def test_tracker_single_process_and_missing_identity_are_benign():
    """No identity gauges (older sidecar) or a single-process fleet: the
    finding may still fire (warn), but no vote is ever cast — there is no
    host to rebalance away from."""
    t = observe.StragglerTracker(0.1, persist_k=1, window_n=1)
    assert t.observe(None) is None
    assert t.observe({}) is None
    # single process: identity -1, count 1 (what telemetry publishes)
    f = t.observe(skew_gauges(1, straggler=-1, processes=1))
    assert f is not None and "straggler" not in f
    assert t.take_persistent() is None
    # multi-process but the identity gauge is absent entirely
    f2 = t.observe(skew_gauges(2, straggler=None))
    assert f2 is not None and "straggler" not in f2
    assert t.take_persistent() is None
    # identity present but the fleet-size gauge says single: still benign
    g = skew_gauges(3, straggler=0, processes=1)
    assert t.observe(g) is not None
    assert t.take_persistent() is None
    # the disabled tracker (bar 0) observes nothing at all
    t0 = observe.StragglerTracker(0.0, persist_k=1, window_n=1)
    assert t0.observe(skew_gauges(1)) is None
    assert t0.take_persistent() is None


@pytest.mark.chaos
def test_tracker_reset_clears_stale_votes():
    """A new child attempt restarts its gauge stream: reset() must drop
    accumulated votes AND the step dedup, or attempt 1's skew would
    convict attempt 2 on its first boundary."""
    t = observe.StragglerTracker(0.1, persist_k=3, window_n=5)
    t.observe(skew_gauges(1))
    t.observe(skew_gauges(2))
    t.reset()
    assert t.observe(skew_gauges(2)) is not None  # same step: dedup cleared
    t.observe(skew_gauges(3))
    assert t.take_persistent() is None  # old votes gone: only 2 of 3


@pytest.mark.chaos
def test_tracker_rejects_bad_config():
    with pytest.raises(ValueError):
        observe.StragglerTracker(1.0, persist_k=0)
    with pytest.raises(ValueError):
        observe.StragglerTracker(1.0, persist_k=3, window_n=2)


# ----------------------------------------------------------------- observe


def test_parse_prometheus_roundtrips_render():
    """The parser must invert utils/prom's renderer for the unlabeled gauge
    lines the trainer sidecar emits (labeled histogram series are skipped,
    not misparsed)."""
    text = prom.render_prometheus([
        ("train_step", None, 120),
        ("train_last_boundary_age_seconds", None, 3.25),
        ("train_exit_code", None, 75),
        ("lat_bucket", {"bucket": "b8", "le": "5"}, 3),  # labeled: skipped
    ])
    parsed = observe.parse_prometheus_text(text + "# HELP noise\nbad line x\n")
    assert parsed == {
        "train_step": 120.0,
        "train_last_boundary_age_seconds": 3.25,
        "train_exit_code": 75.0,
    }


def test_scraper_scrapes_a_real_trainer_sidecar():
    """End-to-end against the REAL sidecar server: the supervisor-facing
    gauges (start_time_seconds at construction, exit_code terminal stamp)
    come back through HTTP exactly as TrainerGauges rendered them."""
    g = prom.TrainerGauges(wall_clock=lambda: 1234.5)
    g.beat(7)
    g.set_exit_code(75)
    server = prom.start_metrics_server(0, g.prometheus_text, host="127.0.0.1")
    try:
        port = server.server_address[1]
        scraped = observe.MetricsScraper(port).scrape()
        assert scraped["train_step"] == 7.0
        assert scraped["train_start_time_seconds"] == 1234.5
        assert scraped["train_exit_code"] == 75.0
        assert scraped["train_last_boundary_age_seconds"] >= 0.0
    finally:
        server.shutdown()


def test_scraper_dead_sidecar_returns_none():
    import socket

    with socket.socket() as s:  # grab then release a port: nothing listens
        s.bind(("127.0.0.1", 0))
        port = s.getsockname()[1]
    assert observe.MetricsScraper(port, timeout_s=0.2).scrape() is None


def test_run_dir_watcher_is_incremental(tmp_path):
    run_dir = tmp_path / "run"
    w = observe.RunDirWatcher(str(run_dir))
    assert w.poll() == ([], [], [])  # dir not there yet: not an error

    run_dir.mkdir()
    (run_dir / "stall_dump_1.txt").write_text("stacks")
    events = run_dir / "events.jsonl"
    events.write_text(
        json.dumps({"name": "health_alarm", "args": {"step": 5}}) + "\n"
        + json.dumps({"name": "flush_boundary"}) + "\n"  # not watched
    )
    (run_dir / "ckpt_epoch_1").mkdir()
    (run_dir / "ckpt_epoch_1" / "meta.json").write_text("{}")

    dumps, evs, ckpts = w.poll()
    assert [os.path.basename(d) for d in dumps] == ["stall_dump_1.txt"]
    assert [e["name"] for e in evs] == ["health_alarm"]
    assert ckpts == ["ckpt_epoch_1"]
    assert w.poll() == ([], [], [])  # nothing new -> nothing reported

    # appends surface; a torn (unterminated) last line is deferred, not lost
    with open(events, "a") as f:
        f.write(json.dumps({"name": "preempt_exit"}) + "\n")
        f.write('{"name": "nan_rollb')  # mid-write race
    _, evs, _ = w.poll()
    assert [e["name"] for e in evs] == ["preempt_exit"]
    with open(events, "a") as f:
        f.write('ack"}\n')
    _, evs, _ = w.poll()
    assert [e["name"] for e in evs] == ["nan_rollback"]

    # resumed sessions open rotated files (events_r2.jsonl): watched too
    (run_dir / "events_r2.jsonl").write_text(
        json.dumps({"name": "stall_detected"}) + "\n"
    )
    _, evs, _ = w.poll()
    assert [(e["name"], e["_file"]) for e in evs] == [
        ("stall_detected", "events_r2.jsonl")
    ]


# ------------------------------------------------------------------ launch


def test_find_resume_dir_newest_excluding_probe_and_ce(tmp_path):
    ws = tmp_path / "ws"
    assert launch.find_resume_dir(str(ws)) is None  # no workdir yet
    old = ws / "cifar10_models" / "cifar10_0101_0000_run"
    new = ws / "synthetic_models" / "synthetic_0102_0000_run"
    for d in (old, new):
        d.mkdir(parents=True)
    os.utime(old, (1000, 1000))
    far = 4102444800
    # probe/CE decoys newer than everything must not win (the launcher's
    # awk-filter contract, now in tested python)
    for decoy in ("classifier_0103_0000_x", "ce_0103_0000_y"):
        d = ws / "cifar10_models" / decoy
        d.mkdir()
        os.utime(d, (far, far))
    assert launch.find_resume_dir(str(ws)) == str(new)


def test_topology_env_rewrites_xla_flags_idempotently():
    base = {"XLA_FLAGS": "--foo=1 --xla_force_host_platform_device_count=8",
            "OTHER": "x"}
    env = launch.topology_env(4, base)
    assert env["XLA_FLAGS"] == "--foo=1 --xla_force_host_platform_device_count=4"
    assert env["OTHER"] == "x"
    # append when absent; None leaves the env alone
    env2 = launch.topology_env(2, {"XLA_FLAGS": "--foo=1"})
    assert env2["XLA_FLAGS"] == "--foo=1 --xla_force_host_platform_device_count=2"
    env3 = launch.topology_env(2, {})
    assert env3["XLA_FLAGS"] == "--xla_force_host_platform_device_count=2"
    assert "XLA_FLAGS" not in launch.topology_env(None, {"A": "b"})


def test_build_command_appends_resume_last_wins():
    cmd = launch.build_command(
        ["python", "main_supcon.py", "--resume", "stale"], "/fresh"
    )
    assert cmd.index("stale") < cmd.index("/fresh")  # argparse last-wins
    assert launch.build_command(["x"], None) == ["x"]


@pytest.mark.chaos
def test_share_env_sets_and_clears_the_rebalance_hint():
    base = {launch.FLEET_SHARE_ENV: "0:0.25", "OTHER": "x"}
    env = launch.share_env("1:0.5", base)
    assert env[launch.FLEET_SHARE_ENV] == "1:0.5" and env["OTHER"] == "x"
    # None REMOVES a stale hint (post-exclusion/resize shares are uniform
    # again) rather than inheriting it
    assert launch.FLEET_SHARE_ENV not in launch.share_env(None, base)
    assert base[launch.FLEET_SHARE_ENV] == "0:0.25"  # input not mutated
    # composes with the topology rewrite (the Child launch env)
    env2 = launch.share_env("1:0.5", launch.topology_env(4, {"A": "b"}))
    assert env2[launch.FLEET_SHARE_ENV] == "1:0.5"
    assert "--xla_force_host_platform_device_count=4" in env2["XLA_FLAGS"]
    assert env2["A"] == "b"


# ----------------------------------------------- the loop (scripted children)


def write_scripted_trainer(tmp_path, exit_codes, run_dir_name="synthetic_0101_0000_run",
                           checkpoint=True):
    """A python child that logs its argv, creates a run dir (like a real
    driver's finalize) with a COMPLETE checkpoint inside (a real exit-75 /
    crash-after-save leaves one; ``checkpoint=False`` models dying before
    the first save), and exits per-invocation scripted codes — the
    test_launchers stub-python pattern."""
    script = tmp_path / "scripted_trainer.py"
    log = tmp_path / "calls.log"
    ws = tmp_path / "ws"
    script.write_text(f"""
import json, os, sys
log = {str(log)!r}
with open(log, "a") as f:
    f.write(json.dumps(sys.argv[1:]) + "\\n")
n = sum(1 for _ in open(log))
run_dir = os.path.join({str(ws)!r}, "synthetic_models", {run_dir_name!r})
os.makedirs(run_dir, exist_ok=True)
if {bool(checkpoint)!r}:
    ckpt = os.path.join(run_dir, "ckpt_epoch_1")
    os.makedirs(ckpt, exist_ok=True)
    with open(os.path.join(ckpt, "meta.json"), "w") as f:
        f.write('{{"epoch": 1}}')
codes = {list(exit_codes)!r}
sys.exit(codes[n - 1])
""")
    return script, log, ws


def run_supervisor(cfg):
    sup = Supervisor(cfg)
    rc = sup.run()
    return sup, rc


def read_events(sup):
    with open(os.path.join(sup.supervise_dir, "events.jsonl")) as f:
        return [json.loads(line) for line in f]


def test_loop_preempt_then_done_injects_resume_and_records(tmp_path):
    script, log, ws = write_scripted_trainer(tmp_path, [75, 0])
    cfg = SuperviseConfig(
        command=[sys.executable, str(script)], workdir=str(ws),
        max_restarts=3, poll_s=0.02, backoff_base_s=0.01,
    )
    sup, rc = run_supervisor(cfg)
    assert rc == 0
    assert [d.action for d in sup.decisions] == [policy.RESTART, policy.DONE]
    calls = [json.loads(line) for line in open(log)]
    assert "--resume" not in calls[0]
    assert calls[1][-2:] == [
        "--resume", os.path.join(str(ws), "synthetic_models",
                                 "synthetic_0101_0000_run"),
    ]
    names = [e["name"] for e in read_events(sup)]
    assert names.count("launch") == 2 and names.count("decision") == 2


def test_loop_gives_up_after_budget_with_shell_normalized_rc(tmp_path):
    script, log, ws = write_scripted_trainer(tmp_path, [1, 1])
    cfg = SuperviseConfig(
        command=[sys.executable, str(script)], workdir=str(ws),
        max_restarts=1, poll_s=0.02, backoff_base_s=0.01,
    )
    sup, rc = run_supervisor(cfg)
    assert rc == 1
    assert [d.action for d in sup.decisions] == [
        policy.BACKOFF_RESTART, policy.GIVE_UP,
    ]
    assert len([json.loads(line) for line in open(log)]) == 2


def test_loop_health_abort_gives_up_immediately(tmp_path):
    script, log, ws = write_scripted_trainer(tmp_path, [3])
    cfg = SuperviseConfig(
        command=[sys.executable, str(script)], workdir=str(ws),
        max_restarts=5, poll_s=0.02,
    )
    sup, rc = run_supervisor(cfg)
    assert rc == 3
    assert [d.action for d in sup.decisions] == [policy.GIVE_UP]
    assert len(list(open(log))) == 1  # no relaunch burned on a collapse


def test_loop_resize_request_preempts_and_relaunches_resized(tmp_path):
    """The elastic path with a scripted child: the child sleeps until the
    supervisor's resize-triggered SIGTERM (exiting 75 like the real
    preemption machinery), and the relaunch must carry the new topology in
    XLA_FLAGS plus --resume."""
    log = tmp_path / "calls.log"
    ws = tmp_path / "ws"
    script = tmp_path / "sleeper.py"
    script.write_text(f"""
import json, os, signal, sys, time
log = {str(log)!r}
with open(log, "a") as f:
    f.write(json.dumps([os.environ.get("XLA_FLAGS", "")] + sys.argv[1:]) + "\\n")
n = sum(1 for _ in open(log))
run_dir = os.path.join({str(ws)!r}, "synthetic_models", "synthetic_0101_0000_run")
ckpt = os.path.join(run_dir, "preempt_epoch_1_step_2")
os.makedirs(ckpt, exist_ok=True)
with open(os.path.join(ckpt, "meta.json"), "w") as f:
    f.write('{{"epoch": 0, "step_in_epoch": 2}}')
if n == 1:
    signal.signal(signal.SIGTERM, lambda *a: sys.exit(75))
    time.sleep(60)
sys.exit(0)
""")
    cfg = SuperviseConfig(
        command=[sys.executable, str(script)], workdir=str(ws),
        max_restarts=3, poll_s=0.05, grace_secs=10.0, devices=8,
    )
    sup = Supervisor(cfg)
    box = {}
    t = threading.Thread(target=lambda: box.update(rc=sup.run()), daemon=True)
    t.start()
    # wait for attempt 1, then file the resize request
    deadline = 50.0
    import time as _time

    while not log.exists() and deadline > 0:
        _time.sleep(0.05)
        deadline -= 0.05
    with open(os.path.join(sup.supervise_dir, "resize_request"), "w") as f:
        f.write("2")
    t.join(timeout=60)
    assert not t.is_alive() and box["rc"] == 0
    assert [d.action for d in sup.decisions] == [
        policy.RESTART_RESIZED, policy.DONE,
    ]
    calls = [json.loads(line) for line in open(log)]
    assert "--xla_force_host_platform_device_count=8" in calls[0][0]
    assert "--xla_force_host_platform_device_count=2" in calls[1][0]
    assert "--resume" in calls[1]
    events = read_events(sup)
    assert any(e["name"] == "resize_request" for e in events)
    resized = [e for e in events if e["name"] == "launch"][1]
    assert resized["args"]["devices"] == 2


@pytest.mark.chaos
def test_loop_straggler_mitigation_drives_the_full_ladder(tmp_path):
    """The LOOP end to end with a scripted fleet: a fake scraper keeps
    reporting host 1 as the boundary straggler, and the supervisor must
    walk the whole ladder — mitigation preempt -> restart_rebalanced with
    the FLEET_SHARE_HINT actually in the relaunch's environment ->
    (still slow) -> restart_resized excluding the host -> (still slow) ->
    give_up, exiting with the child's clean 75.

    The scraper serves gauges only once the CURRENT attempt's child has
    installed its SIGTERM handler (it logs after installing), so the
    graceful preempt is deterministic, not a boot race."""
    import time as _time

    log = tmp_path / "calls.log"
    ws = tmp_path / "ws"
    script = tmp_path / "fleet_stub.py"
    script.write_text(f"""
import json, os, signal, sys, time
signal.signal(signal.SIGTERM, lambda *a: sys.exit(75))
run_dir = os.path.join({str(ws)!r}, "synthetic_models", "synthetic_0101_0000_run")
ckpt = os.path.join(run_dir, "preempt_epoch_1_step_2")
os.makedirs(ckpt, exist_ok=True)
with open(os.path.join(ckpt, "meta.json"), "w") as f:
    f.write('{{"epoch": 1, "step_in_epoch": 2}}')
with open({str(log)!r}, "a") as f:
    f.write(json.dumps({{
        "share": os.environ.get("FLEET_SHARE_HINT", ""),
        "xla": os.environ.get("XLA_FLAGS", ""),
    }}) + "\\n")
time.sleep(60)
sys.exit(0)
""")

    class SkewScraper:
        """train_boundary_* gauges naming host 1, a fresh boundary per
        scrape — but only while the newest child is ready (handler
        installed == its log line written)."""

        sup = None

        def __init__(self):
            self.step = 0

        def scrape(self):
            try:
                with open(log) as f:
                    ready = sum(1 for _ in f)
            except OSError:
                ready = 0
            if self.sup is None or ready <= len(self.sup.decisions):
                return None  # current attempt's handler not installed yet
            self.step += 1
            return {
                "train_step": float(self.step),
                observe.SKEW_GAUGE: 0.2,
                observe.STRAGGLER_GAUGE: 1.0,
                observe.PROC_COUNT_GAUGE: 2.0,
            }

    cfg = SuperviseConfig(
        command=[sys.executable, str(script)], workdir=str(ws),
        max_restarts=10, poll_s=0.05, grace_secs=20.0,
        straggler_skew_secs=0.1, straggler_persist_k=3,
        straggler_window_n=5, straggler_mitigate=True,
    )
    scraper = SkewScraper()
    sup = Supervisor(cfg, scraper=scraper)
    scraper.sup = sup
    box = {}
    t = threading.Thread(target=lambda: box.update(rc=sup.run()), daemon=True)
    t.start()
    t.join(timeout=120)
    assert not t.is_alive(), "mitigation ladder never completed"
    assert box["rc"] == 75  # give_up reports the final clean preempt code
    assert [d.action for d in sup.decisions] == [
        policy.RESTART_REBALANCED, policy.RESTART_RESIZED, policy.GIVE_UP,
    ]
    assert sup.decisions[0].share == "1:0.5"
    assert sup.decisions[1].devices == 1  # 2 processes minus the slow host

    calls = [json.loads(line) for line in open(log)]
    assert len(calls) == 3
    # the rebalance hint reached ONLY the rebalanced relaunch's environment
    assert [c["share"] for c in calls] == ["", "1:0.5", ""]
    # ...and the exclusion rung carried the shrunk topology
    assert "--xla_force_host_platform_device_count=1" in calls[2]["xla"]

    events = read_events(sup)
    names = [e["name"] for e in events]
    assert names.count("straggler_persistent") == 3
    mitigation = [e["args"] for e in events
                  if e["name"] == "straggler_mitigation"]
    assert [m["phase"] for m in mitigation] == [
        "preempt", "decided", "preempt", "decided", "preempt", "decided",
    ]
    assert [m.get("action") for m in mitigation if m["phase"] == "decided"] \
        == ["restart_rebalanced", "restart_resized", "give_up"]
    launches = [e["args"] for e in events if e["name"] == "launch"]
    assert [la.get("share") for la in launches] == [None, "1:0.5", None]
    # every relaunch resumed from the preempt save
    assert all(la["resume"] for la in launches[1:])


@pytest.mark.chaos
def test_loop_warn_only_records_verdicts_without_acting(tmp_path):
    """straggler_mitigate=False (the default): verdicts land on the
    recorder as straggler_persistent events, but the child is never
    preempted — the run completes and the decision log shows only DONE."""
    ws = tmp_path / "ws"
    script = tmp_path / "warn_stub.py"
    # lives long enough to be scraped a few times, then completes cleanly
    script.write_text(f"""
import os, sys, time
os.makedirs(os.path.join({str(ws)!r}, "synthetic_models", "r1"), exist_ok=True)
time.sleep(1.5)
sys.exit(0)
""")

    class OneShotSkew:
        def __init__(self):
            self.step = 0

        def scrape(self):
            self.step += 1
            return {
                "train_step": float(self.step),
                observe.SKEW_GAUGE: 0.2,
                observe.STRAGGLER_GAUGE: 1.0,
                observe.PROC_COUNT_GAUGE: 2.0,
            }

    cfg = SuperviseConfig(
        command=[sys.executable, str(script)], workdir=str(ws),
        max_restarts=3, poll_s=0.02, straggler_skew_secs=0.1,
        straggler_persist_k=1, straggler_window_n=1,
    )
    sup = Supervisor(cfg, scraper=OneShotSkew())
    rc = sup.run()
    assert rc == 0
    assert [d.action for d in sup.decisions] == [policy.DONE]
    events = read_events(sup)
    verdicts = [e["args"] for e in events
                if e["name"] == "straggler_persistent"]
    assert verdicts and all(v["mitigate"] is False for v in verdicts)
    assert not [e for e in events if e["name"] == "straggler_mitigation"]


# ------------------------------------------- committed evidence + ratchet gate


def _gate():
    sys.path.insert(0, os.path.join(REPO, "scripts"))
    import ratchet

    return ratchet


def sample_matrix_artifact():
    return {
        "metric": "supervisor_matrix",
        "scenarios": {
            "sigkill": {"ok": True, "rc": 0,
                        "decisions": ["backoff_restart", "done"]},
            "stall": {"ok": True, "rc": 0,
                      "decisions": ["backoff_restart", "done"],
                      "liveness_stalls": 1, "watchdog_dumps_observed": 1},
            "collapse": {"ok": True, "rc": 3, "decisions": ["give_up"],
                         "health_alarms_observed": 1},
            "preempt_resize": {"ok": True, "rc": 0,
                               "decisions": ["restart_resized", "done"],
                               "launch_devices": [8, 4],
                               "resumed_resized": True},
        },
        "ok": True,
    }


def test_supervisor_gate_record_accepts_complete_matrix():
    r = _gate().supervisor_gate_record(sample_matrix_artifact())
    assert r["ok"], r
    assert r["metric"] == "ratchet_supervisor_matrix"
    assert sorted(r["scenarios"]) == [
        "collapse", "preempt_resize", "sigkill", "stall",
    ]


def test_supervisor_gate_record_rejects_missing_or_failed_scenarios():
    gate = _gate()
    art = sample_matrix_artifact()
    del art["scenarios"]["stall"]
    r = gate.supervisor_gate_record(art)
    assert not r["ok"] and "stall" in r["error"]

    art2 = sample_matrix_artifact()
    art2["scenarios"]["sigkill"]["ok"] = False
    r2 = gate.supervisor_gate_record(art2)
    assert not r2["ok"] and "sigkill" in r2["error"]

    # a resize leg that never actually changed topology must not pass
    art3 = sample_matrix_artifact()
    art3["scenarios"]["preempt_resize"]["resumed_resized"] = False
    r3 = gate.supervisor_gate_record(art3)
    assert not r3["ok"]


def test_committed_evidence_artifact_passes_the_gate():
    """docs/evidence/supervisor_r11.json — produced by
    scripts/supervisor_matrix.py driving the REAL supervisor over the real
    driver — must satisfy the same pure gate ratchet runs."""
    path = os.path.join(REPO, "docs", "evidence", "supervisor_r11.json")
    with open(path) as f:
        artifact = json.load(f)
    r = _gate().supervisor_gate_record(artifact)
    assert r["ok"], r


def sample_chaos_artifact():
    return {
        "metric": "chaos_matrix",
        "schema": "chaos_matrix/v1",
        "scenarios": {
            "straggler": {
                "ok": True, "rc": 0,
                "decisions": ["restart_rebalanced", "done"],
                "straggler_findings": 4, "persistence_verdicts": 1,
                "mitigation_events": 2,
                "launch_shares": [None, "1:0.5"],
                "share_hint_carried": "1:0.5",
                "digests": [12.5, 12.5], "control_digests": [12.5, 12.5],
                "bit_identical": True,
            },
            "chaos": {
                "ok": True, "rc": 0,
                "decisions": ["restart_rebalanced", "backoff_restart",
                              "done"],
                "mitigation_events": 2, "killed_pid": 4242,
                "health_alarms_observed": 6,
            },
        },
        "ok": True,
    }


@pytest.mark.chaos
def test_chaos_gate_record_accepts_complete_artifact():
    r = _gate().chaos_gate_record(sample_chaos_artifact())
    assert r["ok"], r
    assert r["metric"] == "ratchet_chaos_matrix"
    assert sorted(r["scenarios"]) == ["chaos", "straggler"]


@pytest.mark.chaos
def test_chaos_gate_record_rejects_weakened_evidence():
    """Each load-bearing claim, individually removed, must fail the gate —
    a hand-edited artifact cannot sneak past on decision strings alone."""
    gate = _gate()
    art = sample_chaos_artifact()
    art["schema"] = "chaos_matrix/v0"
    assert not gate.chaos_gate_record(art)["ok"]

    art = sample_chaos_artifact()
    del art["scenarios"]["chaos"]
    r = gate.chaos_gate_record(art)
    assert not r["ok"] and "chaos" in r["error"]

    art = sample_chaos_artifact()
    art["scenarios"]["straggler"]["decisions"] = ["backoff_restart", "done"]
    assert not gate.chaos_gate_record(art)["ok"]

    art = sample_chaos_artifact()
    art["scenarios"]["straggler"]["rc"] = 75
    assert not gate.chaos_gate_record(art)["ok"]

    # mitigation must have BOTH phases on record (preempt + decided)
    art = sample_chaos_artifact()
    art["scenarios"]["chaos"]["mitigation_events"] = 1
    assert not gate.chaos_gate_record(art)["ok"]

    # the share hint must have actually reached a relaunch
    art = sample_chaos_artifact()
    art["scenarios"]["straggler"]["launch_shares"] = [None, None]
    r = gate.chaos_gate_record(art)
    assert not r["ok"] and "share" in r["error"]

    # digest divergence from the policy-off control is disqualifying
    art = sample_chaos_artifact()
    art["scenarios"]["straggler"]["bit_identical"] = False
    r = gate.chaos_gate_record(art)
    assert not r["ok"] and "control" in r["error"]

    art = sample_chaos_artifact()
    art["scenarios"]["chaos"]["health_alarms_observed"] = 0
    assert not gate.chaos_gate_record(art)["ok"]

    art = sample_chaos_artifact()
    art["scenarios"]["chaos"]["killed_pid"] = 0
    assert not gate.chaos_gate_record(art)["ok"]


@pytest.mark.chaos
def test_committed_chaos_evidence_passes_the_gate():
    """docs/evidence/chaos_matrix_r16.json — produced by
    scripts/supervisor_matrix.py --scenarios straggler chaos driving the
    REAL supervisor over the real gloo fleet — must satisfy the same pure
    gate ratchet runs."""
    path = os.path.join(REPO, "docs", "evidence", "chaos_matrix_r16.json")
    with open(path) as f:
        artifact = json.load(f)
    r = _gate().chaos_gate_record(artifact)
    assert r["ok"], r


# ------------------------------------------------------- review-pinned fixes


def test_find_resume_dir_exclude_override_for_probe_and_ce(tmp_path):
    """A supervisor babysitting the probe/CE trainer passes exclude=() —
    their run dirs ARE the classifier_*/ce_* folders the pretrain default
    skips (without this the watch channel would be blind)."""
    ws = tmp_path / "ws"
    probe = ws / "cifar10_models" / "classifier_0101_0000_run"
    probe.mkdir(parents=True)
    assert launch.find_resume_dir(str(ws)) is None  # pretrain scan: excluded
    assert launch.find_resume_dir(str(ws), exclude=()) == str(probe)


def test_stale_stall_dump_from_previous_session_does_not_kill(tmp_path):
    """A stall dump left on disk by a PREVIOUS supervisor session must not
    liveness-kill a fresh healthy child: the verdict counts only dumps
    written during the current attempt (mtime), while the stale artifact
    is still recorded as an observation (fresh=false)."""
    script, log, ws = write_scripted_trainer(tmp_path, [0])
    run_dir = ws / "synthetic_models" / "synthetic_0101_0000_run"
    run_dir.mkdir(parents=True)
    dump = run_dir / "stall_dump_1.txt"
    dump.write_text("old stacks")
    os.utime(dump, (1000, 1000))  # long before this attempt
    cfg = SuperviseConfig(
        command=[sys.executable, str(script)], workdir=str(ws),
        max_restarts=3, poll_s=0.02, stall_secs=30.0, grace_secs=1.0,
    )
    sup, rc = run_supervisor(cfg)
    assert rc == 0
    assert [d.action for d in sup.decisions] == [policy.DONE]
    events = read_events(sup)
    assert not [e for e in events if e["name"] == "liveness_stall"]
    observed = [e for e in events if e["name"] == "stall_dump_observed"]
    assert observed and observed[0]["args"]["fresh"] is False


def test_resize_request_unreadable_is_retried_not_discarded(tmp_path):
    """A transient read failure must leave the operator's resize_request in
    place for the next poll (it is the only copy of the intent); only a
    successfully read file is consumed."""
    ws = tmp_path / "ws"
    cfg = SuperviseConfig(command=["true"], workdir=str(ws))
    sup = Supervisor(cfg)
    try:
        path = os.path.join(sup.supervise_dir, "resize_request")
        os.mkdir(path)  # open() -> IsADirectoryError, an OSError
        assert sup._resize_requested() is None
        assert os.path.exists(path)  # left for retry
        os.rmdir(path)
        # empty = caught mid-write (shell truncate-then-write): retried,
        # never deleted — a later poll sees the completed content
        with open(path, "w") as f:
            f.write("")
        assert sup._resize_requested() is None
        assert os.path.exists(path)
        with open(path, "w") as f:
            f.write("4")
        assert sup._resize_requested() == 4
        assert not os.path.exists(path)  # consumed exactly once
        # malformed CONTENT is genuinely bad: discarded with a warning
        with open(path, "w") as f:
            f.write("lots")
        assert sup._resize_requested() is None
        assert not os.path.exists(path)
    finally:
        sup.recorder.close()


def test_terminate_gracefully_honors_injected_clock(tmp_path):
    """The grace deadline runs on the injected clock (paired with the
    injected sleep): a fake pair must escalate to SIGKILL without
    real-time waiting or busy-spinning."""
    import subprocess
    import time as _time

    child = launch.Child([sys.executable, "-c", "import time; time.sleep(60)"])
    try:
        # let the child boot so SIGTERM isn't delivered pre-main
        deadline = _time.time() + 10
        while child.poll() is None and _time.time() < deadline:
            break
        t = {"now": 0.0}
        sleeps = []

        def fake_sleep(s):
            sleeps.append(s)
            t["now"] += s
            _time.sleep(0.01)  # yield so the OS can reap the SIGKILL

        wall0 = _time.time()
        # python ignores nothing here: SIGTERM kills it quickly in reality,
        # so use a SIGTERM-absorbing child to force the escalation path
        child.proc.kill()
        child.proc.wait()
        absorbing = launch.Child([sys.executable, "-c", (
            "import signal, time\n"
            "signal.signal(signal.SIGTERM, lambda *a: None)\n"
            "print('ready', flush=True)\n"
            "time.sleep(60)\n"
        )])
        _time.sleep(1.0)  # crude boot wait: the handler must be installed
        rc = absorbing.terminate_gracefully(
            grace_s=3600.0, sleep=fake_sleep,
            clock=lambda: t["now"], poll_s=1.0,
        )
        assert rc == -9  # escalated to SIGKILL
        # the whole hour of grace elapsed on the FAKE clock, not the wall
        assert _time.time() - wall0 < 60
        assert len(sleeps) <= 3601
    finally:
        if child.poll() is None:
            child.proc.kill()


def test_policy_stall_kill_that_exits_75_is_not_a_clean_preemption():
    """A responsive-enough child turns the supervisor's stall SIGTERM into
    a tidy exit 75 — but the verdict that triggered the kill is still a
    failure: no streak reset, backoff applies, and the reason names the
    kill (not scheduler preemption), or a recurring borderline stall would
    hammer the restart budget in a tight kill/relaunch loop."""
    p = policy.DecisionPolicy(max_restarts=100, backoff_base_s=1.0)
    d1 = p.decide(obs(75, stalled=True, stall_dumps=1))
    assert d1.action == policy.BACKOFF_RESTART
    assert "stalled" in d1.reason and "state saved" in d1.reason
    assert d1.delay_s == 1.0
    d2 = p.decide(obs(75, stalled=True))
    assert d2.delay_s == 2.0  # the streak GREW across stall kills
    # a genuine preemption afterwards still resets cleanly
    assert p.decide(obs(75)).delay_s == 0.0
    assert p.decide(obs(-9)).delay_s == 1.0


def test_resume_injection_requires_a_complete_checkpoint(tmp_path):
    """A child that dies before its FIRST save leaves an empty newest run
    dir: injecting --resume there would fail the trainer's resume
    resolution on every retry (each failed attempt minting another empty
    decoy) until the budget burned. The supervisor must restart from
    scratch instead — and still resume once a complete save exists."""
    script, log, ws = write_scripted_trainer(tmp_path, [1, 1],
                                             checkpoint=False)
    cfg = SuperviseConfig(
        command=[sys.executable, str(script)], workdir=str(ws),
        max_restarts=1, poll_s=0.02, backoff_base_s=0.01,
    )
    sup, rc = run_supervisor(cfg)
    assert rc == 1
    calls = [json.loads(line) for line in open(log)]
    assert len(calls) == 2
    assert all("--resume" not in c for c in calls)  # scratch restarts

    # find_resume_dir itself: unfiltered newest for the WATCH channel,
    # checkpoint-bearing newest for the RESUME channel
    empty = ws / "synthetic_models" / "synthetic_0101_0000_run"
    complete = ws / "synthetic_models" / "synthetic_0001_0000_old"
    (complete / "ckpt_epoch_3").mkdir(parents=True)
    (complete / "ckpt_epoch_3" / "meta.json").write_text('{"epoch": 3}')
    os.utime(complete, (1000, 1000))  # older than the empty decoy
    assert launch.find_resume_dir(str(ws)) == str(empty)
    assert launch.find_resume_dir(
        str(ws), require_checkpoint=True
    ) == str(complete)


def test_resize_request_between_attempts_applies_at_launch(tmp_path):
    """A resize filed while NO child is running (during backoff, or while
    the supervisor itself was down) must apply directly to the next launch
    — routing it through the kill path would boot a child on the old
    topology only to preempt it immediately, burning one restart-budget
    unit and a full startup on a routine operator action."""
    script, log, ws = write_scripted_trainer(tmp_path, [0])
    cfg = SuperviseConfig(
        command=[sys.executable, str(script)], workdir=str(ws),
        max_restarts=3, poll_s=0.02, devices=8,
    )
    sup = Supervisor(cfg)
    os.makedirs(sup.supervise_dir, exist_ok=True)
    with open(os.path.join(sup.supervise_dir, "resize_request"), "w") as f:
        f.write("2")
    rc = sup.run()
    assert rc == 0
    assert [d.action for d in sup.decisions] == [policy.DONE]  # no budget burned
    events = read_events(sup)
    launches = [e["args"] for e in events if e["name"] == "launch"]
    assert len(launches) == 1 and launches[0]["devices"] == 2
    resize_evs = [e["args"] for e in events if e["name"] == "resize_request"]
    assert resize_evs == [{"devices": 2, "applied": "at_launch"}]


def test_watcher_reports_overwritten_stall_dump(tmp_path):
    """A relaunched trainer's watchdog restarts its counter and OVERWRITES
    stall_dump_1.txt in the reused run dir: path identity alone would hide
    every stall after the first (and, without a metrics scrape, leave the
    supervisor polling a wedged child forever) — a changed mtime makes the
    dump new again."""
    run_dir = tmp_path / "run"
    run_dir.mkdir()
    w = observe.RunDirWatcher(str(run_dir))
    dump = run_dir / "stall_dump_1.txt"
    dump.write_text("attempt 1 stacks")
    os.utime(dump, (1000, 1000))
    assert len(w.poll()[0]) == 1
    assert w.poll()[0] == []  # unchanged: not re-reported
    dump.write_text("attempt 2 stacks")  # overwrite, fresh mtime
    assert len(w.poll()[0]) == 1
    assert w.poll()[0] == []


def test_unlaunchable_command_gives_up_with_recorded_decision(tmp_path):
    """A typo'd executable must end in a classified give_up (shell 127)
    with the failure on the recorder — not an unrecorded supervisor
    traceback (the delegated launcher path would otherwise surface a raw
    crash instead of a decision)."""
    ws = tmp_path / "ws"
    cfg = SuperviseConfig(
        command=["no-such-trainer-binary", "--flag"], workdir=str(ws),
        max_restarts=3, poll_s=0.02,
    )
    sup, rc = run_supervisor(cfg)
    assert rc == 127
    assert [d.action for d in sup.decisions] == [policy.GIVE_UP]
    assert "failed to launch" in sup.decisions[0].reason
    events = read_events(sup)
    assert [e["name"] for e in events] == ["launch_failed", "decision"]
    assert events[1]["args"]["rc"] == 127


def test_supervisor_signal_relays_to_child_and_shuts_down(tmp_path):
    """When the SUPERVISOR is preempted (the launchers exec it, so it is
    what a fleet scheduler SIGTERMs), it must relay through the grace
    escalation — giving the trainer its emergency-save window — and exit
    with the child's code instead of relaunching. (Run off the main
    thread, the OS handler degrades; the flag path is driven directly.)"""
    log = tmp_path / "calls.log"
    ws = tmp_path / "ws"
    script = tmp_path / "graceful.py"
    script.write_text(f"""
import json, os, signal, sys, time
with open({str(log)!r}, "a") as f:
    f.write(json.dumps(sys.argv[1:]) + "\\n")
os.makedirs(os.path.join({str(ws)!r}, "synthetic_models", "r1"), exist_ok=True)
signal.signal(signal.SIGTERM, lambda *a: sys.exit(75))
time.sleep(60)
""")
    cfg = SuperviseConfig(
        command=[sys.executable, str(script)], workdir=str(ws),
        max_restarts=3, poll_s=0.05, grace_secs=20.0,
    )
    sup = Supervisor(cfg)
    box = {}
    t = threading.Thread(target=lambda: box.update(rc=sup.run()), daemon=True)
    t.start()
    import time as _time

    deadline = _time.time() + 50
    while not log.exists() and _time.time() < deadline:
        _time.sleep(0.05)
    _time.sleep(0.3)  # let the child install its SIGTERM handler
    sup._handle_signal(15, None)  # what the OS handler would do
    t.join(timeout=60)
    assert not t.is_alive() and box["rc"] == 75  # the child's saved-state code
    assert [d.action for d in sup.decisions] == [policy.SHUTDOWN]
    events = read_events(sup)
    assert any(e["name"] == "supervisor_signal" for e in events)
    assert len([e for e in events if e["name"] == "launch"]) == 1  # no relaunch


def test_terminal_exit_discards_stale_resize_request(tmp_path):
    """A resize_request racing the final child exit must not leak to the
    next, unrelated supervised run in the same workdir: terminal exits
    delete it (and record the discard). The race is made deterministic by
    having the CHILD file the request just before exiting 0 — _watch_child
    observes the exit before its resize poll, so the request is pending at
    the DONE decision."""
    ws = tmp_path / "ws"
    supervise_dir = ws / "supervise"
    script = tmp_path / "racer.py"
    script.write_text(f"""
import os, sys
os.makedirs(os.path.join({str(ws)!r}, "synthetic_models", "r1"), exist_ok=True)
os.makedirs({str(supervise_dir)!r}, exist_ok=True)
with open(os.path.join({str(supervise_dir)!r}, "resize_request"), "w") as f:
    f.write("4")
sys.exit(0)
""")
    cfg = SuperviseConfig(
        command=[sys.executable, str(script)], workdir=str(ws),
        max_restarts=3, poll_s=0.5,
    )
    sup, rc = run_supervisor(cfg)
    assert rc == 0
    assert [d.action for d in sup.decisions] == [policy.DONE]
    assert not os.path.exists(
        os.path.join(sup.supervise_dir, "resize_request"))
    assert any(e["name"] == "resize_request_discarded"
               for e in read_events(sup))


def test_signal_during_backoff_skips_relaunch(tmp_path):
    """A SIGTERM landing while the supervisor sleeps out a backoff must end
    the run WITHOUT booting another child (a fresh trainer would only be
    killed mid-startup, wasting the scheduler's grace window): the backoff
    sleep is chunked and the loop re-checks the flag before relaunching."""
    import time as _time

    script, log, ws = write_scripted_trainer(tmp_path, [1, 0])
    cfg = SuperviseConfig(
        command=[sys.executable, str(script)], workdir=str(ws),
        max_restarts=3, poll_s=0.05, backoff_base_s=30.0,  # a LONG backoff
    )
    sup = Supervisor(cfg)
    box = {}
    t = threading.Thread(target=lambda: box.update(rc=sup.run()), daemon=True)
    t.start()
    deadline = _time.time() + 50
    while len(sup.decisions) < 1 and _time.time() < deadline:
        _time.sleep(0.02)  # wait until attempt 1 crashed -> backoff begins
    sup._handle_signal(15, None)
    t.join(timeout=30)
    assert not t.is_alive(), "supervisor sat out the full 30s backoff"
    assert box["rc"] == 1  # the last child's code, not a fresh kill's
    assert [d.action for d in sup.decisions] == [
        policy.BACKOFF_RESTART, policy.SHUTDOWN,
    ]
    assert len(list(open(log))) == 1  # no second launch
