"""Config parity tests: flag names/defaults and the derivations that matter
(model_name encoding, auto-warm, closed-form warmup_to)."""

import math

from simclr_pytorch_distributed_tpu.config import (
    config_dict,
    parse_linear,
    parse_supcon,
)


def test_supcon_defaults_match_reference(tmp_path):
    cfg = parse_supcon(["--workdir", str(tmp_path)])
    assert cfg.print_freq == 10 and cfg.save_freq == 20
    assert cfg.batch_size == 256 and cfg.epochs == 1000
    assert cfg.learning_rate == 0.5 and cfg.lr_decay_epochs == (700, 800, 900)
    assert cfg.lr_decay_rate == 0.1 and cfg.weight_decay == 1e-4
    assert cfg.model == "resnet50" and cfg.dataset == "cifar10"
    assert cfg.method == "SimCLR" and cfg.temp == 0.5
    assert cfg.norm_momentum == 1.0 and cfg.ngpu == 2
    assert cfg.data_folder == "./datasets/"


def test_model_name_encoding(tmp_path):
    cfg = parse_supcon(
        ["--cosine", "--method", "SimCLR", "--trial", "3", "--workdir", str(tmp_path)]
    )
    assert cfg.model_name == (
        "SimCLR_cifar10_resnet50_lr_0.5_decay_0.0001_bsz_256_temp_0.5_trial_3_cosine"
    )
    assert "cifar10_models" in cfg.save_folder
    assert cfg.model_name in cfg.save_folder


def test_auto_warm_large_batch(tmp_path):
    cfg = parse_supcon(
        ["--batch_size", "512", "--cosine", "--epochs", "200", "--workdir", str(tmp_path)]
    )
    assert cfg.warm  # bs > 256 forces warmup (main_supcon.py:120-121)
    assert cfg.warm_epochs == 10 and cfg.warmup_from == 0.01
    eta_min = 0.5 * 0.1**3
    want = eta_min + (0.5 - eta_min) * (1 + math.cos(math.pi * 10 / 200)) / 2
    assert abs(cfg.warmup_to - want) < 1e-9
    assert cfg.model_name.endswith("_warm")


def test_linear_defaults(tmp_path):
    cfg = parse_linear(["--workdir", str(tmp_path)])
    assert cfg.batch_size == 512 and cfg.epochs == 100
    assert cfg.learning_rate == 0.1 and cfg.lr_decay_epochs == (60, 75, 90)
    assert cfg.lr_decay_rate == 0.2 and cfg.weight_decay == 0.0
    assert cfg.n_cls == 10
    cfg100 = parse_linear(["--dataset", "cifar100", "--workdir", str(tmp_path)])
    assert cfg100.n_cls == 100


def test_config_dict_json_safe(tmp_path):
    import json

    cfg = parse_supcon(["--workdir", str(tmp_path)])
    json.dumps(config_dict(cfg))  # must not raise


def test_download_flag(tmp_path):
    """--no_download flips the (default-on) CIFAR fetch fallback; both
    parsers carry it (torchvision download=True parity, main_supcon.py:181)."""
    assert parse_supcon(["--workdir", str(tmp_path)]).download
    assert not parse_supcon(
        ["--no_download", "--workdir", str(tmp_path)]
    ).download
    assert parse_linear(["--workdir", str(tmp_path)]).download
    assert not parse_linear(
        ["--no_download", "--workdir", str(tmp_path)]
    ).download


def test_ce_syncbn_flag(tmp_path):
    """--syncBN exists on the CE parser only (the probe's encoder is frozen
    eval-mode; the reference pretrain conditional, main_supcon.py:223-224)."""
    import pytest

    ce = parse_linear(["--syncBN", "--workdir", str(tmp_path)], ce=True)
    assert ce.syncBN
    assert not parse_linear([
        "--workdir", str(tmp_path)], ce=True).syncBN
    with pytest.raises(SystemExit):
        parse_linear(["--syncBN", "--workdir", str(tmp_path)], ce=False)
