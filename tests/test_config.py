"""Config parity tests: flag names/defaults and the derivations that matter
(model_name encoding, auto-warm, closed-form warmup_to), plus a MECHANICAL
pin of the full flag surface against the reference's own argparse."""

import argparse
import ast
import math
import os

import pytest

from simclr_pytorch_distributed_tpu.config import (
    config_dict,
    linear_parser,
    parse_linear,
    parse_supcon,
    supcon_parser,
)

REFERENCE_DIR = "/root/reference"


def test_supcon_defaults_match_reference(tmp_path):
    cfg = parse_supcon(["--workdir", str(tmp_path)])
    assert cfg.print_freq == 10 and cfg.save_freq == 20
    assert cfg.batch_size == 256 and cfg.epochs == 1000
    assert cfg.learning_rate == 0.5 and cfg.lr_decay_epochs == (700, 800, 900)
    assert cfg.lr_decay_rate == 0.1 and cfg.weight_decay == 1e-4
    assert cfg.model == "resnet50" and cfg.dataset == "cifar10"
    assert cfg.method == "SimCLR" and cfg.temp == 0.5
    assert cfg.norm_momentum == 1.0 and cfg.ngpu == 2
    assert cfg.data_folder == "./datasets/"


def test_model_name_encoding(tmp_path):
    cfg = parse_supcon(
        ["--cosine", "--method", "SimCLR", "--trial", "3", "--workdir", str(tmp_path)]
    )
    assert cfg.model_name == (
        "SimCLR_cifar10_resnet50_lr_0.5_decay_0.0001_bsz_256_temp_0.5_trial_3_cosine"
    )
    assert "cifar10_models" in cfg.save_folder
    assert cfg.model_name in cfg.save_folder


def test_auto_warm_large_batch(tmp_path):
    cfg = parse_supcon(
        ["--batch_size", "512", "--cosine", "--epochs", "200", "--workdir", str(tmp_path)]
    )
    assert cfg.warm  # bs > 256 forces warmup (main_supcon.py:120-121)
    assert cfg.warm_epochs == 10 and cfg.warmup_from == 0.01
    eta_min = 0.5 * 0.1**3
    want = eta_min + (0.5 - eta_min) * (1 + math.cos(math.pi * 10 / 200)) / 2
    assert abs(cfg.warmup_to - want) < 1e-9
    assert cfg.model_name.endswith("_warm")


def test_linear_defaults(tmp_path):
    cfg = parse_linear(["--workdir", str(tmp_path)])
    assert cfg.batch_size == 512 and cfg.epochs == 100
    assert cfg.learning_rate == 0.1 and cfg.lr_decay_epochs == (60, 75, 90)
    assert cfg.lr_decay_rate == 0.2 and cfg.weight_decay == 0.0
    assert cfg.n_cls == 10
    cfg100 = parse_linear(["--dataset", "cifar100", "--workdir", str(tmp_path)])
    assert cfg100.n_cls == 100


def test_config_dict_json_safe(tmp_path):
    import json

    cfg = parse_supcon(["--workdir", str(tmp_path)])
    json.dumps(config_dict(cfg))  # must not raise


def test_download_flag(tmp_path):
    """--no_download flips the (default-on) CIFAR fetch fallback; both
    parsers carry it (torchvision download=True parity, main_supcon.py:181)."""
    assert parse_supcon(["--workdir", str(tmp_path)]).download
    assert not parse_supcon(
        ["--no_download", "--workdir", str(tmp_path)]
    ).download
    assert parse_linear(["--workdir", str(tmp_path)]).download
    assert not parse_linear(
        ["--no_download", "--workdir", str(tmp_path)]
    ).download


def _reference_parser(rel_path: str) -> argparse.ArgumentParser:
    """The reference's LIVE ArgumentParser, built by executing the
    parser-construction prefix of its ``parse_option`` (everything before
    ``opt = parser.parse_args()``), extracted via ast. The module itself is
    not importable here (torchvision/tensorboard_logger are absent), but the
    prefix is pure argparse — so the enumeration below reads the reference's
    actual registered actions, not a hand-maintained list."""
    with open(os.path.join(REFERENCE_DIR, rel_path)) as f:
        tree = ast.parse(f.read())
    fn = next(
        n for n in tree.body
        if isinstance(n, ast.FunctionDef) and n.name == "parse_option"
    )
    body = []
    for stmt in fn.body:
        if (
            isinstance(stmt, ast.Assign)
            and isinstance(stmt.value, ast.Call)
            and isinstance(stmt.value.func, ast.Attribute)
            and stmt.value.func.attr == "parse_args"
        ):
            break
        body.append(stmt)
    module = ast.Module(body=body, type_ignores=[])
    ast.fix_missing_locations(module)
    ns = {"argparse": argparse}
    exec(compile(module, rel_path, "exec"), ns)  # noqa: S102 — test oracle
    return ns["parser"]


def _actions_by_flag(parser: argparse.ArgumentParser) -> dict:
    return {
        a.option_strings[0].lstrip("-"): a
        for a in parser._actions
        if a.option_strings and a.option_strings[0] not in ("-h", "--help")
    }


# flags the reference carries that this framework deliberately does not,
# with the reason (the ONLY permitted deltas):
SUPCON_FLAG_DELTAS = {
    # torch.distributed launcher plumbing: process identity comes from
    # jax.distributed (parallel/mesh.py), not a per-process CLI flag
    "local_rank",
}
LINEAR_FLAG_DELTAS: set = set()
# flags whose TYPE is a documented superset of the reference's (the parsed
# value for every reference-legal input must still match):
SUPCON_TYPE_DELTAS = {
    # reference type=int; ours also accepts 'auto' (mesh-resolved grad_div,
    # config.ngpu_arg) — integer inputs parse identically (asserted below)
    "ngpu",
}
LINEAR_TYPE_DELTAS: set = set()


@pytest.mark.skipif(
    not os.path.isdir(REFERENCE_DIR), reason="reference checkout not present"
)
@pytest.mark.parametrize(
    "rel_path,ours,deltas,type_deltas,min_flags",
    [
        ("main_supcon.py", supcon_parser, SUPCON_FLAG_DELTAS,
         SUPCON_TYPE_DELTAS, 30),
        ("main_linear.py", lambda: linear_parser(ce=False), LINEAR_FLAG_DELTAS,
         LINEAR_TYPE_DELTAS, 15),
    ],
)
def test_flag_surface_covers_reference(rel_path, ours, deltas, type_deltas, min_flags):
    """EVERY flag the reference's argparse registers exists here with the
    same default (and at least the same choices), modulo the documented
    deltas — so a round-N edit cannot silently drift the schema."""
    ref_flags = _actions_by_flag(_reference_parser(rel_path))
    # extraction sanity: the ast surgery actually saw the full surface
    assert len(ref_flags) >= min_flags, sorted(ref_flags)
    our_flags = _actions_by_flag(ours())

    missing = [f for f in ref_flags if f not in our_flags and f not in deltas]
    assert not missing, f"{rel_path} flags absent here: {missing}"

    for name, ref in ref_flags.items():
        if name in deltas:
            continue
        mine = our_flags[name]
        assert mine.default == ref.default, (
            f"--{name}: default {mine.default!r} != reference {ref.default!r}"
        )
        if ref.choices:
            assert set(ref.choices) <= set(mine.choices or ()), (
                f"--{name}: choices {mine.choices!r} miss {ref.choices!r}"
            )
        if isinstance(ref, argparse._StoreTrueAction):
            assert isinstance(mine, argparse._StoreTrueAction), f"--{name}"
        elif ref.type is not None:
            if name in type_deltas:
                # documented superset: reference-legal inputs parse the same
                assert mine.type(str(ref.type("3"))) == 3, f"--{name}"
            else:
                assert mine.type is ref.type, (
                    f"--{name}: type {mine.type} != reference {ref.type}"
                )


def test_ce_syncbn_flag(tmp_path):
    """--syncBN exists on the CE parser only (the probe's encoder is frozen
    eval-mode; the reference pretrain conditional, main_supcon.py:223-224)."""
    import pytest

    ce = parse_linear(["--syncBN", "--workdir", str(tmp_path)], ce=True)
    assert ce.syncBN
    assert not parse_linear([
        "--workdir", str(tmp_path)], ce=True).syncBN
    with pytest.raises(SystemExit):
        parse_linear(["--syncBN", "--workdir", str(tmp_path)], ce=False)


def test_ngpu_auto_resolves_to_data_parallel(tmp_path):
    """--ngpu auto -> the mesh's data-parallel size at build time; explicit
    integers pass through (incl. int-like strings from restored configs)."""
    from simclr_pytorch_distributed_tpu.config import ngpu_arg, resolve_ngpu

    cfg = parse_supcon(["--ngpu", "auto", "--workdir", str(tmp_path)])
    assert cfg.ngpu == "auto"
    assert resolve_ngpu(cfg.ngpu, data_parallel=8) == 8
    assert resolve_ngpu(cfg.ngpu, data_parallel=1) == 1
    assert resolve_ngpu(2, data_parallel=8) == 2
    assert resolve_ngpu("4", data_parallel=8) == 4  # restored config dict
    assert ngpu_arg("AUTO") == "auto" and ngpu_arg("2") == 2
    with pytest.raises(argparse.ArgumentTypeError):
        ngpu_arg("two")
    # it becomes the gradient divisor: 0/negative must die at parse, not
    # as a ZeroDivisionError mid-startup (or a sign-flipped update)
    for bad in ("0", "-2"):
        with pytest.raises(argparse.ArgumentTypeError, match="positive"):
            ngpu_arg(bad)
    with pytest.raises(ValueError, match="positive"):
        resolve_ngpu(0, data_parallel=4)
    import json

    json.dumps(config_dict(cfg))  # 'auto' stays JSON-safe in checkpoint meta


def test_ngpu_auto_and_banner_in_build(tmp_path, caplog):
    """build() with --ngpu auto emits NO banner; an explicit mismatch emits
    the startup banner naming the effective-LR consequence."""
    import logging

    from simclr_pytorch_distributed_tpu.config import ngpu_mismatch_banner
    from simclr_pytorch_distributed_tpu.train.supcon import build

    auto_cfg = parse_supcon(
        ["--ngpu", "auto", "--model", "resnet10", "--dataset", "synthetic",
         "--workdir", str(tmp_path)]
    )
    with caplog.at_level(logging.WARNING):
        _, _, _, _, step_cfg = build(auto_cfg, steps_per_epoch=10, n_devices=4)
    assert step_cfg.grad_div == 4.0  # mesh-resolved
    assert "--ngpu" not in caplog.text

    caplog.clear()
    mism_cfg = parse_supcon(
        ["--ngpu", "2", "--model", "resnet10", "--dataset", "synthetic",
         "--workdir", str(tmp_path)]
    )
    with caplog.at_level(logging.WARNING):
        _, _, _, _, step_cfg = build(mism_cfg, steps_per_epoch=10, n_devices=4)
    assert step_cfg.grad_div == 2.0  # recipe fidelity preserved
    assert "EFFECTIVE learning rate" in caplog.text
    assert "--ngpu auto" in caplog.text

    banner = ngpu_mismatch_banner(2, 4, 0.5)
    assert "4/2" in banner and "~1" in banner  # 0.5 * 4/2 = 1.0


def test_telemetry_flag_both_parsers(tmp_path):
    """--telemetry {async,sync} on all three trainers' parsers; async is the
    default (the zero-sync hot loop)."""
    assert parse_supcon(["--workdir", str(tmp_path)]).telemetry == "async"
    assert parse_supcon(
        ["--telemetry", "sync", "--workdir", str(tmp_path)]
    ).telemetry == "sync"
    assert parse_linear(["--workdir", str(tmp_path)]).telemetry == "async"
    assert parse_linear(
        ["--telemetry", "sync", "--workdir", str(tmp_path)], ce=True
    ).telemetry == "sync"
    with pytest.raises(SystemExit):
        parse_supcon(["--telemetry", "never", "--workdir", str(tmp_path)])


def test_linear_parser_accepts_resume_for_launcher_contract():
    """Exit code 75's contract is 're-run the same command with --resume':
    the probe parser must accept the flag (retrain-from-scratch semantics)
    rather than die with 'unrecognized arguments'."""
    from simclr_pytorch_distributed_tpu import config as config_lib

    ns = config_lib.linear_parser(ce=False).parse_args(
        ["--dataset", "synthetic", "--resume", "/some/run_dir"]
    )
    assert ns.resume == "/some/run_dir"
    ns_ce = config_lib.linear_parser(ce=True).parse_args(
        ["--dataset", "synthetic", "--resume", "/some/run_dir"]
    )
    assert ns_ce.resume == "/some/run_dir"


def test_data_placement_flag_all_parsers(tmp_path):
    """--data_placement {host,device,auto} on all three trainers' parsers;
    'auto' (decide from the decoded dataset size, degrade to host with a
    banner) is the default everywhere."""
    assert parse_supcon(["--workdir", str(tmp_path)]).data_placement == "auto"
    assert parse_supcon(
        ["--data_placement", "device", "--workdir", str(tmp_path)]
    ).data_placement == "device"
    assert parse_linear(["--workdir", str(tmp_path)]).data_placement == "auto"
    assert parse_linear(
        ["--data_placement", "host", "--workdir", str(tmp_path)], ce=True
    ).data_placement == "host"
    with pytest.raises(SystemExit):
        parse_supcon(["--data_placement", "hbm", "--workdir", str(tmp_path)])


def test_data_placement_device_with_path_rejected_at_parse(tmp_path):
    """The 'device' x 'path' interaction dies AT PARSE TIME with the reason
    (folder trees may decode to an on-disk memmap above --mmap_threshold_mb,
    which residency refuses) — not deep in setup after the decode; 'auto'
    with path parses fine and resolves against the decoded array later."""
    path_args = ["--dataset", "path", "--data_folder", str(tmp_path),
                 "--mean", "(0.5,0.5,0.5)", "--std", "(0.5,0.5,0.5)",
                 "--workdir", str(tmp_path)]
    with pytest.raises(ValueError, match="memmap"):
        parse_supcon(["--data_placement", "device", *path_args])
    assert parse_supcon(
        ["--data_placement", "auto", *path_args]
    ).data_placement == "auto"
    # explicit 'window' x 'path' is FINE: the window store streams from a
    # memmap by construction, so the post-decode representation cannot
    # invalidate the request
    assert parse_supcon(
        ["--data_placement", "window", *path_args]
    ).data_placement == "window"


def test_window_placement_and_knobs_all_parsers(tmp_path):
    """--data_placement window plus the --data_window_batches /
    --device_budget_mb knobs on all three trainers' parsers; non-positive
    values die at parse time (the --ngpu convention — they feed a slice
    modulus and a byte budget)."""
    cfg = parse_supcon(
        ["--data_placement", "window", "--data_window_batches", "16",
         "--device_budget_mb", "2048", "--workdir", str(tmp_path)]
    )
    assert cfg.data_placement == "window"
    assert cfg.data_window_batches == 16 and cfg.device_budget_mb == 2048
    for ce in (False, True):
        lcfg = parse_linear(
            ["--data_placement", "window", "--data_window_batches", "4",
             "--device_budget_mb", "512", "--workdir", str(tmp_path)],
            ce=ce,
        )
        assert lcfg.data_placement == "window"
        assert lcfg.data_window_batches == 4
        assert lcfg.device_budget_mb == 512
    # defaults: window length 32, budget 0 = computed (0.4x free stats)
    d = parse_supcon(["--workdir", str(tmp_path)])
    assert d.data_window_batches == 32 and d.device_budget_mb == 0
    for bad_flag in ("--data_window_batches", "--device_budget_mb"):
        for bad in ("0", "-3", "x"):
            with pytest.raises(SystemExit):
                parse_supcon([bad_flag, bad, "--workdir", str(tmp_path)])
            with pytest.raises(SystemExit):
                parse_linear([bad_flag, bad, "--workdir", str(tmp_path)],
                             ce=True)


def test_budget_override_bytes_mapping():
    """The flag-to-resolver plumbing: MB -> bytes, 0 -> None (computed)."""
    from simclr_pytorch_distributed_tpu.data.device_store import (
        budget_override_bytes,
    )

    assert budget_override_bytes(0) is None
    assert budget_override_bytes(None) is None
    assert budget_override_bytes(1) == 1 << 20
    assert budget_override_bytes(2048) == 2048 << 20
