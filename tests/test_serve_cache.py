"""Unit tests for serve/cache.py — the content-keyed LRU result cache."""

import numpy as np
import pytest

from simclr_pytorch_distributed_tpu.serve.cache import EmbeddingCache

pytestmark = pytest.mark.serve


def row(v, dim=4):
    return np.full((dim,), v, np.float32)


def test_put_get_and_counters():
    c = EmbeddingCache(capacity=8)
    assert c.get(b"a") is None  # miss
    c.put(b"a", row(1.0))
    got = c.get(b"a")
    np.testing.assert_array_equal(got, row(1.0))
    s = c.stats()
    assert s["hits"] == 1 and s["misses"] == 1 and s["entries"] == 1
    assert s["hit_rate"] == 0.5


def test_lru_eviction_order_respects_access():
    c = EmbeddingCache(capacity=2)
    c.put(b"a", row(1))
    c.put(b"b", row(2))
    assert c.get(b"a") is not None  # refresh 'a' — 'b' is now oldest
    c.put(b"c", row(3))  # evicts 'b'
    assert c.get(b"b") is None
    assert c.get(b"a") is not None and c.get(b"c") is not None
    assert c.stats()["evictions"] == 1
    assert len(c) == 2


def test_stored_rows_are_frozen():
    """A caller mutating its input after put, or the returned row after get,
    must not poison later hits."""
    c = EmbeddingCache(capacity=4)
    src = row(1.0)
    c.put(b"k", src)
    src[:] = 99.0  # mutate the caller's array AFTER put
    got = c.get(b"k")
    np.testing.assert_array_equal(got, row(1.0))
    with pytest.raises(ValueError):
        got[0] = 5.0  # returned row is read-only


def test_overwrite_same_key_keeps_size():
    c = EmbeddingCache(capacity=4)
    c.put(b"k", row(1))
    c.put(b"k", row(2))
    assert len(c) == 1
    np.testing.assert_array_equal(c.get(b"k"), row(2))


def test_put_many_matches_put_semantics():
    """The completion stage's batched insert: one lock, same freeze +
    eviction behavior as row-by-row put."""
    c = EmbeddingCache(capacity=3)
    src = row(1.0)
    c.put_many([(b"a", src), (b"b", row(2)), (b"c", row(3)), (b"d", row(4))])
    src[:] = 99.0  # stored copies are frozen against caller mutation
    assert len(c) == 3
    assert c.get(b"a") is None  # oldest of the batch evicted
    assert c.stats()["evictions"] == 1
    np.testing.assert_array_equal(c.get(b"b"), row(2))
    with pytest.raises(ValueError):
        c.get(b"d")[0] = 5.0  # read-only, like put's rows
    c.put_many([])  # no-op, no lock churn
    assert len(c) == 3


def test_clear_and_capacity_validation():
    c = EmbeddingCache(capacity=4)
    c.put(b"k", row(1))
    c.clear()
    assert len(c) == 0 and c.get(b"k") is None
    with pytest.raises(ValueError):
        EmbeddingCache(capacity=0)
