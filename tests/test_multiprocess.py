"""REAL multi-process data-parallel training (the multi-host runtime path).

Everything else in the suite fakes multi-chip with one process + 8 virtual
devices, which never exercises the true multi-host machinery: gloo-backed
``jax.distributed.initialize`` rendezvous, per-process ``EpochLoader`` shards,
and ``jax.make_array_from_process_local_data`` assembling a global batch from
process-local blocks (``parallel/mesh.py shard_host_batch``). These tests spawn
two REAL OS processes — owning one CPU device each (the original topology) or
TWO devices each (a real pod host: N processes x several local chips, where
host-batch slicing vs device sharding, the ring ppermute, and collective saves
cross both the process and the local-device boundary) — run training, and
check agreement with a single-process run of the same global program.
"""

import os
import socket
import subprocess
import sys

import numpy as np
import pytest

pytestmark = pytest.mark.slow

CHILD = os.path.join(os.path.dirname(__file__), "multiprocess_child.py")
REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("localhost", 0))
        return s.getsockname()[1]


def _child_env(local_devices=None):
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
    # children build their own device topology; drop the parent's 8-device flag
    env["XLA_FLAGS"] = " ".join(
        f for f in env.get("XLA_FLAGS", "").split()
        if "host_platform_device_count" not in f
    )
    env.pop("PALLAS_AXON_POOL_IPS", None)
    if local_devices is not None:
        env["CHILD_LOCAL_DEVICES"] = str(local_devices)
    else:
        env.pop("CHILD_LOCAL_DEVICES", None)
    # share the suite's persistent compile cache (conftest isn't imported by
    # the children; without this every run pays the full cold compile)
    env.setdefault(
        "JAX_COMPILATION_CACHE_DIR", os.path.join(REPO, ".jax_cache")
    )
    return env


def _reap(procs, timeout):
    outs = []
    try:
        for p in procs:
            out, _ = p.communicate(timeout=timeout)
            assert p.returncode == 0, out
            outs.append(out)
    finally:
        # a failed coordinator must not orphan the peer blocked in rendezvous
        for p in procs:
            if p.poll() is None:
                p.kill()
    return outs


def _run_children(nproc: int, port: int, mode: str = "step", local_devices=None):
    env = _child_env(local_devices)
    procs = [
        subprocess.Popen(
            [sys.executable, CHILD, str(i), str(nproc), str(port), mode],
            env=env, stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
            text=True, cwd=REPO,
        )
        for i in range(nproc)
    ]
    # generous: a chip job sharing this 1-core host can slow children 2-3x
    return _reap(procs, 900)


def _loss_of(out: str) -> float:
    for line in out.splitlines():
        if line.startswith("LOSS "):
            return float(line.split()[1])
    raise AssertionError(f"no LOSS line in:\n{out}")


@pytest.mark.parametrize("mode", ["step", "ring", "fused"])
def test_two_process_step_matches_single_process(mode):
    """Two training steps across two REAL processes equal the single-process
    run of the identical global batches (the second step's loss witnesses the
    first step's gradients). 'step' exercises the dense loss (XLA
    psum/all-gather over gloo); 'ring' exercises the ring loss, whose rotating
    ppermute is a different collective that only a multi-process run proves
    gloo carries; 'fused' exercises the shard_map-sharded Pallas kernel —
    the path resolve_loss_impl('auto') picks on multi-device TPU meshes,
    whose check_vma=False/psum-cotangent custom VJP is exactly the plumbing
    that could behave differently when the mesh spans processes."""
    ref = _loss_of(_run_children(1, _free_port(), mode=mode)[0])
    outs = _run_children(2, _free_port(), mode=mode)
    losses = [_loss_of(o) for o in outs]
    # both processes compute the same replicated global loss...
    assert losses[0] == losses[1], losses
    # ...equal to the single-process run of the identical global batch
    np.testing.assert_allclose(losses[0], ref, rtol=1e-6)


def _run_driver_children(tmp_path, mode, extra_args=(), timeout=900,
                         local_devices=None):
    env = _child_env(local_devices)
    port = _free_port()
    procs = [
        subprocess.Popen(
            [sys.executable, CHILD, str(i), "2", str(port), mode,
             str(tmp_path), *map(str, extra_args)],
            env=env, stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
            text=True, cwd=REPO,
        )
        for i in range(2)
    ]
    return _reap(procs, timeout)


@pytest.mark.parametrize("mode", ["step", "ring", "fused", "fused_supcon"])
def test_two_process_two_device_step_matches_single_process(mode):
    """The REAL pod topology: 2 processes x 2 local devices (global mesh of
    4) equals one process with a 4-device mesh. This is where host-batch
    slicing (per-process halves) meets device sharding (per-device quarters),
    and where the ring's ppermute hops cross a process boundary on some edges
    and stay host-local on others — untested by either the 8-virtual-device
    suite or the 1-device-per-process tests above (round-3 weak #3).
    'fused'/'fused_supcon' run the sharded Pallas kernel — the mode `auto`
    selects on a real v5e pod (round-4 weak #1): anchor rows sharded 4-way
    (m=8 each), contrast all-gathered across the process boundary, and the
    custom VJP's per-shard cotangent psum crossing gloo; 'fused_supcon'
    additionally carries the replicated global-label leg."""
    ref = _loss_of(
        _run_children(1, _free_port(), mode=mode, local_devices=4)[0]
    )
    outs = _run_children(2, _free_port(), mode=mode, local_devices=2)
    losses = [_loss_of(o) for o in outs]
    assert losses[0] == losses[1], losses
    np.testing.assert_allclose(losses[0], ref, rtol=1e-6)


def test_two_by_two_collective_save_resume(tmp_path):
    """Collective checkpoint save + resume over the 2 processes x 2 devices
    topology: orbax coordinates writers across processes while each process's
    arrays span two local devices. The resumed job must complete on the same
    step with identical parameters on both processes."""
    outs = _run_driver_children(
        tmp_path / "partial", "driver_partial", (4,), local_devices=2
    )
    run_dir = [
        _driver_line(o, "PARTIAL ").split("save_folder=")[1] for o in outs
    ]
    assert run_dir[0] == run_dir[1]
    assert os.path.exists(os.path.join(run_dir[0], "ckpt_epoch_2", "meta.json"))

    resumed = _run_driver_children(
        tmp_path / "resumed", "driver", (4, run_dir[0]), local_devices=2
    )
    steps, digests = [], []
    for o in resumed:
        line = _driver_line(o)
        steps.append(int(line.split("step=")[1].split()[0]))
        digests.append(float(line.split("digest=")[1].split()[0]))
    assert steps == [12, 12], steps  # 3 steps/epoch x 4 epochs
    assert digests[0] == digests[1], digests


def _driver_line(out: str, tag: str = "DRIVER ") -> str:
    lines = [l for l in out.splitlines() if l.startswith(tag)]
    assert lines, out
    return lines[0]


def test_two_process_crash_resume_matches_uninterrupted(tmp_path):
    """Kill-and-resume across BOTH processes (round-2 weak #5: restore is the
    collective symmetric to save and had no multi-process test): a 4-epoch job
    crashed at epoch 3 and resumed with --resume <run_dir> must land on the
    same step AND the same parameters as an uninterrupted 4-epoch run."""
    outs = _run_driver_children(tmp_path / "partial", "driver_partial", (4,))
    run_dir = [
        _driver_line(o, "PARTIAL ").split("save_folder=")[1] for o in outs
    ]
    assert run_dir[0] == run_dir[1]
    # the simulated crash left the epoch-2 scheduled save complete
    assert os.path.exists(os.path.join(run_dir[0], "ckpt_epoch_2", "meta.json"))

    resumed = _run_driver_children(
        tmp_path / "resumed", "driver", (4, run_dir[0])
    )
    straight = _run_driver_children(tmp_path / "straight", "driver", (4,))

    def parse(o):
        line = _driver_line(o)
        return (
            int(line.split("step=")[1].split()[0]),
            float(line.split("digest=")[1].split()[0]),
        )

    (step_r, dig_r), (step_r2, dig_r2) = (parse(o) for o in resumed)
    (step_s, dig_s), _ = (parse(o) for o in straight)
    assert step_r == step_r2 == step_s == 12  # 3 steps/epoch x 4 epochs
    assert dig_r == dig_r2
    # identical post-resume parameters (CPU math is deterministic; the
    # schedule/data/aug streams are pure functions of the global step)
    np.testing.assert_allclose(dig_r, dig_s, rtol=1e-6)


def test_two_process_ce_driver(tmp_path):
    """The CE driver across two real processes (it shares the
    broadcast_from_main + collective-save machinery only supcon exercised)."""
    outs = _run_driver_children(tmp_path, "ce")
    accs = []
    folders = []
    for out in outs:
        line = _driver_line(out, "CE ")
        accs.append(float(line.split("best_acc=")[1].split()[0]))
        folders.append(line.split("save_folder=")[1])
    assert accs[0] == accs[1]
    assert folders[0] == folders[1]
    assert os.path.exists(os.path.join(folders[0], "ckpt_epoch_2", "meta.json"))


def test_two_process_full_driver(tmp_path):
    """The COMPLETE pretrain driver across two real processes: epoch loops,
    per-process data shards, cross-process collectives, and process-0-gated
    checkpoint/log I/O — the closest this host gets to a 2-host launch."""
    outs = _run_driver_children(tmp_path, "driver")

    steps = []
    folders = []
    for out in outs:
        line = [l for l in out.splitlines() if l.startswith("DRIVER ")][0]
        steps.append(int(line.split("step=")[1].split()[0]))
        folders.append(line.split("save_folder=")[1])
    # 128-16 test split = 112 train -> 3 global steps/epoch at batch 32, x2
    assert steps == [6, 6], steps
    assert folders[0] == folders[1], folders  # same derived run folder
    # process-0 wrote the checkpoints; they are complete (meta stamped)
    assert os.path.exists(os.path.join(folders[0], "last", "meta.json"))
    assert os.path.exists(os.path.join(folders[0], "ckpt_epoch_2", "meta.json"))
