"""REAL multi-process data-parallel training (the multi-host runtime path).

Everything else in the suite fakes multi-chip with one process + 8 virtual
devices, which never exercises the true multi-host machinery: gloo-backed
``jax.distributed.initialize`` rendezvous, per-process ``EpochLoader`` shards,
and ``jax.make_array_from_process_local_data`` assembling a global batch from
process-local blocks (``parallel/mesh.py shard_host_batch``). This test spawns
two REAL OS processes, each owning one CPU device, runs one training step, and
checks both agree with a single-process run of the same global step.
"""

import os
import socket
import subprocess
import sys

import numpy as np
import pytest

pytestmark = pytest.mark.slow

CHILD = os.path.join(os.path.dirname(__file__), "multiprocess_child.py")
REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("localhost", 0))
        return s.getsockname()[1]


def _run_children(nproc: int, port: int):
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
    # children build their own device topology; drop the parent's 8-device flag
    env["XLA_FLAGS"] = " ".join(
        f for f in env.get("XLA_FLAGS", "").split()
        if "host_platform_device_count" not in f
    )
    env.pop("PALLAS_AXON_POOL_IPS", None)
    # share the suite's persistent compile cache (conftest isn't imported by
    # the children; without this every run pays the full cold compile)
    env.setdefault(
        "JAX_COMPILATION_CACHE_DIR", os.path.join(REPO, ".jax_cache")
    )
    procs = [
        subprocess.Popen(
            [sys.executable, CHILD, str(i), str(nproc), str(port)],
            env=env, stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
            text=True, cwd=REPO,
        )
        for i in range(nproc)
    ]
    outs = []
    try:
        for p in procs:
            out, _ = p.communicate(timeout=540)
            assert p.returncode == 0, out
            outs.append(out)
    finally:
        # a failed coordinator must not orphan the peer blocked in rendezvous
        for p in procs:
            if p.poll() is None:
                p.kill()
    return outs


def _loss_of(out: str) -> float:
    for line in out.splitlines():
        if line.startswith("LOSS "):
            return float(line.split()[1])
    raise AssertionError(f"no LOSS line in:\n{out}")


def test_two_process_step_matches_single_process():
    ref = _loss_of(_run_children(1, _free_port())[0])
    outs = _run_children(2, _free_port())
    losses = [_loss_of(o) for o in outs]
    # both processes compute the same replicated global loss...
    assert losses[0] == losses[1], losses
    # ...equal to the single-process run of the identical global batch
    np.testing.assert_allclose(losses[0], ref, rtol=1e-6)


def test_two_process_full_driver(tmp_path):
    """The COMPLETE pretrain driver across two real processes: epoch loops,
    per-process data shards, cross-process collectives, and process-0-gated
    checkpoint/log I/O — the closest this host gets to a 2-host launch."""
    port = _free_port()
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
    env["XLA_FLAGS"] = " ".join(
        f for f in env.get("XLA_FLAGS", "").split()
        if "host_platform_device_count" not in f
    )
    env.pop("PALLAS_AXON_POOL_IPS", None)
    env.setdefault("JAX_COMPILATION_CACHE_DIR", os.path.join(REPO, ".jax_cache"))
    procs = [
        subprocess.Popen(
            [sys.executable, CHILD, str(i), "2", str(port), "driver",
             str(tmp_path)],
            env=env, stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
            text=True, cwd=REPO,
        )
        for i in range(2)
    ]
    outs = []
    try:
        for p in procs:
            out, _ = p.communicate(timeout=540)
            assert p.returncode == 0, out
            outs.append(out)
    finally:
        for p in procs:
            if p.poll() is None:
                p.kill()

    steps = []
    folders = []
    for out in outs:
        line = [l for l in out.splitlines() if l.startswith("DRIVER ")][0]
        steps.append(int(line.split("step=")[1].split()[0]))
        folders.append(line.split("save_folder=")[1])
    # 128-16 test split = 112 train -> 3 global steps/epoch at batch 32, x2
    assert steps == [6, 6], steps
    assert folders[0] == folders[1], folders  # same derived run folder
    # process-0 wrote the checkpoints; they are complete (meta stamped)
    assert os.path.exists(os.path.join(folders[0], "last", "meta.json"))
    assert os.path.exists(os.path.join(folders[0], "ckpt_epoch_2", "meta.json"))
