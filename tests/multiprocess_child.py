"""Child worker for test_multiprocess.py: one REAL OS process of a
multi-process data-parallel training step (the multi-host path of
parallel/mesh.py + data/pipeline.py).

Usage: python multiprocess_child.py <process_id> <num_processes> <port> [mode]

With num_processes > 1 it joins a gloo-backed jax.distributed cluster (each
process contributing CHILD_LOCAL_DEVICES virtual CPU devices — default 1, the
original one-device-per-process topology; 2 models a real pod host with
multiple local chips, where host-batch slicing, ring ppermute, and collective
saves cross BOTH the process and the local-device boundary) and prints the
first training step's loss; with num_processes == 1 it computes the same
GLOBAL step alone (CHILD_LOCAL_DEVICES devices, default 2) as the reference
value. The parent asserts all printed losses match.

mode 'driver' runs the FULL pretrain driver (supcon.run) instead of one step:
epoch loops, meters, process-0-gated checkpointing/logging — the closest this
host can get to a real 2-host launch.
"""

import os
import sys

pid, nproc, port = int(sys.argv[1]), int(sys.argv[2]), int(sys.argv[3])
mode = sys.argv[4] if len(sys.argv) > 4 else "step"
# devices this process contributes; the single-process reference defaults to
# 2 so it reproduces the same global partitioning as 2 x 1-device processes
ndev_local = int(os.environ.get("CHILD_LOCAL_DEVICES", "2" if nproc == 1 else "1"))
if ndev_local > 1:
    os.environ["XLA_FLAGS"] = (
        os.environ.get("XLA_FLAGS", "")
        + f" --xla_force_host_platform_device_count={ndev_local}"
    ).strip()

import jax

jax.config.update("jax_platforms", "cpu")
if nproc > 1:
    # cross-process CPU collectives need the gloo implementation selected
    # BEFORE the backend is created — without it every multi-process jit
    # dies with "Multiprocess computations aren't implemented on the CPU
    # backend" (the env-var spelling does not reach this flag on this
    # jax/jaxlib, so it must be a config update here)
    jax.config.update("jax_cpu_collectives_implementation", "gloo")
cache_dir = os.environ.get("JAX_COMPILATION_CACHE_DIR")
if cache_dir:
    jax.config.update("jax_compilation_cache_dir", os.path.abspath(cache_dir))
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)
if nproc > 1:
    jax.distributed.initialize(
        coordinator_address=f"localhost:{port}",
        num_processes=nproc,
        process_id=pid,
    )

if mode in ("driver", "driver_partial", "ce"):
    # full drivers: tiny synthetic run; process 0 owns I/O
    from simclr_pytorch_distributed_tpu import config as config_lib
    from simclr_pytorch_distributed_tpu.data import cifar as cifar_lib

    _orig = cifar_lib.synthetic_dataset
    cifar_lib.synthetic_dataset = (
        lambda n=2048, num_classes=10, seed=0, size=32: _orig(
            n=128, num_classes=num_classes, seed=seed, size=8
        )
    )
    workdir = sys.argv[5]

    if mode == "ce":
        # the CE driver shares broadcast_from_main/collective-save machinery
        # that only supcon exercised before (round-2 weak #5)
        from simclr_pytorch_distributed_tpu.train import ce as ce_driver

        cfg = config_lib.LinearConfig(
            model="resnet10", dataset="synthetic", batch_size=32, epochs=2,
            learning_rate=0.05, save_freq=2, print_freq=2, size=8,
            workdir=workdir, seed=0, trial="mpce",
        )
        cfg = config_lib.finalize_linear(cfg, prefix="ce_")
        best_acc, _ = ce_driver.run(cfg)
        print(f"CE best_acc={best_acc:.4f} save_folder={cfg.save_folder}",
              flush=True)
        sys.exit(0)

    from simclr_pytorch_distributed_tpu.train import supcon as supcon_driver

    # fleet-evidence hook (docs/evidence/fleet_report_r13.json): make ONE
    # process a deliberate straggler by delaying its arrival at every
    # flush-boundary failure-code allgather — the injected skew must show
    # up in trace_report --fleet's skew table with this process named
    straggler_ms = float(os.environ.get("FLEET_STRAGGLER_MS", "0") or 0)
    if straggler_ms and pid == int(os.environ.get("FLEET_STRAGGLER_PID", "1")):
        import time as _time

        from simclr_pytorch_distributed_tpu.utils.telemetry import (
            TelemetrySession,
        )

        _orig_check = TelemetrySession.check_failures_global

        def _late_check(self, step_hint=0):
            _time.sleep(straggler_ms / 1e3)
            return _orig_check(self, step_hint)

        TelemetrySession.check_failures_global = _late_check

    epochs = int(sys.argv[6]) if len(sys.argv) > 6 else 2
    resume = sys.argv[7] if len(sys.argv) > 7 else ""
    cfg = config_lib.SupConConfig(
        model="resnet10", dataset="synthetic", batch_size=32, epochs=epochs,
        learning_rate=0.05, temp=0.5, cosine=True, syncBN=True,
        save_freq=2, print_freq=2, size=8, workdir=workdir, seed=0,
        method="SimCLR", trial="mp", resume=resume,
        # supervised-fleet hook (scripts/fleet_launcher.py sets the env on
        # process 0 only): expose the /metrics sidecar so the supervisor
        # scrapes the REAL gloo fleet's skew gauges
        metrics_port=int(os.environ.get("CHILD_METRICS_PORT", "0") or 0),
    )
    cfg = config_lib.finalize_supcon(cfg)

    if mode == "driver_partial":
        # simulated mid-job crash: die at the START of epoch 3, after the
        # (async) epoch-2 scheduled save; run()'s finally drains the save
        _orig_epoch = supcon_driver.train_one_epoch

        def _patched(epoch, *a, **k):
            if epoch == 3:
                raise RuntimeError("simulated crash before epoch 3")
            return _orig_epoch(epoch, *a, **k)

        supcon_driver.train_one_epoch = _patched
        try:
            supcon_driver.run(cfg)
            raise SystemExit("expected the simulated crash")
        except RuntimeError:
            print(f"PARTIAL save_folder={cfg.save_folder}", flush=True)
            sys.exit(0)

    def _run_and_print():
        state = supcon_driver.run(cfg)
        import jax as _jax

        digest = sum(
            float(abs(x).sum()) for x in _jax.tree.leaves(state.params)
        )
        print(
            f"DRIVER step={int(state.step)} digest={digest:.6f} "
            f"save_folder={cfg.save_folder}",
            flush=True,
        )

    if os.environ.get("CHILD_GUARDED"):
        # supervised-fleet hook: run under the drivers' typed exit-code
        # surface so a collective preemption leaves as the clean exit 75
        # the supervisor's preempt contract classifies (without it a
        # PreemptionError would crash out as a generic rc 1)
        from simclr_pytorch_distributed_tpu.utils import guard as guard_lib

        guard_lib.exit_with_code(_run_and_print)
    else:
        _run_and_print()
    sys.exit(0)

import jax.numpy as jnp
import numpy as np

from simclr_pytorch_distributed_tpu.data.pipeline import EpochLoader
from simclr_pytorch_distributed_tpu.models import SupConResNet
from simclr_pytorch_distributed_tpu.ops.schedules import make_lr_schedule
from simclr_pytorch_distributed_tpu.parallel.mesh import (
    create_mesh,
    shard_host_batch,
)
from simclr_pytorch_distributed_tpu.train.state import (
    create_train_state,
    make_optimizer,
)
from simclr_pytorch_distributed_tpu.train.supcon_step import (
    SupConStepConfig,
    make_sharded_train_step,
)

# mode 'fused'/'fused_supcon' needs >= 8 anchor rows per device (the sharded
# kernel's tiling floor, ops/pallas_loss.py _pick_block): global batch 16 ->
# 32 view rows -> m=8 on the 4-device topologies.
B = 16 if mode.startswith("fused") else 8
size = 8
model = SupConResNet(model_name="resnet10")
schedule = make_lr_schedule(
    learning_rate=0.05, epochs=2, steps_per_epoch=2, cosine=True
)
tx = make_optimizer(schedule, momentum=0.9, weight_decay=1e-4)
state = create_train_state(model, tx, jax.random.key(0), jnp.zeros((2, size, size, 3)))
cfg = SupConStepConfig(
    # 'fused_supcon' drives the label-carrying (SupCon) leg of the sharded
    # fused kernel; every other mode keeps the SimCLR recipe
    method=("SupCon" if mode == "fused_supcon" else "SimCLR"),
    temperature=0.5, epochs=2, steps_per_epoch=2, grad_div=2.0,
    # mode 'ring': the ppermute-rotating sharded loss across REAL process
    # boundaries — the DP step only exercises psum/all-gather over gloo.
    # mode 'fused'/'fused_supcon': the shard_map-sharded Pallas kernel
    # (interpret mode on CPU), the exact path resolve_loss_impl('auto')
    # selects on multi-device TPU meshes — its check_vma=False/psum-cotangent
    # custom VJP is the plumbing most at risk across process boundaries.
    loss_impl={"ring": "ring", "fused": "fused", "fused_supcon": "fused"}.get(
        mode, "dense"
    ),
)
mesh = create_mesh()
assert mesh.size == nproc * ndev_local, (mesh, nproc, ndev_local)
step = make_sharded_train_step(
    model, tx, schedule, cfg, mesh, state_shape=state, donate=False
)

# identical dataset on every process; EpochLoader slices this process's
# contiguous block of each global batch (the DistributedSampler equivalent)
rng = np.random.default_rng(0)
images = rng.standard_normal((2 * B, 2, size, size, 3)).astype(np.float32)
labels = rng.integers(0, 4, 2 * B).astype(np.int32)
loader = EpochLoader(
    images, labels, B, base_seed=0,
    process_index=jax.process_index(), process_count=jax.process_count(),
    prefetch=0,
)
# TWO steps: step 2's loss depends on step 1's parameter update, so the
# printed value witnesses the BACKWARD (grad + optimizer + collectives)
# across the process boundary, not just the forward loss reduction.
for imgs_local, labs_local in loader.epoch(1):
    batch = shard_host_batch((imgs_local, labs_local), mesh)
    state, metrics = step(state, batch[0], batch[1])
print(f"LOSS {float(metrics['loss']):.8f}", flush=True)
