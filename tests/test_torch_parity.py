"""Direct golden parity against the INSTALLED torch reference.

Round-2 verdict item 1: the strongest evidence this data-less environment can
produce that the published 89.05% recipe transfers is to test against the
actual reference implementation, not a re-derivation. These tests import
``/root/reference``'s ``losses.py`` / ``networks/resnet_big.py`` / ``util.py``
via importlib and treat them strictly as numeric oracles:

- loss parity: ``supcon_loss`` / ``fused_supcon_loss`` / ``ring_supcon_loss``
  vs ``SupConLoss.forward`` over temp x method x contrast_mode, values AND
  input gradients;
- weight-transplant forward parity: a torch ``SupConResNet``'s state_dict
  moved into the Flax model must produce the same encoder features and head
  outputs (eval mode, populated running stats), plus an input-grad cosine;
- schedule parity: ``make_lr_schedule`` vs the reference's live
  ``adjust_learning_rate`` + ``warmup_learning_rate`` mutating a real torch
  optimizer, at every step of a 100-epoch run;
- checkpoint interop: a fabricated reference-format ``.pth`` converted by
  ``utils/torch_convert.py`` loads through ``load_pretrained_variables`` and
  reproduces the torch encoder's features.
"""

import importlib.util
import os

import jax
import jax.numpy as jnp
import numpy as np
import pytest
import torch

from simclr_pytorch_distributed_tpu.ops.losses import supcon_loss
from simclr_pytorch_distributed_tpu.ops.pallas_loss import fused_supcon_loss
from simclr_pytorch_distributed_tpu.utils.torch_convert import (
    infer_architecture,
    torch_state_dict_to_variables,
)

REFERENCE_DIR = "/root/reference"

pytestmark = pytest.mark.skipif(
    not os.path.isdir(REFERENCE_DIR), reason="reference checkout not present"
)


def _load_ref(name: str, rel_path: str):
    spec = importlib.util.spec_from_file_location(
        name, os.path.join(REFERENCE_DIR, rel_path)
    )
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


@pytest.fixture(scope="module")
def ref_losses():
    return _load_ref("ref_losses", "losses.py")


@pytest.fixture(scope="module")
def ref_resnet_big():
    return _load_ref("ref_resnet_big", "networks/resnet_big.py")


@pytest.fixture(scope="module")
def ref_util():
    return _load_ref("ref_util", "util.py")


def _features(seed, batch=8, views=2, dim=16):
    x = np.random.default_rng(seed).normal(size=(batch, views, dim))
    x = x / np.linalg.norm(x, axis=-1, keepdims=True)
    return x.astype(np.float32)


def _pos_mask(seed, batch=8):
    """Reference-legal explicit mask: eye + a few symmetric extra positives."""
    rng = np.random.default_rng(seed)
    extra = (rng.random((batch, batch)) < 0.2).astype(np.float32)
    m = np.clip(np.eye(batch, dtype=np.float32) + extra + extra.T, 0, 1)
    return m


# ---------------------------------------------------------------- losses


@pytest.mark.parametrize("temperature", [0.07, 0.5])
@pytest.mark.parametrize("mode", ["simclr", "labels", "mask"])
@pytest.mark.parametrize("contrast_mode", ["all", "one"])
def test_dense_loss_matches_reference(ref_losses, temperature, mode, contrast_mode):
    # deterministic per-case seed (hash() is PYTHONHASHSEED-salted)
    seed = int(temperature * 100) + {"simclr": 0, "labels": 1, "mask": 2}[mode]
    feats = _features(seed=seed)
    labels = np.random.default_rng(3).integers(0, 3, feats.shape[0])
    mask = _pos_mask(5)

    criterion = ref_losses.SupConLoss(
        temperature=temperature, contrast_mode=contrast_mode
    )
    ft = torch.tensor(feats, requires_grad=True)
    kwargs_t = {}
    kwargs_j = {}
    if mode == "labels":
        kwargs_t["labels"] = torch.tensor(labels)
        kwargs_j["labels"] = jnp.asarray(labels)
    elif mode == "mask":
        kwargs_t["mask"] = torch.tensor(mask)
        kwargs_j["mask"] = jnp.asarray(mask)
    loss_t = criterion(ft, **kwargs_t)
    loss_t.backward()

    def loss_j(f):
        return supcon_loss(
            f, temperature=temperature, base_temperature=0.07,
            contrast_mode=contrast_mode, **kwargs_j,
        )

    val, grad = jax.value_and_grad(loss_j)(jnp.asarray(feats))
    np.testing.assert_allclose(float(val), float(loss_t.detach()), rtol=1e-5)
    np.testing.assert_allclose(
        np.asarray(grad), ft.grad.numpy(), rtol=1e-4, atol=1e-6
    )


@pytest.mark.parametrize("temperature", [0.07, 0.5])
@pytest.mark.parametrize("use_labels", [False, True])
def test_fused_loss_matches_reference(ref_losses, temperature, use_labels):
    """The Pallas kernel (interpret mode on CPU) against the torch oracle."""
    feats = _features(seed=11)
    labels = np.random.default_rng(7).integers(0, 3, feats.shape[0])

    criterion = ref_losses.SupConLoss(temperature=temperature)
    ft = torch.tensor(feats, requires_grad=True)
    loss_t = criterion(ft, labels=torch.tensor(labels) if use_labels else None)
    loss_t.backward()

    def loss_j(f):
        return fused_supcon_loss(
            f, jnp.asarray(labels) if use_labels else None,
            temperature=temperature, base_temperature=0.07, interpret=True,
        )

    val, grad = jax.value_and_grad(loss_j)(jnp.asarray(feats))
    np.testing.assert_allclose(float(val), float(loss_t.detach()), rtol=1e-5)
    np.testing.assert_allclose(
        np.asarray(grad), ft.grad.numpy(), rtol=1e-4, atol=1e-6
    )


@pytest.mark.slow
@pytest.mark.parametrize("use_labels", [False, True])
def test_ring_loss_matches_reference(ref_losses, use_labels):
    """The ring-sharded loss on the 8-device mesh against the torch oracle."""
    from simclr_pytorch_distributed_tpu.compat import shard_map
    from jax.sharding import Mesh, PartitionSpec as P

    from simclr_pytorch_distributed_tpu.parallel.collectives import (
        ring_supcon_loss,
    )

    temperature = 0.5
    feats = _features(seed=13, batch=16, dim=24)
    labels = np.random.default_rng(9).integers(0, 4, feats.shape[0])

    criterion = ref_losses.SupConLoss(temperature=temperature)
    ft = torch.tensor(feats, requires_grad=True)
    loss_t = criterion(ft, labels=torch.tensor(labels) if use_labels else None)
    loss_t.backward()

    mesh = Mesh(np.array(jax.devices()), ("data",))
    rows = jnp.transpose(jnp.asarray(feats), (1, 0, 2)).reshape(-1, feats.shape[-1])

    def ring(r):
        fn = shard_map(
            lambda rr: ring_supcon_loss(
                rr, jnp.asarray(labels) if use_labels else None,
                axis_name="data", temperature=temperature, base_temperature=0.07,
            ),
            mesh=mesh, in_specs=P("data"), out_specs=P(),
        )
        return fn(r)

    val, grad_rows = jax.value_and_grad(ring)(rows)
    grad = jnp.transpose(
        grad_rows.reshape(2, feats.shape[0], feats.shape[-1]), (1, 0, 2)
    )
    np.testing.assert_allclose(float(val), float(loss_t.detach()), rtol=2e-5)
    np.testing.assert_allclose(
        np.asarray(grad), ft.grad.numpy(), rtol=1e-4, atol=1e-6
    )


@pytest.mark.slow
@pytest.mark.parametrize("use_labels", [False, True])
def test_fused_sharded_loss_matches_reference(ref_losses, use_labels):
    """The shard_map-sharded Pallas kernel (8-device mesh, interpret mode)
    DIRECTLY against the torch oracle — the fourth engine gets the same
    golden treatment as dense/fused/ring, not just sharded==dense."""
    from simclr_pytorch_distributed_tpu.compat import shard_map
    from jax.sharding import Mesh, PartitionSpec as P

    from simclr_pytorch_distributed_tpu.ops.pallas_loss import (
        fused_sharded_supcon_loss,
    )

    temperature = 0.5
    feats = _features(seed=17, batch=32, dim=24)
    labels = np.random.default_rng(15).integers(0, 4, feats.shape[0])

    criterion = ref_losses.SupConLoss(temperature=temperature)
    ft = torch.tensor(feats, requires_grad=True)
    loss_t = criterion(ft, labels=torch.tensor(labels) if use_labels else None)
    loss_t.backward()

    mesh = Mesh(np.array(jax.devices()), ("data",))
    rows = jnp.transpose(jnp.asarray(feats), (1, 0, 2)).reshape(-1, feats.shape[-1])

    def fused_sharded(r):
        fn = shard_map(
            lambda rr: fused_sharded_supcon_loss(
                rr, jnp.asarray(labels) if use_labels else None,
                axis_name="data", temperature=temperature,
                base_temperature=0.07, interpret=True,
            ),
            mesh=mesh, in_specs=P("data"), out_specs=P(), check_vma=False,
        )
        return fn(r)

    val, grad_rows = jax.value_and_grad(fused_sharded)(rows)
    grad = jnp.transpose(
        grad_rows.reshape(2, feats.shape[0], feats.shape[-1]), (1, 0, 2)
    )
    np.testing.assert_allclose(float(val), float(loss_t.detach()), rtol=2e-5)
    np.testing.assert_allclose(
        np.asarray(grad), ft.grad.numpy(), rtol=1e-4, atol=1e-6
    )


# ------------------------------------------------- weight transplant


def _transplanted_pair(ref_resnet_big, model_name: str, seed: int = 0):
    """(torch model with populated running stats, matching flax variables)."""
    from simclr_pytorch_distributed_tpu.models import SupConResNet

    torch.manual_seed(seed)
    tm = ref_resnet_big.SupConResNet(name=model_name)
    # populate running statistics so the stats copy is actually exercised
    tm.train()
    with torch.no_grad():
        tm(torch.randn(8, 3, 32, 32))
    tm.eval()

    variables = jax.tree.map(
        jnp.asarray, torch_state_dict_to_variables(tm.state_dict())
    )
    fm = SupConResNet(model_name=model_name)
    # shape-check the transplant against a fresh init: identical tree structure
    init_vars = fm.init(jax.random.key(0), jnp.zeros((2, 32, 32, 3)))
    chex_paths = jax.tree_util.tree_structure(init_vars)
    assert jax.tree_util.tree_structure(variables) == chex_paths
    for a, b in zip(jax.tree.leaves(init_vars), jax.tree.leaves(variables)):
        assert a.shape == b.shape
    return tm, fm, variables


@pytest.mark.parametrize("model_name", ["resnet18"])
def test_weight_transplant_forward_parity(ref_resnet_big, model_name):
    """torch SupConResNet == Flax SupConResNet under transplanted weights:
    encoder features and head output in eval mode, and input-grad cosine."""
    from simclr_pytorch_distributed_tpu.models import SupConResNet

    tm, fm, variables = _transplanted_pair(ref_resnet_big, model_name)
    x = np.random.default_rng(1).normal(size=(4, 3, 32, 32)).astype(np.float32)
    x_nhwc = jnp.asarray(np.transpose(x, (0, 2, 3, 1)))

    with torch.no_grad():
        feat_t = tm.encoder(torch.tensor(x)).numpy()
        out_t = tm(torch.tensor(x)).numpy()

    feat_j = fm.apply(variables, x_nhwc, train=False, method=SupConResNet.encode)
    out_j = fm.apply(variables, x_nhwc, train=False)
    np.testing.assert_allclose(np.asarray(feat_j), feat_t, rtol=1e-3, atol=1e-4)
    np.testing.assert_allclose(np.asarray(out_j), out_t, rtol=1e-3, atol=1e-4)

    # gradient direction agrees: d(mean(head_out^2))/d(input)
    xt = torch.tensor(x, requires_grad=True)
    tm(xt).pow(2).mean().backward()
    g_t = np.transpose(xt.grad.numpy(), (0, 2, 3, 1)).ravel()

    g_j = np.asarray(
        jax.grad(
            lambda xx: jnp.mean(fm.apply(variables, xx, train=False) ** 2)
        )(x_nhwc)
    ).ravel()
    cos = g_t @ g_j / (np.linalg.norm(g_t) * np.linalg.norm(g_j))
    assert cos > 0.9999, cos


@pytest.mark.slow
def test_weight_transplant_forward_parity_resnet50(ref_resnet_big):
    """The flagship bottleneck architecture, same transplant contract."""
    from simclr_pytorch_distributed_tpu.models import SupConResNet

    tm, fm, variables = _transplanted_pair(ref_resnet_big, "resnet50")
    x = np.random.default_rng(2).normal(size=(2, 3, 32, 32)).astype(np.float32)
    x_nhwc = jnp.asarray(np.transpose(x, (0, 2, 3, 1)))
    with torch.no_grad():
        feat_t = tm.encoder(torch.tensor(x)).numpy()
        out_t = tm(torch.tensor(x)).numpy()
    feat_j = fm.apply(variables, x_nhwc, train=False, method=SupConResNet.encode)
    out_j = fm.apply(variables, x_nhwc, train=False)
    np.testing.assert_allclose(np.asarray(feat_j), feat_t, rtol=1e-3, atol=2e-4)
    np.testing.assert_allclose(np.asarray(out_j), out_t, rtol=1e-3, atol=2e-4)


def test_full_train_step_gradient_parity(ref_losses, ref_resnet_big):
    """END-TO-END gradient parity of the reference's training computation:
    two-crop batch -> encoder -> head -> row-normalize -> SupConLoss,
    differentiated through the WHOLE chain (train-mode BN) on transplanted
    weights. main_supcon.py:276-290 composition on the torch side; our
    two_view_forward + supcon_loss on the JAX side. Input gradients AND
    representative parameter gradients must agree."""
    import torch.nn.functional as F

    from simclr_pytorch_distributed_tpu.train.supcon_step import (
        two_view_forward,
    )

    b, s, temp = 8, 16, 0.5
    tm, fm, variables = _transplanted_pair(ref_resnet_big, "resnet18")
    tm.train()
    criterion = ref_losses.SupConLoss(temperature=temp)

    x = np.random.default_rng(31).normal(size=(b, 2, 3, s, s)).astype(np.float32)

    # ---- torch side (reference composition, main_supcon.py:276-290)
    xt = torch.tensor(x, requires_grad=True)
    cat = torch.cat([xt[:, 0], xt[:, 1]], dim=0)  # view-major [2B, 3, H, W]
    feats_t = F.normalize(tm(cat), dim=1)
    f1, f2 = torch.split(feats_t, [b, b], dim=0)
    stacked = torch.cat([f1.unsqueeze(1), f2.unsqueeze(1)], dim=1)
    loss_t = criterion(stacked)
    loss_t.backward()

    # ---- jax side (our step's forward, ops losses), same weights
    x_nhwc = jnp.asarray(np.transpose(x, (0, 1, 3, 4, 2)))  # [B, 2, H, W, C]

    def loss_fn(params, xx):
        feats, _ = two_view_forward(
            fm, params, variables["batch_stats"], xx, train=True
        )
        feats = feats / jnp.linalg.norm(feats, axis=-1, keepdims=True)
        fbvd = jnp.transpose(feats.reshape(2, b, -1), (1, 0, 2))
        return supcon_loss(fbvd, temperature=temp, base_temperature=0.07)

    val, (g_params, g_x) = jax.value_and_grad(loss_fn, argnums=(0, 1))(
        variables["params"], x_nhwc
    )
    np.testing.assert_allclose(float(val), float(loss_t.detach()), rtol=1e-4)

    # input gradients: the full backward chain in one number. XLA and torch
    # accumulate 20+ layers of fp32 in different orders, so tiny elements
    # drift to ~1e-3 relative — compare direction + relative L2 error.
    g_x_t = np.transpose(xt.grad.numpy(), (0, 1, 3, 4, 2)).ravel()
    g_x_j = np.asarray(g_x).ravel()
    rel_l2 = np.linalg.norm(g_x_j - g_x_t) / np.linalg.norm(g_x_t)
    cos = g_x_j @ g_x_t / (np.linalg.norm(g_x_j) * np.linalg.norm(g_x_t))
    assert rel_l2 < 5e-3, rel_l2
    assert cos > 0.99999, cos

    # representative parameter gradients across the depth of the network
    named_t = dict(tm.named_parameters())
    checks = [
        (("encoder", "conv1", "kernel"), "encoder.conv1.weight", (2, 3, 1, 0)),
        (("encoder", "bn1", "scale"), "encoder.bn1.weight", None),
        (("encoder", "layer3_block0", "Conv_0", "kernel"),
         "encoder.layer3.0.conv1.weight", (2, 3, 1, 0)),
        (("proj_head", "fc2", "kernel"), "head.2.weight", (1, 0)),
    ]
    for jpath, tname, perm in checks:
        gj = g_params
        for k in jpath:
            gj = gj[k]
        gt = named_t[tname].grad.numpy()
        if perm is not None:
            gt = np.transpose(gt, perm)
        gj, gt = np.asarray(gj).ravel(), gt.ravel()
        rel = np.linalg.norm(gj - gt) / np.linalg.norm(gt)
        assert rel < 5e-3, f"{tname}: rel L2 {rel}"


# ------------------------------------------------------- schedules


@pytest.mark.parametrize("cosine", [True, False])
@pytest.mark.parametrize("warm", [True, False])
def test_schedule_matches_reference_loop(ref_util, cosine, warm):
    """make_lr_schedule(step) == the reference's live adjust+warmup loop
    mutating a real torch optimizer, at EVERY step of a 100-epoch run."""
    import argparse

    from simclr_pytorch_distributed_tpu.ops.schedules import (
        make_lr_schedule,
        warmup_to_value,
    )

    epochs, steps_per_epoch = 100, 5
    lr, decay_rate, decay_epochs = 0.5, 0.1, (60, 75, 90)
    warm_epochs, warmup_from = 10, 0.01
    args = argparse.Namespace(
        learning_rate=lr, cosine=cosine, lr_decay_rate=decay_rate,
        lr_decay_epochs=decay_epochs, epochs=epochs, warm=warm,
        warm_epochs=warm_epochs, warmup_from=warmup_from,
        warmup_to=warmup_to_value(lr, decay_rate, warm_epochs, epochs, cosine),
    )
    opt = torch.optim.SGD([torch.nn.Parameter(torch.zeros(1))], lr=lr)

    schedule = make_lr_schedule(
        learning_rate=lr, epochs=epochs, steps_per_epoch=steps_per_epoch,
        cosine=cosine, lr_decay_rate=decay_rate, lr_decay_epochs=decay_epochs,
        warm=warm, warm_epochs=warm_epochs, warmup_from=warmup_from,
    )
    ours = np.asarray(
        jax.vmap(schedule)(jnp.arange(epochs * steps_per_epoch))
    )

    step = 0
    for epoch in range(1, epochs + 1):  # main_supcon.py:382 epoch loop
        ref_util.adjust_learning_rate(args, opt, epoch)
        for batch_id in range(steps_per_epoch):  # :263 per-iter warmup
            ref_util.warmup_learning_rate(
                args, epoch, batch_id, steps_per_epoch, opt
            )
            ref_lr = opt.param_groups[0]["lr"]
            # our schedule evaluates in fp32 inside the jitted step; the
            # reference computes in python float64 — fp32 ulp tolerance
            np.testing.assert_allclose(
                ours[step], ref_lr, rtol=1e-5, atol=1e-8,
                err_msg=f"epoch {epoch} batch {batch_id} (step {step})",
            )
            step += 1


# ------------------------------------------------ checkpoint interop


def test_reference_checkpoint_converts_and_loads(ref_resnet_big, tmp_path):
    """Fabricated reference-format .pth (util.py:87-96: 'module.'-prefixed
    state_dict under 'model') -> convert -> load via load_pretrained_variables
    -> flax encoder features match the torch encoder."""
    from simclr_pytorch_distributed_tpu.models import SupConResNet
    from simclr_pytorch_distributed_tpu.utils.checkpoint import (
        load_pretrained_variables,
    )
    from simclr_pytorch_distributed_tpu.utils.torch_convert import (
        convert_reference_checkpoint,
    )

    torch.manual_seed(3)
    tm = ref_resnet_big.SupConResNet(name="resnet18")
    tm.train()
    with torch.no_grad():
        tm(torch.randn(8, 3, 32, 32))
    tm.eval()

    pth = tmp_path / "ckpt_epoch_7.pth"
    torch.save(
        {
            "opt": None,
            "model": {f"module.{k}": v for k, v in tm.state_dict().items()},
            "optimizer": {},
            "epoch": 7,
        },
        str(pth),
    )
    out = tmp_path / "converted"
    info = convert_reference_checkpoint(str(pth), str(out))
    assert (info["model_name"], info["head"], info["feat_dim"]) == (
        "resnet18", "mlp", 128,
    )
    assert info["epoch"] == 7

    fm = SupConResNet(model_name="resnet18")
    abstract = fm.init(jax.random.key(0), jnp.zeros((2, 32, 32, 3)))
    variables = load_pretrained_variables(str(out), abstract)

    x = np.random.default_rng(4).normal(size=(4, 3, 32, 32)).astype(np.float32)
    with torch.no_grad():
        feat_t = tm.encoder(torch.tensor(x)).numpy()
    feat_j = fm.apply(
        {"params": variables["params"], "batch_stats": variables["batch_stats"]},
        jnp.asarray(np.transpose(x, (0, 2, 3, 1))),
        train=False, method=SupConResNet.encode,
    )
    np.testing.assert_allclose(np.asarray(feat_j), feat_t, rtol=1e-3, atol=1e-4)

    # and the .pth FILE itself is a valid --ckpt argument (auto-converted)
    direct = load_pretrained_variables(str(pth), abstract)
    for a, b in zip(jax.tree.leaves(direct), jax.tree.leaves(variables)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


def test_export_roundtrip_reproduces_reference_state_dict(ref_resnet_big):
    """variables_to_torch_state_dict is the exact inverse of the import
    mapping: torch state_dict -> variables -> state_dict is the identity
    (keys AND values), so nothing is lost in a pth -> orbax -> pth trip."""
    from simclr_pytorch_distributed_tpu.utils.torch_convert import (
        variables_to_torch_state_dict,
    )

    torch.manual_seed(11)
    tm = ref_resnet_big.SupConResNet(name="resnet18")
    tm.train()
    with torch.no_grad():
        tm(torch.randn(8, 3, 32, 32))
    tm.eval()
    sd = {k: v.numpy() for k, v in tm.state_dict().items()}

    back = variables_to_torch_state_dict(torch_state_dict_to_variables(sd))
    assert set(back) == set(sd)
    for k in sd:
        if k.endswith("num_batches_tracked"):
            continue  # synthesized as 0 on export; torch never reads it
        np.testing.assert_allclose(back[k], sd[k], rtol=1e-6, atol=0, err_msg=k)


def test_export_consumed_by_reference_strict_load(ref_resnet_big, tmp_path):
    """An encoder pretrained HERE exports to a .pth the reference itself can
    consume: torch.load -> 'module.' strip -> load_state_dict(strict=True)
    into the reference SupConResNet -> forward parity with the Flax model."""
    from simclr_pytorch_distributed_tpu.models import SupConResNet
    from simclr_pytorch_distributed_tpu.utils.checkpoint import (
        MODEL_LAYOUT_VERSION,
        _save_tree,
        _write_meta,
    )
    from simclr_pytorch_distributed_tpu.utils.torch_convert import (
        export_reference_checkpoint,
    )

    fm = SupConResNet(model_name="resnet18")
    variables = fm.init(jax.random.key(5), jnp.zeros((2, 32, 32, 3)))
    ckpt = tmp_path / "ckpt_epoch_9"
    _save_tree(str(ckpt / "model"), jax.tree.map(np.asarray, dict(variables)))
    _write_meta(str(ckpt), {"epoch": 9, "model_layout": MODEL_LAYOUT_VERSION,
                            "config": {"model": "resnet18"}})

    # a pre-v2 (shifted conv padding) checkpoint must refuse to export: it
    # would strict-load into the reference cleanly yet be silently wrong
    stale = tmp_path / "stale"
    _save_tree(str(stale / "model"), jax.tree.map(np.asarray, dict(variables)))
    _write_meta(str(stale), {"epoch": 1})  # no model_layout -> v1
    with pytest.raises(ValueError, match="layout v1"):
        export_reference_checkpoint(str(stale), str(tmp_path / "stale.pth"))

    out_pth = tmp_path / "exported.pth"
    info = export_reference_checkpoint(str(ckpt), str(out_pth))
    assert (info["model_name"], info["head"], info["feat_dim"]) == (
        "resnet18", "mlp", 128,
    )
    assert info["epoch"] == 9

    payload = torch.load(str(out_pth), map_location="cpu", weights_only=False)
    assert set(payload) == {"opt", "model", "optimizer", "epoch"}
    assert payload["epoch"] == 9
    assert all(k.startswith("module.") for k in payload["model"])

    tm = ref_resnet_big.SupConResNet(name="resnet18")
    tm.load_state_dict(
        {k[len("module."):]: v for k, v in payload["model"].items()},
        strict=True,
    )
    tm.eval()

    x = np.random.default_rng(6).normal(size=(4, 3, 32, 32)).astype(np.float32)
    with torch.no_grad():
        feat_t = tm.encoder(torch.tensor(x)).numpy()
        out_t = tm(torch.tensor(x)).numpy()
    x_nhwc = jnp.asarray(np.transpose(x, (0, 2, 3, 1)))
    feat_j = fm.apply(variables, x_nhwc, train=False, method=SupConResNet.encode)
    out_j = fm.apply(variables, x_nhwc, train=False)
    np.testing.assert_allclose(np.asarray(feat_j), feat_t, rtol=1e-3, atol=1e-4)
    np.testing.assert_allclose(np.asarray(out_j), out_t, rtol=1e-3, atol=1e-4)


def test_export_refuses_missing_meta(tmp_path):
    """A model/ payload without meta.json (the completeness marker and sole
    model_layout carrier) refuses to export unless explicitly overridden —
    an incomplete save must not pass the layout guard silently."""
    from simclr_pytorch_distributed_tpu.models import SupConResNet
    from simclr_pytorch_distributed_tpu.utils.checkpoint import _save_tree
    from simclr_pytorch_distributed_tpu.utils.torch_convert import (
        export_reference_checkpoint,
    )

    fm = SupConResNet(model_name="resnet18")
    variables = fm.init(jax.random.key(8), jnp.zeros((2, 32, 32, 3)))
    ckpt = tmp_path / "incomplete"
    _save_tree(str(ckpt / "model"), jax.tree.map(np.asarray, dict(variables)))
    with pytest.raises(ValueError, match="meta.json"):
        export_reference_checkpoint(str(ckpt), str(tmp_path / "out.pth"))
    info = export_reference_checkpoint(
        str(ckpt), str(tmp_path / "out.pth"), allow_missing_meta=True
    )
    assert os.path.exists(info["path"])


def test_export_refuses_framework_only_model(tmp_path):
    """resnet10 has no entry in the reference's model_dict (resnet_big.py:
    121-142); exporting it would write a .pth the reference cannot load."""
    from simclr_pytorch_distributed_tpu.models import SupConResNet
    from simclr_pytorch_distributed_tpu.utils.checkpoint import (
        MODEL_LAYOUT_VERSION,
        _save_tree,
        _write_meta,
    )
    from simclr_pytorch_distributed_tpu.utils.torch_convert import (
        export_reference_checkpoint,
    )

    fm = SupConResNet(model_name="resnet10")
    variables = fm.init(jax.random.key(9), jnp.zeros((2, 32, 32, 3)))
    ckpt = tmp_path / "r10"
    _save_tree(str(ckpt / "model"), jax.tree.map(np.asarray, dict(variables)))
    _write_meta(str(ckpt), {"epoch": 1, "model_layout": MODEL_LAYOUT_VERSION})
    with pytest.raises(ValueError, match="framework-only"):
        export_reference_checkpoint(str(ckpt), str(tmp_path / "r10.pth"))


def test_missing_batch_stats_raise_named_value_error():
    """A variables tree missing BN stats raises ValueError naming the node
    (the module's stated error contract), not a bare KeyError."""
    from simclr_pytorch_distributed_tpu.models import SupConResNet
    from simclr_pytorch_distributed_tpu.utils.torch_convert import (
        variables_to_torch_state_dict,
    )

    fm = SupConResNet(model_name="resnet18")
    variables = jax.tree.map(
        np.asarray, dict(fm.init(jax.random.key(10), jnp.zeros((2, 32, 32, 3))))
    )
    with pytest.raises(ValueError, match="encoder/bn1"):
        variables_to_torch_state_dict({"params": variables["params"]})

    broken = {
        "params": variables["params"],
        "batch_stats": {
            "encoder": {
                k: v
                for k, v in variables["batch_stats"]["encoder"].items()
                if k != "layer2_block0"
            }
        },
    }
    with pytest.raises(ValueError, match="encoder/layer2_block0"):
        variables_to_torch_state_dict(broken)


def test_export_rejects_s2d_stem():
    """The repacked '--stem s2d' layout has no reference equivalent; export
    must fail loudly rather than write a silently-wrong .pth."""
    from simclr_pytorch_distributed_tpu.models import SupConResNet
    from simclr_pytorch_distributed_tpu.utils.torch_convert import (
        variables_to_torch_state_dict,
    )

    fm = SupConResNet(model_name="resnet18", stem="s2d")
    variables = fm.init(jax.random.key(7), jnp.zeros((2, 32, 32, 3)))
    with pytest.raises(ValueError, match="s2d"):
        variables_to_torch_state_dict(
            jax.tree.map(np.asarray, dict(variables))
        )


def test_topk_accuracy_matches_reference(ref_util):
    """ops.metrics.topk_accuracy vs the reference's accuracy() (util.py:37-51).

    Quirk pinned here: on the installed (modern) torch, the reference's own
    ``correct[:k].view(-1)`` CRASHES for maxk>1 — elementwise ``eq`` preserves
    the transposed striding, so the view is illegal. The reference probe would
    therefore crash calling ``accuracy(..., topk=(1, 5))`` on this torch. We
    oracle-test k=1 (where the reference runs), verify the maxk>1 crash, and
    check (1, 5) against the standard ``.reshape`` repair of the same code."""
    from simclr_pytorch_distributed_tpu.ops.metrics import topk_accuracy

    rng = np.random.default_rng(21)
    logits = rng.normal(size=(64, 10)).astype(np.float32)
    target = rng.integers(0, 10, 64)
    lt, tt = torch.tensor(logits), torch.tensor(target)
    ours = topk_accuracy(jnp.asarray(logits), jnp.asarray(target), topk=(1, 5))

    (ref1,) = ref_util.accuracy(lt, tt, topk=(1,))
    np.testing.assert_allclose(float(ours[0]), float(ref1.item()), rtol=1e-6)

    with pytest.raises(RuntimeError, match="view size"):
        ref_util.accuracy(lt, tt, topk=(1, 5))

    # the reference algorithm with the one-token repair (view -> reshape)
    maxk = 5
    _, pred = lt.topk(maxk, 1, True, True)
    pred = pred.t()
    correct = pred.eq(tt.view(1, -1).expand_as(pred))
    for k, o in zip((1, 5), ours):
        ref_k = correct[:k].reshape(-1).float().sum(0) * (100.0 / len(target))
        np.testing.assert_allclose(float(o), float(ref_k.item()), rtol=1e-6)


def test_average_meter_matches_reference(ref_util):
    from simclr_pytorch_distributed_tpu.ops.metrics import AverageMeter

    ours, ref = AverageMeter(), ref_util.AverageMeter()
    rng = np.random.default_rng(22)
    for _ in range(17):
        v, n = float(rng.normal()), int(rng.integers(1, 9))
        ours.update(v, n)
        ref.update(v, n)
    assert ours.count == ref.count
    np.testing.assert_allclose(ours.avg, ref.avg, rtol=1e-12)
    np.testing.assert_allclose(ours.val, ref.val, rtol=1e-12)


def test_infer_architecture_variants(ref_resnet_big):
    for name, head, feat in [("resnet18", "mlp", 128), ("resnet34", "linear", 64)]:
        tm = ref_resnet_big.SupConResNet(name=name, head=head, feat_dim=feat)
        got = infer_architecture(
            {k: v.numpy() for k, v in tm.state_dict().items()}
        )
        assert got == (name, head, feat)
