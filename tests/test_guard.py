"""NaN-loss failure detection: abort + emergency checkpoint via the driver."""

import math

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from simclr_pytorch_distributed_tpu import config as config_lib
from simclr_pytorch_distributed_tpu.parallel.mesh import create_mesh
from simclr_pytorch_distributed_tpu.train.supcon import train_one_epoch
from simclr_pytorch_distributed_tpu.utils.guard import (
    NonFiniteLossError,
    check_finite_loss,
)


def test_check_finite_loss():
    check_finite_loss(1.0, 0)
    check_finite_loss(float("nan"), 0, enabled=False)  # disabled: no raise
    for bad in (float("nan"), float("inf"), -float("inf")):
        with pytest.raises(NonFiniteLossError, match="non-finite loss"):
            check_finite_loss(bad, 7)


class _FakeLoader:
    def __init__(self, n_steps, batch):
        self.n_steps, self.batch = n_steps, batch

    def epoch(self, _):
        images = np.zeros((self.batch, 4, 4, 3), np.uint8)
        labels = np.zeros((self.batch,), np.int32)
        for _ in range(self.n_steps):
            yield images, labels


def test_epoch_loop_raises_on_nan(monkeypatch):
    cfg = config_lib.SupConConfig(print_freq=1, batch_size=8, nan_guard=True)
    mesh = create_mesh(devices=jax.devices()[:1])
    metrics = {
        "loss": jnp.float32(float("nan")), "norm_mean": jnp.float32(0),
        "norm_var": jnp.float32(0), "record_norm_mean": jnp.float32(0),
        "loss_sec": jnp.float32(0), "loss_l2reg": jnp.float32(0),
    }

    def fake_update(state, images, labels, key):
        return state, metrics

    with pytest.raises(NonFiniteLossError):
        train_one_epoch(
            1, _FakeLoader(3, 8), fake_update, state=None, mesh=mesh,
            base_key=jax.random.key(0), cfg=cfg, tb=None, steps_per_epoch=3,
        )

    # guard off: the same epoch completes and reports the NaN average
    cfg_off = config_lib.SupConConfig(print_freq=1, batch_size=8, nan_guard=False)
    _, loss_avg, _ = train_one_epoch(
        1, _FakeLoader(3, 8), fake_update, state=None, mesh=mesh,
        base_key=jax.random.key(0), cfg=cfg_off, tb=None, steps_per_epoch=3,
    )
    assert math.isnan(loss_avg)
