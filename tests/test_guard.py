"""NaN-loss failure detection + policy: abort vs rollback, and the
preemption flag's flush-boundary observation in the epoch loop."""

import math

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from simclr_pytorch_distributed_tpu import config as config_lib
from simclr_pytorch_distributed_tpu.parallel.mesh import create_mesh
from simclr_pytorch_distributed_tpu.train.supcon import train_one_epoch
from simclr_pytorch_distributed_tpu.train.supcon_step import METRIC_KEYS
from simclr_pytorch_distributed_tpu.utils import preempt
from simclr_pytorch_distributed_tpu.utils.telemetry import TelemetrySession
from simclr_pytorch_distributed_tpu.utils.guard import (
    MAX_ROLLBACKS,
    ROLLBACK_LR_MULT,
    FailurePolicy,
    NonFiniteLossError,
    check_finite_loss,
)


def test_check_finite_loss():
    check_finite_loss(1.0, 0)
    check_finite_loss(float("nan"), 0, enabled=False)  # disabled: no raise
    for bad in (float("nan"), float("inf"), -float("inf")):
        with pytest.raises(NonFiniteLossError, match="non-finite loss"):
            check_finite_loss(bad, 7)


class _FakeLoader:
    def __init__(self, n_steps, batch):
        self.n_steps, self.batch = n_steps, batch

    def check_start_step(self, start_step):
        # the real EpochLoader contract the driver invokes pre-loop
        assert 0 <= start_step < self.n_steps, start_step

    def epoch(self, _, start_step=0):
        images = np.zeros((self.batch, 4, 4, 3), np.uint8)
        labels = np.zeros((self.batch,), np.int32)
        for _ in range(start_step, self.n_steps):
            yield images, labels


def _ring_fake_update(session, metrics):
    """A fake ring-mode update: writes ``metrics`` into the ring at a
    self-tracked step counter (epoch 1 -> step == idx), like the jitted
    update writes at ``state.step % window``."""
    calls = []

    def fake_update(state, ring, images, labels, key):
        calls.append(1)
        return state, session.ring.write(
            ring, metrics, jnp.int32(len(calls) - 1)
        )

    return fake_update, calls


def test_epoch_loop_raises_on_nan(monkeypatch):
    cfg = config_lib.SupConConfig(print_freq=1, batch_size=8, nan_guard=True)
    mesh = create_mesh(devices=jax.devices()[:1])
    metrics = dict.fromkeys(METRIC_KEYS, jnp.float32(0))
    metrics["loss"] = jnp.float32(float("nan"))

    session = TelemetrySession(cfg.print_freq, METRIC_KEYS, cfg.telemetry)
    fake_update, _ = _ring_fake_update(session, metrics)
    try:
        with pytest.raises(NonFiniteLossError):
            train_one_epoch(
                1, _FakeLoader(3, 8), fake_update, state=None, mesh=mesh,
                base_key=jax.random.key(0), cfg=cfg, tb=None, steps_per_epoch=3,
                telemetry=session,
            )
    finally:
        session.close()

    # guard off: the same epoch completes and reports the NaN average
    cfg_off = config_lib.SupConConfig(print_freq=1, batch_size=8, nan_guard=False)
    session_off = TelemetrySession(cfg_off.print_freq, METRIC_KEYS, cfg_off.telemetry)
    fake_update, _ = _ring_fake_update(session_off, metrics)
    try:
        _, loss_avg, _, preempted_at = train_one_epoch(
            1, _FakeLoader(3, 8), fake_update, state=None, mesh=mesh,
            base_key=jax.random.key(0), cfg=cfg_off, tb=None, steps_per_epoch=3,
            telemetry=session_off,
        )
    finally:
        session_off.close()
    assert math.isnan(loss_avg)
    assert preempted_at is None


def _finite_metrics():
    m = dict.fromkeys(METRIC_KEYS, jnp.float32(0))
    m["loss"] = jnp.float32(1.0)
    return m


def test_epoch_loop_observes_preemption_at_flush_boundary():
    """The flag set by the (simulated) signal is observed at the NEXT
    print_freq flush; the loop returns the steps-completed count so the
    driver can stamp step_in_epoch into the emergency save."""
    cfg = config_lib.SupConConfig(print_freq=2, batch_size=8)
    mesh = create_mesh(devices=jax.devices()[:1])
    session = TelemetrySession(cfg.print_freq, METRIC_KEYS, cfg.telemetry)
    fake_update, calls = _ring_fake_update(session, _finite_metrics())

    def preempting_update(state, ring, images, labels, key):
        state, ring = fake_update(state, ring, images, labels, key)
        if len(calls) == 1:
            preempt.request()  # signal lands during step 1's window
        return state, ring

    try:
        state, loss_avg, _, preempted_at = train_one_epoch(
            1, _FakeLoader(8, 8), preempting_update, state=None, mesh=mesh,
            base_key=jax.random.key(0), cfg=cfg, tb=None, steps_per_epoch=8,
            telemetry=session,
        )
    finally:
        preempt.uninstall()
        session.close()
    assert preempted_at == 2  # observed at the first flush (print_freq=2)
    assert len(calls) == 2  # no further steps dispatched
    assert loss_avg == 1.0


def test_epoch_loop_last_step_preemption_falls_through():
    """A signal observed only at the final flush is an ordinary epoch end:
    the epoch-boundary path in run() handles it (no mid-epoch marker)."""
    cfg = config_lib.SupConConfig(print_freq=10, batch_size=8)
    mesh = create_mesh(devices=jax.devices()[:1])
    session = TelemetrySession(cfg.print_freq, METRIC_KEYS, cfg.telemetry)
    fake_update, _ = _ring_fake_update(session, _finite_metrics())

    def preempting_update(state, ring, images, labels, key):
        preempt.request()
        return fake_update(state, ring, images, labels, key)

    try:
        _, _, _, preempted_at = train_one_epoch(
            1, _FakeLoader(3, 8), preempting_update, state=None, mesh=mesh,
            base_key=jax.random.key(0), cfg=cfg, tb=None, steps_per_epoch=3,
            telemetry=session,
        )
        assert preempted_at is None
        assert preempt.requested()  # still pending for run()'s boundary check
    finally:
        preempt.uninstall()
        session.close()


def test_failure_policy_abort_never_rolls_back():
    p = FailurePolicy("abort")
    assert not p.should_rollback()
    assert p.lr_scale == 1.0 and p.rollbacks == 0


def test_failure_policy_rollback_damps_lr_and_caps():
    p = FailurePolicy("rollback")
    grants = [p.should_rollback() for _ in range(MAX_ROLLBACKS + 2)]
    assert grants == [True] * MAX_ROLLBACKS + [False, False]
    assert p.rollbacks == MAX_ROLLBACKS
    np.testing.assert_allclose(p.lr_scale, ROLLBACK_LR_MULT ** MAX_ROLLBACKS)


def test_failure_policy_rejects_unknown():
    with pytest.raises(ValueError, match="nan_policy"):
        FailurePolicy("retry")


def test_preempt_install_uninstall_roundtrip():
    """install() swaps handlers in, uninstall() restores the originals and
    clears the flag — a driver run inside pytest leaves SIGINT alone."""
    import signal

    before = signal.getsignal(signal.SIGTERM)
    preempt.install()
    try:
        assert not preempt.requested()
        preempt.request()
        assert preempt.requested()
        assert preempt.signal_name() == "SIGTERM"
    finally:
        preempt.uninstall()
    assert signal.getsignal(signal.SIGTERM) is before
    assert not preempt.requested()


def test_realign_schedule_count_moves_applied_lr_position():
    """The applied LR reads ScaleByScheduleState.count, not TrainState.step:
    the rollback's epoch skip must move BOTH (sgd and lars chains), and a
    constant-LR chain is a no-op."""
    import optax

    from simclr_pytorch_distributed_tpu.train.state import (
        make_optimizer,
        realign_schedule_count,
    )

    params = {"w": jnp.ones((3, 3))}
    for opt in ("sgd", "lars"):
        tx = make_optimizer(lambda s: 0.1, momentum=0.9, weight_decay=1e-4,
                            optimizer=opt)
        st = realign_schedule_count(tx.init(params), 42)
        counts = [s.count for s in jax.tree.leaves(
            st, is_leaf=lambda s: isinstance(s, optax.ScaleByScheduleState)
        ) if isinstance(s, optax.ScaleByScheduleState)]
        assert len(counts) == 1 and int(counts[0]) == 42, opt
        # everything else untouched
        trace = [s for s in jax.tree.leaves(
            st, is_leaf=lambda s: isinstance(s, optax.TraceState)
        ) if isinstance(s, optax.TraceState)]
        assert trace, opt

    tx_const = make_optimizer(0.1, momentum=0.9, weight_decay=1e-4)
    st = tx_const.init(params)
    assert realign_schedule_count(st, 7) == st  # no schedule state: no-op


def test_exit_code_for_typed_table():
    """The typed exit-code surface (docs/RESILIENCE.md): codes mirror the
    collective failure codes (health 3 > flush 2 > NaN 1), SystemExit
    passes through (preempt 75), clean return is 0, and an arbitrary crash
    degrades to the interpreter's 1."""
    from simclr_pytorch_distributed_tpu.utils.guard import (
        EXIT_FLUSH,
        EXIT_HEALTH,
        EXIT_NONFINITE,
        NonFiniteLossError,
        RepresentationHealthError,
        exit_code_for,
        exit_with_code,
    )
    from simclr_pytorch_distributed_tpu.utils.telemetry import (
        TelemetryFlushError,
    )

    assert exit_code_for(None) == 0
    assert exit_code_for(SystemExit(75)) == 75
    assert exit_code_for(SystemExit()) == 0
    assert exit_code_for(SystemExit("msg")) == 1
    assert exit_code_for(NonFiniteLossError(float("nan"), 3)) == EXIT_NONFINITE == 1
    assert exit_code_for(TelemetryFlushError("io")) == EXIT_FLUSH == 2
    assert exit_code_for(RepresentationHealthError(["f"], 3)) == EXIT_HEALTH == 3
    assert exit_code_for(ValueError("boom")) == 1

    # the drivers' main() epilogue: typed failures become SystemExit with
    # the right code; everything else propagates untouched
    import pytest as _pytest

    with _pytest.raises(SystemExit) as e:
        exit_with_code(lambda: (_ for _ in ()).throw(
            RepresentationHealthError(["collapse"], 1)))
    assert e.value.code == 3
    with _pytest.raises(ValueError):
        exit_with_code(lambda: (_ for _ in ()).throw(ValueError("real bug")))
