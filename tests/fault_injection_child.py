"""Child worker for tests/test_fault_injection.py: one REAL OS process
running the supcon pretrain driver on a tiny synthetic config, so the parent
can deliver actual signals (SIGTERM, SIGKILL) at randomized steps and then
resume — the only honest way to test the preemption machinery end-to-end
(an in-process simulation cannot witness exit codes or kill -9 torn state).

Usage: python fault_injection_child.py <workdir> <epochs> <resume> <trial> \
           [save_freq] [data_placement] [ngpu] [syncbn]

``ngpu``/``syncbn`` exist for the elastic-resume mesh matrix (the parent
also rewrites XLA_FLAGS' host-platform device count per child): pinning
``--ngpu`` to a constant and ``--syncBN`` on removes the two documented
shape-dependent terms (gradient divisor, per-device BN statistics), which
is exactly the configuration under which an N-device -> M-device resume
must reproduce the uninterrupted run (docs/RESILIENCE.md elastic-resume
contract).

Prints, on stdout (parent parses these):
- ``SAVE_FOLDER <path>``  once config is finalized (before training);
- the driver's ``Train: [e][s/S]`` log lines, one per step (print_freq=1);
- ``DONE step=<n>`` only when the run completes uninterrupted.

Exit codes: 0 done; preempt.EXIT_PREEMPTED (75) after a clean
SIGTERM-triggered emergency checkpoint; anything else is a real failure.
"""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax

jax.config.update("jax_platforms", "cpu")
cache_dir = os.environ.get("JAX_COMPILATION_CACHE_DIR")
if cache_dir:
    jax.config.update("jax_compilation_cache_dir", os.path.abspath(cache_dir))
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)

import logging  # noqa: E402

# the parent reads stdout; route the driver's log lines there unbuffered
logging.basicConfig(stream=sys.stdout, level=logging.INFO, force=True)

from simclr_pytorch_distributed_tpu import config as config_lib  # noqa: E402
from simclr_pytorch_distributed_tpu.data import cifar as cifar_lib  # noqa: E402

# 256 examples at size 8 -> 224 train -> 7 steps/epoch at batch 32: enough
# steps that a SIGTERM sent after the first step's log line is always
# observed MID-epoch (the handler runs during step 2's host code), small
# enough that a child run is seconds after the first compile is cached.
_orig_synthetic = cifar_lib.synthetic_dataset
cifar_lib.synthetic_dataset = (
    lambda n=2048, num_classes=10, seed=0, size=32: _orig_synthetic(
        n=256, num_classes=num_classes, seed=seed, size=8
    )
)

workdir = sys.argv[1]
epochs = int(sys.argv[2])
resume = sys.argv[3]
trial = sys.argv[4]
save_freq = int(sys.argv[5]) if len(sys.argv) > 5 else 100
# 'auto' resolves to DEVICE placement here (tiny in-RAM synthetic set on
# CPU); the parent pins 'host' to prove the preemption/resume contract on
# the per-step H2D loop too — it is placement-independent (RESILIENCE.md)
data_placement = sys.argv[6] if len(sys.argv) > 6 else "auto"
ngpu = sys.argv[7] if len(sys.argv) > 7 else "2"
sync_bn = (sys.argv[8] == "1") if len(sys.argv) > 8 else False

from simclr_pytorch_distributed_tpu.train import supcon as supcon_driver  # noqa: E402

cfg = config_lib.SupConConfig(
    model="resnet10", dataset="synthetic", batch_size=32, epochs=epochs,
    learning_rate=0.05, temp=0.5, cosine=True, save_freq=save_freq,
    print_freq=1, size=8, workdir=workdir, seed=0, method="SimCLR",
    trial=trial, resume=resume, data_placement=data_placement,
    ngpu=config_lib.ngpu_arg(ngpu), syncBN=sync_bn,
)
cfg = config_lib.finalize_supcon(cfg)
print(f"SAVE_FOLDER {cfg.save_folder}", flush=True)

state = supcon_driver.run(cfg)
print(f"DONE step={int(state.step)}", flush=True)
