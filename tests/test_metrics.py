import jax.numpy as jnp
import numpy as np

from simclr_pytorch_distributed_tpu.ops.metrics import AverageMeter, topk_accuracy


def test_topk_accuracy_known_values():
    logits = jnp.asarray(
        [
            [0.1, 0.9, 0.0],  # pred 1
            [0.8, 0.05, 0.15],  # pred 0, second-best 2
            [0.2, 0.3, 0.5],  # pred 2, second-best 1
        ]
    )
    target = jnp.asarray([1, 1, 1])
    acc1, acc2 = topk_accuracy(logits, target, topk=(1, 2))
    np.testing.assert_allclose(float(acc1), 100.0 / 3, rtol=1e-5)
    np.testing.assert_allclose(float(acc2), 200.0 / 3, rtol=1e-5)


def test_average_meter():
    m = AverageMeter()
    m.update(1.0, n=2)
    m.update(4.0, n=1)
    assert m.val == 4.0
    assert m.count == 3
    np.testing.assert_allclose(m.avg, 2.0)
