"""Augmentation tests: geometry, color-op numerics vs direct formulas,
probabilities over many keys, and batch plumbing."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from simclr_pytorch_distributed_tpu.ops.augment import (
    AugmentConfig,
    adjust_brightness,
    adjust_contrast,
    adjust_hue,
    adjust_saturation,
    augment_batch,
    color_jitter,
    crop_and_resize,
    eval_batch,
    normalize,
    random_grayscale,
    random_horizontal_flip,
    random_resized_crop,
    simclr_transform,
    two_crop_batch,
)

CFG = AugmentConfig()


def rand_img(rng, h=32, w=32):
    return rng.uniform(0, 1, size=(h, w, 3)).astype(np.float32)


def test_crop_and_resize_identity(rng):
    img = jnp.asarray(rand_img(rng))
    out = crop_and_resize(img, jnp.float32(0), jnp.float32(0), jnp.float32(32), jnp.float32(32), 32)
    np.testing.assert_allclose(np.asarray(out), np.asarray(img), atol=1e-6)


def test_crop_and_resize_upscale_constant(rng):
    img = jnp.ones((32, 32, 3)) * 0.5
    out = crop_and_resize(img, jnp.float32(4), jnp.float32(7), jnp.float32(10), jnp.float32(12), 32)
    np.testing.assert_allclose(np.asarray(out), 0.5, atol=1e-6)


def test_crop_and_resize_2x_upscale_exact():
    """2x upsample of a 2x2 checker with half-pixel centers: corners keep values."""
    img = jnp.asarray([[0.0, 1.0], [1.0, 0.0]]).reshape(2, 2, 1)
    out = crop_and_resize(img, jnp.float32(0), jnp.float32(0), jnp.float32(2), jnp.float32(2), 4)
    out = np.asarray(out)[..., 0]
    np.testing.assert_allclose(out[0, 0], 0.0, atol=1e-6)
    np.testing.assert_allclose(out[0, 3], 1.0, atol=1e-6)
    # dst (1,1) -> src (0.25, 0.25): 0.75^2*0 + 2*0.25*0.75*1 + 0.25^2*0
    np.testing.assert_allclose(out[1, 1], 0.375, atol=1e-6)


def test_rrc_shapes_and_range(rng):
    img = jnp.asarray(rand_img(rng))
    out = random_resized_crop(jax.random.key(0), img, 32)
    assert out.shape == (32, 32, 3)
    assert float(out.min()) >= -1e-6 and float(out.max()) <= 1 + 1e-6


def test_rrc_scale_statistics(rng):
    """Sampled crop areas should span the (0.2, 1.0) scale range: a constant
    gradient image's crop mean varies; check variability across keys."""
    img = jnp.asarray(np.linspace(0, 1, 32 * 32 * 3).reshape(32, 32, 3).astype(np.float32))
    outs = jax.vmap(lambda k: random_resized_crop(k, img, 32))(
        jax.random.split(jax.random.key(0), 64)
    )
    means = np.asarray(outs.mean(axis=(1, 2, 3)))
    assert means.std() > 0.02  # crops differ
    # every output is a valid resample of the source range
    assert outs.min() >= 0 and outs.max() <= 1 + 1e-6


def test_hflip_probability():
    img = jnp.asarray(np.arange(32 * 32 * 3, dtype=np.float32).reshape(32, 32, 3))
    keys = jax.random.split(jax.random.key(0), 400)
    flipped = jax.vmap(lambda k: random_horizontal_flip(k, img)[0, 0, 0])(keys)
    frac = float(jnp.mean(flipped != img[0, 0, 0]))
    assert 0.4 < frac < 0.6


def test_brightness_contrast_saturation_formulas(rng):
    img = jnp.asarray(rand_img(rng))
    np.testing.assert_allclose(
        np.asarray(adjust_brightness(img, 0.5)), np.clip(np.asarray(img) * 0.5, 0, 1), atol=1e-6
    )
    x = np.asarray(img)
    gray = (x * [0.299, 0.587, 0.114]).sum(-1, keepdims=True)
    np.testing.assert_allclose(
        np.asarray(adjust_saturation(img, 1.3)),
        np.clip(1.3 * x + (1 - 1.3) * gray, 0, 1), atol=1e-5,
    )
    np.testing.assert_allclose(
        np.asarray(adjust_contrast(img, 0.7)),
        np.clip(0.7 * x + 0.3 * gray.mean(), 0, 1), atol=1e-5,
    )


def test_hue_roundtrip(rng):
    img = jnp.asarray(rand_img(rng))
    out = adjust_hue(img, jnp.float32(0.0))
    np.testing.assert_allclose(np.asarray(out), np.asarray(img), atol=1e-5)
    # full rotation returns to start
    out = adjust_hue(adjust_hue(img, jnp.float32(0.5)), jnp.float32(0.5))
    np.testing.assert_allclose(np.asarray(out), np.asarray(img), atol=1e-4)


def test_hue_shift_changes_channels(rng):
    img = jnp.asarray(rand_img(rng))
    out = adjust_hue(img, jnp.float32(0.1))
    assert not np.allclose(np.asarray(out), np.asarray(img), atol=1e-3)
    # value (max channel) is preserved by pure hue shifts
    np.testing.assert_allclose(
        np.asarray(out.max(axis=-1)), np.asarray(img.max(axis=-1)), atol=1e-5
    )


def test_grayscale_probability_and_channels(rng):
    img = jnp.asarray(rand_img(rng))
    keys = jax.random.split(jax.random.key(1), 400)
    outs = jax.vmap(lambda k: random_grayscale(k, img))(keys)
    outs = np.asarray(outs)
    is_gray = np.all(np.abs(outs[..., 0] - outs[..., 1]) < 1e-6, axis=(1, 2))
    assert 0.12 < is_gray.mean() < 0.30  # p=0.2


def test_color_jitter_order_matters_and_is_applied(rng):
    img = jnp.asarray(rand_img(rng))
    out1 = color_jitter(jax.random.key(0), img)
    out2 = color_jitter(jax.random.key(1), img)
    assert not np.allclose(np.asarray(out1), np.asarray(out2), atol=1e-4)


def test_normalize():
    img = jnp.ones((4, 4, 3)) * 0.5
    out = normalize(img, CFG.mean, CFG.std)
    want = (0.5 - np.array(CFG.mean)) / np.array(CFG.std)
    np.testing.assert_allclose(np.asarray(out[0, 0]), want, rtol=1e-5)


def test_two_crop_batch_shapes_and_independence(rng):
    imgs = (rand_img(rng, 32, 32) * 255).astype(np.uint8)[None].repeat(4, axis=0)
    out = two_crop_batch(jax.random.key(0), jnp.asarray(imgs), CFG)
    assert out.shape == (4, 2, 32, 32, 3)
    out = np.asarray(out)
    # the two views of the same image must differ (independent transform draws)
    assert not np.allclose(out[:, 0], out[:, 1], atol=1e-3)
    # different batch elements get different randomness even for identical input
    assert not np.allclose(out[0, 0], out[1, 0], atol=1e-3)


def test_eval_batch_deterministic(rng):
    imgs = (rand_img(rng) * 255).astype(np.uint8)[None]
    a = eval_batch(jnp.asarray(imgs), CFG)
    b = eval_batch(jnp.asarray(imgs), CFG)
    np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


def test_simclr_transform_jits(rng):
    img = jnp.asarray((rand_img(rng) * 255).astype(np.uint8))
    f = jax.jit(lambda k, im: simclr_transform(k, im, CFG))
    out = f(jax.random.key(0), img)
    assert out.shape == (32, 32, 3)
    assert np.isfinite(np.asarray(out)).all()
    # deterministic per key
    np.testing.assert_array_equal(
        np.asarray(f(jax.random.key(5), img)), np.asarray(f(jax.random.key(5), img))
    )


def test_augment_batch_no_color_ops(rng):
    """Linear/CE stage: RRC+flip+normalize only — gray pixels stay gray."""
    cfg = AugmentConfig(color_ops=False)
    gray_val = 128
    imgs = np.full((2, 32, 32, 3), gray_val, np.uint8)
    out = np.asarray(augment_batch(jax.random.key(0), jnp.asarray(imgs), cfg))
    want = (gray_val / 255.0 - np.array(cfg.mean)) / np.array(cfg.std)
    np.testing.assert_allclose(out, np.broadcast_to(want, out.shape), atol=1e-4)


def test_crop_resize_matches_pil_bilinear(rng):
    """Golden fidelity vs the reference's actual host path: torchvision's
    RandomResizedCrop = PIL crop().resize(BILINEAR). PIL computes in fixed
    point, so agreement is ~1-2/255. Covers interior crops (border samples
    must replicate the CROP edge, not bleed into the surrounding image)."""
    from PIL import Image

    img = rng.integers(0, 256, size=(8, 8, 3), dtype=np.uint8)
    cases = [  # (PIL box (l,u,r,low), (top,left,h,w), out)
        ((0, 0, 8, 8), (0.0, 0.0, 8.0, 8.0), 16),
        ((1, 2, 6, 7), (2.0, 1.0, 5.0, 5.0), 32),
        ((3, 1, 7, 8), (1.0, 3.0, 7.0, 4.0), 20),
    ]
    for box, (top, left, h, w), out in cases:
        pil = np.asarray(
            Image.fromarray(img).crop(box).resize((out, out), Image.BILINEAR),
            np.float32,
        ) / 255.0
        ours = np.asarray(
            crop_and_resize(jnp.asarray(img, jnp.float32) / 255.0,
                            top, left, h, w, out)
        )
        np.testing.assert_allclose(ours, pil, atol=2.0 / 255.0)


def test_color_ops_match_pil(rng):
    """Fixed-factor goldens vs PIL ImageEnhance / HSV — the code paths
    torchvision's ColorJitter actually executes on the reference's host.
    Brightness/contrast/saturation agree within uint8 quantization; hue is
    looser because PIL shifts a hue channel quantized to 256 levels while the
    device op is continuous."""
    from PIL import Image, ImageEnhance

    img = rng.integers(0, 256, size=(16, 16, 3), dtype=np.uint8)
    pim = Image.fromarray(img)
    x = jnp.asarray(img, jnp.float32) / 255.0
    f = 1.3

    for name, pil_out, ours in [
        ("brightness", ImageEnhance.Brightness(pim).enhance(f),
         adjust_brightness(x, f)),
        ("contrast", ImageEnhance.Contrast(pim).enhance(f),
         adjust_contrast(x, f)),
        ("saturation", ImageEnhance.Color(pim).enhance(f),
         adjust_saturation(x, f)),
    ]:
        ref = np.asarray(pil_out, np.float32) / 255.0
        np.testing.assert_allclose(
            np.asarray(ours), ref, atol=1.5 / 255.0, err_msg=name
        )

    delta = 0.05
    h, s, v = pim.convert("HSV").split()
    h = h.point(lambda p: (p + int(delta * 255)) % 256)
    hue_ref = np.asarray(
        Image.merge("HSV", (h, s, v)).convert("RGB"), np.float32
    ) / 255.0
    np.testing.assert_allclose(
        np.asarray(adjust_hue(x, delta)), hue_ref, atol=10.0 / 255.0,
        err_msg="hue",
    )
