"""Fused Pallas contrastive loss vs the dense oracle (interpret mode on CPU).

The dense oracle ``ops.losses.supcon_loss`` is itself golden-tested against the
reference math in ``test_losses.py``; here the flash-style kernel must match it
(value and gradient) across methods, shapes that exercise multi-block grids,
and temperatures.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from simclr_pytorch_distributed_tpu.compat import shard_map
from jax.sharding import Mesh, PartitionSpec as P

from simclr_pytorch_distributed_tpu.ops.losses import supcon_loss
from simclr_pytorch_distributed_tpu.ops.pallas_loss import (
    fused_sharded_supcon_loss,
    fused_supcon_loss,
    supports,
    supports_sharded,
)


def _features(rng, batch, n_views=2, dim=24):
    f = rng.standard_normal((batch, n_views, dim)).astype(np.float32)
    f /= np.linalg.norm(f, axis=-1, keepdims=True)
    return jnp.asarray(f)


@pytest.mark.parametrize("batch,dim", [(16, 24), (32, 128)])
@pytest.mark.parametrize("use_labels", [False, True])
@pytest.mark.parametrize("temp", [0.07, 0.5])
def test_fused_matches_dense(rng, batch, dim, use_labels, temp):
    f = _features(rng, batch, dim=dim)
    labels = (
        jnp.asarray(rng.integers(0, 5, batch).astype(np.int32))
        if use_labels
        else None
    )
    dense = supcon_loss(f, labels=labels, temperature=temp)
    fused = fused_supcon_loss(f, labels=labels, temperature=temp, interpret=True)
    np.testing.assert_allclose(np.asarray(fused), np.asarray(dense), rtol=2e-6)


@pytest.mark.parametrize("use_labels", [False, True])
def test_fused_gradient_matches_dense(rng, use_labels):
    batch = 16
    f = _features(rng, batch)
    labels = (
        jnp.asarray(rng.integers(0, 4, batch).astype(np.int32))
        if use_labels
        else None
    )
    gd = jax.grad(lambda x: supcon_loss(x, labels=labels, temperature=0.5))(f)
    gf = jax.grad(
        lambda x: fused_supcon_loss(
            x, labels=labels, temperature=0.5, interpret=True
        )
    )(f)
    np.testing.assert_allclose(np.asarray(gf), np.asarray(gd), atol=1e-6)


def test_multi_block_grid(rng):
    # V*B = 96 with small caps => 12x6 grid: online-LSE streaming across many
    # column blocks and several row programs.
    f = _features(rng, 48, dim=16)
    dense = supcon_loss(f, temperature=0.3)
    fused = fused_supcon_loss(
        f, temperature=0.3, interpret=True, block_rows=8, block_cols=16
    )
    np.testing.assert_allclose(np.asarray(fused), np.asarray(dense), rtol=2e-6)


def test_recipe_scale_ratio_preserved(rng):
    # the tau/tau_base=0.07 multiplier (reference losses.py:90) must carry over
    f = _features(rng, 8)
    a = fused_supcon_loss(f, temperature=0.5, interpret=True)
    b = fused_supcon_loss(
        f, temperature=0.5, base_temperature=0.5, interpret=True
    )
    np.testing.assert_allclose(
        np.asarray(a) / np.asarray(b), 0.5 / 0.07, rtol=1e-5
    )


def test_supports():
    assert supports(256, 2)  # the recipe: V*B = 512
    assert supports(4, 2)
    assert not supports(3, 1)  # N=3 not divisible by 8


# ---------------------------------------------------------------------------
# Sharded mode: the kernel inside shard_map over an 8-device mesh.
# ---------------------------------------------------------------------------


def _data_mesh():
    return Mesh(np.array(jax.devices()[:8]), ("data",))


def _sharded_fn(mesh, labels, temp):
    """shard_map-wrapped sharded fused loss over view-major global rows."""
    if labels is None:
        return shard_map(
            lambda r: fused_sharded_supcon_loss(
                r, None, axis_name="data", temperature=temp, interpret=True
            ),
            mesh=mesh, in_specs=P("data"), out_specs=P(), check_vma=False,
        )
    fn = shard_map(
        lambda r, l: fused_sharded_supcon_loss(
            r, l, axis_name="data", temperature=temp, interpret=True
        ),
        mesh=mesh, in_specs=(P("data"), P()), out_specs=P(), check_vma=False,
    )
    return lambda r: fn(r, labels)


@pytest.mark.parametrize("use_labels", [False, True])
@pytest.mark.parametrize("temp", [0.07, 0.5])
def test_sharded_fused_matches_dense(rng, use_labels, temp):
    """The shard_map-sharded kernel == dense on the 8-device mesh (value)."""
    batch = 32  # 64 view-major rows -> 8 anchor rows per device
    f = _features(rng, batch)
    labels = (
        jnp.asarray(rng.integers(0, 5, batch).astype(np.int32))
        if use_labels
        else None
    )
    rows = jnp.transpose(f, (1, 0, 2)).reshape(2 * batch, -1)
    dense = supcon_loss(f, labels=labels, temperature=temp)
    sharded = _sharded_fn(_data_mesh(), labels, temp)(rows)
    np.testing.assert_allclose(np.asarray(sharded), np.asarray(dense), rtol=2e-6)


@pytest.mark.parametrize("use_labels", [False, True])
def test_sharded_fused_gradient_matches_dense(rng, use_labels):
    """Each device's custom-VJP backward computes the exact global gradient of
    its own anchor rows (incl. the Gᵀ cross-device term via gathered lse/cnt)."""
    batch = 32
    f = _features(rng, batch)
    labels = (
        jnp.asarray(rng.integers(0, 4, batch).astype(np.int32))
        if use_labels
        else None
    )
    rows = jnp.transpose(f, (1, 0, 2)).reshape(2 * batch, -1)

    def dense_of_rows(r):
        return supcon_loss(
            jnp.stack([r[:batch], r[batch:]], axis=1),
            labels=labels, temperature=0.5,
        )

    gd = jax.grad(dense_of_rows)(rows)
    gs = jax.grad(_sharded_fn(_data_mesh(), labels, 0.5))(rows)
    np.testing.assert_allclose(np.asarray(gs), np.asarray(gd), atol=1e-6)


def test_supports_sharded():
    assert supports_sharded(256, 2, 8)  # the recipe on a v5e-8: m=64
    assert supports_sharded(4096, 2, 8)  # ImageNet-scale: m=1024
    assert not supports_sharded(16, 2, 8)  # m=4 < one 8-row tile
    assert not supports_sharded(20, 2, 8)  # 40 rows not divisible by 8
    assert not supports_sharded(256, 2, 0)


def test_unsupported_size_raises(rng):
    f = _features(rng, 3, n_views=1)
    with pytest.raises(ValueError):
        fused_supcon_loss(f, interpret=True)


@pytest.mark.slow
def test_fused_train_step_single_device(rng):
    """make_train_step with loss_impl='fused' runs and matches the dense step."""
    import optax

    from simclr_pytorch_distributed_tpu.models import SupConResNet
    from simclr_pytorch_distributed_tpu.train.state import create_train_state
    from simclr_pytorch_distributed_tpu.train.supcon_step import (
        SupConStepConfig,
        make_train_step,
    )

    model = SupConResNet(model_name="resnet18", head="mlp", feat_dim=128)
    tx = optax.sgd(0.1, momentum=0.9)
    state = create_train_state(
        model, tx, jax.random.key(0), jnp.zeros((2, 16, 16, 3))
    )
    images = jnp.asarray(
        rng.standard_normal((8, 2, 16, 16, 3)).astype(np.float32)
    )
    labels = jnp.asarray(rng.integers(0, 4, 8).astype(np.int32))

    outs = {}
    for impl in ("dense", "fused"):
        cfg = SupConStepConfig(
            method="SimCLR", temperature=0.5, epochs=2, steps_per_epoch=1,
            grad_div=2.0, loss_impl=impl,
        )
        step = make_train_step(model, tx, lambda s: 0.1, cfg)
        new_state, metrics = step(state, images, labels)
        outs[impl] = (new_state, metrics)

    np.testing.assert_allclose(
        float(outs["fused"][1]["loss"]), float(outs["dense"][1]["loss"]),
        rtol=1e-5,
    )
    d_leaves = jax.tree.leaves(outs["dense"][0].params)
    f_leaves = jax.tree.leaves(outs["fused"][0].params)
    for a, b in zip(d_leaves, f_leaves):
        np.testing.assert_allclose(np.asarray(b), np.asarray(a), atol=2e-5)
