"""Distributed-semantics tests on the virtual 8-device CPU mesh.

These validate the TPU-native replacements for the reference's NCCL machinery
(SURVEY.md §4.3): the sharded global-batch loss vs the reference's explicit
all_gather, and the DDP gradient-mean equivalence that the grad_div loss scale
reproduces.
"""

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

pytestmark = pytest.mark.slow  # compile-heavy: sharded-step programs on the 1-core CPU host

from simclr_pytorch_distributed_tpu.models import SupConResNet
from simclr_pytorch_distributed_tpu.ops.losses import supcon_loss
from simclr_pytorch_distributed_tpu.ops.schedules import make_lr_schedule
from simclr_pytorch_distributed_tpu.parallel.mesh import create_mesh, shard_host_batch
from simclr_pytorch_distributed_tpu.train.state import create_train_state, make_optimizer
from simclr_pytorch_distributed_tpu.train.supcon_step import (
    SupConStepConfig,
    make_sharded_train_step,
    make_train_step,
)


def tiny_setup(method="SimCLR", batch=16, image=8, model_name="resnet18"):
    model = SupConResNet(model_name=model_name)
    schedule = make_lr_schedule(
        learning_rate=0.05, epochs=10, steps_per_epoch=4, cosine=True
    )
    tx = make_optimizer(schedule, momentum=0.9, weight_decay=1e-4)
    rng = jax.random.key(0)
    example = jnp.zeros((2, image, image, 3))
    state = create_train_state(model, tx, rng, example)
    cfg = SupConStepConfig(
        method=method, temperature=0.5, epochs=10, steps_per_epoch=4, grad_div=2.0
    )
    images = jax.random.normal(jax.random.key(1), (batch, 2, image, image, 3))
    labels = jax.random.randint(jax.random.key(2), (batch,), 0, 4)
    return model, tx, schedule, cfg, state, images, labels


def test_sharded_step_equals_unsharded():
    """The GSPMD step over 8 devices == the same step on one logical array.

    This is the mesh-native statement of 'all-gathered loss == single-device
    loss on the concatenated batch' (SURVEY.md §4 item 3a)."""
    model, tx, schedule, cfg, state, images, labels = tiny_setup()
    plain_step = make_train_step(model, tx, schedule, cfg)
    ref_state, ref_metrics = jax.jit(plain_step)(state, images, labels)

    mesh = create_mesh()
    assert mesh.shape["data"] == 8
    sharded_step = make_sharded_train_step(
        model, tx, schedule, cfg, mesh, state_shape=state, donate=False
    )
    sh_images, sh_labels = shard_host_batch((images, labels), mesh)
    new_state, metrics = sharded_step(state, sh_images, sh_labels)

    np.testing.assert_allclose(
        float(metrics["loss"]), float(ref_metrics["loss"]), rtol=2e-5
    )
    np.testing.assert_allclose(
        float(metrics["norm_mean"]), float(ref_metrics["norm_mean"]), rtol=2e-5
    )
    # parameter updates agree (collectives did not change the math)
    ref_leaves = jax.tree.leaves(ref_state.params)
    new_leaves = jax.tree.leaves(new_state.params)
    for a, b in zip(ref_leaves, new_leaves):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), rtol=1e-3, atol=2e-5)


@pytest.mark.parametrize("method", ["SimCLR", "SupCon"])
def test_supcon_works_distributed(method):
    """SupCon must run sharded (the reference crashes: local labels vs gathered
    features, main_supcon.py:287-288)."""
    model, tx, schedule, cfg, state, images, labels = tiny_setup(method=method)
    mesh = create_mesh()
    step = make_sharded_train_step(
        model, tx, schedule, cfg, mesh, state_shape=state, donate=False
    )
    sh_images, sh_labels = shard_host_batch((images, labels), mesh)
    _, metrics = step(state, sh_images, sh_labels)
    assert np.isfinite(float(metrics["loss"]))


def test_ddp_grad_mean_equivalence():
    """grad(loss / ngpu) == mean over ranks of per-rank-only-local-grads.

    Simulates the reference's gradient path: each rank backwards through its OWN
    feature rows only (all_gather re-insertion, main_supcon.py:268-279), then DDP
    means gradients. Our single-program grad of loss/ngpu must match exactly."""
    ngpu, B_local, D, feat = 2, 4, 12, 8
    B = ngpu * B_local
    W = jax.random.normal(jax.random.key(0), (D, feat)) * 0.3
    x = jax.random.normal(jax.random.key(1), (2 * B, D))  # [v1 all; v2 all]

    def features(W):
        return x @ W

    def loss_from_feats(feats):
        n = feats / jnp.linalg.norm(feats, axis=1, keepdims=True)
        nf = jnp.stack([n[:B], n[B:]], axis=1)
        return supcon_loss(nf, temperature=0.5)

    # ours: exact grad of loss / ngpu
    ours = jax.grad(lambda W: loss_from_feats(features(W)) / ngpu)(W)

    # reference: per-rank grads flow only through local rows, then mean
    def rank_loss(W, r):
        feats = features(W)
        row = jnp.arange(2 * B) % B  # sample index of each view-major row
        own = (row >= r * B_local) & (row < (r + 1) * B_local)
        feats = jnp.where(own[:, None], feats, jax.lax.stop_gradient(feats))
        return loss_from_feats(feats)

    grads = [jax.grad(rank_loss)(W, r) for r in range(ngpu)]
    ddp = sum(grads) / ngpu
    np.testing.assert_allclose(np.asarray(ours), np.asarray(ddp), rtol=1e-5, atol=1e-7)


def test_two_view_forward_layout():
    """View-major flattening matches the reference's gathered ordering
    [all-v1; all-v2] (main_supcon.py:279)."""
    from simclr_pytorch_distributed_tpu.train.supcon_step import two_view_forward

    class Identity:
        def apply(self, variables, x, train=False, mutable=None):
            out = x.reshape(x.shape[0], -1)
            return (out, {"batch_stats": {}}) if mutable else out

    images = jnp.arange(2 * 3 * 2 * 2 * 1, dtype=jnp.float32).reshape(3, 2, 2, 2, 1)
    feats, _ = two_view_forward(Identity(), {}, {}, images, train=True)
    np.testing.assert_array_equal(
        np.asarray(feats[:3]), np.asarray(images[:, 0].reshape(3, -1))
    )
    np.testing.assert_array_equal(
        np.asarray(feats[3:]), np.asarray(images[:, 1].reshape(3, -1))
    )


def test_sgd_chain_matches_torch():
    """optax chain == torch SGD(momentum, weight_decay) including decay of BN-like
    params (util.py:79-84 uses ALL params)."""
    import torch

    lr, mu, wd = 0.1, 0.9, 1e-2
    w0 = np.random.default_rng(0).normal(size=(5, 3)).astype(np.float32)

    wt = torch.nn.Parameter(torch.from_numpy(w0.copy()))
    opt = torch.optim.SGD([wt], lr=lr, momentum=mu, weight_decay=wd)
    for i in range(3):
        opt.zero_grad()
        loss = ((wt * (i + 1)) ** 2).sum()
        loss.backward()
        opt.step()

    tx = make_optimizer(lr, momentum=mu, weight_decay=wd)
    wj = jnp.asarray(w0)
    opt_state = tx.init(wj)
    for i in range(3):
        g = jax.grad(lambda w: ((w * (i + 1)) ** 2).sum())(wj)
        updates, opt_state = tx.update(g, opt_state, wj)
        wj = optax.apply_updates(wj, updates)
    np.testing.assert_allclose(np.asarray(wj), wt.detach().numpy(), rtol=1e-5, atol=1e-6)


def test_loss_decreases_over_steps():
    """Integration smoke: tiny encoder, 4 jitted steps, contrastive loss drops."""
    model, tx, schedule, cfg, state, images, labels = tiny_setup(batch=8, image=8)
    step = jax.jit(make_train_step(model, tx, schedule, cfg))
    losses = []
    for i in range(4):
        state, metrics = step(state, images, labels)
        losses.append(float(metrics["loss"]))
    assert losses[-1] < losses[0], losses


@pytest.mark.parametrize("method", ["SimCLR", "SupCon"])
def test_ring_loss_impl_step_matches_dense(method):
    """loss_impl='ring' in the sharded step == the dense sharded step: the
    ppermute-streamed loss is a drop-in for the all-gather + full-matrix path."""
    model, tx, schedule, cfg, state, images, labels = tiny_setup(method=method)
    mesh = create_mesh()
    sh_images, sh_labels = shard_host_batch((images, labels), mesh)

    dense_step = make_sharded_train_step(
        model, tx, schedule, cfg, mesh, state_shape=state, donate=False
    )
    d_state, d_metrics = dense_step(state, sh_images, sh_labels)

    ring_cfg = SupConStepConfig(**{
        **{f.name: getattr(cfg, f.name) for f in dataclasses.fields(cfg)},
        "loss_impl": "ring",
    })
    ring_step = make_sharded_train_step(
        model, tx, schedule, ring_cfg, mesh, state_shape=state, donate=False
    )
    r_state, r_metrics = ring_step(state, sh_images, sh_labels)

    np.testing.assert_allclose(
        float(r_metrics["loss"]), float(d_metrics["loss"]), rtol=2e-5
    )
    # ring streams the log-sum-exp in a different accumulation order; the
    # ~1e-6 loss-gradient noise amplifies through the deep net's Jacobian, so
    # updated params agree only to ~1e-3 absolute in fp32 (tight gradient
    # equivalence is test_ring_loss.py::test_ring_gradients_match_dense; this
    # guards the step wiring, where a mask/scale bug would diverge at O(1)).
    for a, b in zip(jax.tree.leaves(d_state.params), jax.tree.leaves(r_state.params)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=5e-3)


@pytest.mark.parametrize("method", ["SimCLR", "SupCon"])
def test_fused_sharded_loss_impl_step_matches_dense(method):
    """loss_impl='fused' on a multi-device mesh routes through the shard_map-
    sharded Pallas kernel and matches the dense sharded step — the round-3 gap
    where 'fused' hard-errored (and 'auto' silently downgraded) on the mesh."""
    model, tx, schedule, cfg, state, images, labels = tiny_setup(
        method=method, batch=32
    )
    mesh = create_mesh()
    sh_images, sh_labels = shard_host_batch((images, labels), mesh)

    dense_step = make_sharded_train_step(
        model, tx, schedule, cfg, mesh, state_shape=state, donate=False
    )
    d_state, d_metrics = dense_step(state, sh_images, sh_labels)

    fused_cfg = dataclasses.replace(cfg, loss_impl="fused")
    fused_step = make_sharded_train_step(
        model, tx, schedule, fused_cfg, mesh, state_shape=state, donate=False
    )
    f_state, f_metrics = fused_step(state, sh_images, sh_labels)

    np.testing.assert_allclose(
        float(f_metrics["loss"]), float(d_metrics["loss"]), rtol=2e-5
    )
    # same tolerance rationale as the ring test above: the online-LSE
    # accumulation order differs from dense by ~1e-6 per gradient entry.
    for a, b in zip(jax.tree.leaves(d_state.params), jax.tree.leaves(f_state.params)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=5e-3)


def test_ring_requires_mesh():
    model, tx, schedule, cfg, state, images, labels = tiny_setup()
    ring_cfg = SupConStepConfig(**{
        **{f.name: getattr(cfg, f.name) for f in dataclasses.fields(cfg)},
        "loss_impl": "ring",
    })
    with pytest.raises(ValueError, match="needs the mesh"):
        make_train_step(model, tx, schedule, ring_cfg)


def test_tensor_parallel_step_matches_replicated():
    """model_parallel=4 (mesh data=2 x model=4) shards trailing channel axes
    over 'model'; GSPMD's tensor-parallel layout must not change the math."""
    from simclr_pytorch_distributed_tpu.parallel.mesh import state_sharding, tp_leaf_spec
    from jax.sharding import PartitionSpec as P

    assert tp_leaf_spec((3, 3, 64, 128), 4) == P(None, None, None, "model")
    assert tp_leaf_spec((130,), 4) == P()     # not divisible
    assert tp_leaf_spec((2048, 8), 4) == P()  # too small to split
    assert tp_leaf_spec((64,), 1) == P()      # no model axis

    model, tx, schedule, cfg, state, images, labels = tiny_setup()
    plain_step = make_train_step(model, tx, schedule, cfg)
    ref_state, ref_metrics = jax.jit(plain_step)(state, images, labels)

    mesh = create_mesh(model_parallel=4)
    assert mesh.shape == {"data": 2, "model": 4}
    sharded = jax.tree.leaves(
        jax.tree.map(lambda s: s.spec, state_sharding(mesh, state.params))
    )
    assert any(spec != P() for spec in sharded), "no param was TP-sharded"

    step = make_sharded_train_step(
        model, tx, schedule, cfg, mesh, state_shape=state, donate=False
    )
    sh_images, sh_labels = shard_host_batch((images, labels), mesh)
    new_state, metrics = step(state, sh_images, sh_labels)

    np.testing.assert_allclose(
        float(metrics["loss"]), float(ref_metrics["loss"]), rtol=2e-5
    )
    for a, b in zip(jax.tree.leaves(ref_state.params), jax.tree.leaves(new_state.params)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), rtol=1e-3, atol=2e-5)


def test_tp_with_fused_sharded_loss():
    """loss_impl='fused' on a (data=4, model=2) TENSOR-PARALLEL mesh — the
    composition resolve_loss_impl('auto') selects whenever model_parallel>1
    leaves a multi-device data axis. The kernel's shard_map runs over the
    full mesh with rows sharded only over 'data'; its check_vma=False custom
    VJP psums the cotangent over 'data' alone, so this pins that the
    gradient scale stays exact when a 'model' axis is present too."""
    model, tx, schedule, cfg, state, images, labels = tiny_setup(
        method="SimCLR", batch=32
    )
    mesh = create_mesh(model_parallel=2)
    assert mesh.shape == {"data": 4, "model": 2}
    sh_images, sh_labels = shard_host_batch((images, labels), mesh)

    dense_step = make_sharded_train_step(
        model, tx, schedule, cfg, mesh, state_shape=state, donate=False
    )
    d_state, d_metrics = dense_step(state, sh_images, sh_labels)

    fused_cfg = dataclasses.replace(cfg, loss_impl="fused")
    fused_step = make_sharded_train_step(
        model, tx, schedule, fused_cfg, mesh, state_shape=state, donate=False
    )
    f_state, f_metrics = fused_step(state, sh_images, sh_labels)

    np.testing.assert_allclose(
        float(f_metrics["loss"]), float(d_metrics["loss"]), rtol=2e-5
    )
    # a wrong cotangent scale (e.g. psum over 'data' missing a 1/model
    # factor) would shift EVERY parameter by ~2x the update size — far
    # outside this tolerance (same rationale as the pure-data fused test)
    for a, b in zip(
        jax.tree.leaves(d_state.params), jax.tree.leaves(f_state.params)
    ):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=5e-3)


def test_tp_with_ring_loss_at_scale():
    """VERDICT r1 #6: tensor-parallel (model=2) x ring loss together on a
    bigger-than-tiny step — global batch 256 (32 rows/device over data=4),
    resnet10 @ 16x16 — must match the replicated dense single-program step."""
    model, tx, schedule, cfg, state, images, labels = tiny_setup(
        batch=256, image=16, model_name="resnet10"
    )
    plain_step = make_train_step(model, tx, schedule, cfg)
    ref_state, ref_metrics = jax.jit(plain_step)(state, images, labels)

    mesh = create_mesh(model_parallel=2)
    assert mesh.shape == {"data": 4, "model": 2}
    ring_cfg = dataclasses.replace(cfg, loss_impl="ring")
    step = make_sharded_train_step(
        model, tx, schedule, ring_cfg, mesh, state_shape=state, donate=False
    )
    sh_images, sh_labels = shard_host_batch((images, labels), mesh)
    new_state, metrics = step(state, sh_images, sh_labels)

    np.testing.assert_allclose(
        float(metrics["loss"]), float(ref_metrics["loss"]), rtol=2e-5
    )
    for a, b in zip(jax.tree.leaves(ref_state.params), jax.tree.leaves(new_state.params)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=5e-3)


def test_ce_per_device_bn_matches_independent_slices():
    """SupCEResNet with --syncBN off on a mesh == G independent per-slice
    global-BN forwards (the reference's per-GPU BatchNorm2d semantics on the
    CE path, round-3 weak #4: the plumbing previously stopped at sync_bn)."""
    from jax.sharding import NamedSharding, PartitionSpec as P

    from simclr_pytorch_distributed_tpu.models import SupCEResNet

    mesh = create_mesh()
    G = mesh.shape["data"]
    B, size = 16, 8
    images = jax.random.normal(jax.random.key(3), (B, size, size, 3))

    grouped = SupCEResNet(
        model_name="resnet10", num_classes=4,
        sync_bn=False, bn_local_groups=G, bn_group_views=1,
    )
    global_bn = SupCEResNet(model_name="resnet10", num_classes=4, sync_bn=True)
    variables = global_bn.init(
        jax.random.key(4), jnp.zeros((2, size, size, 3)), train=True
    )

    # grouped forward executed SHARDED over the mesh
    sh_images = jax.device_put(images, NamedSharding(mesh, P("data")))
    out_g, mut_g = jax.jit(
        lambda v, x: grouped.apply(v, x, train=True, mutable=["batch_stats"])
    )(variables, sh_images)

    # oracle: the global-BN model applied to each slice independently
    m = B // G
    outs = []
    muts = []
    for g in range(G):
        o, mu = global_bn.apply(
            variables, images[g * m:(g + 1) * m], train=True,
            mutable=["batch_stats"],
        )
        outs.append(o)
        muts.append(mu)
    # layer-exact equivalence is test_norm.py's job; through the deep net the
    # different reduction orders accumulate ~1e-4 fp32 noise in the logits
    np.testing.assert_allclose(
        np.asarray(out_g), np.concatenate([np.asarray(o) for o in outs]),
        rtol=5e-3, atol=5e-4,
    )
    # running stats follow slice 0 (DDP broadcast_buffers semantics)
    for a, b in zip(
        jax.tree.leaves(mut_g["batch_stats"]),
        jax.tree.leaves(muts[0]["batch_stats"]),
    ):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), rtol=1e-3, atol=1e-5)


def test_per_device_bn_step_on_mesh():
    """--syncBN off (the reference default: per-GPU BatchNorm2d) through the
    full GSPMD step: runs on the 8-device mesh, and its loss DIFFERS from the
    synchronized-BN step's — the flag must do something (round-2 weak #2)."""
    model, tx, schedule, cfg, state, images, labels = tiny_setup()
    mesh = create_mesh()
    local_model = SupConResNet(
        model_name="resnet18", sync_bn=False, bn_local_groups=mesh.shape["data"]
    )

    step_sync = make_sharded_train_step(
        model, tx, schedule, cfg, mesh, state_shape=state, donate=False
    )
    step_local = make_sharded_train_step(
        local_model, tx, schedule, cfg, mesh, state_shape=state, donate=False
    )
    sh_images, sh_labels = shard_host_batch((images, labels), mesh)
    _, m_sync = step_sync(state, sh_images, sh_labels)
    _, m_local = step_local(state, sh_images, sh_labels)
    assert np.isfinite(float(m_local["loss"]))
    assert abs(float(m_local["loss"]) - float(m_sync["loss"])) > 1e-4
