"""serve/fleet tests: registry routing, the Events-gated hot-swap drain,
FIFO across a promote, cache identity across byte-identical weights, the
retrieval index vs a numpy oracle, admission quotas, and the HTTP frontend.

Layering mirrors the serve suite: registry/admission/frontend tests run on
per-row FAKE engines (no jax compiles — the hot-swap drain proof gates the
fake's result() on a threading.Event, so the in-flight window is held open
deterministically, not by sleeping); the cache-staleness pin uses two REAL
engines built from the same seed (byte-identical weights — the exact case
only the ``name@version`` identity key can distinguish); NeighborIndex
compiles one tiny matmul per query bucket.
"""

import json
import threading
import time
import urllib.error
import urllib.request

import numpy as np
import pytest

from simclr_pytorch_distributed_tpu.serve.batcher import QueueFull
from simclr_pytorch_distributed_tpu.serve.cache import EmbeddingCache
from simclr_pytorch_distributed_tpu.serve.engine import EmbeddingEngine
from simclr_pytorch_distributed_tpu.serve.fleet import (
    AdmissionController,
    ModelRegistry,
    NeighborIndex,
)
from simclr_pytorch_distributed_tpu.serve.fleet.frontend import (
    create_fleet_server,
    fleet_metrics_fn,
)
from simclr_pytorch_distributed_tpu.serve.server import start_in_thread

pytestmark = [pytest.mark.serve, pytest.mark.servefleet]

H = W = 2


def imgs(*values):
    out = np.zeros((len(values), H, W, 3), np.uint8)
    for i, v in enumerate(values):
        out[i] = v
    return out


class FakeHandle:
    def __init__(self, engine, images):
        self._engine = engine
        self._images = images
        self.n_rows = len(images)

    def done(self):
        gate = self._engine.gate
        return gate is None or gate.is_set()

    def result(self):
        gate = self._engine.gate
        if gate is not None:
            assert gate.wait(30), "test gate never opened"
        return self._engine.rows(self._images)


class FakeEngine:
    """Per-row map with the engine's dispatch surface. ``scale`` makes each
    version's output distinguishable (WHICH engine served a row is the fact
    the drain tests assert); ``gate`` holds every dispatched batch's
    completion until the test releases it."""

    feat_dim = 3

    def __init__(self, scale=1.0, gate=None):
        self.scale = scale
        self.gate = gate
        self.identity = ""

    def set_identity(self, identity):
        self.identity = identity

    def rows(self, images):
        # distinct image values get distinct DIRECTIONS (v, v^2, 1), so
        # cosine retrieval over fake embeddings is tie-free; ``scale``
        # changes magnitude only
        v = np.asarray(images, np.float32).reshape(len(images), -1)[:, :1] + 1.0
        return np.hstack([v, v ** 2, np.ones_like(v)]) * self.scale

    def validate_images(self, images):
        images = np.asarray(images)
        if images.ndim != 4 or images.shape[0] == 0:
            raise ValueError("need a non-empty [N,H,W,3] batch")
        return images

    def bucket_for(self, n):
        return n

    def dispatch(self, images):
        return FakeHandle(self, images)

    def stats(self):
        return {"identity": self.identity, "fake": True}


def make_registry(**kwargs):
    kwargs.setdefault("batcher_kwargs", {"max_wait_ms": 1})
    kwargs.setdefault("index_capacity", 0)
    return ModelRegistry(**kwargs)


def wait_for(predicate, timeout=5.0):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if predicate():
            return True
        time.sleep(0.002)
    return False


# ------------------------------------------------------------ registry core


def test_routing_default_and_explicit():
    reg = make_registry()
    try:
        reg.add_model("prod", FakeEngine(scale=1.0))
        reg.add_model("exp", FakeEngine(scale=10.0))
        assert reg.default_model() == "exp"  # newest added wins the default
        x = imgs(2)
        name, fut = reg.submit(x)
        assert name == "exp"
        np.testing.assert_array_equal(fut.result(5), FakeEngine(10.0).rows(x))
        name, fut = reg.submit(x, model="prod")
        assert name == "prod"
        np.testing.assert_array_equal(fut.result(5), FakeEngine(1.0).rows(x))
    finally:
        reg.close()


def test_duplicate_and_unknown_models():
    reg = make_registry()
    try:
        reg.add_model("m", FakeEngine())
        with pytest.raises(ValueError, match="already hosted"):
            reg.add_model("m", FakeEngine())
        with pytest.raises(KeyError):
            reg.submit(imgs(1), model="nope")
        with pytest.raises(KeyError):
            reg.promote("nope", FakeEngine())
        with pytest.raises(KeyError):
            reg.wait_drained("m", 7, timeout=0)
    finally:
        reg.close()
    with pytest.raises(RuntimeError, match="closed"):
        reg.add_model("late", FakeEngine())


def test_submit_with_no_models_is_keyerror():
    reg = make_registry()
    try:
        with pytest.raises(KeyError, match="no models"):
            reg.submit(imgs(1))
    finally:
        reg.close()


# ----------------------------------------------------------- hot-swap drain


def test_hot_swap_drains_inflight_on_old_engine():
    """THE promote contract: a batch in flight when promote() lands
    completes on the OLD engine (its rows carry the old scale), the old
    version retires only after that completion, and nothing fails. The
    in-flight window is held open by an Event, so the swap provably
    happens DURING the batch, not around it."""
    gate = threading.Event()
    old = FakeEngine(scale=1.0, gate=gate)
    reg = make_registry()
    try:
        mv1 = reg.add_model("m", old)
        assert old.identity == "m@v1"
        x1 = imgs(3, 4)
        _, f1 = reg.submit(x1)
        assert wait_for(lambda: mv1.inflight > 0)  # dispatched, gated

        new = FakeEngine(scale=5.0)
        mv2 = reg.promote("m", new)
        assert (mv1.state, mv2.state) == ("draining", "serving")
        assert new.identity == "m@v2"
        assert not reg.wait_drained("m", 1, timeout=0.05)  # pinned by f1
        assert not f1.done()

        x2 = imgs(7)
        _, f2 = reg.submit(x2)  # routes to v2

        gate.set()
        np.testing.assert_array_equal(f1.result(5), old.rows(x1))  # scale 1
        np.testing.assert_array_equal(f2.result(5), new.rows(x2))  # scale 5
        assert reg.wait_drained("m", 1, timeout=5)
        assert mv1.state == "retired" and mv1.engine is None
        s = reg.stats()["models"]["m"]
        assert s["batcher"]["errors"] == 0 and s["batcher"]["timeouts"] == 0
        assert s["serving"] == 2
        assert [v["state"] for v in s["versions"]] == ["retired", "serving"]
    finally:
        reg.close()


def test_fifo_holds_across_the_swap():
    """Completion order is submit order even when a promote lands between
    two requests: the post-swap request (on the fast new engine) must NOT
    overtake the gated pre-swap one."""
    gate = threading.Event()
    reg = make_registry()
    try:
        mv1 = reg.add_model("m", FakeEngine(scale=1.0, gate=gate))
        order = []
        _, f1 = reg.submit(imgs(1))
        f1.add_done_callback(lambda _f: order.append(1))
        assert wait_for(lambda: mv1.inflight > 0)  # dispatched pre-swap
        reg.promote("m", FakeEngine(scale=2.0))
        _, f2 = reg.submit(imgs(2))
        f2.add_done_callback(lambda _f: order.append(2))
        # the new engine is ungated, but FIFO pins f2 behind f1
        time.sleep(0.05)
        assert not f2.done() and order == []
        gate.set()
        f2.result(5)
        assert wait_for(lambda: len(order) == 2)
        assert order == [1, 2]
    finally:
        reg.close()


def test_queued_requests_retarget_to_the_new_version():
    """Requests still QUEUED (not dispatched) at promote time dispatch on
    the new engine — only dispatched work drains on the old one."""
    reg = ModelRegistry(
        batcher_kwargs={"max_wait_ms": 1, "start": False},
        index_capacity=0,
    )
    try:
        reg.add_model("m", FakeEngine(scale=1.0))
        x = imgs(6)
        _, fut = reg.submit(x)  # queued; no worker threads to dispatch it
        mv2 = reg.promote("m", FakeEngine(scale=3.0))
        b = reg.batcher("m")
        b._dispatch(b._next_batch())
        np.testing.assert_array_equal(
            fut.result(5), FakeEngine(3.0).rows(x)
        )
        assert mv2.inflight == 0  # completed and released
        assert reg.wait_drained("m", 1, timeout=5)  # v1 never pinned
    finally:
        reg.close()


# ------------------------------------------------- cache identity (real jax)


def test_shared_cache_misses_after_swap_to_identical_weights():
    """Satellite (a): the cache key carries ``name@version``. Two engines
    from the SAME seed have byte-identical weights — same weights probe —
    so without the identity component a post-swap request would be a stale
    HIT. Pinned: post-swap requests miss, then re-hit under the new key."""
    shared = EmbeddingCache(capacity=256)
    e1 = EmbeddingEngine.random_init(
        model_name="resnet10", size=8, seed=0, buckets=(2,), cache=shared
    )
    e2 = EmbeddingEngine.random_init(
        model_name="resnet10", size=8, seed=0, buckets=(2,), cache=shared
    )
    assert e1._weights_probe == e2._weights_probe  # the trap being defused
    rng = np.random.default_rng(0)
    x = rng.integers(0, 256, size=(2, 8, 8, 3), dtype=np.uint8)

    reg = make_registry()
    try:
        reg.add_model("m", e1)
        _, f = reg.submit(x)
        first = f.result(30)
        assert e1.stats()["cache_hit_rows"] == 0
        _, f = reg.submit(x)
        np.testing.assert_array_equal(f.result(30), first)
        assert e1.stats()["cache_hit_rows"] == 2  # warm under m@v1

        reg.promote("m", e2)
        _, f = reg.submit(x)
        np.testing.assert_array_equal(f.result(30), first)  # same weights
        assert e2.stats()["cache_hit_rows"] == 0  # m@v2 key: MISS, not stale
        _, f = reg.submit(x)
        f.result(30)
        assert e2.stats()["cache_hit_rows"] == 2  # and re-warms under v2
    finally:
        reg.close()


# ------------------------------------------------------------------ admission


def test_admission_controller_quota_and_release():
    adm = AdmissionController(max_tenant_rows=4)
    rel_a = adm.admit("m", "a", 3)
    adm.admit("m", "b", 4)  # different tenant: independent bucket
    with pytest.raises(QueueFull, match="tenant"):
        adm.admit("m", "a", 2)  # 3+2 > 4
    rel_a()
    adm.admit("m", "a", 4)  # released rows freed the quota
    s = adm.stats()
    assert s["rejected"] == 1 and s["admitted"] == 3
    # disabled controller admits anything
    assert AdmissionController(0).admit("m", "t", 10 ** 6)() is None


def test_admission_releases_when_the_future_resolves():
    gate = threading.Event()
    reg = ModelRegistry(
        batcher_kwargs={"max_wait_ms": 1},
        admission=AdmissionController(max_tenant_rows=2),
        index_capacity=0,
    )
    try:
        reg.add_model("m", FakeEngine(gate=gate))
        _, f1 = reg.submit(imgs(1, 2), tenant="t")  # 2 rows: quota full
        with pytest.raises(QueueFull):
            reg.submit(imgs(3), tenant="t")
        _, f_other = reg.submit(imgs(3), tenant="u")  # other tenants fine
        gate.set()
        f1.result(5)
        f_other.result(5)
        # completion released the rows: the same tenant admits again
        assert wait_for(
            lambda: reg.admission.stats()["outstanding_rows"] == 0
        )
        _, f2 = reg.submit(imgs(4, 5), tenant="t")
        f2.result(5)
    finally:
        reg.close()


# ------------------------------------------------------------------ retrieval


def test_neighbor_index_matches_numpy_oracle():
    dim, n = 16, 40
    rng = np.random.default_rng(0)
    rows = rng.normal(size=(n, dim)).astype(np.float32)
    keys = [f"k{i}" for i in range(n)]
    index = NeighborIndex(dim, capacity=64)
    index.add(keys, rows)
    queries = rng.normal(size=(5, dim)).astype(np.float32)
    got = index.query(queries, k=7)

    unit = rows / np.linalg.norm(rows, axis=1, keepdims=True)
    q_unit = queries / np.linalg.norm(queries, axis=1, keepdims=True)
    scores = q_unit @ unit.T
    for qi, hits in enumerate(got):
        oracle = np.argsort(-scores[qi])[:7]
        assert [key for key, _ in hits] == [keys[j] for j in oracle]
        np.testing.assert_allclose(
            [s for _, s in hits], scores[qi][oracle], rtol=1e-5, atol=1e-5
        )


def test_neighbor_index_lru_eviction_and_update_refresh():
    rng = np.random.default_rng(1)
    rows = rng.normal(size=(6, 4)).astype(np.float32)
    index = NeighborIndex(4, capacity=4)
    index.add([f"k{i}" for i in range(4)], rows[:4])
    index.add(["k0"], rows[4:5])  # UPDATE refreshes k0's LRU position
    index.add(["k4"], rows[5:6])  # evicts k1 (oldest untouched), not k0
    assert len(index) == 4
    held = {key for key, _ in index.query(rows[0:1], k=4)[0]}
    assert held == {"k0", "k2", "k3", "k4"}
    s = index.stats()
    assert s["evictions"] == 1 and s["updates"] == 1 and s["inserts"] == 5
    # the updated k0 now scores as its NEW vector
    top_key, top_score = index.query(rows[4:5], k=1)[0][0]
    assert top_key == "k0" and top_score == pytest.approx(1.0, abs=1e-5)


def test_neighbor_index_empty_clear_and_small_k():
    index = NeighborIndex(4, capacity=8)
    assert index.query(np.ones((2, 4), np.float32), k=3) == [[], []]
    index.add(["a", "b"], np.eye(4, dtype=np.float32)[:2])
    got = index.query(np.eye(4, dtype=np.float32)[:1], k=10)[0]
    assert [k for k, _ in got] == ["a", "b"]  # k clamps to the 2 entries
    index.clear()
    assert len(index) == 0
    assert index.query(np.ones((1, 4), np.float32), k=1) == [[]]


# ------------------------------------------------------------- HTTP frontend


def post(base, path, obj, timeout=10):
    req = urllib.request.Request(
        f"{base}{path}", data=json.dumps(obj).encode(),
        headers={"Content-Type": "application/json"},
    )
    with urllib.request.urlopen(req, timeout=timeout) as r:
        return r.status, json.loads(r.read())


def get_raw(base, path, timeout=10):
    with urllib.request.urlopen(f"{base}{path}", timeout=timeout) as r:
        return r.status, r.read().decode()


@pytest.fixture()
def fleet():
    reg = ModelRegistry(
        batcher_kwargs={"max_wait_ms": 1},
        admission=AdmissionController(max_tenant_rows=0),
        index_capacity=16,
    )
    reg.add_model("exp", FakeEngine(scale=10.0))
    reg.add_model("prod", FakeEngine(scale=1.0))
    loads = []

    def loader(name, ckpt):
        loads.append((name, ckpt))
        return FakeEngine(scale=5.0)

    server = create_fleet_server(
        reg, port=0, promote_loader=loader,
        metrics_fn=fleet_metrics_fn(reg),
    )
    start_in_thread(server)
    host, port = server.server_address[:2]
    yield f"http://{host}:{port}", reg, loads
    server.shutdown()
    server.server_close()
    reg.close()


@pytest.fixture()
def fleet_ivf():
    """The fleet fixture with the IVF rung behind the registry's
    index_factory seam — the exact wiring build_fleet_stack does when the
    --retrieval_impl ladder resolves to ivf."""
    from simclr_pytorch_distributed_tpu.serve.fleet import IVFIndex

    reg = ModelRegistry(
        batcher_kwargs={"max_wait_ms": 1},
        admission=AdmissionController(max_tenant_rows=0),
        index_capacity=16,
        index_factory=lambda dim: IVFIndex(
            dim, capacity=16, nlist=2, nprobe=2, train_min_rows=1000
        ),
    )
    reg.add_model("prod", FakeEngine(scale=1.0))
    server = create_fleet_server(
        reg, port=0, metrics_fn=fleet_metrics_fn(reg),
    )
    start_in_thread(server)
    host, port = server.server_address[:2]
    yield f"http://{host}:{port}", reg, None
    server.shutdown()
    server.server_close()
    reg.close()


def test_http_embed_routes_and_defaults(fleet):
    base, _, _ = fleet
    x = imgs(3)
    status, r = post(base, "/embed", {"images": x.tolist()})
    assert status == 200 and r["model"] == "prod"  # newest added = default
    np.testing.assert_allclose(r["embeddings"], FakeEngine(1.0).rows(x))
    status, r = post(base, "/embed", {"images": x.tolist(), "model": "exp"})
    assert r["model"] == "exp"
    np.testing.assert_allclose(r["embeddings"], FakeEngine(10.0).rows(x))
    assert r["dim"] == 3 and r["n"] == 1


def test_http_unknown_model_and_bad_inputs_400(fleet):
    base, _, _ = fleet
    for body in (
        {"images": imgs(1).tolist(), "model": "nope"},
        {"images": imgs(1).tolist(), "model": 7},
        {"images": [[1]]},
        {"images": imgs(1).tolist(), "tenant": 3},
    ):
        with pytest.raises(urllib.error.HTTPError) as exc:
            post(base, "/embed", body)
        assert exc.value.code == 400


def test_http_neighbors_roundtrip(fleet):
    base, reg, _ = fleet
    corpus = imgs(10, 20, 30)
    post(base, "/embed", {"images": corpus.tolist()})  # populates the index
    status, r = post(base, "/neighbors", {"images": imgs(20).tolist(), "k": 2})
    assert status == 200 and r["model"] == "prod" and r["k"] == 2
    hits = r["neighbors"][0]
    assert len(hits) == 2
    assert hits[0]["id"] == reg.content_id(imgs(20)[0])  # self is top-1
    assert hits[0]["score"] == pytest.approx(1.0, abs=1e-5)
    with pytest.raises(urllib.error.HTTPError) as exc:
        post(base, "/neighbors", {"images": imgs(1).tolist(), "k": 0})
    assert exc.value.code == 400


def test_http_neighbors_k_bounded_by_max_k(fleet):
    """k above --neighbors_max_k is a 400, not an O(k) scan: the bound is
    the frontend's, the index's min(k, entries) clamp stays below it."""
    base, _, _ = fleet
    post(base, "/embed", {"images": imgs(7).tolist()})
    # the default bound (100) admits k=100 and rejects k=101
    status, r = post(base, "/neighbors", {"images": imgs(7).tolist(), "k": 100})
    assert status == 200 and len(r["neighbors"][0]) == 1  # clamps to entries
    with pytest.raises(urllib.error.HTTPError) as exc:
        post(base, "/neighbors", {"images": imgs(7).tolist(), "k": 101})
    assert exc.value.code == 400
    assert "neighbors_max_k" in json.loads(exc.value.read())["error"]


def test_http_neighbors_max_k_disabled():
    """--neighbors_max_k 0 disables the bound (the opt-out the flag help
    promises)."""
    reg = make_registry(index_capacity=8)
    reg.add_model("m", FakeEngine())
    server = create_fleet_server(reg, port=0, neighbors_max_k=0)
    start_in_thread(server)
    host, port = server.server_address[:2]
    try:
        base = f"http://{host}:{port}"
        post(base, "/embed", {"images": imgs(1).tolist()})
        status, r = post(
            base, "/neighbors", {"images": imgs(1).tolist(), "k": 5000}
        )
        assert status == 200 and len(r["neighbors"][0]) == 1
    finally:
        server.shutdown()
        server.server_close()
        reg.close()


def test_http_neighbors_roundtrip_on_ivf_index(fleet_ivf):
    """The IVF rung behind the SAME HTTP surface: /embed feeds the index
    through index_factory-built IVFIndex, /neighbors answers from it, and
    the untrained small corpus answers exactly (self top-1 at score 1)."""
    base, reg, _ = fleet_ivf
    corpus = imgs(10, 20, 30)
    post(base, "/embed", {"images": corpus.tolist()})
    status, r = post(base, "/neighbors", {"images": imgs(20).tolist(), "k": 2})
    assert status == 200 and r["k"] == 2
    hits = r["neighbors"][0]
    assert hits[0]["id"] == reg.content_id(imgs(20)[0])
    assert hits[0]["score"] == pytest.approx(1.0, abs=1e-5)
    # promote clears rows AND centroids through the impl-blind registry
    reg.promote(r["model"], FakeEngine(scale=2.0))
    stats = reg.stats()["models"][r["model"]]["index"]
    assert stats["entries"] == 0 and stats["trained_lists"] == 0


def test_http_promote_swaps_and_drains(fleet):
    base, reg, loads = fleet
    x = imgs(4)
    status, r = post(base, "/models/promote", {"model": "prod", "ckpt": "/fake/ckpt"})
    assert status == 200
    assert r == {"model": "prod", "version": 2, "draining": 1}
    assert loads == [("prod", "/fake/ckpt")]
    assert reg.wait_drained("prod", 1, timeout=5)  # nothing was in flight
    _, r = post(base, "/embed", {"images": x.tolist(), "model": "prod"})
    np.testing.assert_allclose(r["embeddings"], FakeEngine(5.0).rows(x))
    _, payload = get_raw(base, "/models")
    models = json.loads(payload)["models"]
    assert [v["state"] for v in models["prod"]["versions"]] == [
        "retired", "serving",
    ]
    with pytest.raises(urllib.error.HTTPError) as exc:
        post(base, "/models/promote", {"model": "ghost", "ckpt": "/x"})
    assert exc.value.code == 400


def test_http_promote_without_loader_is_503():
    reg = make_registry()
    reg.add_model("m", FakeEngine())
    server = create_fleet_server(reg, port=0)  # no promote_loader
    start_in_thread(server)
    host, port = server.server_address[:2]
    try:
        with pytest.raises(urllib.error.HTTPError) as exc:
            post(f"http://{host}:{port}", "/models/promote",
                 {"model": "m", "ckpt": "/x"})
        assert exc.value.code == 503
    finally:
        server.shutdown()
        server.server_close()
        reg.close()


def test_http_metrics_exposition(fleet):
    base, _, _ = fleet
    post(base, "/embed", {"images": imgs(1).tolist()})
    _, text = get_raw(base, "/metrics")
    # the unlabeled aggregates the replica supervisor scrapes...
    assert "\nserve_batcher_queue_depth " in "\n" + text
    assert "serve_batcher_last_completion_age_s " in text
    assert "serve_fleet_models 2" in text
    # ...and the labeled per-model operator series
    assert 'serve_fleet_model_serving_version{model="prod"} 1' in text
    assert 'serve_fleet_index_entries{model="prod"} 1' in text
    # the per-model retrieval counters (probes/retrains read 0 on the
    # brute rung — the gauge set is impl-uniform so dashboards never
    # branch on the ladder)
    assert 'serve_fleet_index_inserts_total{model="prod"} 1' in text
    assert 'serve_fleet_index_evictions_total{model="prod"} 0' in text
    assert 'serve_fleet_index_queries_total{model="prod"} 0' in text
    assert 'serve_fleet_index_probes_total{model="prod"} 0' in text
    assert 'serve_fleet_index_retrains_total{model="prod"} 0' in text


def test_fleet_cli_retrieval_ladder_flags():
    """The --retrieval_impl ladder on the fleet CLI: defaults, and the
    honored-or-raise contract firing at startup BEFORE any engine is
    built when an explicit ivf ask contradicts --index_capacity 0."""
    from simclr_pytorch_distributed_tpu.serve.fleet.frontend import (
        DEFAULT_NEIGHBORS_MAX_K,
        build_fleet_stack,
        build_parser,
    )

    args = build_parser().parse_args([])
    assert args.retrieval_impl == "auto"
    assert args.ivf_nlist == 0  # 0 = sqrt(capacity) auto
    assert args.ivf_nprobe == 8
    assert args.neighbors_max_k == DEFAULT_NEIGHBORS_MAX_K == 100
    bad = build_parser().parse_args(
        ["--retrieval_impl", "ivf", "--index_capacity", "0"]
    )
    with pytest.raises(ValueError, match="index_capacity"):
        build_fleet_stack(bad)


def test_http_metrics_ivf_probe_and_query_counters(fleet_ivf):
    base, _, _ = fleet_ivf
    post(base, "/embed", {"images": imgs(1, 2).tolist()})
    post(base, "/neighbors", {"images": imgs(1).tolist(), "k": 1})
    _, text = get_raw(base, "/metrics")
    assert 'serve_fleet_index_entries{model="prod"} 2' in text
    assert 'serve_fleet_index_queries_total{model="prod"} 1' in text
    # untrained rung: one provisional list, one probe per query
    assert 'serve_fleet_index_probes_total{model="prod"} 1' in text
    assert 'serve_fleet_index_retrains_total{model="prod"} 0' in text
