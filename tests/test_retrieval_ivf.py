"""IVFIndex lifecycle-edge tests: the untrained rung IS brute force, the
trained rung's recall on clustered corpora, seeded determinism, per-list
LRU eviction under the global budget (with an nprobe=nlist exactness
oracle that survives churn and retrains), the promote-clear seam under
concurrent queries, concurrent add/query/retrain threads, and the
``--retrieval_impl`` ladder resolution. Pure numpy — no jax compiles:
the IVF rung is deliberately host-side (see ivf.py's docstring), so the
whole file runs at unit-test speed.
"""

import threading

import numpy as np
import pytest

from simclr_pytorch_distributed_tpu.serve.fleet.ivf import (
    AUTO_IVF_MIN_CAPACITY,
    IVFIndex,
    auto_nlist,
    resolve_retrieval_impl,
)

pytestmark = [pytest.mark.serve, pytest.mark.servefleet]


def unit(rows):
    rows = np.asarray(rows, np.float32)
    return rows / np.maximum(
        np.linalg.norm(rows, axis=-1, keepdims=True), 1e-12
    )


def clustered(rng, n, dim, n_clusters=8, noise=0.25):
    """The regime served embeddings live in: points scattered around a few
    directions, not isotropic noise (where no quantizer could help)."""
    centers = unit(rng.normal(size=(n_clusters, dim)))
    rows = centers[rng.integers(0, n_clusters, size=n)]
    return (rows + noise * rng.normal(size=(n, dim))).astype(np.float32)


def brute_ids(corpus_unit, keys, q, k):
    scores = corpus_unit @ unit(q)
    order = np.argsort(-scores, kind="stable")[:k]
    return [keys[i] for i in order]


# ------------------------------------------------------------- exactness


def test_untrained_ivf_is_exact_brute():
    """Below train_min_rows there is one provisional list and a query
    scans it exactly — answers match the brute oracle including order."""
    rng = np.random.default_rng(0)
    rows = rng.normal(size=(40, 16)).astype(np.float32)
    keys = [f"k{i}" for i in range(40)]
    index = IVFIndex(16, capacity=64, nlist=8, train_min_rows=1000)
    index.add(keys, rows)
    assert index.stats()["trained_lists"] == 0

    corpus = unit(rows)
    for q in rng.normal(size=(5, 16)).astype(np.float32):
        got = index.query(q[None], k=7)[0]
        assert [key for key, _ in got] == brute_ids(corpus, keys, q, 7)
        oracle_scores = np.sort(corpus @ unit(q))[::-1][:7]
        np.testing.assert_allclose(
            [s for _, s in got], oracle_scores, rtol=1e-5, atol=1e-6
        )


def test_trained_recall_on_clustered_corpus():
    rng = np.random.default_rng(1)
    dim, n, k = 16, 2000, 10
    rows = clustered(rng, n, dim, n_clusters=16)
    keys = [f"k{i}" for i in range(n)]
    index = IVFIndex(dim, capacity=4096, nlist=16, nprobe=8,
                     train_min_rows=256)
    index.add(keys, rows)
    s = index.stats()
    assert s["trained_lists"] == 16 and s["retrains"] >= 1

    corpus = unit(rows)
    queries = rows[rng.choice(n, size=20, replace=False)]
    queries = queries + 0.1 * rng.normal(size=queries.shape).astype(np.float32)
    hits = total = 0
    for q in queries.astype(np.float32):
        got = {key for key, _ in index.query(q[None], k=k)[0]}
        hits += len(got & set(brute_ids(corpus, keys, q, k)))
        total += k
    assert hits / total >= 0.9
    assert index.stats()["probes"] >= 8 * len(queries)


def test_determinism_same_seed_same_order():
    """Same seed + same insert order -> identical centroids, lists, and
    answers (the property the committed A/B artifact leans on)."""
    rng = np.random.default_rng(2)
    rows = clustered(rng, 600, 8)
    keys = [f"k{i}" for i in range(600)]
    queries = rng.normal(size=(8, 8)).astype(np.float32)

    answers = []
    for _ in range(2):
        index = IVFIndex(8, capacity=1024, nlist=8, nprobe=2, seed=3,
                         train_min_rows=128)
        index.add(keys, rows)
        answers.append([index.query(q[None], k=5)[0] for q in queries])
    assert answers[0] == answers[1]  # keys AND float scores, exactly


# ------------------------------------------------------ eviction / recency


def test_per_list_lru_global_budget_and_churn_exactness():
    """Churn 3x the capacity through a trained index: the global budget
    holds, evictions are counted, and — with nprobe=nlist so every list
    is probed — answers over the SURVIVING corpus stay EXACTLY brute
    (recall invariance under churn is not a statistical claim here)."""
    rng = np.random.default_rng(3)
    dim, capacity = 8, 64
    index = IVFIndex(dim, capacity=capacity, nlist=4, nprobe=4,
                     train_min_rows=32, seed=0)
    n_total = 3 * capacity
    rows = clustered(rng, n_total, dim)
    for i in range(n_total):
        index.add([f"k{i}"], rows[i:i + 1])

    s = index.stats()
    assert s["entries"] == capacity == len(index)
    assert s["evictions"] == s["inserts"] - capacity
    assert s["trained_lists"] == 4

    # reconstruct the surviving corpus and compare against brute
    with index._lock:
        survivors = list(index._order)
        corpus = index._buf[[index._order[key] for key in survivors]].copy()
    for q in rng.normal(size=(10, dim)).astype(np.float32):
        got = [key for key, _ in index.query(q[None], k=5)[0]]
        assert got == brute_ids(corpus, survivors, q, 5)

    # the very last inserted row is always present: self-query is top-1
    last = f"k{n_total - 1}"
    top_key, top_score = index.query(rows[-1:], k=1)[0][0]
    assert top_key == last and top_score == pytest.approx(1.0, abs=1e-5)


def test_queries_never_touch_recency():
    index = IVFIndex(4, capacity=4, nlist=1, train_min_rows=1000)
    eye = np.eye(4, dtype=np.float32)
    index.add(["a", "b", "c", "d"], eye)
    for _ in range(5):  # hammering "a" must NOT refresh it
        index.query(eye[:1], k=1)
    index.add(["e"], eye[:1])  # evicts "a", the oldest INSERT
    held = {key for key, _ in index.query(eye[:1], k=4)[0]}
    assert held == {"b", "c", "d", "e"}


def test_update_is_idempotent_and_moves_lists():
    """Re-adding a key overwrites its row; the ROW decides the list, so an
    update may migrate the key across inverted lists."""
    rng = np.random.default_rng(4)
    dim = 8
    a_dir, b_dir = unit(np.eye(dim, dtype=np.float32)[:2])
    rows = np.concatenate([
        unit(a_dir + 0.1 * rng.normal(size=(40, dim)).astype(np.float32)),
        unit(b_dir + 0.1 * rng.normal(size=(40, dim)).astype(np.float32)),
    ])
    keys = [f"k{i}" for i in range(80)]
    index = IVFIndex(dim, capacity=128, nlist=2, nprobe=1, train_min_rows=64)
    index.add(keys, rows)
    assert index.stats()["trained_lists"] == 2

    index.add(["probe"], a_dir[None])
    entries = index.stats()["entries"]
    assert [k for k, _ in index.query(a_dir[None], k=1)[0]] == ["probe"]
    index.add(["probe"], b_dir[None])  # same key, opposite cluster
    s = index.stats()
    assert s["entries"] == entries and s["updates"] == 1
    # with nprobe=1 only the nearest list is scanned: the key answers from
    # its NEW direction and is gone from the old one
    assert [k for k, _ in index.query(b_dir[None], k=1)[0]] == ["probe"]
    assert "probe" not in {
        k for k, _ in index.query(a_dir[None], k=50)[0]
    }


# ------------------------------------------------------- clear / threads


def test_clear_drops_rows_and_centroids():
    rng = np.random.default_rng(5)
    index = IVFIndex(8, capacity=256, nlist=4, train_min_rows=32)
    index.add([f"k{i}" for i in range(64)], clustered(rng, 64, 8))
    assert index.stats()["trained_lists"] == 4
    index.clear()
    s = index.stats()
    assert len(index) == 0 and s["trained_lists"] == 0
    assert index.query(np.ones((1, 8), np.float32), k=3) == [[]]
    # the index is fully reusable after the promote seam
    index.add([f"n{i}" for i in range(64)], clustered(rng, 64, 8))
    assert len(index) == 64 and index.stats()["trained_lists"] == 4


def test_clear_under_concurrent_queries():
    """The promote seam races live /neighbors traffic: queries before the
    clear see the old corpus, queries after see empty-or-new, and nothing
    raises or returns a torn view (keys from both spaces in one answer)."""
    rng = np.random.default_rng(6)
    index = IVFIndex(8, capacity=256, nlist=4, train_min_rows=32)
    index.add([f"old{i}" for i in range(64)], clustered(rng, 64, 8))
    q = rng.normal(size=(1, 8)).astype(np.float32)
    stop = threading.Event()
    errors, torn = [], []

    def hammer():
        while not stop.is_set():
            try:
                for hits in index.query(q, k=8):
                    spaces = {key[:3] for key, _ in hits}
                    if len(spaces) > 1:
                        torn.append(spaces)
            except Exception as e:  # noqa: BLE001
                errors.append(e)
                return

    threads = [threading.Thread(target=hammer) for _ in range(3)]
    for t in threads:
        t.start()
    index.clear()
    index.add([f"new{i}" for i in range(64)], clustered(rng, 64, 8))
    stop.set()
    for t in threads:
        t.join(10)
    assert not errors and not torn


def test_concurrent_add_query_retrain_threads():
    """Writers push enough rows to cross several retrain triggers while
    readers hammer queries: no exceptions, budget respected, counters
    coherent."""
    rng = np.random.default_rng(7)
    index = IVFIndex(8, capacity=128, nlist=4, nprobe=2,
                     train_min_rows=32, retrain_drift=0.25)
    stop = threading.Event()
    errors = []

    def writer(tag):
        try:
            for i in range(400):
                row = clustered(rng, 1, 8)
                index.add([f"{tag}{i}"], row)
        except Exception as e:  # noqa: BLE001
            errors.append(e)

    def reader():
        q = np.ones((1, 8), np.float32)
        try:
            while not stop.is_set():
                for hits in index.query(q, k=5):
                    for _, score in hits:
                        assert -1.001 <= score <= 1.001
        except Exception as e:  # noqa: BLE001
            errors.append(e)

    writers = [threading.Thread(target=writer, args=(t,)) for t in "ab"]
    readers = [threading.Thread(target=reader) for _ in range(2)]
    for t in readers + writers:
        t.start()
    for t in writers:
        t.join(60)
    stop.set()
    for t in readers:
        t.join(10)
    assert not errors
    s = index.stats()
    assert s["entries"] == 128  # 800 inserts through a 128 budget
    assert s["inserts"] == 800
    assert s["evictions"] == s["inserts"] - s["entries"]
    assert s["retrains"] >= 2  # drift ratio fired beyond the first train


# ------------------------------------------------------------ the ladder


def test_resolve_retrieval_impl_ladder():
    below, above = AUTO_IVF_MIN_CAPACITY - 1, AUTO_IVF_MIN_CAPACITY
    assert resolve_retrieval_impl("auto", below)[0] == "brute"
    assert resolve_retrieval_impl("auto", above)[0] == "ivf"
    # explicit choices are honored regardless of the threshold
    assert resolve_retrieval_impl("brute", above)[0] == "brute"
    impl, reason = resolve_retrieval_impl("ivf", 4096)
    assert impl == "ivf" and "4096" in reason
    # disabled index: auto/brute degrade with a reason, ivf contradicts
    impl, reason = resolve_retrieval_impl("auto", 0)
    assert impl == "brute" and "disabled" in reason
    with pytest.raises(ValueError, match="index_capacity is 0"):
        resolve_retrieval_impl("ivf", 0)
    with pytest.raises(ValueError, match="index_capacity >= nlist"):
        resolve_retrieval_impl("ivf", 16, nlist=64)
    with pytest.raises(ValueError, match="brute/ivf/auto"):
        resolve_retrieval_impl("faiss", 4096)


def test_auto_nlist_and_ctor_validation():
    assert auto_nlist(4096) == 64  # sqrt rule
    assert auto_nlist(1) == 8      # floor
    assert auto_nlist(10 ** 9) == 1024  # ceiling
    with pytest.raises(ValueError):
        IVFIndex(0, capacity=16)
    with pytest.raises(ValueError):
        IVFIndex(4, capacity=16, nlist=32)  # nlist > capacity
    with pytest.raises(ValueError):
        IVFIndex(4, capacity=16).add(["a"], np.ones((1, 5), np.float32))
    with pytest.raises(ValueError):
        IVFIndex(4, capacity=16).query(np.ones((1, 4), np.float32), k=0)
