"""The pluggable SSL-recipe subsystem (recipes/, --recipe).

The load-bearing claims, tested mechanically (the test_health conventions):

- REFACTOR NEUTRALITY: ``--recipe supcon`` through the recipe interface
  produces BITWISE-identical params/BN-stats/optimizer-state to the
  pre-refactor inline update (``make_fused_update(recipe=None)``) — at step
  level and through the REAL driver over 2 epochs, under host AND device
  data placement (the acceptance bar; docs/PARITY.md).
- EVERY RECIPE RIDES THE SUBSTRATE: one real sync-mode driver epoch per
  recipe on the host path (the consume-signature smoke), and the PR-4/PR-5
  zero-sync transfer contract re-proven per recipe on the device path —
  exactly 3 ring D2H + 1 index upload with health + probe + the recipe on
  (the device-placement smoke and the mechanical transfer proof in one).
- COLLAPSE IS CAUGHT PER RECIPE: a degenerate constant-embedding run under
  each new recipe (BYOL in its predictor-ABLATED form — the configuration
  whose collapse the detector exists for) trips the typed code-3 abort
  through the ring->monitor->collective-exchange path.
- CHECKPOINT HYGIENE: recipe slots live in their own ``recipe`` payload
  keyed by the meta-recorded recipe name; cross-recipe resumes degrade
  loudly to fresh slots, same-recipe resumes restore bitwise.
"""

import json
import math
import os

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from simclr_pytorch_distributed_tpu import config as config_lib
from simclr_pytorch_distributed_tpu import recipes as recipes_lib
from simclr_pytorch_distributed_tpu.models import SupConResNet
from simclr_pytorch_distributed_tpu.ops.losses import (
    byol_loss,
    moco_queue_loss,
    simsiam_loss,
    supcon_loss,
    vicreg_loss,
)
from simclr_pytorch_distributed_tpu.ops.metrics import embedding_covariance
from simclr_pytorch_distributed_tpu.recipes.byol import BYOLRecipe
from simclr_pytorch_distributed_tpu.train import supcon_step
from simclr_pytorch_distributed_tpu.train.state import (
    create_train_state,
    make_optimizer,
)
from simclr_pytorch_distributed_tpu.train.supcon_step import (
    SupConStepConfig,
    make_train_step,
    metric_keys,
)
from simclr_pytorch_distributed_tpu.utils.guard import (
    HealthThresholds,
    RepresentationHealthError,
    thresholds_for_recipe,
)

pytestmark = pytest.mark.recipe

SIZE = 8


def assert_trees_bitwise(a, b):
    fa = jax.tree.leaves(jax.device_get(a))
    fb = jax.tree.leaves(jax.device_get(b))
    assert len(fa) == len(fb)
    for xa, xb in zip(fa, fb):
        np.testing.assert_array_equal(np.asarray(xa), np.asarray(xb))


# ------------------------------------------------------------ the loss terms


def _two_view(rng, b=6, d=10):
    v1 = rng.normal(size=(b, d)).astype(np.float32)
    v2 = rng.normal(size=(b, d)).astype(np.float32)
    return np.concatenate([v1, v2])  # view-major [2B, D]


def test_byol_loss_zero_at_alignment_two_when_orthogonal(rng):
    t = _two_view(rng)
    b = t.shape[0] // 2
    # pred row i == normalized target row (i+B)%2B -> exact regression, 0
    pred = np.concatenate([t[b:], t[:b]])
    assert float(byol_loss(jnp.asarray(pred), jnp.asarray(t))) == pytest.approx(
        0.0, abs=1e-6
    )
    # orthogonal pred/target -> ||p - t||^2 = 2 per row
    d = 8
    e = np.eye(d, dtype=np.float32)
    pred = np.concatenate([e[:3], e[:3]])
    targ = np.concatenate([e[3:6], e[3:6]])
    assert float(byol_loss(jnp.asarray(pred), jnp.asarray(targ))) == pytest.approx(
        2.0, abs=1e-6
    )


def test_simsiam_loss_bounds_and_alignment(rng):
    z = _two_view(rng)
    b = z.shape[0] // 2
    pred = np.concatenate([z[b:], z[:b]])
    # pred == cross(proj) -> cos = 1 -> loss -1 (its minimum)
    assert float(simsiam_loss(jnp.asarray(pred), jnp.asarray(z))) == pytest.approx(
        -1.0, abs=1e-6
    )
    val = float(simsiam_loss(jnp.asarray(z), jnp.asarray(z)))
    assert -1.0 <= val <= 1.0


def test_simsiam_stop_gradient_is_inside_the_loss(rng):
    """The projection side must be detached IN the loss: grads w.r.t. the
    proj argument are exactly zero while the pred side's are not."""
    z = jnp.asarray(_two_view(rng))
    p = jnp.asarray(_two_view(rng))
    gp, gz = jax.grad(lambda a, b: simsiam_loss(a, b), argnums=(0, 1))(p, z)
    assert float(jnp.sum(jnp.abs(gz))) == 0.0
    assert float(jnp.sum(jnp.abs(gp))) > 0.0


def test_vicreg_loss_matches_numpy_reference(rng):
    b, d = 12, 6
    z1 = rng.normal(size=(b, d)).astype(np.float32) * 2.0
    z2 = (z1 + 0.3 * rng.normal(size=(b, d))).astype(np.float32)
    loss, parts = vicreg_loss(
        jnp.asarray(z1), jnp.asarray(z2),
        sim_coeff=25.0, std_coeff=25.0, cov_coeff=1.0,
    )
    inv_ref = np.mean((z1 - z2) ** 2)
    var_ref, cov_ref = 0.0, 0.0
    for z in (z1, z2):
        std = np.sqrt(z.var(axis=0) + 1e-4)
        var_ref += np.mean(np.maximum(0.0, 1.0 - std)) / 2
        zc = z - z.mean(axis=0)
        cov = (zc.T @ zc) / (b - 1)
        cov_ref += np.sum((cov - np.diag(np.diag(cov))) ** 2) / d / 2
    assert float(parts["vicreg_inv"]) == pytest.approx(inv_ref, rel=1e-4)
    assert float(parts["vicreg_var"]) == pytest.approx(var_ref, rel=1e-4, abs=1e-6)
    assert float(parts["vicreg_cov"]) == pytest.approx(cov_ref, rel=1e-3)
    assert float(loss) == pytest.approx(
        25 * inv_ref + 25 * var_ref + cov_ref, rel=1e-3
    )
    # well-spread embeddings (std > 1): the variance hinge contributes 0
    z_wide = rng.normal(size=(b, d)).astype(np.float32) * 5.0
    _, parts_wide = vicreg_loss(jnp.asarray(z_wide), jnp.asarray(z_wide))
    assert float(parts_wide["vicreg_var"]) == pytest.approx(0.0, abs=1e-6)


def test_embedding_covariance_shared_construction(rng):
    z = rng.normal(size=(10, 4)).astype(np.float32)
    # uncentered second moment == the health diagnostics' expression
    np.testing.assert_allclose(
        np.asarray(embedding_covariance(jnp.asarray(z))), z.T @ z / 10,
        rtol=1e-6,
    )
    zc = z - z.mean(axis=0)
    np.testing.assert_allclose(
        np.asarray(embedding_covariance(jnp.asarray(z), center=True, ddof=1)),
        zc.T @ zc / 9, rtol=1e-5,
    )


def test_moco_queue_loss_matches_numpy_reference(rng):
    b, d, k = 4, 8, 6
    query = _two_view(rng, b=b, d=d)
    query = query / np.linalg.norm(query, axis=1, keepdims=True)
    key = _two_view(rng, b=b, d=d)
    key = key / np.linalg.norm(key, axis=1, keepdims=True)
    queue = rng.normal(size=(k, d)).astype(np.float32)
    queue = queue / np.linalg.norm(queue, axis=1, keepdims=True)
    temp, base = 0.5, 0.07
    n = 2 * b
    contrast = np.concatenate([key, queue])
    logits = query @ contrast.T / temp
    logits -= logits.max(axis=1, keepdims=True)
    mask = np.ones((n, n + k), np.float32)
    mask[np.arange(n), np.arange(n)] = 0.0  # own view's key: false negative
    log_prob = logits - np.log((np.exp(logits) * mask).sum(axis=1, keepdims=True))
    pos = (np.arange(n) + b) % n
    ref = -(temp / base) * log_prob[np.arange(n), pos]
    got = float(moco_queue_loss(
        jnp.asarray(query), jnp.asarray(key), jnp.asarray(queue),
        temperature=temp, base_temperature=base,
    ))
    assert got == pytest.approx(float(ref.mean()), rel=1e-5)


def test_moco_queue_loss_degenerates_to_simclr(rng):
    """K=0 with key == query must equal the dense SimCLR loss exactly —
    the MoCo extension is a strict superset of the existing op sequence."""
    b, d = 4, 8
    feats = _two_view(rng, b=b, d=d)
    feats = feats / np.linalg.norm(feats, axis=1, keepdims=True)
    n_features = jnp.stack([jnp.asarray(feats[:b]), jnp.asarray(feats[b:])], 1)
    dense = float(supcon_loss(
        n_features, temperature=0.5, base_temperature=0.07
    ))
    queued = float(moco_queue_loss(
        jnp.asarray(feats), jnp.asarray(feats),
        jnp.zeros((0, d), jnp.float32),
        temperature=0.5, base_temperature=0.07,
    ))
    assert queued == pytest.approx(dense, rel=1e-6)


# --------------------------------------------------- config surface + registry


def test_recipe_auto_resolves_from_method():
    cfg = config_lib.SupConConfig(method="SimCLR")
    config_lib.validate_recipe(cfg)
    assert cfg.recipe == "simclr"
    cfg = config_lib.SupConConfig(method="SupCon")
    config_lib.validate_recipe(cfg)
    assert cfg.recipe == "supcon"


def test_recipe_forces_method():
    # supcon forcing is unambiguous (SimCLR == the --method default)
    cfg = config_lib.SupConConfig(recipe="supcon", method="SimCLR")
    config_lib.validate_recipe(cfg)
    assert cfg.method == "SupCon"


@pytest.mark.parametrize("over,match", [
    (dict(recipe="byol", method="SupCon"), "label-free"),
    # SupCon is not the --method default, so this is an explicit
    # contradiction — silently dropping the labels would be worse
    (dict(recipe="simclr", method="SupCon"), "contradicts"),
    (dict(recipe="supcon", moco_queue=512), "NEGATIVES only"),
    (dict(recipe="byol", moco_queue=512), "NEGATIVES only"),
    (dict(recipe="simclr", moco_queue=100, batch_size=64), "multiple of"),
    (dict(recipe="simclr", moco_queue=512, loss_impl="fused"), "dense"),
    (dict(recipe="simclr", moco_queue=512, loss_impl="ring"), "dense"),
    (dict(recipe="byol", ema_momentum=1.0), "ema_momentum"),
    (dict(recipe="byol", ema_momentum=-0.1), "ema_momentum"),
    (dict(recipe="vicreg", vicreg_std_coeff=-1.0), "vicreg_std_coeff"),
])
def test_validate_recipe_rejects(over, match):
    cfg = config_lib.SupConConfig(**over)
    with pytest.raises(ValueError, match=match):
        config_lib.validate_recipe(cfg)


def test_recipe_flags_parse_and_finalize(tmp_path):
    cfg = config_lib.parse_supcon([
        "--recipe", "byol", "--ema_momentum", "0.99",
        "--predictor_hidden", "64", "--workdir", str(tmp_path),
    ])
    assert cfg.recipe == "byol" and cfg.ema_momentum == 0.99
    cfg = config_lib.parse_supcon([
        "--recipe", "simclr", "--moco_queue", "512",
        "--workdir", str(tmp_path),
    ])
    assert cfg.moco_queue == 512


def test_build_recipe_slots_per_recipe():
    model = SupConResNet(model_name="resnet10", feat_dim=16)
    tx = make_optimizer(0.1)
    state = create_train_state(
        model, tx, jax.random.key(0), jnp.zeros((2, SIZE, SIZE, 3))
    )

    def attach(**over):
        cfg = config_lib.SupConConfig(
            feat_dim=16, predictor_hidden=32, batch_size=4, **over
        )
        config_lib.validate_recipe(cfg)
        return recipes_lib.attach_for_config(cfg, model, state)

    # contrastive, no queue: attach is a strict no-op (same object)
    s, r = attach(recipe="supcon")
    assert s is state and r.name == "supcon"
    s, r = attach(recipe="simclr")
    assert s is state and r.name == "simclr"

    s, r = attach(recipe="simclr", moco_queue=16)
    assert s.recipe_params is None and s.recipe_opt_state is None
    assert s.recipe_state["queue_emb"].shape == (16, 16)
    norms = jnp.linalg.norm(s.recipe_state["queue_emb"], axis=1)
    np.testing.assert_allclose(np.asarray(norms), 1.0, rtol=1e-5)
    # the momentum KEY encoder starts as a copy of the online network
    assert_trees_bitwise(s.recipe_state["key_params"], state.params)

    s, r = attach(recipe="byol")
    assert r.trainable and s.recipe_params is not None
    assert s.recipe_opt_state is not None
    assert_trees_bitwise(s.recipe_state["target_params"], state.params)

    s, r = attach(recipe="byol", byol_predictor="none")
    assert not r.trainable and s.recipe_params is None
    assert s.recipe_state is not None

    s, r = attach(recipe="simsiam")
    assert r.trainable and s.recipe_params is not None
    assert s.recipe_state is None

    s, r = attach(recipe="vicreg")
    assert s is state and r.metric_keys == ("vicreg_cov", "vicreg_inv",
                                            "vicreg_var")


def test_thresholds_for_recipe():
    assert thresholds_for_recipe("byol").eff_rank_min == 3.0
    assert thresholds_for_recipe("simsiam").eff_rank_min == 3.0
    assert thresholds_for_recipe("simclr") == HealthThresholds()
    assert thresholds_for_recipe("vicreg") == HealthThresholds()
    assert thresholds_for_recipe(None) == HealthThresholds()


def test_resolve_loss_impl_queue_forces_dense(monkeypatch):
    from simclr_pytorch_distributed_tpu.train.supcon import resolve_loss_impl

    monkeypatch.setattr(jax, "default_backend", lambda: "tpu")
    assert resolve_loss_impl("auto", 256, 1, moco_queue=512) == "dense"
    assert resolve_loss_impl("dense", 256, 1, moco_queue=512) == "dense"


# ------------------------------------------------------------- step level


def _tiny_recipe(recipe_name, n_steps=2, batch=4, **cfg_over):
    cfg = config_lib.SupConConfig(
        model="resnet10", feat_dim=16, batch_size=batch, recipe=recipe_name,
        predictor_hidden=32, learning_rate=0.1, **cfg_over,
    )
    config_lib.validate_recipe(cfg)
    model = SupConResNet(model_name="resnet10", feat_dim=16)
    tx = make_optimizer(0.1)
    state = create_train_state(
        model, tx, jax.random.key(0), jnp.zeros((2, SIZE, SIZE, 3))
    )
    state, recipe = recipes_lib.attach_for_config(cfg, model, state)
    scfg = SupConStepConfig(
        method=cfg.method, steps_per_epoch=4, loss_impl="dense",
    )
    step = jax.jit(make_train_step(model, tx, lambda s: 0.1, scfg,
                                   recipe=recipe))
    images = jax.random.uniform(jax.random.key(1), (batch, 2, SIZE, SIZE, 3))
    labels = jnp.arange(batch) % 2
    metrics = None
    for _ in range(n_steps):
        state, metrics = step(state, images, labels)
    return state, recipe, jax.device_get(metrics)


def test_supcon_recipe_step_bitwise_vs_inline():
    """Step-level refactor neutrality: the recipe dispatch around the
    extracted contrastive term changes NOTHING — params, BN stats,
    optimizer state, and every metric bitwise-equal after 3 steps (the
    driver-level 2-epoch proof below is the acceptance bar)."""
    model = SupConResNet(model_name="resnet10", feat_dim=16)
    tx = make_optimizer(0.1)
    scfg = SupConStepConfig(method="SupCon", steps_per_epoch=4,
                            loss_impl="dense")
    images = jax.random.uniform(jax.random.key(1), (4, 2, SIZE, SIZE, 3))
    labels = jnp.arange(4) % 2

    def run(recipe):
        state = create_train_state(
            model, tx, jax.random.key(0), jnp.zeros((2, SIZE, SIZE, 3))
        )
        step = jax.jit(make_train_step(model, tx, lambda s: 0.1, scfg,
                                       recipe=recipe))
        for _ in range(3):
            state, metrics = step(state, images, labels)
        return state, jax.device_get(metrics)

    cfg = config_lib.SupConConfig(recipe="supcon", batch_size=4)
    config_lib.validate_recipe(cfg)
    s_recipe, m_recipe = run(recipes_lib.build_recipe(cfg))
    s_inline, m_inline = run(None)
    assert_trees_bitwise(s_recipe.params, s_inline.params)
    assert_trees_bitwise(s_recipe.batch_stats, s_inline.batch_stats)
    assert_trees_bitwise(s_recipe.opt_state, s_inline.opt_state)
    assert s_recipe.recipe_params is None and s_recipe.recipe_state is None
    assert m_recipe == m_inline


def test_byol_step_trains_predictor_and_ema_target():
    state0, recipe, _ = _tiny_recipe("byol", n_steps=0)
    target0 = jax.device_get(state0.recipe_state["target_params"])
    pred0 = jax.device_get(state0.recipe_params)
    state1, _, metrics = _tiny_recipe("byol", n_steps=1)
    # predictor trained (joint gradient reached it)...
    moved = jax.tree.map(
        lambda a, b: not np.array_equal(np.asarray(a), np.asarray(b)),
        pred0, jax.device_get(state1.recipe_params),
    )
    assert any(jax.tree.leaves(moved))
    # ...the encoder trained THROUGH the predictor path...
    enc_moved = jax.tree.map(
        lambda a, b: not np.array_equal(np.asarray(a), np.asarray(b)),
        jax.device_get(state0.params), jax.device_get(state1.params),
    )
    assert any(jax.tree.leaves(enc_moved))
    # ...and the post-step EMA is exactly tau*target + (1-tau)*new_online
    tau = recipe.ema_momentum
    expect = jax.tree.map(
        lambda t, o: tau * np.asarray(t) + (1 - tau) * np.asarray(o),
        target0, jax.device_get(state1.params),
    )
    got = jax.device_get(state1.recipe_state["target_params"])
    for a, b in zip(jax.tree.leaves(expect), jax.tree.leaves(got)):
        np.testing.assert_allclose(a, b, rtol=2e-5, atol=1e-6)
    assert math.isfinite(metrics["loss"])


def test_simsiam_step_trains():
    state0, _, _ = _tiny_recipe("simsiam", n_steps=0)
    state1, _, metrics = _tiny_recipe("simsiam", n_steps=2)
    assert state1.recipe_state is None
    moved = jax.tree.map(
        lambda a, b: not np.array_equal(np.asarray(a), np.asarray(b)),
        jax.device_get(state0.recipe_params),
        jax.device_get(state1.recipe_params),
    )
    assert any(jax.tree.leaves(moved))
    assert math.isfinite(metrics["loss"])


def test_queue_rotation_and_key_ema_in_step():
    """One step writes exactly 2B detached KEY rows at the pointer and
    advances it (untouched ring rows keep their seeded init), and the
    momentum key encoder EMAs toward the online params — all in-program."""
    batch = 4  # 2B = 8 rows/step into a 16-ring
    state0, recipe, _ = _tiny_recipe("simclr", n_steps=0, batch=batch,
                                     moco_queue=16)
    q0 = np.asarray(jax.device_get(state0.recipe_state["queue_emb"]))
    key0 = jax.device_get(state0.recipe_state["key_params"])
    state1, _, _ = _tiny_recipe("simclr", n_steps=1, batch=batch,
                                moco_queue=16)
    q1 = np.asarray(jax.device_get(state1.recipe_state["queue_emb"]))
    assert int(state1.recipe_state["queue_ptr"]) == 8
    assert not np.array_equal(q1[:8], q0[:8])  # written
    np.testing.assert_array_equal(q1[8:], q0[8:])  # untouched
    np.testing.assert_allclose(  # unit rows: normalized keys landed
        np.linalg.norm(q1[:8], axis=1), 1.0, rtol=1e-5,
    )
    # key encoder EMA'd exactly: m*key0 + (1-m)*new_online
    m = recipe.ema_momentum
    expect = jax.tree.map(
        lambda k, o: m * np.asarray(k) + (1 - m) * np.asarray(o),
        key0, jax.device_get(state1.params),
    )
    got = jax.device_get(state1.recipe_state["key_params"])
    for a, b in zip(jax.tree.leaves(expect), jax.tree.leaves(got)):
        np.testing.assert_allclose(a, b, rtol=2e-5, atol=1e-6)
    state2, _, _ = _tiny_recipe("simclr", n_steps=2, batch=batch,
                                moco_queue=16)
    assert int(state2.recipe_state["queue_ptr"]) == 0  # wrapped


def test_vicreg_metrics_stream_through_the_ring_keys():
    _, recipe, metrics = _tiny_recipe("vicreg", n_steps=1)
    expected = metric_keys(extra=recipe.metric_keys)
    assert tuple(sorted(metrics)) == expected
    for k in recipe.metric_keys:
        assert math.isfinite(metrics[k])


# ------------------------------------------------------ checkpoint hygiene


def _byol_state_and_cfg():
    cfg = config_lib.SupConConfig(
        model="resnet10", feat_dim=16, predictor_hidden=32, batch_size=4,
        recipe="byol",
    )
    config_lib.validate_recipe(cfg)
    model = SupConResNet(model_name="resnet10", feat_dim=16)
    tx = make_optimizer(0.1)
    state = create_train_state(
        model, tx, jax.random.key(0), jnp.zeros((2, SIZE, SIZE, 3))
    )
    return recipes_lib.attach_for_config(cfg, model, state), model


def test_recipe_checkpoint_roundtrip_and_cross_recipe_hygiene(
    tmp_path, caplog
):
    import logging

    from simclr_pytorch_distributed_tpu.utils.checkpoint import (
        restore_checkpoint,
        save_checkpoint,
    )

    (state, recipe), model = _byol_state_and_cfg()
    state, _, _ = _tiny_recipe("byol", n_steps=1)
    save_checkpoint(
        str(tmp_path), "ckpt", state, epoch=1,
        extra_meta={"recipe": "byol", "moco_queue": 0},
    )
    saved_slots = jax.device_get({
        "p": state.recipe_params, "o": state.recipe_opt_state,
        "s": state.recipe_state,
    })

    # same recipe: the slots restore bitwise
    (abstract, _), _ = _byol_state_and_cfg()
    restored, meta = restore_checkpoint(
        str(tmp_path / "ckpt"), abstract, recipe="byol"
    )
    assert meta["recipe"] == "byol"
    assert_trees_bitwise(saved_slots, {
        "p": restored.recipe_params, "o": restored.recipe_opt_state,
        "s": restored.recipe_state,
    })

    # byol ckpt resumed under supcon (slot-free): encoder restores, the
    # recipe payload is loudly ignored
    cfg_sc = config_lib.SupConConfig(recipe="supcon", batch_size=4,
                                     feat_dim=16)
    config_lib.validate_recipe(cfg_sc)
    model2 = SupConResNet(model_name="resnet10", feat_dim=16)
    tx = make_optimizer(0.1)
    plain = create_train_state(
        model2, tx, jax.random.key(0), jnp.zeros((2, SIZE, SIZE, 3))
    )
    with caplog.at_level(logging.WARNING):
        restored_sc, _ = restore_checkpoint(
            str(tmp_path / "ckpt"), plain, recipe="supcon"
        )
    assert "recipe slots ignored" in caplog.text
    assert restored_sc.recipe_params is None
    assert restored_sc.recipe_state is None
    assert_trees_bitwise(restored_sc.params, state.params)

    # byol ckpt resumed under simsiam (different slot recipe): fresh init
    caplog.clear()
    cfg_ss = config_lib.SupConConfig(
        recipe="simsiam", batch_size=4, feat_dim=16, predictor_hidden=32,
    )
    config_lib.validate_recipe(cfg_ss)
    ss_state, _ = recipes_lib.attach_for_config(cfg_ss, model2, plain)
    fresh = jax.device_get(ss_state.recipe_params)
    with caplog.at_level(logging.WARNING):
        restored_ss, _ = restore_checkpoint(
            str(tmp_path / "ckpt"), ss_state, recipe="simsiam"
        )
    assert "recipe slots" in caplog.text and "start fresh" in caplog.text
    assert_trees_bitwise(fresh, restored_ss.recipe_params)


def test_queue_geometry_change_degrades_to_fresh(tmp_path, caplog):
    """Same recipe, different --moco_queue across a resume: the meta-
    recorded ring geometry gates the payload, so the queue/key-encoder
    slots re-initialize loudly instead of restoring a mismatched ring."""
    import logging

    from simclr_pytorch_distributed_tpu.utils.checkpoint import (
        restore_checkpoint,
        save_checkpoint,
    )

    state, _, _ = _tiny_recipe("simclr", n_steps=1, moco_queue=16)
    save_checkpoint(
        str(tmp_path), "ckpt", state, epoch=1,
        extra_meta={"recipe": "simclr", "moco_queue": 16},
    )
    abstract, _, _ = _tiny_recipe("simclr", n_steps=0, moco_queue=24)
    fresh = jax.device_get(abstract.recipe_state)
    with caplog.at_level(logging.WARNING):
        restored, _ = restore_checkpoint(
            str(tmp_path / "ckpt"), abstract, recipe="simclr", moco_queue=24
        )
    assert "ring geometry changed" in caplog.text
    assert_trees_bitwise(fresh, restored.recipe_state)
    # same geometry restores bitwise
    abstract2, _, _ = _tiny_recipe("simclr", n_steps=0, moco_queue=16)
    restored2, _ = restore_checkpoint(
        str(tmp_path / "ckpt"), abstract2, recipe="simclr", moco_queue=16
    )
    assert_trees_bitwise(
        jax.device_get(state.recipe_state), restored2.recipe_state
    )


def test_supcon_ckpt_resumed_under_byol_degrades_to_fresh(tmp_path, caplog):
    import logging

    from simclr_pytorch_distributed_tpu.utils.checkpoint import (
        restore_checkpoint,
        save_checkpoint,
    )

    state, _, _ = _tiny_recipe("supcon", n_steps=1)
    assert state.recipe_params is None  # slot-free checkpoint
    save_checkpoint(
        str(tmp_path), "ckpt", state, epoch=1,
        extra_meta={"recipe": "supcon", "moco_queue": 0},
    )
    (byol_state, _), _ = _byol_state_and_cfg()
    fresh = jax.device_get({
        "p": byol_state.recipe_params, "s": byol_state.recipe_state,
    })
    with caplog.at_level(logging.WARNING):
        restored, _ = restore_checkpoint(
            str(tmp_path / "ckpt"), byol_state, recipe="byol"
        )
    assert "no recipe payload" in caplog.text
    assert_trees_bitwise(fresh, {
        "p": restored.recipe_params, "s": restored.recipe_state,
    })
    assert_trees_bitwise(restored.params, state.params)


# ------------------------------------------------- driver-level proofs


@pytest.fixture
def tiny_driver(monkeypatch):
    """The test_telemetry tiny-driver rig: 200-sample size-8 synthetic set,
    1-device mesh (multi-way sharding is test_distributed's job)."""
    from simclr_pytorch_distributed_tpu.data import cifar as cifar_lib
    from simclr_pytorch_distributed_tpu.parallel import mesh as mesh_lib
    from simclr_pytorch_distributed_tpu.train import supcon as supcon_driver

    orig = cifar_lib.synthetic_dataset

    def small(n=2048, num_classes=10, seed=0, size=32):
        return orig(n=200, num_classes=num_classes, seed=seed, size=SIZE)

    monkeypatch.setattr(cifar_lib, "synthetic_dataset", small)

    def limited_create_mesh(devices=None, **kw):
        if devices is None:
            devices = jax.devices()[:1]
        return mesh_lib.create_mesh(devices=devices, **kw)

    monkeypatch.setattr(supcon_driver, "create_mesh", limited_create_mesh)
    return supcon_driver


def _driver_cfg(tmp_path, sub, **over):
    base = dict(
        model="resnet10", dataset="synthetic", batch_size=32, epochs=1,
        learning_rate=0.05, cosine=True, save_freq=5, print_freq=2,
        size=SIZE, workdir=str(tmp_path / sub), seed=0, method="SimCLR",
        telemetry="sync", data_placement="host", predictor_hidden=32,
        feat_dim=16,
    )
    base.update(over)
    return config_lib.finalize_supcon(config_lib.SupConConfig(**base))


RECIPE_SMOKE_ARMS = [
    ("byol", {}),
    ("simsiam", {}),
    ("vicreg", {}),
    ("simclr", {"moco_queue": 128}),  # 2B=64 rows/step into a 128-ring
]


@pytest.mark.parametrize("recipe,over", RECIPE_SMOKE_ARMS,
                         ids=[r for r, _ in RECIPE_SMOKE_ARMS])
def test_recipe_driver_smoke_host(tmp_path, tiny_driver, recipe, over):
    """The recipe<->driver consume-signature contract, host placement: one
    sync-mode epoch per recipe through the REAL trainer (the
    test_all_drivers_flush_boundary_smoke convention — sync telemetry runs
    every window job inline, so a diverged signature raises HERE, in
    tier-1). The device-placement half of this smoke is the zero-sync
    transfer proof below."""
    cfg = _driver_cfg(tmp_path, recipe, recipe=recipe, **over)
    state = tiny_driver.run(cfg)
    assert int(state.step) == 5  # 160 train samples / batch 32


@pytest.mark.parametrize("recipe,over", RECIPE_SMOKE_ARMS,
                         ids=[r for r, _ in RECIPE_SMOKE_ARMS])
def test_recipe_zero_sync_device_placement(
    tmp_path, tiny_driver, monkeypatch, recipe, over
):
    """The PR-4/PR-5 mechanical transfer contract re-proven per recipe
    (the acceptance bar): one real epoch under DEVICE placement with
    health_freq=1 + the online probe + the recipe on counts EXACTLY 3 ring
    D2H (windows 2+2+1) and 1 index upload — EMA updates, queue rotation,
    and the extra target forward all stay in-program. Doubles as the
    device-placement driver smoke."""
    from simclr_pytorch_distributed_tpu.data import device_store
    from simclr_pytorch_distributed_tpu.utils.telemetry import TelemetrySession

    counts = {"ring": 0, "index": 0}

    class CountingSession(TelemetrySession):
        def __init__(self, window, keys, mode="async", **kw):
            def counting_get(x):
                counts["ring"] += 1
                return jax.device_get(x)

            super().__init__(window, keys, mode, device_get=counting_get, **kw)

    real_store = device_store.DeviceStore

    class CountingStore(real_store):
        def __init__(self, loader, mesh, **kw):
            super().__init__(loader, mesh, **kw)
            inner = self._index_put

            def counting_put(idx):
                counts["index"] += 1
                return inner(idx)

            self._index_put = counting_put

    monkeypatch.setattr(tiny_driver, "TelemetrySession", CountingSession)
    monkeypatch.setattr(device_store, "DeviceStore", CountingStore)

    cfg = _driver_cfg(
        tmp_path, recipe, recipe=recipe, data_placement="device",
        flight_recorder="on", health_freq=1, online_probe="on",
        health_policy="warn", **over,
    )
    tiny_driver.run(cfg)
    assert counts == {"ring": 3, "index": 1}

    # the health stream flowed through those same transfers, recipe keys
    # included, and the recipe marker landed on the recorder
    events_path = os.path.join(cfg.save_folder, "events.jsonl")
    events = [json.loads(x) for x in open(events_path).read().splitlines()]
    windows = [e for e in events if e["name"] == "health_window"]
    assert len(windows) == 3
    markers = [e for e in events if e["name"] == "run_recipe"]
    assert markers and markers[0]["args"]["recipe"] == recipe
    if recipe == "vicreg":
        last = windows[-1]["args"]
        for k in ("vicreg_cov", "vicreg_inv", "vicreg_var"):
            assert k in last and math.isfinite(last[k])
    assert not [e for e in events if e["name"] == "health_alarm"]


@pytest.mark.parametrize("placement", ["host", "device"])
def test_supcon_recipe_bitwise_vs_prerefactor_driver(
    tmp_path, tiny_driver, placement
):
    """THE acceptance bar: --recipe supcon through the interface produces
    bitwise-identical params to the pre-refactor update over a 2-epoch
    REAL-driver run, host and device placement. The pre-refactor arm is
    the retained inline path (make_fused_update(recipe=None)) — the
    contrastive term itself is shared, so this pins the neutrality of
    everything the refactor wrapped around it."""
    orig_mfu = tiny_driver.make_fused_update

    def run(arm):
        if arm == "legacy":
            def legacy_mfu(*a, **kw):
                kw["recipe"] = None
                return orig_mfu(*a, **kw)

            tiny_driver.make_fused_update = legacy_mfu
        try:
            cfg = _driver_cfg(
                tmp_path, f"{placement}_{arm}", recipe="supcon",
                method="SupCon", epochs=2, data_placement=placement,
            )
            return tiny_driver.run(cfg)
        finally:
            tiny_driver.make_fused_update = orig_mfu

    s_recipe = run("recipe")
    s_legacy = run("legacy")
    assert int(s_recipe.step) == 10
    assert_trees_bitwise(s_recipe.params, s_legacy.params)
    assert_trees_bitwise(s_recipe.batch_stats, s_legacy.batch_stats)
    assert_trees_bitwise(s_recipe.opt_state, s_legacy.opt_state)


COLLAPSE_ARMS = [
    ("byol", {"byol_predictor": "none"}),  # the ABLATED form: no asymmetry
    ("simsiam", {}),
    ("vicreg", {}),
]


@pytest.mark.parametrize("recipe,over", COLLAPSE_ARMS,
                         ids=[r for r, _ in COLLAPSE_ARMS])
def test_recipe_collapse_injection_trips_code3_abort(
    tmp_path, tiny_driver, monkeypatch, recipe, over
):
    """Per-recipe collapse injection (the test_health pattern): constant
    embeddings through the REAL driver under each recipe must trip the
    per-recipe windowed detector and — under --health_policy abort — exit
    with the typed RepresentationHealthError via the collective code-3
    exchange. The BYOL arm runs predictor-ABLATED (--byol_predictor none):
    the known-collapsing configuration the raised eff-rank bar exists for.
    """
    from simclr_pytorch_distributed_tpu.recipes import byol as byol_mod

    def constant_forward(model, params, batch_stats, images, *, train=True,
                         with_features=False):
        B = images.shape[0]
        feats = jnp.ones((2 * B, 16), jnp.float32)
        if with_features:
            return (feats, feats), batch_stats
        return feats, batch_stats

    # both forward call sites: the step's online forward AND the BYOL
    # target forward (recipes/byol.py binds the name at import)
    monkeypatch.setattr(supcon_step, "two_view_forward", constant_forward)
    monkeypatch.setattr(byol_mod, "two_view_forward", constant_forward)

    cfg = _driver_cfg(
        tmp_path, recipe, recipe=recipe, epochs=2,
        health_freq=1, health_policy="abort", flight_recorder="on", **over,
    )
    with pytest.raises(RepresentationHealthError, match="collapse"):
        tiny_driver.run(cfg)

    events_path = os.path.join(cfg.save_folder, "events.jsonl")
    events = [json.loads(x) for x in open(events_path).read().splitlines()]
    alarms = [e for e in events if e["name"] == "health_alarm"]
    assert alarms and alarms[0]["args"]["policy"] == "abort"
    failures = [e for e in events if e["name"] == "flush_failure"]
    assert failures and failures[0]["args"]["code"] == 3


# ------------------------------------- offline readers + the ratchet gate


def _window_event(step, **over):
    args = {
        "health_align": 0.5, "health_con_top1": 30.0,
        "health_eff_rank": 2.5, "health_grad_norm": 5.0,
        "health_neg_max": 0.7, "health_neg_mean": 0.4, "health_unif": -2.0,
        "step": step,
    }
    args.update(over)
    return {"name": "health_window", "track": "health", "ph": "i",
            "ts": 0.1 * step, "args": args}


def test_health_report_recipe_aware_collapse_signature():
    """eff_rank 2.5 is healthy under the contrastive bar (2.0) but COLLAPSED
    under the byol/simsiam bar (3.0): the offline reader must reach the
    same verdict as the live per-recipe monitor, keyed off the stream's
    run_recipe event (or the --recipe override)."""
    import scripts.health_report as hr

    marker = {"name": "run_recipe", "track": "main:guard", "ph": "i",
              "ts": 0.0, "args": {"recipe": "byol", "moco_queue": 0}}
    rep = hr.build_report([marker, _window_event(2)])
    assert rep["recipe"] == "byol"
    assert rep["thresholds"]["eff_rank_min"] == 3.0
    assert any(f["kind"] == "collapse_signature" for f in rep["findings"])

    # same stream, contrastive recipe: no finding
    marker_sc = {"name": "run_recipe", "track": "main:guard", "ph": "i",
                 "ts": 0.0, "args": {"recipe": "simclr", "moco_queue": 0}}
    rep = hr.build_report([marker_sc, _window_event(2)])
    assert not any(
        f["kind"] == "collapse_signature" for f in rep["findings"]
    )

    # explicit override beats the recorded marker
    rep = hr.build_report([marker_sc, _window_event(2)], recipe="simsiam")
    assert rep["recipe"] == "simsiam"
    assert any(f["kind"] == "collapse_signature" for f in rep["findings"])


def _eval_artifact(device="cpu", **over):
    base = {
        "schema": "recipes_eval/v1", "device": device, "smoke": True,
        "config": {},
        "bit_identity": {"ok": True, "epochs": 2, "steps": 10,
                         "placements": {"host": True, "device": True}},
        "recipes": {
            name: {"recipe": name.split("_")[0], "moco_queue": 0,
                   "probe_best_top1": 60.0, "probe_first_top1": 12.0,
                   "probe_last_top1": 55.0, "windows": 3, "alarms": 0,
                   "consistency_ok": True,
                   "thresholds": {"eff_rank_min": 2.0}}
            for name in ("supcon", "byol", "simsiam", "vicreg",
                         "simclr_queue")
        },
    }
    base.update(over)
    return base


def test_recipe_gate_record_pass_fail_and_skip():
    import scripts.ratchet as ratchet

    rec = ratchet.recipe_gate_record(_eval_artifact())
    assert rec["ok"] and "skipped" not in rec

    # bit-identity failure binds everywhere
    bad = _eval_artifact(device="tpu")
    bad["bit_identity"] = {"ok": False,
                           "placements": {"host": True, "device": False}}
    rec = ratchet.recipe_gate_record(bad)
    assert not rec["ok"] and "bit-identity" in rec["error"]

    # a collapse alarm binds everywhere
    bad = _eval_artifact(device="tpu")
    bad["recipes"]["byol"]["alarms"] = 2
    rec = ratchet.recipe_gate_record(bad)
    assert not rec["ok"] and "false positive" in rec["error"]

    # probe bar binds on CPU...
    low = _eval_artifact()
    low["recipes"]["simsiam"]["probe_best_top1"] = 11.0
    rec = ratchet.recipe_gate_record(low)
    assert not rec["ok"] and "did not learn" in rec["error"]
    # ...and pass-skips elsewhere with the reason on record
    low_tpu = _eval_artifact(device="tpu")
    low_tpu["recipes"]["simsiam"]["probe_best_top1"] = 11.0
    rec = ratchet.recipe_gate_record(low_tpu)
    assert rec["ok"] and "calibrated" in rec["skipped"]

    # a missing arm fails
    missing = _eval_artifact()
    del missing["recipes"]["vicreg"]
    rec = ratchet.recipe_gate_record(missing)
    assert not rec["ok"] and "missing" in rec["error"]


def test_recipes_eval_build_output_schema_pinned():
    import scripts.recipes_eval as ev

    out = ev.build_output(
        "cpu", True, {"epochs": 1}, {"ok": True, "placements": {}}, {},
    )
    assert set(out) == {"schema", "device", "smoke", "config",
                        "bit_identity", "recipes"}
    assert out["schema"] == ev.SCHEMA
    # the bars the gate binds against exist for every shipped probe arm
    import scripts.ratchet as ratchet

    assert set(ratchet.RECIPE_PROBE_CPU_BARS) == {
        name for name, _ in ev.PROBE_ARMS
    }
