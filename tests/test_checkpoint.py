"""Checkpoint save / full-state resume / model-only warm-start roundtrips."""

import jax
import jax.numpy as jnp
import numpy as np

from simclr_pytorch_distributed_tpu.models import SupConResNet
from simclr_pytorch_distributed_tpu.train.state import create_train_state, make_optimizer
from simclr_pytorch_distributed_tpu.utils.checkpoint import (
    load_pretrained_variables,
    restore_checkpoint,
    save_checkpoint,
)


def small_state(seed=0):
    model = SupConResNet(model_name="resnet18")
    tx = make_optimizer(0.1, momentum=0.9, weight_decay=1e-4)
    state = create_train_state(
        model, tx, jax.random.key(seed), jnp.zeros((2, 8, 8, 3))
    )
    return model, tx, state


def test_save_restore_roundtrip(tmp_path):
    _, _, state = small_state()
    state = state.replace(
        step=jnp.asarray(7, jnp.int32), record_norm_mean=jnp.asarray(3.25)
    )
    path = save_checkpoint(str(tmp_path), "ckpt_epoch_7", state,
                           config={"temp": 0.5}, epoch=7)
    _, _, fresh = small_state(seed=1)
    restored, meta = restore_checkpoint(path, fresh)
    assert int(restored.step) == 7
    assert float(restored.record_norm_mean) == 3.25
    assert meta["epoch"] == 7
    assert meta["config"]["temp"] == 0.5
    for a, b in zip(jax.tree.leaves(state.params), jax.tree.leaves(restored.params)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    for a, b in zip(jax.tree.leaves(state.opt_state), jax.tree.leaves(restored.opt_state)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


def test_model_only_warm_start(tmp_path):
    """Probe/warm-start path: restore params+batch_stats without opt structure
    (reference main_supcon.py:216-220, main_linear.py:125-142)."""
    _, _, state = small_state()
    path = save_checkpoint(str(tmp_path), "last", state, epoch=3)

    _, _, other = small_state(seed=2)
    variables = load_pretrained_variables(
        path, {"params": other.params, "batch_stats": other.batch_stats}
    )
    for a, b in zip(jax.tree.leaves(state.params), jax.tree.leaves(variables["params"])):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    for a, b in zip(
        jax.tree.leaves(state.batch_stats), jax.tree.leaves(variables["batch_stats"])
    ):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


def test_async_save_roundtrip(tmp_path):
    """block=False saves complete after wait_for_saves() and restore exactly.

    meta.json (the completeness marker) must NOT exist until the payload
    writes commit at wait_for_saves()."""
    import os

    from simclr_pytorch_distributed_tpu.utils.checkpoint import wait_for_saves

    _, _, state = small_state()
    save_checkpoint(str(tmp_path), "async_ck", state, epoch=3, block=False)
    assert not os.path.exists(tmp_path / "async_ck" / "meta.json")
    wait_for_saves()
    assert os.path.exists(tmp_path / "async_ck" / "meta.json")
    restored, meta = restore_checkpoint(str(tmp_path / "async_ck"), state)
    assert meta["epoch"] == 3
    for a, b in zip(jax.tree.leaves(state.params), jax.tree.leaves(restored.params)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


def test_restore_interrupted_checkpoint_fails_loudly(tmp_path):
    """A checkpoint whose meta.json never got stamped (crash mid-save) must
    refuse to resume rather than silently restarting at epoch 1."""
    import os

    import pytest

    _, _, state = small_state()
    path = save_checkpoint(str(tmp_path), "ck", state, epoch=5)
    os.remove(os.path.join(path, "meta.json"))
    with pytest.raises(RuntimeError, match="interrupted"):
        restore_checkpoint(path, state)


def test_resolve_resume_picks_latest_complete(tmp_path):
    """--resume <run_dir> resolves to the highest-epoch COMPLETE checkpoint."""
    import os

    import pytest

    from simclr_pytorch_distributed_tpu.utils.checkpoint import (
        resolve_resume_path,
    )

    _, _, state = small_state()
    save_checkpoint(str(tmp_path), "ckpt_epoch_2", state, epoch=2)
    p5 = save_checkpoint(str(tmp_path), "crash_epoch_5", state, epoch=5)
    p9 = save_checkpoint(str(tmp_path), "ckpt_epoch_9", state, epoch=9)
    # an interrupted save (no meta.json) must not win
    os.remove(os.path.join(p9, "meta.json"))
    assert resolve_resume_path(str(tmp_path)) == p5
    # a direct checkpoint path passes through unchanged
    assert resolve_resume_path(p5) == p5
    with pytest.raises(FileNotFoundError):
        resolve_resume_path(str(tmp_path / "empty_nothing_here"))


def test_resolve_resume_epoch_tie_prefers_scheduled_save(tmp_path):
    """crash_epoch_N+1 records epoch N, tying with ckpt_epoch_N: the
    scheduled save wins the tie explicitly (not by path lexicography)."""
    from simclr_pytorch_distributed_tpu.utils.checkpoint import (
        resolve_resume_path,
    )

    _, _, state = small_state()
    p_ckpt = save_checkpoint(str(tmp_path), "ckpt_epoch_4", state, epoch=4)
    save_checkpoint(str(tmp_path), "crash_epoch_5", state, epoch=4)
    assert resolve_resume_path(str(tmp_path)) == p_ckpt


def test_warm_start_accepts_run_dir_and_model_only(tmp_path):
    """--ckpt takes a run dir (resolved to latest complete) or a bare
    model-only payload dir (no meta.json needed for variables-only loads)."""
    import jax
    import numpy as np

    _, _, state = small_state()
    save_checkpoint(str(tmp_path), "ckpt_epoch_3", state, epoch=3)
    abstract = {"params": state.params, "batch_stats": state.batch_stats}
    via_run_dir = load_pretrained_variables(str(tmp_path), abstract)
    for a, b in zip(jax.tree.leaves(state.params),
                    jax.tree.leaves(via_run_dir["params"])):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))

    from simclr_pytorch_distributed_tpu.utils.checkpoint import _save_tree

    bare = tmp_path / "bare_encoder"
    _save_tree(str(bare / "model"),
               {"params": state.params, "batch_stats": state.batch_stats})
    via_bare = load_pretrained_variables(str(bare), abstract)
    for a, b in zip(jax.tree.leaves(state.params),
                    jax.tree.leaves(via_bare["params"])):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


def test_resolve_resume_interrupted_checkpoint_diagnostic(tmp_path):
    """Pointing --resume at an interrupted checkpoint dir (payload, no
    meta.json) keeps the 'interrupted' diagnostic instead of claiming the
    dir contains no checkpoint."""
    import os

    import pytest

    from simclr_pytorch_distributed_tpu.utils.checkpoint import (
        resolve_resume_path,
    )

    _, _, state = small_state()
    path = save_checkpoint(str(tmp_path), "ckpt_epoch_4", state, epoch=4)
    os.remove(os.path.join(path, "meta.json"))
    with pytest.raises(RuntimeError, match="interrupted"):
        resolve_resume_path(path)


def test_save_load_classifier_roundtrip(tmp_path):
    import os

    from simclr_pytorch_distributed_tpu.utils.checkpoint import (
        load_classifier,
        save_classifier,
    )

    params = {"head": {"kernel": np.arange(12.0, dtype=np.float32).reshape(3, 4),
                       "bias": np.zeros(4, np.float32)}}
    path = save_classifier(str(tmp_path), params, 87.5)
    assert os.path.exists(os.path.join(path, "meta.json"))
    restored = load_classifier(path, params)
    for a, b in zip(jax.tree.leaves(params), jax.tree.leaves(restored)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


def test_save_checkpoint_records_step_in_epoch(tmp_path):
    """Mid-epoch emergency saves stamp (epoch, step_in_epoch) — the full
    dataset-position coordinate a bit-identical resume needs."""
    import json
    import os

    _, _, state = small_state()
    path = save_checkpoint(
        str(tmp_path), "preempt_epoch_3_step_7", state, epoch=2, step_in_epoch=7
    )
    with open(os.path.join(path, "meta.json")) as f:
        meta = json.load(f)
    assert meta["epoch"] == 2 and meta["step_in_epoch"] == 7

    restored, meta2 = restore_checkpoint(path, state)
    assert meta2["step_in_epoch"] == 7


def test_resolve_resume_corrupt_meta_skipped_for_older_complete(tmp_path):
    """A truncated/corrupt meta.json (kill -9 mid-stamp, torn disk write)
    must NEVER win resolution: the older complete save is chosen, and the
    corrupt one is skipped silently rather than crashing the resolver."""
    import os

    from simclr_pytorch_distributed_tpu.utils.checkpoint import (
        resolve_resume_path,
    )

    _, _, state = small_state()
    p3 = save_checkpoint(str(tmp_path), "ckpt_epoch_3", state, epoch=3)
    p9 = save_checkpoint(str(tmp_path), "ckpt_epoch_9", state, epoch=9)
    # three corruption shapes: truncated JSON, garbage bytes, empty file
    with open(os.path.join(p9, "meta.json"), "w") as f:
        f.write('{"epoch": 9, "conf')
    p7 = save_checkpoint(str(tmp_path), "crash_epoch_7", state, epoch=7)
    with open(os.path.join(p7, "meta.json"), "wb") as f:
        f.write(b"\x00\xff\x00garbage")
    p5 = save_checkpoint(str(tmp_path), "preempt_epoch_5_step_2", state,
                         epoch=5, step_in_epoch=2)
    with open(os.path.join(p5, "meta.json"), "w") as f:
        f.write("")
    assert resolve_resume_path(str(tmp_path)) == p3


def test_resolve_resume_mid_epoch_save_outranks_prior_boundary(tmp_path):
    """Progress ordering: a preemption save at (epoch 4, step 5) holds MORE
    progress than the scheduled ckpt_epoch_4 (epoch 4, step 0) and less than
    ckpt_epoch_5 — resolution follows (epoch, step_in_epoch)."""
    from simclr_pytorch_distributed_tpu.utils.checkpoint import (
        resolve_resume_path,
    )

    _, _, state = small_state()
    save_checkpoint(str(tmp_path), "ckpt_epoch_4", state, epoch=4)
    p_mid = save_checkpoint(str(tmp_path), "preempt_epoch_5_step_5", state,
                            epoch=4, step_in_epoch=5)
    assert resolve_resume_path(str(tmp_path)) == p_mid

    p5 = save_checkpoint(str(tmp_path), "ckpt_epoch_5", state, epoch=5)
    assert resolve_resume_path(str(tmp_path)) == p5


def test_resolve_resume_tie_prefers_scheduled_over_preempt(tmp_path):
    """An epoch-boundary preemption save ties a scheduled save of the same
    epoch at (epoch, 0): the scheduled save wins, same rule as crash_*."""
    from simclr_pytorch_distributed_tpu.utils.checkpoint import (
        resolve_resume_path,
    )

    _, _, state = small_state()
    save_checkpoint(str(tmp_path), "preempt_epoch_6", state, epoch=6)
    p_sched = save_checkpoint(str(tmp_path), "ckpt_epoch_6", state, epoch=6)
    assert resolve_resume_path(str(tmp_path)) == p_sched


def test_resume_position_decode_and_garbage_tolerance():
    """(epoch, step_in_epoch) -> (start_epoch, start_step); a full-epoch or
    unparseable offset degrades to the next epoch boundary (matching what
    resolve_resume_path tolerates) instead of crashing the driver."""
    from simclr_pytorch_distributed_tpu.utils.checkpoint import resume_position

    assert resume_position({"epoch": 3, "step_in_epoch": 7}, 10) == (4, 7)
    assert resume_position({"epoch": 3}, 10) == (4, 0)
    assert resume_position({}, 10) == (1, 0)
    assert resume_position({"epoch": 3, "step_in_epoch": 12}, 10) == (5, 0)
    assert resume_position({"epoch": 3, "step_in_epoch": "abc"}, 10) == (4, 0)
    assert resume_position({"epoch": 3, "step_in_epoch": None}, 10) == (4, 0)


def test_save_checkpoint_extra_meta_roundtrip(tmp_path):
    """Driver-side run state (rollback damping, best-acc watermark) rides
    checkpoint meta and comes back on restore."""
    import json
    import os

    _, _, state = small_state()
    path = save_checkpoint(
        str(tmp_path), "ckpt_epoch_1", state, epoch=1,
        extra_meta={"lr_scale": 0.25, "rollbacks": 2, "best_acc": 61.5},
    )
    with open(os.path.join(path, "meta.json")) as f:
        meta = json.load(f)
    assert meta["lr_scale"] == 0.25 and meta["rollbacks"] == 2
    assert meta["best_acc"] == 61.5
    # reserved keys win over extra_meta collisions
    assert meta["epoch"] == 1


# ----------------------------------------------------- elastic (mesh-agnostic)


def _mesh_of(n):
    from simclr_pytorch_distributed_tpu.parallel.mesh import create_mesh

    return create_mesh(jax.devices()[:n])


def test_restore_is_mesh_shape_agnostic(tmp_path):
    """The elastic-resume core contract (docs/RESILIENCE.md): a checkpoint
    saved under mesh shape A restores under mesh shape B with the full
    TrainState — params, batch_stats, OPTIMIZER momentum, step — intact,
    resharded by orbax onto the current mesh at load (no host round-trip
    through a single-device layout)."""
    from simclr_pytorch_distributed_tpu.parallel.mesh import state_sharding

    _, tx, state = small_state()
    state = state.replace(step=jnp.asarray(42, jnp.int32))
    # mutate the optimizer state so "restored intact" is a real claim, not
    # an all-zeros coincidence
    grads = jax.tree.map(lambda p: jnp.full_like(p, 0.125), state.params)
    updates, opt_state = tx.update(grads, state.opt_state, state.params)
    state = state.replace(opt_state=opt_state)

    mesh_a, mesh_b = _mesh_of(8), _mesh_of(2)
    state_a = jax.device_put(state, state_sharding(mesh_a, state))
    save_checkpoint(str(tmp_path), "ckpt_epoch_1", state_a,
                    config={"trial": "elastic"}, epoch=1)

    _, _, fresh = small_state(seed=3)
    restored, meta = restore_checkpoint(
        str(tmp_path) + "/ckpt_epoch_1", fresh, mesh=mesh_b
    )
    assert meta["devices"] == jax.device_count()  # the SAVING topology
    assert int(restored.step) == 42
    for a, b in zip(jax.tree.leaves(state.params), jax.tree.leaves(restored.params)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    for a, b in zip(
        jax.tree.leaves(state.opt_state), jax.tree.leaves(restored.opt_state)
    ):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    # every restored leaf is COMMITTED to mesh B (resharded on load)
    for leaf in jax.tree.leaves(restored.params):
        assert set(leaf.sharding.device_set) <= set(mesh_b.devices.flatten())


def test_restore_mesh_change_warns_and_same_shape_does_not(tmp_path, caplog):
    """An elastic resume is legal but loud: restoring under a different
    device count names the documented consequences (per-device BN, --ngpu);
    a same-shape resume stays quiet."""
    import logging

    _, _, state = small_state()
    save_checkpoint(str(tmp_path), "ckpt_epoch_1", state, epoch=1)
    _, _, fresh = small_state(seed=1)

    with caplog.at_level(logging.WARNING):
        restore_checkpoint(str(tmp_path) + "/ckpt_epoch_1", fresh)
    assert not [r for r in caplog.records if "elastic resume" in r.message]

    # forge a different saved topology (the same-process test cannot change
    # jax.device_count between save and restore)
    import json
    import os

    meta_path = os.path.join(tmp_path, "ckpt_epoch_1", "meta.json")
    with open(meta_path) as f:
        meta = json.load(f)
    meta["devices"] = 4096
    with open(meta_path, "w") as f:
        json.dump(meta, f)
    caplog.clear()
    with caplog.at_level(logging.WARNING):
        restore_checkpoint(str(tmp_path) + "/ckpt_epoch_1", fresh)
    warned = [r for r in caplog.records if "elastic resume" in r.message]
    assert warned and "4096" in warned[0].getMessage()


def test_restore_with_mesh_feeds_a_donating_jitted_step(tmp_path):
    """The re-owning contract survives the sharded restore path: leaves
    restored onto a mesh must still be safe to DONATE to a jitted update
    (the heap-corruption regression restore_checkpoint documents)."""
    mesh_b = _mesh_of(2)
    _, _, state = small_state()
    save_checkpoint(str(tmp_path), "last", state, epoch=1)
    _, _, fresh = small_state(seed=1)
    restored, _ = restore_checkpoint(str(tmp_path) + "/last", fresh, mesh=mesh_b)

    @jax.jit
    def bump(tree):
        return jax.tree.map(lambda x: x + 1, tree)

    donating = jax.jit(lambda t: jax.tree.map(lambda x: x * 2, t),
                       donate_argnums=(0,))
    out = donating(restored.params)
    ref = bump(out)  # dispatch more work against the donated result
    assert all(np.all(np.isfinite(np.asarray(x))) for x in jax.tree.leaves(ref))
