"""HTTP endpoint tests (serve/server.py) — real sockets, fake engine.

The handler is bound to a DynamicBatcher + a ``stats_fn`` callable, so these
tests drive the REAL wire protocol (status codes, both JSON image encodings,
backpressure/timeout mapping) through a per-row fake embed function — no jax
compiles, except the one CLI-plumbing test that builds the real
``--dtype bf16`` stack through ``build_stack``. The full
engine→batcher→HTTP path runs in ``scripts/serve_bench.py --smoke``
(tests/test_scripts.py).
"""

import base64
import json
import urllib.error
import urllib.request

import numpy as np
import pytest

from simclr_pytorch_distributed_tpu.serve.batcher import DynamicBatcher
from simclr_pytorch_distributed_tpu.serve.server import (
    create_server,
    start_in_thread,
)

pytestmark = pytest.mark.serve

H = W = 2


def fake_embed(images):
    images = np.asarray(images)
    return images.reshape(len(images), -1).sum(axis=1, keepdims=True).astype(np.float32)


def post(base, path, obj, timeout=10):
    req = urllib.request.Request(
        f"{base}{path}", data=json.dumps(obj).encode(),
        headers={"Content-Type": "application/json"},
    )
    with urllib.request.urlopen(req, timeout=timeout) as r:
        return r.status, json.loads(r.read())


def get(base, path, timeout=10):
    with urllib.request.urlopen(f"{base}{path}", timeout=timeout) as r:
        return r.status, json.loads(r.read())


@pytest.fixture()
def served():
    batcher = DynamicBatcher(fake_embed, max_batch=8, max_wait_ms=2)
    server = create_server(batcher, lambda: {"batcher": batcher.stats()}, port=0)
    start_in_thread(server)
    host, port = server.server_address[:2]
    yield f"http://{host}:{port}", batcher
    server.shutdown()
    server.server_close()
    batcher.close()


def test_healthz_and_stats(served):
    base, _ = served
    assert get(base, "/healthz") == (200, {"status": "ok"})
    status, stats = get(base, "/stats")
    assert status == 200 and "batcher" in stats


def test_embed_nested_list_and_b64_agree(served):
    base, _ = served
    images = np.arange(2 * H * W * 3, dtype=np.uint8).reshape(2, H, W, 3)
    s1, r1 = post(base, "/embed", {"images": images.tolist()})
    s2, r2 = post(base, "/embed", {
        "images_b64": base64.b64encode(images.tobytes()).decode(),
        "shape": list(images.shape),
    })
    assert s1 == s2 == 200
    assert r1["n"] == 2 and r1["dim"] == 1
    np.testing.assert_array_equal(r1["embeddings"], r2["embeddings"])
    np.testing.assert_allclose(
        np.asarray(r1["embeddings"]), fake_embed(images)
    )


@pytest.mark.parametrize("body", [
    {"images": [[1, 2], [3, 4]]},              # wrong rank
    {"images": [[[["x"]]]]},                   # non-numeric
    {"images_b64": "AAAA", "shape": [1, H, W]},  # bad shape length
    {"images_b64": "AAAA", "shape": [4, H, W, 3]},  # byte count mismatch
    {"images_b64": "not base64!!", "shape": [1, 1, 1, 3]},
    {"wrong_key": 1},
    {"images": [[[[0, 0, 0]]]], "timeout_ms": "100"},  # non-numeric timeout
])
def test_embed_bad_input_is_400(served, body):
    base, _ = served
    with pytest.raises(urllib.error.HTTPError) as exc:
        post(base, "/embed", body)
    assert exc.value.code == 400
    assert "error" in json.loads(exc.value.read())


def test_unknown_path_is_404(served):
    base, _ = served
    with pytest.raises(urllib.error.HTTPError) as exc:
        get(base, "/nope")
    assert exc.value.code == 404


def test_queue_full_maps_to_503_with_retry_after():
    # start=False: nothing drains, so the bounded queue actually fills
    batcher = DynamicBatcher(fake_embed, max_batch=8, max_queue=1, start=False)
    server = create_server(batcher, lambda: {}, port=0)
    start_in_thread(server)
    host, port = server.server_address[:2]
    base = f"http://{host}:{port}"
    try:
        batcher.submit(np.zeros((1, H, W, 3), np.uint8))  # occupy the queue
        with pytest.raises(urllib.error.HTTPError) as exc:
            post(base, "/embed", {"images": np.zeros((1, H, W, 3)).tolist()})
        assert exc.value.code == 503
        assert exc.value.headers["Retry-After"] == "1"
    finally:
        server.shutdown()
        server.server_close()
        batcher.close(drain=False)


def test_closed_batcher_maps_to_503_not_400():
    """A valid request hitting a closing server is retryable (503), not the
    client's fault (400)."""
    batcher = DynamicBatcher(fake_embed, max_batch=8, start=False)
    batcher.close()
    server = create_server(batcher, lambda: {}, port=0)
    start_in_thread(server)
    host, port = server.server_address[:2]
    try:
        with pytest.raises(urllib.error.HTTPError) as exc:
            post(f"http://{host}:{port}", "/embed",
                 {"images": np.zeros((1, H, W, 3), np.uint8).tolist()})
        assert exc.value.code == 503
    finally:
        server.shutdown()
        server.server_close()


def test_oversized_content_length_replies_400_and_closes_connection():
    """Replying without reading the body must also drop the keep-alive
    connection — otherwise the unread bytes desync the next request."""
    import http.client

    batcher = DynamicBatcher(fake_embed, max_batch=8, max_wait_ms=2)
    server = create_server(batcher, lambda: {}, port=0)
    start_in_thread(server)
    host, port = server.server_address[:2]
    try:
        conn = http.client.HTTPConnection(host, port, timeout=10)
        conn.putrequest("POST", "/embed")
        conn.putheader("Content-Type", "application/json")
        conn.putheader("Content-Length", str(10**9))  # body never sent
        conn.endheaders()
        resp = conn.getresponse()
        assert resp.status == 400
        assert resp.getheader("Connection") == "close"
        resp.read()
        conn.close()
    finally:
        server.shutdown()
        server.server_close()
        batcher.close()


@pytest.mark.serve
def test_build_stack_cli_plumbing_bf16_and_pipeline_knobs():
    """--dtype bf16 / --max_inflight reach the engine and batcher through
    the CLI parser, and one real request flows through the full pipelined
    stack (assembler -> inflight window -> completer -> HTTP). The one test
    in this file that compiles (a single bf16 bucket-2 program)."""
    from simclr_pytorch_distributed_tpu.serve.server import (
        build_parser,
        build_stack,
    )

    args = build_parser().parse_args([
        "--model", "resnet10", "--buckets", "2", "--img_size", "8",
        "--dtype", "bf16", "--max_inflight", "3",
        "--max_inflight_images", "64", "--max_wait_ms", "1", "--port", "0",
    ])
    engine, batcher, server = build_stack(args)
    try:
        assert engine.dtype == "bf16"
        s = batcher.stats()
        assert s["max_inflight"] == 3 and s["max_inflight_images"] == 64
        start_in_thread(server)
        host, port = server.server_address[:2]
        base = f"http://{host}:{port}"
        images = np.zeros((1, 8, 8, 3), np.uint8)
        status, reply = post(base, "/embed", {"images": images.tolist()},
                             timeout=120)
        assert status == 200
        assert reply["dim"] == 512 and reply["n"] == 1
        assert np.isfinite(np.asarray(reply["embeddings"])).all()
        status, stats = get(base, "/stats")
        assert stats["engine"]["dtype"] == "bf16"
        assert stats["batcher"]["dispatched_batches"] >= 1
        assert "inflight_batches" in stats["batcher"]
        assert "pipeline_occupancy" in stats["batcher"]
    finally:
        server.shutdown()
        server.server_close()
        batcher.close()


def test_request_timeout_maps_to_504():
    batcher = DynamicBatcher(fake_embed, max_batch=8, start=False)  # never served
    server = create_server(batcher, lambda: {}, port=0)
    start_in_thread(server)
    host, port = server.server_address[:2]
    try:
        with pytest.raises(urllib.error.HTTPError) as exc:
            post(f"http://{host}:{port}", "/embed", {
                "images": np.zeros((1, H, W, 3), np.uint8).tolist(),
                "timeout_ms": 30,
            })
        assert exc.value.code == 504
    finally:
        server.shutdown()
        server.server_close()
        batcher.close(drain=False)


def test_stats_latency_quantiles_and_metrics_exposition():
    """Observability satellite: /stats carries p50/p95/p99 per jit bucket
    from the clock-injectable LatencyHistogram, /metrics exposes the SAME
    histogram in Prometheus text format plus the batcher's time-weighted
    occupancy gauges — one measurement source, two views."""
    from simclr_pytorch_distributed_tpu.serve.server import (
        combined_stats_fn,
        serve_metrics_fn,
    )
    from simclr_pytorch_distributed_tpu.utils.prom import LatencyHistogram

    latency = LatencyHistogram()

    def fake_bucket_for(n):  # the engine's smallest-bucket-≥-n contract
        for b in (1, 8, 32):
            if n <= b:
                return b
        return 32

    batcher = DynamicBatcher(
        fake_embed, max_batch=8, max_wait_ms=2,
        latency=latency, bucket_fn=fake_bucket_for,
    )

    class FakeEngine:
        bucket_for = staticmethod(fake_bucket_for)

        def stats(self):
            return {"requests": 2, "images": 7, "padded_rows": 3,
                    "cache_hit_rows": 1, "bucket_dispatches": {8: 2},
                    "cache": {"hits": 1, "misses": 6}}

    server = create_server(
        batcher, combined_stats_fn(FakeEngine(), batcher, latency),
        port=0, metrics_fn=serve_metrics_fn(FakeEngine(), batcher, latency),
    )
    start_in_thread(server)
    host, port = server.server_address[:2]
    base = f"http://{host}:{port}"
    try:
        imgs = np.zeros((3, H, W, 3), np.uint8)
        for _ in range(4):
            batcher.submit(imgs).result(timeout=10)
        status, stats = get(base, "/stats")
        assert status == 200
        lat = stats["latency"]["8"]  # n=3 pads into bucket 8
        assert lat["count"] == 4
        assert 0 <= lat["p50_ms"] <= lat["p95_ms"] <= lat["p99_ms"]
        # the occupancy gauges ride the same /stats payload
        assert "pipeline_occupancy" in stats["batcher"]
        assert "avg_inflight_depth" in stats["batcher"]

        with urllib.request.urlopen(f"{base}/metrics", timeout=10) as r:
            assert r.status == 200
            assert "text/plain" in r.headers["Content-Type"]
            body = r.read().decode()
        assert 'serve_request_latency_ms_bucket{bucket="8",le="+Inf"} 4' in body
        assert 'serve_request_latency_ms_count{bucket="8"} 4' in body
        assert "serve_batcher_pipeline_occupancy" in body
        assert "serve_engine_requests_total 2" in body
        assert 'serve_engine_bucket_dispatches_total{bucket="8"} 2' in body
        assert "serve_cache_hits 1" in body
    finally:
        server.shutdown()
        server.server_close()
        batcher.close()


def test_metrics_404_without_metrics_fn(served):
    """The pre-observability surface is unchanged when no metrics_fn is
    wired (create_server default)."""
    base, _ = served
    with pytest.raises(urllib.error.HTTPError) as exc:
        get(base, "/metrics")
    assert exc.value.code == 404


def test_serve_watchdog_arms_only_while_inflight(tmp_path):
    """The serve stall contract: armed on dispatch, beaten/disarmed by
    completions — an IDLE server never pages anyone (fake clocks on both
    sides; no real waiting)."""
    from simclr_pytorch_distributed_tpu.utils.tracing import StallWatchdog

    class Clock:
        t = 0.0

        def __call__(self):
            return self.t

    clk = Clock()
    wd = StallWatchdog(10.0, str(tmp_path), clock=clk, start=False,
                       name="serve")
    # max_wait_ms=0: a fake clock never closes a nonzero coalescing window
    batcher = DynamicBatcher(fake_embed, max_batch=8, max_wait_ms=0,
                             start=False, watchdog=wd, clock=clk)
    # idle: huge silence, no fire
    clk.t += 1000.0
    assert not wd.check()
    # a dispatched-and-completed batch passes through arm -> disarm
    batcher.submit(np.zeros((2, H, W, 3), np.uint8))
    batcher._dispatch(batcher._next_batch())
    # the synchronous _dispatch path completes inline; manually exercise
    # the completer's bookkeeping contract
    wd.arm()
    clk.t += 11.0
    assert wd.check()  # armed + stuck fires...
    wd.disarm()
    clk.t += 1000.0
    assert not wd.check()  # ...disarmed idle never does
    batcher.close()
