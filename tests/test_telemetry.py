"""Zero-sync telemetry: mechanical proofs for the device-side metric ring +
background flush executor (ops/metrics.MetricRing, utils/telemetry.py).

The claims are tested, not assumed:

- OVERLAP: with the async executor, step k+1 dispatches while flush k is
  still in flight (the fake transfer is gated on an Event); the sync control
  provably never does.
- ONE TRANSFER: a flush performs exactly one host transfer per window
  (instrumented injectable device_get), regardless of steps or key count.
- WRAPAROUND: epoch tails shorter than the window, and windows that start at
  a non-zero ``step % window`` (mid-epoch resume / print_freq not dividing
  steps_per_epoch), resolve the right rows.
- FAILURE: a worker-side NonFiniteLossError re-raises on the MAIN thread at
  the next boundary, and the executor stays usable afterwards (the rollback
  policy keeps training).
- PREEMPTION: the boundary preemption decision is taken on the main thread
  while a flush is still in flight; draining then completes the meters.
- EQUIVALENCE: the async path produces the identical TB stream
  (tags x steps x values) as the sync path. The fast test drives the loop
  shape directly; the slow tests run all three REAL trainers both ways.
"""

import threading
import time

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from simclr_pytorch_distributed_tpu.ops.metrics import MetricRing
from simclr_pytorch_distributed_tpu.utils import preempt
from simclr_pytorch_distributed_tpu.utils.guard import NonFiniteLossError
from simclr_pytorch_distributed_tpu.utils.telemetry import (
    FlushExecutor,
    TelemetryFlushError,
    TelemetrySession,
)

KEYS = ("loss", "m1")


def _metrics(loss, m1=0.0):
    return {"loss": jnp.float32(loss), "m1": jnp.float32(m1)}


def _drive(session, n_steps, window, events=None, loss_of=float):
    """The drivers' loop shape: write -> append -> boundary submit.

    ``events`` (a list) records the interleaving: ``dispatch k`` when step k
    runs, ``flush done @k`` when the window job ending at step k completes.
    Returns the fetched rows in flush order.
    """
    out = []
    ring_buf = session.init_buffer()
    for step in range(n_steps):
        if events is not None:
            events.append(f"dispatch {step}")
        ring_buf = session.ring.write(ring_buf, _metrics(loss_of(step)), step)
        session.append(step, step)
        if (step + 1) % window == 0 or step + 1 == n_steps:
            boundary = step

            def consume(fetched, boundary=boundary):
                out.extend(fetched)
                if events is not None:
                    events.append(f"flush done @{boundary}")

            session.submit_window(ring_buf, consume)
    session.drain()
    return out


def test_async_overlap_sync_control():
    """Step k+1 dispatches while flush k is in flight under async; the sync
    control completes flush k BEFORE any later dispatch. Same loop, same
    gated transfer — only the executor mode differs."""
    n_steps, window = 6, 2

    def make_gated(release):
        def gated_get(x):
            release.wait(timeout=10)
            return jax.device_get(x)

        return gated_get

    # async arm: hold every flush hostage; the loop must keep going anyway.
    # Drive the loop in a worker so the main thread can assert mid-flight.
    release = threading.Event()
    events = []
    session = TelemetrySession(window, KEYS, "async", device_get=make_gated(release))
    result = {}
    loop = threading.Thread(
        target=lambda: result.update(rows=_drive(session, n_steps, window, events)),
        daemon=True,
    )
    loop.start()
    # the loop can only finish dispatching everything if no flush blocks it
    for _ in range(200):
        if sum(e.startswith("dispatch") for e in events) == n_steps:
            break
        time.sleep(0.01)
    dispatched_while_gated = sum(e.startswith("dispatch") for e in events)
    flushes_done_while_gated = sum(e.startswith("flush done") for e in events)
    release.set()
    loop.join(timeout=10)
    assert not loop.is_alive()
    session.close()
    assert dispatched_while_gated == n_steps  # dispatch ran ahead of flush 0
    assert flushes_done_while_gated == 0  # while every flush was still gated
    assert [i for i, _ in result["rows"]] == list(range(n_steps))

    # sync control: the gate must be OPEN or the loop deadlocks — which is
    # itself the proof that sync flushes block dispatch; run it open and
    # assert the interleaving is strictly flush-before-next-dispatch
    release2 = threading.Event()
    release2.set()
    events2 = []
    control = TelemetrySession(window, KEYS, "sync", device_get=make_gated(release2))
    _drive(control, n_steps, window, events2)
    control.close()
    for boundary in range(window - 1, n_steps, window):
        flush_pos = events2.index(f"flush done @{boundary}")
        later_dispatches = [
            e for e in events2[:flush_pos] if e.startswith("dispatch")
        ]
        # every dispatch that happened before this flush belongs to steps
        # <= boundary: the sync path NEVER runs ahead of an open flush
        assert all(int(e.split()[1]) <= boundary for e in later_dispatches)


def test_flush_is_exactly_one_transfer_per_window():
    calls = []

    def counting_get(x):
        calls.append(1)
        return jax.device_get(x)

    session = TelemetrySession(5, KEYS, "sync", device_get=counting_get)
    rows = _drive(session, 15, 5)  # 3 full windows
    session.close()
    assert len(calls) == 3
    assert session.ring.transfers == 3
    assert len(rows) == 15


def test_ring_wraparound_tail_and_unaligned_windows():
    """7 steps through a window of 5 (tail shorter than the window, slots
    wrapping 5->0, 6->1), then a window starting at step%window != 0 (the
    supcon epoch-2 shape when print_freq doesn't divide steps_per_epoch)."""
    session = TelemetrySession(5, KEYS, "sync")
    rows = _drive(session, 7, 5, loss_of=lambda s: 10.0 + s)
    assert [(i, m["loss"]) for i, m in rows] == [
        (s, 10.0 + s) for s in range(7)
    ]

    # unaligned continuation: steps 7..10 in one window (slots 2,3,4,0)
    ring_buf = session.init_buffer()
    out = []
    for step in range(7, 11):
        ring_buf = session.ring.write(ring_buf, _metrics(100.0 + step), step)
        session.append(step, step)
    session.submit_window(ring_buf, out.extend)
    session.drain()
    session.close()
    assert [(i, m["loss"]) for i, m in out] == [
        (s, 100.0 + s) for s in range(7, 11)
    ]


def test_ring_overflow_and_key_mismatch_raise():
    ring = MetricRing(2, KEYS)
    ring.append(0, 0)
    ring.append(1, 1)
    with pytest.raises(RuntimeError, match="overflow"):
        ring.append(2, 2)
    with pytest.raises(ValueError, match="metric keys"):
        ring.write(ring.init_buffer(), {"loss": jnp.float32(0)}, 0)
    with pytest.raises(ValueError, match="window"):
        MetricRing(0, KEYS)


def test_worker_exception_surfaces_on_main_thread_then_executor_reusable():
    """The NaN guard runs in the window job: its NonFiniteLossError must
    re-raise on the main thread at the next boundary, discard any queued
    poisoned jobs, and leave the executor usable (rollback continues)."""
    ex = FlushExecutor("async")
    ran = []

    def bad_job():
        raise NonFiniteLossError(float("nan"), 7)

    ex.submit(bad_job)
    ex.submit(lambda: ran.append("poisoned"))  # queued after the failure
    with pytest.raises(NonFiniteLossError, match="step 7"):
        ex.drain()
    assert ran == []  # the queued job post-dating the failure was discarded
    ex.submit(lambda: ran.append("after"))  # the executor recovered
    ex.drain()
    assert ran == ["after"]
    ex.close()


def test_check_failures_global_drains_and_raises_at_boundary():
    """The drivers' collective failure observation: a pending worker
    failure raises at the NEXT deterministic boundary (single-process
    short-circuits the allgather), and submit() itself never raises — the
    raise point must not depend on per-host flush scheduling."""
    session = TelemetrySession(2, KEYS, "async")
    ring_buf = session.init_buffer()
    session.ring.write(ring_buf, _metrics(0.0), 0)
    session.append(0, 0)

    def bad_consume(fetched):
        raise NonFiniteLossError(float("nan"), 3)

    session.submit_window(ring_buf, bad_consume)
    # let the worker actually fail, then submit another window: no raise here
    session.executor.wait_idle()
    session.ring.write(ring_buf, _metrics(1.0), 1)
    session.append(1, 1)
    session.submit_window(ring_buf, lambda rows: None)
    with pytest.raises(NonFiniteLossError, match="step 3"):
        session.check_failures_global(step_hint=1)
    session.check_failures_global()  # cleared: the executor is reusable
    session.close()


def test_check_failures_global_skew_guard(monkeypatch):
    """A host whose OWN windows were clean but whose peer flagged a failure
    must still leave the loop, with the exception type the allgathered code
    names: NonFiniteLossError for a NaN peer, TelemetryFlushError for a
    non-NaN flush failure."""
    session = TelemetrySession(2, KEYS, "async")
    monkeypatch.setattr(session, "_failure_code", lambda: 1)
    with pytest.raises(NonFiniteLossError):
        session.check_failures_global(step_hint=7)
    monkeypatch.setattr(session, "_failure_code", lambda: 2)
    with pytest.raises(TelemetryFlushError):
        session.check_failures_global(step_hint=7)
    session.close()


def test_late_local_failure_exits_with_allgathered_type(monkeypatch):
    """The exit type is a pure function of the ALLGATHERED code: a local
    failure that lands AFTER the code exchange (the window was still in
    flight at the snapshot) must not reclassify the exit. Simulated here:
    the collective code says 1 (a peer's NaN) while this host's drain
    surfaces a TB-style IOError — the host must leave through the NaN
    policy like its peers, with the local error chained as __cause__."""
    session = TelemetrySession(2, KEYS, "async")
    ring_buf = session.init_buffer()
    session.ring.write(ring_buf, _metrics(0.0), 0)
    session.append(0, 0)

    def late_disk_error(fetched):
        raise OSError("No space left on device")

    session.submit_window(ring_buf, late_disk_error)
    session.executor.wait_idle()
    # as-if the allgather ran while this host's job was still in flight
    # (local snapshot 0) and a peer reported a non-finite loss (max = 1)
    monkeypatch.setattr(session, "_failure_code", lambda: 1)
    with pytest.raises(NonFiniteLossError) as ei:
        session.check_failures_global(step_hint=9)
    assert isinstance(ei.value.__cause__, OSError)
    session.close()


def test_non_nan_flush_failure_never_triggers_nan_policy():
    """A TB-write IOError (or any non-NaN job failure) must surface as
    TelemetryFlushError — NOT NonFiniteLossError — or --nan_policy rollback
    would discard clean epochs over a disk error. The original exception
    rides as __cause__ and the executor is clean afterwards."""
    session = TelemetrySession(2, KEYS, "async")
    ring_buf = session.init_buffer()
    session.ring.write(ring_buf, _metrics(0.0), 0)
    session.append(0, 0)

    def disk_full(fetched):
        raise OSError("No space left on device")

    session.submit_window(ring_buf, disk_full)
    session.executor.wait_idle()
    with pytest.raises(TelemetryFlushError) as ei:
        session.check_failures_global(step_hint=5)
    assert isinstance(ei.value.__cause__, OSError)
    session.check_failures_global()  # cleared: the executor is reusable
    session.close()


def test_drain_global_waits_then_raises_classified_type():
    """The drivers' pre-collective-save drain: completes all jobs WITHOUT a
    host-local raise, then surfaces the failure through the collective
    observation with its classified type — so every host's raise point (and
    type) stays matched ahead of a collective checkpoint save. An empty
    trailing submit_window is never a raise point either."""
    session = TelemetrySession(2, KEYS, "async")
    ring_buf = session.init_buffer()
    session.ring.write(ring_buf, _metrics(0.0), 0)
    session.append(0, 0)
    gate = threading.Event()

    def slow_nan(fetched):
        gate.wait(timeout=5)
        raise NonFiniteLossError(float("nan"), 0)

    session.submit_window(ring_buf, slow_nan)
    session.submit_window(ring_buf, lambda rows: None)  # empty: no raise
    gate.set()
    with pytest.raises(NonFiniteLossError):
        session.drain_global(step_hint=0)
    session.drain_global()  # cleared: reusable
    session.close()


def test_trailing_submit_clears_short_epoch_pending():
    """Steps left pending by an epoch shorter than expected must not leak
    into the next epoch's windows (ring bookkeeping is session-lifetime):
    the drivers' trailing submit_window flushes them."""
    session = TelemetrySession(5, KEYS, "sync")
    out = []
    ring_buf = session.init_buffer()
    for step in range(3):  # "epoch" ends before any boundary fires
        ring_buf = session.ring.write(ring_buf, _metrics(step), step)
        session.append(step, step)
    session.submit_window(ring_buf, out.extend)  # the trailing call
    session.drain()
    assert [i for i, _ in out] == [0, 1, 2]
    assert session.ring.take_window() == []  # nothing stale for epoch 2
    session.close()


def test_sync_mode_defers_failure_like_async():
    """Sync mode runs jobs inline but failures follow the SAME deferred
    protocol as async — stored, not raised out of submit (a raw raise would
    skip the collective failure-code exchange and exit with the wrong type),
    then surfaced by poll/drain/check_failures_global at the boundary."""
    ex = FlushExecutor("sync")
    ran = []
    ex.submit(lambda: (_ for _ in ()).throw(NonFiniteLossError(0.0, 1)))
    ex.submit(lambda: ran.append(1))  # poisoned: discarded like async
    assert ran == []
    with pytest.raises(NonFiniteLossError):
        ex.poll()
    ex.submit(lambda: ran.append(2))  # clean again after poll
    assert ran == [2]
    ex.drain()  # no-op, clean
    ex.close()


def test_preemption_decided_while_flush_in_flight():
    """The collective preemption decision runs on the MAIN thread at the
    boundary — it never waits for the in-flight D2H; draining afterwards
    completes the meters before the emergency save would read them."""
    release = threading.Event()
    fetched = []

    def gated_get(x):
        release.wait(timeout=10)
        return jax.device_get(x)

    session = TelemetrySession(2, KEYS, "async", device_get=gated_get)
    ring_buf = session.init_buffer()
    for step in range(2):
        ring_buf = session.ring.write(ring_buf, _metrics(step), step)
        session.append(step, step)
    session.submit_window(ring_buf, fetched.extend)  # in flight, gated

    preempt.request()
    try:
        # the decision completes while the flush is STILL gated
        assert preempt.requested_global()
        assert fetched == []
    finally:
        preempt.uninstall()
    release.set()
    session.drain()
    session.close()
    assert [i for i, _ in fetched] == [0, 1]  # meters complete post-drain


def test_tb_stream_equivalent_sync_vs_async():
    """Same loop, same values: the async arm's (tag, step, value) stream is
    identical to the sync arm's — ordering included (jobs are FIFO on one
    worker)."""

    def run(mode):
        stream = []
        session = TelemetrySession(3, KEYS, mode)
        rows = _drive(
            session, 8, 3, loss_of=lambda s: float(np.sin(s))
        )
        for i, m in rows:
            stream.append(("info/loss", i, m["loss"]))
        session.close()
        return stream

    assert run("sync") == run("async")


# ---------------------------------------------------------------------------
# driver-level equivalence: the three REAL trainers, sync vs async telemetry
# ---------------------------------------------------------------------------

SIZE = 8


class RecordingTB:
    """TBLogger stand-in: records (tag, value, step) on every process."""

    last_stream = None

    def __init__(self, logdir, enabled=True):
        self.records = []
        RecordingTB.last_stream = self.records

    def log_value(self, tag, value, step):
        self.records.append((tag, float(value), int(step)))

    def close(self):
        pass


@pytest.fixture
def tiny_drivers(monkeypatch):
    import jax as _jax

    from simclr_pytorch_distributed_tpu.data import cifar as cifar_lib
    from simclr_pytorch_distributed_tpu.parallel import mesh as mesh_lib
    from simclr_pytorch_distributed_tpu.train import ce as ce_driver
    from simclr_pytorch_distributed_tpu.train import linear as linear_driver
    from simclr_pytorch_distributed_tpu.train import supcon as supcon_driver

    orig = cifar_lib.synthetic_dataset

    def small(n=2048, num_classes=10, seed=0, size=32):
        return orig(n=200, num_classes=num_classes, seed=seed, size=SIZE)

    monkeypatch.setattr(cifar_lib, "synthetic_dataset", small)

    def limited_create_mesh(devices=None, **kw):
        if devices is None:
            devices = _jax.devices()[:1]
        return mesh_lib.create_mesh(devices=devices, **kw)

    for driver in (supcon_driver, linear_driver, ce_driver):
        monkeypatch.setattr(driver, "create_mesh", limited_create_mesh)
        monkeypatch.setattr(driver, "TBLogger", RecordingTB)
    return supcon_driver, linear_driver, ce_driver


def _tb_ab(run_fn):
    """Run a driver twice (sync then async telemetry); return both streams."""
    streams = {}
    for mode in ("sync", "async"):
        run_fn(mode)
        streams[mode] = list(RecordingTB.last_stream)
    return streams


@pytest.mark.parametrize("placement", ["host", "device", "window"])
def test_all_drivers_flush_boundary_smoke(tmp_path, tiny_drivers, placement):
    """FAST guard on the driver<->flush_boundary contract: one sync-mode
    epoch through each REAL trainer. Sync telemetry runs every window job
    inline, so a driver whose ``consume`` signature diverges from what
    ``flush_boundary`` calls (one arg vs the ``(fetched, bt)`` pair when
    ``batch_meter`` is given) raises a ``TypeError`` right here instead of
    only in the slow-marked equivalence tests the default suite deselects.

    Parametrized over ``--data_placement`` so EVERY driver loop stays under
    driver-level test: 'device' is the HBM-resident branch, 'window' the
    streaming window-store branch (data_window_batches=2 forces real
    mid-epoch window swaps in the 5-step epoch), 'host' the per-step H2D
    branch (the production path for over-budget datasets — 'auto' alone
    would always resolve to 'device' on the tiny in-RAM synthetic set and
    leave the other loops covered only at the data-layer)."""
    supcon_driver, linear_driver, ce_driver = tiny_drivers
    from simclr_pytorch_distributed_tpu import config as config_lib

    cfg = config_lib.SupConConfig(
        model="resnet10", dataset="synthetic", batch_size=32, epochs=1,
        learning_rate=0.05, cosine=True, save_freq=5, print_freq=2,
        size=SIZE, workdir=str(tmp_path / "sc"), seed=0, method="SimCLR",
        telemetry="sync", data_placement=placement, data_window_batches=2,
    )
    supcon_driver.run(config_lib.finalize_supcon(cfg))
    assert any(r[0].startswith("info/") for r in RecordingTB.last_stream)
    for driver, prefix, sub in ((linear_driver, "", "lin"), (ce_driver, "ce_", "ce")):
        lcfg = config_lib.LinearConfig(
            model="resnet10", dataset="synthetic", batch_size=32, epochs=1,
            learning_rate=0.1, size=SIZE, val_batch_size=40,
            workdir=str(tmp_path / sub), print_freq=2, telemetry="sync",
            data_placement=placement, data_window_batches=2,
        )
        driver.run(config_lib.finalize_linear(lcfg, prefix=prefix) if prefix
                   else config_lib.finalize_linear(lcfg))


@pytest.mark.slow
def test_supcon_tb_stream_bitwise_equal(tmp_path, tiny_drivers):
    supcon_driver, _, _ = tiny_drivers
    from simclr_pytorch_distributed_tpu import config as config_lib

    def go(mode):
        cfg = config_lib.SupConConfig(
            model="resnet10", dataset="synthetic", batch_size=32, epochs=2,
            learning_rate=0.05, cosine=True, save_freq=5,
            print_freq=2, size=SIZE, workdir=str(tmp_path / mode), seed=0,
            method="SimCLR", telemetry=mode,
        )
        supcon_driver.run(config_lib.finalize_supcon(cfg))

    streams = _tb_ab(go)
    # per-iter info/* tags at EVERY step + epoch tags, bit-for-float equal;
    # 200-sample synthetic: 160 train -> 5 steps/epoch (windows 2+2+1 tail)
    assert streams["sync"] == streams["async"]
    info_tags = [r for r in streams["sync"] if r[0].startswith("info/")]
    assert {r[2] for r in info_tags} == set(range(10))  # all 10 global steps


@pytest.mark.slow
def test_linear_and_ce_tb_streams_bitwise_equal(tmp_path, tiny_drivers):
    _, linear_driver, ce_driver = tiny_drivers
    from simclr_pytorch_distributed_tpu import config as config_lib

    def go_linear(mode):
        cfg = config_lib.LinearConfig(
            model="resnet10", dataset="synthetic", batch_size=32, epochs=2,
            learning_rate=0.5, size=SIZE, val_batch_size=40,
            workdir=str(tmp_path / f"lin_{mode}"), print_freq=2, telemetry=mode,
        )
        linear_driver.run(config_lib.finalize_linear(cfg))

    def go_ce(mode):
        cfg = config_lib.LinearConfig(
            model="resnet10", dataset="synthetic", batch_size=32, epochs=2,
            learning_rate=0.1, size=SIZE, val_batch_size=40,
            workdir=str(tmp_path / f"ce_{mode}"), print_freq=2, telemetry=mode,
        )
        ce_driver.run(config_lib.finalize_linear(cfg, prefix="ce_"))

    lin = _tb_ab(go_linear)
    assert lin["sync"] == lin["async"] and lin["sync"]
    ce = _tb_ab(go_ce)
    assert ce["sync"] == ce["async"] and ce["sync"]
