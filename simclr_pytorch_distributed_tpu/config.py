"""Configuration: the reference's argparse surface, dataclass-backed.

Flag names, defaults, and DERIVED fields match the reference parsers —
``main_supcon.py:22-152`` (pretrain), ``main_linear.py:21-116`` (probe), and the
CE baseline (whose parser was lost in the reference fork; rebuilt from the
probe's). The derivations that matter for recipe parity are kept bit-identical:

- ``model_name`` run-string encoding (``main_supcon.py:109-117``);
- auto-warmup when ``batch_size > 256`` (``:120-121``);
- closed-form ``warmup_to`` (``:124-131``, via ops/schedules.warmup_to_value);
- timestamped tb/save folder layout (``:133-142``), created on the main process.

TPU-native additions (not in the reference): ``--bf16`` compute dtype,
``--resume`` full-state resume, ``--model_parallel`` mesh axis size,
``--seed``, ``--dataset synthetic``, ``--workdir``. The reference's ``--ngpu``
flag is kept but means "DDP gradient-scale equivalence divisor" (see
train/supcon_step.py) — actual parallelism comes from the mesh, not a flag.
"""

from __future__ import annotations

import argparse
import dataclasses
import datetime
import os
from typing import Optional, Tuple

from simclr_pytorch_distributed_tpu.ops.schedules import warmup_to_value
from simclr_pytorch_distributed_tpu.parallel.mesh import is_main_process


@dataclasses.dataclass
class SupConConfig:
    # cadence
    print_freq: int = 10
    save_freq: int = 20
    batch_size: int = 256
    num_workers: int = 16  # CLI-parity only: augmentation runs on device
    epochs: int = 1000
    # optimization (main_supcon.py:37-47)
    learning_rate: float = 0.5
    lr_decay_epochs: Tuple[int, ...] = (700, 800, 900)
    lr_decay_rate: float = 0.1
    weight_decay: float = 1e-4
    momentum: float = 0.9
    # model / dataset (main_supcon.py:49-56)
    model: str = "resnet50"
    dataset: str = "cifar10"  # {cifar10, cifar100, path, synthetic, synthetic_hard, synthetic_hard32}
    mean: Optional[str] = None
    std: Optional[str] = None
    data_folder: Optional[str] = None
    size: int = 32
    # 'path' datasets: host-side storage resolution (0 = 2*size); the device
    # RandomResizedCrop samples from this resolution (data/folder.py)
    store_size: int = 0
    # 'path' datasets: decoded trees above this go through the on-disk memmap
    # cache instead of RAM (data/folder.py; bounded host RSS for big trees)
    mmap_threshold_mb: int = 1024
    # method (main_supcon.py:58-64)
    method: str = "SimCLR"  # {SupCon, SimCLR}
    temp: float = 0.5
    # other settings (main_supcon.py:66-88)
    cosine: bool = False
    syncBN: bool = False
    warm: bool = False
    trial: str = "0"
    sec: bool = False
    sec_wei: float = 0.0
    norm_momentum: float = 1.0
    l2reg: bool = False
    l2reg_wei: float = 0.0
    ckpt: str = ""
    # grad-scale equivalence divisor (reference --ngpu default 2); also
    # accepts 'auto' = resolve to the mesh's data-parallel size at startup
    # (resolve_ngpu). A non-auto mismatch prints a startup banner naming the
    # effective-LR consequence (ngpu_mismatch_banner).
    ngpu: object = 2
    # head (reference hardcodes SupConResNet defaults, resnet_big.py:161)
    head: str = "mlp"
    feat_dim: int = 128
    # --- TPU-native additions ---
    # fetch CIFAR if absent (the reference's torchvision download=True,
    # main_supcon.py:181-188); process-0-gated in the drivers
    download: bool = True
    bf16: bool = False
    resume: str = ""
    model_parallel: int = 1
    seed: int = 0
    workdir: str = "./work_space"
    # NOTE: per-iter TB scalars follow --print_freq (the reference logs every
    # iter, which forces a device sync per step)
    # contrastive-loss implementation: 'auto' picks the fused Pallas kernel on
    # a single TPU chip, the dense XLA path otherwise (ops/pallas_loss.py);
    # 'ring' streams contrast blocks around the data axis with ppermute
    # (parallel/collectives.py) for large-global-batch memory scaling
    loss_impl: str = "auto"
    # conv-block implementation for the encoder's hot path: 'pallas' routes
    # the stem, BasicBlocks (identity AND projection/stride-2 shortcuts),
    # and rn50-family Bottlenecks through the fused conv+BN+ReLU kernels
    # (ops/pallas_conv.py — the inter-op activation round-trips that fund
    # XLA's stage-1 BN-backward/residual fusions never touch HBM), in fp32
    # or bf16 compute (fp32 MXU accumulation, fp32 BN statistics); 'xla'
    # is the bitwise-pinned default path; 'auto' picks pallas only on a
    # single-chip TPU mesh at supported stage geometries
    # (train.supcon.resolve_conv_impl, the --loss_impl ladder convention,
    # startup banner names the resolution and the compute dtype)
    conv_impl: str = "auto"
    # 'sgd' is the published recipe (util.py:79-84); 'lars' for the
    # large-global-batch configs (SimCLR ImageNet bs=4096, BASELINE configs[4])
    optimizer: str = "sgd"
    # jax.profiler trace capture (SURVEY.md §5 tracing row; reference has none)
    trace_dir: str = ""
    trace_start_step: int = 10
    trace_steps: int = 10
    # persistent XLA compile cache ('auto' = <workdir>/.jax_cache, '' = off);
    # cuts the ~40-80s first-step compile on restarts/resumes
    compile_cache: str = "auto"
    # abort + emergency-checkpoint on NaN/Inf loss (utils/guard.py)
    nan_guard: bool = True
    # what to DO about a non-finite loss (utils/guard.py FailurePolicy):
    # 'abort' dies after the crash_epoch_N save; 'rollback' restores the
    # epoch-boundary backup, skips the poisoned epoch with the LR halved,
    # and continues (bounded by guard.MAX_ROLLBACKS)
    nan_policy: str = "abort"
    # per-block activation rematerialization: trades recompute FLOPs for HBM
    # so bigger per-chip batches fit (identical numerics; models/resnet.py)
    remat: bool = False
    # where the per-window metric flush (D2H + NaN check + meters + TB) runs:
    # 'async' = background telemetry thread, zero sync on the hot loop (NaN
    # detection at most one print_freq window late — utils/telemetry.py);
    # 'sync' = inline on the dispatch thread (the pre-ring semantics)
    telemetry: str = "async"
    # where training batches live (data/device_store.py): 'device' keeps the
    # uint8 dataset HBM-resident (one index upload + compiled shuffle-gather
    # per epoch; the hot loop is dispatch-only — no per-step H2D); 'window'
    # streams a double-buffered window of permutation-ordered batches (one
    # H2D per window — datasets that don't fit HBM, incl. memmap-backed
    # folder trees); 'host' is the per-step device_put loop; 'auto' walks
    # the device -> window -> host ladder against the budget. Batch
    # composition is bit-identical in every placement.
    data_placement: str = "auto"
    # windowed placement: batches per resident window; HBM cost is 2x one
    # window (the training window + the prefetched shadow buffer)
    data_window_batches: int = 32
    # override the computed per-device placement budget, in MB (0 = 0.4x
    # free memory_stats, with a fixed 4 GB fallback where stats are absent
    # — untunable exactly where it matters without this)
    device_budget_mb: int = 0
    # --- observability (docs/OBSERVABILITY.md) ---
    # representation-health diagnostics (train/supcon_step.py
    # HEALTH_METRIC_KEYS): alignment / uniformity / contrastive top-1 /
    # negative-similarity stats / gradient norm / embedding effective rank,
    # computed inside the jitted update every health_freq-th step and shipped
    # through the existing metric ring (zero new per-step D2H); 0 = off
    health_freq: int = 10
    # what a collapse/divergence verdict does (utils/guard.HealthMonitor):
    # 'warn' logs + emits health_alarm flight-recorder events; 'abort' exits
    # with RepresentationHealthError (collective, like the NaN exit; NEVER
    # rolled back — see docs/RESILIENCE.md precedence note)
    health_policy: str = "warn"
    # online linear probe (train/supcon_step.py): a detached classifier head
    # on stop_gradient encoder features trained by the same compiled update,
    # so probe top-1 streams live through the ring instead of waiting for
    # the post-hoc main_linear.py pass; checkpointed in its own payload
    online_probe: str = "off"
    probe_lr: float = 0.1
    # --- SSL recipes (simclr_pytorch_distributed_tpu/recipes/) ---
    # which loss head rides the substrate: 'auto' = the --method-matching
    # contrastive recipe (the pre-recipe behavior); 'supcon'/'simclr' force
    # the method; 'byol'/'simsiam'/'vicreg' are the negative-free /
    # redundancy-reduction siblings (validate_recipe resolves + checks the
    # flag interactions at parse time)
    recipe: str = "auto"
    # MoCo-style device-side negative queue (recipes/supcon.py): K past
    # embeddings contrasted as extra negatives, rotated in-program — simclr
    # only, K a multiple of 2*batch_size, dense loss path; 0 = off
    moco_queue: int = 0
    # EMA momentum of the slow branch: byol's target network AND the moco
    # queue's key encoder (tau/m; slow = tau*slow + (1-tau)*online per step)
    ema_momentum: float = 0.996
    # byol: 'none' ablates the predictor — the known-collapsing form that
    # must trip the eff-rank collapse alarm (the recipes' injection arm)
    byol_predictor: str = "mlp"
    # byol/simsiam predictor hidden width (models/heads.PredictorHead)
    predictor_hidden: int = 512
    # vicreg term weights (ops/losses.vicreg_loss; paper defaults 25/25/1)
    vicreg_sim_coeff: float = 25.0
    vicreg_std_coeff: float = 25.0
    vicreg_cov_coeff: float = 1.0
    # flight recorder (utils/tracing.py): host-boundary span/event log ->
    # <run_dir>/events.jsonl + Chrome-trace trace.json; zero device
    # syncs/transfers added (asserted mechanically in tier-1)
    flight_recorder: str = "on"
    # stall watchdog: if the flush boundary hasn't advanced in this many
    # seconds, dump all thread stacks + a recorder snapshot to the run dir
    # (a silent collective deadlock becomes an attributable artifact);
    # 0 = off. Must comfortably exceed the first-step compile.
    watchdog_secs: float = 0.0
    # Prometheus /metrics sidecar (utils/prom.py TrainerGauges): step,
    # last-boundary age, in-flight windows, pending checkpoint saves;
    # 0 = off. Binds loopback by default — exposing an unauthenticated
    # endpoint on all interfaces is an explicit choice (--metrics_host).
    metrics_port: int = 0
    metrics_host: str = "127.0.0.1"
    # derived (finalize_supcon)
    warm_epochs: int = 10
    warmup_from: float = 0.01
    warmup_to: float = 0.0
    model_name: str = ""
    tb_folder: str = ""
    save_folder: str = ""


def _add_bool_flag(parser, name, default=False, help=""):
    parser.add_argument(f"--{name}", action="store_true", default=default, help=help)


def _parse_bool(s: str) -> bool:
    v = s.lower()
    if v in ("1", "true", "yes", "on"):
        return True
    if v in ("0", "false", "no", "off"):
        return False
    raise argparse.ArgumentTypeError(f"expected a boolean, got {s!r}")


def ngpu_arg(s: str):
    """--ngpu accepts the reference's int OR 'auto' (mesh-resolved)."""
    if s.strip().lower() == "auto":
        return "auto"
    try:
        v = int(s)
    except ValueError:
        raise argparse.ArgumentTypeError(
            f"--ngpu expects a positive integer or 'auto', got {s!r}"
        ) from None
    if v <= 0:
        # it becomes the gradient DIVISOR: 0 divides by zero, negatives
        # flip the update direction — reject at parse, not mid-startup
        raise argparse.ArgumentTypeError(f"--ngpu must be positive, got {v}")
    return v


def positive_int_arg(name: str):
    """argparse type for flags that must be >= 1 (the --ngpu convention:
    reject at parse, not mid-startup — these feed divisors and byte
    budgets where 0/negatives fail far from the flag)."""

    def parse(s: str) -> int:
        try:
            v = int(s)
        except ValueError:
            raise argparse.ArgumentTypeError(
                f"--{name} expects a positive integer, got {s!r}"
            ) from None
        if v <= 0:
            raise argparse.ArgumentTypeError(
                f"--{name} must be positive, got {v}"
            )
        return v

    return parse


def resolve_ngpu(ngpu, data_parallel: int) -> int:
    """The effective grad divisor: ``'auto'`` -> the mesh's data-parallel
    size; integers (or int-like strings from restored config dicts) pass
    through unchanged."""
    if isinstance(ngpu, str) and ngpu.strip().lower() == "auto":
        return int(data_parallel)
    v = int(ngpu)
    if v <= 0:  # programmatic configs bypass ngpu_arg
        raise ValueError(f"ngpu must be positive, got {v}")
    return v


def ngpu_mismatch_banner(ngpu: int, data_parallel: int, learning_rate: float) -> str:
    """Startup banner for an explicit --ngpu that differs from the mesh.

    The step divides the exact global-batch gradient by ``ngpu`` (DDP
    grad-mean fidelity with the reference's ``ngpu``-GPU runs,
    train/supcon_step.py). When the mesh's data-parallel size differs, that
    divisor no longer matches the hardware, which silently rescales the
    effective learning rate — worth a banner, not a log line lost in startup
    noise (VERDICT round 5 #8).
    """
    eff = learning_rate * data_parallel / ngpu
    bar = "=" * 72
    return (
        f"\n{bar}\n"
        f"  --ngpu {ngpu} but the mesh is data-parallel over {data_parallel} "
        f"device(s).\n"
        f"  Gradients are divided by {ngpu} (recipe fidelity with the "
        f"reference's {ngpu}-GPU runs): relative to mesh-matched scaling the "
        f"applied update is {data_parallel}/{ngpu} = "
        f"{data_parallel / ngpu:.3g}x, i.e. an EFFECTIVE learning rate of "
        f"~{eff:.4g} instead of the configured {learning_rate:g}.\n"
        f"  Pass --ngpu auto (or --ngpu {data_parallel}) to scale with this "
        f"mesh instead.\n"
        f"{bar}"
    )


def supcon_parser() -> argparse.ArgumentParser:
    d = SupConConfig()
    p = argparse.ArgumentParser("argument for training")
    p.add_argument("--print_freq", type=int, default=d.print_freq)
    p.add_argument("--save_freq", type=int, default=d.save_freq)
    p.add_argument("--batch_size", type=int, default=d.batch_size)
    p.add_argument("--num_workers", type=int, default=d.num_workers)
    p.add_argument("--epochs", type=int, default=d.epochs)
    p.add_argument("--learning_rate", type=float, default=d.learning_rate)
    p.add_argument("--lr_decay_epochs", type=str, default="700,800,900")
    p.add_argument("--lr_decay_rate", type=float, default=d.lr_decay_rate)
    p.add_argument("--weight_decay", type=float, default=d.weight_decay)
    p.add_argument("--momentum", type=float, default=d.momentum)
    p.add_argument("--model", type=str, default=d.model)
    p.add_argument("--dataset", type=str, default=d.dataset,
                   choices=["cifar10", "cifar100", "path", "synthetic", "synthetic_hard", "synthetic_hard32"])
    p.add_argument("--mean", type=str, default=None,
                   help="mean of dataset in path in form of str tuple")
    p.add_argument("--std", type=str, default=None)
    p.add_argument("--data_folder", type=str, default=None)
    p.add_argument("--no_download", dest="download", action="store_false",
                   default=True, help="never fetch CIFAR over the network")
    p.add_argument("--size", type=int, default=d.size)
    p.add_argument("--store_size", type=int, default=d.store_size,
                   help="path datasets: stored resolution (0 = 2*size)")
    p.add_argument("--mmap_threshold_mb", type=int, default=d.mmap_threshold_mb,
                   help="path datasets: decode to an on-disk memmap above this size")
    p.add_argument("--method", type=str, default=d.method, choices=["SupCon", "SimCLR"])
    p.add_argument("--temp", type=float, default=d.temp)
    _add_bool_flag(p, "cosine")
    _add_bool_flag(p, "syncBN")
    _add_bool_flag(p, "warm")
    p.add_argument("--trial", type=str, default=d.trial)
    _add_bool_flag(p, "sec")
    p.add_argument("--sec_wei", type=float, default=d.sec_wei)
    p.add_argument("--norm_momentum", type=float, default=d.norm_momentum)
    _add_bool_flag(p, "l2reg")
    p.add_argument("--l2reg_wei", type=float, default=d.l2reg_wei)
    p.add_argument("--ckpt", type=str, default=d.ckpt)
    p.add_argument("--ngpu", type=ngpu_arg, default=d.ngpu,
                   help="DDP grad-mean divisor (reference fidelity), or "
                        "'auto' = the mesh's data-parallel size")
    p.add_argument("--head", type=str, default=d.head, choices=["mlp", "linear"])
    p.add_argument("--feat_dim", type=int, default=d.feat_dim)
    _add_bool_flag(p, "bf16")
    p.add_argument("--resume", type=str, default=d.resume)
    p.add_argument("--model_parallel", type=int, default=d.model_parallel)
    p.add_argument("--seed", type=int, default=d.seed)
    p.add_argument("--workdir", type=str, default=d.workdir)
    p.add_argument("--loss_impl", type=str, default=d.loss_impl,
                   choices=["auto", "dense", "fused", "ring"])
    p.add_argument("--conv_impl", type=str, default=d.conv_impl,
                   choices=["auto", "xla", "pallas"],
                   help="encoder conv-block path: fused Pallas "
                        "conv+BN+ReLU kernels (ops/pallas_conv.py) for "
                        "the stem, BasicBlocks (identity and "
                        "projection/stride-2 shortcuts), and rn50-family "
                        "Bottlenecks, fp32 or bf16 compute, vs the "
                        "bitwise-pinned XLA path; 'auto' = pallas only "
                        "on a single-chip TPU at supported geometries "
                        "(startup banner names the resolution and "
                        "compute dtype)")
    p.add_argument("--optimizer", type=str, default=d.optimizer,
                   choices=["sgd", "lars"],
                   help="lars: layer-adaptive scaling for large global batches")
    _add_bool_flag(p, "remat", help="remat residual blocks (HBM for recompute)")
    p.add_argument("--nan_guard", type=_parse_bool,
                   default=d.nan_guard, help="abort + checkpoint on NaN loss")
    p.add_argument("--nan_policy", type=str, default=d.nan_policy,
                   choices=["abort", "rollback"],
                   help="on NaN loss: die after the crash save (typed exit "
                        "code 1, docs/RESILIENCE.md — what the supervisor "
                        "keys on), or restore the epoch backup, halve the "
                        "LR, and continue")
    p.add_argument("--health_freq", type=nonnegative_int_arg("health_freq"),
                   default=d.health_freq,
                   help="compute the representation-health diagnostics "
                        "(alignment/uniformity/contrastive top-1/negative "
                        "sims/grad norm/effective rank) inside the jitted "
                        "update every Nth step, shipped through the metric "
                        "ring (no new per-step transfers); 0 = off")
    p.add_argument("--health_policy", type=str, default=d.health_policy,
                   choices=["warn", "abort"],
                   help="on a windowed collapse/divergence verdict: log + "
                        "flight-recorder event, or exit with the typed "
                        "RepresentationHealthError (exit code 3 — the "
                        "supervisor gives up rather than retrying, since "
                        "collapse lives in the weights; never rolled back)")
    p.add_argument("--recipe", type=str, default=d.recipe,
                   choices=["auto", "supcon", "simclr", "byol", "simsiam",
                            "vicreg"],
                   help="SSL loss head (recipes/): 'auto' = the --method-"
                        "matching contrastive recipe; supcon/simclr force "
                        "the method; byol = predictor + EMA target; simsiam "
                        "= predictor + stop-gradient; vicreg = invariance/"
                        "variance/covariance")
    p.add_argument("--moco_queue", type=nonnegative_int_arg("moco_queue"),
                   default=d.moco_queue,
                   help="MoCo-style negative queue: an EMA key encoder + a "
                        "device-side ring of K past keys as extra NT-Xent "
                        "negatives, rotated in-program (simclr recipe only; "
                        "K a multiple of 2*batch_size; dense loss path); "
                        "0=off")
    p.add_argument("--ema_momentum", type=float, default=d.ema_momentum,
                   help="EMA momentum in [0, 1) of the slow branch: byol's "
                        "target network / the moco queue's key encoder")
    p.add_argument("--byol_predictor", type=str, default=d.byol_predictor,
                   choices=["mlp", "none"],
                   help="byol predictor head; 'none' ablates it (the known-"
                        "collapsing form — the collapse-injection arm)")
    p.add_argument("--predictor_hidden",
                   type=positive_int_arg("predictor_hidden"),
                   default=d.predictor_hidden,
                   help="byol/simsiam predictor MLP hidden width")
    p.add_argument("--vicreg_sim_coeff", type=float, default=d.vicreg_sim_coeff,
                   help="vicreg invariance weight (paper: 25)")
    p.add_argument("--vicreg_std_coeff", type=float, default=d.vicreg_std_coeff,
                   help="vicreg variance-hinge weight (paper: 25)")
    p.add_argument("--vicreg_cov_coeff", type=float, default=d.vicreg_cov_coeff,
                   help="vicreg covariance weight (paper: 1)")
    p.add_argument("--online_probe", type=str, default=d.online_probe,
                   choices=["on", "off"],
                   help="train a detached linear probe on stop_gradient "
                        "encoder features inside the same compiled update; "
                        "probe loss/top-1 stream live through the ring")
    p.add_argument("--probe_lr", type=float, default=d.probe_lr,
                   help="online probe SGD learning rate (constant; the "
                        "probe chases a moving encoder)")
    _add_shared_runtime_flags(p, d)
    _add_observability_flags(p, d)
    return p


def nonnegative_int_arg(name: str):
    """argparse type for cadence flags where 0 means 'off' but negatives are
    nonsense (the positive_int_arg convention, with 0 admitted)."""

    def parse(s: str) -> int:
        try:
            v = int(s)
        except ValueError:
            raise argparse.ArgumentTypeError(
                f"--{name} expects a non-negative integer, got {s!r}"
            ) from None
        if v < 0:
            raise argparse.ArgumentTypeError(
                f"--{name} must be >= 0 (0 = off), got {v}"
            )
        return v

    return parse


def _add_shared_runtime_flags(p: argparse.ArgumentParser, d) -> None:
    """The shared runtime surface (telemetry/data-placement/profiling/
    compile-cache): ONE registry serving all three trainers' parsers.

    These flags mean the same thing on every stage, so they must parse the
    same way everywhere — previously three hand-synced copies, now the one
    definition the invariant linter's flag-consistency rule
    (analysis/rule_registry.py SHARED_RUNTIME_FLAGS) verifies by USAGE:
    registering one of these inline in a parser again is a lint finding,
    and the dataclass defaults (``d.<field>``) must agree across
    SupConConfig/LinearConfig.
    """
    p.add_argument("--telemetry", type=str, default=d.telemetry,
                   choices=["async", "sync"],
                   help="metric flush: background thread (zero sync on the "
                        "hot loop; NaN detection <=1 window late) or inline")
    p.add_argument("--data_placement", type=str, default=d.data_placement,
                   choices=["host", "device", "window", "auto"],
                   help="training batches: 'device' = HBM-resident epoch "
                        "buffer; 'window' = double-buffered streaming "
                        "window, one H2D per window (fits datasets HBM "
                        "can't hold, incl. memmap-backed trees); 'auto' "
                        "walks the device->window->host ladder; 'host' = "
                        "per-step H2D")
    p.add_argument("--data_window_batches",
                   type=positive_int_arg("data_window_batches"),
                   default=d.data_window_batches,
                   help="windowed placement: batches per resident window "
                        "(HBM cost = 2x one window: training + shadow)")
    p.add_argument("--device_budget_mb",
                   type=positive_int_arg("device_budget_mb"),
                   default=d.device_budget_mb,
                   help="override the per-device placement budget in MB "
                        "(default: 0.4x free memory_stats, 4 GB fallback "
                        "where the backend reports no stats)")
    p.add_argument("--trace_dir", type=str, default=d.trace_dir,
                   help="capture a jax.profiler trace into this dir")
    p.add_argument("--trace_start_step", type=int, default=d.trace_start_step)
    p.add_argument("--trace_steps", type=int, default=d.trace_steps)
    p.add_argument("--compile_cache", type=str, default=d.compile_cache)


def _add_observability_flags(p: argparse.ArgumentParser, d) -> None:
    """The shared observability surface (docs/OBSERVABILITY.md): identical
    on all three trainers, like the runtime flags above."""
    p.add_argument("--flight_recorder", type=str, default=d.flight_recorder,
                   choices=["on", "off"],
                   help="host-boundary span/event recorder -> "
                        "<run_dir>/events.jsonl + trace.json "
                        "(utils/tracing.py); adds no device syncs")
    p.add_argument("--watchdog_secs", type=float, default=d.watchdog_secs,
                   help="stall watchdog: dump all thread stacks + a "
                        "recorder snapshot when the flush boundary stalls "
                        "this long (0 = off; set well above the first-step "
                        "compile)")
    p.add_argument("--metrics_port", type=int, default=d.metrics_port,
                   help="Prometheus /metrics sidecar port (step, "
                        "last-boundary age, in-flight windows, pending "
                        "saves); 0 = off")
    p.add_argument("--metrics_host", type=str, default=d.metrics_host,
                   help="sidecar bind address (default loopback; set "
                        "0.0.0.0 to let a remote Prometheus scrape)")


def validate_data_placement(dataset: str, data_placement: str) -> None:
    """Parse-time check of --data_placement interactions.

    ``path`` trees can decode into an on-disk memmap (data/folder.py above
    ``--mmap_threshold_mb``), which device residency refuses — whether THIS
    tree does is only known after the decode, so an explicit ``device``
    request is rejected up front rather than failing deep in setup; ``auto``
    resolves against the decoded array (and walks the ladder with a
    banner). Explicit ``window`` passes: the window store streams from a
    memmap by construction (each window's gather reads only its own rows),
    so the post-decode representation cannot invalidate the request.
    """
    if data_placement == "device" and dataset == "path":
        raise ValueError(
            "--data_placement device is not accepted with --dataset path: "
            "folder datasets may decode to an on-disk memmap "
            "(--mmap_threshold_mb), which cannot be made device-resident — "
            "use --data_placement auto (decides from the decoded size, "
            "falls back to host with a banner) or host"
        )


def validate_conv_impl(cfg: SupConConfig) -> None:
    """Parse-time seam for --conv_impl interactions (the
    validate_data_placement convention: reject up front what would
    otherwise silently no-op far from the flag).

    Deliberately empty since round 19: the fused kernels carry bf16
    variants (fp32 MXU accumulation, fp32 BN statistics), so
    ``--conv_impl pallas --bf16`` is a real configuration, admitted
    site-by-site at RESOLUTION time (train.supcon.resolve_conv_impl —
    explicit pallas raises there only where zero sites admit, 'auto'
    degrades with the reason in the startup banner). The seam stays so a
    future parse-time contradiction has a pinned home and the call site
    in finalize_supcon keeps its ordering guarantee.
    """


def impl_resolution_banner(
    flag: str, requested: str, resolved: str, reason: str
) -> str:
    """One-line startup banner for an impl-resolution ladder
    (``--loss_impl`` / ``--conv_impl`` — the data_placement ladder
    convention): names the RESOLVED implementation and WHY, so a silent
    degradation (unsupported geometry, non-TPU backend) is discoverable
    from the log instead of only from the resolution code."""
    if requested == resolved:
        return f"[{flag}] '{resolved}': {reason}"
    return f"[{flag}] requested '{requested}' -> resolved '{resolved}': {reason}"


def validate_recipe(cfg: SupConConfig) -> None:
    """Resolve ``--recipe auto`` and check the recipe flag interactions at
    PARSE time (the --ngpu convention: these feed tree geometry and loss
    kernels where a bad value fails far from the flag).

    Mutates ``cfg.recipe`` to the concrete name and, for the contrastive
    recipes, forces ``cfg.method`` to match (``--recipe`` is the outer
    selector; a method the recipe contradicts is an error only for the
    label-free recipes, where an explicit ``--method SupCon`` would be
    silently meaningless).
    """
    if cfg.recipe == "auto":
        cfg.recipe = "supcon" if cfg.method == "SupCon" else "simclr"
    elif cfg.recipe == "supcon":
        # forcing the method here is unambiguous: --method defaults to
        # SimCLR, so a SimCLR value cannot be distinguished from "not given"
        cfg.method = "SupCon"
    elif cfg.recipe == "simclr":
        if cfg.method == "SupCon":
            # SupCon is NOT the --method default, so this is an explicit,
            # contradictory ask — dropping the labels silently would train
            # unsupervised while the user believes otherwise
            raise ValueError(
                "--recipe simclr contradicts --method SupCon (the recipe "
                "is label-free NT-Xent) — drop --method, or use "
                "--recipe supcon"
            )
        cfg.method = "SimCLR"
    else:  # byol / simsiam / vicreg: label-free
        if cfg.method == "SupCon":
            raise ValueError(
                f"--recipe {cfg.recipe} is label-free; --method SupCon has "
                "no effect there — drop the flag (or use --recipe supcon)"
            )
    if cfg.moco_queue:
        if cfg.recipe != "simclr":
            raise ValueError(
                f"--moco_queue holds NEGATIVES only, which --recipe "
                f"{cfg.recipe} cannot use "
                + ("(supervised positives may sit in the queue)"
                   if cfg.recipe == "supcon" else "(no contrastive term)")
                + " — it requires --recipe simclr"
            )
        if cfg.moco_queue % (2 * cfg.batch_size) != 0:
            raise ValueError(
                f"--moco_queue {cfg.moco_queue} must be a multiple of "
                f"2*batch_size ({2 * cfg.batch_size}): the in-program ring "
                "write (dynamic_update_slice) clamps at the edge instead of "
                "wrapping, so partial-batch rotations would corrupt the queue"
            )
        if cfg.loss_impl in ("fused", "ring"):
            raise ValueError(
                f"--moco_queue extends the contrast side past the fixed "
                f"2B geometry the {cfg.loss_impl!r} kernel tiles — use "
                "--loss_impl dense (or auto, which resolves to dense)"
            )
    if not 0.0 <= cfg.ema_momentum < 1.0:
        raise ValueError(
            f"--ema_momentum must be in [0, 1), got {cfg.ema_momentum}"
        )
    for name in ("vicreg_sim_coeff", "vicreg_std_coeff", "vicreg_cov_coeff"):
        if getattr(cfg, name) < 0:
            raise ValueError(
                f"--{name} must be >= 0, got {getattr(cfg, name)}"
            )


def parse_supcon(argv=None) -> SupConConfig:
    ns = supcon_parser().parse_args(argv)
    kwargs = vars(ns)
    kwargs["lr_decay_epochs"] = tuple(int(x) for x in kwargs["lr_decay_epochs"].split(","))
    cfg = SupConConfig(**kwargs)
    return finalize_supcon(cfg)


def finalize_supcon(cfg: SupConConfig, make_dirs: bool = True) -> SupConConfig:
    """Derived fields, replicating main_supcon.py:92-150."""
    validate_data_placement(cfg.dataset, cfg.data_placement)
    validate_conv_impl(cfg)
    validate_recipe(cfg)
    if cfg.dataset == "path":
        assert cfg.data_folder is not None and cfg.mean is not None and cfg.std is not None
    if cfg.data_folder is None:
        cfg.data_folder = "./datasets/"

    cfg.model_name = (
        f"{cfg.method}_{cfg.dataset}_{cfg.model}_lr_{cfg.learning_rate}"
        f"_decay_{cfg.weight_decay}_bsz_{cfg.batch_size}_temp_{cfg.temp}_trial_{cfg.trial}"
    )
    if cfg.cosine:
        cfg.model_name = f"{cfg.model_name}_cosine"
    if cfg.sec:
        cfg.model_name = f"{cfg.model_name}_sec"
    if cfg.batch_size > 256:
        cfg.warm = True
    if cfg.warm:
        cfg.model_name = f"{cfg.model_name}_warm"
        cfg.warmup_from = 0.01
        cfg.warm_epochs = 10
        cfg.warmup_to = warmup_to_value(
            cfg.learning_rate, cfg.lr_decay_rate, cfg.warm_epochs, cfg.epochs, cfg.cosine
        )

    now_time = datetime.datetime.now().strftime("%m%d_%H%M")
    prefix = f"{cfg.dataset}_{now_time}_"
    model_path = os.path.join(cfg.workdir, f"{cfg.dataset}_models")
    tb_path = os.path.join(cfg.workdir, f"{cfg.dataset}_tensorboard")
    cfg.tb_folder = os.path.join(tb_path, prefix + cfg.model_name)
    cfg.save_folder = os.path.join(model_path, prefix + cfg.model_name)
    if make_dirs and is_main_process():
        os.makedirs(cfg.tb_folder, exist_ok=True)
        os.makedirs(cfg.save_folder, exist_ok=True)
    return cfg


@dataclasses.dataclass
class LinearConfig:
    """Probe config (main_linear.py:21-116); also serves the CE baseline."""

    print_freq: int = 10
    save_freq: int = 10
    batch_size: int = 512
    num_workers: int = 16
    epochs: int = 100
    learning_rate: float = 0.1
    lr_decay_epochs: Tuple[int, ...] = (60, 75, 90)
    lr_decay_rate: float = 0.2
    weight_decay: float = 0.0
    momentum: float = 0.9
    model: str = "resnet50"
    dataset: str = "cifar10"  # {cifar10, cifar100, synthetic, synthetic_hard, synthetic_hard32}
    cosine: bool = False
    warm: bool = False
    # CE trainer only: per-device vs synchronized BN, same conditional the
    # reference's pretrain applies (main_supcon.py:223-224); default off =
    # per-device statistics. The probe ignores it (frozen eval-mode encoder).
    syncBN: bool = False
    download: bool = True  # fetch CIFAR if absent (torchvision parity)
    ckpt: str = ""
    # TPU-native additions
    # CE trainer only: full-state (step-granular) resume, same semantics as
    # the pretrain --resume; the probe ignores it (no full-state checkpoints)
    resume: str = ""
    data_folder: str = "./datasets/"
    size: int = 32
    val_batch_size: int = 256  # main_ce.py:64-66
    bf16: bool = False
    seed: int = 0
    workdir: str = "./work_space"
    trial: str = "0"
    compile_cache: str = "auto"  # same semantics as the pretrain flag
    telemetry: str = "async"  # same semantics as the pretrain flag
    data_placement: str = "auto"  # same semantics as the pretrain flag
    data_window_batches: int = 32  # same semantics as the pretrain flag
    device_budget_mb: int = 0  # same semantics as the pretrain flag
    # jax.profiler trace capture — previously pretrain-only, so the probe/CE
    # stages could not capture an xplane window (utils/profiling.StepTracer)
    trace_dir: str = ""
    trace_start_step: int = 10
    trace_steps: int = 10
    flight_recorder: str = "on"  # same semantics as the pretrain flag
    watchdog_secs: float = 0.0  # same semantics as the pretrain flag
    metrics_port: int = 0  # same semantics as the pretrain flag
    metrics_host: str = "127.0.0.1"  # same semantics as the pretrain flag
    # derived
    n_cls: int = 10
    warm_epochs: int = 10
    warmup_from: float = 0.01
    warmup_to: float = 0.0
    model_name: str = ""
    tb_folder: str = ""
    save_folder: str = ""


def linear_parser(ce: bool = False) -> argparse.ArgumentParser:
    d = LinearConfig()
    p = argparse.ArgumentParser("argument for training")
    p.add_argument("--print_freq", type=int, default=d.print_freq)
    p.add_argument("--save_freq", type=int, default=d.save_freq)
    p.add_argument("--batch_size", type=int, default=d.batch_size)
    p.add_argument("--num_workers", type=int, default=d.num_workers)
    p.add_argument("--epochs", type=int, default=d.epochs)
    p.add_argument("--learning_rate", type=float, default=d.learning_rate)
    p.add_argument("--lr_decay_epochs", type=str, default="60,75,90")
    p.add_argument("--lr_decay_rate", type=float, default=d.lr_decay_rate)
    p.add_argument("--weight_decay", type=float, default=d.weight_decay)
    p.add_argument("--momentum", type=float, default=d.momentum)
    p.add_argument("--model", type=str, default=d.model)
    p.add_argument("--dataset", type=str, default=d.dataset,
                   choices=["cifar10", "cifar100", "synthetic", "synthetic_hard", "synthetic_hard32"])
    _add_bool_flag(p, "cosine")
    _add_bool_flag(p, "warm")
    if ce:
        _add_bool_flag(p, "syncBN")
        p.add_argument("--resume", type=str, default=d.resume,
                       help="checkpoint (or run dir) to resume from")
    if not ce:
        p.add_argument("--ckpt", type=str, default=d.ckpt,
                       help="path to pre-trained model checkpoint dir")
        p.add_argument("--resume", type=str, default=d.resume,
                       help="accepted for the exit-75 launcher contract "
                            "(re-run the same command with --resume); the "
                            "probe keeps no full-state checkpoints, so it "
                            "retrains from scratch")
    p.add_argument("--data_folder", type=str, default=d.data_folder)
    p.add_argument("--no_download", dest="download", action="store_false",
                   default=True, help="never fetch CIFAR over the network")
    p.add_argument("--val_batch_size", type=int, default=d.val_batch_size)
    _add_bool_flag(p, "bf16")
    p.add_argument("--seed", type=int, default=d.seed)
    p.add_argument("--workdir", type=str, default=d.workdir)
    p.add_argument("--trial", type=str, default=d.trial)
    _add_shared_runtime_flags(p, d)
    _add_observability_flags(p, d)
    return p


def parse_linear(argv=None, ce: bool = False) -> LinearConfig:
    ns = linear_parser(ce=ce).parse_args(argv)
    kwargs = vars(ns)
    kwargs["lr_decay_epochs"] = tuple(int(x) for x in kwargs["lr_decay_epochs"].split(","))
    cfg = LinearConfig(**kwargs)
    return finalize_linear(cfg, prefix="ce_" if ce else "classifier_")


def finalize_linear(
    cfg: LinearConfig, prefix: str = "classifier_", make_dirs: bool = True
) -> LinearConfig:
    """Derived fields, replicating main_linear.py:65-114."""
    cfg.model_name = (
        f"{cfg.dataset}_{cfg.model}_lr_{cfg.learning_rate}"
        f"_decay_{cfg.weight_decay}_bsz_{cfg.batch_size}"
    )
    if cfg.cosine:
        cfg.model_name = f"{cfg.model_name}_cosine"
    if cfg.warm:
        cfg.model_name = f"{cfg.model_name}_warm"
        cfg.warmup_from = 0.01
        cfg.warm_epochs = 10
        cfg.warmup_to = warmup_to_value(
            cfg.learning_rate, cfg.lr_decay_rate, cfg.warm_epochs, cfg.epochs, cfg.cosine
        )
    cfg.n_cls = {"cifar10": 10, "cifar100": 100, "synthetic": 10, "synthetic_hard": 10,
                 "synthetic_hard32": 32}[cfg.dataset]

    now_time = datetime.datetime.now().strftime("%m%d_%H%M")
    run = prefix + now_time + "_"
    cfg.tb_folder = os.path.join(cfg.workdir, f"{cfg.dataset}_tensorboard", run + cfg.model_name)
    cfg.save_folder = os.path.join(cfg.workdir, f"{cfg.dataset}_models", run + cfg.model_name)
    if make_dirs and is_main_process():
        os.makedirs(cfg.tb_folder, exist_ok=True)
        os.makedirs(cfg.save_folder, exist_ok=True)
    return cfg


def config_dict(cfg) -> dict:
    """JSON-safe config for checkpoint metadata (unlike the reference, which
    pickles the whole namespace incl. a live tensor, util.py:89-94)."""
    out = {}
    for k, v in dataclasses.asdict(cfg).items():
        out[k] = list(v) if isinstance(v, tuple) else v
    return out
