"""Hot-loop sync lint: the zero-sync contract as a static property.

The dispatch-only hot loop is the repo's core perf invariant (PR 4/5/6:
between flush boundaries the main thread only dispatches — one ring D2H
per window, one index/window upload per epoch/window, nothing else). Until
now it was proven dynamically, one configuration at a time, by the
mechanical transfer-count tests. This rule makes it a whole-tree static
property over two region kinds:

- **jitted step builders**: any local function passed directly to
  ``jax.jit``/``jit`` (or decorated with it). Host-sync constructs inside
  would either crash at trace time (``float`` on a tracer) or silently
  constant-fold — both review-time findings;
- **boundary loops**: the innermost ``for``/``while`` enclosing a call
  that reaches ``TelemetrySession.flush_boundary`` (directly or through a
  local helper like the drivers' ``submit_window``) — exactly the
  boundary-to-boundary driver loops the zero-sync contract covers;
- **Pallas kernel builders**: any local function handed to
  ``pl.pallas_call`` as the kernel — directly, or through a
  ``functools.partial(<kernel>, ...)`` (possibly via an intermediate
  assignment, the ops/pallas_loss.py / ops/pallas_conv.py shape). A host
  sync inside a kernel body would either fail the TPU lowering or
  silently constant-fold in interpret mode while the compiled path
  diverges — both review-time findings.

Forbidden inside: ``jax.device_get``, ``.block_until_ready()``,
``.item()``, ``np.asarray``/``np.array`` (a device->host materialization),
and ``float()``/``bool()`` on non-literals (``__float__``/``__bool__`` on
a jax array is a blocking D2H). A DESIGNED sync point is annotated in
source with ``# sync-ok: <reason>`` on (or directly above) the line — the
annotation is the flush-boundary registry; a bare marker without a reason
is itself a finding.
"""

from __future__ import annotations

import ast
from typing import Iterator, List, Set, Tuple

from simclr_pytorch_distributed_tpu.analysis import callgraph
from simclr_pytorch_distributed_tpu.analysis.core import (
    Finding,
    LintModule,
    call_name,
    dotted_prefix,
)

RULE_LOOP = "hot-loop-sync:boundary-loop"
RULE_JIT = "hot-loop-sync:jitted-fn"
RULE_KERNEL = "hot-loop-sync:pallas-kernel"
RULE_ANNOTATION = "hot-loop-sync:annotation-missing-reason"

_SYNC_METHODS = frozenset({"block_until_ready", "item"})
_SYNC_CALLS = frozenset({"device_get"})
_NUMPY_MODULES = frozenset({"np", "numpy", "onp"})
_NUMPY_SYNC_FNS = frozenset({"asarray", "array"})
_SYNC_BUILTINS = frozenset({"float", "bool"})


def _sync_construct(node: ast.AST) -> str:
    """Non-empty description when ``node`` is a sync-forcing call."""
    if not isinstance(node, ast.Call):
        return ""
    name = call_name(node)
    if name in _SYNC_CALLS:
        return f"{name}() is a blocking device->host transfer"
    if name in _SYNC_METHODS and isinstance(node.func, ast.Attribute):
        return f".{name}() forces a device sync"
    if name in _NUMPY_SYNC_FNS and dotted_prefix(node) in _NUMPY_MODULES:
        return (
            f"{dotted_prefix(node)}.{name}() materializes its argument on "
            "the host (blocking D2H for device arrays)"
        )
    if (
        name in _SYNC_BUILTINS
        and isinstance(node.func, ast.Name)
        and node.args
        and not isinstance(node.args[0], ast.Constant)
    ):
        return (
            f"{name}() on a non-literal: __{name}__ on a traced/device "
            "value is a blocking readback"
        )
    return ""


def _jitted_functions(mod: LintModule) -> Set[ast.AST]:
    """Function defs compiled by jit: passed as jit's first positional
    argument, or decorated with @jit/@jax.jit/@partial(jax.jit, ...)."""
    by_name = {}
    for node in ast.walk(mod.tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            by_name.setdefault(node.name, []).append(node)
    out: Set[ast.AST] = set()
    for node in ast.walk(mod.tree):
        if isinstance(node, ast.Call) and call_name(node) == "jit" \
                and node.args and isinstance(node.args[0], ast.Name):
            for fn in by_name.get(node.args[0].id, ()):
                out.add(fn)
    for node in ast.walk(mod.tree):
        if not isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            continue
        for dec in node.decorator_list:
            if call_name(dec) == "jit" or (
                isinstance(dec, (ast.Name, ast.Attribute))
                and (getattr(dec, "id", None) == "jit"
                     or getattr(dec, "attr", None) == "jit")
            ):
                out.add(node)
            elif isinstance(dec, ast.Call) and call_name(dec) == "partial" \
                    and any(
                        (getattr(a, "id", None) == "jit"
                         or getattr(a, "attr", None) == "jit")
                        for a in dec.args
                    ):
                out.add(node)
    return out


def _pallas_kernel_functions(mod: LintModule) -> Set[ast.AST]:
    """Function defs handed to ``pallas_call`` as the kernel: the first
    positional argument as a bare Name, an inline
    ``functools.partial(<def>, ...)``, or a Name bound earlier in the
    module to such a partial (the ops/pallas_loss.py builder shape)."""
    by_name: dict = {}
    for node in ast.walk(mod.tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            by_name.setdefault(node.name, []).append(node)
    # var name -> EVERY function it is bound to via functools.partial,
    # module-wide: builders routinely reuse one local name ('kernel ='),
    # and a linter must over-approximate — resolving only the last
    # binding would silently drop all but one kernel from coverage
    partial_of: dict = {}
    for node in ast.walk(mod.tree):
        if (
            isinstance(node, ast.Assign)
            and len(node.targets) == 1
            and isinstance(node.targets[0], ast.Name)
            and isinstance(node.value, ast.Call)
            and call_name(node.value) == "partial"
            and node.value.args
            and isinstance(node.value.args[0], ast.Name)
        ):
            partial_of.setdefault(node.targets[0].id, set()).add(
                node.value.args[0].id
            )
    out: Set[ast.AST] = set()
    for node in ast.walk(mod.tree):
        if not (
            isinstance(node, ast.Call)
            and call_name(node) == "pallas_call"
            and node.args
        ):
            continue
        arg = node.args[0]
        names = []
        if isinstance(arg, ast.Name):
            names.append(arg.id)
            names.extend(partial_of.get(arg.id, ()))
        elif (
            isinstance(arg, ast.Call)
            and call_name(arg) == "partial"
            and arg.args
            and isinstance(arg.args[0], ast.Name)
        ):
            names.append(arg.args[0].id)
        for nm in names:
            out.update(by_name.get(nm, ()))
    return out


def _boundary_loops(mod: LintModule) -> Set[ast.AST]:
    """Innermost loops enclosing a flush-boundary call — direct, or via a
    LOCAL helper (a function defined inside the same enclosing function,
    the drivers' ``submit_window`` shape). Module-level functions that
    reach the boundary (``train_one_epoch``) are deliberately not loop
    markers: the loop that calls one is the per-EPOCH driver loop, whose
    once-per-epoch host syncs (validation, TB schedule eval) sit outside
    the boundary-to-boundary contract."""
    reachers = callgraph.flush_boundary_reachers(mod)
    local_defs: dict = {}
    for node in ast.walk(mod.tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            local_defs.setdefault(node.name, []).append(node)
    loops: Set[ast.AST] = set()
    for node in ast.walk(mod.tree):
        if not isinstance(node, ast.Call):
            continue
        name = call_name(node)
        hits = name == "flush_boundary"
        if not hits and isinstance(node.func, ast.Name) and name in reachers:
            owner = mod.enclosing_function(node)
            hits = owner is not None and any(
                mod.enclosing_function(d) is owner
                for d in local_defs.get(name, ())
            )
        if not hits:
            continue
        cur = mod.parent(node)
        while cur is not None:
            if isinstance(cur, (ast.For, ast.While, ast.AsyncFor)):
                loops.add(cur)
                break
            if isinstance(cur, (ast.FunctionDef, ast.AsyncFunctionDef,
                                ast.Lambda)):
                break  # the call runs in its own scope, not in this loop
            cur = mod.parent(cur)
    return loops


def _region_nodes(region: ast.AST) -> Iterator[ast.AST]:
    """Nodes executing in the region per iteration/trace: the subtree minus
    nested function bodies (a nested def runs on ITS call — the drivers'
    consume() callbacks run on the telemetry thread, where host syncs are
    the design)."""
    stack = list(ast.iter_child_nodes(region))
    while stack:
        node = stack.pop()
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.Lambda)):
            continue
        yield node
        stack.extend(ast.iter_child_nodes(node))


def check_module(mod: LintModule) -> List[Finding]:
    findings: List[Finding] = []
    regions: List[Tuple[str, str, ast.AST]] = []
    for fn in _jitted_functions(mod):
        regions.append((RULE_JIT, fn.name, fn))
    for fn in _pallas_kernel_functions(mod):
        regions.append((RULE_KERNEL, fn.name, fn))
    for loop in _boundary_loops(mod):
        owner = mod.enclosing_function(loop)
        owner_name = owner.name if owner is not None else "<module>"
        regions.append((RULE_LOOP, owner_name, loop))

    for rule, region_name, region in regions:
        for node in _region_nodes(region):
            desc = _sync_construct(node)
            if not desc:
                continue
            reason = mod.sync_ok_reason(node.lineno)
            sym = call_name(node)
            key = f"{rule}:{mod.rel}:{region_name}:{sym}"
            if reason:
                continue  # annotated flush-boundary site, reason recorded
            if reason is not None:  # marker present but empty
                findings.append(Finding(
                    rule=RULE_ANNOTATION, file=mod.rel, line=node.lineno,
                    why=(
                        "sync-ok annotation without a reason: every "
                        "designed sync point must record WHY it is outside "
                        "the zero-sync contract"
                    ),
                    allowlist_key=f"{RULE_ANNOTATION}:{mod.rel}:"
                                  f"{region_name}:{sym}",
                ))
                continue
            where = {
                RULE_JIT: "a jitted step function",
                RULE_KERNEL: "a Pallas kernel builder",
            }.get(rule, "a flush-boundary hot loop")
            findings.append(Finding(
                rule=rule, file=mod.rel, line=node.lineno,
                why=(
                    f"{desc} inside {where} ({region_name!r}): the "
                    "dispatch-only/zero-sync contract forbids host syncs "
                    "here — move it behind the flush boundary or annotate "
                    "a designed site with '# sync-ok: <reason>'"
                ),
                allowlist_key=key,
            ))
    return findings
