"""Collective classification + the module-level call graph.

The repo's host-level collectives (the calls whose SCHEDULE must match
across processes — docs/RESILIENCE.md, utils/telemetry.py): a process that
skips one while its peers enter it deadlocks the pod. They are reached
both as bare imports and as attributes (``preempt.requested_global``,
``telemetry.flush_boundary``), so classification is by TERMINAL name
(core.call_name), and reachability closes over same-module function calls
(a driver calling its local ``submit_window`` helper reaches the
collective inside it).

In-program collectives (``lax.ppermute``/``psum`` under jit) are
deliberately NOT here: inside one compiled SPMD program the schedule is
XLA's problem; the deadlock class this lint targets is the HOST-level
call-schedule divergence.
"""

from __future__ import annotations

import ast
from typing import Dict, Set

from simclr_pytorch_distributed_tpu.analysis.core import (
    LintModule,
    call_name,
    scope_nodes,
)

# Host-level collective primitives and the repo functions that wrap them
# (parallel/collectives.py, parallel/mesh.py, utils/preempt.py,
# utils/telemetry.py, data/device_store.py, utils/checkpoint.py — orbax
# multi-process saves are collective: every process must call save/wait).
COLLECTIVE_CALLS = frozenset({
    # jax multihost primitives
    "process_allgather", "broadcast_one_to_all", "sync_global_devices",
    # parallel/mesh.py + parallel/collectives.py wrappers
    "broadcast_from_main", "sync_processes", "gather_global_labels",
    # utils/preempt.py
    "requested_global", "emergency_save_and_exit",
    # utils/telemetry.py (flush_boundary/drain_global/finish_epoch all
    # contain the failure-code allgather)
    "check_failures_global", "drain_global", "flush_boundary",
    "finish_epoch",
    # data/device_store.py (placement resolution allgathers per rung)
    "_agree_across_processes", "resolve_data_placement", "make_store",
    # utils/checkpoint.py (orbax multi-process saves are collective)
    "save_checkpoint", "wait_for_saves",
})

# Calls whose value is PROCESS-DEPENDENT (differs across processes): a
# branch on one selects different collective schedules on different hosts.
PROCESS_DEPENDENT_CALLS = frozenset({"is_main_process", "process_index"})

# Process-UNIFORM runtime queries (same value everywhere) — listed so the
# classifier's intent is explicit: ``if jax.process_count() == 1: ...`` is
# the repo's standard single-process short-circuit, NOT a hazard.
PROCESS_UNIFORM_CALLS = frozenset({"process_count"})


def reaching_functions(mod: LintModule, targets: frozenset) -> Set[str]:
    """Names of functions in ``mod`` that (transitively, via same-module
    bare-name calls) make a call whose terminal name is in ``targets``.

    The fixed point runs over ALL function defs in the module, module-level
    and nested alike, keyed by bare name — the resolution a same-module
    call site actually uses.
    """
    calls: Dict[str, Set[str]] = {}
    for node in ast.walk(mod.tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            called = set()
            for sub in ast.walk(node):
                name = call_name(sub)
                if name:
                    called.add(name)
            # a name defined twice keeps the union (conservative)
            calls.setdefault(node.name, set()).update(called)

    reaching: Set[str] = {
        name for name, called in calls.items() if called & targets
    }
    changed = True
    while changed:
        changed = False
        for name, called in calls.items():
            if name not in reaching and called & reaching:
                reaching.add(name)
                changed = True
    return reaching


def collective_reachers(mod: LintModule) -> Set[str]:
    return reaching_functions(mod, COLLECTIVE_CALLS)


def is_collective_call(node: ast.AST, reachers: Set[str]) -> bool:
    """Does this Call node enter a collective (directly or via a
    same-module function known to reach one)?"""
    name = call_name(node)
    if name is None:
        return False
    if name in COLLECTIVE_CALLS:
        return True
    # transitive resolution only for BARE-name calls: attribute calls
    # resolve to other objects' methods, which terminal-name matching
    # already covered above
    return isinstance(node.func, ast.Name) and name in reachers


def expr_is_process_dependent(expr: ast.AST) -> bool:
    """Does evaluating ``expr`` read a per-process value? (Calls to
    ``is_main_process``/``process_index`` anywhere inside — bare or as
    attributes — make a test process-dependent; ``process_count`` does
    not.)"""
    for node in ast.walk(expr):
        name = call_name(node)
        if name in PROCESS_DEPENDENT_CALLS:
            return True
    return False


def flush_boundary_reachers(mod: LintModule) -> Set[str]:
    """Functions reaching the telemetry flush boundary — the hot-loop
    rule's loop marker (rule_hotloop)."""
    return reaching_functions(mod, frozenset({"flush_boundary"}))
