"""Donation-safety lint: no read of a donated binding after donation.

The bug class this rule reconstructs has bitten this repo three times
(PR 1 twice, PR 12 once): a buffer handed to a ``jit(...,
donate_argnums=...)`` program is DELETED on dispatch — any later host read
(or re-dispatch of the same object) touches freed/aliased device memory:
a segfault on a good day, silently torn state on a bad one.

Statically: a call through a donating callable whose donated argument is a
plain Name that the call's own statement does NOT rebind, followed by a
later lexical read of that Name in the same scope (or the same call again
from inside a loop), is a finding.

Donating callables are found three ways, all per-module with a shared
cross-module seed registry:

- a ``jax.jit``/``jit`` call with a literal ``donate_argnums=...``;
- a function whose return sites are such jit calls (a step BUILDER: the
  intersection of the return sites' donated positions — only positions
  donated in EVERY variant are assumed, so the scalar/ring signature split
  in ``make_fused_update`` doesn't over-claim);
- the repo's known builder/parameter names (``make_fused_update``,
  ``jit_scalar_or_ring_step``, drivers' ``update_fn``/``train_jit``
  parameters) so the real call sites in the drivers are checked even
  though the jit happens a module away.
"""

from __future__ import annotations

import ast
from typing import Dict, List, Optional, Tuple

from simclr_pytorch_distributed_tpu.analysis.core import (
    Finding,
    LintModule,
    assigned_names,
    call_name,
    end_line,
    scope_nodes,
    statement_of,
)

RULE = "donation-safety:post-donation-read"

# Cross-module seed: builders that RETURN a donating callable, with the
# donated positions their returned callables share across variants
# (train/supcon.make_fused_update, train/linear.jit_scalar_or_ring_step:
# position 0 = the TrainState; the ring at position 1 is donated only in
# ring mode, so it is deliberately not assumed).
KNOWN_DONATING_BUILDERS: Dict[str, Tuple[int, ...]] = {
    "make_fused_update": (0,),
    "jit_scalar_or_ring_step": (0,),
}

# Parameter names through which the drivers receive a donating step
# callable (train_one_epoch's ``update_fn``, the probe/CE loops'
# ``train_jit``): the jit lives a module away, but the call sites these
# names mark are exactly where the PR-1 bugs lived.
KNOWN_DONATING_PARAMS: Dict[str, Tuple[int, ...]] = {
    "update_fn": (0,),
    "train_jit": (0,),
}


def _donate_argnums(call: ast.Call) -> Optional[Tuple[int, ...]]:
    """Literal donate_argnums of a jit call, or None."""
    if call_name(call) != "jit":
        return None
    for kw in call.keywords:
        if kw.arg == "donate_argnums":
            v = kw.value
            if isinstance(v, ast.Tuple):
                out = []
                for e in v.elts:
                    if isinstance(e, ast.Constant) and isinstance(e.value, int):
                        out.append(e.value)
                    else:
                        return None
                return tuple(out)
            if isinstance(v, ast.Constant) and isinstance(v.value, int):
                return (v.value,)
            return None
    return None


def _module_donating_builders(mod: LintModule) -> Dict[str, Tuple[int, ...]]:
    """Function names in ``mod`` whose return value donates: direct
    ``return jit(..., donate_argnums=...)`` sites, plus functions whose
    return is a call to an already-known builder. Positions = the
    intersection over all donating return sites."""
    builders: Dict[str, Tuple[int, ...]] = dict(KNOWN_DONATING_BUILDERS)
    changed = True
    while changed:
        changed = False
        for node in ast.walk(mod.tree):
            if not isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                continue
            if node.name in builders:
                continue
            positions: Optional[set] = None
            saw_donating_return = False
            for sub in ast.walk(node):
                if not (isinstance(sub, ast.Return) and sub.value is not None):
                    continue
                ret = sub.value
                pos = None
                if isinstance(ret, ast.Call):
                    pos = _donate_argnums(ret)
                    if pos is None and call_name(ret) in builders:
                        pos = builders[call_name(ret)]
                if pos is not None:
                    saw_donating_return = True
                    positions = (
                        set(pos) if positions is None
                        else positions & set(pos)
                    )
            if saw_donating_return and positions:
                builders[node.name] = tuple(sorted(positions))
                changed = True
    return builders


def _scope_donating_vars(
    mod: LintModule, scope: ast.AST, builders: Dict[str, Tuple[int, ...]],
) -> Dict[str, Tuple[int, ...]]:
    """Names in ``scope`` bound to a donating callable: direct
    ``x = jit(..., donate_argnums=...)`` / ``x = <builder>(...)``
    assignments, plus the known donating parameter names when ``scope``
    declares them."""
    out: Dict[str, Tuple[int, ...]] = {}
    if isinstance(scope, (ast.FunctionDef, ast.AsyncFunctionDef)):
        for arg in list(scope.args.args) + list(scope.args.kwonlyargs):
            if arg.arg in KNOWN_DONATING_PARAMS:
                out[arg.arg] = KNOWN_DONATING_PARAMS[arg.arg]
    for node in scope_nodes(mod, scope):
        if not isinstance(node, ast.Assign):
            continue
        if not (len(node.targets) == 1
                and isinstance(node.targets[0], ast.Name)):
            continue
        value = node.value
        if not isinstance(value, ast.Call):
            continue
        pos = _donate_argnums(value)
        if pos is None and call_name(value) in builders \
                and isinstance(value.func, (ast.Name, ast.Attribute)):
            pos = builders[call_name(value)]
        if pos:
            out[node.targets[0].id] = pos
    return out


def _enclosing_loop(mod: LintModule, node: ast.AST, scope: ast.AST):
    cur = mod.parent(node)
    while cur is not None and cur is not scope:
        if isinstance(cur, (ast.For, ast.While, ast.AsyncFor)):
            return cur
        if isinstance(cur, (ast.FunctionDef, ast.AsyncFunctionDef,
                            ast.Lambda)):
            return None
        cur = mod.parent(cur)
    return None


def check_module(mod: LintModule) -> List[Finding]:
    findings: List[Finding] = []
    builders = _module_donating_builders(mod)

    for scope_name, scope in mod.function_scopes():
        donating = _scope_donating_vars(mod, scope, builders)
        if not donating:
            continue
        # loads of each name, by line, for the post-donation scan
        loads: Dict[str, List[ast.Name]] = {}
        for node in scope_nodes(mod, scope):
            if isinstance(node, ast.Name) and isinstance(node.ctx, ast.Load):
                loads.setdefault(node.id, []).append(node)

        for node in scope_nodes(mod, scope):
            if not isinstance(node, ast.Call):
                continue
            fn_name = None
            if isinstance(node.func, ast.Name):
                fn_name = node.func.id
            if fn_name not in donating:
                continue
            stmt = statement_of(mod, node)
            rebound = assigned_names(stmt)
            for pos in donating[fn_name]:
                if pos >= len(node.args):
                    continue
                arg = node.args[pos]
                if not isinstance(arg, ast.Name):
                    continue
                donated = arg.id
                if donated in rebound:
                    continue  # the canonical `state, ring = f(state, ring)`
                key = f"{RULE}:{mod.rel}:{scope_name}:{fn_name}:{donated}"
                later = [
                    n for n in loads.get(donated, ())
                    if n.lineno > end_line(stmt) and n is not arg
                ]
                if later:
                    first = min(later, key=lambda n: n.lineno)
                    findings.append(Finding(
                        rule=RULE, file=mod.rel, line=first.lineno,
                        why=(
                            f"{donated!r} is donated to {fn_name}() at line "
                            f"{node.lineno} (its device buffers are deleted "
                            "on dispatch) but read again here without being "
                            "rebound by the donating call — the PR-1 "
                            "use-after-donation class (segfault or torn "
                            "state)"
                        ),
                        allowlist_key=key,
                    ))
                    continue
                loop = _enclosing_loop(mod, node, scope)
                if loop is not None:
                    # not rebound by the call's own statement: is it rebound
                    # anywhere else in the loop before the next iteration?
                    rebinds_in_loop = any(
                        donated in assigned_names(s)
                        for s in ast.walk(loop) if isinstance(s, ast.stmt)
                    )
                    if not rebinds_in_loop:
                        findings.append(Finding(
                            rule=RULE, file=mod.rel, line=node.lineno,
                            why=(
                                f"{donated!r} is donated to {fn_name}() "
                                "inside a loop without ever being rebound: "
                                "the next iteration re-dispatches a deleted "
                                "buffer"
                            ),
                            allowlist_key=key,
                        ))
    return findings
