"""Contract-registry checks: the repo's writer/reader registries, pinned.

Three shared-registry contracts hold this codebase's data plumbing
together, and each has a static shape a reviewer can miss:

- **metric-key tuples** (``*_METRIC_KEYS``, recipes' ``metric_keys``): the
  ring column order is ``sorted(keys)`` derived on BOTH the jitted writer
  and the host reader (train/supcon_step.metric_keys), so declarations
  must be sorted + unique (a duplicate silently halves the column count,
  an unsorted literal misleads every reader of the declaration) and each
  registry name must have ONE defining module — readers import it, they
  never re-type it (a re-typed copy is exactly the writer/reader drift the
  trace-time check cannot see until the configs collide);
- **schema stamps**: evidence scripts pin their artifact schema in a
  module constant (``SCHEMA = "x/v1"``) that ``build_output`` references —
  a dict literal carrying a hardcoded ``"schema": "..."`` string bypasses
  the pin, so the gate and the writer can drift;
- **shared trainer flags**: flags the three trainers share must be
  registered through the shared helpers in ``config.py``
  (``_add_shared_runtime_flags``/``_add_observability_flags``) — the rule
  verifies USAGE (each registry flag reaches both parsers through one
  helper, dataclass defaults agree) instead of three hand-synced copies,
  and any flag present in several parsers must agree on its argparse
  TYPE (an int/float drift changes parsing silently).
"""

from __future__ import annotations

import ast
import re
from typing import Dict, List, Optional, Tuple

from simclr_pytorch_distributed_tpu.analysis.core import (
    Finding,
    LintModule,
    call_name,
)

RULE_KEYS_SORTED = "contract-registry:metric-keys-unsorted"
RULE_KEYS_DUP = "contract-registry:metric-keys-multi-source"
RULE_SCHEMA = "contract-registry:schema-literal-unpinned"
RULE_FLAG_TYPE = "contract-registry:flag-type-mismatch"
RULE_FLAG_DEFAULT = "contract-registry:flag-default-mismatch"
RULE_FLAG_INLINE = "contract-registry:shared-flag-not-shared"

_METRIC_KEYS_RE = re.compile(r"^[A-Z0-9_]*METRIC_KEYS$")

# The flags every trainer shares (the runtime/observability surface —
# docs/OBSERVABILITY.md, --telemetry/--data_placement family). These must
# be registered by ONE shared helper and their dataclass defaults must
# agree across configs; recipe hyperparameters (--learning_rate & co)
# deliberately differ per stage and are only type-checked.
SHARED_RUNTIME_FLAGS = frozenset({
    "telemetry", "data_placement", "data_window_batches",
    "device_budget_mb", "compile_cache",
    "trace_dir", "trace_start_step", "trace_steps",
    "flight_recorder", "watchdog_secs", "metrics_port", "metrics_host",
})


# -- metric-key tuples ----------------------------------------------------

def _literal_str_tuple(node: ast.AST) -> Optional[Tuple[str, ...]]:
    if isinstance(node, ast.Tuple) and all(
        isinstance(e, ast.Constant) and isinstance(e.value, str)
        for e in node.elts
    ):
        return tuple(e.value for e in node.elts)
    return None


def _metric_key_assignments(mod: LintModule):
    """``(name, values, lineno)`` for every metric-key tuple literal —
    module-level ``*_METRIC_KEYS`` constants and class-level
    ``metric_keys`` recipe declarations alike."""
    for node in ast.walk(mod.tree):
        targets = []
        if isinstance(node, ast.Assign):
            targets, value = node.targets, node.value
        elif isinstance(node, ast.AnnAssign) and node.value is not None:
            targets, value = [node.target], node.value
        else:
            continue
        for t in targets:
            if not isinstance(t, ast.Name):
                continue
            if not (_METRIC_KEYS_RE.match(t.id) or t.id == "metric_keys"):
                continue
            values = _literal_str_tuple(value)
            if values is not None:
                yield t.id, values, node.lineno


def check_metric_keys(mods: List[LintModule]) -> List[Finding]:
    findings: List[Finding] = []
    definers: Dict[str, List[str]] = {}
    for mod in mods:
        for name, values, lineno in _metric_key_assignments(mod):
            expect = tuple(sorted(set(values)))
            if values != expect:
                findings.append(Finding(
                    rule=RULE_KEYS_SORTED, file=mod.rel, line=lineno,
                    why=(
                        f"{name} = {values!r} is not sorted+unique "
                        f"(expected {expect!r}): the ring column order is "
                        "sorted(keys) on writer AND reader, so the "
                        "declaration must read in column order and carry "
                        "no duplicates"
                    ),
                    allowlist_key=f"{RULE_KEYS_SORTED}:{mod.rel}:{name}",
                ))
            if _METRIC_KEYS_RE.match(name):
                definers.setdefault(name, []).append(mod.rel)
    for name, files in sorted(definers.items()):
        if len(files) > 1:
            for rel in files[1:]:
                findings.append(Finding(
                    rule=RULE_KEYS_DUP, file=rel, line=0,
                    why=(
                        f"{name} is literally re-defined here AND in "
                        f"{files[0]}: ring registries have one source — "
                        "readers must import it, or the writer/reader "
                        "column derivations drift"
                    ),
                    allowlist_key=f"{RULE_KEYS_DUP}:{rel}:{name}",
                ))
    return findings


# -- schema stamps --------------------------------------------------------

def check_schema_stamps(mods: List[LintModule]) -> List[Finding]:
    findings: List[Finding] = []
    for mod in mods:
        if not mod.rel.startswith("scripts/"):
            continue
        for node in ast.walk(mod.tree):
            if not isinstance(node, ast.Dict):
                continue
            for k, v in zip(node.keys, node.values):
                if not (isinstance(k, ast.Constant) and k.value == "schema"):
                    continue
                if isinstance(v, ast.Constant) and isinstance(v.value, str):
                    findings.append(Finding(
                        rule=RULE_SCHEMA, file=mod.rel, line=v.lineno,
                        why=(
                            f'hardcoded "schema": {v.value!r} in a dict '
                            "literal: pin it to a module-level *SCHEMA* "
                            "constant so the writer and every gate/reader "
                            "reference one definition"
                        ),
                        allowlist_key=f"{RULE_SCHEMA}:{mod.rel}:{v.value}",
                    ))
    return findings


# -- shared trainer flags -------------------------------------------------

def _flag_registrations(fn: ast.AST) -> List[dict]:
    """Direct flag registrations inside one function body: add_argument
    calls and the _add_bool_flag helper shorthand."""
    out = []
    for node in ast.walk(fn):
        if not isinstance(node, ast.Call):
            continue
        name = call_name(node)
        if name == "add_argument" and node.args and isinstance(
                node.args[0], ast.Constant) and isinstance(
                node.args[0].value, str) and \
                node.args[0].value.startswith("--"):
            kw = {k.arg: k.value for k in node.keywords}
            action = kw.get("action")
            if "type" in kw:
                ftype = ast.unparse(kw["type"])
            elif isinstance(action, ast.Constant):
                ftype = str(action.value)
            else:
                ftype = "str"  # argparse default
            out.append({
                "flag": node.args[0].value[2:],
                "type": ftype,
                "default": kw.get("default"),
                "line": node.lineno,
            })
        elif name == "_add_bool_flag" and len(node.args) >= 2 and \
                isinstance(node.args[1], ast.Constant):
            out.append({
                "flag": node.args[1].value,
                "type": "store_true",
                "default": None,
                "line": node.lineno,
            })
    return out


def _dataclass_defaults(mod: LintModule) -> Dict[str, Dict[str, str]]:
    """class name -> {field: unparsed default} for module dataclasses."""
    out: Dict[str, Dict[str, str]] = {}
    for node in ast.walk(mod.tree):
        if not isinstance(node, ast.ClassDef):
            continue
        fields = {}
        for stmt in node.body:
            if isinstance(stmt, ast.AnnAssign) and stmt.value is not None \
                    and isinstance(stmt.target, ast.Name):
                fields[stmt.target.id] = ast.unparse(stmt.value)
        if fields:
            out[node.name] = fields
    return out


def _resolve_default(value: Optional[ast.AST], dc_fields: Dict[str, str]
                     ) -> Optional[str]:
    """Normalized default: ``d.<field>`` resolves through the parser's
    dataclass instance; literals unparse directly; unresolvable -> None
    (not compared)."""
    if value is None:
        return None
    if isinstance(value, ast.Attribute) and isinstance(value.value, ast.Name):
        return dc_fields.get(value.attr)
    try:
        return ast.unparse(value)
    except Exception:  # pragma: no cover - defensive
        return None


def check_parser_flags(mod: LintModule) -> List[Finding]:
    """Flag-consistency over one module's ``*_parser`` functions (the
    config.py surface; fixtures use the same convention)."""
    findings: List[Finding] = []
    fns = {
        node.name: node for node in mod.tree.body
        if isinstance(node, ast.FunctionDef)
    }
    classes = _dataclass_defaults(mod)

    # which dataclass instance each parser function reads defaults from
    # (the `d = SupConConfig()` convention)
    def dc_for(fn: ast.AST) -> Dict[str, str]:
        for node in ast.walk(fn):
            if isinstance(node, ast.Assign) and isinstance(node.value,
                                                           ast.Call):
                cname = call_name(node.value)
                if cname in classes:
                    return classes[cname]
        return {}

    # registrations per top-level parser, resolving helper calls one level
    # (helpers themselves may not call further helpers — they don't here)
    parsers: Dict[str, Dict[str, List[dict]]] = {}
    for name, fn in fns.items():
        if not name.endswith("_parser"):
            continue
        dc_fields = dc_for(fn)
        flags: Dict[str, List[dict]] = {}

        def add(regs, registered_by, fields):
            for r in regs:
                entry = dict(r)
                entry["registered_by"] = registered_by
                entry["default_resolved"] = _resolve_default(
                    r["default"], fields
                )
                flags.setdefault(r["flag"], []).append(entry)

        add(_flag_registrations(fn), name, dc_fields)
        for node in ast.walk(fn):
            if isinstance(node, ast.Call) and isinstance(node.func, ast.Name):
                helper = fns.get(node.func.id)
                if helper is not None and node.func.id != "_add_bool_flag" \
                        and _flag_registrations(helper):
                    add(_flag_registrations(helper), node.func.id, dc_fields)
        parsers[name] = flags

    if len(parsers) < 2:
        return findings

    all_flags = sorted({f for flags in parsers.values() for f in flags})
    for flag in all_flags:
        present = {
            pname: flags[flag] for pname, flags in parsers.items()
            if flag in flags
        }
        if len(present) < 2:
            continue
        # TYPE agreement for every shared flag
        types = {e["type"] for entries in present.values() for e in entries}
        if len(types) > 1:
            line = min(e["line"] for v in present.values() for e in v)
            findings.append(Finding(
                rule=RULE_FLAG_TYPE, file=mod.rel, line=line,
                why=(
                    f"--{flag} is registered with different argparse types "
                    f"across parsers ({sorted(types)}): the trainers parse "
                    "the same CLI surface, so a type drift silently changes "
                    "values on one stage only"
                ),
                allowlist_key=f"{RULE_FLAG_TYPE}:{mod.rel}:{flag}",
            ))
        if flag not in SHARED_RUNTIME_FLAGS:
            continue
        # registry flags: must come through one shared helper...
        inline = sorted({
            pname for pname, entries in present.items()
            if any(e["registered_by"] == pname for e in entries)
        })
        if inline:
            line = min(e["line"] for v in present.values() for e in v)
            findings.append(Finding(
                rule=RULE_FLAG_INLINE, file=mod.rel, line=line,
                why=(
                    f"shared runtime flag --{flag} is registered inline in "
                    f"{inline} instead of through the shared helper: the "
                    "flag-consistency contract verifies one registry, not "
                    "hand-synced copies"
                ),
                allowlist_key=f"{RULE_FLAG_INLINE}:{mod.rel}:{flag}",
            ))
        # ...and their resolved defaults must agree across configs
        defaults = {
            e["default_resolved"]
            for entries in present.values() for e in entries
            if e["default_resolved"] is not None
        }
        if len(defaults) > 1:
            line = min(e["line"] for v in present.values() for e in v)
            findings.append(Finding(
                rule=RULE_FLAG_DEFAULT, file=mod.rel, line=line,
                why=(
                    f"shared runtime flag --{flag} resolves to different "
                    f"defaults across the trainer configs "
                    f"({sorted(defaults)}): the shared surface must behave "
                    "identically on all three trainers"
                ),
                allowlist_key=f"{RULE_FLAG_DEFAULT}:{mod.rel}:{flag}",
            ))
    return findings


def check_module_flags(mods: List[LintModule]) -> List[Finding]:
    findings: List[Finding] = []
    for mod in mods:
        if any(
            isinstance(n, ast.FunctionDef) and n.name.endswith("_parser")
            for n in mod.tree.body
        ):
            findings.extend(check_parser_flags(mod))
    return findings


def check_modules(mods: List[LintModule]) -> List[Finding]:
    return (
        check_metric_keys(mods)
        + check_schema_stamps(mods)
        + check_module_flags(mods)
    )
