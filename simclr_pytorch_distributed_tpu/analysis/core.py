"""Shared machinery for the AST rules: parsed modules, findings, scopes.

Everything here is stdlib-only and PURE (no imports of the code under
analysis — the linter must never execute the tree it inspects, and must
run without jax on a box that has none).
"""

from __future__ import annotations

import ast
import dataclasses
import os
import re
from typing import Dict, Iterator, List, Optional, Tuple

# Inline suppression for the hot-loop rule's annotated flush-boundary
# sites: a sync-forcing construct on a line (or directly under a line)
# carrying ``# sync-ok: <reason>`` is a DESIGNED sync point. The reason is
# mandatory — a bare marker is itself a finding (the allowlist convention:
# every exception carries its why).
SYNC_OK_RE = re.compile(r"#\s*sync-ok\s*:?\s*(?P<reason>.*)$")


@dataclasses.dataclass
class Finding:
    """One rule violation: ``rule`` (family:check id), ``file:line``, the
    ``why`` a reviewer needs, and the stable ``allowlist_key`` an entry in
    :mod:`.allowlist` must match to accept it as a designed matched point.
    The key deliberately excludes line numbers so unrelated edits above a
    designed point do not invalidate its allowlist entry."""

    rule: str
    file: str
    line: int
    why: str
    allowlist_key: str

    def to_dict(self) -> dict:
        return dataclasses.asdict(self)

    def render(self) -> str:
        return (
            f"{self.file}:{self.line}: [{self.rule}] {self.why}\n"
            f"    allowlist_key: {self.allowlist_key}"
        )


class LintModule:
    """One parsed source file: tree + source lines + parent links.

    ``rel`` is the repo-relative posix path (the coordinate findings and
    allowlist keys use, so artifacts are machine-independent).
    """

    def __init__(self, path: str, rel: str, source: str):
        self.path = path
        self.rel = rel
        self.source_lines = source.splitlines()
        self.tree = ast.parse(source, filename=rel)
        self.parents: Dict[ast.AST, ast.AST] = {}
        for parent in ast.walk(self.tree):
            for child in ast.iter_child_nodes(parent):
                self.parents[child] = parent

    # -- navigation ------------------------------------------------------
    def parent(self, node: ast.AST) -> Optional[ast.AST]:
        return self.parents.get(node)

    def ancestors(self, node: ast.AST) -> Iterator[ast.AST]:
        """Innermost-first chain of ancestors up to the Module."""
        cur = self.parents.get(node)
        while cur is not None:
            yield cur
            cur = self.parents.get(cur)

    def enclosing_function(self, node: ast.AST) -> Optional[ast.AST]:
        for anc in self.ancestors(node):
            if isinstance(anc, (ast.FunctionDef, ast.AsyncFunctionDef)):
                return anc
        return None

    def function_scopes(self) -> List[Tuple[str, ast.AST]]:
        """``(qualname, node)`` for the module itself and every function
        (nested functions get dotted qualnames). Each node later owns
        exactly the statements whose *innermost* enclosing function is it —
        see :meth:`scope_of`."""
        out: List[Tuple[str, ast.AST]] = [("<module>", self.tree)]

        def visit(node, prefix):
            for child in ast.iter_child_nodes(node):
                if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef)):
                    qual = f"{prefix}{child.name}"
                    out.append((qual, child))
                    visit(child, qual + ".")
                elif isinstance(child, ast.ClassDef):
                    visit(child, f"{prefix}{child.name}.")
                else:
                    visit(child, prefix)

        visit(self.tree, "")
        return out

    def scope_of(self, node: ast.AST) -> ast.AST:
        """The innermost function owning ``node`` (or the Module)."""
        fn = self.enclosing_function(node)
        return fn if fn is not None else self.tree

    # -- inline annotations ----------------------------------------------
    def sync_ok_reason(self, lineno: int) -> Optional[str]:
        """The ``# sync-ok: reason`` annotation covering ``lineno`` — on
        the line itself or the line directly above. Returns the reason
        string ('' when the marker carries none), or None when the line is
        unannotated."""
        for ln in (lineno, lineno - 1):
            if 1 <= ln <= len(self.source_lines):
                m = SYNC_OK_RE.search(self.source_lines[ln - 1])
                if m:
                    return m.group("reason").strip()
        return None


def scope_nodes(mod: LintModule, scope: ast.AST) -> Iterator[ast.AST]:
    """All nodes whose innermost enclosing function is ``scope`` — i.e. the
    code that EXECUTES when that scope runs, excluding nested function
    bodies (they execute on their own call, in their own scope pass)."""
    for node in ast.walk(scope):
        if node is scope:
            continue
        if mod.scope_of(node) is scope:
            yield node


def call_name(node: ast.AST) -> Optional[str]:
    """The terminal name of a call target: ``f(...)`` -> 'f',
    ``a.b.f(...)`` -> 'f'. Terminal-name matching is the deliberate
    resolution level: the repo's collectives are reached both as bare
    imports and as module/method attributes, and a rare same-name
    false positive is an allowlist entry, not a blind spot."""
    if not isinstance(node, ast.Call):
        return None
    f = node.func
    if isinstance(f, ast.Name):
        return f.id
    if isinstance(f, ast.Attribute):
        return f.attr
    return None


def dotted_prefix(node: ast.Call) -> Optional[str]:
    """``np.asarray(...)`` -> 'np'; None for bare-name calls."""
    f = node.func
    if isinstance(f, ast.Attribute) and isinstance(f.value, ast.Name):
        return f.value.id
    return None


def statement_of(mod: LintModule, node: ast.AST) -> ast.AST:
    """The enclosing statement of an expression node."""
    cur = node
    while cur is not None and not isinstance(cur, ast.stmt):
        cur = mod.parent(cur)
    return cur if cur is not None else node


def assigned_names(stmt: ast.AST) -> set:
    """Flat set of Names (re)bound by a statement's targets."""
    out = set()
    targets = []
    if isinstance(stmt, ast.Assign):
        targets = stmt.targets
    elif isinstance(stmt, (ast.AugAssign, ast.AnnAssign)):
        targets = [stmt.target]
    for t in targets:
        for n in ast.walk(t):
            if isinstance(n, ast.Name):
                out.add(n.id)
    return out


def end_line(node: ast.AST) -> int:
    return getattr(node, "end_lineno", None) or node.lineno


# -- tree discovery -------------------------------------------------------

# directories never scanned (generated/third-party/test-support trees; the
# known-bad fixture corpus must obviously not fail the clean-tree gate)
EXCLUDED_DIRS = {
    "__pycache__", ".git", "work_space", "datasets", "lint_fixtures",
    ".jax_cache", "node_modules",
}

# roots relative to the repo: the package, the scripts, and the root-level
# entry points (incl. main_ce.py — a thin shim over train/ce.py, kept so
# the call-graph pass sees the real entry point, not a dead remnant)
DEFAULT_ROOTS = (
    "simclr_pytorch_distributed_tpu",
    "scripts",
    "main_supcon.py",
    "main_linear.py",
    "main_ce.py",
    "bench.py",
)


def iter_source_files(repo_root: str, roots=DEFAULT_ROOTS) -> Iterator[str]:
    for root in roots:
        path = os.path.join(repo_root, root)
        if os.path.isfile(path):
            yield path
            continue
        for dirpath, dirnames, filenames in os.walk(path):
            dirnames[:] = sorted(
                d for d in dirnames if d not in EXCLUDED_DIRS
            )
            for fn in sorted(filenames):
                if fn.endswith(".py"):
                    yield os.path.join(dirpath, fn)


def load_modules(repo_root: str, roots=DEFAULT_ROOTS) -> List[LintModule]:
    mods = []
    for path in iter_source_files(repo_root, roots):
        rel = os.path.relpath(path, repo_root).replace(os.sep, "/")
        with open(path, encoding="utf-8") as f:
            source = f.read()
        mods.append(LintModule(path, rel, source))
    return mods


def load_module(path: str, repo_root: Optional[str] = None) -> LintModule:
    """One-file loader (the fixture tests' entry point)."""
    root = repo_root or os.path.dirname(path)
    rel = os.path.relpath(path, root).replace(os.sep, "/")
    with open(path, encoding="utf-8") as f:
        return LintModule(path, rel, f.read())
