"""Collective-schedule lint: the deadlock invariant, statically.

The repo's standing invariant (PR 1/4/5 docs, utils/telemetry.py,
utils/preempt.py, data/device_store.py): every host-level collective is
called at a point every process reaches with an identical call count. The
three static shapes that break it — each reconstructed from a real bug or
review fix in this repo's history — are:

- ``conditional-collective``: a collective (or a call reaching one)
  nested under an ``if``/``while``/ternary whose test is process-dependent
  (``is_main_process()`` / ``process_index()``), or short-circuited behind
  a process-dependent operand. One host runs the allgather, its peers
  don't: the pod wedges (the ``device_store`` split-verdict class).
- ``early-exit``: a process-dependent conditional that exits the scope
  (``return``/``raise``/``continue``/``break``/``sys.exit``) while
  collectives follow later in the same scope — the lone-host-leaves-the-
  loop hazard ``drain_global`` exists to prevent.
- ``swallowed-try``: a collective-reaching call inside a ``try`` whose
  handler has no unconditional top-level re-raise. Exception delivery is
  per-host (a local TB IOError, a local orbax fault), so a host that
  swallows locally and keeps going diverges its collective schedule from a
  peer that propagated — the exact hazard the failure-code allgather
  (``check_failures_global``) was built to close. Designed recovery
  points whose raise IS collectively agreed (the NaN-rollback handler)
  belong in the allowlist with that reason.
"""

from __future__ import annotations

import ast
from typing import List

from simclr_pytorch_distributed_tpu.analysis import callgraph
from simclr_pytorch_distributed_tpu.analysis.core import (
    Finding,
    LintModule,
    call_name,
    end_line,
    scope_nodes,
)

RULE_CONDITIONAL = "collective-schedule:conditional"
RULE_EARLY_EXIT = "collective-schedule:early-exit"
RULE_SWALLOWED = "collective-schedule:swallowed-try"

_EXIT_STMTS = (ast.Return, ast.Raise, ast.Continue, ast.Break)


def _key(rule: str, mod: LintModule, scope_name: str, symbol: str) -> str:
    return f"{rule}:{mod.rel}:{scope_name}:{symbol}"


def _contains_return(stmt: ast.AST) -> bool:
    """A ``return`` anywhere in this statement (outside nested defs)."""
    stack = [stmt]
    while stack:
        node = stack.pop()
        if isinstance(node, ast.Return):
            return True
        for child in ast.iter_child_nodes(node):
            if not isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef,
                                      ast.Lambda, ast.ClassDef)):
                stack.append(child)
    return False


def _stmt_can_exit_handler(stmt: ast.AST) -> bool:
    """Can executing this handler statement leave the handler WITHOUT
    raising? ``return`` always can; ``continue``/``break`` can unless they
    bind to a loop nested inside the statement itself (inside a
    ``for``/``while`` only a nested ``return`` escapes the handler);
    nested function defs never execute here."""
    if isinstance(stmt, ast.Return):
        return True
    if isinstance(stmt, (ast.Continue, ast.Break)):
        return True  # binds to a loop OUTSIDE the handler at this depth
    if isinstance(stmt, (ast.For, ast.AsyncFor, ast.While)):
        return _contains_return(stmt)
    if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef,
                         ast.ClassDef)):
        return False
    return any(
        _stmt_can_exit_handler(child)
        for child in ast.iter_child_nodes(stmt)
        if isinstance(child, ast.stmt)
    )


def _handler_always_reraises(stmts) -> bool:
    """Does every path through these handler statements hit a ``raise``?

    A ``raise`` that can be BYPASSED by an earlier return/continue/break —
    top-level or nested in any compound statement — is not a re-raise
    guarantee: on the host where the bypass path is taken the exception is
    swallowed, which is the per-host divergence this rule exists to catch.
    Scanning in order: a ``raise`` before any bypass -> guaranteed; an
    ``if`` whose branches BOTH always raise -> guaranteed; any statement
    that can exit the handler -> swallowed."""
    for stmt in stmts:
        if isinstance(stmt, ast.Raise):
            return True
        if isinstance(stmt, ast.If) and stmt.orelse \
                and _handler_always_reraises(stmt.body) \
                and _handler_always_reraises(stmt.orelse):
            return True
        if _stmt_can_exit_handler(stmt):
            return False
    return False


def _exits_control_flow(if_node: ast.If) -> bool:
    """Does the if's body (or orelse) end the scope's control flow?"""
    for branch in (if_node.body, if_node.orelse):
        for stmt in branch:
            if isinstance(stmt, _EXIT_STMTS):
                return True
            if isinstance(stmt, ast.Expr) and call_name(stmt.value) == "exit":
                return True
    return False


def _under_process_dependent_branch(mod: LintModule, node: ast.AST,
                                    scope: ast.AST):
    """The innermost process-dependent conditional governing ``node``
    within ``scope`` (None when unconditional). A node sitting in the
    TEST of an if is evaluated unconditionally and is not 'under' it;
    a node in a later operand of a BoolOp is short-circuited behind the
    earlier operands."""
    child = node
    for anc in mod.ancestors(node):
        if anc is scope:
            break
        if isinstance(anc, (ast.FunctionDef, ast.AsyncFunctionDef,
                            ast.Lambda)):
            break  # the conditional would govern the DEF, not this call
        if isinstance(anc, (ast.If, ast.While)):
            # ``child`` is the direct child we arrived through: the test
            # itself is evaluated unconditionally, body/orelse are governed
            if child is not anc.test and \
                    callgraph.expr_is_process_dependent(anc.test):
                return anc
        elif isinstance(anc, ast.IfExp):
            if child is not anc.test and \
                    callgraph.expr_is_process_dependent(anc.test):
                return anc
        elif isinstance(anc, ast.BoolOp):
            try:
                idx = anc.values.index(child)
            except ValueError:
                idx = 0
            if idx > 0 and any(
                callgraph.expr_is_process_dependent(v)
                for v in anc.values[:idx]
            ):
                return anc
        child = anc
    return None


def check_module(mod: LintModule) -> List[Finding]:
    findings: List[Finding] = []
    reachers = callgraph.collective_reachers(mod)

    for scope_name, scope in mod.function_scopes():
        collective_calls = [
            n for n in scope_nodes(mod, scope)
            if isinstance(n, ast.Call)
            and callgraph.is_collective_call(n, reachers)
        ]
        if not collective_calls:
            continue

        # (a) conditional-collective
        for call in collective_calls:
            gov = _under_process_dependent_branch(mod, call, scope)
            if gov is not None:
                name = call_name(call)
                findings.append(Finding(
                    rule=RULE_CONDITIONAL, file=mod.rel, line=call.lineno,
                    why=(
                        f"collective-reaching call {name!r} is guarded by a "
                        f"process-dependent conditional (line {gov.lineno}):"
                        " hosts on the other branch skip the collective and"
                        " the pod deadlocks at it"
                    ),
                    allowlist_key=_key(RULE_CONDITIONAL, mod, scope_name,
                                       name),
                ))

        # (b) process-dependent early exit with collectives after it
        for node in scope_nodes(mod, scope):
            if not isinstance(node, ast.If):
                continue
            if not callgraph.expr_is_process_dependent(node.test):
                continue
            if not _exits_control_flow(node):
                continue
            later = [
                c for c in collective_calls if c.lineno > end_line(node)
            ]
            if later:
                names = sorted({call_name(c) for c in later})
                findings.append(Finding(
                    rule=RULE_EARLY_EXIT, file=mod.rel, line=node.lineno,
                    why=(
                        "process-dependent early exit: some hosts leave "
                        f"{scope_name!r} here while others continue into "
                        f"collective call(s) {names} below — the "
                        "split-verdict deadlock shape"
                    ),
                    allowlist_key=_key(RULE_EARLY_EXIT, mod, scope_name,
                                       ",".join(names)),
                ))

        # (c) collective inside an exception-swallowing try
        for node in scope_nodes(mod, scope):
            if not isinstance(node, ast.Try):
                continue
            body_nodes = set()
            for stmt in node.body:
                body_nodes.update(ast.walk(stmt))
            in_try = [c for c in collective_calls if c in body_nodes]
            if not in_try:
                continue
            for handler in node.handlers:
                if _handler_always_reraises(handler.body):
                    continue
                names = sorted({call_name(c) for c in in_try})
                htype = (
                    ast.unparse(handler.type) if handler.type is not None
                    else "BaseException"
                )
                findings.append(Finding(
                    rule=RULE_SWALLOWED, file=mod.rel, line=handler.lineno,
                    why=(
                        f"'except {htype}' swallows (no unconditional "
                        f"top-level re-raise) around collective call(s) "
                        f"{names}: exception delivery is per-host, so a "
                        "locally-swallowed failure desynchronizes this "
                        "host's collective schedule from its peers'"
                    ),
                    allowlist_key=_key(
                        RULE_SWALLOWED, mod, scope_name,
                        f"{htype}:{','.join(names)}",
                    ),
                ))
    return findings
