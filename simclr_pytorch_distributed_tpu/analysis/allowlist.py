"""The matched-point registry: designed exceptions, each with its reason.

An entry here says: this finding's shape is real, but the point is MATCHED
across processes (or the sync/read is designed) by a mechanism the static
rule cannot see — and the reason records that mechanism so a reviewer can
re-check it when the cited code changes. An entry with an empty reason is
invalid (the runner rejects it), and an entry matching no finding is STALE
and reported as one — the allowlist must shrink when the code gets
cleaner.

Keys are ``Finding.allowlist_key``: ``<rule>:<file>:<scope>:<symbol>``,
deliberately line-number-free so edits above a designed point do not
invalidate its entry.
"""

from __future__ import annotations

from typing import Dict

ALLOWLIST: Dict[str, str] = {
    # -- train/supcon.py: the NaN-rollback recovery point -----------------
    # The except NonFiniteLossError handler performs a collective
    # crash-save and may swallow (rollback) rather than re-raise. This is
    # the designed recovery point: NonFiniteLossError is raised on EVERY
    # host at the same flush boundary by the collective failure-code
    # exchange (TelemetrySession.check_failures_global allgathers the
    # failure code, and the exit type is a pure function of the gathered
    # code), and should_rollback() is deterministic per-host from
    # meta-carried policy state — so all hosts enter the handler, run the
    # collective save, and take the same swallow-vs-reraise branch
    # together. docs/RESILIENCE.md "NaN policy".
    "collective-schedule:swallowed-try:simclr_pytorch_distributed_tpu/"
    "train/supcon.py:run:NonFiniteLossError:train_one_epoch":
        "matched point: NonFiniteLossError is raised collectively on every "
        "host by check_failures_global's failure-code allgather, and the "
        "rollback-vs-reraise branch is deterministic from meta-carried "
        "policy state — all hosts swallow or re-raise together",
}


def validate(allowlist: Dict[str, str] = None) -> None:
    """Reject malformed entries up front (the gate's reason contract)."""
    if allowlist is None:
        allowlist = ALLOWLIST
    for key, reason in allowlist.items():
        if not isinstance(reason, str) or not reason.strip():
            raise ValueError(
                f"allowlist entry {key!r} carries no reason — every "
                "designed matched point must record why it is safe"
            )
