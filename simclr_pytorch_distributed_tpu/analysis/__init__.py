"""Static invariant linter for the repo's distributed contracts.

Every hard-won correctness property of this codebase — the matched
collective call schedule (the deadlock invariant PR 1/5/13 enforce by
convention), the donation discipline (two latent segfault/torn-state bugs
and one buffer-aliased EMA init so far), the zero-sync dispatch-only hot
loop, and the writer/reader contract registries (metric-ring columns,
schema-pinned artifacts, the shared trainer flags) — is otherwise enforced
only by docstrings and dynamic tests that re-prove one configuration at a
time. This package checks them STATICALLY over the whole tree with stdlib
``ast`` (no jax import — the linter must run anywhere, instantly):

- :mod:`.rule_collectives` — collective-schedule lint: no collective
  reachable under a process-dependent conditional, after a
  process-dependent early exit, or inside an exception-swallowing ``try``;
- :mod:`.rule_donation` — donation-safety lint: no read of a donated
  binding after the donating call;
- :mod:`.rule_hotloop` — hot-loop sync lint: no sync-forcing host op
  inside the jitted step builders or the drivers' flush-boundary loops;
- :mod:`.rule_registry` — contract-registry checks: metric-key tuples
  sorted+unique+single-sourced, ``build_output`` schemas pinned to module
  constants, the trainers' shared argparse flags agreeing.

Designed matched points (a collective under a conditional that IS agreed
across processes by construction) live in :mod:`.allowlist` with a recorded
reason; everything else is a finding. ``scripts/invariant_lint.py`` is the
CLI; ``scripts/ratchet.py`` gates the tree on zero unallowlisted findings
(the contract is hardware-independent, so the gate binds on every device).
See docs/ANALYSIS.md.
"""

from simclr_pytorch_distributed_tpu.analysis.core import Finding  # noqa: F401
from simclr_pytorch_distributed_tpu.analysis.runner import (  # noqa: F401
    SCHEMA,
    build_output,
    run_lint,
)
