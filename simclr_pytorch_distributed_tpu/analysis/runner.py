"""The lint run: all rules over the tree, allowlist applied, one artifact.

``run_lint`` is pure file-system-in, records-out (no jax, no imports of
the analyzed code); ``build_output`` is the schema-pinned artifact shape
the ratchet gate (scripts/ratchet.py lint_gate_record) and the committed
evidence (docs/evidence/invariant_lint_r19.json) both bind on.
"""

from __future__ import annotations

from typing import List, Optional, Sequence

from simclr_pytorch_distributed_tpu.analysis import (
    allowlist as allowlist_mod,
    rule_collectives,
    rule_donation,
    rule_hotloop,
    rule_registry,
)
from simclr_pytorch_distributed_tpu.analysis.core import (
    DEFAULT_ROOTS,
    Finding,
    load_modules,
)

SCHEMA = "invariant_lint/v1"

# the four rule families the gate requires to have run (a rules module
# silently dropped from the runner must fail the gate, not pass it)
RULE_FAMILIES = (
    "collective-schedule",
    "donation-safety",
    "hot-loop-sync",
    "contract-registry",
)

RULE_STALE = "allowlist:stale-entry"


def run_lint(
    repo_root: str,
    roots: Sequence[str] = DEFAULT_ROOTS,
    allowlist: Optional[dict] = None,
) -> dict:
    """Lint the tree. Returns::

        {
          "findings":    [Finding...]  # unallowlisted — these FAIL
          "allowlisted": [{key, reason, findings: [...]}, ...]
          "files_scanned": int,
          "rules_run": [family, ...],
        }
    """
    if allowlist is None:
        allowlist = allowlist_mod.ALLOWLIST
    allowlist_mod.validate(allowlist)
    mods = load_modules(repo_root, roots)

    # rules_run records what ACTUALLY executed (appended only after each
    # family's pass completes) — the gate's "all four families ran" check
    # must be able to catch a rule module dropped from this loop, so the
    # list must not be a constant restated here
    raw: List[Finding] = []
    rules_run: List[str] = []
    per_module_rules = (
        ("collective-schedule", rule_collectives.check_module),
        ("donation-safety", rule_donation.check_module),
        ("hot-loop-sync", rule_hotloop.check_module),
    )
    for family, check in per_module_rules:
        for mod in mods:
            raw.extend(check(mod))
        rules_run.append(family)
    raw.extend(rule_registry.check_modules(mods))
    rules_run.append("contract-registry")

    findings: List[Finding] = []
    allowlisted = {key: [] for key in allowlist}
    for f in raw:
        if f.allowlist_key in allowlist:
            allowlisted[f.allowlist_key].append(f.to_dict())
        else:
            findings.append(f)
    for key, matched in sorted(allowlisted.items()):
        if not matched:
            findings.append(Finding(
                rule=RULE_STALE,
                file="simclr_pytorch_distributed_tpu/analysis/allowlist.py",
                line=0,
                why=(
                    f"allowlist entry {key!r} matches no finding: the "
                    "designed point it covered is gone — delete the entry "
                    "(the allowlist must shrink with the code)"
                ),
                allowlist_key=f"{RULE_STALE}:{key}",
            ))
    findings.sort(key=lambda f: (f.file, f.line, f.rule))
    return {
        "findings": findings,
        "allowlisted": [
            {"key": key, "reason": allowlist[key], "findings": matched}
            for key, matched in sorted(allowlisted.items()) if matched
        ],
        "files_scanned": len(mods),
        "rules_run": rules_run,
    }


def build_output(result: dict) -> dict:
    """The committed artifact (pure; schema pinned by tests and the
    ratchet lint gate)."""
    return {
        "schema": SCHEMA,
        "ok": not result["findings"],
        "n_findings": len(result["findings"]),
        "findings": [f.to_dict() for f in result["findings"]],
        "allowlisted": result["allowlisted"],
        "files_scanned": result["files_scanned"],
        "rules_run": result["rules_run"],
    }
