"""Cross-replica batch normalization, TPU-native.

The reference gets synchronized BN by swapping every BatchNorm2d for
``torch.nn.SyncBatchNorm`` (``main_supcon.py:223-224``), which all-reduces batch
statistics across GPUs with a dedicated CUDA kernel. On TPU under GSPMD there is
no kernel to swap: the train step is ONE logical program over the global batch,
so computing ``mean(x, axis=(0,1,2))`` on a batch-sharded NHWC array *is*
synchronized BN — XLA inserts the cross-chip reductions over ICI automatically.

This module therefore implements plain batch statistics plus:

- torch-matching semantics: biased variance for normalization, UNBIASED variance
  for the running-stat update, running update ``new = (1-m)*old + m*batch`` with
  ``momentum=0.1``, ``eps=1e-5`` (torch BatchNorm2d defaults used throughout the
  reference's ``networks/resnet_big.py``);
- an optional ``axis_name`` for explicit-collective contexts (``shard_map`` /
  ``pmap``), where stats are combined with ``lax.pmean`` — this is the
  per-device-program equivalent of SyncBatchNorm and also what a multi-host
  data-parallel step uses across the ``data`` axis;
- fp32 statistics regardless of compute dtype (bf16 activations are normalized
  with fp32 mean/var, matching what mixed-precision SyncBN does).
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp
from flax import linen as nn


class CrossReplicaBatchNorm(nn.Module):
    """BatchNorm over the (logically global) batch for NHWC activations.

    Attributes:
      momentum: torch-convention running-stat momentum (weight of the NEW batch
        statistic; torch default 0.1).
      epsilon: numerical-stability constant (torch default 1e-5).
      use_running_average: eval mode — normalize with running stats.
      axis_name: if set, batch statistics are additionally ``lax.pmean``-ed over
        this mapped axis (shard_map/pmap path). Leave ``None`` under GSPMD jit,
        where sharded-batch statistics are already global.
      sync: if False, skip the ``axis_name`` reduction even when provided —
        reproduces the reference's non-``--syncBN`` per-device BN semantics.
    """

    momentum: float = 0.1
    epsilon: float = 1e-5
    use_running_average: bool = False
    axis_name: Optional[str] = None
    sync: bool = True
    dtype: Optional[jnp.dtype] = None

    @nn.compact
    def __call__(self, x: jax.Array, use_running_average: Optional[bool] = None):
        use_ra = nn.merge_param(
            "use_running_average", self.use_running_average, use_running_average
        )
        num_features = x.shape[-1]
        reduce_axes = tuple(range(x.ndim - 1))  # (N, H, W) for NHWC

        scale = self.param("scale", nn.initializers.ones, (num_features,), jnp.float32)
        bias = self.param("bias", nn.initializers.zeros, (num_features,), jnp.float32)

        ra_mean = self.variable(
            "batch_stats", "mean", lambda: jnp.zeros((num_features,), jnp.float32)
        )
        ra_var = self.variable(
            "batch_stats", "var", lambda: jnp.ones((num_features,), jnp.float32)
        )

        xf = x.astype(jnp.float32)
        if use_ra:
            mean, var = ra_mean.value, ra_var.value
        else:
            mean = jnp.mean(xf, axis=reduce_axes)
            mean_sq = jnp.mean(jnp.square(xf), axis=reduce_axes)
            count = 1
            for a in reduce_axes:
                count *= x.shape[a]
            if self.axis_name is not None and self.sync:
                mean = jax.lax.pmean(mean, self.axis_name)
                mean_sq = jax.lax.pmean(mean_sq, self.axis_name)
                count *= jax.lax.axis_size(self.axis_name)
            var = mean_sq - jnp.square(mean)  # biased — used for normalization

            if not self.is_initializing():
                # torch running update: biased mean, UNBIASED variance.
                unbiased_var = var * (count / max(count - 1, 1))
                m = self.momentum
                ra_mean.value = (1.0 - m) * ra_mean.value + m * mean
                ra_var.value = (1.0 - m) * ra_var.value + m * unbiased_var

        y = (xf - mean) * jax.lax.rsqrt(var + self.epsilon) * scale + bias
        return y.astype(self.dtype or x.dtype)
