"""Cross-replica batch normalization, TPU-native.

The reference gets synchronized BN by swapping every BatchNorm2d for
``torch.nn.SyncBatchNorm`` (``main_supcon.py:223-224``), which all-reduces batch
statistics across GPUs with a dedicated CUDA kernel. On TPU under GSPMD there is
no kernel to swap: the train step is ONE logical program over the global batch,
so computing ``mean(x, axis=(0,1,2))`` on a batch-sharded NHWC array *is*
synchronized BN — XLA inserts the cross-chip reductions over ICI automatically.

This module therefore implements plain batch statistics plus:

- torch-matching semantics: biased variance for normalization, UNBIASED variance
  for the running-stat update, running update ``new = (1-m)*old + m*batch`` with
  ``momentum=0.1``, ``eps=1e-5`` (torch BatchNorm2d defaults used throughout the
  reference's ``networks/resnet_big.py``);
- an optional ``axis_name`` for explicit-collective contexts (``shard_map`` /
  ``pmap``), where stats are combined with ``lax.pmean`` — this is the
  per-device-program equivalent of SyncBatchNorm and also what a multi-host
  data-parallel step uses across the ``data`` axis;
- a grouped per-device mode (``sync=False, local_groups=G``) reproducing the
  reference's DEFAULT non-``--syncBN`` semantics (``main_supcon.py:223-224``
  converts to SyncBN only when the flag is given; otherwise each GPU's
  ``BatchNorm2d`` normalizes with its own local-batch statistics). Under GSPMD
  there are no per-device programs to scope the statistics to, so the batch is
  reshaped into G groups matching the per-device slices and statistics are
  computed per group. Running stats follow group 0 — DDP's default
  ``broadcast_buffers=True`` re-broadcasts rank 0's BN buffers at every
  forward, so rank 0's local statistics ARE the persistent ones upstream;
- fp32 statistics regardless of compute dtype (bf16 activations are normalized
  with fp32 mean/var, matching what mixed-precision SyncBN does).
"""

from __future__ import annotations

from typing import Optional, Tuple

import jax
import jax.numpy as jnp
from flax import linen as nn


def running_stats_update(
    ra_mean: jax.Array, ra_var: jax.Array,
    batch_mean: jax.Array, batch_var_biased: jax.Array,
    count: int, momentum: float,
) -> Tuple[jax.Array, jax.Array]:
    """The torch-convention running-stat update, single-sourced.

    ``new = (1-m)*old + m*batch`` with the BIASED batch variance rescaled
    to UNBIASED for the running buffer (torch BatchNorm semantics; module
    docstring). Shared by ``CrossReplicaBatchNorm`` and the fused Pallas
    conv path (``FusedTrainBN``), so the two impls cannot drift.
    """
    unbiased = batch_var_biased * (count / max(count - 1, 1))
    m = momentum
    return (
        (1.0 - m) * ra_mean + m * batch_mean,
        (1.0 - m) * ra_var + m * unbiased,
    )


class FusedTrainBN(nn.Module):
    """Parameter/variable shadow of ``CrossReplicaBatchNorm`` for the fused
    Pallas conv path (``--conv_impl pallas``, ops/pallas_conv.py).

    The fused kernels compute the batch statistics and the normalization
    INSIDE the conv kernel, so this module only owns what must live in the
    Flax tree: the affine params and the running-stat variables, under
    exactly the names/shapes/inits ``CrossReplicaBatchNorm`` creates — the
    param tree is impl-independent by construction (a ``--conv_impl
    pallas`` checkpoint restores under ``--conv_impl xla`` and vice versa).

    Call once with no statistics to fetch ``(scale, bias)`` for the
    kernel, then AGAIN with the kernel's returned batch moments to apply
    the running update (``running_stats_update``); train mode only — the
    eval path stays on the Flax module.
    """

    features: int
    momentum: float = 0.1

    @nn.compact
    def __call__(self, batch_mean=None, batch_var_biased=None, count: int = 0):
        scale = self.param(
            "scale", nn.initializers.ones, (self.features,), jnp.float32
        )
        bias = self.param(
            "bias", nn.initializers.zeros, (self.features,), jnp.float32
        )
        ra_mean = self.variable(
            "batch_stats", "mean",
            lambda: jnp.zeros((self.features,), jnp.float32),
        )
        ra_var = self.variable(
            "batch_stats", "var",
            lambda: jnp.ones((self.features,), jnp.float32),
        )
        if batch_mean is not None and not self.is_initializing():
            ra_mean.value, ra_var.value = running_stats_update(
                ra_mean.value, ra_var.value, batch_mean, batch_var_biased,
                count, self.momentum,
            )
        return scale, bias


class CrossReplicaBatchNorm(nn.Module):
    """BatchNorm over the (logically global) batch for NHWC activations.

    Attributes:
      momentum: torch-convention running-stat momentum (weight of the NEW batch
        statistic; torch default 0.1).
      epsilon: numerical-stability constant (torch default 1e-5).
      use_running_average: eval mode — normalize with running stats.
      axis_name: if set, batch statistics are additionally ``lax.pmean``-ed over
        this mapped axis (shard_map/pmap path). Leave ``None`` under GSPMD jit,
        where sharded-batch statistics are already global.
      sync: if False, skip the ``axis_name`` reduction even when provided —
        reproduces the reference's non-``--syncBN`` per-device BN semantics.
      local_groups: per-device BN under GSPMD jit (``axis_name=None``): when
        ``sync=False`` and ``local_groups=G > 1``, the batch is split into G
        groups (the data-parallel device slices) and each group normalizes
        with its OWN statistics — the reference's default per-GPU BN.
      group_views: view-major folds in the leading axis. The train step flattens
        the two crops view-major (``[v1 rows | v2 rows]``, supcon_step.py), while
        the reference's per-GPU batch holds BOTH views of its image slice —
        ``group_views=2`` makes group g = {view-1 slice g} ∪ {view-2 slice g},
        matching that composition exactly.
    """

    momentum: float = 0.1
    epsilon: float = 1e-5
    use_running_average: bool = False
    axis_name: Optional[str] = None
    sync: bool = True
    local_groups: int = 1
    group_views: int = 1
    dtype: Optional[jnp.dtype] = None

    @nn.compact
    def __call__(self, x: jax.Array, use_running_average: Optional[bool] = None):
        use_ra = nn.merge_param(
            "use_running_average", self.use_running_average, use_running_average
        )
        num_features = x.shape[-1]
        reduce_axes = tuple(range(x.ndim - 1))  # (N, H, W) for NHWC

        scale = self.param("scale", nn.initializers.ones, (num_features,), jnp.float32)
        bias = self.param("bias", nn.initializers.zeros, (num_features,), jnp.float32)

        ra_mean = self.variable(
            "batch_stats", "mean", lambda: jnp.zeros((num_features,), jnp.float32)
        )
        ra_var = self.variable(
            "batch_stats", "var", lambda: jnp.ones((num_features,), jnp.float32)
        )

        xf = x.astype(jnp.float32)
        grouped = (
            not use_ra
            and not self.sync
            and self.axis_name is None
            and self.local_groups > 1
            # init traces with a tiny example batch (e.g. 2 rows) that need
            # not divide into the groups; shapes/params don't depend on the
            # statistics path, so init uses the whole-batch branch
            and not self.is_initializing()
        )
        if grouped:
            # Per-device BN under one GSPMD program: statistics scoped to the
            # G data-parallel slices instead of the global batch. The [G, C]
            # stats may straddle shard boundaries — XLA inserts tiny
            # reductions; semantics (the reference's default per-GPU BN, not
            # perf) is the point of this mode.
            v, g = self.group_views, self.local_groups
            n = x.shape[0]
            if n % (v * g):
                raise ValueError(
                    f"batch {n} not divisible into {v} views x {g} BN groups"
                )
            spatial = 1
            for a in range(1, x.ndim - 1):
                spatial *= x.shape[a]
            count = (n // g) * spatial
            xg = xf.reshape((v, g, n // (v * g)) + x.shape[1:])
            red = (0,) + tuple(range(2, xg.ndim - 1))
            mean = jnp.mean(xg, axis=red)  # [G, C]
            mean_sq = jnp.mean(jnp.square(xg), axis=red)
            var = mean_sq - jnp.square(mean)  # biased, per group
            if not self.is_initializing():
                # Running stats track group 0: DDP's broadcast_buffers=True
                # re-broadcasts rank 0's BN buffers every forward, so rank 0's
                # local statistics are the persistent ones in the reference.
                ra_mean.value, ra_var.value = running_stats_update(
                    ra_mean.value, ra_var.value, mean[0], var[0],
                    count, self.momentum,
                )
            bshape = (1, g) + (1,) * (xg.ndim - 3) + (num_features,)
            yg = (xg - mean.reshape(bshape)) * jax.lax.rsqrt(
                var.reshape(bshape) + self.epsilon
            )
            y = yg.reshape(x.shape) * scale + bias
            return y.astype(self.dtype or x.dtype)
        if use_ra:
            mean, var = ra_mean.value, ra_var.value
        else:
            mean = jnp.mean(xf, axis=reduce_axes)
            mean_sq = jnp.mean(jnp.square(xf), axis=reduce_axes)
            count = 1
            for a in reduce_axes:
                count *= x.shape[a]
            if self.axis_name is not None and self.sync:
                from simclr_pytorch_distributed_tpu.compat import axis_size

                mean = jax.lax.pmean(mean, self.axis_name)
                mean_sq = jax.lax.pmean(mean_sq, self.axis_name)
                count *= axis_size(self.axis_name)
            var = mean_sq - jnp.square(mean)  # biased — used for normalization

            if not self.is_initializing():
                # torch running update: biased mean, UNBIASED variance.
                ra_mean.value, ra_var.value = running_stats_update(
                    ra_mean.value, ra_var.value, mean, var,
                    count, self.momentum,
                )

        y = (xf - mean) * jax.lax.rsqrt(var + self.epsilon) * scale + bias
        return y.astype(self.dtype or x.dtype)
