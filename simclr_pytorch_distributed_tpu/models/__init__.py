from simclr_pytorch_distributed_tpu.models.resnet import (  # noqa: F401
    MODEL_DICT,
    ResNet,
    resnet18,
    resnet34,
    resnet50,
    resnet101,
)
from simclr_pytorch_distributed_tpu.models.heads import (  # noqa: F401
    LinearClassifier,
    SupCEResNet,
    SupConResNet,
    infer_architecture_from_variables,
)
from simclr_pytorch_distributed_tpu.models.norm import CrossReplicaBatchNorm  # noqa: F401
