"""CIFAR-variant ResNet family (18/34/50/101), TPU-native NHWC Flax modules.

Architecture parity with the reference ``networks/resnet_big.py``:

- CIFAR stem: single 3x3 stride-1 conv, NO maxpool (reference ``:75-77``);
- four stages of widths 64/128/256/512 with strides 1/2/2/2 (``:78-81``);
- ``BasicBlock`` (expansion 1, ``:7-34``) and ``Bottleneck`` (expansion 4,
  ``:37-67``) with 1x1-conv+BN projection shortcuts on shape change (``:18-23``);
- global average pool + flatten (``:82,116-117``) giving 512 (rn18/34) or 2048
  (rn50/101) features — see ``MODEL_DICT`` (reference ``model_dict :137-142``);
- Kaiming-normal fan-out conv init, BN gamma=1/beta=0 (``:84-89``); optional
  ``zero_init_residual`` zeroing the last BN gamma per block (``:94-99``).

Deliberately NOT carried over (dead code in the reference, SURVEY.md §2.1 #11):
the never-enabled ``is_last``/preact return path, the unused ``layer`` forward
argument, and ``LinearBatchNorm``.

TPU-first choices: NHWC layout (XLA:TPU's native conv layout), fp32 params with
an optional bf16 compute ``dtype`` (convs hit the MXU in bf16; BN statistics stay
fp32 inside ``CrossReplicaBatchNorm``).
"""

from __future__ import annotations

from functools import partial
from typing import Any, Callable, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
from flax import linen as nn

from simclr_pytorch_distributed_tpu.models.norm import (
    CrossReplicaBatchNorm,
    FusedTrainBN,
)
from simclr_pytorch_distributed_tpu.ops import pallas_conv

# torch nn.init.kaiming_normal_(mode='fan_out', nonlinearity='relu')
conv_kernel_init = nn.initializers.variance_scaling(2.0, "fan_out", "normal")


class _ConvKernel(nn.Module):
    """Parameter shadow of ``nn.Conv`` for the fused Pallas path: owns ONLY
    the ``kernel`` param, under nn.Conv's name/shape/init/param_dtype, so
    the param tree is impl-independent (``--conv_impl pallas`` checkpoints
    restore under ``--conv_impl xla`` and vice versa). Init always traces
    the XLA branch, so this shadow only ever READS the existing param."""

    shape: Tuple[int, ...]

    @nn.compact
    def __call__(self) -> jax.Array:
        return self.param("kernel", conv_kernel_init, self.shape, jnp.float32)


def _interpret_pallas() -> bool:
    """Pallas kernels run compiled on TPU, interpreted elsewhere (the CPU
    parity/test path — slow, for correctness only)."""
    return jax.default_backend() != "tpu"


# torch Conv2d(k=3, padding=1) pads (1,1) on each spatial dim. Flax's default
# 'SAME' agrees at stride 1 but at stride 2 XLA pads (0,1), shifting every
# window by one pixel vs torch — weight transplants from the reference would
# silently diverge (caught by tests/test_torch_parity.py). Explicit padding
# pins torch alignment; 1x1 convs use torch's padding=0 ('VALID').
PAD3 = ((1, 1), (1, 1))


class BasicBlock(nn.Module):
    """3x3 + 3x3 residual block, expansion 1 (reference resnet_big.py:7-34)."""

    planes: int
    stride: int = 1
    expansion: int = 1
    dtype: Any = jnp.float32
    norm: Callable[..., nn.Module] = CrossReplicaBatchNorm
    # "pallas": route identity-shortcut train-mode applies through the
    # fused conv+BN+ReLU residual-block kernel (ops/pallas_conv.py) when
    # supports_block admits the geometry; everything else (stride-2 /
    # projection blocks, eval mode, init, unsupported shapes) stays on the
    # bitwise-pinned XLA path below. The ResNet owner only passes "pallas"
    # when the BN config is whole-batch (models/norm.py semantics the
    # kernel implements) and the compute dtype is fp32.
    conv_impl: str = "xla"

    @nn.compact
    def __call__(self, x, train: bool = True):  # train is
        # positional-or-keyword so nn.remat can mark it static (argnum 2)
        if (
            self.conv_impl == "pallas"
            and train
            and not self.is_initializing()
            and pallas_conv.supports_block(
                x.shape[0], x.shape[1], x.shape[2], self.planes,
                stride=self.stride, in_channels=x.shape[-1],
            )
        ):
            k1 = _ConvKernel((3, 3, x.shape[-1], self.planes), name="Conv_0")()
            k2 = _ConvKernel((3, 3, self.planes, self.planes), name="Conv_1")()
            bn1 = FusedTrainBN(self.planes, name="bn1")
            bn2 = FusedTrainBN(self.planes, name="bn2")
            g1, b1 = bn1()
            g2, b2 = bn2()
            out, m1, v1, m2, v2 = pallas_conv.fused_basic_block(
                x, k1, g1, b1, k2, g2, b2, interpret=_interpret_pallas()
            )
            count = x.shape[0] * x.shape[1] * x.shape[2]
            bn1(m1, v1, count)  # running-stat update (second call)
            bn2(m2, v2, count)
            return out.astype(self.dtype)
        norm = partial(self.norm, use_running_average=not train)
        conv = partial(
            nn.Conv, use_bias=False, kernel_init=conv_kernel_init, dtype=self.dtype,
            param_dtype=jnp.float32,
        )
        out = conv(
            self.planes, (3, 3), strides=(self.stride, self.stride), padding=PAD3
        )(x)
        out = nn.relu(norm(name="bn1")(out))
        out = conv(self.planes, (3, 3), padding=PAD3)(out)
        out = norm(name="bn2")(out)

        shortcut = x
        if self.stride != 1 or x.shape[-1] != self.expansion * self.planes:
            shortcut = conv(
                self.expansion * self.planes, (1, 1),
                strides=(self.stride, self.stride), padding="VALID",
                name="shortcut_conv",
            )(x)
            shortcut = norm(name="shortcut_bn")(shortcut)
        return nn.relu(out + shortcut)


class Bottleneck(nn.Module):
    """1x1 -> 3x3 -> 1x1 residual block, expansion 4 (reference resnet_big.py:37-67)."""

    planes: int
    stride: int = 1
    expansion: int = 4
    dtype: Any = jnp.float32
    norm: Callable[..., nn.Module] = CrossReplicaBatchNorm
    # accepted for ctor uniformity with BasicBlock but IGNORED: the fused
    # kernel implements the 3x3+3x3 BasicBlock only — the bottleneck's
    # 1x1-3x3-1x1 chain (three BN stages) is the recorded open edge
    # (docs/PERF.md round 15); rn50-family blocks always take the XLA path
    conv_impl: str = "xla"

    @nn.compact
    def __call__(self, x, train: bool = True):  # train is
        # positional-or-keyword so nn.remat can mark it static (argnum 2)
        norm = partial(self.norm, use_running_average=not train)
        conv = partial(
            nn.Conv, use_bias=False, kernel_init=conv_kernel_init, dtype=self.dtype,
            param_dtype=jnp.float32,
        )
        out = conv(self.planes, (1, 1), padding="VALID")(x)
        out = nn.relu(norm(name="bn1")(out))
        out = conv(
            self.planes, (3, 3), strides=(self.stride, self.stride), padding=PAD3
        )(out)
        out = nn.relu(norm(name="bn2")(out))
        out = conv(self.expansion * self.planes, (1, 1), padding="VALID")(out)
        out = norm(name="bn3")(out)

        shortcut = x
        if self.stride != 1 or x.shape[-1] != self.expansion * self.planes:
            shortcut = conv(
                self.expansion * self.planes, (1, 1),
                strides=(self.stride, self.stride), padding="VALID",
                name="shortcut_conv",
            )(x)
            shortcut = norm(name="shortcut_bn")(shortcut)
        return nn.relu(out + shortcut)


class ResNet(nn.Module):
    """CIFAR-stem ResNet encoder -> [N, feat_dim] (reference resnet_big.py:70-118)."""

    block_cls: Any = Bottleneck
    stage_sizes: Sequence[int] = (3, 4, 6, 3)
    in_channel: int = 3
    dtype: Any = jnp.float32
    axis_name: Optional[str] = None
    sync_bn: bool = True
    # per-device BN groups under GSPMD when sync_bn=False (the reference's
    # default per-GPU BatchNorm2d; see models/norm.py); 1 = whole-batch stats
    bn_local_groups: int = 1
    bn_group_views: int = 1
    # "conv": the reference 3x3/s1 stem. "s2d": 2x2 space-to-depth repacked
    # stem (throughput experiment, NOT in the reference): the 3-channel conv
    # wastes ~80% of the MXU's 128 input lanes (K=27 after im2col); repacking
    # to [H/2, W/2, 12] and convolving 108->256 packed channels halves the
    # padded MXU work, then depth-to-space restores [H, W, 64] so every later
    # layer is unchanged. Slightly larger hypothesis class (6x6 receptive
    # field); not weight-compatible with the reference stem.
    stem: str = "conv"
    # activation rematerialization per residual block: backward recomputes
    # each block's activations instead of keeping them in HBM — the standard
    # FLOPs-for-memory trade for bigger per-chip batches (identical numerics)
    remat: bool = False
    # "xla" (default, bitwise-pinned) or "pallas": fused conv+BN+ReLU
    # kernels (ops/pallas_conv.py) for the stem and the identity-shortcut
    # BasicBlocks whose geometry supports_block/supports_stem admit; only
    # effective in train mode under whole-batch BN statistics and fp32
    # compute — everything else falls back per-site to the XLA path.
    # Resolve from the --conv_impl flag via train.supcon.resolve_conv_impl.
    conv_impl: str = "xla"

    @nn.compact
    def __call__(self, x: jax.Array, *, train: bool = True) -> jax.Array:
        norm = partial(
            CrossReplicaBatchNorm, axis_name=self.axis_name, sync=self.sync_bn,
            local_groups=self.bn_local_groups, group_views=self.bn_group_views,
        )
        block_cls = (
            nn.remat(self.block_cls, static_argnums=(2,))
            if self.remat else self.block_cls
        )
        x = x.astype(self.dtype)
        # fused kernels implement whole-batch fp32 train-mode BN only: the
        # grouped per-device mode (sync=False, local_groups>1) and explicit
        # axis_name reductions stay on the Flax path (models/norm.py)
        fused_ok = (
            self.conv_impl == "pallas"
            and self.axis_name is None
            and (self.sync_bn or self.bn_local_groups == 1)
            and self.dtype == jnp.float32
        )
        block_conv_impl = "pallas" if fused_ok else "xla"
        if (
            fused_ok
            and self.stem == "conv"
            and train
            and not self.is_initializing()
            and pallas_conv.supports_stem(
                x.shape[0], x.shape[1], x.shape[2], x.shape[3], 64
            )
        ):
            kernel = _ConvKernel((3, 3, x.shape[-1], 64), name="conv1")()
            bn1 = FusedTrainBN(64, name="bn1")
            g, b = bn1()
            x, m, v = pallas_conv.fused_conv_bn_relu(
                x, kernel, g, b, interpret=_interpret_pallas()
            )
            count = x.shape[0] * x.shape[1] * x.shape[2]
            bn1(m, v, count)  # running-stat update (second call)
            x = x.astype(self.dtype)
        elif self.stem == "s2d":
            n, h, w, c = x.shape
            x = x.reshape(n, h // 2, 2, w // 2, 2, c)
            x = x.transpose(0, 1, 3, 2, 4, 5).reshape(n, h // 2, w // 2, 4 * c)
            x = nn.Conv(
                4 * 64, (3, 3), strides=(1, 1), use_bias=False, padding=PAD3,
                kernel_init=conv_kernel_init, dtype=self.dtype,
                param_dtype=jnp.float32, name="conv1_s2d",
            )(x)
            x = x.reshape(n, h // 2, w // 2, 2, 2, 64)
            x = x.transpose(0, 1, 3, 2, 4, 5).reshape(n, h, w, 64)
        else:
            x = nn.Conv(
                64, (3, 3), strides=(1, 1), use_bias=False, padding=PAD3,
                kernel_init=conv_kernel_init, dtype=self.dtype,
                param_dtype=jnp.float32, name="conv1",
            )(x)
            x = nn.relu(norm(use_running_average=not train, name="bn1")(x))
        if self.stem == "s2d":
            x = nn.relu(norm(use_running_average=not train, name="bn1")(x))
        widths = (64, 128, 256, 512)
        strides = (1, 2, 2, 2)
        for stage, (n_blocks, width, stage_stride) in enumerate(
            zip(self.stage_sizes, widths, strides)
        ):
            for block in range(n_blocks):
                x = block_cls(
                    planes=width,
                    stride=stage_stride if block == 0 else 1,
                    dtype=self.dtype,
                    norm=norm,
                    conv_impl=block_conv_impl,
                    name=f"layer{stage + 1}_block{block}",
                )(x, train)
        x = jnp.mean(x, axis=(1, 2))  # global average pool (AdaptiveAvgPool2d((1,1)))
        return x.astype(jnp.float32)


def resnet10(**kwargs) -> ResNet:
    """One BasicBlock per stage — NOT in the reference model_dict
    (resnet_big.py:137-142); an extension for fast smoke tests and small
    experiments where resnet18's compile time dominates."""
    return ResNet(block_cls=BasicBlock, stage_sizes=(1, 1, 1, 1), **kwargs)


def resnet18(**kwargs) -> ResNet:
    return ResNet(block_cls=BasicBlock, stage_sizes=(2, 2, 2, 2), **kwargs)


def resnet34(**kwargs) -> ResNet:
    return ResNet(block_cls=BasicBlock, stage_sizes=(3, 4, 6, 3), **kwargs)


def resnet50(**kwargs) -> ResNet:
    return ResNet(block_cls=Bottleneck, stage_sizes=(3, 4, 6, 3), **kwargs)


def resnet101(**kwargs) -> ResNet:
    return ResNet(block_cls=Bottleneck, stage_sizes=(3, 4, 23, 3), **kwargs)


# name -> (constructor, feature dim); reference model_dict resnet_big.py:137-142.
MODEL_DICT: dict[str, Tuple[Callable[..., ResNet], int]] = {
    "resnet10": (resnet10, 512),  # test/smoke extension, not in the reference
    "resnet18": (resnet18, 512),
    "resnet34": (resnet34, 512),
    "resnet50": (resnet50, 2048),
    "resnet101": (resnet101, 2048),
}
