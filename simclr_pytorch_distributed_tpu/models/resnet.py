"""CIFAR-variant ResNet family (18/34/50/101), TPU-native NHWC Flax modules.

Architecture parity with the reference ``networks/resnet_big.py``:

- CIFAR stem: single 3x3 stride-1 conv, NO maxpool (reference ``:75-77``);
- four stages of widths 64/128/256/512 with strides 1/2/2/2 (``:78-81``);
- ``BasicBlock`` (expansion 1, ``:7-34``) and ``Bottleneck`` (expansion 4,
  ``:37-67``) with 1x1-conv+BN projection shortcuts on shape change (``:18-23``);
- global average pool + flatten (``:82,116-117``) giving 512 (rn18/34) or 2048
  (rn50/101) features — see ``MODEL_DICT`` (reference ``model_dict :137-142``);
- Kaiming-normal fan-out conv init, BN gamma=1/beta=0 (``:84-89``); optional
  ``zero_init_residual`` zeroing the last BN gamma per block (``:94-99``).

Deliberately NOT carried over (dead code in the reference, SURVEY.md §2.1 #11):
the never-enabled ``is_last``/preact return path, the unused ``layer`` forward
argument, and ``LinearBatchNorm``.

TPU-first choices: NHWC layout (XLA:TPU's native conv layout), fp32 params with
an optional bf16 compute ``dtype`` (convs hit the MXU in bf16; BN statistics stay
fp32 inside ``CrossReplicaBatchNorm``).
"""

from __future__ import annotations

from functools import partial
from typing import Any, Callable, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
from flax import linen as nn

from simclr_pytorch_distributed_tpu.models.norm import (
    CrossReplicaBatchNorm,
    FusedTrainBN,
)
from simclr_pytorch_distributed_tpu.ops import pallas_conv

# torch nn.init.kaiming_normal_(mode='fan_out', nonlinearity='relu')
conv_kernel_init = nn.initializers.variance_scaling(2.0, "fan_out", "normal")


class _ConvKernel(nn.Module):
    """Parameter shadow of ``nn.Conv`` for the fused Pallas path: owns ONLY
    the ``kernel`` param, under nn.Conv's name/shape/init/param_dtype, so
    the param tree is impl-independent (``--conv_impl pallas`` checkpoints
    restore under ``--conv_impl xla`` and vice versa). Init always traces
    the XLA branch, so this shadow only ever READS the existing param."""

    shape: Tuple[int, ...]

    @nn.compact
    def __call__(self) -> jax.Array:
        return self.param("kernel", conv_kernel_init, self.shape, jnp.float32)


def _interpret_pallas() -> bool:
    """Pallas kernels run compiled on TPU, interpreted elsewhere (the CPU
    parity/test path — slow, for correctness only)."""
    return jax.default_backend() != "tpu"


# torch Conv2d(k=3, padding=1) pads (1,1) on each spatial dim. Flax's default
# 'SAME' agrees at stride 1 but at stride 2 XLA pads (0,1), shifting every
# window by one pixel vs torch — weight transplants from the reference would
# silently diverge (caught by tests/test_torch_parity.py). Explicit padding
# pins torch alignment; 1x1 convs use torch's padding=0 ('VALID').
PAD3 = ((1, 1), (1, 1))


class BasicBlock(nn.Module):
    """3x3 + 3x3 residual block, expansion 1 (reference resnet_big.py:7-34)."""

    planes: int
    stride: int = 1
    expansion: int = 1
    dtype: Any = jnp.float32
    norm: Callable[..., nn.Module] = CrossReplicaBatchNorm
    # "pallas": route train-mode applies through the fused conv+BN+ReLU
    # residual-block kernels (ops/pallas_conv.py) when supports_block
    # admits the geometry — identity-shortcut blocks through
    # fused_basic_block, projection/stride-2 blocks through
    # fused_projection_block (the 1x1-conv+BN shortcut rides the same
    # sequential grid). Everything else (eval mode, init, unsupported
    # shapes, odd stride-2 dims) stays on the bitwise-pinned XLA path
    # below. The ResNet owner only passes "pallas" when the BN config is
    # whole-batch (models/norm.py semantics the kernels implement) and the
    # compute dtype is fp32 or bf16 (bf16 matmuls accumulate fp32 on the
    # MXU; BN statistics stay fp32 either way).
    conv_impl: str = "xla"

    @nn.compact
    def __call__(self, x, train: bool = True):  # train is
        # positional-or-keyword so nn.remat can mark it static (argnum 2)
        if (
            self.conv_impl == "pallas"
            and train
            and not self.is_initializing()
            and pallas_conv.supports_block(
                x.shape[0], x.shape[1], x.shape[2], self.planes,
                stride=self.stride, in_channels=x.shape[-1],
                dtype=self.dtype,
            )
        ):
            cin = x.shape[-1]
            k1 = _ConvKernel((3, 3, cin, self.planes), name="Conv_0")()
            k2 = _ConvKernel((3, 3, self.planes, self.planes), name="Conv_1")()
            bn1 = FusedTrainBN(self.planes, name="bn1")
            bn2 = FusedTrainBN(self.planes, name="bn2")
            g1, b1 = bn1()
            g2, b2 = bn2()
            interp = _interpret_pallas()
            if self.stride == 1 and cin == self.planes:
                out, m1, v1, m2, v2 = pallas_conv.fused_basic_block(
                    x, k1, g1, b1, k2, g2, b2, interpret=interp
                )
                count = x.shape[0] * x.shape[1] * x.shape[2]
            else:
                ks = _ConvKernel((1, 1, cin, self.planes), name="shortcut_conv")()
                bns = FusedTrainBN(self.planes, name="shortcut_bn")
                gs, bs = bns()
                out, m1, v1, m2, v2, mS, vS = pallas_conv.fused_projection_block(
                    x, k1, g1, b1, k2, g2, b2, ks, gs, bs,
                    stride=self.stride, interpret=interp,
                )
                # all three BNs normalize over the block's OUTPUT grid
                count = (
                    x.shape[0]
                    * (x.shape[1] // self.stride)
                    * (x.shape[2] // self.stride)
                )
                bns(mS, vS, count)
            bn1(m1, v1, count)  # running-stat update (second call)
            bn2(m2, v2, count)
            return out.astype(self.dtype)
        norm = partial(self.norm, use_running_average=not train)
        conv = partial(
            nn.Conv, use_bias=False, kernel_init=conv_kernel_init, dtype=self.dtype,
            param_dtype=jnp.float32,
        )
        out = conv(
            self.planes, (3, 3), strides=(self.stride, self.stride), padding=PAD3
        )(x)
        out = nn.relu(norm(name="bn1")(out))
        out = conv(self.planes, (3, 3), padding=PAD3)(out)
        out = norm(name="bn2")(out)

        shortcut = x
        if self.stride != 1 or x.shape[-1] != self.expansion * self.planes:
            shortcut = conv(
                self.expansion * self.planes, (1, 1),
                strides=(self.stride, self.stride), padding="VALID",
                name="shortcut_conv",
            )(x)
            shortcut = norm(name="shortcut_bn")(shortcut)
        return nn.relu(out + shortcut)


class Bottleneck(nn.Module):
    """1x1 -> 3x3 -> 1x1 residual block, expansion 4 (reference resnet_big.py:37-67)."""

    planes: int
    stride: int = 1
    expansion: int = 4
    dtype: Any = jnp.float32
    norm: Callable[..., nn.Module] = CrossReplicaBatchNorm
    # "pallas": route train-mode applies through fused_bottleneck_block
    # (ops/pallas_conv.py) — the whole 1x1-3x3-1x1 chain (three BN stages,
    # plus the 1x1-conv+BN projection shortcut when the shape changes) in
    # one kernel each way; the 1x1 convs are pure [N·H·W,C]@[C,C']
    # contractions needing no im2col scratch. Eval mode, init, and
    # geometries supports_bottleneck rejects stay on the bitwise-pinned
    # XLA path below.
    conv_impl: str = "xla"

    @nn.compact
    def __call__(self, x, train: bool = True):  # train is
        # positional-or-keyword so nn.remat can mark it static (argnum 2)
        if (
            self.conv_impl == "pallas"
            and train
            and not self.is_initializing()
            and self.expansion == 4
            and pallas_conv.supports_bottleneck(
                x.shape[0], x.shape[1], x.shape[2], self.planes,
                stride=self.stride, in_channels=x.shape[-1],
                dtype=self.dtype,
            )
        ):
            cin = x.shape[-1]
            c4 = self.expansion * self.planes
            k1 = _ConvKernel((1, 1, cin, self.planes), name="Conv_0")()
            k2 = _ConvKernel((3, 3, self.planes, self.planes), name="Conv_1")()
            k3 = _ConvKernel((1, 1, self.planes, c4), name="Conv_2")()
            bn1 = FusedTrainBN(self.planes, name="bn1")
            bn2 = FusedTrainBN(self.planes, name="bn2")
            bn3 = FusedTrainBN(c4, name="bn3")
            g1, b1 = bn1()
            g2, b2 = bn2()
            g3, b3 = bn3()
            shortcut = None
            bns = None
            if self.stride != 1 or cin != c4:
                ks = _ConvKernel((1, 1, cin, c4), name="shortcut_conv")()
                bns = FusedTrainBN(c4, name="shortcut_bn")
                gs, bs = bns()
                shortcut = (ks, gs, bs)
            r = pallas_conv.fused_bottleneck_block(
                x, k1, g1, b1, k2, g2, b2, k3, g3, b3, shortcut,
                stride=self.stride, interpret=_interpret_pallas(),
            )
            # bn1 sees the input grid (the 1x1 reduce runs pre-stride);
            # bn2/bn3/shortcut_bn see the strided output grid
            count1 = x.shape[0] * x.shape[1] * x.shape[2]
            count2 = (
                x.shape[0]
                * (x.shape[1] // self.stride)
                * (x.shape[2] // self.stride)
            )
            bn1(r[1], r[2], count1)  # running-stat update (second call)
            bn2(r[3], r[4], count2)
            bn3(r[5], r[6], count2)
            if bns is not None:
                bns(r[7], r[8], count2)
            return r[0].astype(self.dtype)
        norm = partial(self.norm, use_running_average=not train)
        conv = partial(
            nn.Conv, use_bias=False, kernel_init=conv_kernel_init, dtype=self.dtype,
            param_dtype=jnp.float32,
        )
        out = conv(self.planes, (1, 1), padding="VALID")(x)
        out = nn.relu(norm(name="bn1")(out))
        out = conv(
            self.planes, (3, 3), strides=(self.stride, self.stride), padding=PAD3
        )(out)
        out = nn.relu(norm(name="bn2")(out))
        out = conv(self.expansion * self.planes, (1, 1), padding="VALID")(out)
        out = norm(name="bn3")(out)

        shortcut = x
        if self.stride != 1 or x.shape[-1] != self.expansion * self.planes:
            shortcut = conv(
                self.expansion * self.planes, (1, 1),
                strides=(self.stride, self.stride), padding="VALID",
                name="shortcut_conv",
            )(x)
            shortcut = norm(name="shortcut_bn")(shortcut)
        return nn.relu(out + shortcut)


class ResNet(nn.Module):
    """CIFAR-stem ResNet encoder -> [N, feat_dim] (reference resnet_big.py:70-118)."""

    block_cls: Any = Bottleneck
    stage_sizes: Sequence[int] = (3, 4, 6, 3)
    in_channel: int = 3
    dtype: Any = jnp.float32
    axis_name: Optional[str] = None
    sync_bn: bool = True
    # per-device BN groups under GSPMD when sync_bn=False (the reference's
    # default per-GPU BatchNorm2d; see models/norm.py); 1 = whole-batch stats
    bn_local_groups: int = 1
    bn_group_views: int = 1
    # "conv": the reference 3x3/s1 stem. "s2d": 2x2 space-to-depth repacked
    # stem (throughput experiment, NOT in the reference): the 3-channel conv
    # wastes ~80% of the MXU's 128 input lanes (K=27 after im2col); repacking
    # to [H/2, W/2, 12] and convolving 108->256 packed channels halves the
    # padded MXU work, then depth-to-space restores [H, W, 64] so every later
    # layer is unchanged. Slightly larger hypothesis class (6x6 receptive
    # field); not weight-compatible with the reference stem.
    stem: str = "conv"
    # activation rematerialization per residual block: backward recomputes
    # each block's activations instead of keeping them in HBM — the standard
    # FLOPs-for-memory trade for bigger per-chip batches (identical numerics)
    remat: bool = False
    # "xla" (default, bitwise-pinned) or "pallas": fused conv+BN+ReLU
    # kernels (ops/pallas_conv.py) for the stem, BasicBlocks (identity AND
    # projection/stride-2 shortcuts), and rn50-family Bottlenecks whose
    # geometry the per-site supports_* gates admit; only effective in
    # train mode under whole-batch BN statistics and fp32/bf16 compute
    # (bf16 matmuls accumulate fp32; BN statistics stay fp32) —
    # everything else falls back per-site to the XLA path. Resolve from
    # the --conv_impl flag via train.supcon.resolve_conv_impl; the
    # per-site plan is fused_site_plan below (single-sourced with the
    # resolution banner).
    conv_impl: str = "xla"

    @nn.compact
    def __call__(self, x: jax.Array, *, train: bool = True) -> jax.Array:
        norm = partial(
            CrossReplicaBatchNorm, axis_name=self.axis_name, sync=self.sync_bn,
            local_groups=self.bn_local_groups, group_views=self.bn_group_views,
        )
        block_cls = (
            nn.remat(self.block_cls, static_argnums=(2,))
            if self.remat else self.block_cls
        )
        x = x.astype(self.dtype)
        # fused kernels implement whole-batch train-mode BN only: the
        # grouped per-device mode (sync=False, local_groups>1) and explicit
        # axis_name reductions stay on the Flax path (models/norm.py).
        # Compute dtype may be fp32 or bf16 (the kernels accumulate fp32
        # on the MXU and keep BN statistics fp32 either way).
        fused_ok = (
            self.conv_impl == "pallas"
            and self.axis_name is None
            and (self.sync_bn or self.bn_local_groups == 1)
            and self.dtype in (jnp.float32, jnp.bfloat16)
        )
        block_conv_impl = "pallas" if fused_ok else "xla"
        if (
            fused_ok
            and self.stem == "conv"
            and train
            and not self.is_initializing()
            and pallas_conv.supports_stem(
                x.shape[0], x.shape[1], x.shape[2], x.shape[3], 64,
                dtype=self.dtype,
            )
        ):
            kernel = _ConvKernel((3, 3, x.shape[-1], 64), name="conv1")()
            bn1 = FusedTrainBN(64, name="bn1")
            g, b = bn1()
            x, m, v = pallas_conv.fused_conv_bn_relu(
                x, kernel, g, b, interpret=_interpret_pallas()
            )
            count = x.shape[0] * x.shape[1] * x.shape[2]
            bn1(m, v, count)  # running-stat update (second call)
            x = x.astype(self.dtype)
        elif self.stem == "s2d":
            n, h, w, c = x.shape
            x = x.reshape(n, h // 2, 2, w // 2, 2, c)
            x = x.transpose(0, 1, 3, 2, 4, 5).reshape(n, h // 2, w // 2, 4 * c)
            x = nn.Conv(
                4 * 64, (3, 3), strides=(1, 1), use_bias=False, padding=PAD3,
                kernel_init=conv_kernel_init, dtype=self.dtype,
                param_dtype=jnp.float32, name="conv1_s2d",
            )(x)
            x = x.reshape(n, h // 2, w // 2, 2, 2, 64)
            x = x.transpose(0, 1, 3, 2, 4, 5).reshape(n, h, w, 64)
        else:
            x = nn.Conv(
                64, (3, 3), strides=(1, 1), use_bias=False, padding=PAD3,
                kernel_init=conv_kernel_init, dtype=self.dtype,
                param_dtype=jnp.float32, name="conv1",
            )(x)
            x = nn.relu(norm(use_running_average=not train, name="bn1")(x))
        if self.stem == "s2d":
            x = nn.relu(norm(use_running_average=not train, name="bn1")(x))
        widths = (64, 128, 256, 512)
        strides = (1, 2, 2, 2)
        for stage, (n_blocks, width, stage_stride) in enumerate(
            zip(self.stage_sizes, widths, strides)
        ):
            for block in range(n_blocks):
                x = block_cls(
                    planes=width,
                    stride=stage_stride if block == 0 else 1,
                    dtype=self.dtype,
                    norm=norm,
                    conv_impl=block_conv_impl,
                    name=f"layer{stage + 1}_block{block}",
                )(x, train)
        x = jnp.mean(x, axis=(1, 2))  # global average pool (AdaptiveAvgPool2d((1,1)))
        return x.astype(jnp.float32)


def resnet10(**kwargs) -> ResNet:
    """One BasicBlock per stage — NOT in the reference model_dict
    (resnet_big.py:137-142); an extension for fast smoke tests and small
    experiments where resnet18's compile time dominates."""
    return ResNet(block_cls=BasicBlock, stage_sizes=(1, 1, 1, 1), **kwargs)


def resnet18(**kwargs) -> ResNet:
    return ResNet(block_cls=BasicBlock, stage_sizes=(2, 2, 2, 2), **kwargs)


def resnet34(**kwargs) -> ResNet:
    return ResNet(block_cls=BasicBlock, stage_sizes=(3, 4, 6, 3), **kwargs)


def resnet50(**kwargs) -> ResNet:
    return ResNet(block_cls=Bottleneck, stage_sizes=(3, 4, 6, 3), **kwargs)


def resnet101(**kwargs) -> ResNet:
    return ResNet(block_cls=Bottleneck, stage_sizes=(3, 4, 23, 3), **kwargs)


# name -> (constructor, feature dim); reference model_dict resnet_big.py:137-142.
MODEL_DICT: dict[str, Tuple[Callable[..., ResNet], int]] = {
    "resnet10": (resnet10, 512),  # test/smoke extension, not in the reference
    "resnet18": (resnet18, 512),
    "resnet34": (resnet34, 512),
    "resnet50": (resnet50, 2048),
    "resnet101": (resnet101, 2048),
}


def fused_site_plan(
    model: str, rows: int, size: int, dtype: Any = jnp.float32
) -> list:
    """The single-sourced per-site geometry walk for ``--conv_impl pallas``.

    Mirrors ``ResNet.__call__``'s stage loop exactly and consults the same
    ``ops/pallas_conv.supports_*`` gates the block modules call with their
    runtime input shapes — so the resolution banner
    (train.supcon.resolve_conv_impl), the per-site module gate, and the
    kernel wrappers can never disagree about which sites fuse. The
    supports_* convention is block INPUT spatial dims; the walk tracks the
    XLA stride-2 output as ``ceil(h/2)`` ((1,1) padding at stride 2), which
    the kernels' even-dims requirement makes exact (``h//2``) wherever a
    stride-2 site is actually admitted.

    ``rows`` is the encoder's view-major batch (``2*batch_size`` for the
    two-crop step). Returns one dict per potential fusion site::

        {"name", "kind": "stem"|"basic"|"proj"|"bottleneck",
         "h", "w", "in_channels", "width", "stride", "admitted", "desc"}
    """
    ctor, _ = MODEL_DICT[model]
    mod = ctor()
    sites: list = []
    h = w = size
    stem_ok = bool(
        mod.stem == "conv"
        and pallas_conv.supports_stem(rows, h, w, mod.in_channel, 64, dtype=dtype)
    )
    sites.append({
        "name": "stem", "kind": "stem", "h": h, "w": w,
        "in_channels": mod.in_channel, "width": 64, "stride": 1,
        "admitted": stem_ok, "desc": f"stem {mod.in_channel}->64@{h}x{w}",
    })
    widths = (64, 128, 256, 512)
    stage_strides = (1, 2, 2, 2)
    expansion = mod.block_cls.expansion
    in_c = 64
    for stage, (n_blocks, width, stage_stride) in enumerate(
        zip(mod.stage_sizes, widths, stage_strides)
    ):
        for block in range(n_blocks):
            stride = stage_stride if block == 0 else 1
            name = f"layer{stage + 1}_block{block}"
            if mod.block_cls is BasicBlock:
                kind = "basic" if (stride == 1 and in_c == width) else "proj"
                admitted = bool(pallas_conv.supports_block(
                    rows, h, w, width, stride=stride, in_channels=in_c,
                    dtype=dtype,
                ))
            elif mod.block_cls is Bottleneck and expansion == 4:
                kind = "bottleneck"
                admitted = bool(pallas_conv.supports_bottleneck(
                    rows, h, w, width, stride=stride, in_channels=in_c,
                    dtype=dtype,
                ))
            else:  # pragma: no cover - no such block class registered
                kind, admitted = "unknown", False
            out_c = width * expansion
            sites.append({
                "name": name, "kind": kind, "h": h, "w": w,
                "in_channels": in_c, "width": width, "stride": stride,
                "admitted": admitted,
                "desc": f"{name}[{kind}] {in_c}->{out_c}@{h}x{w}/s{stride}",
            })
            if stride != 1:
                h = (h + 1) // 2
                w = (w + 1) // 2
            in_c = out_c
    return sites
