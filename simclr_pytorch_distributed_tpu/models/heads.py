"""Task heads over the ResNet encoder (reference resnet_big.py:159-204).

- ``SupConResNet``: encoder + projection head ('mlp' default: dim->dim->ReLU->128,
  or 'linear'), returning the UNNORMALIZED embedding — L2 normalization happens in
  the train step after the global gather, matching the reference driver
  (``main_supcon.py:283``; head defined at ``resnet_big.py:165-172``).
- ``LinearClassifier``: single linear layer over frozen encoder features
  (``resnet_big.py:196-204``).
- ``SupCEResNet``: encoder + linear classifier for the cross-entropy baseline
  (``resnet_big.py:184-193``; its trainer was lost in the reference fork and is
  rebuilt in ``train/ce.py``).

Linear layers use torch's default init (uniform ±1/sqrt(fan_in) for both kernel
and bias) so the published recipe's init statistics carry over.
"""

from __future__ import annotations

import re
from typing import Any, Optional, Tuple

import jax
import jax.numpy as jnp
from flax import linen as nn

from simclr_pytorch_distributed_tpu.models.resnet import MODEL_DICT, Bottleneck


class TorchDense(nn.Module):
    """nn.Dense with torch nn.Linear's default U(±1/sqrt(fan_in)) init."""

    features: int
    dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, x: jax.Array) -> jax.Array:
        fan_in = x.shape[-1]
        bound = 1.0 / (fan_in**0.5)

        def uniform_init(key, shape, dtype=jnp.float32):
            return jax.random.uniform(key, shape, dtype, -bound, bound)

        kernel = self.param("kernel", uniform_init, (fan_in, self.features))
        bias = self.param("bias", uniform_init, (self.features,))
        y = x.astype(self.dtype) @ kernel.astype(self.dtype)
        return y + bias.astype(self.dtype)


class ProjectionHead(nn.Module):
    """'mlp' (dim_in -> dim_in -> ReLU -> feat_dim) or 'linear' head
    (reference resnet_big.py:165-172)."""

    head: str = "mlp"
    dim_in: int = 2048
    feat_dim: int = 128
    dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, x: jax.Array) -> jax.Array:
        if self.head == "linear":
            return TorchDense(self.feat_dim, dtype=self.dtype, name="fc")(x)
        if self.head == "mlp":
            h = TorchDense(self.dim_in, dtype=self.dtype, name="fc1")(x)
            h = nn.relu(h)
            return TorchDense(self.feat_dim, dtype=self.dtype, name="fc2")(h)
        raise NotImplementedError(f"head not supported: {self.head}")


class PredictorHead(nn.Module):
    """BYOL/SimSiam prediction MLP over the projector output
    (dim_out -> hidden -> batch-norm -> ReLU -> dim_out).

    The asymmetric half of the negative-free recipes
    (simclr_pytorch_distributed_tpu/recipes/): the online branch predicts the
    (stop-gradient) target/sibling projection through this head, which is
    what keeps those losses from collapsing — ablating it is the recipes'
    collapse-injection arm (``--byol_predictor none``). The hidden-layer
    batch normalization is the papers' own (BYOL §3.3 / SimSiam §4.4 name
    it as stability-critical, and this repo MEASURED the BN-free variant
    collapsing within 2 tiny epochs — the detector caught it); it
    normalizes by the CURRENT batch's statistics with no running-stat
    tracking, because the predictor only ever runs in train mode — which
    keeps the head's variables in ``params`` alone (no ``batch_stats``
    collection riding the recipe slots).
    """

    dim_hidden: int = 512
    dim_out: int = 128
    dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, z: jax.Array) -> jax.Array:
        h = TorchDense(self.dim_hidden, dtype=self.dtype, name="fc1")(z)
        mean = jnp.mean(h, axis=0, keepdims=True)
        var = jnp.var(h, axis=0, keepdims=True)
        h = (h - mean) / jnp.sqrt(var + 1e-5)
        h = h * self.param("bn_scale", nn.initializers.ones,
                           (self.dim_hidden,))
        h = h + self.param("bn_bias", nn.initializers.zeros,
                           (self.dim_hidden,))
        h = nn.relu(h)
        return TorchDense(self.dim_out, dtype=self.dtype, name="fc2")(h)


class SupConResNet(nn.Module):
    """Backbone + projection head (reference resnet_big.py:159-181)."""

    model_name: str = "resnet50"
    head: str = "mlp"
    feat_dim: int = 128
    dtype: Any = jnp.float32
    axis_name: Optional[str] = None
    sync_bn: bool = True
    # per-device BN when sync_bn=False: groups = data-parallel degree, views=2
    # (the step's view-major two-crop layout; models/norm.py)
    bn_local_groups: int = 1
    bn_group_views: int = 2
    remat: bool = False  # per-block activation remat (models/resnet.py)
    stem: str = "conv"  # "s2d" = repacked stem experiment (models/resnet.py)
    # "xla" (bitwise-pinned default) or "pallas": fused conv+BN+ReLU stem/
    # BasicBlock kernels where the geometry admits (models/resnet.py,
    # ops/pallas_conv.py); resolve via train.supcon.resolve_conv_impl
    conv_impl: str = "xla"

    def setup(self):
        model_fn, dim_in = MODEL_DICT[self.model_name]
        self.encoder = model_fn(
            dtype=self.dtype, axis_name=self.axis_name, sync_bn=self.sync_bn,
            bn_local_groups=self.bn_local_groups,
            bn_group_views=self.bn_group_views,
            remat=self.remat, stem=self.stem, conv_impl=self.conv_impl,
        )
        self.proj_head = ProjectionHead(
            head=self.head, dim_in=dim_in, feat_dim=self.feat_dim, dtype=self.dtype
        )

    def __call__(self, x: jax.Array, *, train: bool = True) -> jax.Array:
        return self.proj_head(self.encoder(x, train=train))

    def encode(self, x: jax.Array, *, train: bool = False) -> jax.Array:
        """Encoder features only — the probe's frozen feature extractor
        (reference main_linear.py:170-172)."""
        return self.encoder(x, train=train)

    def forward_with_features(self, x: jax.Array, *, train: bool = True):
        """``(projection, encoder_features)`` from ONE backbone forward.

        The online linear probe (train/supcon_step.py) trains on
        ``stop_gradient`` of the encoder features the contrastive forward
        already computes — this method exposes them without a second
        backbone pass (``__call__`` discards the intermediate)."""
        h = self.encoder(x, train=train)
        return self.proj_head(h), h


def infer_architecture_from_variables(variables: dict) -> Tuple[str, str, int]:
    """``(model_name, head, feat_dim)`` from a ``SupConResNet`` params tree.

    The checkpoint layer can restore a ``model`` payload without an abstract
    tree (``utils/checkpoint.load_model_payload``), but consumers still need
    to know WHICH architecture the tree encodes to rebuild the module — this
    reads it off the tree itself (stage block counts + Bottleneck's third
    conv + the proj_head leaf shapes), the orbax-side analogue of
    ``utils/torch_convert.infer_architecture`` for reference state_dicts.
    Accepts ``{'params': ..., ...}`` or a bare params tree.
    """
    params = variables.get("params", variables)
    try:
        enc = params["encoder"]
        head_tree = params["proj_head"]
    except (KeyError, TypeError):
        raise ValueError(
            "variables tree has no encoder/proj_head — not a SupConResNet "
            f"checkpoint (top-level keys: {sorted(params)})"
        )
    stages = [0, 0, 0, 0]
    for name in enc:
        if m := re.match(r"layer(\d)_block(\d+)$", name):
            layer, block = int(m.group(1)), int(m.group(2))
            stages[layer - 1] = max(stages[layer - 1], block + 1)
    bottleneck = "Conv_2" in enc.get("layer1_block0", {})
    name = next(
        (
            n for n, (ctor, _) in MODEL_DICT.items()
            if tuple(ctor().stage_sizes) == tuple(stages)
            and (ctor().block_cls is Bottleneck) == bottleneck
        ),
        None,
    )
    if name is None:
        raise ValueError(
            f"unrecognized encoder geometry: stages={tuple(stages)}, "
            f"bottleneck={bottleneck}"
        )
    if "fc1" in head_tree:
        head, feat_dim = "mlp", int(head_tree["fc2"]["kernel"].shape[-1])
    elif "fc" in head_tree:
        head, feat_dim = "linear", int(head_tree["fc"]["kernel"].shape[-1])
    else:
        raise ValueError(f"unrecognized proj_head tree: {sorted(head_tree)}")
    return name, head, feat_dim


class SupCEResNet(nn.Module):
    """Encoder + classifier for supervised CE (reference resnet_big.py:184-193)."""

    model_name: str = "resnet50"
    num_classes: int = 10
    dtype: Any = jnp.float32
    axis_name: Optional[str] = None
    # The reference's surviving CE entry (main_ce.py, a 68-line stub after the
    # fork) never trains, but the trainer it lost carried the same conditional
    # SyncBN conversion as main_supcon.py:223-224 — so the CE path gets the
    # same semantics: sync_bn=True for global-batch statistics, or grouped
    # per-device statistics (models/norm.py) with bn_local_groups = the
    # data-parallel degree. CE batches are single-view: bn_group_views=1.
    sync_bn: bool = True
    bn_local_groups: int = 1
    bn_group_views: int = 1

    def setup(self):
        model_fn, _ = MODEL_DICT[self.model_name]
        self.encoder = model_fn(
            dtype=self.dtype, axis_name=self.axis_name, sync_bn=self.sync_bn,
            bn_local_groups=self.bn_local_groups,
            bn_group_views=self.bn_group_views,
        )
        self.fc = TorchDense(self.num_classes, dtype=jnp.float32)

    def __call__(self, x: jax.Array, *, train: bool = True) -> jax.Array:
        return self.fc(self.encoder(x, train=train))


class LinearClassifier(nn.Module):
    """Linear probe over precomputed features (reference resnet_big.py:196-204)."""

    model_name: str = "resnet50"
    num_classes: int = 10

    @nn.compact
    def __call__(self, features: jax.Array) -> jax.Array:
        return TorchDense(self.num_classes, name="fc")(features)
