"""Runtime / mesh layer — the TPU-native replacement for the reference's L1.

The reference initializes one NCCL process per GPU via ``torch.distributed.launch``
(``main_supcon.py:359-364``) and weaves collectives through DDP/SyncBN. Here the
runtime is a single SPMD program:

- one process per HOST (not per chip); ``jax.distributed.initialize()`` for
  multi-host rendezvous (replaces the env:// MASTER_ADDR/PORT dance);
- a ``jax.sharding.Mesh`` whose ``data`` axis spans every chip; collectives ride
  ICI within a slice and DCN across slices, chosen by XLA from the shardings;
- a second ``model`` axis is supported for future tensor-parallel layouts — the
  reference has no model parallelism (SURVEY.md §2.2) so it defaults to size 1.

"rank 0"-style I/O gating (reference ``main_supcon.py:137-148,327,397``) becomes
``is_main_process()`` == ``jax.process_index() == 0``.
"""

from __future__ import annotations

import logging
from typing import Optional, Sequence

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

logger = logging.getLogger(__name__)

DATA_AXIS = "data"
MODEL_AXIS = "model"


def setup_distributed(
    coordinator_address: Optional[str] = None,
    num_processes: Optional[int] = None,
    process_id: Optional[int] = None,
) -> None:
    """Multi-host rendezvous (replaces init_process_group, main_supcon.py:359-364).

    No-op on a single host with no coordinator configured. On TPU pods the
    arguments are normally inferred from the environment, so a bare
    ``setup_distributed()`` suffices.
    """
    if coordinator_address is None:
        # No explicit coordinator: either the runtime was already initialized
        # by a launcher wrapper (process_count > 1 — initialize() would
        # raise), or this is a plain single-host run (nothing to do). Only
        # this branch may touch process_count(): the explicit-coordinator
        # path below must reach initialize() before any backend init.
        if jax.process_count() > 1 or num_processes in (None, 1):
            return
    jax.distributed.initialize(
        coordinator_address=coordinator_address,
        num_processes=num_processes,
        process_id=process_id,
    )
    logger.info(
        "distributed: process %d/%d, %d local / %d global devices",
        jax.process_index(), jax.process_count(),
        jax.local_device_count(), jax.device_count(),
    )


def create_mesh(
    devices: Optional[Sequence[jax.Device]] = None,
    model_parallel: int = 1,
    axis_names: Sequence[str] = (DATA_AXIS, MODEL_AXIS),
) -> Mesh:
    """Build a (data, model) mesh over all devices; model axis defaults to 1."""
    if devices is None:
        devices = jax.devices()
    devices = list(devices)
    n = len(devices)
    if n % model_parallel != 0:
        raise ValueError(f"{n} devices not divisible by model_parallel={model_parallel}")
    dev_array = np.array(devices).reshape(n // model_parallel, model_parallel)
    return Mesh(dev_array, tuple(axis_names))


def broadcast_from_main(s: str, max_len: int = 512) -> str:
    """Every process adopts process 0's value of a small string.

    Run/checkpoint folder names embed a minute-resolution wall-clock
    timestamp derived independently on each process (config parity with the
    reference); with collective orbax saves the folder must agree across
    hosts, so clock skew across a minute boundary would corrupt checkpoints.
    No-op on a single process.
    """
    if jax.process_count() == 1:
        return s
    from jax.experimental import multihost_utils

    buf = np.zeros(max_len, np.uint8)
    raw = s.encode()
    if len(raw) > max_len:
        raise ValueError(f"string too long to broadcast ({len(raw)} > {max_len})")
    buf[: len(raw)] = np.frombuffer(raw, np.uint8)
    out = multihost_utils.broadcast_one_to_all(buf)
    # cast by VALUE, not raw memory: the broadcast can return the uint8
    # payload in a widened dtype (observed with gloo CPU collectives),
    # and bytes() of that buffer interleaves every char with nulls
    out = np.asarray(out).astype(np.uint8)
    return out.tobytes().rstrip(b"\x00").decode()


def sync_processes(tag: str) -> None:
    """Cross-process barrier before exit paths.

    In a multi-host job, process 0 finishes slow end-of-run I/O (final orbax
    save, meter drains) AFTER the other processes fall off the epoch loop; if
    they exit immediately, the JAX coordination-service shutdown barrier times
    out and every process dies with a spurious INTERNAL error. One explicit
    sync keeps all processes alive until the slowest is done. No-op on a
    single process.
    """
    if jax.process_count() > 1:
        from jax.experimental import multihost_utils

        multihost_utils.sync_global_devices(tag)


def is_main_process() -> bool:
    """Process-0 gating for I/O (reference local_rank==0 checks)."""
    return jax.process_index() == 0


def batch_sharding(mesh: Mesh, ndim: int = 1) -> NamedSharding:
    """Shard the leading (batch) dim over 'data'; replicate everything else."""
    return NamedSharding(mesh, P(DATA_AXIS, *([None] * (ndim - 1))))


def replicated_sharding(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, P())


def epoch_buffer_sharding(mesh: Mesh, ndim: int) -> NamedSharding:
    """Layout of a device-resident ``[steps, batch, ...]`` epoch buffer
    (data/device_store.py): the BATCH dim sharded over 'data', the steps dim
    replicated. Each device therefore holds its own batch slice of EVERY
    step, so the per-step ``lax.dynamic_slice`` on the leading axis is a
    purely local slice — no communication in the hot loop — and on a
    multi-host mesh each process's devices hold exactly that process's
    ``EpochLoader`` slice of every global batch. The windowed store's
    ``[window_batches, batch, ...]`` buffers use the same convention (the
    leading dim is just shorter), so one compiled step layout serves both
    resident shapes."""
    if ndim < 2:
        raise ValueError(f"epoch buffers are [steps, batch, ...]; got ndim={ndim}")
    return NamedSharding(mesh, P(None, DATA_AXIS, *([None] * (ndim - 2))))


def batch_sharding_if_divisible(mesh: Mesh, batch: int, ndim: int = 1) -> NamedSharding:
    """Batch sharding when the size divides the 'data' axis, else replicated.

    GSPMD requires the sharded dim to divide the axis; serving-style
    callers with a FIXED small batch (the engine's jit buckets,
    serve/engine.py) want "shard when it fits, fall back to one-device
    replication when it doesn't" rather than an error — a bucket of 1 on an
    8-chip mesh is a latency path, not a mistake.
    """
    if batch % mesh.shape.get(DATA_AXIS, 1) == 0:
        return batch_sharding(mesh, ndim)
    return replicated_sharding(mesh)


def put_batch_if_divisible(mesh: Mesh, x: np.ndarray) -> jax.Array:
    """Dispatch-stage H2D: place a host batch under the bucket layout NOW.

    The serving engine's dispatch/completion split (serve/engine.py) wants
    the host->device transfer to happen AT DISPATCH — owned by the stage
    that runs while earlier batches are still computing — rather than
    implicitly inside the jitted call's argument handling at whatever moment
    the call is reached. ``device_put`` starts the transfer asynchronously
    and returns immediately; the array lands already laid out as the bucket
    program's ``in_shardings`` expects, so the call commits no further
    host work and XLA never re-shards.
    """
    return jax.device_put(
        x, batch_sharding_if_divisible(mesh, int(x.shape[0]), np.ndim(x))
    )


def tp_leaf_spec(shape, model_size: int, min_last: int = 64) -> P:
    """Channel-wise tensor-parallel spec for one state leaf.

    Shards the trailing (output-channel / feature) axis over 'model' when it
    divides evenly and is large enough to be worth splitting. Applied uniformly
    to params, BN running stats, and optimizer momentum (their shapes mirror
    the params), so the whole train state partitions consistently; GSPMD
    propagates the layouts through convs/matmuls and inserts the tensor-parallel
    collectives. With model_size == 1 everything is replicated (the default —
    the reference has no model parallelism, SURVEY.md §2.2).
    """
    if (
        model_size > 1
        and len(shape) > 0
        and shape[-1] % model_size == 0
        and shape[-1] >= min_last
    ):
        return P(*([None] * (len(shape) - 1)), MODEL_AXIS)
    return P()


def state_sharding(mesh: Mesh, state) -> "jax.tree_util.PyTreeDef":
    """NamedSharding tree for a TrainState-like pytree under the mesh's
    (data, model) layout: batch-independent state is model-axis sharded by
    ``tp_leaf_spec`` and replicated over 'data'."""
    model_size = mesh.shape.get(MODEL_AXIS, 1)

    def leaf(x):
        shape = getattr(x, "shape", ())
        return NamedSharding(mesh, tp_leaf_spec(tuple(shape), model_size))

    return jax.tree.map(leaf, state)


def shard_host_batch(batch, mesh: Mesh):
    """Place a host batch onto the mesh, sharded along 'data'.

    Single-host: a plain ``device_put`` with the batch sharding (the whole array
    is local). Multi-host: each process holds its own shard of the global batch
    (the ``DistributedSampler`` equivalent lives in data/pipeline.py) and the
    global array is assembled from process-local data.
    """
    def put(x):
        sharding = batch_sharding(mesh, np.ndim(x))
        if jax.process_count() == 1:
            return jax.device_put(x, sharding)
        return jax.make_array_from_process_local_data(sharding, np.asarray(x))

    return jax.tree.map(put, batch)
