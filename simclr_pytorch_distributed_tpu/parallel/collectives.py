"""Explicit-collective building blocks: ring-sharded contrastive loss.

The reference materializes the full [2B, 2B] NT-Xent logits matrix on every GPU
(``losses.py:64-66``) after all-gathering every rank's features
(``main_supcon.py:268-269``). That is fine at B=256 but quadratic in HBM: at the
ImageNet-scale bs=4096 recipe the matrix is 8192x8192 per device, and the full
feature gather costs O(2B·D) replicated memory.

``ring_supcon_loss`` is the ring-attention-style decomposition (SURVEY.md §5
long-context row): anchors stay sharded; contrast feature blocks rotate around
the ``data`` ring with ``lax.ppermute`` while each device streams a numerically
exact online log-sum-exp (flash-softmax style) and accumulates positive-pair
similarities. Per-device memory drops to O((2B/P)^2) per ring step and the
block matmuls overlap with neighbor transfers over ICI.

Exactness: the reference's detached row-max subtraction (``losses.py:68-69``)
cancels in ``logit - logsumexp``, so the streamed loss equals the dense loss to
fp tolerance — verified against ``ops.losses.supcon_loss`` in
``tests/test_ring_loss.py``. Differentiable end-to-end (scan + ppermute).

Layout convention matches the train step: global rows are view-major
``[v1 of all samples; v2 of all samples]`` (``main_supcon.py:279``), sharded
contiguously: device d owns rows ``[d*m, (d+1)*m)``, m = 2B/P.
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp

_NEG_INF = -1e30


def ring_supcon_loss(
    feats_local: jax.Array,
    global_labels: Optional[jax.Array] = None,
    *,
    axis_name: str,
    temperature: float = 0.07,
    base_temperature: float = 0.07,
    n_views: int = 2,
) -> jax.Array:
    """SupCon/SimCLR loss over row-sharded L2-normalized features.

    Args:
      feats_local: ``[m, D]`` this device's block of the global view-major
        feature matrix ``[V*B, D]`` (already normalized).
      global_labels: ``[B]`` REPLICATED labels for SupCon, or ``None`` for
        SimCLR (positives = other views of the same sample).
      axis_name: mesh axis the rows are sharded over.
      temperature / base_temperature: as in ``ops.losses.supcon_loss``.
      n_views: V (2 for the TwoCrop recipe).

    Returns:
      Per-device mean anchor loss pmean-ed over the axis == the global loss.
    """
    m, _ = feats_local.shape
    from simclr_pytorch_distributed_tpu.compat import axis_size

    p = axis_size(axis_name)
    my = jax.lax.axis_index(axis_name)
    rows_total = m * p  # V*B
    batch = rows_total // n_views

    g_anchor = my * m + jnp.arange(m)  # global row ids of local anchors
    anchor_sample = g_anchor % batch

    if global_labels is not None:
        anchor_label = global_labels[anchor_sample]

    perm = [(i, (i + 1) % p) for i in range(p)]

    def ring_step(carry, step):
        block, run_max, run_sum, pos_acc, pos_cnt = carry
        src = (my - step) % p  # who this block belongs to
        g_col = src * m + jnp.arange(m)
        sims = (feats_local @ block.T) / temperature  # [m, m] MXU tile

        self_mask = g_anchor[:, None] == g_col[None, :]
        sims_no_self = jnp.where(self_mask, _NEG_INF, sims)

        # online log-sum-exp over non-self columns
        blk_max = jnp.max(sims_no_self, axis=1)
        new_max = jnp.maximum(run_max, blk_max)
        run_sum = run_sum * jnp.exp(run_max - new_max) + jnp.sum(
            jnp.exp(sims_no_self - new_max[:, None]), axis=1
        )

        # positive pairs (excluding self): same sample (SimCLR) / same label (SupCon)
        col_sample = g_col % batch
        if global_labels is None:
            pos_mask = (anchor_sample[:, None] == col_sample[None, :]) & ~self_mask
        else:
            col_label = global_labels[col_sample]
            pos_mask = (anchor_label[:, None] == col_label[None, :]) & ~self_mask
        pos_acc = pos_acc + jnp.sum(jnp.where(pos_mask, sims, 0.0), axis=1)
        pos_cnt = pos_cnt + jnp.sum(pos_mask, axis=1)

        block = jax.lax.ppermute(block, axis_name, perm)
        return (block, new_max, run_sum, pos_acc, pos_cnt), None

    def dev_varying(x):
        # mark fresh accumulators as device-varying for shard_map's vma
        # typing (identity on pre-vma jax, compat.pvary)
        from simclr_pytorch_distributed_tpu.compat import pvary

        return pvary(x, (axis_name,))

    init = (
        feats_local,
        dev_varying(jnp.full((m,), _NEG_INF, feats_local.dtype)),
        dev_varying(jnp.zeros((m,), feats_local.dtype)),
        dev_varying(jnp.zeros((m,), feats_local.dtype)),
        dev_varying(jnp.zeros((m,), feats_local.dtype)),
    )
    (_, run_max, run_sum, pos_acc, pos_cnt), _ = jax.lax.scan(
        ring_step, init, jnp.arange(p)
    )

    log_denom = run_max + jnp.log(run_sum)
    mean_log_prob_pos = pos_acc / pos_cnt - log_denom
    loss_local = -(temperature / base_temperature) * mean_log_prob_pos
    return jax.lax.pmean(jnp.mean(loss_local), axis_name)


def gather_global_labels(labels_local: jax.Array, axis_name: str) -> jax.Array:
    """All-gather the (tiny) per-device label shards into the replicated [B]
    vector the ring loss consumes — the fix for the reference's distributed
    SupCon crash (local labels vs gathered features, main_supcon.py:287-288)."""
    return jax.lax.all_gather(labels_local, axis_name).reshape(-1)
