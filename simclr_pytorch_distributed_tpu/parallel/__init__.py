from simclr_pytorch_distributed_tpu.parallel.mesh import (  # noqa: F401
    batch_sharding,
    create_mesh,
    is_main_process,
    replicated_sharding,
    setup_distributed,
    shard_host_batch,
)
