from simclr_pytorch_distributed_tpu.parallel.mesh import (  # noqa: F401
    batch_sharding,
    create_mesh,
    is_main_process,
    replicated_sharding,
    setup_distributed,
    shard_host_batch,
    state_sharding,
    tp_leaf_spec,
)
from simclr_pytorch_distributed_tpu.parallel.collectives import (  # noqa: F401
    gather_global_labels,
    ring_supcon_loss,
)
