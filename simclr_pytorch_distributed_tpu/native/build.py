"""Build + load the native staging library (ctypes, no pip/pybind needed).

Compiled once per machine into the package dir; falls back to None (callers use
numpy paths) if no toolchain is available.
"""

from __future__ import annotations

import ctypes
import logging
import os
import subprocess
import threading
from typing import Optional

_DIR = os.path.dirname(os.path.abspath(__file__))
_SRC = os.path.join(_DIR, "gather.cpp")
_LIB = os.path.join(_DIR, "libsptpu_native.so")
_lock = threading.Lock()
_lib: Optional[ctypes.CDLL] = None
_tried = False


def _compile() -> bool:
    cmd = ["g++", "-O3", "-shared", "-fPIC", "-std=c++17", _SRC, "-o", _LIB]
    try:
        subprocess.run(cmd, check=True, capture_output=True, timeout=120)
        return True
    except Exception as e:  # toolchain missing / sandboxed
        logging.info("native staging lib unavailable (%s); using numpy paths", e)
        return False


def load() -> Optional[ctypes.CDLL]:
    """Returns the loaded library or None. Thread-safe, compiles on first use."""
    global _lib, _tried
    with _lock:
        if _lib is not None or _tried:
            return _lib
        _tried = True
        if os.environ.get("SPTPU_NATIVE", "1") == "0":
            return None
        if not os.path.exists(_LIB) or os.path.getmtime(_LIB) < os.path.getmtime(_SRC):
            if not _compile():
                return None
        try:
            lib = ctypes.CDLL(_LIB)
        except OSError as e:
            logging.info("failed to load native lib: %s", e)
            return None
        lib.gather_rows_u8.argtypes = [
            ctypes.c_void_p, ctypes.c_void_p, ctypes.c_int64, ctypes.c_int64,
            ctypes.c_void_p,
        ]
        lib.gather_rows_i32.argtypes = [
            ctypes.c_void_p, ctypes.c_void_p, ctypes.c_int64, ctypes.c_void_p,
        ]
        _lib = lib
        return _lib
