// Native host-side batch staging for the data pipeline.
//
// The reference hides its host data path inside torch's C-accelerated
// DataLoader worker pool (8 workers + pinned memory, main_supcon.py:200-207).
// Our host work is far smaller — augmentation happens on device — but the one
// hot host op left is assembling a uint8 batch from a shuffled index set every
// step. This library does that gather in C++ (memcpy per row, no Python object
// overhead) and, crucially, releases the GIL so a prefetch thread overlaps
// batch assembly with the device step (see data/pipeline.py).
//
// Built on demand with g++ -O3 -shared -fPIC (see native/build.py); loaded via
// ctypes. Pure C ABI, no Python headers needed.

#include <cstdint>
#include <cstring>

extern "C" {

// dst[i, :] = src[idx[i], :] for row_bytes-sized rows.
void gather_rows_u8(const uint8_t* src, const int64_t* idx, int64_t n_idx,
                    int64_t row_bytes, uint8_t* dst) {
  for (int64_t i = 0; i < n_idx; ++i) {
    std::memcpy(dst + i * row_bytes, src + idx[i] * row_bytes,
                static_cast<size_t>(row_bytes));
  }
}

// int32 label gather (labels are 4-byte scalars).
void gather_rows_i32(const int32_t* src, const int64_t* idx, int64_t n_idx,
                     int32_t* dst) {
  for (int64_t i = 0; i < n_idx; ++i) {
    dst[i] = src[idx[i]];
  }
}

}  // extern "C"
