"""Contrastive losses (SupCon / SimCLR NT-Xent), functional and jit-friendly.

Semantics match the reference ``losses.py:17-93`` (SupConLoss.forward) exactly in
fp32, including every quirk that shapes the published 89.05% recipe:

- the final ``-(temperature / base_temperature)`` scale with ``base_temperature``
  fixed at 0.07 regardless of ``temperature`` (reference ``losses.py:90`` — at the
  recipe's ``--temp 0.5`` this is a silent ~7.14x loss multiplier),
- the detached per-row max subtraction (reference ``losses.py:68-69``),
- self-contrast masking of the leading diagonal only (reference ``losses.py:74-80``),
- ``contrast_mode`` 'one' / 'all' (reference ``losses.py:54-61``).

The single O((V*B)^2) anchor-by-contrast matmul is the hot kernel; it maps straight
onto the MXU and XLA fuses the mask/log-softmax epilogue around it.
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp


def supcon_loss(
    features: jax.Array,
    labels: Optional[jax.Array] = None,
    mask: Optional[jax.Array] = None,
    *,
    temperature: float = 0.07,
    base_temperature: float = 0.07,
    contrast_mode: str = "all",
) -> jax.Array:
    """Supervised-contrastive / SimCLR loss over multi-view features.

    Args:
      features: ``[batch, n_views, dim]`` feature matrix. The caller is expected to
        L2-normalize rows (the reference driver normalizes post-gather,
        ``main_supcon.py:283`` — this function does not normalize).
      labels: optional ``[batch]`` integer labels (SupCon). Mutually exclusive with
        ``mask``. When both are ``None`` the loss degenerates to SimCLR NT-Xent.
      mask: optional ``[batch, batch]`` explicit positive-pair mask.
      temperature: softmax temperature tau.
      base_temperature: the fixed denominator of the final scale. The reference
        never sets this from ``temperature`` — keep the default to reproduce the
        published recipe.
      contrast_mode: ``'all'`` (every view anchors, the driver default) or
        ``'one'`` (only view 0 anchors).

    Returns:
      Scalar loss.
    """
    if features.ndim < 3:
        raise ValueError("`features` must be [batch, n_views, ...]")
    if features.ndim > 3:
        features = features.reshape(features.shape[0], features.shape[1], -1)

    batch_size, n_views = features.shape[0], features.shape[1]
    compute_dtype = features.dtype

    if labels is not None and mask is not None:
        raise ValueError("Cannot define both `labels` and `mask`")
    if labels is None and mask is None:
        mask = jnp.eye(batch_size, dtype=compute_dtype)
    elif labels is not None:
        labels = labels.reshape(-1, 1)
        if labels.shape[0] != batch_size:
            raise ValueError("Num of labels does not match num of features")
        mask = (labels == labels.T).astype(compute_dtype)
    else:
        mask = mask.astype(compute_dtype)

    # Views stacked batch-major per view: rows [v0 b0..bN, v1 b0..bN, ...]
    # (same ordering as unbind(dim=1)+cat(dim=0), reference losses.py:53).
    contrast_feature = jnp.transpose(features, (1, 0, 2)).reshape(
        n_views * batch_size, -1
    )
    if contrast_mode == "one":
        anchor_feature = features[:, 0]
        anchor_count = 1
    elif contrast_mode == "all":
        anchor_feature = contrast_feature
        anchor_count = n_views
    else:
        raise ValueError(f"Unknown mode: {contrast_mode}")

    # [anchor_count*B, n_views*B] similarity logits — the MXU matmul.
    anchor_dot_contrast = (anchor_feature @ contrast_feature.T) / temperature
    logits_max = jax.lax.stop_gradient(
        jnp.max(anchor_dot_contrast, axis=1, keepdims=True)
    )
    logits = anchor_dot_contrast - logits_max

    # Tile positives mask to all view pairs; zero the self-pair diagonal.
    mask = jnp.tile(mask, (anchor_count, n_views))
    n_anchor_rows = batch_size * anchor_count
    diag = jnp.arange(n_anchor_rows)
    logits_mask = jnp.ones_like(mask).at[diag, diag].set(0.0)
    mask = mask * logits_mask

    exp_logits = jnp.exp(logits) * logits_mask
    log_prob = logits - jnp.log(jnp.sum(exp_logits, axis=1, keepdims=True))

    mean_log_prob_pos = jnp.sum(mask * log_prob, axis=1) / jnp.sum(mask, axis=1)

    loss = -(temperature / base_temperature) * mean_log_prob_pos
    return jnp.mean(loss.reshape(anchor_count, batch_size))


def l2_normalize(x: jax.Array, eps: float = 1e-12) -> jax.Array:
    """Row-wise L2 normalization with a zero-row guard — the recipe losses'
    shared normalizer (byol/simsiam here, the MoCo key branch in
    recipes/supcon.py). The CONTRASTIVE path deliberately does not use it:
    its bare ``feats / norm(feats)`` expression is pinned bitwise against
    the pre-recipe step (docs/PARITY.md)."""
    return x / jnp.maximum(jnp.linalg.norm(x, axis=-1, keepdims=True), eps)


def _cross_views(x: jax.Array) -> jax.Array:
    """Swap the two view blocks of a view-major ``[2B, D]`` matrix, so row
    ``i`` lands on its positive's row ``(i + B) % 2B`` (the train step's
    two-crop layout, train/supcon_step.two_view_forward)."""
    b = x.shape[0] // 2
    return jnp.concatenate([x[b:], x[:b]], axis=0)


def byol_loss(online_pred: jax.Array, target_proj: jax.Array) -> jax.Array:
    """BYOL regression loss (Grill et al. 2020, eq. 2), symmetrized.

    ``online_pred`` is the online branch's predictor output and
    ``target_proj`` the EMA target network's projection, both ``[2B, D]``
    view-major and UNNORMALIZED (normalization happens here, like the
    contrastive path normalizes post-gather). The caller stop-gradients the
    target. Each row regresses onto the OTHER view's target row; with both
    sides unit-norm the squared error is ``2 - 2 cos``, so perfect
    alignment gives 0 and orthogonal views give 2.
    """
    p = l2_normalize(online_pred.astype(jnp.float32))
    t = _cross_views(l2_normalize(target_proj.astype(jnp.float32)))
    return jnp.mean(jnp.sum(jnp.square(p - t), axis=1))


def simsiam_loss(pred: jax.Array, proj: jax.Array) -> jax.Array:
    """SimSiam negative-cosine loss (Chen & He 2021, eq. 1), symmetrized.

    ``pred = h(f(x))`` and ``proj = f(x)`` are the SAME branch's predictor
    output and projection (``[2B, D]`` view-major, unnormalized); the
    stop-gradient on the projection side — the paper's whole mechanism — is
    applied HERE so no caller can forget it. Bounded in ``[-1, 0]`` at
    perfect alignment.
    """
    p = l2_normalize(pred.astype(jnp.float32))
    z = jax.lax.stop_gradient(
        _cross_views(l2_normalize(proj.astype(jnp.float32)))
    )
    return -jnp.mean(jnp.sum(p * z, axis=1))


def vicreg_loss(
    z1: jax.Array,
    z2: jax.Array,
    *,
    sim_coeff: float = 25.0,
    std_coeff: float = 25.0,
    cov_coeff: float = 1.0,
    eps: float = 1e-4,
):
    """VICReg (Bardes et al. 2022): invariance + variance + covariance.

    ``z1``/``z2`` are the two views' UNNORMALIZED projections ``[B, D]``
    (VICReg never L2-normalizes — the variance hinge needs the raw scale).
    Returns ``(loss, parts)`` where ``parts`` carries the three unweighted
    terms under the recipe metric keys (``vicreg_inv``/``vicreg_var``/
    ``vicreg_cov``), streamed through the metric ring so a collapsing
    variance term is visible live. The covariance penalty reuses the
    health diagnostics' covariance construction
    (ops/metrics.embedding_covariance, centered/unbiased here).
    """
    from simclr_pytorch_distributed_tpu.ops.metrics import embedding_covariance

    z1 = z1.astype(jnp.float32)
    z2 = z2.astype(jnp.float32)
    d = z1.shape[1]
    inv = jnp.mean(jnp.square(z1 - z2))
    var_terms = []
    cov_terms = []
    for z in (z1, z2):
        std = jnp.sqrt(jnp.var(z, axis=0) + eps)
        var_terms.append(jnp.mean(jax.nn.relu(1.0 - std)))
        cov = embedding_covariance(z, center=True, ddof=1)
        off_diag = cov - jnp.diag(jnp.diag(cov))
        cov_terms.append(jnp.sum(jnp.square(off_diag)) / d)
    var = 0.5 * (var_terms[0] + var_terms[1])
    cov = 0.5 * (cov_terms[0] + cov_terms[1])
    loss = sim_coeff * inv + std_coeff * var + cov_coeff * cov
    parts = {"vicreg_inv": inv, "vicreg_var": var, "vicreg_cov": cov}
    return loss, parts


def moco_queue_loss(
    query: jax.Array,
    key: jax.Array,
    queue: jax.Array,
    *,
    temperature: float = 0.07,
    base_temperature: float = 0.07,
) -> jax.Array:
    """MoCo-style NT-Xent: online queries against momentum-encoder keys +
    a negative queue of PAST keys.

    ``query`` is the online branch's L2-normalized view-major ``[2B, D]``
    matrix, ``key`` the EMA key encoder's matching ``[2B, D]`` embeddings
    (the caller stop-gradients them — keys never backprop, He et al. 2020),
    and ``queue`` the ``[K, D]`` ring of past keys (recipes/supcon.py
    rotates it in-program), negatives only. Row ``i``'s positive is the
    key of its OTHER view, ``key[(i + B) % 2B]``; its own view's key
    (column ``i`` — the same image through two near-identical encoders) is
    masked like the SimCLR self-pair. The momentum encoder is load-bearing,
    not decorative: enqueueing ONLINE embeddings instead reproduces the
    MoCo paper's ``m = 0`` failure — the one-sided repulsion from the
    rapidly-moving self-cluster collapses the representation within an
    epoch at this repo's scale (measured; recipes/supcon.py docstring).
    Mirrors ``supcon_loss``'s op sequence (detached row-max subtraction,
    self masking, the ``-(T / base_T)`` scale), so with ``K = 0`` and
    ``key == query`` it degenerates to the SimCLR loss exactly.
    """
    n = query.shape[0]
    b = n // 2
    contrast = jnp.concatenate([key, queue.astype(query.dtype)], axis=0)
    logits = (query @ contrast.T) / temperature
    logits = logits - jax.lax.stop_gradient(
        jnp.max(logits, axis=1, keepdims=True)
    )
    idx = jnp.arange(n)
    # column i = MY OWN view's key (sim ~ 1 across the two encoders): a
    # false negative, masked exactly like the SimCLR self-pair diagonal;
    # queue columns are always valid contrast
    logits_mask = jnp.ones_like(logits).at[idx, idx].set(0.0)
    exp_logits = jnp.exp(logits) * logits_mask
    log_prob = logits - jnp.log(jnp.sum(exp_logits, axis=1, keepdims=True))
    pos_idx = (idx + b) % n
    loss = -(temperature / base_temperature) * log_prob[idx, pos_idx]
    return jnp.mean(loss)


def cross_entropy_loss(logits: jax.Array, labels: jax.Array) -> jax.Array:
    """Mean softmax cross-entropy with integer labels (the CE-baseline loss).

    Matches ``torch.nn.CrossEntropyLoss`` mean-reduction semantics used by the
    reference probe driver (``main_linear.py:121,173``).
    """
    log_probs = jax.nn.log_softmax(logits, axis=-1)
    nll = -jnp.take_along_axis(log_probs, labels[:, None], axis=-1)[:, 0]
    return jnp.mean(nll)
