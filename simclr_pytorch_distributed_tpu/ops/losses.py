"""Contrastive losses (SupCon / SimCLR NT-Xent), functional and jit-friendly.

Semantics match the reference ``losses.py:17-93`` (SupConLoss.forward) exactly in
fp32, including every quirk that shapes the published 89.05% recipe:

- the final ``-(temperature / base_temperature)`` scale with ``base_temperature``
  fixed at 0.07 regardless of ``temperature`` (reference ``losses.py:90`` — at the
  recipe's ``--temp 0.5`` this is a silent ~7.14x loss multiplier),
- the detached per-row max subtraction (reference ``losses.py:68-69``),
- self-contrast masking of the leading diagonal only (reference ``losses.py:74-80``),
- ``contrast_mode`` 'one' / 'all' (reference ``losses.py:54-61``).

The single O((V*B)^2) anchor-by-contrast matmul is the hot kernel; it maps straight
onto the MXU and XLA fuses the mask/log-softmax epilogue around it.
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp


def supcon_loss(
    features: jax.Array,
    labels: Optional[jax.Array] = None,
    mask: Optional[jax.Array] = None,
    *,
    temperature: float = 0.07,
    base_temperature: float = 0.07,
    contrast_mode: str = "all",
) -> jax.Array:
    """Supervised-contrastive / SimCLR loss over multi-view features.

    Args:
      features: ``[batch, n_views, dim]`` feature matrix. The caller is expected to
        L2-normalize rows (the reference driver normalizes post-gather,
        ``main_supcon.py:283`` — this function does not normalize).
      labels: optional ``[batch]`` integer labels (SupCon). Mutually exclusive with
        ``mask``. When both are ``None`` the loss degenerates to SimCLR NT-Xent.
      mask: optional ``[batch, batch]`` explicit positive-pair mask.
      temperature: softmax temperature tau.
      base_temperature: the fixed denominator of the final scale. The reference
        never sets this from ``temperature`` — keep the default to reproduce the
        published recipe.
      contrast_mode: ``'all'`` (every view anchors, the driver default) or
        ``'one'`` (only view 0 anchors).

    Returns:
      Scalar loss.
    """
    if features.ndim < 3:
        raise ValueError("`features` must be [batch, n_views, ...]")
    if features.ndim > 3:
        features = features.reshape(features.shape[0], features.shape[1], -1)

    batch_size, n_views = features.shape[0], features.shape[1]
    compute_dtype = features.dtype

    if labels is not None and mask is not None:
        raise ValueError("Cannot define both `labels` and `mask`")
    if labels is None and mask is None:
        mask = jnp.eye(batch_size, dtype=compute_dtype)
    elif labels is not None:
        labels = labels.reshape(-1, 1)
        if labels.shape[0] != batch_size:
            raise ValueError("Num of labels does not match num of features")
        mask = (labels == labels.T).astype(compute_dtype)
    else:
        mask = mask.astype(compute_dtype)

    # Views stacked batch-major per view: rows [v0 b0..bN, v1 b0..bN, ...]
    # (same ordering as unbind(dim=1)+cat(dim=0), reference losses.py:53).
    contrast_feature = jnp.transpose(features, (1, 0, 2)).reshape(
        n_views * batch_size, -1
    )
    if contrast_mode == "one":
        anchor_feature = features[:, 0]
        anchor_count = 1
    elif contrast_mode == "all":
        anchor_feature = contrast_feature
        anchor_count = n_views
    else:
        raise ValueError(f"Unknown mode: {contrast_mode}")

    # [anchor_count*B, n_views*B] similarity logits — the MXU matmul.
    anchor_dot_contrast = (anchor_feature @ contrast_feature.T) / temperature
    logits_max = jax.lax.stop_gradient(
        jnp.max(anchor_dot_contrast, axis=1, keepdims=True)
    )
    logits = anchor_dot_contrast - logits_max

    # Tile positives mask to all view pairs; zero the self-pair diagonal.
    mask = jnp.tile(mask, (anchor_count, n_views))
    n_anchor_rows = batch_size * anchor_count
    diag = jnp.arange(n_anchor_rows)
    logits_mask = jnp.ones_like(mask).at[diag, diag].set(0.0)
    mask = mask * logits_mask

    exp_logits = jnp.exp(logits) * logits_mask
    log_prob = logits - jnp.log(jnp.sum(exp_logits, axis=1, keepdims=True))

    mean_log_prob_pos = jnp.sum(mask * log_prob, axis=1) / jnp.sum(mask, axis=1)

    loss = -(temperature / base_temperature) * mean_log_prob_pos
    return jnp.mean(loss.reshape(anchor_count, batch_size))


def cross_entropy_loss(logits: jax.Array, labels: jax.Array) -> jax.Array:
    """Mean softmax cross-entropy with integer labels (the CE-baseline loss).

    Matches ``torch.nn.CrossEntropyLoss`` mean-reduction semantics used by the
    reference probe driver (``main_linear.py:121,173``).
    """
    log_probs = jax.nn.log_softmax(logits, axis=-1)
    nll = -jnp.take_along_axis(log_probs, labels[:, None], axis=-1)[:, 0]
    return jnp.mean(nll)
