"""Fused Pallas TPU kernels for the CIFAR-geometry ResNet conv blocks.

WHY: the compiled step is bandwidth-bound at 0.85 of its measured mixed
roofline, and the residual lives in XLA's conv emitter — conv fusions carry
82% of step time (49.0 of 59.5 ms) at 69% of peak HBM BW, with the stage-1
BN-backward and residual/ReLU fusions topping the per-fusion traffic table
(docs/PERF.md round 4, ``docs/evidence/xplane_bw_r4.json`` fusion.81/74/75
and the fusion.160/161/162 trio). PERF.md's own conclusion: raising MFU
"requires reducing bytes, not faster matmuls". At 32x32 the activations are
too thin per byte for XLA's generic conv emitter, and every inter-op
boundary (conv -> BN stats -> normalize/ReLU -> conv -> BN -> residual add)
funds a full HBM round trip of a ``[2B, H, W, C]`` activation array.

WHAT: two fused ops that keep those boundaries in VMEM/registers —

- ``fused_conv_bn_relu``: the ResNet stem (conv3x3/s1 + train-mode BN +
  ReLU) as one kernel;
- ``fused_basic_block``: the identity-shortcut BasicBlock
  (conv3x3 -> BN -> ReLU -> conv3x3 -> BN -> +residual -> ReLU) as one
  kernel, forward and custom-VJP backward.

HOW: the conv is an MXU matmul over VMEM-resident im2col tiles (the
crop-as-matmul precedent, docs/PERF.md 227x): each 3x3 window offset is one
``[bn*H*W, Cin] @ [Cin, Cout]`` contraction against a spatially-shifted
slice of a zero-padded VMEM scratch tile. Train-mode BN needs batch
statistics BEFORE it can normalize, so each kernel runs a sequential
PHASE-major grid ``(phases, batch_tiles)`` over the same input tiles:
stats phases accumulate per-channel sums in VMEM scratch and the emit
phase recomputes the convs in-register with the now-known scale/shift —
a FLOPs-for-bytes trade (the convs here are bandwidth-bound, the MXU is
62% idle). Per-activation-array HBM traffic of the block forward drops
from the ~9 traversals XLA's fusion decomposition pays to
``FWD_HBM_TRAVERSALS_BLOCK`` (3 reads of x + 1 write of out); the backward
keeps only O(C) residuals (saved batch moments) and recomputes everything
else, ``BWD_HBM_TRAVERSALS_BLOCK`` vs the ~12 of the separate BN-backward /
conv-backward / residual fusions.

BN semantics are models/norm.py's torch-matching whole-batch train mode:
biased variance for normalization, fp32 statistics, running-stat update
(UNBIASED variance, momentum-weighted) applied by the caller
(``models.norm.running_stats_update``) from the returned batch moments —
the kernels never touch running stats. Cross-replica semantics are
preserved by construction: the kernel computes stats over exactly the
array it is given (per-device = whole batch on the single-chip mesh the
resolution ladder admits; grouped/multi-device BN configurations are
gated off in ``supports_block``/``resolve_conv_impl``).

The VJP treats the returned batch moments as ancillary (their cotangents
are discarded): they feed only the mutable running-stat buffers, exactly
like Flax's BN variables, while the normalization statistics' gradient
contribution is fully inside the standard train-mode BN backward the
kernel implements.

``interpret=True`` runs the Pallas interpreter — the CPU path used by the
tier-1 parity suite (tests/test_pallas_conv.py) and by ``--conv_impl
pallas`` on non-TPU backends (slow; for tests and the checkpoint
round-trip smoke, not for training throughput).
"""

from __future__ import annotations

import functools
from typing import List, Optional, Tuple

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

# Per-activation-array HBM traversals of ONE block apply, by path. The
# Pallas counts are properties of the kernels' BlockSpecs below (each
# phase re-reads its input tiles; outputs are written once via the
# phase-gated index maps); the XLA counts are read off the round-4 xplane
# fusion decomposition (docs/PERF.md: conv kernel writes y1; BN-stat
# fusion reads y1; normalize+ReLU fusion reads y1, writes a1; conv reads
# a1, writes y2; BN-stat reads y2; normalize+residual+ReLU fusion reads
# y2 + x, writes out — and the backward's fusion.81/74/75-class stat +
# dx chains). scripts/convblock_ab.py's CPU proxy injects one modeled
# delay per traversal; docs/PERF.md round 15 carries the derivation.
FWD_HBM_TRAVERSALS_BLOCK = 4   # 3 phase-reads of x + 1 write of out
FWD_HBM_TRAVERSALS_XLA = 9    # see derivation above
BWD_HBM_TRAVERSALS_BLOCK = 7   # 3 reads of x + 3 reads of g + 1 write of dx
BWD_HBM_TRAVERSALS_XLA = 12   # BN-bwd stat reads x2, dx chains, residual adds

# VMEM budget the geometry gate admits against (bytes). Deliberately
# conservative vs the ~16 MB/core physical VMEM: the estimate below is a
# model of the kernel's resident set, not the compiler's exact allocation.
VMEM_BUDGET = 10 * 1024 * 1024


def _pick_batch_tile(n: int, h: int, w: int, cin: int, cout: int,
                     *, residual: bool) -> Optional[int]:
    """Largest batch-tile size (<= 8) dividing ``n`` whose estimated VMEM
    resident set fits the budget, or None."""
    for bn in (8, 4, 2, 1):
        if n % bn:
            continue
        if _vmem_estimate(bn, h, w, cin, cout, residual=residual) <= VMEM_BUDGET:
            return bn
    return None


def _vmem_estimate(bn: int, h: int, w: int, cin: int, cout: int,
                   *, residual: bool) -> int:
    """Modeled peak VMEM bytes of the WORST kernel (the backward) at this
    geometry: padded scratch tiles, weight blocks (incl. the flipped
    copies), dW accumulators, and a conservative multiplier for the
    per-step activation values the compiler keeps live."""
    pad = bn * (h + 2) * (w + 2) * 4
    tile = bn * h * w * 4
    if not residual:  # stem: one conv, cin != cout
        pads = 2 * pad * max(cin, cout)  # xpad + gpad
        weights = 2 * 9 * cin * cout * 4  # k + kt
        dw_acc = 9 * cin * cout * 4
        live = 6 * tile * max(cin, cout)
    else:  # basic block: two cin==cout convs
        pads = 3 * pad * cout            # xpad + apad + gpad
        weights = 4 * 9 * cout * cout * 4  # k1, k2, k1t, k2t
        dw_acc = 2 * 9 * cout * cout * 4
        live = 8 * tile * cout
    return pads + weights + dw_acc + live


def supports_block(n: int, h: int, w: int, c: int, *, stride: int = 1,
                   in_channels: Optional[int] = None) -> bool:
    """True if the fused BasicBlock kernel admits this geometry: identity
    shortcut (stride 1, in==out channels), spatial dims that the padded
    3x3 window covers, and a batch tile whose resident set fits VMEM."""
    if stride != 1 or (in_channels is not None and in_channels != c):
        return False
    if h < 3 or w < 3 or n < 1 or c < 1:
        return False
    return _pick_batch_tile(n, h, w, c, c, residual=True) is not None


def supports_stem(n: int, h: int, w: int, cin: int, cout: int) -> bool:
    """True if the fused stem kernel admits this geometry (conv3x3/s1)."""
    if h < 3 or w < 3 or n < 1 or cin < 1 or cout < 1:
        return False
    return _pick_batch_tile(n, h, w, cin, cout, residual=False) is not None


def _vmem_spec(block_shape=None, index_map=None):
    if block_shape is None:
        return pl.BlockSpec(memory_space=pltpu.VMEM)
    return pl.BlockSpec(block_shape, index_map, memory_space=pltpu.VMEM)


def _fill_pad(pad_ref, x):
    """Zero-pad ``x`` by 1 pixel on each spatial edge into VMEM scratch."""
    pad_ref[:] = jnp.zeros(pad_ref.shape, jnp.float32)
    pad_ref[:, 1:-1, 1:-1, :] = x


def _conv3x3(pad_ref, w, h: int, wdt: int):
    """3x3/s1 conv as 9 shifted MXU matmuls over the padded VMEM tile.

    ``pad_ref``: scratch ref ``[bn, h+2, w+2, cin]`` (already filled);
    ``w``: kernel VALUE ``[3, 3, cin, cout]``. Each window offset is one
    ``[bn*h*w, cin] @ [cin, cout]`` contraction — the im2col matrix is
    never materialized, only its shifted views are read back out of the
    same padded tile.
    """
    bn, _, _, cin = pad_ref.shape
    cout = w.shape[3]
    acc = None
    for di in range(3):
        for dj in range(3):
            xs = pad_ref[:, di:di + h, dj:dj + wdt, :].reshape(bn * h * wdt, cin)
            t = jnp.dot(xs, w[di, dj], preferred_element_type=jnp.float32)
            acc = t if acc is None else acc + t
    return acc.reshape(bn, h, wdt, cout)


def _dw_accumulate(dw_ref, pad_ref, dy, h: int, wdt: int):
    """dW[di,dj] += x_window(di,dj)^T @ dy for all 9 offsets, into the
    ``[9*cin, cout]`` scratch accumulator."""
    bn, _, _, cin = pad_ref.shape
    cout = dy.shape[3]
    dyf = dy.reshape(bn * h * wdt, cout)
    for di in range(3):
        for dj in range(3):
            xs = pad_ref[:, di:di + h, dj:dj + wdt, :].reshape(bn * h * wdt, cin)
            k = di * 3 + dj
            dw_ref[k * cin:(k + 1) * cin, :] += jnp.dot(
                xs.T, dyf, preferred_element_type=jnp.float32
            )


def _channel_sums(v, c: int):
    """``(1, C)`` per-channel sum over (batch-tile, H, W)."""
    return jnp.sum(v.reshape(-1, c), axis=0, keepdims=True)


def _flip_transpose(k):
    """Spatially-flipped, channel-transposed kernel: the weight of the
    transposed conv that computes dx from dy (computed OUTSIDE the kernel;
    O(9*Cin*Cout) bytes)."""
    return jnp.transpose(k[::-1, ::-1, :, :], (0, 1, 3, 2))


# ---------------------------------------------------------------------------
# Fused stem: conv3x3/s1 + train-mode BN + ReLU.
# ---------------------------------------------------------------------------


def _stem_fwd_kernel(
    x_ref, k_ref, g_ref, b_ref,
    out_ref, m_ref, v_ref,
    xpad, acc_s, acc_q, sc_s, sc_t,
    *, h: int, w: int, count: float, eps: float,
):
    p = pl.program_id(0)
    i = pl.program_id(1)
    cout = out_ref.shape[3]

    @pl.when((p == 0) & (i == 0))
    def _():
        acc_s[:] = jnp.zeros_like(acc_s)
        acc_q[:] = jnp.zeros_like(acc_q)

    # stage-1 finalize: batch moments -> folded scale/shift, once, before
    # the first emit-phase tile consumes them
    @pl.when((p == 1) & (i == 0))
    def _():
        m = acc_s[:] / count
        v = acc_q[:] / count - m * m  # biased (norm.py convention)
        m_ref[:] = m
        v_ref[:] = v
        s = g_ref[:] * jax.lax.rsqrt(v + eps)
        sc_s[:] = s
        sc_t[:] = b_ref[:] - m * s

    _fill_pad(xpad, x_ref[:].astype(jnp.float32))
    y = _conv3x3(xpad, k_ref[:], h, w)

    @pl.when(p == 0)
    def _():
        acc_s[:] += _channel_sums(y, cout)
        acc_q[:] += _channel_sums(jnp.square(y), cout)

    @pl.when(p == 1)
    def _():
        out_ref[:] = jnp.maximum(y * sc_s[:] + sc_t[:], 0.0)


def _stem_bwd_kernel(
    x_ref, k_ref, kt_ref, g_ref, b_ref, m_ref, v_ref, gout_ref,
    dx_ref, dw_ref, dg_ref, db_ref,
    xpad, gpad, dw_acc, acc_db, acc_dg,
    *, h: int, w: int, count: float, eps: float,
):
    p = pl.program_id(0)
    i = pl.program_id(1)
    nt = pl.num_programs(1)
    cin = x_ref.shape[3]

    @pl.when((p == 0) & (i == 0))
    def _():
        acc_db[:] = jnp.zeros_like(acc_db)
        acc_dg[:] = jnp.zeros_like(acc_dg)
        dw_acc[:] = jnp.zeros_like(dw_acc)

    # recompute the tile's forward from the saved batch moments
    m, v, g = m_ref[:], v_ref[:], g_ref[:]
    rs = jax.lax.rsqrt(v + eps)
    _fill_pad(xpad, x_ref[:].astype(jnp.float32))
    y = _conv3x3(xpad, k_ref[:], h, w)
    yh = (y - m) * rs
    pre = yh * g + b_ref[:]
    dp = gout_ref[:].astype(jnp.float32) * (pre > 0.0)

    @pl.when(p == 0)
    def _():
        acc_db[:] += _channel_sums(dp, dp.shape[3])
        acc_dg[:] += _channel_sums(dp * yh, dp.shape[3])

    @pl.when(p == 1)
    def _():
        # standard train-mode BN backward (biased variance): the batch
        # moments' own gradient contribution is the two mean-subtractions
        dy = rs * g * (dp - acc_db[:] / count - yh * acc_dg[:] / count)
        _dw_accumulate(dw_acc, xpad, dy, h, w)
        _fill_pad(gpad, dy)
        dx_ref[:] = _conv3x3(gpad, kt_ref[:], h, w)

    @pl.when((p == 1) & (i == nt - 1))
    def _():
        dw_ref[:] = dw_acc[:].reshape(3, 3, cin, dw_ref.shape[3])
        dg_ref[:] = acc_dg[:]
        db_ref[:] = acc_db[:]


def _stem_call(x, k, g, b, eps, interpret, bn):
    n, h, w, cin = x.shape
    cout = k.shape[3]
    nt = n // bn
    count = float(n * h * w)
    kernel = functools.partial(
        _stem_fwd_kernel, h=h, w=w, count=count, eps=eps
    )
    tile = _vmem_spec((bn, h, w, cin), lambda p, i: (i, 0, 0, 0))
    out_tile = _vmem_spec(
        (bn, h, w, cout), lambda p, i: ((p == 1) * i, 0, 0, 0)
    )
    full = _vmem_spec((3, 3, cin, cout), lambda p, i: (0, 0, 0, 0))
    row = _vmem_spec((1, cout), lambda p, i: (0, 0))
    return pl.pallas_call(
        kernel,
        grid=(2, nt),
        in_specs=[tile, full, row, row],
        out_specs=[out_tile, row, row],
        out_shape=[
            jax.ShapeDtypeStruct((n, h, w, cout), jnp.float32),
            jax.ShapeDtypeStruct((1, cout), jnp.float32),
            jax.ShapeDtypeStruct((1, cout), jnp.float32),
        ],
        scratch_shapes=[
            pltpu.VMEM((bn, h + 2, w + 2, cin), jnp.float32),
            pltpu.VMEM((1, cout), jnp.float32),
            pltpu.VMEM((1, cout), jnp.float32),
            pltpu.VMEM((1, cout), jnp.float32),
            pltpu.VMEM((1, cout), jnp.float32),
        ],
        interpret=interpret,
    )(x, k, g[None, :], b[None, :])


def _stem_bwd_call(x, k, g, b, m, v, gout, eps, interpret, bn):
    n, h, w, cin = x.shape
    cout = k.shape[3]
    nt = n // bn
    count = float(n * h * w)
    kernel = functools.partial(
        _stem_bwd_kernel, h=h, w=w, count=count, eps=eps
    )
    in_tile = _vmem_spec((bn, h, w, cin), lambda p, i: (i, 0, 0, 0))
    g_tile = _vmem_spec((bn, h, w, cout), lambda p, i: (i, 0, 0, 0))
    dx_tile = _vmem_spec(
        (bn, h, w, cin), lambda p, i: ((p == 1) * i, 0, 0, 0)
    )
    kfull = _vmem_spec((3, 3, cin, cout), lambda p, i: (0, 0, 0, 0))
    ktfull = _vmem_spec((3, 3, cout, cin), lambda p, i: (0, 0, 0, 0))
    row = _vmem_spec((1, cout), lambda p, i: (0, 0))
    return pl.pallas_call(
        kernel,
        grid=(2, nt),
        in_specs=[in_tile, kfull, ktfull, row, row, row, row, g_tile],
        out_specs=[dx_tile, kfull, row, row],
        out_shape=[
            jax.ShapeDtypeStruct((n, h, w, cin), jnp.float32),
            jax.ShapeDtypeStruct((3, 3, cin, cout), jnp.float32),
            jax.ShapeDtypeStruct((1, cout), jnp.float32),
            jax.ShapeDtypeStruct((1, cout), jnp.float32),
        ],
        scratch_shapes=[
            pltpu.VMEM((bn, h + 2, w + 2, cin), jnp.float32),
            pltpu.VMEM((bn, h + 2, w + 2, cout), jnp.float32),
            pltpu.VMEM((9 * cin, cout), jnp.float32),
            pltpu.VMEM((1, cout), jnp.float32),
            pltpu.VMEM((1, cout), jnp.float32),
        ],
        interpret=interpret,
    )(
        x, k, _flip_transpose(k), g[None, :], b[None, :],
        m[None, :], v[None, :], gout,
    )


@functools.partial(jax.custom_vjp, nondiff_argnums=(4, 5, 6))
def _stem(x, k, g, b, eps, interpret, bn):
    out, _ = _stem_fwd(x, k, g, b, eps, interpret, bn)
    return out


def _stem_fwd(x, k, g, b, eps, interpret, bn):
    out, m, v = _stem_call(x, k, g, b, eps, interpret, bn)
    return (out, m[0], v[0]), (x, k, g, b, m[0], v[0])


def _stem_bwd(eps, interpret, bn, res, ct):
    x, k, g, b, m, v = res
    gout = ct[0]  # batch-moment cotangents discarded (module docstring)
    dx, dw, dg, db = _stem_bwd_call(x, k, g, b, m, v, gout, eps, interpret, bn)
    return dx, dw, dg[0], db[0]


_stem.defvjp(_stem_fwd, _stem_bwd)


def fused_conv_bn_relu(
    x: jax.Array, kernel: jax.Array, scale: jax.Array, bias: jax.Array,
    *, eps: float = 1e-5, interpret: bool = False,
) -> Tuple[jax.Array, jax.Array, jax.Array]:
    """Fused stem: ``relu(bn_train(conv3x3_s1(x, kernel)))`` in one kernel.

    Returns ``(out, batch_mean, batch_var_biased)``; the caller applies the
    running-stat update (``models.norm.running_stats_update``). Gradients
    flow to ``x``/``kernel``/``scale``/``bias``; the returned moments are
    ancillary (zero cotangent, like Flax BN variables).
    """
    n, h, w, cin = x.shape
    cout = kernel.shape[3]
    bn = _pick_batch_tile(n, h, w, cin, cout, residual=False)
    if bn is None:
        raise ValueError(
            f"fused stem does not admit geometry [{n},{h},{w},{cin}]->{cout}"
            " (supports_stem gate)"
        )
    return _stem(
        x.astype(jnp.float32), kernel.astype(jnp.float32),
        scale.astype(jnp.float32), bias.astype(jnp.float32),
        float(eps), bool(interpret), bn,
    )


# ---------------------------------------------------------------------------
# Fused BasicBlock: conv-BN-ReLU-conv-BN-(+x)-ReLU, identity shortcut.
# ---------------------------------------------------------------------------


def _block_fwd_kernel(
    x_ref, k1_ref, k2_ref, g1_ref, b1_ref, g2_ref, b2_ref,
    out_ref, m1_ref, v1_ref, m2_ref, v2_ref,
    xpad, apad, acc1s, acc1q, acc2s, acc2q, scA, shA, scB, shB,
    *, h: int, w: int, count: float, eps: float,
):
    p = pl.program_id(0)
    i = pl.program_id(1)
    c = out_ref.shape[3]

    @pl.when((p == 0) & (i == 0))
    def _():
        acc1s[:] = jnp.zeros_like(acc1s)
        acc1q[:] = jnp.zeros_like(acc1q)
        acc2s[:] = jnp.zeros_like(acc2s)
        acc2q[:] = jnp.zeros_like(acc2q)

    # stage-1 stats finalize (before the first phase-1 tile reads scA/shA)
    @pl.when((p == 1) & (i == 0))
    def _():
        m = acc1s[:] / count
        v = acc1q[:] / count - m * m
        m1_ref[:] = m
        v1_ref[:] = v
        s = g1_ref[:] * jax.lax.rsqrt(v + eps)
        scA[:] = s
        shA[:] = b1_ref[:] - m * s

    # stage-2 stats finalize (before the first phase-2 tile reads scB/shB)
    @pl.when((p == 2) & (i == 0))
    def _():
        m = acc2s[:] / count
        v = acc2q[:] / count - m * m
        m2_ref[:] = m
        v2_ref[:] = v
        s = g2_ref[:] * jax.lax.rsqrt(v + eps)
        scB[:] = s
        shB[:] = b2_ref[:] - m * s

    x = x_ref[:].astype(jnp.float32)
    _fill_pad(xpad, x)
    y1 = _conv3x3(xpad, k1_ref[:], h, w)

    @pl.when(p == 0)
    def _():
        acc1s[:] += _channel_sums(y1, c)
        acc1q[:] += _channel_sums(jnp.square(y1), c)

    @pl.when(p >= 1)
    def _():
        a1 = jnp.maximum(y1 * scA[:] + shA[:], 0.0)
        _fill_pad(apad, a1)
        y2 = _conv3x3(apad, k2_ref[:], h, w)

        @pl.when(p == 1)
        def _():
            acc2s[:] += _channel_sums(y2, c)
            acc2q[:] += _channel_sums(jnp.square(y2), c)

        @pl.when(p == 2)
        def _():
            out_ref[:] = jnp.maximum(y2 * scB[:] + shB[:] + x, 0.0)


def _block_bwd_kernel(
    x_ref, k1_ref, k2_ref, k1t_ref, k2t_ref,
    g1_ref, b1_ref, g2_ref, b2_ref,
    m1_ref, v1_ref, m2_ref, v2_ref, gout_ref,
    dx_ref, dw1_ref, dw2_ref, dg1_ref, db1_ref, dg2_ref, db2_ref,
    xpad, apad, gpad, dw1_acc, dw2_acc, s_dz, s_dzy, s_dp, s_dpy,
    *, h: int, w: int, count: float, eps: float,
):
    p = pl.program_id(0)
    i = pl.program_id(1)
    nt = pl.num_programs(1)
    c = x_ref.shape[3]

    @pl.when((p == 0) & (i == 0))
    def _():
        s_dz[:] = jnp.zeros_like(s_dz)
        s_dzy[:] = jnp.zeros_like(s_dzy)
        s_dp[:] = jnp.zeros_like(s_dp)
        s_dpy[:] = jnp.zeros_like(s_dpy)
        dw1_acc[:] = jnp.zeros_like(dw1_acc)
        dw2_acc[:] = jnp.zeros_like(dw2_acc)

    # recompute the tile's whole forward from the saved batch moments —
    # the FLOPs-for-bytes trade: no activation residual was ever stored
    g1, g2 = g1_ref[:], g2_ref[:]
    rs1 = jax.lax.rsqrt(v1_ref[:] + eps)
    rs2 = jax.lax.rsqrt(v2_ref[:] + eps)
    x = x_ref[:].astype(jnp.float32)
    _fill_pad(xpad, x)
    y1 = _conv3x3(xpad, k1_ref[:], h, w)
    yh1 = (y1 - m1_ref[:]) * rs1
    p1 = yh1 * g1 + b1_ref[:]
    a1 = jnp.maximum(p1, 0.0)
    _fill_pad(apad, a1)
    y2 = _conv3x3(apad, k2_ref[:], h, w)
    yh2 = (y2 - m2_ref[:]) * rs2
    z = yh2 * g2 + b2_ref[:] + x
    dz = gout_ref[:].astype(jnp.float32) * (z > 0.0)

    @pl.when(p == 0)
    def _():
        s_dz[:] += _channel_sums(dz, c)
        s_dzy[:] += _channel_sums(dz * yh2, c)

    @pl.when(p >= 1)
    def _():
        # train-mode BN2 backward, then back through conv2 to the stage-1
        # pre-activation
        dy2 = rs2 * g2 * (dz - s_dz[:] / count - yh2 * s_dzy[:] / count)

        @pl.when(p == 1)
        def _():
            _dw_accumulate(dw2_acc, apad, dy2, h, w)

        _fill_pad(gpad, dy2)
        da1 = _conv3x3(gpad, k2t_ref[:], h, w)
        dp1 = da1 * (p1 > 0.0)

        @pl.when(p == 1)
        def _():
            s_dp[:] += _channel_sums(dp1, c)
            s_dpy[:] += _channel_sums(dp1 * yh1, c)

        @pl.when(p == 2)
        def _():
            dy1 = rs1 * g1 * (dp1 - s_dp[:] / count - yh1 * s_dpy[:] / count)
            _dw_accumulate(dw1_acc, xpad, dy1, h, w)
            _fill_pad(gpad, dy1)
            # residual shortcut gradient + conv1 transpose
            dx_ref[:] = dz + _conv3x3(gpad, k1t_ref[:], h, w)

    @pl.when((p == 2) & (i == nt - 1))
    def _():
        dw1_ref[:] = dw1_acc[:].reshape(3, 3, c, c)
        dw2_ref[:] = dw2_acc[:].reshape(3, 3, c, c)
        dg1_ref[:] = s_dpy[:]
        db1_ref[:] = s_dp[:]
        dg2_ref[:] = s_dzy[:]
        db2_ref[:] = s_dz[:]


def _block_call(x, k1, g1, b1, k2, g2, b2, eps, interpret, bn):
    n, h, w, c = x.shape
    nt = n // bn
    count = float(n * h * w)
    kernel = functools.partial(
        _block_fwd_kernel, h=h, w=w, count=count, eps=eps
    )
    tile = _vmem_spec((bn, h, w, c), lambda p, i: (i, 0, 0, 0))
    out_tile = _vmem_spec(
        (bn, h, w, c), lambda p, i: ((p == 2) * i, 0, 0, 0)
    )
    kfull = _vmem_spec((3, 3, c, c), lambda p, i: (0, 0, 0, 0))
    row = _vmem_spec((1, c), lambda p, i: (0, 0))
    row_out = [row] * 4
    return pl.pallas_call(
        kernel,
        grid=(3, nt),
        in_specs=[tile, kfull, kfull, row, row, row, row],
        out_specs=[out_tile] + row_out,
        out_shape=[jax.ShapeDtypeStruct((n, h, w, c), jnp.float32)]
        + [jax.ShapeDtypeStruct((1, c), jnp.float32)] * 4,
        scratch_shapes=[
            pltpu.VMEM((bn, h + 2, w + 2, c), jnp.float32),
            pltpu.VMEM((bn, h + 2, w + 2, c), jnp.float32),
        ] + [pltpu.VMEM((1, c), jnp.float32)] * 8,
        interpret=interpret,
    )(x, k1, k2, g1[None, :], b1[None, :], g2[None, :], b2[None, :])


def _block_bwd_call(
    x, k1, g1, b1, k2, g2, b2, m1, v1, m2, v2, gout, eps, interpret, bn
):
    n, h, w, c = x.shape
    nt = n // bn
    count = float(n * h * w)
    kernel = functools.partial(
        _block_bwd_kernel, h=h, w=w, count=count, eps=eps
    )
    tile = _vmem_spec((bn, h, w, c), lambda p, i: (i, 0, 0, 0))
    dx_tile = _vmem_spec(
        (bn, h, w, c), lambda p, i: ((p == 2) * i, 0, 0, 0)
    )
    kfull = _vmem_spec((3, 3, c, c), lambda p, i: (0, 0, 0, 0))
    row = _vmem_spec((1, c), lambda p, i: (0, 0))
    return pl.pallas_call(
        kernel,
        grid=(3, nt),
        in_specs=[tile, kfull, kfull, kfull, kfull,
                  row, row, row, row, row, row, row, row, tile],
        out_specs=[dx_tile, kfull, kfull, row, row, row, row],
        out_shape=[
            jax.ShapeDtypeStruct((n, h, w, c), jnp.float32),
            jax.ShapeDtypeStruct((3, 3, c, c), jnp.float32),
            jax.ShapeDtypeStruct((3, 3, c, c), jnp.float32),
        ] + [jax.ShapeDtypeStruct((1, c), jnp.float32)] * 4,
        scratch_shapes=[
            pltpu.VMEM((bn, h + 2, w + 2, c), jnp.float32),
            pltpu.VMEM((bn, h + 2, w + 2, c), jnp.float32),
            pltpu.VMEM((bn, h + 2, w + 2, c), jnp.float32),
            pltpu.VMEM((9 * c, c), jnp.float32),
            pltpu.VMEM((9 * c, c), jnp.float32),
        ] + [pltpu.VMEM((1, c), jnp.float32)] * 4,
        interpret=interpret,
    )(
        x, k1, k2, _flip_transpose(k1), _flip_transpose(k2),
        g1[None, :], b1[None, :], g2[None, :], b2[None, :],
        m1[None, :], v1[None, :], m2[None, :], v2[None, :], gout,
    )


@functools.partial(jax.custom_vjp, nondiff_argnums=(7, 8, 9))
def _block(x, k1, g1, b1, k2, g2, b2, eps, interpret, bn):
    out, _ = _block_fwd(x, k1, g1, b1, k2, g2, b2, eps, interpret, bn)
    return out


def _block_fwd(x, k1, g1, b1, k2, g2, b2, eps, interpret, bn):
    out, m1, v1, m2, v2 = _block_call(
        x, k1, g1, b1, k2, g2, b2, eps, interpret, bn
    )
    res = (x, k1, g1, b1, k2, g2, b2, m1[0], v1[0], m2[0], v2[0])
    return (out, m1[0], v1[0], m2[0], v2[0]), res


def _block_bwd(eps, interpret, bn, res, ct):
    x, k1, g1, b1, k2, g2, b2, m1, v1, m2, v2 = res
    gout = ct[0]  # batch-moment cotangents discarded (module docstring)
    dx, dw1, dw2, dg1, db1, dg2, db2 = _block_bwd_call(
        x, k1, g1, b1, k2, g2, b2, m1, v1, m2, v2, gout, eps, interpret, bn
    )
    return dx, dw1, dg1[0], db1[0], dw2, dg2[0], db2[0]


_block.defvjp(_block_fwd, _block_bwd)


def fused_basic_block(
    x: jax.Array,
    kernel1: jax.Array, scale1: jax.Array, bias1: jax.Array,
    kernel2: jax.Array, scale2: jax.Array, bias2: jax.Array,
    *, eps: float = 1e-5, interpret: bool = False,
) -> Tuple[jax.Array, jax.Array, jax.Array, jax.Array, jax.Array]:
    """Fused identity-shortcut BasicBlock, train mode, one kernel each way.

    ``relu(bn2(conv3x3(relu(bn1(conv3x3(x, k1))), k2)) + x)`` with both BNs
    in whole-batch train mode. Returns
    ``(out, mean1, var1_biased, mean2, var2_biased)``; the caller applies
    the running-stat updates. Differentiable w.r.t. every array argument
    (custom VJP; the backward kernel recomputes the forward per phase and
    stores no activation residual — only the O(C) batch moments).
    """
    n, h, w, c = x.shape
    if not supports_block(n, h, w, c):
        raise ValueError(
            f"fused basic block does not admit geometry [{n},{h},{w},{c}] "
            "(supports_block gate)"
        )
    bn = _pick_batch_tile(n, h, w, c, c, residual=True)
    f32 = jnp.float32
    return _block(
        x.astype(f32), kernel1.astype(f32), scale1.astype(f32),
        bias1.astype(f32), kernel2.astype(f32), scale2.astype(f32),
        bias2.astype(f32), float(eps), bool(interpret), bn,
    )
